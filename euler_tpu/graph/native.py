"""ctypes bindings for libeuler_graph.so (built from graph/_native)."""

from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libeuler_graph.so")

_lib = None


def build_native(force: bool = False) -> str:
    """Build the native library with make if missing or stale."""
    if os.environ.get("EG_NATIVE_LIB"):
        # explicit prebuilt library (scripts/sanitize.sh points this at
        # an instrumented side build): never rebuild, never second-guess
        return os.environ["EG_NATIVE_LIB"]
    sources = [
        os.path.join(_NATIVE_DIR, f)
        for f in os.listdir(_NATIVE_DIR)
        if f.endswith((".cc", ".h"))
    ]
    flavor = os.path.join(_NATIVE_DIR, ".flavor")
    sanitized = False
    if os.path.exists(flavor):
        with open(flavor) as f:
            sanitized = f.read().strip() != "normal"
    if sanitized and any(
        rt in os.environ.get("LD_PRELOAD", "")
        for rt in ("libtsan", "libasan")
    ):
        # the sanitizer runtime is preloaded: this IS the sanitizer test
        # run — keep the instrumented library (rebuilding normal here
        # would make the run pass vacuously)
        sanitized = False
    stale = force or sanitized or not os.path.exists(_LIB_PATH) or any(
        os.path.getmtime(s) > os.path.getmtime(_LIB_PATH) for s in sources
    )
    if stale:
        subprocess.run(
            ["make", "-s", "-j"], cwd=_NATIVE_DIR, check=True,
            capture_output=True, text=True,
        )
    return _LIB_PATH


def _sig(fn, restype, argtypes) -> None:
    fn.restype = restype
    fn.argtypes = argtypes


def lib() -> ctypes.CDLL:
    """Load (building if needed) and return the native library singleton."""
    global _lib
    if _lib is not None:
        return _lib
    L = ctypes.CDLL(build_native())
    c = ctypes
    p = c.c_void_p
    u64p = c.POINTER(c.c_uint64)
    i32p = c.POINTER(c.c_int32)
    f32p = c.POINTER(c.c_float)
    _sig(L.eg_last_error, c.c_char_p, [])
    _sig(L.eg_create, p, [])
    _sig(L.eg_destroy, None, [p])
    _sig(L.eg_load, c.c_int, [p, c.c_char_p, c.c_int, c.c_int])
    _sig(L.eg_load_files, c.c_int, [p, c.POINTER(c.c_char_p), c.c_int])
    _sig(L.eg_load_buffers, c.c_int,
         [p, c.POINTER(c.c_void_p), u64p, c.POINTER(c.c_char_p), c.c_int])
    _sig(L.eg_load_deltas, c.c_int, [p, c.c_char_p])
    _sig(L.eg_graph_epoch, c.c_uint64, [p])
    _sig(L.eg_seed, None, [c.c_uint64])
    _sig(L.eg_stat_count, c.c_int, [])
    _sig(L.eg_stat_name, c.c_char_p, [c.c_int])
    _sig(L.eg_stats_snapshot, None, [u64p, u64p, u64p])
    _sig(L.eg_stats_reset, None, [])
    _sig(L.eg_counter_count, c.c_int, [])
    _sig(L.eg_counter_name, c.c_char_p, [c.c_int])
    _sig(L.eg_counters_snapshot, None, [u64p])
    _sig(L.eg_counters_reset, None, [])
    _sig(L.eg_counter_add, None, [c.c_int, c.c_uint64])
    _sig(L.eg_phase_record, None, [c.c_int, c.c_uint64])
    _sig(L.eg_phase_gauge, None, [c.c_int, c.c_uint64])
    _sig(L.eg_serve_record, None, [c.c_int, c.c_uint64])
    _sig(L.eg_serve_batch, None, [c.c_uint64])
    _sig(L.eg_devprof_set_mem, None, [c.c_int64, c.c_int64])
    _sig(L.eg_serve_slo_set, None,
         [c.c_uint64, c.c_uint64, c.c_uint64, c.c_uint64])
    _sig(L.eg_telemetry_enabled, c.c_int, [])
    _sig(L.eg_telemetry_set_enabled, None, [c.c_int])
    _sig(L.eg_telemetry_reset, None, [])
    _sig(L.eg_telemetry_set_slow_capacity, None, [c.c_int])
    _sig(L.eg_telemetry_json, c.c_int, [c.c_char_p, c.c_int])
    _sig(
        L.eg_telemetry_record_span,
        None,
        [c.c_int, c.c_int, c.c_int, c.c_int, c.c_uint64, c.c_uint64,
         c.c_uint64, c.c_uint64, c.c_uint64],
    )
    _sig(L.eg_remote_ping, c.c_int, [p, c.c_int])
    _sig(L.eg_remote_scrape, c.c_int, [p, c.c_int, c.c_char_p, c.c_int])
    _sig(L.eg_remote_history, c.c_int, [p, c.c_int, c.c_char_p, c.c_int])
    _sig(L.eg_heat_enabled, c.c_int, [])
    _sig(L.eg_heat_set_enabled, None, [c.c_int])
    _sig(L.eg_heat_set_topk, None, [c.c_int])
    _sig(L.eg_heat_record, None, [c.c_int, c.c_int, u64p, c.c_int64])
    _sig(L.eg_heat_estimate, c.c_uint64, [c.c_int, c.c_uint64])
    _sig(L.eg_heat_json, c.c_int, [c.c_char_p, c.c_int])
    _sig(L.eg_heat_reset, None, [])
    _sig(L.eg_remote_heat, c.c_int, [p, c.c_int, c.c_char_p, c.c_int])
    _sig(L.eg_blackbox_enabled, c.c_int, [])
    _sig(L.eg_blackbox_set_enabled, None, [c.c_int])
    _sig(L.eg_blackbox_init, c.c_int, [c.c_char_p, c.c_int, c.c_int])
    _sig(
        L.eg_blackbox_record,
        None,
        [c.c_int, c.c_int, c.c_int, c.c_uint64, c.c_uint64, c.c_int],
    )
    _sig(L.eg_blackbox_json, c.c_int, [c.c_char_p, c.c_int])
    _sig(L.eg_blackbox_history, c.c_int, [c.c_char_p, c.c_int])
    _sig(L.eg_blackbox_dump, c.c_int, [c.c_char_p])
    _sig(L.eg_blackbox_reset, None, [])
    _sig(L.eg_fault_config, c.c_int, [c.c_char_p, c.c_uint64])
    _sig(L.eg_fault_clear, None, [])
    _sig(L.eg_fault_count, c.c_int, [])
    _sig(L.eg_fault_name, c.c_char_p, [c.c_int])
    _sig(L.eg_fault_injected, None, [u64p])
    _sig(L.eg_remote_create, p, [c.c_char_p])
    _sig(L.eg_remote_shards, c.c_int, [p])
    _sig(L.eg_remote_partitions, c.c_int, [p])
    _sig(L.eg_remote_replica_count, c.c_int, [p, c.c_int])
    _sig(L.eg_remote_has_placement, c.c_int, [p])
    _sig(L.eg_remote_route, None, [p, u64p, c.c_int, i32p])
    _sig(L.eg_remote_strict_error, c.c_int, [p, c.c_char_p, c.c_int])
    _sig(L.eg_remote_epoch, c.c_uint64, [p, c.c_int])
    _sig(L.eg_remote_cache_gen, c.c_uint64, [p])
    _sig(L.eg_remote_load_delta, c.c_int64, [p, c.c_int, c.c_char_p])
    _sig(
        L.eg_remote_sample_async,
        c.c_int,
        [
            p, u64p, c.c_int, i32p, i32p, i32p, c.c_int, c.c_uint64,
            c.POINTER(u64p), c.POINTER(f32p), c.POINTER(i32p),
        ],
    )
    _sig(L.eg_remote_async_poll, c.c_int, [p, c.c_int])
    _sig(L.eg_remote_async_take, c.c_int, [p, c.c_int])
    _sig(
        L.eg_service_start,
        p,
        [c.c_char_p, c.c_int, c.c_int, c.c_char_p, c.c_int, c.c_char_p,
         c.c_char_p],
    )
    _sig(L.eg_service_port, c.c_int, [p])
    _sig(L.eg_service_drain, None, [p, c.c_int])
    _sig(L.eg_service_load_delta, c.c_int64, [p, c.c_char_p])
    _sig(L.eg_service_epoch, c.c_uint64, [p])
    _sig(L.eg_service_stop, None, [p])
    _sig(L.eg_registry_start, p, [c.c_char_p, c.c_int, c.c_int])
    _sig(L.eg_registry_port, c.c_int, [p])
    _sig(L.eg_registry_stop, None, [p])
    _sig(
        L.eg_registry_query,
        c.c_int,
        [c.c_char_p, c.c_int, c.c_int, c.c_char_p, c.c_int],
    )
    _sig(L.eg_num_nodes, c.c_int64, [p])
    _sig(L.eg_num_edges, c.c_int64, [p])
    _sig(L.eg_node_type_num, c.c_int32, [p])
    _sig(L.eg_edge_type_num, c.c_int32, [p])
    _sig(L.eg_feature_num, c.c_int32, [p, c.c_int])
    _sig(L.eg_type_weight_sums, None, [p, c.c_int, f32p])
    _sig(L.eg_sample_node, None, [p, c.c_int, c.c_int32, u64p])
    _sig(L.eg_sample_edge, None, [p, c.c_int, c.c_int32, u64p, u64p, i32p])
    _sig(L.eg_sample_node_with_src, None, [p, u64p, c.c_int, c.c_int, u64p])
    _sig(L.eg_get_node_type, None, [p, u64p, c.c_int, i32p])
    _sig(L.eg_get_node_weight, c.c_int, [p, u64p, c.c_int, f32p])
    _sig(
        L.eg_sample_neighbor,
        None,
        [p, u64p, c.c_int, i32p, c.c_int, c.c_int, c.c_uint64, u64p, f32p, i32p],
    )
    _sig(
        L.eg_sample_fanout,
        None,
        [
            p, u64p, c.c_int, i32p, i32p, i32p, c.c_int, c.c_uint64,
            c.POINTER(u64p), c.POINTER(f32p), c.POINTER(i32p),
        ],
    )
    _sig(
        L.eg_build_alias_csr,
        None,
        [c.POINTER(c.c_int64), c.c_int64, f32p, f32p, i32p],
    )
    _sig(L.eg_get_full_neighbor, p, [p, u64p, c.c_int, i32p, c.c_int, c.c_int])
    _sig(
        L.eg_get_top_k_neighbor,
        None,
        [p, u64p, c.c_int, i32p, c.c_int, c.c_int, c.c_uint64, u64p, f32p, i32p],
    )
    _sig(
        L.eg_random_walk,
        None,
        [p, u64p, c.c_int, i32p, i32p, c.c_int, c.c_float, c.c_float,
         c.c_uint64, u64p],
    )
    _sig(
        L.eg_get_dense_feature,
        None,
        [p, u64p, c.c_int, i32p, i32p, c.c_int, f32p],
    )
    _sig(
        L.eg_get_edge_dense_feature,
        None,
        [p, u64p, u64p, i32p, c.c_int, i32p, i32p, c.c_int, f32p],
    )
    _sig(L.eg_get_sparse_feature, p, [p, u64p, c.c_int, i32p, c.c_int])
    _sig(
        L.eg_get_edge_sparse_feature,
        p,
        [p, u64p, u64p, i32p, c.c_int, i32p, c.c_int],
    )
    _sig(L.eg_get_binary_feature, p, [p, u64p, c.c_int, i32p, c.c_int])
    _sig(
        L.eg_get_edge_binary_feature,
        p,
        [p, u64p, u64p, i32p, c.c_int, i32p, c.c_int],
    )
    _sig(L.eg_result_size, c.c_int64, [p, c.c_int, c.c_int])
    _sig(L.eg_result_copy, None, [p, c.c_int, c.c_int, p])
    _sig(L.eg_result_free, None, [p])
    _lib = L
    return L


def stats() -> dict:
    """Snapshot of the native span-timer accumulators (process-global:
    embedded engine calls, remote client round-trips, and served shard
    requests all record here — see _native/eg_stats.h). Returns
    {op: {count, total_ms, avg_us, max_us}} for ops with count > 0."""
    import numpy as np

    L = lib()
    n = L.eg_stat_count()
    counts = np.zeros(n, dtype=np.uint64)
    total = np.zeros(n, dtype=np.uint64)
    mx = np.zeros(n, dtype=np.uint64)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    L.eg_stats_snapshot(
        counts.ctypes.data_as(u64p),
        total.ctypes.data_as(u64p),
        mx.ctypes.data_as(u64p),
    )
    out = {}
    for i in range(n):
        if counts[i] == 0:
            continue
        name = L.eg_stat_name(i).decode()
        out[name] = {
            "count": int(counts[i]),
            "total_ms": float(total[i]) / 1e6,
            "avg_us": float(total[i]) / float(counts[i]) / 1e3,
            "max_us": float(mx[i]) / 1e3,
        }
    return out


def stats_reset() -> None:
    """Zero the native span-timer accumulators."""
    lib().eg_stats_reset()


def counters() -> dict:
    """Snapshot of the native counters (process-global, see
    _native/eg_stats.h Counters). Failure side — how often the remote
    transport had to fight for an answer: {"dials_failed": n,
    "retries": n, "quarantines": n, "failovers": n, "calls_failed": n,
    "deadlines_exceeded": n, "frames_rejected": n, "rediscoveries": n,
    "heartbeat_misses": n, "rpc_errors": n}. Efficiency side — the
    remote hot path's communication-win ledger: {"ids_deduped": n,
    "cache_hits": n, "cache_misses": n, "rpc_chunks": n}
    (ids_on_wire = ids_requested - ids_deduped - cache_hits; see
    FAULTS.md for per-counter semantics). Snapshot-epoch side —
    the graph-refresh ledger: {"epoch_flips": n, "epoch_drains": n,
    "epoch_stale_hits_evicted": n, "delta_loads_failed": n} (flips ==
    drains once quiescent; see FAULTS.md). All keys always present (zero
    included), so dashboards and the chaos soak can diff snapshots
    without key existence checks."""
    L = lib()
    n = L.eg_counter_count()
    arr = (ctypes.c_uint64 * n)()
    L.eg_counters_snapshot(arr)
    return {L.eg_counter_name(i).decode(): int(arr[i]) for i in range(n)}


def reset_counters() -> None:
    """Zero the native failure/efficiency counters (process-global) —
    the clean-slate primitive tests and benches use instead of
    before/after delta arithmetic over :func:`counters` snapshots."""
    lib().eg_counters_reset()


# older spelling, kept so existing callers and muscle memory both work
counters_reset = reset_counters

_counter_ids: dict = {}


def counter_add(name: str, n: int = 1) -> None:
    """Bump one native counter by name (the prefetch pipeline's Python
    threads account into the same ledger the native transport uses, so
    one :func:`counters` snapshot or STATS scrape covers both).
    Raises KeyError on an unknown counter name."""
    if not _counter_ids:
        L = lib()
        for i in range(L.eg_counter_count()):
            _counter_ids[L.eg_counter_name(i).decode()] = i
    lib().eg_counter_add(_counter_ids[name], n)


def fault_config(spec: str, seed: int = 0) -> None:
    """Install a process-global deterministic failpoint spec (FAULTS.md),
    e.g. ``recv_frame:err@0.5,dial:delay@200``. ``seed`` makes each
    failpoint's failure sequence replayable: the same seed fires the
    same pattern of faults at each point. Raises ValueError on a
    malformed spec (nothing installed). An empty spec clears."""
    rc = lib().eg_fault_config(spec.encode(), seed)
    if rc != 0:
        raise ValueError(lib().eg_last_error().decode())


def fault_clear() -> None:
    """Remove every installed failpoint (back to the zero-cost path)."""
    lib().eg_fault_clear()


def fault_injected() -> dict:
    """Injected-fault ledger: {failpoint: fires since its last config},
    all failpoints always present — the ground truth the failure
    counters are audited against in the chaos soak."""
    L = lib()
    n = L.eg_fault_count()
    arr = (ctypes.c_uint64 * n)()
    L.eg_fault_injected(arr)
    return {L.eg_fault_name(i).decode(): int(arr[i]) for i in range(n)}
