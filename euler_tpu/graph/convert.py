"""JSON graph → binary .dat partition converter.

Produces the same length-prefixed block format as the reference tooling
(format spec derived from /root/reference/euler/tools/json2dat.py:40-175 and
the Java partitioned converter tools/graph_data_parser/GraphDataParser.java:85),
so fixtures and datasets interoperate in both directions. Partitioning follows
the reference convention by default: node_id % num_partitions ->
``<prefix>_<p>.dat``. ``placement='degree'`` swaps in a greedy
degree-descending placer that co-locates hub vertices with their sampled
neighborhoods under a balance cap and emits a compact
``<prefix>.placement`` artifact (id -> partition; format in
eg_placement.h) that shards serve to clients over the kPlacement wire op
— the locality-aware half of ROADMAP item 5 (PERF.md "Locality").

Input: one JSON object per line::

    {"node_id": 1, "node_type": 0, "node_weight": 1.0,
     "neighbor": {"0": {"2": 1.0}},          # edge_type -> {dst: weight}
     "uint64_feature": {"0": [1, 2]},        # slot -> values
     "float_feature": {"0": [0.5]},
     "binary_feature": {"0": "ab"},
     "edge": [{"src_id": 1, "dst_id": 2, "edge_type": 0, "weight": 1.0,
               "uint64_feature": {}, "float_feature": {},
               "binary_feature": {}}]}

plus a meta.json declaring type/slot counts (node_type_num, edge_type_num,
node_uint64_feature_num, node_float_feature_num, node_binary_feature_num and
the three edge_* equivalents).
"""

from __future__ import annotations

import json
import struct
from typing import IO

# Balance slack of the degree-aware partitioner: no partition may hold
# more than ceil(slack * N / P) nodes, so locality can never collapse
# every hub neighborhood into one shard (the load-balance half of the
# GNNSampler/FastSample trade-off).
PLACEMENT_SLACK = 1.2


def _pack_features(record: dict, slot_nums: dict[str, int]) -> bytes:
    """Pack the u64/f32/binary feature sections shared by node and edge
    records: for each kind, ``i32 slot_num, i32 sizes[slot_num], values``."""
    out = []
    for kind, fmt_char in (("uint64", "Q"), ("float", "f"), ("binary", "s")):
        nslots = slot_nums[kind]
        slots = record.get(kind + "_feature", {}) or {}
        sizes = []
        values = []
        for i in range(nslots):
            v = slots.get(str(i), [] if kind != "binary" else "")
            if kind == "binary":
                b = v.encode() if isinstance(v, str) else bytes(v)
                sizes.append(len(b))
                values.append(b)
            else:
                sizes.append(len(v))
                values.extend(v)
        out.append(struct.pack("<i%di" % nslots, nslots, *sizes))
        if kind == "binary":
            out.append(b"".join(values))
        else:
            out.append(struct.pack("<%d%s" % (len(values), fmt_char), *values))
    return b"".join(out)


def _pack_edge(edge: dict, meta: dict) -> bytes:
    slot_nums = {
        "uint64": int(meta["edge_uint64_feature_num"]),
        "float": int(meta["edge_float_feature_num"]),
        "binary": int(meta["edge_binary_feature_num"]),
    }
    head = struct.pack(
        "<QQif",
        int(edge["src_id"]),
        int(edge["dst_id"]),
        int(edge["edge_type"]),
        float(edge["weight"]),
    )
    return head + _pack_features(edge, slot_nums)


def pack_block(node: dict, meta: dict) -> bytes:
    """Serialize one node line into a framed block."""
    edge_type_num = int(meta["edge_type_num"])
    neighbor = node.get("neighbor", {}) or {}
    group_sizes = []
    group_weights = []
    nbr_ids = []
    nbr_ws = []
    for t in range(edge_type_num):
        group = neighbor.get(str(t), {}) or {}
        group_sizes.append(len(group))
        group_weights.append(float(sum(group.values())))
        for dst, w in group.items():
            nbr_ids.append(int(dst))
            nbr_ws.append(float(w))

    slot_nums = {
        "uint64": int(meta["node_uint64_feature_num"]),
        "float": int(meta["node_float_feature_num"]),
        "binary": int(meta["node_binary_feature_num"]),
    }
    node_rec = b"".join(
        [
            struct.pack(
                "<Qifi",
                int(node["node_id"]),
                int(node["node_type"]),
                float(node["node_weight"]),
                edge_type_num,
            ),
            struct.pack("<%di" % edge_type_num, *group_sizes),
            struct.pack("<%df" % edge_type_num, *group_weights),
            struct.pack("<%dQ" % len(nbr_ids), *nbr_ids),
            struct.pack("<%df" % len(nbr_ws), *nbr_ws),
            _pack_features(node, slot_nums),
        ]
    )

    edges = [_pack_edge(e, meta) for e in node.get("edge", [])]
    edge_sizes = [len(e) for e in edges]
    # block_bytes counts everything after itself: the node_info_bytes field,
    # the node record, the edge_num field, the edge size list, and the edges.
    block_bytes = 4 + len(node_rec) + 4 + 4 * len(edges) + sum(edge_sizes)
    return b"".join(
        [
            struct.pack("<ii", block_bytes, len(node_rec)),
            node_rec,
            struct.pack("<i%di" % len(edges), len(edges), *edge_sizes),
            b"".join(edges),
        ]
    )


def degree_placement(
    nodes: list[dict],
    num_partitions: int,
    slack: float = PLACEMENT_SLACK,
) -> dict[int, int]:
    """Greedy degree-descending placement: node_id -> partition.

    Hubs (highest total degree: out-edges plus the in-edges the reverse
    adjacency reveals) are placed first and spread across partitions by
    load; every later node lands in the partition where the most of its
    already-placed neighborhood edge mass lives, under the balance cap
    ceil(slack * N / P). On power-law graphs this co-locates each
    low-degree node with the hub(s) it points at, which is where nearly
    all of its sampled hops go — the edge-cut win hash sharding cannot
    see (GNNSampler arXiv:2108.11571, FastSample arXiv:2311.17847).
    """
    # adjacency as (neighbor, weight) in BOTH directions: a node's
    # sampled hops follow its out-edges, but a hub's affinity must also
    # count the many nodes pointing AT it
    adj: dict[int, list[tuple[int, float]]] = {}
    degree: dict[int, float] = {}
    for node in nodes:
        u = int(node["node_id"])
        adj.setdefault(u, [])
        degree.setdefault(u, 0.0)
        for group in (node.get("neighbor", {}) or {}).values():
            for dst, w in (group or {}).items():
                v, w = int(dst), float(w)
                adj[u].append((v, w))
                adj.setdefault(v, []).append((u, w))
                degree[u] = degree.get(u, 0.0) + w
                degree[v] = degree.get(v, 0.0) + w
    order = sorted(
        (int(n["node_id"]) for n in nodes),
        key=lambda u: (-degree.get(u, 0.0), u),
    )
    n_nodes = len(nodes)
    cap = max(1, -(-int(n_nodes * slack) // num_partitions))
    load = [0] * num_partitions
    placed: dict[int, int] = {}
    for u in order:
        score = [0.0] * num_partitions
        for v, w in adj.get(u, ()):
            p = placed.get(v)
            if p is not None:
                score[p] += w
        best, best_key = -1, None
        for p in range(num_partitions):
            if load[p] >= cap:
                continue
            key = (score[p], -load[p])  # affinity first, then balance
            if best_key is None or key > best_key:
                best, best_key = p, key
        if best < 0:  # every partition at cap (slack rounding): spill
            best = min(range(num_partitions), key=lambda p: load[p])
        placed[u] = best
        load[best] += 1
    return placed


def write_placement(
    path: str, placed: dict[int, int], num_partitions: int
) -> None:
    """Serialize a placement map into the compact artifact the shards
    serve (kPlacement) and clients route by — format pinned by the
    native parser (eg_placement.h): ``EGP1 | i32 P | i64 count |
    u64 ids[count] | i32 parts[count]``, little-endian."""
    import numpy as np

    ids = np.fromiter(placed.keys(), dtype=np.int64,
                      count=len(placed)).view(np.uint64)
    parts = np.fromiter(placed.values(), dtype=np.int32, count=len(placed))
    order = np.argsort(ids)
    with open(path, "wb") as f:
        f.write(b"EGP1")
        f.write(struct.pack("<iq", num_partitions, len(placed)))
        f.write(ids[order].tobytes())
        f.write(parts[order].tobytes())


def _check_partitions(num_partitions: int) -> None:
    if num_partitions < 1:
        raise ValueError(
            f"num_partitions must be >= 1, got {num_partitions} (0 or "
            "negative would write no .dat files at all)"
        )


def _check_placement(placement: str) -> None:
    if placement not in ("hash", "degree"):
        raise ValueError(
            f"placement must be 'hash' (node_id % P, the default) or "
            f"'degree' (greedy hub co-location + placement artifact), "
            f"got {placement!r}"
        )


def _write_partitions(
    nodes: list[dict],
    meta: dict,
    output_prefix: str,
    num_partitions: int,
    placement: str,
) -> list[str]:
    """Shared writer: route every node block to its partition (hash or
    placement map), rejecting duplicate node_ids LOUDLY — a duplicate
    would silently overwrite the row in whichever partition wins, and
    under placement routing could even land the two copies on different
    shards."""
    placed = (
        degree_placement(nodes, num_partitions)
        if placement == "degree"
        else None
    )
    paths = ["%s_%d.dat" % (output_prefix, p) for p in range(num_partitions)]
    outs: list[IO[bytes]] = [open(p, "wb") for p in paths]
    seen: set[int] = set()
    try:
        for node in nodes:
            nid = int(node["node_id"])
            if nid in seen:
                raise ValueError(
                    f"duplicate node_id {nid} in input — each node must "
                    "appear exactly once (a duplicate would overwrite "
                    "the earlier row in whichever partition wins)"
                )
            seen.add(nid)
            p = placed[nid] if placed is not None else nid % num_partitions
            outs[p].write(pack_block(node, meta))
    finally:
        for o in outs:
            o.close()
    if placed is not None:
        write_placement(
            output_prefix + ".placement", placed, num_partitions
        )
    return paths


def convert(
    meta_path: str,
    input_path: str,
    output_prefix: str,
    num_partitions: int = 1,
    placement: str = "hash",
) -> list[str]:
    """Convert a JSON-lines graph into ``num_partitions`` .dat files.

    ``placement='degree'`` replaces hash partitioning with the greedy
    degree-descending placement (hub neighborhoods co-located under a
    balance cap) and writes the ``<prefix>.placement`` artifact next to
    the partitions; shards serve it and clients route by it
    (eg_placement.h). The whole graph is held in memory for the
    placement pass — for hash partitioning too, since duplicate-id
    validation needs the full id set anyway and fixture-scale inputs
    dominate this path.

    Returns the list of written partition paths.
    """
    _check_partitions(num_partitions)
    _check_placement(placement)
    with open(meta_path) as f:
        meta = json.load(f)
    nodes = []
    with open(input_path) as f:
        for line in f:
            line = line.strip()
            if line:
                nodes.append(json.loads(line))
    return _write_partitions(
        nodes, meta, output_prefix, num_partitions, placement
    )


def convert_dicts(
    nodes: list[dict],
    meta: dict,
    output_prefix: str,
    num_partitions: int = 1,
    placement: str = "hash",
) -> list[str]:
    """Like :func:`convert` but from in-memory dicts (used by tests and the
    synthetic benchmark generator)."""
    _check_partitions(num_partitions)
    _check_placement(placement)
    return _write_partitions(
        nodes, meta, output_prefix, num_partitions, placement
    )


# ---- snapshot-epoch delta files (eg_epoch.h) ----
# `<prefix>.delta.<n>` carries one graph refresh: removed node ids,
# removed edge keys, and a standard .dat block stream of added/replaced
# records (full replacement — GraphStore::Build's first-occurrence-wins
# dedup makes the newest delta authoritative when stagings merge
# newest-first). Layout, all little-endian, array = i64 count + raw
# elements (WireWriter::Arr), string = i64 length + bytes:
#   "EGD1" [u32 version=1] [u64 seq]
#   [arr u64 removed_nodes]
#   [arr u64 rme_src] [arr u64 rme_dst] [arr i32 rme_type]
#   [str dat_blob]


def pack_delta(
    seq: int,
    removed_nodes: list[int],
    removed_edges: list[tuple[int, int, int]],
    dat_blob: bytes,
) -> bytes:
    """Serialize one delta payload (format above). ``removed_edges`` are
    (src, dst, edge_type) keys; ``dat_blob`` a .dat block stream of the
    added/replaced node records."""

    def arr(fmt: str, vals) -> bytes:
        vals = list(vals)
        return struct.pack("<q", len(vals)) + struct.pack(
            "<%d%s" % (len(vals), fmt), *vals
        )

    u64 = lambda v: int(v) & 0xFFFFFFFFFFFFFFFF  # noqa: E731
    return b"".join(
        [
            b"EGD1",
            struct.pack("<IQ", 1, int(seq)),
            arr("Q", (u64(v) for v in removed_nodes)),
            arr("Q", (u64(e[0]) for e in removed_edges)),
            arr("Q", (u64(e[1]) for e in removed_edges)),
            arr("i", (int(e[2]) for e in removed_edges)),
            struct.pack("<q", len(dat_blob)),
            dat_blob,
        ]
    )


def _index_nodes(nodes: list[dict], label: str) -> dict[int, dict]:
    """Index nodes by id, rejecting duplicates LOUDLY — a duplicate in a
    delta input is a contradictory edit (two different replacement rows
    for one node; whichever won would be arbitrary)."""
    out: dict[int, dict] = {}
    for node in nodes:
        nid = int(node["node_id"])
        if nid in out:
            raise ValueError(
                f"duplicate node_id {nid} in {label} input — a delta "
                "must carry exactly one replacement record per node"
            )
        out[nid] = node
    return out


def _edge_keys(node: dict, label: str) -> set[tuple[int, int, int]]:
    """The (src, dst, type) edge-record keys of one node, rejecting
    duplicates — two records for one key is a contradictory edit (their
    weights/features could differ and one would silently win)."""
    keys: set[tuple[int, int, int]] = set()
    for e in node.get("edge", []) or []:
        k = (int(e["src_id"]), int(e["dst_id"]), int(e["edge_type"]))
        if k in keys:
            raise ValueError(
                f"duplicate edge record {k} in {label} input — a delta "
                "must carry exactly one record per (src, dst, type)"
            )
        keys.add(k)
    return keys


def make_delta(
    old_nodes: list[dict], new_nodes: list[dict], meta: dict
) -> tuple[list[int], list[tuple[int, int, int]], bytes]:
    """Diff two JSON-lines snapshots into one delta payload:
    (removed_nodes, removed_edges, dat_blob).

    Changed nodes are detected by canonical block bytes (pack_block), so
    a reordered-but-identical JSON line emits nothing. Edge-record
    removals are emitted only for edges entirely gone from a surviving
    node — a modified edge rides the node's replacement record instead
    (removing AND re-adding one key is the contradiction the native
    Validate rejects). Removed nodes drop their own edge records
    native-side (endpoint removal), so no keys are emitted for them."""
    old = _index_nodes(old_nodes, "old")
    new = _index_nodes(new_nodes, "new")
    removed_nodes = sorted(set(old) - set(new))
    removed_edges: list[tuple[int, int, int]] = []
    blocks: list[bytes] = []
    for nid in sorted(new):
        nb = pack_block(new[nid], meta)
        if nid not in old:
            blocks.append(nb)
            continue
        ob = pack_block(old[nid], meta)
        gone = sorted(
            _edge_keys(old[nid], "old") - _edge_keys(new[nid], "new")
        )
        removed_edges.extend(gone)
        if ob != nb:
            blocks.append(nb)
    return removed_nodes, removed_edges, b"".join(blocks)


def convert_delta(
    meta_path: str,
    old_input_path: str,
    new_input_path: str,
    output_prefix: str,
    seq: int = 1,
) -> str:
    """Diff two JSON-lines graphs into ``<output_prefix>.delta.<seq>``
    (the refresh payload shards merge and flip to; eg_epoch.h). Raises
    on duplicate/contradictory edits. Returns the written path."""

    def read_lines(path: str) -> list[dict]:
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out

    with open(meta_path) as f:
        meta = json.load(f)
    removed_nodes, removed_edges, blob = make_delta(
        read_lines(old_input_path), read_lines(new_input_path), meta
    )
    path = "%s.delta.%d" % (output_prefix, int(seq))
    with open(path, "wb") as f:
        f.write(pack_delta(seq, removed_nodes, removed_edges, blob))
    return path


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("meta", help="meta.json path")
    ap.add_argument("input", help="JSON-lines graph path")
    ap.add_argument("output_prefix", help="output prefix; writes <prefix>_<p>.dat")
    ap.add_argument("--partitions", type=int, default=1)
    ap.add_argument("--placement", choices=("hash", "degree"),
                    default="hash", help=(
                        "partitioning rule: 'hash' = node_id %% P "
                        "(reference convention); 'degree' = greedy hub "
                        "co-location + a <prefix>.placement artifact "
                        "shards serve to clients (locality-aware "
                        "routing, ROADMAP item 5)"))
    ap.add_argument("--delta-from", default=None, metavar="OLD_INPUT", help=(
        "emit a snapshot-epoch delta instead of partitions: diff "
        "OLD_INPUT (the currently-served JSON-lines graph) against "
        "INPUT (the refreshed one) into <output_prefix>.delta.<seq> — "
        "the payload `service --load_delta` / Graph.load_delta merge "
        "and flip to (eg_epoch.h). Duplicate or contradictory edits "
        "are rejected loudly"))
    ap.add_argument("--delta-seq", type=int, default=1, help=(
        "sequence number of the emitted delta (deltas apply in seq "
        "order; name and header both carry it)"))
    args = ap.parse_args()
    if args.delta_from is not None:
        print(convert_delta(args.meta, args.delta_from, args.input,
                            args.output_prefix, seq=args.delta_seq))
        return
    for p in convert(args.meta, args.input, args.output_prefix,
                     args.partitions, placement=args.placement):
        print(p)


if __name__ == "__main__":
    main()
