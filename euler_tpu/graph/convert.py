"""JSON graph → binary .dat partition converter.

Produces the same length-prefixed block format as the reference tooling
(format spec derived from /root/reference/euler/tools/json2dat.py:40-175 and
the Java partitioned converter tools/graph_data_parser/GraphDataParser.java:85),
so fixtures and datasets interoperate in both directions. Partitioning follows
the reference convention: node_id % num_partitions -> ``<prefix>_<p>.dat``.

Input: one JSON object per line::

    {"node_id": 1, "node_type": 0, "node_weight": 1.0,
     "neighbor": {"0": {"2": 1.0}},          # edge_type -> {dst: weight}
     "uint64_feature": {"0": [1, 2]},        # slot -> values
     "float_feature": {"0": [0.5]},
     "binary_feature": {"0": "ab"},
     "edge": [{"src_id": 1, "dst_id": 2, "edge_type": 0, "weight": 1.0,
               "uint64_feature": {}, "float_feature": {},
               "binary_feature": {}}]}

plus a meta.json declaring type/slot counts (node_type_num, edge_type_num,
node_uint64_feature_num, node_float_feature_num, node_binary_feature_num and
the three edge_* equivalents).
"""

from __future__ import annotations

import json
import struct
from typing import IO


def _pack_features(record: dict, slot_nums: dict[str, int]) -> bytes:
    """Pack the u64/f32/binary feature sections shared by node and edge
    records: for each kind, ``i32 slot_num, i32 sizes[slot_num], values``."""
    out = []
    for kind, fmt_char in (("uint64", "Q"), ("float", "f"), ("binary", "s")):
        nslots = slot_nums[kind]
        slots = record.get(kind + "_feature", {}) or {}
        sizes = []
        values = []
        for i in range(nslots):
            v = slots.get(str(i), [] if kind != "binary" else "")
            if kind == "binary":
                b = v.encode() if isinstance(v, str) else bytes(v)
                sizes.append(len(b))
                values.append(b)
            else:
                sizes.append(len(v))
                values.extend(v)
        out.append(struct.pack("<i%di" % nslots, nslots, *sizes))
        if kind == "binary":
            out.append(b"".join(values))
        else:
            out.append(struct.pack("<%d%s" % (len(values), fmt_char), *values))
    return b"".join(out)


def _pack_edge(edge: dict, meta: dict) -> bytes:
    slot_nums = {
        "uint64": int(meta["edge_uint64_feature_num"]),
        "float": int(meta["edge_float_feature_num"]),
        "binary": int(meta["edge_binary_feature_num"]),
    }
    head = struct.pack(
        "<QQif",
        int(edge["src_id"]),
        int(edge["dst_id"]),
        int(edge["edge_type"]),
        float(edge["weight"]),
    )
    return head + _pack_features(edge, slot_nums)


def pack_block(node: dict, meta: dict) -> bytes:
    """Serialize one node line into a framed block."""
    edge_type_num = int(meta["edge_type_num"])
    neighbor = node.get("neighbor", {}) or {}
    group_sizes = []
    group_weights = []
    nbr_ids = []
    nbr_ws = []
    for t in range(edge_type_num):
        group = neighbor.get(str(t), {}) or {}
        group_sizes.append(len(group))
        group_weights.append(float(sum(group.values())))
        for dst, w in group.items():
            nbr_ids.append(int(dst))
            nbr_ws.append(float(w))

    slot_nums = {
        "uint64": int(meta["node_uint64_feature_num"]),
        "float": int(meta["node_float_feature_num"]),
        "binary": int(meta["node_binary_feature_num"]),
    }
    node_rec = b"".join(
        [
            struct.pack(
                "<Qifi",
                int(node["node_id"]),
                int(node["node_type"]),
                float(node["node_weight"]),
                edge_type_num,
            ),
            struct.pack("<%di" % edge_type_num, *group_sizes),
            struct.pack("<%df" % edge_type_num, *group_weights),
            struct.pack("<%dQ" % len(nbr_ids), *nbr_ids),
            struct.pack("<%df" % len(nbr_ws), *nbr_ws),
            _pack_features(node, slot_nums),
        ]
    )

    edges = [_pack_edge(e, meta) for e in node.get("edge", [])]
    edge_sizes = [len(e) for e in edges]
    # block_bytes counts everything after itself: the node_info_bytes field,
    # the node record, the edge_num field, the edge size list, and the edges.
    block_bytes = 4 + len(node_rec) + 4 + 4 * len(edges) + sum(edge_sizes)
    return b"".join(
        [
            struct.pack("<ii", block_bytes, len(node_rec)),
            node_rec,
            struct.pack("<i%di" % len(edges), len(edges), *edge_sizes),
            b"".join(edges),
        ]
    )


def convert(
    meta_path: str,
    input_path: str,
    output_prefix: str,
    num_partitions: int = 1,
) -> list[str]:
    """Convert a JSON-lines graph into ``num_partitions`` .dat files.

    Returns the list of written partition paths.
    """
    with open(meta_path) as f:
        meta = json.load(f)
    paths = ["%s_%d.dat" % (output_prefix, p) for p in range(num_partitions)]
    outs: list[IO[bytes]] = [open(p, "wb") for p in paths]
    try:
        with open(input_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                node = json.loads(line)
                p = int(node["node_id"]) % num_partitions
                outs[p].write(pack_block(node, meta))
    finally:
        for o in outs:
            o.close()
    return paths


def convert_dicts(
    nodes: list[dict],
    meta: dict,
    output_prefix: str,
    num_partitions: int = 1,
) -> list[str]:
    """Like :func:`convert` but from in-memory dicts (used by tests and the
    synthetic benchmark generator)."""
    paths = ["%s_%d.dat" % (output_prefix, p) for p in range(num_partitions)]
    outs = [open(p, "wb") for p in paths]
    try:
        for node in nodes:
            p = int(node["node_id"]) % num_partitions
            outs[p].write(pack_block(node, meta))
    finally:
        for o in outs:
            o.close()
    return paths


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("meta", help="meta.json path")
    ap.add_argument("input", help="JSON-lines graph path")
    ap.add_argument("output_prefix", help="output prefix; writes <prefix>_<p>.dat")
    ap.add_argument("--partitions", type=int, default=1)
    args = ap.parse_args()
    for p in convert(args.meta, args.input, args.output_prefix, args.partitions):
        print(p)


if __name__ == "__main__":
    main()
