"""Remote-filesystem graph ingestion: stage fsspec URLs to a local cache.

Role equivalent of the reference's HDFS FileIO
(reference euler/common/hdfs_file_io.cc:79-80 reads graph partitions
straight off HDFS via libhdfs, selected through the scheme-keyed factory at
euler/common/file_io_factory.cc). The TPU-native reshape: the sampling
engine keeps one fast local read path (mmap-friendly, no network stalls in
the hot loop) and remote schemes — ``gs://``, ``s3://``, ``hdfs://``,
``memory://``, anything fsspec resolves — are staged once to a local cache
directory before the engine loads. That is also how TPU VMs are actually
fed (data staged to local SSD), and it is shard-aware: a shard downloads
only its own partitions, mirroring the native selection rule
(eg_engine.cc Engine::Load: partition index p from ``*_<p>.dat``,
kept when ``p % shard_num == shard_idx``).

Staging is idempotent and crash-safe: files land under a tmp name and are
renamed into place; a file already cached with the same size is not
re-fetched. Protocol drivers install separately (e.g. gcsfs for ``gs://``);
a missing driver raises with the package name instead of an opaque import
error.
"""

from __future__ import annotations

import hashlib
import os
import re
import shutil
import threading
from concurrent.futures import ThreadPoolExecutor

_PART_RE = re.compile(r"_(\d+)\.dat$")

#: schemes that are plain local paths even though they carry a "://"
_LOCAL_SCHEMES = ("file", "local")


def is_remote_path(path: str) -> bool:
    """True for fsspec-style URLs that need staging (gs://, s3://, ...)."""
    if "://" not in path:
        return False
    scheme = path.split("://", 1)[0]
    return scheme not in _LOCAL_SCHEMES


def strip_local_scheme(path: str) -> str:
    """file:///data/x -> /data/x; plain paths pass through."""
    for scheme in _LOCAL_SCHEMES:
        prefix = scheme + "://"
        if path.startswith(prefix):
            return path[len(prefix):] or "/"
    return path


def default_cache_dir() -> str:
    return os.environ.get(
        "EULER_TPU_CACHE",
        os.path.join(
            os.path.expanduser("~"), ".cache", "euler_tpu", "staged"
        ),
    )


def _filesystem(url: str):
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is a base dep here
        raise RuntimeError(
            f"loading {url} needs the fsspec package"
        ) from e
    try:
        return fsspec.core.url_to_fs(url)
    except (ImportError, ValueError) as e:
        scheme = url.split("://", 1)[0]
        raise RuntimeError(
            f"no fsspec driver installed for {scheme}:// "
            f"(install e.g. gcsfs for gs://, s3fs for s3://): {e}"
        ) from e


def partition_index(name: str) -> int:
    """Trailing ``_<p>.dat`` partition index; -1 when absent.

    Mirrors the native parser (eg_engine.cc:14-16) so remote staging and
    local loading select identical file sets.
    """
    m = _PART_RE.search(os.path.basename(name))
    return int(m.group(1)) if m else -1


def _fetch(fs, remote: str, local: str) -> None:
    # tmp name unique per process AND thread: concurrent stagers (worker
    # processes or threads on one host) must never interleave writes into
    # the same partial file; os.replace publishes only complete files
    tmp = f"{local}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        fs.get_file(remote, tmp)
        os.replace(tmp, local)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _shard_partitions(fs, root: str, shard_idx: int, shard_num: int,
                      url: str | None = None):
    """List this shard's ``.dat`` partition entries under ``root`` —
    the ONE copy of the selection rule, shared by staged and streamed
    ingest so the two modes can never pick different file sets. It
    matches the native loader exactly (eg_engine.cc Engine::Load): a
    name without a ``_<p>.dat`` suffix belongs to partition 0, so under
    sharding it goes to shard 0, not to no shard.

    Returns (partition entries, meta.json entry or None).
    """
    picked = []
    meta = None
    for ent in fs.ls(root, detail=True):
        name = os.path.basename(ent["name"])
        if name == "meta.json":
            meta = ent
            continue
        if not name.endswith(".dat"):
            continue
        p = partition_index(name)
        if p < 0:
            p = 0
        if shard_num > 1 and p % shard_num != shard_idx:
            continue
        picked.append(ent)
    if not picked:
        # report the URL the caller actually passed, not the
        # scheme-stripped root — the error must map back to the config
        raise FileNotFoundError(
            f"no .dat partitions for shard {shard_idx}/{shard_num} "
            f"in {url or root}"
        )
    return picked, meta


def stage_directory(
    url: str,
    cache_dir: str | None = None,
    shard_idx: int = 0,
    shard_num: int = 1,
    refresh: bool = False,
) -> str:
    """Download a remote graph directory's ``.dat`` partitions (and
    meta.json when present) for this shard; return the local directory.

    The cache key includes the URL and the shard selection, so different
    shards staged on one host do not collide.
    """
    fs, root = _filesystem(url)
    key = hashlib.sha1(
        f"{url}|{shard_idx}/{shard_num}".encode()
    ).hexdigest()[:16]
    out = os.path.join(cache_dir or default_cache_dir(), key)
    os.makedirs(out, exist_ok=True)

    picked, meta = _shard_partitions(fs, root, shard_idx, shard_num, url)

    want = picked + ([meta] if meta else [])
    keep = {os.path.basename(e["name"]) for e in want}
    # drop cache entries absent from the current remote listing — a
    # repartitioned dataset at the same URL must not mix old and new
    # files when eg_load scans the staged directory
    for name in os.listdir(out):
        if name not in keep and ".tmp." not in name:
            # (.tmp.* files may belong to a concurrent stager mid-fetch)
            os.unlink(os.path.join(out, name))

    def fetch_one(ent):
        name = os.path.basename(ent["name"])
        local = os.path.join(out, name)
        size = ent.get("size")
        if (
            not refresh
            and os.path.exists(local)
            and size is not None
            and os.path.getsize(local) == size
        ):
            return
        _fetch(fs, ent["name"], local)

    # concurrent fetches: object stores serve objects far below host
    # bandwidth; distinct files are safe to fetch in parallel
    with ThreadPoolExecutor(max_workers=min(8, len(want))) as ex:
        list(ex.map(fetch_one, want))
    return out


def read_directory(
    url: str,
    shard_idx: int = 0,
    shard_num: int = 1,
) -> list[tuple[str, bytes]]:
    """Fetch this shard's ``.dat`` partitions straight into memory —
    the STREAMING ingest path (``Graph(..., stream=True)``): bytes go
    fetch → native parse → store with no local staging file, so a host
    needs RAM for the graph but zero local disk (the stage-then-load
    default additionally needs disk ≥ the shard's partition bytes; see
    DEPLOY.md). Same shard-selection rule as stage_directory/eg_load.

    Returns (basename, bytes) pairs; the native merge sorts by name, so
    fetch completion order cannot change the built store.

    RAM budget: the raw partition bytes, their parse-staging copies,
    and the built store are all resident at the peak (inside the one
    ``eg_load_buffers`` call) — plan for roughly raw + store, i.e.
    ~2-3x the store alone. The staged default instead needs local disk
    for the raw bytes and only ``nthreads`` files in memory at once.
    """
    fs, root = _filesystem(url)
    picked, _ = _shard_partitions(fs, root, shard_idx, shard_num, url)
    names = [ent["name"] for ent in picked]
    with ThreadPoolExecutor(max_workers=min(8, len(names))) as ex:
        blobs = list(ex.map(fs.cat_file, names))
    return [(os.path.basename(p), b) for p, b in zip(names, blobs)]


def _reject_duplicates(urls: list[str]) -> None:
    """Duplicate URLs in an explicit file list must fail loudly here:
    they would reach the native name-sorted merge as equal keys, where
    std::sort leaves their relative order unspecified — the built store
    would differ run to run with no hint why."""
    seen: set[str] = set()
    dups = sorted({u for u in urls if u in seen or seen.add(u)})
    if dups:
        raise ValueError(
            f"duplicate file URL(s) in files=: {dups} (the native merge "
            "sorts by name, so every name must be unique for a "
            "deterministic store)"
        )


def read_files(urls: list[str]) -> list[tuple[str, bytes]]:
    """Streamed counterpart of stage_files: fetch each file's bytes —
    remote via fsspec, local straight off disk — with no staging copy.
    The full URL/path is the returned name (basenames in an explicit
    file list can collide, and the native merge sorts by name, so names
    must be unique for the order to be deterministic; duplicates raise).
    """
    _reject_duplicates(urls)

    def fetch_one(url: str) -> tuple[str, bytes]:
        if is_remote_path(url):
            fs, path = _filesystem(url)
            try:
                return url, fs.cat_file(path)
            except FileNotFoundError:
                raise FileNotFoundError(f"no such remote file: {url}")
        local = strip_local_scheme(url)
        with open(local, "rb") as f:
            return url, f.read()

    if not urls:
        return []
    # concurrent like stage/read_directory: object stores serve objects
    # far below host bandwidth
    with ThreadPoolExecutor(max_workers=min(8, len(urls))) as ex:
        return list(ex.map(fetch_one, urls))


def stage_files(
    urls: list[str],
    cache_dir: str | None = None,
    refresh: bool = False,
) -> list[str]:
    """Stage an explicit file list; local paths pass through untouched.
    Duplicate URLs raise, for the same determinism reason as read_files."""
    _reject_duplicates(urls)
    out = []
    for url in urls:
        if not is_remote_path(url):
            out.append(strip_local_scheme(url))
            continue
        fs, path = _filesystem(url)
        key = hashlib.sha1(url.encode()).hexdigest()[:16]
        d = os.path.join(cache_dir or default_cache_dir(), key)
        os.makedirs(d, exist_ok=True)
        local = os.path.join(d, os.path.basename(path))
        try:
            size = fs.info(path).get("size")
        except FileNotFoundError:
            raise FileNotFoundError(f"no such remote file: {url}")
        fresh = (
            not refresh
            and os.path.exists(local)
            and size is not None
            and os.path.getsize(local) == size
        )
        if not fresh:
            _fetch(fs, path, local)
        out.append(local)
    return out


def clear_cache(cache_dir: str | None = None) -> None:
    d = cache_dir or default_cache_dir()
    if os.path.isdir(d):
        shutil.rmtree(d)
