"""Graph-service shard launcher.

Role equivalent of the reference's service launcher
(reference euler/python/service.py:30-50, which ctypes-loads
libeuler_service.so and runs StartService on a daemon thread): here the
native Service (eg_service.cc) runs its own poller + bounded handler
pool (eg_admission.h), so ``GraphService(...)`` returns as soon as the
shard has loaded its partitions and bound its port. Discovery replaces
ZooKeeper with either a flat-file registry directory (shared filesystem)
or a TCP registry (``registry="tcp://host:port"`` of a
euler_tpu.graph.registry server, for multi-host pods without a shared
FS; the shard heartbeats to keep its TTL entry alive — see
eg_registry.h).

Survivability knobs (eg_admission.h): ``workers=`` bounds the handler
pool (default 2x cores), ``pending=`` the admitted-work headroom beyond
it — excess connections get a BUSY reply the client fails over on —
and ``drain()`` runs the graceful half of a rolling restart
(deregister -> finish in-flight -> close; DEPLOY.md runbook). The
standalone process wires SIGTERM to exactly that drain.

Also runnable as a standalone shard process:
    python -m euler_tpu.graph.service --data_dir d --shard_idx 0 \
        --shard_num 2 --port 9001 --registry /shared/reg
"""

from __future__ import annotations

from euler_tpu.graph.native import lib


class GraphService:
    """One graph shard served over TCP; stops on close() or GC."""

    def __init__(
        self,
        data_dir: str,
        shard_idx: int = 0,
        shard_num: int = 1,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: str | None = None,
        workers: int | None = None,
        pending: int | None = None,
        options: str | None = None,
        postmortem_dir: str | None = None,
        blackbox: bool | None = None,
    ):
        self._lib = lib()
        from euler_tpu.graph import remote_fs

        if remote_fs.is_remote_path(data_dir):
            # shared/multi-host mode is the path that most needs remote
            # data: stage this shard's partitions before the native loader
            data_dir = remote_fs.stage_directory(
                data_dir, shard_idx=shard_idx, shard_num=shard_num
            )
        else:
            data_dir = remote_fs.strip_local_scheme(data_dir)
        # admission spec (eg_admission.h): the common knobs get kwargs,
        # the long tail (max_conns, io_timeout_ms, idle_timeout_ms,
        # linger_ms, drain_ms, wire_version, telemetry, slow_spans,
        # blackbox, heat, heat_topk, postmortem_dir) rides in options=
        opts = []
        if workers is not None:
            opts.append(f"workers={int(workers)}")
        if pending is not None:
            opts.append(f"pending={int(pending)}")
        if blackbox is not None:
            opts.append(f"blackbox={1 if blackbox else 0}")
        if postmortem_dir is not None:
            # the native probe fails loudly on an unwritable dir; create
            # it here so `postmortem_dir=<fresh tmp path>` just works
            import os

            os.makedirs(postmortem_dir, exist_ok=True)
            opts.append(f"postmortem_dir={postmortem_dir}")
        if options:
            opts.append(options)
        self._h = self._lib.eg_service_start(
            data_dir.encode(),
            shard_idx,
            shard_num,
            host.encode(),
            port,
            (registry or "").encode(),
            ";".join(opts).encode(),
        )
        if not self._h:
            err = self._lib.eg_last_error().decode()
            raise RuntimeError(f"graph service start failed: {err}")
        self.host = host
        self.port = self._lib.eg_service_port(self._h)
        self.shard_idx = shard_idx
        self.shard_num = shard_num

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def epoch(self) -> int:
        """Current serving snapshot epoch (0 until the first delta
        flip; eg_epoch.h)."""
        if not getattr(self, "_h", None):
            return 0
        return int(self._lib.eg_service_epoch(self._h))

    def load_delta(self, path: str) -> int:
        """Merge one `<prefix>.delta.<n>` file (convert.py --delta-from)
        into a fresh snapshot and flip the serving epoch — in-flight and
        previous-epoch-pinned requests keep reading the old snapshot
        until they drain (DEPLOY.md 'Rolling graph refresh'). Returns
        the new epoch; raises on parse/validation/merge failure, with
        the old snapshot still serving (counted delta_loads_failed)."""
        ep = self._lib.eg_service_load_delta(self._h, path.encode())
        if ep < 0:
            raise RuntimeError(self._lib.eg_last_error().decode())
        return int(ep)

    def drain(self, grace_ms: int = 0) -> None:
        """Graceful rolling-restart half: deregister from discovery,
        stop accepting, let in-flight requests finish (bounded by
        grace_ms; 0 = the service's drain_ms option, default 5 s), close
        every connection. Idempotent; stop() still frees the handle."""
        if getattr(self, "_h", None):
            self._lib.eg_service_drain(self._h, int(grace_ms))

    def stop(self) -> None:
        if getattr(self, "_h", None):
            self._lib.eg_service_stop(self._h)
            self._h = None

    close = stop

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


def main() -> None:
    import argparse
    import signal
    import sys
    import time

    ap = argparse.ArgumentParser(description="Run one graph-service shard.")
    ap.add_argument("--data_dir", required=True)
    ap.add_argument("--shard_idx", type=int, default=0)
    ap.add_argument("--shard_num", type=int, default=1)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--registry", default=None)
    ap.add_argument("--workers", type=int, default=None, help=(
        "handler pool size (default: 2x cores). Bounded admission: "
        "connections beyond workers+pending get a BUSY reply the "
        "client fails over on"))
    ap.add_argument("--pending", type=int, default=None, help=(
        "admitted-work headroom beyond the handler pool before new "
        "connections are answered BUSY (default 64)"))
    ap.add_argument("--options", default=None, help=(
        "extra k=v;k=v admission options (max_conns, io_timeout_ms, "
        "idle_timeout_ms, linger_ms, drain_ms, wire_version, telemetry, "
        "slow_spans, blackbox, heat, heat_topk, postmortem_dir — see "
        "eg_admission.h)"))
    ap.add_argument("--postmortem_dir", default=None, help=(
        "arm the fatal-signal postmortem path: on SIGSEGV/SIGBUS/"
        "SIGABRT/SIGFPE this shard writes <dir>/postmortem.<pid>.json "
        "(flight-recorder rings + counters + gauges + backtrace; "
        "OBSERVABILITY.md 'Postmortems') before dying"))
    ap.add_argument("--blackbox", type=int, default=None, help=(
        "flight-recorder kill-switch: 0 disables ring recording AND "
        "suppresses the postmortem dump (default: on)"))
    ap.add_argument("--load_delta", action="append", default=[], help=(
        "delta file(s) (`<prefix>.delta.<n>`, convert.py --delta-from) "
        "to merge right after the base load, flipping the serving epoch "
        "once per file (repeatable; applied in the order given). The "
        "shard starts serving only after every delta has flipped"))
    ap.add_argument("--fault", default="", help=(
        "deterministic failpoint spec injected in THIS shard process "
        "(service_reply/recv_frame/handler_stall/busy_force/... — see "
        "FAULTS.md)"))
    ap.add_argument("--fault_seed", type=int, default=0)
    args = ap.parse_args()
    if args.fault:
        from euler_tpu.graph.native import fault_config

        fault_config(args.fault, args.fault_seed)
    svc = GraphService(
        args.data_dir,
        args.shard_idx,
        args.shard_num,
        args.host,
        args.port,
        args.registry,
        workers=args.workers,
        pending=args.pending,
        options=args.options,
        postmortem_dir=args.postmortem_dir,
        blackbox=None if args.blackbox is None else bool(args.blackbox),
    )
    for dpath in args.load_delta:
        ep = svc.load_delta(dpath)
        print(f"shard {svc.shard_idx} applied {dpath} -> epoch {ep}",
              flush=True)
    print(
        f"graph shard {svc.shard_idx}/{svc.shard_num} serving on"
        f" {svc.address}",
        flush=True,
    )
    stop = []
    # SIGTERM runs the rolling-restart drain (DEPLOY.md runbook):
    # deregister -> stop accepting -> finish in-flight -> close. SIGINT
    # takes the same path — an operator ^C should not drop in-flight work.
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.2)
    svc.drain()
    # server-side survivability ledger for the operator's terminal, via
    # the same eg_counters_* ABI the console's `stats` command reads
    from euler_tpu.graph.native import counters

    served = {k: v for k, v in counters().items() if v}
    if served:
        print(f"shard {svc.shard_idx} drained; counters: {served}",
              file=sys.stderr, flush=True)
    svc.stop()


if __name__ == "__main__":
    main()
