"""TCP shard registry: multi-host discovery without a shared filesystem.

Role equivalent of the reference's ZooKeeper coordination plane
(reference euler/common/zk_server_register.cc creates ephemeral znodes
"<shard>#<ip:port>"; zk_server_monitor.cc:50-64 watches them). Here a tiny
native TCP server (eg_registry.cc) holds soft TTL state: shard servers
REGister and heartbeat; entries of dead shards expire on their own; clients
LIST live shards. Run it from the training coordinator —

    registry = RegistryServer(port=9100)            # in-process
    python -m euler_tpu.graph.registry --port 9100  # or standalone

— then point every shard server and client at ``tcp://<coordinator>:9100``
via the same ``registry=`` parameter that otherwise takes a shared
directory (GraphService / Graph(mode="remote") / run_loop --registry).
"""

from __future__ import annotations

import ctypes

from euler_tpu.graph.native import lib


class RegistryServer:
    """The registry service; stops on close() or GC.

    ttl_ms is the ephemeral-entry lifetime: a shard that misses heartbeats
    for this long disappears from LIST (shards re-REG every ~3 s, so the
    10 s default tolerates two lost heartbeats).
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 ttl_ms: int = 10000):
        self._lib = lib()
        self._h = self._lib.eg_registry_start(host.encode(), port, ttl_ms)
        if not self._h:
            err = self._lib.eg_last_error().decode()
            raise RuntimeError(f"registry start failed: {err}")
        self.host = host
        self.port = self._lib.eg_registry_port(self._h)

    @property
    def address(self) -> str:
        host = "127.0.0.1" if self.host in ("", "0.0.0.0") else self.host
        return f"tcp://{host}:{self.port}"

    def stop(self) -> None:
        if getattr(self, "_h", None):
            self._lib.eg_registry_stop(self._h)
            self._h = None

    close = stop

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


def parse_tcp_url(url: str) -> tuple[str, int] | None:
    """'tcp://host:port' -> (host, port); None when not a tcp URL."""
    if not url.startswith("tcp://"):
        return None
    rest = url[len("tcp://"):]
    host, sep, port_s = rest.rpartition(":")
    if not sep or not host or not port_s.isdigit():
        raise ValueError(f"bad tcp registry url: {url}")
    return host, int(port_s)


def query(url: str, timeout_ms: int = 2000) -> dict[int, list[str]]:
    """LIST a registry: {shard: [\"host:port\", ...]} of live entries.

    Raises ConnectionError when the registry is unreachable.
    """
    parsed = parse_tcp_url(url)
    if parsed is None:
        raise ValueError(f"not a tcp:// registry url: {url}")
    host, port = parsed
    L = lib()
    buf = ctypes.create_string_buffer(1 << 20)
    n = L.eg_registry_query(
        host.encode(), port, timeout_ms, buf, len(buf)
    )
    if n < 0:
        raise ConnectionError(f"registry unreachable: {url}")
    out: dict[int, list[str]] = {}
    # defensive decode: a (mis)behaving registry must not crash the
    # client — skip any line that isn't "<int> host:port"
    for line in buf.raw[:n].decode(errors="replace").splitlines():
        shard_s, _, addr = line.partition(" ")
        # isascii too: isdigit() alone accepts unicode digit-likes
        # (superscripts) that int() then rejects
        if addr and shard_s.isascii() and shard_s.isdigit():
            out.setdefault(int(shard_s), []).append(addr)
    return out


def main() -> None:
    import argparse
    import signal
    import time

    ap = argparse.ArgumentParser(
        description="Run the TCP shard registry (coordination plane)."
    )
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--ttl_ms", type=int, default=10000)
    args = ap.parse_args()
    reg = RegistryServer(args.host, args.port, args.ttl_ms)
    print(f"shard registry serving on {reg.address}", flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.2)
    reg.stop()


if __name__ == "__main__":
    main()
