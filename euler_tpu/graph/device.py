"""Device-resident graph sampling: adjacency in HBM, fanout inside the
jitted step.

The reference's hot loop is host-side per-draw binary search
(reference euler/core/compact_node.cc:42-101 SampleNeighbor, called
batch x prod(fanouts) times per step through the TF AsyncOpKernels). On
TPU the roles invert: a single chip runs the whole GraphSAGE train step in
~0.1 ms, so any host-side sampling — however fast — dominates the step.
For graphs that fit in HBM (hundreds of millions of edges at int32), the
TPU-native design uploads the adjacency ONCE and samples on device:

- ``build_adjacency`` exports a padded-CSR slab per edge-type set from the
  host engine: ``nbr [N+2, W] int32`` neighbor ids and ``cum [N+2, W]
  float32`` normalized cumulative weights per row (CompactNode's
  cumulative layout, vectorized). Row max_id+1 is the default node
  (degree 0), so chained hops through padding stay padding — the same
  convention as the host path.
- ``sample_neighbor`` draws weighted neighbors with replacement inside
  jit: gather the row, one uniform per draw, and an index =
  sum(u >= cum) comparison — the vectorized equivalent of the binary
  search, exact same distribution (statistically verified against the
  host engine in tests/test_device_graph.py).
- ``build_node_sampler`` / ``sample_node`` do the same for weighted
  global root sampling (reference compact_graph.cc:32-56), via
  searchsorted over the per-type cumulative weights.

Everything returned is a dict of numpy arrays meant to live in
``state["consts"]`` — replicated (or sharded) over the mesh, aliased
across steps by donation, free after the one-time upload. Export works
against local AND remote graphs: adjacency rides get_full_neighbor and
the samplers ride node_weights/node_types, all of which scatter per
shard in remote mode — so device-sampling training composes with a
sharded TCP-registry cluster (tests/test_remote.py).
"""

from __future__ import annotations

import numpy as np

try:  # imported lazily in most callers; keep module importable without jax
    import jax
    import jax.numpy as jnp
except Exception:  # pragma: no cover
    jax = None
    jnp = None


def _fetch_flat_csr(graph, edge_types, max_id: int, chunk: int,
                    sorted: bool = False):
    """Chunked full-neighbor export shared by the slab and alias
    builders: (counts [N+2] int64, nbr_flat int64, w_flat float32
    contiguous, offsets [N+3] int64 with offsets[-1] == len(nbr_flat)).
    Row max_id+1 (the default row) is always empty here; builders add
    their own default semantics."""
    n_rows = max_id + 2
    et = list(edge_types)
    counts_all = np.zeros(n_rows, dtype=np.int64)
    nbr_parts: list[np.ndarray] = []
    w_parts: list[np.ndarray] = []
    for lo in range(0, max_id + 1, chunk):
        ids = np.arange(lo, min(lo + chunk, max_id + 1), dtype=np.int64)
        nbr, w, _, counts = graph.get_full_neighbor(ids, et, sorted=sorted)
        counts_all[lo:lo + len(ids)] = counts
        nbr_parts.append(nbr)
        w_parts.append(w)
    nbr_flat = (
        np.concatenate(nbr_parts) if nbr_parts else np.zeros(0, np.int64)
    )
    w_flat = np.ascontiguousarray(
        np.concatenate(w_parts) if w_parts else np.zeros(0), np.float32
    )
    offsets = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts_all, out=offsets[1:])
    return counts_all, nbr_flat, w_flat, offsets


def build_adjacency(
    graph,
    edge_types,
    max_id: int,
    max_degree: int | None = None,
    chunk: int = 65536,
    sorted: bool = False,
    _prefetched=None,
) -> dict:
    """Export the adjacency restricted to ``edge_types`` as device slabs.

    Returns {"nbr": [N+2, W] int32, "cum": [N+2, W] float32,
    "deg": [N+2] int32} with N = max_id + 1; W = observed max degree (or
    ``max_degree`` cap — rows beyond it are truncated to their W heaviest
    neighbors and renormalized, with a warning). ``deg`` is the in-slab
    neighbor count (min(true degree, W)) — the full-neighborhood models
    mask padding slots with it. Unknown ids and the default row sample
    the default node (max_id + 1).
    """
    n_rows = max_id + 2
    default = max_id + 1
    counts_all, nbr_flat, w_flat, offsets = (
        _prefetched
        if _prefetched is not None
        else _fetch_flat_csr(graph, edge_types, max_id, chunk, sorted=sorted)
    )

    W = int(counts_all.max()) if len(counts_all) else 0
    truncated = np.zeros(0, dtype=np.int64)
    if max_degree is not None and W > max_degree:
        W = max_degree
        truncated = np.flatnonzero(counts_all > W)
    W = max(W, 1)

    # vectorized scatter into the padded slabs (no per-row Python loop:
    # real graphs have hundreds of thousands of rows)
    rows = np.repeat(np.arange(n_rows), counts_all)
    cols = np.arange(len(nbr_flat)) - np.repeat(offsets[:-1], counts_all)
    keep = cols < W  # drop overflow entries; heavy-tail fix-up below
    nbr_out = np.full((n_rows, W), default, dtype=np.int32)
    cum_out = np.ones((n_rows, W), dtype=np.float32)
    nbr_out[rows[keep], cols[keep]] = nbr_flat[keep]
    # per-row normalized cumulative weights from one flat cumsum
    csum = np.cumsum(w_flat, dtype=np.float64)
    csum_z = np.concatenate([[0.0], csum])
    row_base = csum_z[np.repeat(offsets[:-1], counts_all)]
    row_total = (csum_z[offsets[1:]] - csum_z[offsets[:-1]])[rows]
    with np.errstate(invalid="ignore", divide="ignore"):
        cum_flat = (csum_z[1:] - row_base) / row_total
    cum_out[rows[keep], cols[keep]] = cum_flat[keep]
    # guard float drift: the last real slot must be exactly 1 so u < 1
    # always lands in-row
    has = counts_all > 0
    cum_out[np.flatnonzero(has),
            np.minimum(counts_all[has], W) - 1] = 1.0
    # rows whose weights sum to 0 are UNSAMPLEABLE (host sampling fills
    # the default node) but their neighbors still EXIST (host
    # GetFullNeighbor returns them, and the full-neighborhood GCN
    # aggregates them) — so keep nbr/deg intact, neutralize the nan cum,
    # and record unsampleability separately for sample_neighbor
    zero_w = np.flatnonzero(
        has & (csum_z[offsets[1:]] - csum_z[offsets[:-1]] <= 0)
    )
    sampleable = np.ones(n_rows, dtype=bool)
    if len(zero_w):
        cum_out[zero_w] = 1.0
        sampleable[zero_w] = False

    # rows beyond the cap: redo exactly (keep the heaviest W neighbors)
    for i in truncated:
        nb = nbr_flat[offsets[i]:offsets[i + 1]]
        wt = w_flat[offsets[i]:offsets[i + 1]]
        sel = np.argsort(wt)[::-1][:W]
        if sorted:  # keep the heaviest W but preserve the id order
            sel = np.sort(sel)
        nb, wt = nb[sel], wt[sel]
        total = wt.sum()
        if total <= 0:
            continue
        nbr_out[i, :W] = nb
        c = np.cumsum(wt) / total
        c[-1] = 1.0
        cum_out[i, :W] = c
    if len(truncated):
        import warnings

        warnings.warn(
            f"build_adjacency: {len(truncated)} rows exceeded "
            f"max_degree={W}; truncated to their heaviest neighbors "
            "(renormalized)"
        )
    deg = np.minimum(counts_all, W).astype(np.int32)
    # sorted=True rows are id-ordered (padding = default = largest id, so
    # whole rows sort ascending) — the precondition for
    # biased_random_walk's searchsorted membership test. Not recorded in
    # the dict: consts pytrees are traced through jit, where a flag leaf
    # could not be branch-checked anyway; callers keep sorted slabs under
    # distinct consts keys (Model.adj_key(et, sorted=True)).
    # "truncated_rows" is HOST-side metadata (a plain int, popped by
    # Model.add_sampling_consts before the dict reaches jit): biased
    # walks on a truncated slab are measurably distorted (PERF.md walk
    # study) and callers must be able to detect the condition.
    return {
        "nbr": nbr_out,
        "cum": cum_out,
        "deg": deg,
        "sampleable": sampleable,
        "truncated_rows": int(len(truncated)),
    }


def build_alias_adjacency(
    graph,
    edge_types,
    max_id: int,
    chunk: int = 65536,
    sorted: bool = False,
    _prefetched=None,
) -> dict:
    """Export the adjacency restricted to ``edge_types`` as device-side
    EXACT sampling structures: flat-CSR Walker alias tables, O(1) per
    draw with NO max_degree truncation — the heavy-tail alternative to
    build_adjacency's padded slab, whose width is the max observed
    degree (unbuildable on power-law graphs where hubs reach tens of
    thousands of neighbors; reference semantics being preserved:
    CompactNode::SampleNeighbor draws exactly over ALL neighbors,
    euler/core/compact_node.cc:42-101).

    Returns {"off": [N+2] int32 row starts, "deg": [N+2] int32,
    "nbr": [E] int32, "alias": [E] int32 (GLOBAL ids, prebaked so the
    draw needs no second row-local hop), "prob": [E] float32,
    "sampleable": [N+2] bool, "bisect_steps": [ceil(log2(max_degree))]
    int8 zeros — a SHAPE-carried static (array shapes survive jit
    tracing where an int leaf would be traced) that lets the rejection
    walk's membership bisection stop at the max ROW width instead of
    log2(E) iterations} with N = max_id + 1 and E = total edges.
    Memory is O(E) — 12 bytes/edge vs the slab's O(N * max_degree) —
    e.g. ~1.4 GB for a 114M-edge Reddit-scale graph. The alias build
    itself runs in native code (eg_build_alias_csr, OpenMP over rows).
    Unknown ids and the default row sample the default node, exactly
    like build_adjacency.

    ``sorted=True`` exports id-sorted CSR rows — the precondition for
    alias_biased_random_walk's parent-membership bisection (the alias
    draw itself is order-independent, so sorted tables sample the same
    distribution)."""
    import ctypes

    from euler_tpu.graph import native

    n_rows = max_id + 2
    default = max_id + 1
    counts_all, nbr_flat, w_flat, offsets = (
        _prefetched
        if _prefetched is not None
        else _fetch_flat_csr(graph, edge_types, max_id, chunk, sorted=sorted)
    )
    e = len(nbr_flat)
    if e >= 1 << 31:
        raise ValueError(
            f"alias adjacency needs int32 slots: {e} edges; shard the "
            "graph first"
        )
    prob = np.ones(e, dtype=np.float32)
    alias_local = np.zeros(e, dtype=np.int32)
    if e:
        L = native.lib()
        L.eg_build_alias_csr(
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(n_rows),
            w_flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            prob.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            alias_local.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
    row_base = np.repeat(offsets[:-1], counts_all)
    alias_ids = (
        nbr_flat[row_base + alias_local].astype(np.int32)
        if e else np.zeros(0, np.int32)
    )
    # zero-total rows are UNSAMPLEABLE (host engine fills the default
    # node); the native build already made their tables uniform, the
    # mask keeps the contract
    csum_z = np.concatenate(
        [[0.0], np.cumsum(w_flat, dtype=np.float64)]
    )
    sums = csum_z[offsets[1:]] - csum_z[offsets[:-1]]
    sampleable = (counts_all > 0) & (sums > 0)
    sampleable[default] = False
    max_deg = int(counts_all.max()) if len(counts_all) else 0
    return {
        "off": offsets[:-1].astype(np.int32),
        "deg": counts_all.astype(np.int32),
        "nbr": nbr_flat.astype(np.int32),
        "alias": alias_ids,
        "prob": prob,
        "sampleable": sampleable,
        "bisect_steps": np.zeros(max(max_deg.bit_length(), 1), np.int8),
    }


def _alias_sample_neighbor(adj: dict, nodes, key, count: int):
    """Exact CSR-alias draw: j ~ U[0, deg), keep nbr[off+j] with
    prob[off+j] else alias[off+j]. Same distribution as the padded-slab
    compare-sum draw but over the FULL neighbor list — no truncation —
    at O(1) ops and 4 gathers per draw."""
    n_rows = adj["off"].shape[0]
    default = n_rows - 1
    # tolerate plain-numpy consts (tests build them host-side; traced
    # callers pass device arrays already)
    offs, degs, probs, nbrs, aliases, ok_rows = (
        jnp.asarray(adj[k])
        for k in ("off", "deg", "prob", "nbr", "alias", "sampleable")
    )
    nodes = jnp.asarray(nodes, dtype=jnp.int32)
    nodes = jnp.where(nodes < 0, default, jnp.minimum(nodes, default))
    deg = degs[nodes]                              # [M]
    off = offs[nodes]
    k1, k2 = jax.random.split(key)
    u1 = jax.random.uniform(k1, (*nodes.shape, count))
    u2 = jax.random.uniform(k2, (*nodes.shape, count))
    j = jnp.minimum(
        (u1 * deg[..., None]).astype(jnp.int32),
        jnp.maximum(deg[..., None] - 1, 0),
    )
    e = probs.shape[0]
    if e == 0:  # no edges of these types at all: everything defaults
        return jnp.full((*nodes.shape, count), default, jnp.int32)
    # empty rows at the CSR's end have off == E; their draws are masked
    # to the default below, so clamping the slot only prevents the OOB
    slot = jnp.minimum(off[..., None] + j, e - 1)
    pick = jnp.where(u2 < probs[slot], nbrs[slot], aliases[slot])
    ok = ok_rows[nodes] & (deg > 0)
    return jnp.where(ok[..., None], pick, default)


SEG = 1 << 16  # two-level draw segment size: device arrays are float32
# (jax x32), so a SINGLE cumulative over ~16M comparably-weighted nodes
# collides at float32 resolution (spacing near 1.0 is 2^-24) and tail
# nodes silently get probability 0. Normalizing the cumulative WITHIN
# 2^16-node segments keeps adjacent steps >= ~2^-16 (always
# representable), and the segment-level cumulative only needs one value
# per 65536 nodes — resolution holds to ~2^36 nodes. Adjacency rows
# never hit this: W stays small.


def _segment_cum(weights: np.ndarray, seg: int | None = None):
    """(seg_cum [S] f32, within [M] f32): float64 host cumsum split into
    ceil(M/seg) segments — seg_cum is the normalized cumulative over
    segment totals, within is the cumulative normalized inside each
    segment, last entry of every segment pinned to exactly 1.0 so u < 1
    always lands in-segment. All weights must be > 0 (filtered by the
    callers), so every segment total is positive."""
    if seg is None:
        seg = SEG  # module attr read at call time: tests shrink it
    w = weights.astype(np.float64)
    m = len(w)
    starts = np.arange(0, m, seg)
    seg_tot = np.add.reduceat(w, starts)
    seg_cum = np.cumsum(seg_tot)
    seg_cum /= seg_cum[-1]
    seg_cum[-1] = 1.0
    cum = np.cumsum(w)
    base = np.concatenate([[0.0], np.cumsum(seg_tot)])
    seg_idx = np.arange(m) // seg
    within = (cum - base[seg_idx]) / seg_tot[seg_idx]
    within[np.minimum(starts + seg, m) - 1] = 1.0  # pin segment ends
    return seg_cum.astype(np.float32), within.astype(np.float32)


def _bisect_first_ge(cum, lo, hi, u, steps: int):
    """Vectorized first index in [lo, hi) with cum[idx] >= u (the
    fixed-depth binary search shared by the two-level draws; lo/hi/u are
    broadcast-compatible int32/float arrays)."""
    M = max(int(cum.shape[0]), 1)
    for _ in range(steps):
        active = lo < hi
        # lo + (hi - lo)//2, NOT (lo + hi)//2: int32 lo+hi wraps
        # negative for rows near the end of a >2^30-entry table (a size
        # build_alias_adjacency permits), silently corrupting the search
        mid = lo + (hi - lo) // 2
        go_right = cum[jnp.clip(mid, 0, M - 1)] < u
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return jnp.clip(lo, 0, M - 1)


def _export_node_arrays(graph, max_id: int, need_types: bool,
                        chunk: int = 1 << 20):
    """Chunked node_weights (+ node_types) export over [0, max_id]: keeps
    each remote-mode RPC reply bounded (weights/types work in remote mode
    too — one kNodeWeight/kNodeType scatter per shard per chunk), and
    costs local mode nothing."""
    w_parts, t_parts = [], []
    for lo in range(0, max_id + 1, chunk):
        ids = np.arange(lo, min(lo + chunk, max_id + 1), dtype=np.int64)
        w_parts.append(graph.node_weights(ids))
        if need_types:
            t_parts.append(graph.node_types(ids))
    weights = (
        np.concatenate(w_parts) if w_parts else np.zeros(0, np.float32)
    )
    types = (
        (np.concatenate(t_parts) if t_parts else np.zeros(0, np.int32))
        if need_types
        else None
    )
    return weights, types


def build_node_sampler(graph, node_type: int = -1, max_id: int = 0) -> dict:
    """Weighted global root sampler for one node type (-1 = all types,
    type picked by weight sum first — reference compact_graph.cc:32-56;
    with-replacement draws over cum weights give exactly that marginal).

    Returns the two-level layout {"ids": [M] int32, "cum": [M] float32
    (normalized within SEG-node segments), "seg_cum": [S] float32} over
    the matching nodes, sorted by id for determinism — exact beyond the
    ~16M-node float32 cliff a flat cumulative would hit (see SEG). Works
    against local AND remote graphs (node_weights/node_types scatter per
    shard since round 3).
    """
    ids = np.arange(max_id + 1, dtype=np.int64)
    weights, types = _export_node_arrays(graph, max_id, node_type != -1)
    if node_type != -1:
        mask = types == node_type
        ids, weights = ids[mask], weights[mask]
    keep = weights > 0
    ids, weights = ids[keep], weights[keep]
    if len(ids) == 0:
        raise ValueError(f"no nodes of type {node_type} with weight > 0")
    seg_cum, within = _segment_cum(weights)
    return {
        "ids": ids.astype(np.int32),
        "cum": within,
        "seg_cum": seg_cum,
    }


# ---- jit-side sampling ----


def sample_node(sampler: dict, key, count: int):
    """[count] int32 roots drawn weight-proportionally on device.

    Two-level draw: u1 picks a SEG-node segment from seg_cum, u2
    bisects that segment's within-normalized cumulative — P(node) =
    (seg_total/total) * (w/seg_total) = w/total exactly, with every
    float32 step representable regardless of graph size (see SEG)."""
    k1, k2 = jax.random.split(key)
    m = int(sampler["ids"].shape[0])
    s = jnp.searchsorted(sampler["seg_cum"], jax.random.uniform(k1, (count,)))
    s = jnp.clip(s, 0, sampler["seg_cum"].shape[0] - 1)
    lo = (s * SEG).astype(jnp.int32)
    hi = jnp.minimum(lo + SEG, m).astype(jnp.int32)
    u2 = jax.random.uniform(k2, (count,))
    steps = max(min(m, SEG).bit_length(), 1)
    idx = _bisect_first_ge(sampler["cum"], lo, hi, u2, steps)
    return sampler["ids"][idx]


_KERNEL_MESH = None  # (Mesh, data_axis) set by set_kernel_mesh


def set_kernel_mesh(mesh, axis: str = "data") -> None:
    """Route eligible packed-slab draws through the Pallas kernel PER
    SHARD of ``mesh`` (shard_map over ``axis``) — the SPMD composition
    plain pjit cannot express. Call with None to clear. run_loop wires
    this automatically when --device_sampling runs on a multi-device TPU
    mesh (pallas_sampling.sharded_available())."""
    global _KERNEL_MESH
    _KERNEL_MESH = None if mesh is None else (mesh, axis)


def kernel_mesh():
    return _KERNEL_MESH


def sample_neighbor(adj: dict, nodes, key, count: int):
    """[len(nodes), count] int32 weighted neighbor draws (replacement).

    Exact CompactNode semantics: per draw, pick the first slot whose
    cumulative weight exceeds u. Nodes with no matching neighbors (and
    the default row) yield the default node.

    When the adjacency carries a "packed" slab (added by
    base.Model.add_sampling_consts on a TPU backend), the draw runs as
    one fused Pallas kernel instead of this op chain — same
    distribution, ~3x faster at bench dims (graph/pallas_sampling.py).
    On a single device the kernel is called directly; under a mesh
    registered via set_kernel_mesh it runs per-shard through shard_map.

    Alias adjacencies (build_alias_adjacency — flat-CSR alias tables,
    exact over the full neighbor list, the heavy-tail form) dispatch on
    their "off" key to the O(1) alias draw instead of the slab chain.
    """
    from euler_tpu.graph import pallas_sampling

    if "off" in adj:
        return _alias_sample_neighbor(adj, nodes, key, count)

    m = int(np.prod(jnp.shape(nodes)))
    if "packed" in adj:
        # kernel seed, shared by both routes: two independent int31
        # words -> 62 bits of the key's entropy reach the core PRNG (a
        # single int31 seed would birthday-collide across long runs,
        # replaying identical on-core streams)
        def kernel_seed():
            return jax.random.randint(
                key, (2,), 0, jnp.iinfo(jnp.int32).max
            )

        if _KERNEL_MESH is not None:
            mesh, axis = _KERNEL_MESH
            n_sh = mesh.shape[axis]
            if m > 0 and m % n_sh == 0 and pallas_sampling.eligible(
                m // n_sh, count
            ):
                return pallas_sampling.sample_neighbor_sharded(
                    adj, nodes, kernel_seed(), count, mesh, axis
                )
        elif pallas_sampling.eligible(m, count) and pallas_sampling.available():
            # available() (single-device unless force-flagged) guards
            # consts that carry a packed slab from a multi-device build:
            # after set_kernel_mesh(None) the unsharded pallas_call under
            # pjit would be the exact composition the module warns about
            return pallas_sampling.sample_neighbor(
                adj, nodes, kernel_seed(), count
            )
    nodes = jnp.asarray(nodes, dtype=jnp.int32)
    # unknown ids sample the default node: negatives and past-the-slab
    # ids map to the default row on BOTH paths (the kernel clamps the
    # same way; a bare numpy-style wrap would send -2 to a real row)
    n_rows = adj["nbr"].shape[0]
    nodes = jnp.where(nodes < 0, n_rows - 1, jnp.minimum(nodes, n_rows - 1))
    cum = adj["cum"][nodes]                       # [M, W]
    u = jax.random.uniform(key, (*nodes.shape, count))
    # index = #thresholds strictly below u  (u < cum[0] -> 0, ...)
    idx = (u[..., None] >= cum[..., None, :]).sum(-1)
    idx = jnp.clip(idx, 0, adj["nbr"].shape[1] - 1)
    out = jnp.take_along_axis(adj["nbr"][nodes], idx, axis=-1)
    # rows with zero total weight have neighbors but no sampling mass:
    # the host engine fills the default node there
    default = adj["nbr"].shape[0] - 1
    return jnp.where(adj["sampleable"][nodes][..., None], out, default)


def random_walk(adj, roots, key, walk_len: int):
    """[len(roots), walk_len+1] int32 walks sampled on device (column 0 =
    start). Uniform-or-weighted per-step draws — the p=q=1 fast path of
    the reference's biased walk (euler/client/graph.cc:196-199); the
    biased p/q merge stays host-side. Dead ends chain into the default
    row and stay there, like the host walk's default_node fill.

    ``adj`` is one adjacency dict (homogeneous walk) or a per-step list
    of walk_len dicts (heterogeneous metapath walk, the LsHNE pattern)."""
    adjs = adj if isinstance(adj, (list, tuple)) else [adj] * walk_len
    if len(adjs) != walk_len:
        raise ValueError(
            f"metapath walk needs {walk_len} per-step adjacencies, "
            f"got {len(adjs)}"
        )
    cur = jnp.asarray(roots, dtype=jnp.int32).reshape(-1)
    cols = [cur]
    for i in range(walk_len):
        cur = sample_neighbor(
            adjs[i], cur, jax.random.fold_in(key, i), 1
        )[:, 0]
        cols.append(cur)
    return jnp.stack(cols, axis=1)


def biased_random_walk(adj, roots, key, walk_len: int, p: float, q: float):
    """[len(roots), walk_len+1] int32 node2vec-biased walks on device
    (reference euler/client/graph.cc:120-151 BuildWeights: candidate
    weights scaled by 1/p when the candidate IS the parent [d_tx=0], 1
    when the candidate is a neighbor of the parent [d_tx=1], 1/q
    otherwise [d_tx=2], then a weighted draw over the rescaled row).

    ``adj`` MUST be built with build_adjacency(..., sorted=True): the
    d_tx=1 membership test is a per-row binary search of the current
    node's candidates in the parent's id-sorted neighbor row. Step 0 has
    no parent and takes the plain weighted draw, exactly like the host
    walk. Dead ends chain into the default row and stay there.

    With max_degree truncation the parent's slab row holds only its
    heaviest W neighbors, so a dropped real neighbor classifies as
    d_tx=2 (1/q) instead of d_tx=1 — a bias distortion on top of the
    truncated sampling support. MEASURED (PERF.md walk-distortion
    study): on a heavy-tail graph, hub-parent steps sit at mean total
    variation 0.35 from the exact distribution even at W=512 — so when
    p/q matter, either size W to the observed max degree or keep the
    walk on the host path (exact reference semantics).
    """
    nbr, cum = adj["nbr"], adj["cum"]
    deg, sampleable = adj["deg"], adj["sampleable"]
    default = nbr.shape[0] - 1
    W = nbr.shape[1]
    cur = jnp.asarray(roots, dtype=jnp.int32).reshape(-1)
    parent = jnp.full_like(cur, default)
    prow = None  # parent's neighbor row = previous step's cand gather
    cols = [cur]
    slot = jnp.arange(W)
    for step in range(walk_len):
        cand = nbr[cur]                                    # [M, W]
        c = cum[cur]
        # per-slot weights from the normalized cumulative row; padding
        # and unsampleable rows zero out
        w = jnp.concatenate([c[:, :1], c[:, 1:] - c[:, :-1]], axis=1)
        w = w * (slot[None, :] < deg[cur][:, None])
        w = w * sampleable[cur][:, None]
        if prow is not None:
            # d_tx: parent-row membership via binary search (rows
            # sorted); step 0 skips this — no parent, and a uniform 1/q
            # would cancel in the normalization anyway
            pos = jax.vmap(
                lambda row, cds: jnp.searchsorted(row, cds)
            )(prow, cand)
            hit = jnp.take_along_axis(
                prow, jnp.clip(pos, 0, W - 1), axis=1
            ) == cand
            in_parent_nbr = hit & (pos < deg[parent][:, None])
            is_parent = cand == parent[:, None]
            # d_tx=1 wins over d_tx=0 when the parent has a self-loop:
            # the reference merge's equality branch runs before its
            # candidate<parent check (euler/client/graph.cc:126-140),
            # so a candidate that IS the parent AND appears in the
            # parent's neighbor list keeps weight w, not w/p
            scale = jnp.where(
                in_parent_nbr, 1.0,
                jnp.where(is_parent, 1.0 / p, 1.0 / q),
            )
            w = w * scale
        cw = jnp.cumsum(w, axis=1)
        total = cw[:, -1:]
        cw = cw / jnp.maximum(total, 1e-30)
        u = jax.random.uniform(
            jax.random.fold_in(key, step), (cur.shape[0], 1)
        )
        idx = jnp.clip((u >= cw).sum(-1), 0, W - 1)
        nxt = jnp.take_along_axis(cand, idx[:, None], axis=1)[:, 0]
        nxt = jnp.where(total[:, 0] > 0, nxt, default)
        # next step's parent is this step's node; its neighbor row is
        # exactly this step's cand gather — no second HBM gather.
        # (Dead-ended walkers land on the default row whose weights are
        # all zero, so their scale is irrelevant.)
        parent, cur, prow = cur, nxt, cand
        cols.append(cur)
    return jnp.stack(cols, axis=1)


DEFAULT_WALK_TRIALS = 64  # rejection-walk proposal budget per step: the
# worst realistic node2vec grid point (p or q = 1/4 -> envelope M = 4,
# acceptance >= 1/16 even when every candidate is d_tx=2) leaves
# (1 - 1/16)^64 ~ 1.6% of steps falling back to the unbiased first
# draw; typical p/q near 1 accept on the first or second proposal.


def _alias_biased_step(adj, cur, parent, key, p: float, q: float,
                       trials: int):
    """One EXACT node2vec-biased transition over full neighbor lists:
    propose from the current node's alias row (unbiased, O(1)), accept
    with probability s(d_tx)/M where s is the reference's d_tx scale
    (1 if the candidate is a parent neighbor — which wins on parent
    self-loops, matching the reference merge's branch order,
    euler/client/graph.cc:126-140 — else 1/p for the parent itself,
    else 1/q) and M = max(1/p, 1, 1/q). Accepted draws are distributed
    exactly ∝ w(y)*s(y); exhausting ``trials`` proposals falls back to
    the first (unbiased) draw. The d_tx membership test is a fixed-depth
    bisection of each candidate in the parent's id-sorted CSR row —
    ``adj`` MUST come from build_alias_adjacency(..., sorted=True).

    Returns [len(cur)] int32 next nodes (dead ends -> default row)."""
    offs, degs, probs, nbrs, aliases, ok_rows = (
        jnp.asarray(adj[k])
        for k in ("off", "deg", "prob", "nbr", "alias", "sampleable")
    )
    n_rows = offs.shape[0]
    default = n_rows - 1
    e = int(probs.shape[0])
    b = cur.shape[0]
    if e == 0:
        return jnp.full((b,), default, jnp.int32)
    k1, k2, k3 = jax.random.split(key, 3)
    deg = degs[cur]
    off = offs[cur]
    u1 = jax.random.uniform(k1, (b, trials))
    u2 = jax.random.uniform(k2, (b, trials))
    j = jnp.minimum(
        (u1 * deg[:, None]).astype(jnp.int32),
        jnp.maximum(deg[:, None] - 1, 0),
    )
    slot = jnp.minimum(off[:, None] + j, e - 1)
    cand = jnp.where(u2 < probs[slot], nbrs[slot], aliases[slot])
    # membership of each candidate in the parent's id-sorted CSR row:
    # first flat index in [plo, phi) with nbrs[idx] >= cand. Depth
    # covers the largest possible row (deg <= E), converged lanes
    # no-op; a lo that converged to phi (or ran past a last-row phi==E)
    # can never satisfy the equality check below.
    plo = jnp.broadcast_to(offs[parent][:, None], (b, trials))
    phi = jnp.broadcast_to(
        (offs[parent] + degs[parent])[:, None], (b, trials)
    )
    # bisection depth: the max ROW width bound when the builder recorded
    # it (shape-carried static — log2(58k)=16 vs log2(114M)=27 on the
    # heavy-tail flagship), else the always-safe log2(E)
    steps = (
        int(adj["bisect_steps"].shape[0])
        if "bisect_steps" in adj
        else max(e.bit_length(), 1)
    )
    pos = _bisect_first_ge(nbrs, plo, phi, cand, steps)
    hit = (nbrs[jnp.clip(pos, 0, e - 1)] == cand) & (pos < phi)
    is_par = cand == parent[:, None]
    s = jnp.where(hit, 1.0, jnp.where(is_par, 1.0 / p, 1.0 / q))
    m = max(1.0 / p, 1.0, 1.0 / q)
    accept = jax.random.uniform(k3, (b, trials)) < s / m
    # first accepted proposal; none accepted -> index 0, the first
    # (unbiased) draw — the bounded-retry fallback
    first = jnp.argmax(accept, axis=1)
    pick = jnp.take_along_axis(cand, first[:, None], axis=1)[:, 0]
    ok = ok_rows[cur] & (deg > 0)
    return jnp.where(ok, pick, default)


def alias_biased_random_walk(adj, roots, key, walk_len: int, p: float,
                             q: float, trials: int | None = None):
    """[len(roots), walk_len+1] int32 node2vec-biased walks sampled on
    device EXACTLY over the FULL neighbor lists — the heavy-tail form of
    biased_random_walk. Where the padded-slab walk must truncate hub
    rows (measured mean TVD 0.35 from the exact distribution at W=512,
    PERF.md walk study), this draws proposals from the flat-CSR alias
    tables (no truncation, O(E) memory) and rejection-corrects them to
    the reference's d_tx-scaled distribution
    (euler/client/graph.cc:120-151): P(accept y) = s(y)/M, leaving
    accepted candidates ∝ w(y)*s(y) exactly.

    ``adj`` MUST be built with build_alias_adjacency(..., sorted=True)
    (the membership bisection needs id-sorted rows). Step 0 has no
    parent and takes the plain alias draw, exactly like the host walk;
    dead ends chain into the default row and stay there. ``trials``
    bounds the per-step proposal budget (default DEFAULT_WALK_TRIALS);
    an exhausted step falls back to its first unbiased draw, a <~2%
    event at the worst realistic p/q (see DEFAULT_WALK_TRIALS)."""
    if trials is None:
        trials = DEFAULT_WALK_TRIALS
    n_rows = adj["off"].shape[0]
    default = n_rows - 1
    cur = jnp.asarray(roots, dtype=jnp.int32).reshape(-1)
    cur = jnp.where(cur < 0, default, jnp.minimum(cur, default))
    parent = jnp.full_like(cur, default)
    cols = [cur]
    for step in range(walk_len):
        k = jax.random.fold_in(key, step)
        if step == 0:
            # no parent: plain exact alias draw (the host walk's first
            # hop is the same unbiased draw)
            nxt = _alias_sample_neighbor(adj, cur, k, 1)[:, 0]
        else:
            nxt = _alias_biased_step(adj, cur, parent, k, p, q, trials)
        parent, cur = cur, nxt
        cols.append(cur)
    return jnp.stack(cols, axis=1)


def build_typed_node_sampler(graph, num_types: int, max_id: int) -> dict:
    """Per-node-type weighted samplers packed into one flat layout for the
    device sample_node_with_src (reference sample_node_with_src semantics:
    each source draws negatives from ITS node type's global sampler,
    tf_euler euler_ops/sample_ops.py:39-67).

    Returns {"ids": [M] int32 (nodes sorted by type), "cum": [M] float32
    (cumulative weights normalized within SEG-node sub-segments of each
    type — the same two-level layout as build_node_sampler, so a single
    type beyond ~16M nodes keeps exact float32 draws), "off": [T+1]
    int32 type offsets into ids, "seg_cum": [G] float32 (per-type
    normalized cumulative over sub-segment totals), "tseg_off": [T+1]
    int32 type offsets into seg_cum, "types": [N+2] int32 node-type
    lookup (-1 for unknown/default)}.
    """
    all_ids = np.arange(max_id + 1, dtype=np.int64)
    weights, types = _export_node_arrays(graph, max_id, need_types=True)
    type_table = np.full(max_id + 2, -1, dtype=np.int32)
    type_table[: max_id + 1] = types

    ids_out: list[np.ndarray] = []
    cum_out: list[np.ndarray] = []
    seg_out: list[np.ndarray] = []
    off = [0]
    tseg_off = [0]
    empty_types = []
    for t in range(num_types):
        mask = (types == t) & (weights > 0)
        tids = all_ids[mask]
        tw = weights[mask]
        if len(tids):
            seg_cum, within = _segment_cum(tw)
        else:
            seg_cum, within = np.zeros(0, np.float32), np.zeros(0, np.float32)
            if (types == t).any():
                empty_types.append(t)
        ids_out.append(tids)
        cum_out.append(within)
        seg_out.append(seg_cum)
        off.append(off[-1] + len(tids))
        tseg_off.append(tseg_off[-1] + len(seg_cum))
    if empty_types:
        import warnings

        warnings.warn(
            f"build_typed_node_sampler: node types {empty_types} exist "
            "but have no weight>0 nodes; sources of these types will "
            "draw the default (zero-feature) node as negatives — give "
            "those nodes sampling weight or use host-side negatives"
        )
    ids_cat = (
        np.concatenate(ids_out) if off[-1] else np.zeros(0, np.int64)
    )
    cum_cat = (
        np.concatenate(cum_out) if off[-1] else np.zeros(0, np.float32)
    )
    seg_cat = (
        np.concatenate(seg_out) if tseg_off[-1] else np.zeros(0, np.float32)
    )
    return {
        "ids": ids_cat.astype(np.int32),
        "cum": cum_cat.astype(np.float32),
        "off": np.asarray(off, dtype=np.int32),
        "seg_cum": seg_cat,
        "tseg_off": np.asarray(tseg_off, dtype=np.int32),
        "types": type_table,
    }


def sample_node_with_src(tsampler: dict, src, key, count: int):
    """[len(src), count] int32 negatives: each source draws from its own
    node type's weighted sampler (device analog of the native
    eg_sample_node_with_src). Sources of unknown/default type fall back
    to type 0's segment. Two fixed-depth vectorized bisections per draw
    (the two-level layout of build_typed_node_sampler): u1 picks a SEG
    sub-segment within the type, u2 a node within the sub-segment —
    float32-exact past the ~16M-nodes-per-type cliff."""
    src = jnp.asarray(src, dtype=jnp.int32).reshape(-1)
    t = tsampler["types"][src]
    # clamp out-of-range types into the sampler's range (mirrors the
    # TypedDense tower clamping): unknown (<0) falls to type 0, types
    # beyond the configured count to the last segment — never the
    # accidental empty-segment path, which would silently train against
    # all-default (zero-feature) negatives
    num_types = tsampler["off"].shape[0] - 1
    t = jnp.clip(t, 0, num_types - 1)
    shape = (src.shape[0], count)
    node_lo = tsampler["off"][t][:, None].astype(jnp.int32)
    node_hi = tsampler["off"][t + 1][:, None].astype(jnp.int32)
    empty = jnp.broadcast_to(node_hi <= node_lo, shape)
    k1, k2 = jax.random.split(key)
    # level 1: sub-segment within the type's seg_cum span
    g_lo = jnp.broadcast_to(
        tsampler["tseg_off"][t][:, None].astype(jnp.int32), shape
    )
    g_hi = jnp.broadcast_to(
        tsampler["tseg_off"][t + 1][:, None].astype(jnp.int32), shape
    )
    G = max(int(tsampler["seg_cum"].shape[0]), 1)
    g = _bisect_first_ge(
        tsampler["seg_cum"], g_lo, g_hi,
        jax.random.uniform(k1, shape), max(G.bit_length(), 1),
    )
    # level 2: node within sub-segment g (sub-segments of a type are
    # SEG-aligned from the type's node offset)
    j = g - tsampler["tseg_off"][t][:, None]
    lo = (node_lo + j * SEG).astype(jnp.int32)
    hi = jnp.minimum(lo + SEG, node_hi).astype(jnp.int32)
    M = max(int(tsampler["cum"].shape[0]), 1)
    idx = _bisect_first_ge(
        tsampler["cum"], lo, hi, jax.random.uniform(k2, shape),
        max(min(M, SEG).bit_length(), 1),
    )
    out = tsampler["ids"][idx]
    default = tsampler["types"].shape[0] - 1
    return jnp.where(empty, default, out)


def multi_hop_neighbor(adjs, roots, node_caps):
    """Full-neighbor multi-hop expansion with per-hop dedup, inside jit
    (device analog of ops.get_multi_hop_neighbor; deterministic — no
    sampling, no RNG).

    Per hop: gather every current node's full slab row, dedup the
    neighbor ids with a sort-based dense-rank (jnp.unique's size=
    truncation leaves inverse indices unspecified, so rank is computed
    explicitly), and emit the same padded COO the host path produces —
    {"nodes": [cap] (default-padded, sorted like np.unique),
    "src"/"dst": [C*W] indices into the current/next hop arrays,
    "mask": [C*W] 1.0 on real edges, "w": alias of mask (the sparse
    aggregators use binary adjacency)}.

    Divergences from the host path, both graceful where the host raises:
    rows beyond the slab's max_degree were already truncated to their
    heaviest neighbors at build_adjacency time, and a hop with more than
    node_caps[h] unique neighbors drops the largest-id overflow nodes
    (their edges are masked out) instead of raising — caps must be sized
    generously, exactly like the host's max_nodes_per_hop.
    """
    cur = jnp.asarray(roots, dtype=jnp.int32).reshape(-1)
    hops = []
    for adj, cap in zip(adjs, node_caps):
        default = adj["nbr"].shape[0] - 1
        W = adj["nbr"].shape[1]
        C = cur.shape[0]
        nbrs = adj["nbr"][cur]                            # [C, W]
        valid = jnp.arange(W)[None, :] < adj["deg"][cur][:, None]
        flat = jnp.where(valid, nbrs, default).reshape(-1)  # [C*W]
        # sort-based dedup: dense rank of each flat entry among the
        # sorted unique ids. The default node is the largest id, so
        # padding entries sort last and never displace real nodes.
        order = jnp.argsort(flat)
        s = flat[order]
        first = jnp.concatenate(
            [jnp.ones(1, dtype=bool), s[1:] != s[:-1]]
        )
        rank_sorted = jnp.cumsum(first) - 1               # [C*W]
        rank = jnp.zeros_like(rank_sorted).at[order].set(rank_sorted)
        # overflow ranks (>= cap) scatter out of bounds and are dropped
        nodes = (
            jnp.full((cap,), default, dtype=jnp.int32)
            .at[rank_sorted]
            .set(s.astype(jnp.int32), mode="drop")
        )
        src = jnp.repeat(jnp.arange(C, dtype=jnp.int32), W)
        dst = jnp.clip(rank, 0, cap - 1).astype(jnp.int32)
        mask = (
            valid.reshape(-1)
            & (rank < cap)
            & (flat != default)
        ).astype(jnp.float32)
        hops.append(
            {
                "nodes": nodes,
                "src": src,
                "dst": dst,
                "mask": mask,
                "w": mask,
            }
        )
        cur = nodes
    return hops


def sample_fanout(adjs, roots, key, counts):
    """Fused multi-hop device fanout (host analog: graph.sample_fanout).

    adjs: one adjacency dict per hop (repeat the same dict for a
    homogeneous metapath). Returns [roots, hop1, hop2, ...] flat id
    arrays, hop h sized prod(counts[:h+1]) * len(roots).

    Two-hop fanouts over packed slabs route through the CHAINED kernel
    (pallas_sampling.sample_fanout2): both hops in one program, the
    data-dependent hop-2 row DMAs hidden behind the next stage's hop-1
    compute — directly on a single device, per-shard via shard_map when
    a kernel mesh is registered. Everything else keeps the per-hop loop
    (whose single-hop draws still use the kernel when eligible).
    """
    if len(adjs) != len(counts):
        raise ValueError(
            f"sample_fanout needs one adjacency per hop: got {len(adjs)} "
            f"adjacencies for {len(counts)} fanout counts"
        )
    roots = jnp.asarray(roots, dtype=jnp.int32).reshape(-1)

    chained = _sample_fanout2_route(adjs, roots, key, counts)
    if chained is not None:
        return chained

    out = [roots]
    cur = roots
    for h, (adj, c) in enumerate(zip(adjs, counts)):
        k = jax.random.fold_in(key, h)
        cur = sample_neighbor(adj, cur, k, c).reshape(-1)
        out.append(cur)
    return out


def _sample_fanout2_route(adjs, roots, key, counts):
    """[roots, hop1, hop2] via the chained kernel when this fanout
    qualifies, else None (caller keeps the per-hop loop). Mirrors
    sample_neighbor's routing: direct kernel on a single device
    (available()), shard_map per-shard when a kernel mesh is
    registered."""
    from euler_tpu.graph import pallas_sampling

    if len(adjs) != 2:
        return None
    a1, a2 = adjs
    if "packed" not in a1 or "packed" not in a2:
        return None
    if a1["nbr"].shape[0] != a2["nbr"].shape[0]:
        return None
    f1, f2 = counts
    m = int(roots.shape[0])
    if m == 0:
        return None
    n_rows = a1["nbr"].shape[0]
    k1 = a1["packed"].shape[0] // (2 * n_rows)
    k2 = a2["packed"].shape[0] // (2 * n_rows)

    def kernel_seed():
        return jax.random.randint(key, (2,), 0, jnp.iinfo(jnp.int32).max)

    if _KERNEL_MESH is not None:
        mesh, axis = _KERNEL_MESH
        n_sh = mesh.shape[axis]
        if m % n_sh == 0 and pallas_sampling.eligible2(
            m // n_sh, f1, f2, k1, k2
        ):
            h1, h2 = pallas_sampling.sample_fanout2_sharded(
                a1, a2, roots, kernel_seed(), f1, f2, mesh, axis
            )
            return [roots, h1.reshape(-1), h2.reshape(-1)]
    elif pallas_sampling.eligible2(
        m, f1, f2, k1, k2
    ) and pallas_sampling.available():
        h1, h2 = pallas_sampling.sample_fanout2(
            a1, a2, roots, kernel_seed(), f1, f2
        )
        return [roots, h1.reshape(-1), h2.reshape(-1)]
    return None
