"""Embedded graph client: a Python facade over the native engine returning
fixed-shape numpy arrays ready for the TPU input pipeline.

Role equivalent of the reference client stack in Local mode
(reference euler/client/graph.h:47 + local_graph.cc + the 17 custom TF ops in
tf_euler/ops and kernels) — but synchronous-batch instead of callback-async,
because the TPU design overlaps sampling with device compute through a
prefetch thread pool rather than through per-op async kernels. All ids are
int64 on the Python side (JAX-friendly); the native layer works in uint64 and
the bit patterns pass through unchanged (default ids like -1 wrap).
"""

from __future__ import annotations

import ctypes

import numpy as np

from euler_tpu.graph.native import lib

# Feature-kind selectors of the C ABI (eg_capi.cc eg_feature_num).
NODE_U64, NODE_F32, NODE_BIN, EDGE_U64, EDGE_F32, EDGE_BIN = range(6)

_U64P = ctypes.POINTER(ctypes.c_uint64)
_F32P = ctypes.POINTER(ctypes.c_float)
_I32P = ctypes.POINTER(ctypes.c_int32)


def _ids(a) -> np.ndarray:
    """Accept any integer array-like; reinterpret int64 as uint64 bits."""
    arr = np.ascontiguousarray(np.asarray(a).reshape(-1))
    if arr.dtype == np.uint64:
        return arr
    return arr.astype(np.int64, copy=False).view(np.uint64)


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int32).reshape(-1))


def _ptr(a: np.ndarray, ty):
    return a.ctypes.data_as(ty)


def _default_u64(default_node: int) -> int:
    return int(np.int64(default_node).view(np.uint64))


class Graph:
    """Graph client: embedded engine (mode='local') or sharded remote
    client (mode='remote').

    Mode selection mirrors the reference factory Graph::NewGraph
    (reference euler/client/graph.cc:157-185): local embeds the engine
    in-process; remote discovers shards from a flat-file ``registry``
    directory (written by :class:`euler_tpu.graph.GraphService`) or an
    explicit ``shards`` list, routes ids shard(id) = (id % P) % S, and
    merges scatter/gather replies — all in native code (eg_remote.cc).
    """

    def __init__(
        self,
        directory: str | None = None,
        files: list[str] | None = None,
        shard_idx: int = 0,
        shard_num: int = 1,
        mode: str = "local",
        registry: str | None = None,
        shards: list[str] | list[list[str]] | None = None,
        retries: int = 3,
        timeout_ms: int = 5000,
        quarantine_ms: int = 3000,
        cache_dir: str | None = None,
    ):
        self._lib = lib()
        if mode not in ("local", "remote"):
            raise ValueError("mode must be 'local' or 'remote'")
        # Remote filesystems (the reference reads graph data straight off
        # HDFS, euler/common/hdfs_file_io.cc:79-80): any fsspec URL is
        # staged shard-aware to a local cache, then loaded through the one
        # fast local path (see euler_tpu/graph/remote_fs.py).
        from euler_tpu.graph import remote_fs

        if mode == "local":
            # directory=/files= are only consumed by the embedded engine;
            # remote mode must not stage data it will never read
            if directory is not None:
                if remote_fs.is_remote_path(directory):
                    directory = remote_fs.stage_directory(
                        directory,
                        cache_dir=cache_dir,
                        shard_idx=shard_idx,
                        shard_num=shard_num,
                    )
                    # staging already applied the shard selection; the
                    # native re-filter on the staged names is a no-op
                else:
                    directory = remote_fs.strip_local_scheme(directory)
            if files:
                files = remote_fs.stage_files(files, cache_dir=cache_dir)
        if (
            registry is not None
            and not registry.startswith("tcp://")
            and remote_fs.is_remote_path(registry)
        ):
            raise NotImplementedError(
                f"registry on a remote filesystem is not supported "
                f"({registry}); the registry is a liveness-watched "
                "directory — use a local/NFS path, tcp://host:port of a "
                "euler_tpu.graph.registry server, or an explicit "
                "shards= list"
            )
        self.mode = mode
        if mode == "remote":
            if registry:
                conf = f"registry={registry}"
            elif shards:
                # each entry: an address, or a list of replica addresses
                parts = [
                    s if isinstance(s, str) else "|".join(s) for s in shards
                ]
                conf = "shards=" + ",".join(parts)
            else:
                raise ValueError("remote mode needs registry= or shards=")
            conf += (
                f";retries={retries};timeout_ms={timeout_ms}"
                f";quarantine_ms={quarantine_ms}"
            )
            self._h = self._lib.eg_remote_create(conf.encode())
            if not self._h:
                err = self._lib.eg_last_error().decode()
                raise RuntimeError(f"remote graph init failed: {err}")
            return
        self._h = self._lib.eg_create()
        if directory is not None:
            rc = self._lib.eg_load(
                self._h, directory.encode(), shard_idx, shard_num
            )
        elif files:
            arr = (ctypes.c_char_p * len(files))(*[f.encode() for f in files])
            rc = self._lib.eg_load_files(self._h, arr, len(files))
        else:
            raise ValueError("pass directory= or files=")
        if rc != 0:
            err = self._lib.eg_last_error().decode()
            self._lib.eg_destroy(self._h)
            self._h = None
            raise RuntimeError(f"graph load failed: {err}")

    @property
    def num_shards(self) -> int:
        return (
            self._lib.eg_remote_shards(self._h) if self.mode == "remote" else 1
        )

    @property
    def num_partitions(self) -> int:
        return (
            self._lib.eg_remote_partitions(self._h)
            if self.mode == "remote"
            else 1
        )

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.eg_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ---- introspection ----
    @property
    def num_nodes(self) -> int:
        return self._lib.eg_num_nodes(self._h)

    @property
    def num_edges(self) -> int:
        return self._lib.eg_num_edges(self._h)

    @property
    def node_type_num(self) -> int:
        return self._lib.eg_node_type_num(self._h)

    @property
    def edge_type_num(self) -> int:
        return self._lib.eg_edge_type_num(self._h)

    def feature_num(self, kind: int) -> int:
        return self._lib.eg_feature_num(self._h, kind)

    def type_weight_sums(self, edges: bool = False) -> np.ndarray:
        n = self.edge_type_num if edges else self.node_type_num
        out = np.zeros(n, dtype=np.float32)
        if n:
            self._lib.eg_type_weight_sums(
                self._h, 1 if edges else 0, _ptr(out, _F32P)
            )
        return out

    # ---- global sampling ----
    def sample_node(self, count: int, node_type: int = -1) -> np.ndarray:
        out = np.empty(count, dtype=np.uint64)
        self._lib.eg_sample_node(self._h, count, node_type, _ptr(out, _U64P))
        return out.view(np.int64)

    def sample_edge(self, count: int, edge_type: int = -1):
        src = np.empty(count, dtype=np.uint64)
        dst = np.empty(count, dtype=np.uint64)
        t = np.empty(count, dtype=np.int32)
        self._lib.eg_sample_edge(
            self._h, count, edge_type, _ptr(src, _U64P), _ptr(dst, _U64P),
            _ptr(t, _I32P),
        )
        return src.view(np.int64), dst.view(np.int64), t

    def sample_node_with_src(self, src_ids, count: int) -> np.ndarray:
        """[n, count] negatives drawn from each src's node-type sampler."""
        ids = _ids(src_ids)
        out = np.empty((len(ids), count), dtype=np.uint64)
        self._lib.eg_sample_node_with_src(
            self._h, _ptr(ids, _U64P), len(ids), count, _ptr(out, _U64P)
        )
        return out.view(np.int64)

    def node_types(self, ids) -> np.ndarray:
        ids = _ids(ids)
        out = np.empty(len(ids), dtype=np.int32)
        self._lib.eg_get_node_type(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(out, _I32P)
        )
        return out

    # ---- neighbor ops ----
    def sample_neighbor(
        self, ids, edge_types, count: int, default_node: int = -1
    ):
        """Returns (nbr_ids [n,count] i64, weights [n,count] f32,
        types [n,count] i32)."""
        ids = _ids(ids)
        et = _i32(edge_types)
        n = len(ids)
        out_i = np.empty((n, count), dtype=np.uint64)
        out_w = np.empty((n, count), dtype=np.float32)
        out_t = np.empty((n, count), dtype=np.int32)
        self._lib.eg_sample_neighbor(
            self._h, _ptr(ids, _U64P), n, _ptr(et, _I32P), len(et), count,
            _default_u64(default_node), _ptr(out_i, _U64P), _ptr(out_w, _F32P),
            _ptr(out_t, _I32P),
        )
        return out_i.view(np.int64), out_w, out_t

    def sample_fanout(self, ids, edge_types, counts, default_node: int = -1):
        """Fused multi-hop sampling: one native call for all hops.

        edge_types: per-hop list of edge-type lists; counts: per-hop fanouts.
        Returns (ids_per_hop, weights_per_hop, types_per_hop); hop h arrays
        are flat with n * prod(counts[:h+1]) rows. ids_per_hop[0] is the
        (flattened) input.
        """
        ids = _ids(ids)
        nhops = len(counts)
        et_lists = [_i32(e) for e in edge_types]
        et_flat = (
            np.concatenate(et_lists) if et_lists else np.zeros(0, np.int32)
        )
        et_counts = _i32([len(e) for e in et_lists])
        counts_arr = _i32(counts)
        out_i, out_w, out_t = [], [], []
        m = len(ids)
        for h in range(nhops):
            m *= int(counts[h])
            out_i.append(np.empty(m, dtype=np.uint64))
            out_w.append(np.empty(m, dtype=np.float32))
            out_t.append(np.empty(m, dtype=np.int32))
        ids_ptrs = (_U64P * nhops)(*[_ptr(a, _U64P) for a in out_i])
        w_ptrs = (_F32P * nhops)(*[_ptr(a, _F32P) for a in out_w])
        t_ptrs = (_I32P * nhops)(*[_ptr(a, _I32P) for a in out_t])
        self._lib.eg_sample_fanout(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(et_flat, _I32P),
            _ptr(et_counts, _I32P), _ptr(counts_arr, _I32P), nhops,
            _default_u64(default_node), ids_ptrs, w_ptrs, t_ptrs,
        )
        return (
            [ids.view(np.int64)] + [a.view(np.int64) for a in out_i],
            out_w,
            out_t,
        )

    def get_full_neighbor(self, ids, edge_types, sorted: bool = False):
        """Ragged full adjacency: (nbr_ids, weights, types, row_counts)."""
        ids = _ids(ids)
        et = _i32(edge_types)
        r = self._lib.eg_get_full_neighbor(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(et, _I32P), len(et),
            1 if sorted else 0,
        )
        try:
            nbr = self._fetch(r, 0, 0, np.uint64)
            w = self._fetch(r, 1, 0, np.float32)
            t = self._fetch(r, 2, 0, np.int32)
            counts = self._fetch(r, 2, 1, np.int32)
        finally:
            self._lib.eg_result_free(r)
        return nbr.view(np.int64), w, t, counts

    def get_top_k_neighbor(self, ids, edge_types, k: int, default_node=-1):
        ids = _ids(ids)
        et = _i32(edge_types)
        n = len(ids)
        out_i = np.empty((n, k), dtype=np.uint64)
        out_w = np.empty((n, k), dtype=np.float32)
        out_t = np.empty((n, k), dtype=np.int32)
        self._lib.eg_get_top_k_neighbor(
            self._h, _ptr(ids, _U64P), n, _ptr(et, _I32P), len(et), k,
            _default_u64(default_node), _ptr(out_i, _U64P), _ptr(out_w, _F32P),
            _ptr(out_t, _I32P),
        )
        return out_i.view(np.int64), out_w, out_t

    # ---- walks ----
    def random_walk(
        self, ids, edge_types, walk_len: int = None, p: float = 1.0,
        q: float = 1.0, default_node: int = -1,
    ) -> np.ndarray:
        """[n, walk_len+1] int64 walks; column 0 is the start node.

        edge_types is either a flat list (same types every step; walk_len
        required) or a per-step list of lists defining a heterogeneous
        metapath (walk_len inferred), e.g. [[0], [1], [0]].
        """
        ids = _ids(ids)
        if len(edge_types) > 0 and isinstance(
            edge_types[0], (list, tuple, np.ndarray)
        ):
            steps = [_i32(e) for e in edge_types]
            if walk_len is None:
                walk_len = len(steps)
            elif walk_len != len(steps):
                raise ValueError("walk_len != len(edge_types metapath)")
        else:
            if walk_len is None:
                raise ValueError("walk_len required with flat edge_types")
            steps = [_i32(edge_types)] * walk_len
        et_flat = (
            np.concatenate(steps) if steps else np.zeros(0, np.int32)
        )
        et_counts = _i32([len(s) for s in steps])
        out = np.empty((len(ids), walk_len + 1), dtype=np.uint64)
        self._lib.eg_random_walk(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(et_flat, _I32P),
            _ptr(et_counts, _I32P), walk_len, p, q,
            _default_u64(default_node), _ptr(out, _U64P),
        )
        return out.view(np.int64)

    # ---- features ----
    def get_dense_feature(self, ids, fids, dims) -> np.ndarray:
        """[n, sum(dims)] float32, zero-padded per slot."""
        ids = _ids(ids)
        fids = _i32(fids)
        dims = _i32(dims)
        out = np.empty((len(ids), int(dims.sum())), dtype=np.float32)
        self._lib.eg_get_dense_feature(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(fids, _I32P),
            _ptr(dims, _I32P), len(fids), _ptr(out, _F32P),
        )
        return out

    def get_edge_dense_feature(self, src, dst, types, fids, dims) -> np.ndarray:
        src = _ids(src)
        dst = _ids(dst)
        types = _i32(types)
        fids = _i32(fids)
        dims = _i32(dims)
        out = np.empty((len(src), int(dims.sum())), dtype=np.float32)
        self._lib.eg_get_edge_dense_feature(
            self._h, _ptr(src, _U64P), _ptr(dst, _U64P), _ptr(types, _I32P),
            len(src), _ptr(fids, _I32P), _ptr(dims, _I32P), len(fids),
            _ptr(out, _F32P),
        )
        return out

    def get_sparse_feature(self, ids, fids):
        """Per slot: (values i64 concat, row_counts i32[n])."""
        ids = _ids(ids)
        fids = _i32(fids)
        r = self._lib.eg_get_sparse_feature(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(fids, _I32P), len(fids)
        )
        return self._drain_sparse(r, len(fids))

    def get_edge_sparse_feature(self, src, dst, types, fids):
        src = _ids(src)
        dst = _ids(dst)
        types = _i32(types)
        fids = _i32(fids)
        r = self._lib.eg_get_edge_sparse_feature(
            self._h, _ptr(src, _U64P), _ptr(dst, _U64P), _ptr(types, _I32P),
            len(src), _ptr(fids, _I32P), len(fids),
        )
        return self._drain_sparse(r, len(fids))

    def get_binary_feature(self, ids, fids):
        """Per slot: list of bytes, one per row."""
        ids = _ids(ids)
        fids = _i32(fids)
        r = self._lib.eg_get_binary_feature(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(fids, _I32P), len(fids)
        )
        return self._drain_binary(r, len(fids))

    def get_edge_binary_feature(self, src, dst, types, fids):
        src = _ids(src)
        dst = _ids(dst)
        types = _i32(types)
        fids = _i32(fids)
        r = self._lib.eg_get_edge_binary_feature(
            self._h, _ptr(src, _U64P), _ptr(dst, _U64P), _ptr(types, _I32P),
            len(src), _ptr(fids, _I32P), len(fids),
        )
        return self._drain_binary(r, len(fids))

    # ---- result plumbing ----
    def _fetch(self, r, kind: int, slot: int, dtype) -> np.ndarray:
        n = self._lib.eg_result_size(r, kind, slot)
        out = np.empty(max(n, 0), dtype=dtype)
        if n > 0:
            self._lib.eg_result_copy(
                r, kind, slot, out.ctypes.data_as(ctypes.c_void_p)
            )
        return out

    def _drain_sparse(self, r, nslots: int):
        try:
            out = []
            for k in range(nslots):
                vals = self._fetch(r, 0, k, np.uint64).view(np.int64)
                counts = self._fetch(r, 2, k, np.int32)
                out.append((vals, counts))
            return out
        finally:
            self._lib.eg_result_free(r)

    def _drain_binary(self, r, nslots: int):
        try:
            out = []
            for k in range(nslots):
                n = self._lib.eg_result_size(r, 3, k)
                buf = ctypes.create_string_buffer(max(int(n), 1))
                if n > 0:
                    self._lib.eg_result_copy(r, 3, k, buf)
                data = buf.raw[: int(n)]
                sizes = self._fetch(r, 2, k, np.int32)
                rows = []
                off = 0
                for s in sizes:
                    rows.append(data[off : off + int(s)])
                    off += int(s)
                out.append(rows)
            return out
        finally:
            self._lib.eg_result_free(r)
