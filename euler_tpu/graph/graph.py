"""Embedded graph client: a Python facade over the native engine returning
fixed-shape numpy arrays ready for the TPU input pipeline.

Role equivalent of the reference client stack in Local mode
(reference euler/client/graph.h:47 + local_graph.cc + the 17 custom TF ops in
tf_euler/ops and kernels) — but synchronous-batch instead of callback-async,
because the TPU design overlaps sampling with device compute through a
prefetch thread pool rather than through per-op async kernels. All ids are
int64 on the Python side (JAX-friendly); the native layer works in uint64 and
the bit patterns pass through unchanged (default ids like -1 wrap).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from euler_tpu.graph.native import lib

# Feature-kind selectors of the C ABI (eg_capi.cc eg_feature_num).
NODE_U64, NODE_F32, NODE_BIN, EDGE_U64, EDGE_F32, EDGE_BIN = range(6)

_U64P = ctypes.POINTER(ctypes.c_uint64)
_F32P = ctypes.POINTER(ctypes.c_float)
_I32P = ctypes.POINTER(ctypes.c_int32)


def _ids(a) -> np.ndarray:
    """Accept any integer array-like; reinterpret int64 as uint64 bits."""
    arr = np.ascontiguousarray(np.asarray(a).reshape(-1))
    if arr.dtype == np.uint64:
        return arr
    return arr.astype(np.int64, copy=False).view(np.uint64)


def _i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int32).reshape(-1))


def _ptr(a: np.ndarray, ty):
    return a.ctypes.data_as(ty)


def _default_u64(default_node: int) -> int:
    return int(np.int64(default_node).view(np.uint64))


def str2bool(v) -> bool:
    """ONE truthy-string rule for every bool that can arrive as text
    (config strings here, CLI flags in run_loop) — two parsers with
    different accepted spellings is how `stream=y` silently stages to
    disk while `--stream y` streams."""
    return str(v).lower() in ("1", "true", "yes", "y")


def parse_config(source: str) -> dict:
    """Parse a client config: a ``.ini``-style file of ``key = value``
    lines ('#'/';' comments, optional [sections] ignored) or an inline
    ``k=v;k=v`` string. Values that look numeric come back as ints.

    Role equivalent of the reference's GraphConfig loader
    (reference euler/client/graph_config.cc:33-56) plus the semicolon
    string form used across its C ABI (create_graph.cc:50-60).
    """
    import os

    # a path wins over the inline form when both could apply (paths may
    # legitimately contain '='; inline strings are never existing files)
    if os.path.exists(source) or "=" not in source:
        with open(source) as f:
            lines = f.read().splitlines()
    else:
        lines = source.split(";")
    out: dict = {}
    for line in lines:
        line = line.strip()
        if not line or line[0] in "#;[":
            continue
        if "=" not in line:
            raise ValueError(f"bad config line (want key=value): {line!r}")
        k, v = (s.strip() for s in line.split("=", 1))
        try:
            out[k] = int(v)
        except ValueError:
            out[k] = v
    return out


class Graph:
    """Graph client: embedded engine (mode='local') or sharded remote
    client (mode='remote').

    Mode selection mirrors the reference factory Graph::NewGraph
    (reference euler/client/graph.cc:157-185): local embeds the engine
    in-process; remote discovers shards from a ``registry`` (flat-file
    directory written by :class:`euler_tpu.graph.GraphService`, or
    ``tcp://host:port`` of a euler_tpu.graph.registry server) or an
    explicit ``shards`` list, routes ids shard(id) = (id % P) % S, and
    merges scatter/gather replies — all in native code (eg_remote.cc).

    Like the reference, the client also takes a config file or inline
    config string (``config=``: ``key = value`` lines or ``k=v;k=v``,
    graph_config.cc:33-56) with explicit kwargs taking precedence, and
    ``init="lazy"`` defers engine construction to first use
    (graph.cc:176-183).
    """

    def __init__(
        self,
        directory: str | None = None,
        files: list[str] | None = None,
        shard_idx: int | None = None,
        shard_num: int | None = None,
        mode: str | None = None,
        registry: str | None = None,
        shards: list[str] | list[list[str]] | None = None,
        retries: int | None = None,
        timeout_ms: int | None = None,
        quarantine_ms: int | None = None,
        rediscover_ms: int | None = None,
        backoff_ms: int | None = None,
        deadline_ms: int | None = None,
        fault: str | None = None,
        fault_seed: int | None = None,
        feature_cache_mb: int | None = None,
        neighbor_cache_mb: int | None = None,
        cache_policy: str | None = None,
        placement: bool | None = None,
        strict: bool | None = None,
        coalesce: bool | None = None,
        chunk_ids: int | None = None,
        dispatch_workers: int | None = None,
        wire_version: int | None = None,
        telemetry: bool | None = None,
        slow_spans: int | None = None,
        heat: bool | None = None,
        heat_topk: int | None = None,
        blackbox: bool | None = None,
        devprof: bool | None = None,
        postmortem_dir: str | None = None,
        cache_dir: str | None = None,
        stream: bool | None = None,
        delta: str | list[str] | None = None,
        config: str | None = None,
        init: str | None = None,
    ):
        self._lib = lib()
        self._handle = None
        self._closed = False
        self._connect_lock = threading.Lock()
        # config file / inline string (reference Graph::NewGraph(filename),
        # euler/client/graph.cc:163-185); explicit kwargs override it
        cfg = parse_config(config) if config else {}
        known = {
            "directory", "files", "shard_idx", "shard_num", "mode",
            "registry", "shards", "retries", "timeout_ms", "quarantine_ms",
            "rediscover_ms", "backoff_ms", "deadline_ms", "fault",
            "fault_seed", "feature_cache_mb", "neighbor_cache_mb",
            "cache_policy", "placement", "strict", "coalesce",
            "chunk_ids", "dispatch_workers", "wire_version", "telemetry",
            "slow_spans", "heat", "heat_topk", "blackbox", "devprof",
            "postmortem_dir", "cache_dir", "stream", "delta", "init",
        }
        unknown = set(cfg) - known
        if unknown:
            # only a fixed key set is consumed — a typo'd key would
            # otherwise be dropped silently (e.g. timout_ms)
            raise ValueError(
                f"unknown config keys {sorted(unknown)}; valid: "
                f"{sorted(known)}"
            )

        def pick(name, explicit, default):
            return explicit if explicit is not None else cfg.get(name, default)

        directory = pick("directory", directory, None)
        files = pick("files", files, None)
        if isinstance(files, str):
            files = [s.strip() for s in files.split(",")]
        shard_idx = int(pick("shard_idx", shard_idx, 0))
        shard_num = int(pick("shard_num", shard_num, 1))
        mode = str(pick("mode", mode, "local")).lower()
        registry = pick("registry", registry, None)
        shards = pick("shards", shards, None)
        if isinstance(shards, str):
            shards = [s.strip() for s in shards.split(",")]
        retries = int(pick("retries", retries, 3))
        timeout_ms = int(pick("timeout_ms", timeout_ms, 5000))
        quarantine_ms = int(pick("quarantine_ms", quarantine_ms, 3000))
        # mid-run registry re-LIST period (native RediscoverLoop); None =
        # the native default (3000 ms with a registry, off for shards=)
        rediscover_ms = pick("rediscover_ms", rediscover_ms, None)
        # retry pacing (native ConnPool::Call): base of the jittered
        # exponential backoff, and the overall per-call deadline spanning
        # all retries; None = native defaults (20 ms / timeout*(retries+1))
        backoff_ms = pick("backoff_ms", backoff_ms, None)
        deadline_ms = pick("deadline_ms", deadline_ms, None)
        # deterministic transport failpoints (FAULTS.md), e.g.
        # "recv_frame:err@0.5,dial:delay@200"; process-global
        fault = pick("fault", fault, None)
        fault_seed = pick("fault_seed", fault_seed, None)
        # remote hot-path knobs (native defaults apply when None):
        # feature_cache_mb (64; 0 off) bounds the client-side dense-
        # feature-row cache, strict (0) raises on a shard that failed
        # after all transport retries instead of training on defaults,
        # coalesce (1) dedups duplicate ids before wire encode,
        # chunk_ids (16384) splits large per-shard requests into
        # concurrent chunks, dispatch_workers (auto) sizes the
        # persistent dispatcher pool
        feature_cache_mb = pick("feature_cache_mb", feature_cache_mb, None)
        # locality knobs (ROADMAP item 5; native defaults apply when
        # None): neighbor_cache_mb (16; 0 off) bounds the client-side
        # neighbor-list cache — hot nodes' adjacency slices sampled
        # locally instead of per-hop wire trips; cache_policy
        # ("freq"|"fifo", default freq) selects TinyLFU-shaped vs
        # unconditional admission for BOTH client caches; placement
        # (True) fetches the shard's id->partition map at init and
        # routes through it, hash fallback when no map exists
        neighbor_cache_mb = pick("neighbor_cache_mb", neighbor_cache_mb,
                                 None)
        cache_policy = pick("cache_policy", cache_policy, None)
        placement = pick("placement", placement, None)
        if isinstance(placement, str):
            placement = str2bool(placement)
        strict = pick("strict", strict, None)
        if isinstance(strict, str):
            strict = str2bool(strict)
        coalesce = pick("coalesce", coalesce, None)
        if isinstance(coalesce, str):
            coalesce = str2bool(coalesce)
        chunk_ids = pick("chunk_ids", chunk_ids, None)
        dispatch_workers = pick("dispatch_workers", dispatch_workers, None)
        # wire_version=1 emulates a pre-envelope client (compat drills /
        # operational escape hatch), 2 forces the v2 deadline envelope;
        # None = negotiate per replica (old servers are auto-downgraded,
        # counted in wire_downgrades)
        wire_version = pick("wire_version", wire_version, None)
        # observability (eg_telemetry.h; process-global like fault=):
        # telemetry=0 kills histogram/slow-span recording, slow_spans=
        # resizes the slowest-N journal
        telemetry = pick("telemetry", telemetry, None)
        if isinstance(telemetry, str):
            telemetry = str2bool(telemetry)
        slow_spans = pick("slow_spans", slow_spans, None)
        # data-plane heat profiler (eg_heat.h; process-global like
        # telemetry=): heat=0 stops id feeds / fan-out attribution /
        # cache-class recording, heat_topk= resizes the hot-key tracker
        heat = pick("heat", heat, None)
        if isinstance(heat, str):
            heat = str2bool(heat)
        heat_topk = pick("heat_topk", heat_topk, None)
        # blackbox flight recorder + postmortem dump path
        # (eg_blackbox.h; process-global like telemetry=, but valid in
        # BOTH modes — an embedded-engine trainer crashes too, and its
        # postmortem is exactly as valuable as a shard's)
        blackbox = pick("blackbox", blackbox, None)
        if isinstance(blackbox, str):
            blackbox = str2bool(blackbox)
        # device-plane observability (eg_devprof.h; process-global like
        # blackbox=, valid in BOTH modes — an embedded-engine trainer
        # compiles and recompiles XLA programs exactly like a remote one)
        devprof = pick("devprof", devprof, None)
        if isinstance(devprof, str):
            devprof = str2bool(devprof)
        postmortem_dir = pick("postmortem_dir", postmortem_dir, None)
        cache_dir = pick("cache_dir", cache_dir, None)
        stream = pick("stream", stream, False)
        if isinstance(stream, str):
            stream = str2bool(stream)
        # snapshot-epoch delta files (eg_epoch.h; `<prefix>.delta.<n>`,
        # see convert.py --delta-from): applied over the base load at
        # connect, leaving the engine at epoch = len(delta)
        delta = pick("delta", delta, None)
        if isinstance(delta, str):
            delta = [s.strip() for s in delta.replace(";", ",").split(",")
                     if s.strip()]
        init = str(pick("init", init, "eager")).lower()
        if mode not in ("local", "remote"):
            raise ValueError("mode must be 'local' or 'remote'")
        if directory is not None and files:
            # never dropped silently: the load dispatch would consume
            # directory= and ignore the file list entirely
            raise ValueError(
                "pass directory= OR files=, not both (the embedded "
                "engine loads exactly one of them; a files= list next "
                "to directory= would be silently ignored)"
            )
        if fault_seed is not None and fault is None:
            raise ValueError(
                "fault_seed= without fault= would seed nothing — pass the "
                "failpoint spec too (FAULTS.md)"
            )
        if fault is not None and mode != "remote":
            # the failpoints live in the TCP transport; accepting the key
            # on a local graph would just mislead (nothing would fire)
            raise ValueError(
                "fault= applies to mode='remote' graphs (failpoints sit "
                "in the transport, see FAULTS.md; for service-side "
                "injection use euler_tpu.graph.native.fault_config in "
                "the shard process)"
            )
        if mode != "remote":
            # same loudness rule: these keys configure the remote client's
            # wire path (dedup, cache, chunking, dispatcher, strict shard
            # failures); an embedded engine has no wire, so accepting
            # them would silently do nothing
            for key, val in (
                ("feature_cache_mb", feature_cache_mb), ("strict", strict),
                ("neighbor_cache_mb", neighbor_cache_mb),
                ("cache_policy", cache_policy), ("placement", placement),
                ("coalesce", coalesce), ("chunk_ids", chunk_ids),
                ("dispatch_workers", dispatch_workers),
                ("wire_version", wire_version),
                ("telemetry", telemetry), ("slow_spans", slow_spans),
                ("heat", heat), ("heat_topk", heat_topk),
            ):
                if val is not None:
                    raise ValueError(
                        f"{key}= applies to mode='remote' graphs (it "
                        "configures the remote client's request path; "
                        "the embedded engine reads local memory)"
                    )
        if delta and mode != "local":
            # never dropped silently: a remote client holds no graph data
            # to merge — shards apply their own deltas (Graph.load_delta
            # per shard, or `service --load_delta`)
            raise ValueError(
                "delta= applies to mode='local' graphs (remote shards "
                "merge their own delta files — use load_delta(path, "
                "shard=...) or `python -m euler_tpu.graph.service "
                "--load_delta`; see DEPLOY.md 'Rolling graph refresh')"
            )
        if stream and mode != "local":
            # never dropped silently: remote mode reads no graph data
            # itself, so accepting the flag would just mislead
            raise ValueError(
                "stream=True applies to mode='local' graphs "
                "(remote-mode clients read from shard services, which "
                "stage their own data; see DEPLOY.md 'Remote data')"
            )
        if init not in ("eager", "lazy"):
            raise ValueError("init must be 'eager' or 'lazy'")
        # graph init arms the blackbox (the service arms it on its own
        # side): kill-switch first, then the postmortem path — BEFORE
        # the engine/remote handle exists, so even a crash during load
        # or discovery leaves a dump
        if blackbox is not None:
            from euler_tpu import blackbox as _blackbox

            _blackbox.set_blackbox(bool(blackbox))
        if devprof is not None:
            from euler_tpu import devprof as _devprof

            _devprof.set_devprof(bool(devprof))
            if devprof:
                _devprof.install()
        if postmortem_dir is not None:
            from euler_tpu import blackbox as _blackbox

            _blackbox.install(postmortem_dir)
        self._params = dict(
            directory=directory, files=files, shard_idx=shard_idx,
            shard_num=shard_num, registry=registry, shards=shards,
            retries=retries, timeout_ms=timeout_ms,
            quarantine_ms=quarantine_ms, rediscover_ms=rediscover_ms,
            backoff_ms=backoff_ms, deadline_ms=deadline_ms,
            fault=fault, fault_seed=fault_seed,
            feature_cache_mb=feature_cache_mb,
            neighbor_cache_mb=neighbor_cache_mb,
            cache_policy=cache_policy, placement=placement,
            strict=strict,
            coalesce=coalesce, chunk_ids=chunk_ids,
            dispatch_workers=dispatch_workers, wire_version=wire_version,
            telemetry=telemetry, slow_spans=slow_spans, heat=heat,
            heat_topk=heat_topk, cache_dir=cache_dir, stream=bool(stream),
            delta=delta,
        )
        self.mode = mode
        self._strict = bool(strict) if strict is not None else False
        # local-mode delta chain applied so far (load_delta re-sends the
        # whole chain per flip; seeded by the delta= config key)
        self._applied_deltas: list[str] = list(delta) if delta else []
        if init == "eager":
            self._connect()

    @property
    def _h(self):
        """Native handle; a lazy-init graph connects on first use
        (reference init=lazy, graph.cc:176-183). Thread-safe: concurrent
        first users (prefetch workers) connect exactly once."""
        if self._handle is None:
            with self._connect_lock:
                if self._handle is None:
                    self._connect()
        return self._handle

    def _connect(self) -> None:
        if self._closed:
            # close() must be final: a lingering reference (say a prefetch
            # thread) must not silently re-load the store or re-dial the
            # cluster through the lazy property
            raise RuntimeError("graph is closed")
        p = self._params
        directory = p["directory"]
        files = p["files"]
        shard_idx, shard_num = p["shard_idx"], p["shard_num"]
        registry, shards = p["registry"], p["shards"]
        cache_dir = p["cache_dir"]
        retries = p["retries"]
        timeout_ms, quarantine_ms = p["timeout_ms"], p["quarantine_ms"]
        mode = self.mode
        # Remote filesystems (the reference reads graph data straight off
        # HDFS, euler/common/hdfs_file_io.cc:79-80): any fsspec URL is
        # staged shard-aware to a local cache, then loaded through the one
        # fast local path (see euler_tpu/graph/remote_fs.py).
        from euler_tpu.graph import remote_fs

        buffers = None
        if mode == "local":
            # directory=/files= are only consumed by the embedded engine;
            # remote mode must not stage data it will never read
            if directory is not None:
                if remote_fs.is_remote_path(directory):
                    if p["stream"]:
                        # streaming ingest: fetch partition bytes to
                        # memory and parse them directly — zero local
                        # disk (the reference likewise streams off HDFS
                        # without staging, hdfs_file_io.cc:79-80)
                        buffers = remote_fs.read_directory(
                            directory,
                            shard_idx=shard_idx,
                            shard_num=shard_num,
                        )
                    else:
                        directory = remote_fs.stage_directory(
                            directory,
                            cache_dir=cache_dir,
                            shard_idx=shard_idx,
                            shard_num=shard_num,
                        )
                    # staging already applied the shard selection; the
                    # native re-filter on the staged names is a no-op
                else:
                    directory = remote_fs.strip_local_scheme(directory)
            if files and directory is None:
                # directory= wins at the load dispatch below; fetching
                # or staging a files= list that will then be ignored is
                # pure waste (and under stream=, RAM)
                if p["stream"]:
                    # stream= must never be dropped silently (the
                    # scratch-poor operator would stage to disk anyway
                    # and hit ENOSPC with no hint why)
                    buffers = remote_fs.read_files(files)
                else:
                    files = remote_fs.stage_files(
                        files, cache_dir=cache_dir
                    )
        if (
            registry is not None
            and not registry.startswith("tcp://")
            and remote_fs.is_remote_path(registry)
        ):
            raise NotImplementedError(
                f"registry on a remote filesystem is not supported "
                f"({registry}); the registry is a liveness-watched "
                "directory — use a local/NFS path, tcp://host:port of a "
                "euler_tpu.graph.registry server, or an explicit "
                "shards= list"
            )
        if mode == "remote":
            if registry:
                conf = f"registry={registry}"
            elif shards:
                # each entry: an address, or a list of replica addresses
                parts = [
                    s if isinstance(s, str) else "|".join(s) for s in shards
                ]
                conf = "shards=" + ",".join(parts)
            else:
                raise ValueError("remote mode needs registry= or shards=")
            conf += (
                f";retries={retries};timeout_ms={timeout_ms}"
                f";quarantine_ms={quarantine_ms}"
            )
            if p["rediscover_ms"] is not None:
                conf += f";rediscover_ms={int(p['rediscover_ms'])}"
            if p["backoff_ms"] is not None:
                conf += f";backoff_ms={int(p['backoff_ms'])}"
            if p["deadline_ms"] is not None:
                conf += f";deadline_ms={int(p['deadline_ms'])}"
            if p["feature_cache_mb"] is not None:
                conf += f";feature_cache_mb={int(p['feature_cache_mb'])}"
            if p["neighbor_cache_mb"] is not None:
                conf += f";neighbor_cache_mb={int(p['neighbor_cache_mb'])}"
            if p["cache_policy"] is not None:
                conf += f";cache_policy={p['cache_policy']}"
            if p["placement"] is not None:
                conf += f";placement={1 if p['placement'] else 0}"
            if p["strict"] is not None:
                conf += f";strict={1 if p['strict'] else 0}"
            if p["coalesce"] is not None:
                conf += f";coalesce={1 if p['coalesce'] else 0}"
            if p["chunk_ids"] is not None:
                conf += f";chunk_ids={int(p['chunk_ids'])}"
            if p["dispatch_workers"] is not None:
                conf += f";dispatch_workers={int(p['dispatch_workers'])}"
            if p["wire_version"] is not None:
                conf += f";wire_version={int(p['wire_version'])}"
            if p["telemetry"] is not None:
                conf += f";telemetry={1 if p['telemetry'] else 0}"
            if p["slow_spans"] is not None:
                conf += f";slow_spans={int(p['slow_spans'])}"
            if p["heat"] is not None:
                conf += f";heat={1 if p['heat'] else 0}"
            if p["heat_topk"] is not None:
                conf += f";heat_topk={int(p['heat_topk'])}"
            if p["fault"] is not None:
                # ';' is the k=v separator, so the fault grammar uses ','
                # between failpoints (FAULTS.md)
                conf += f";fault={p['fault']}"
                if p["fault_seed"] is not None:
                    conf += f";fault_seed={int(p['fault_seed'])}"
            self._handle = self._lib.eg_remote_create(conf.encode())
            if not self._handle:
                self._handle = None
                err = self._lib.eg_last_error().decode()
                raise RuntimeError(f"remote graph init failed: {err}")
            return
        h = self._lib.eg_create()
        if buffers is not None:
            n = len(buffers)
            names = (ctypes.c_char_p * n)(
                *[name.encode() for name, _ in buffers]
            )
            bufs = (ctypes.c_void_p * n)()
            lens = (ctypes.c_uint64 * n)()
            for i, (_, blob) in enumerate(buffers):
                bufs[i] = ctypes.cast(
                    ctypes.c_char_p(blob), ctypes.c_void_p
                )
                lens[i] = len(blob)
            # `buffers` stays referenced through the call; the engine
            # copies during parse, so the bytes can drop right after
            rc = self._lib.eg_load_buffers(h, bufs, lens, names, n)
        elif directory is not None:
            rc = self._lib.eg_load(
                h, directory.encode(), shard_idx, shard_num
            )
        elif files:
            arr = (ctypes.c_char_p * len(files))(*[f.encode() for f in files])
            rc = self._lib.eg_load_files(h, arr, len(files))
        else:
            self._lib.eg_destroy(h)
            raise ValueError("pass directory= or files=")
        if rc != 0:
            err = self._lib.eg_last_error().decode()
            self._lib.eg_destroy(h)
            raise RuntimeError(f"graph load failed: {err}")
        if p.get("delta"):
            # merge the delta chain over the fresh base: a failed merge
            # fails the whole connect (a graph silently missing its
            # updates is worse than no graph)
            joined = ";".join(p["delta"])
            if self._lib.eg_load_deltas(h, joined.encode()) != 0:
                err = self._lib.eg_last_error().decode()
                self._lib.eg_destroy(h)
                raise RuntimeError(f"delta load failed: {err}")
        self._handle = h

    @property
    def num_shards(self) -> int:
        return (
            self._lib.eg_remote_shards(self._h) if self.mode == "remote" else 1
        )

    @property
    def num_partitions(self) -> int:
        return (
            self._lib.eg_remote_partitions(self._h)
            if self.mode == "remote"
            else 1
        )

    def num_replicas(self, shard: int) -> int:
        """Current replica count of one shard's connection pool (remote
        mode) — observability for mid-run re-discovery."""
        if self.mode != "remote":
            return 1
        return self._lib.eg_remote_replica_count(self._h, shard)

    @property
    def has_placement(self) -> bool:
        """True when this remote client routes ids through a placement
        map fetched at init (kPlacement; see convert.py's degree-aware
        partitioner), False when it hash-routes — the compat fallback
        against old servers and hash-sharded data."""
        if self.mode != "remote":
            return False
        return self._lib.eg_remote_has_placement(self._h) == 1

    def shard_of(self, ids) -> np.ndarray:
        """Serving shard of each id through the client's ACTUAL routing
        (placement map when loaded, hash fallback otherwise). The
        edge-cut instrument (scripts/heat_dump.py --probe) measures
        locality with this instead of re-deriving the hash rule, so a
        placement-routed cluster is measured by the routing it uses."""
        if self.mode != "remote":
            raise ValueError(
                "shard_of() applies to mode='remote' graphs (a local "
                "graph has no shards to route to)"
            )
        arr = _ids(ids)
        out = np.empty(len(arr), dtype=np.int32)
        self._lib.eg_remote_route(
            self._h, _ptr(arr, _U64P), len(arr), _ptr(out, _I32P)
        )
        return out

    # ---- snapshot epochs (eg_epoch.h; DEPLOY.md "Rolling graph
    # refresh") ----
    def epoch(self) -> int:
        """Current snapshot epoch. Local: the epoch the embedded engine's
        snapshot was built at (0 = base load, N = after N deltas).
        Remote: the max epoch any shard has announced so far — learned
        passively from v4 reply stamps and registry heartbeats, so it
        can lag a fresh flip by one call/poll."""
        return int(self._lib.eg_graph_epoch(self._h))

    def shard_epoch(self, shard: int) -> int:
        """Last epoch announced by one shard (remote mode; 0 = never
        flipped or not yet observed)."""
        if self.mode != "remote":
            raise ValueError(
                "shard_epoch() applies to mode='remote' graphs (a local "
                "graph has exactly one epoch — use epoch())"
            )
        return int(self._lib.eg_remote_epoch(self._h, shard))

    @property
    def cache_gen(self) -> int:
        """The client's cache generation (remote mode; 0 for local):
        bumped once per observed epoch raise on any shard. Python-side
        caches (euler_tpu/serving/microbatch.py) key entries by this,
        exactly like the native feature/neighbor caches."""
        if self.mode != "remote":
            return 0
        return int(self._lib.eg_remote_cache_gen(self._h))

    def load_delta(self, path: str, shard: int | None = None) -> int:
        """Apply one delta file and flip to a fresh snapshot; returns the
        new epoch.

        Local graphs take the delta path directly (shard= must be None).
        Remote graphs ask ONE shard to merge a file on the SHARD's
        filesystem (shard= required) — roll through shards one at a time
        so the previous-epoch window covers in-flight multi-hop reads
        (DEPLOY.md 'Rolling graph refresh'). Raises on parse/validation/
        merge failure; the serving snapshot is untouched on failure."""
        if self.mode == "remote":
            if shard is None:
                raise ValueError(
                    "remote load_delta needs shard= (each shard merges "
                    "its own delta file; roll through shards in turn)"
                )
            ep = self._lib.eg_remote_load_delta(
                self._h, int(shard), path.encode()
            )
            if ep < 0:
                raise RuntimeError(self._lib.eg_last_error().decode())
            return int(ep)
        if shard is not None:
            raise ValueError(
                "shard= applies to mode='remote' graphs (a local graph "
                "merges the delta into its own embedded engine)"
            )
        # the native merge rebuilds base + the WHOLE chain (epoch = chain
        # length), so successive local flips re-send every delta applied
        # so far — the flipped snapshot stays bit-identical to a fresh
        # load of the same merged inputs
        chain = list(self._applied_deltas) + [path]
        joined = ";".join(chain)
        if self._lib.eg_load_deltas(self._h, joined.encode()) != 0:
            raise RuntimeError(self._lib.eg_last_error().decode())
        self._applied_deltas = chain
        return self.epoch()

    def _check_strict(self):
        """Raise the pending strict-mode failure, if any. With
        ``strict=True`` (remote graphs) a shard call that exhausted every
        transport retry must surface as an error instead of silently
        degrading its rows to defaults; the fixed-shape native query ABI
        returns void, so the failure crosses the C ABI through this poll
        (eg_remote_strict_error; counted in `rpc_errors`, FAULTS.md)."""
        if not self._strict:
            return
        buf = ctypes.create_string_buffer(512)
        if self._lib.eg_remote_strict_error(self._handle, buf, 512) > 0:
            raise RuntimeError(buf.value.decode())

    def close(self) -> None:
        # touch _handle, not _h: closing a lazy graph must not connect it
        self._closed = True
        if getattr(self, "_handle", None):
            self._lib.eg_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ---- introspection ----
    @property
    def num_nodes(self) -> int:
        return self._lib.eg_num_nodes(self._h)

    @property
    def num_edges(self) -> int:
        return self._lib.eg_num_edges(self._h)

    @property
    def node_type_num(self) -> int:
        return self._lib.eg_node_type_num(self._h)

    @property
    def edge_type_num(self) -> int:
        return self._lib.eg_edge_type_num(self._h)

    def feature_num(self, kind: int) -> int:
        return self._lib.eg_feature_num(self._h, kind)

    def type_weight_sums(self, edges: bool = False) -> np.ndarray:
        n = self.edge_type_num if edges else self.node_type_num
        out = np.zeros(n, dtype=np.float32)
        if n:
            self._lib.eg_type_weight_sums(
                self._h, 1 if edges else 0, _ptr(out, _F32P)
            )
        return out

    # ---- global sampling ----
    def sample_node(self, count: int, node_type: int = -1) -> np.ndarray:
        out = np.empty(count, dtype=np.uint64)
        self._lib.eg_sample_node(self._h, count, node_type, _ptr(out, _U64P))
        self._check_strict()
        return out.view(np.int64)

    def sample_edge(self, count: int, edge_type: int = -1):
        src = np.empty(count, dtype=np.uint64)
        dst = np.empty(count, dtype=np.uint64)
        t = np.empty(count, dtype=np.int32)
        self._lib.eg_sample_edge(
            self._h, count, edge_type, _ptr(src, _U64P), _ptr(dst, _U64P),
            _ptr(t, _I32P),
        )
        self._check_strict()
        return src.view(np.int64), dst.view(np.int64), t

    def sample_node_with_src(self, src_ids, count: int) -> np.ndarray:
        """[n, count] negatives drawn from each src's node-type sampler."""
        ids = _ids(src_ids)
        out = np.empty((len(ids), count), dtype=np.uint64)
        self._lib.eg_sample_node_with_src(
            self._h, _ptr(ids, _U64P), len(ids), count, _ptr(out, _U64P)
        )
        self._check_strict()
        return out.view(np.int64)

    def node_types(self, ids) -> np.ndarray:
        ids = _ids(ids)
        out = np.empty(len(ids), dtype=np.int32)
        self._lib.eg_get_node_type(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(out, _I32P)
        )
        self._check_strict()
        return out

    def node_weights(self, ids) -> np.ndarray:
        """Per-node sampling weights (0 for unknown ids). Works in both
        modes: local reads the embedded engine; remote scatters a
        kNodeWeight RPC per shard — so the device-graph exporter
        (build_node_sampler / build_typed_node_sampler) composes with
        sharded graphs. Raises when a shard cannot answer: a weight
        silently read as 0 would bias the exported sampler (unlike the
        query ops, which legitimately degrade to defaults)."""
        ids = _ids(ids)
        out = np.empty(len(ids), dtype=np.float32)
        rc = self._lib.eg_get_node_weight(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(out, _F32P)
        )
        if rc != 0:
            # consume any pending strict record first (same failure, the
            # shard-naming message) so it cannot go stale and fire on an
            # unrelated later call
            self._check_strict()
            raise RuntimeError(self._lib.eg_last_error().decode())
        self._check_strict()
        return out

    # ---- neighbor ops ----
    def sample_neighbor(
        self, ids, edge_types, count: int, default_node: int = -1
    ):
        """Returns (nbr_ids [n,count] i64, weights [n,count] f32,
        types [n,count] i32)."""
        ids = _ids(ids)
        et = _i32(edge_types)
        n = len(ids)
        out_i = np.empty((n, count), dtype=np.uint64)
        out_w = np.empty((n, count), dtype=np.float32)
        out_t = np.empty((n, count), dtype=np.int32)
        self._lib.eg_sample_neighbor(
            self._h, _ptr(ids, _U64P), n, _ptr(et, _I32P), len(et), count,
            _default_u64(default_node), _ptr(out_i, _U64P), _ptr(out_w, _F32P),
            _ptr(out_t, _I32P),
        )
        self._check_strict()
        return out_i.view(np.int64), out_w, out_t

    def sample_fanout(self, ids, edge_types, counts, default_node: int = -1):
        """Fused multi-hop sampling: one native call for all hops.

        edge_types: per-hop list of edge-type lists; counts: per-hop fanouts.
        Returns (ids_per_hop, weights_per_hop, types_per_hop); hop h arrays
        are flat with n * prod(counts[:h+1]) rows. ids_per_hop[0] is the
        (flattened) input.
        """
        ids = _ids(ids)
        nhops = len(counts)
        et_lists = [_i32(e) for e in edge_types]
        et_flat = (
            np.concatenate(et_lists) if et_lists else np.zeros(0, np.int32)
        )
        et_counts = _i32([len(e) for e in et_lists])
        counts_arr = _i32(counts)
        out_i, out_w, out_t = [], [], []
        m = len(ids)
        for h in range(nhops):
            m *= int(counts[h])
            out_i.append(np.empty(m, dtype=np.uint64))
            out_w.append(np.empty(m, dtype=np.float32))
            out_t.append(np.empty(m, dtype=np.int32))
        ids_ptrs = (_U64P * nhops)(*[_ptr(a, _U64P) for a in out_i])
        w_ptrs = (_F32P * nhops)(*[_ptr(a, _F32P) for a in out_w])
        t_ptrs = (_I32P * nhops)(*[_ptr(a, _I32P) for a in out_t])
        self._lib.eg_sample_fanout(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(et_flat, _I32P),
            _ptr(et_counts, _I32P), _ptr(counts_arr, _I32P), nhops,
            _default_u64(default_node), ids_ptrs, w_ptrs, t_ptrs,
        )
        self._check_strict()
        return (
            [ids.view(np.int64)] + [a.view(np.int64) for a in out_i],
            out_w,
            out_t,
        )

    def sample_fanout_async(
        self, ids, edge_types, counts, default_node: int = -1
    ):
        """Submit one whole multi-hop sample as an in-flight async op.

        Remote graphs only. The native hop chain runs entirely on the
        client's dispatcher pool (hop h+1's shard jobs are enqueued by
        hop h's completion continuation), so this returns immediately
        with an :class:`AsyncFanout` handle — ``poll()`` it, then
        ``take()`` for the same (ids_per_hop, weights, types) tuple
        ``sample_fanout`` returns. The handle owns every buffer the
        native op writes into; keep it referenced until the take.

        Returns None when the native async-op pool is full or the graph
        is not remote — callers fall back to the sync ``sample_fanout``
        (the depth pipeline in euler_tpu/parallel/prefetch.py does this
        transparently).
        """
        if self.mode != "remote":
            return None
        ids = _ids(ids)
        nhops = len(counts)
        et_lists = [_i32(e) for e in edge_types]
        et_flat = (
            np.concatenate(et_lists) if et_lists else np.zeros(0, np.int32)
        )
        et_counts = _i32([len(e) for e in et_lists])
        counts_arr = _i32(counts)
        out_i, out_w, out_t = [], [], []
        m = len(ids)
        for h in range(nhops):
            m *= int(counts[h])
            out_i.append(np.empty(m, dtype=np.uint64))
            out_w.append(np.empty(m, dtype=np.float32))
            out_t.append(np.empty(m, dtype=np.int32))
        ids_ptrs = (_U64P * nhops)(*[_ptr(a, _U64P) for a in out_i])
        w_ptrs = (_F32P * nhops)(*[_ptr(a, _F32P) for a in out_w])
        t_ptrs = (_I32P * nhops)(*[_ptr(a, _I32P) for a in out_t])
        slot = self._lib.eg_remote_sample_async(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(et_flat, _I32P),
            _ptr(et_counts, _I32P), _ptr(counts_arr, _I32P), nhops,
            _default_u64(default_node), ids_ptrs, w_ptrs, t_ptrs,
        )
        if slot < 0:
            return None
        return AsyncFanout(
            self, slot, ids, et_flat, et_counts, counts_arr,
            out_i, out_w, out_t,
        )

    def get_full_neighbor(self, ids, edge_types, sorted: bool = False):
        """Ragged full adjacency: (nbr_ids, weights, types, row_counts)."""
        ids = _ids(ids)
        et = _i32(edge_types)
        r = self._lib.eg_get_full_neighbor(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(et, _I32P), len(et),
            1 if sorted else 0,
        )
        try:
            nbr = self._fetch(r, 0, 0, np.uint64)
            w = self._fetch(r, 1, 0, np.float32)
            t = self._fetch(r, 2, 0, np.int32)
            counts = self._fetch(r, 2, 1, np.int32)
        finally:
            self._lib.eg_result_free(r)
        self._check_strict()
        return nbr.view(np.int64), w, t, counts

    def get_top_k_neighbor(self, ids, edge_types, k: int, default_node=-1):
        ids = _ids(ids)
        et = _i32(edge_types)
        n = len(ids)
        out_i = np.empty((n, k), dtype=np.uint64)
        out_w = np.empty((n, k), dtype=np.float32)
        out_t = np.empty((n, k), dtype=np.int32)
        self._lib.eg_get_top_k_neighbor(
            self._h, _ptr(ids, _U64P), n, _ptr(et, _I32P), len(et), k,
            _default_u64(default_node), _ptr(out_i, _U64P), _ptr(out_w, _F32P),
            _ptr(out_t, _I32P),
        )
        self._check_strict()
        return out_i.view(np.int64), out_w, out_t

    # ---- walks ----
    def random_walk(
        self, ids, edge_types, walk_len: int = None, p: float = 1.0,
        q: float = 1.0, default_node: int = -1,
    ) -> np.ndarray:
        """[n, walk_len+1] int64 walks; column 0 is the start node.

        edge_types is either a flat list (same types every step; walk_len
        required) or a per-step list of lists defining a heterogeneous
        metapath (walk_len inferred), e.g. [[0], [1], [0]].
        """
        ids = _ids(ids)
        if len(edge_types) > 0 and isinstance(
            edge_types[0], (list, tuple, np.ndarray)
        ):
            steps = [_i32(e) for e in edge_types]
            if walk_len is None:
                walk_len = len(steps)
            elif walk_len != len(steps):
                raise ValueError("walk_len != len(edge_types metapath)")
        else:
            if walk_len is None:
                raise ValueError("walk_len required with flat edge_types")
            steps = [_i32(edge_types)] * walk_len
        et_flat = (
            np.concatenate(steps) if steps else np.zeros(0, np.int32)
        )
        et_counts = _i32([len(s) for s in steps])
        out = np.empty((len(ids), walk_len + 1), dtype=np.uint64)
        self._lib.eg_random_walk(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(et_flat, _I32P),
            _ptr(et_counts, _I32P), walk_len, p, q,
            _default_u64(default_node), _ptr(out, _U64P),
        )
        self._check_strict()
        return out.view(np.int64)

    # ---- features ----
    def get_dense_feature(self, ids, fids, dims) -> np.ndarray:
        """[n, sum(dims)] float32, zero-padded per slot."""
        ids = _ids(ids)
        fids = _i32(fids)
        dims = _i32(dims)
        out = np.empty((len(ids), int(dims.sum())), dtype=np.float32)
        self._lib.eg_get_dense_feature(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(fids, _I32P),
            _ptr(dims, _I32P), len(fids), _ptr(out, _F32P),
        )
        self._check_strict()
        return out

    def get_edge_dense_feature(self, src, dst, types, fids, dims) -> np.ndarray:
        src = _ids(src)
        dst = _ids(dst)
        types = _i32(types)
        fids = _i32(fids)
        dims = _i32(dims)
        out = np.empty((len(src), int(dims.sum())), dtype=np.float32)
        self._lib.eg_get_edge_dense_feature(
            self._h, _ptr(src, _U64P), _ptr(dst, _U64P), _ptr(types, _I32P),
            len(src), _ptr(fids, _I32P), _ptr(dims, _I32P), len(fids),
            _ptr(out, _F32P),
        )
        self._check_strict()
        return out

    def get_sparse_feature(self, ids, fids):
        """Per slot: (values i64 concat, row_counts i32[n])."""
        ids = _ids(ids)
        fids = _i32(fids)
        r = self._lib.eg_get_sparse_feature(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(fids, _I32P), len(fids)
        )
        return self._drain_sparse(r, len(fids))

    def get_edge_sparse_feature(self, src, dst, types, fids):
        src = _ids(src)
        dst = _ids(dst)
        types = _i32(types)
        fids = _i32(fids)
        r = self._lib.eg_get_edge_sparse_feature(
            self._h, _ptr(src, _U64P), _ptr(dst, _U64P), _ptr(types, _I32P),
            len(src), _ptr(fids, _I32P), len(fids),
        )
        return self._drain_sparse(r, len(fids))

    def get_binary_feature(self, ids, fids):
        """Per slot: list of bytes, one per row."""
        ids = _ids(ids)
        fids = _i32(fids)
        r = self._lib.eg_get_binary_feature(
            self._h, _ptr(ids, _U64P), len(ids), _ptr(fids, _I32P), len(fids)
        )
        return self._drain_binary(r, len(fids))

    def get_edge_binary_feature(self, src, dst, types, fids):
        src = _ids(src)
        dst = _ids(dst)
        types = _i32(types)
        fids = _i32(fids)
        r = self._lib.eg_get_edge_binary_feature(
            self._h, _ptr(src, _U64P), _ptr(dst, _U64P), _ptr(types, _I32P),
            len(src), _ptr(fids, _I32P), len(fids),
        )
        return self._drain_binary(r, len(fids))

    # ---- result plumbing ----
    def _fetch(self, r, kind: int, slot: int, dtype) -> np.ndarray:
        n = self._lib.eg_result_size(r, kind, slot)
        out = np.empty(max(n, 0), dtype=dtype)
        if n > 0:
            self._lib.eg_result_copy(
                r, kind, slot, out.ctypes.data_as(ctypes.c_void_p)
            )
        return out

    def _drain_sparse(self, r, nslots: int):
        try:
            out = []
            for k in range(nslots):
                vals = self._fetch(r, 0, k, np.uint64).view(np.int64)
                counts = self._fetch(r, 2, k, np.int32)
                out.append((vals, counts))
        finally:
            self._lib.eg_result_free(r)
        self._check_strict()
        return out

    def _drain_binary(self, r, nslots: int):
        try:
            out = []
            for k in range(nslots):
                n = self._lib.eg_result_size(r, 3, k)
                buf = ctypes.create_string_buffer(max(int(n), 1))
                if n > 0:
                    self._lib.eg_result_copy(r, 3, k, buf)
                data = buf.raw[: int(n)]
                sizes = self._fetch(r, 2, k, np.int32)
                rows = []
                off = 0
                for s in sizes:
                    rows.append(data[off : off + int(s)])
                    off += int(s)
                out.append(rows)
        finally:
            self._lib.eg_result_free(r)
        self._check_strict()
        return out


class AsyncFanout:
    """Handle of one in-flight async multi-hop sample
    (:meth:`Graph.sample_fanout_async`).

    Owns every buffer the native op writes into (the request arrays are
    copied native-side, but the per-hop outputs are written in place),
    so the handle must stay referenced until :meth:`take` returns. One
    take per handle; the native slot recycles on take.
    """

    def __init__(self, graph, slot, ids, et_flat, et_counts, counts_arr,
                 out_i, out_w, out_t):
        self._graph = graph
        self._slot = slot
        self._ids = ids
        # pinned until the take: the native op borrows these buffers
        self._pin = (et_flat, et_counts, counts_arr)
        self._out_i = out_i
        self._out_w = out_w
        self._out_t = out_t
        self._taken = False

    def poll(self) -> bool:
        """True when the op has completed (take will not block)."""
        if self._taken:
            return True
        return self._graph._lib.eg_remote_async_poll(
            self._graph._h, self._slot) == 1

    def take(self):
        """Block until the op completes, recycle its native slot, and
        return the same (ids_per_hop, weights_per_hop, types_per_hop)
        tuple ``sample_fanout`` returns. Raises under ``strict=`` when
        a shard failed inside the op — identical semantics to the sync
        path, just surfaced at the take instead of the call."""
        if self._taken:
            raise RuntimeError("AsyncFanout.take() called twice")
        rc = self._graph._lib.eg_remote_async_take(
            self._graph._h, self._slot)
        self._taken = True
        if rc != 0:
            raise RuntimeError(
                "eg_remote_async_take failed for slot %d" % self._slot)
        self._graph._check_strict()
        return (
            [self._ids.view(np.int64)]
            + [a.view(np.int64) for a in self._out_i],
            self._out_w,
            self._out_t,
        )

    def __del__(self):
        # an abandoned handle must not leak its native slot (and the op
        # may still be writing into our buffers): block for completion
        try:
            if not self._taken:
                self._graph._lib.eg_remote_async_take(
                    self._graph._h, self._slot)
                self._taken = True
        except Exception:
            pass
