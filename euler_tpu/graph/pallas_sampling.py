"""Fused weighted-neighbor draw as a Pallas TPU kernel.

The XLA device-sampling path (device.py sample_neighbor) lowers to a
chain of ~6 small ops per hop (row gathers, RNG, compare-sum, pick) and
is latency-bound at GNN batch dims: measured on a v5e chip, the two-hop
PPI fanout (512x10 + 5120x10 draws) costs 0.72 ms/step of the 1.27 ms
train step while the MXU math is ~free (see PERF.md step anatomy). This
kernel fuses the whole per-hop draw into ONE program: the source nodes'
slab rows stream HBM->VMEM through a double-buffered row-DMA pipeline,
the on-core PRNG draws the uniforms, and the compare-sum pick happens on
the rows while the next batch of rows is in flight. Same fanout measured
at 0.24 ms/step — 3x over the XLA chain.

Layout: ``pack_adjacency`` stores each node as 2K adjacent rows of one
``[2KN, 128]`` array — its K neighbor-id rows then its K
cumulative-weight rows (bitcast to int32), K = ceil(W / 128) — so ONE
2K-row DMA fetches the whole node and every row stays aligned to the
(1, 128) HBM tiling that scattered-row slices require (a ``[N, 2K*128]``
array would tile (8, 128) and break scattered-row DMA). Pad slots hold
cum=1.0, which ``idx = #(u >= cum)`` can never select while u < 1 (the
last real slot is pinned to 1.0 at build time), and the VPU compares
each 128-lane row in one op anyway, so the pad is free compute-wise.
Graphs whose slab width exceeds MAX_W = 512 keep the XLA path (cap with
``build_adjacency(..., max_degree=512)`` to opt in — the same
truncate-to-heaviest semantics the reference applies to heavy-tailed
graphs).

Draw semantics are identical to device.sample_neighbor — first slot
whose cumulative weight exceeds u, default node for unsampleable rows
(baked into the slab: their neighbor lanes are default-filled at pack
time, so the kernel needs no mask gather; reference
CompactNode::SampleNeighbor, euler/core/compact_node.cc:42-101) — but
from the core PRNG's stream rather than threefry, so
sequences differ for the same seed while distributions match
(statistically pinned against the host engine in
tests/test_pallas_sampling.py, TPU-only).

SPMD note: pallas_call does not partition under pjit, so the kernel
auto-activates only on a single-device TPU (``available()``); meshes
keep the XLA path. Force on/off with EULER_TPU_PALLAS_SAMPLING=1/0.

Chained two-hop variant: ``sample_fanout2`` fuses BOTH fanout hops into
one program — each stage of root rows draws its hop-1 picks, async-
copies them VMEM->SMEM so they can address HBM, and issues the
data-dependent hop-2 row DMAs, which complete behind the NEXT stage's
hop-1 compute (hop-2 processing runs one stage behind hop-1). This
removes the second kernel dispatch and the hop-1 -> HBM -> hop-2
round-trip of the per-hop path. Folding the FEATURE gather in as well
was evaluated and rejected: a per-row DMA gather of the [B*f1*f2]-row
feature matrix costs ~40 ns of issue per row (~2 ms at PPI dims) vs
~0.49 ms for XLA's gather — see PERF.md.

CPU validation: EULER_TPU_PALLAS_INTERPRET=1 routes every pallas_call
through pallas' TPU interpret mode (emulated DMAs/semaphores on CPU;
=races additionally turns on its DMA race detector). The emulated core
PRNG returns zeros, so interpret-mode tests inject precomputed uniforms
(the ``u``/``u1``/``u2`` arguments) — which also makes them EXACT:
identical uniforms must reproduce the XLA path's picks bit-for-bit
(tests/test_pallas_interpret.py). Hardware runs never inject.
"""

from __future__ import annotations

import functools
import os

import numpy as np

LANES = 128
MAX_COUNT = 128  # larger per-node draw counts keep the XLA path: the
# count loop is unrolled in the kernel and the [M, count] output lives
# whole in VMEM, both of which scale linearly with count; every model
# draw (fanouts, walks, negatives) is far below this
MAX_OUT_ELEMS = 1 << 20  # [M, count] output cap (4 MB VMEM): bigger
# draws keep the XLA path — see eligible()
MAX_M = 1 << 15  # source-node cap: ids ride scalar prefetch (SMEM, far
# smaller than VMEM — 128 KB of ids at this cap), so M needs its own
# bound even when M*count fits the output budget (e.g. count=1 walks)
MAX_W = 4 * LANES  # widest slab the kernel handles (K = ceil(W/128)
# row-pairs per node, compare-sum unrolled over K); wider keeps XLA
MAX_PACKED_BYTES = 2 << 30  # pack_adjacency opt-out: the packed slab is
# always a K*128-lane multiple (1 KB/node per K), a (K*128)/W inflation
# over nbr+cum that it is ADDED to; beyond this budget the kernel is not
# worth the HBM
_MAX_R = 512  # rows per pipeline stage (2 DMA semaphores regardless)


def _backend_ok(require_single_device: bool) -> bool:
    try:
        import jax

        if jax.default_backend() != "tpu":
            return False
        if require_single_device and len(jax.devices()) != 1:
            return False
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except Exception:  # pragma: no cover - import/backend probing
        return False
    return True


def _force_flag():
    """Strictly parsed EULER_TPU_PALLAS_SAMPLING: True ("1"/"true"),
    False ("0"/"false"), or None (unset/empty). Anything else —
    "off", "no", "False " with a space — warns once and counts as
    unset rather than silently force-enabling the kernel."""
    raw = os.environ.get("EULER_TPU_PALLAS_SAMPLING")
    if raw is None or raw == "":
        return None
    v = raw.strip().lower()
    if v in ("1", "true"):
        return True
    if v in ("0", "false"):
        return False
    import warnings

    warnings.warn(
        f"EULER_TPU_PALLAS_SAMPLING={raw!r} is not one of 0/1/false/true"
        " (case-insensitive); ignoring it",
        stacklevel=3,
    )
    return None


def available() -> bool:
    """True when the kernel path should auto-activate: TPU backend, one
    device (see SPMD note above), imports work, not overridden by env.
    EULER_TPU_PALLAS_SAMPLING=1 skips the single-device heuristic —
    but only once a kernel mesh is registered
    (device.set_kernel_mesh, which run_loop calls on the
    --device_sampling path): on a multi-device backend with NO mesh
    registered the flag warns and still returns False, because the
    direct (non-shard_map) route would run an unsharded pallas_call
    under pjit — silently wrong per-shard draws. Experts composing
    their own shard_map call pallas_sampling.sample_neighbor directly,
    which never consults this gate. The flag still requires a TPU
    backend with pallas importable — the kernel's primitives exist
    nowhere else; =0 forces the XLA path."""
    force = _force_flag()
    if force is not None:
        if not force:
            return False
        ok = _backend_ok(require_single_device=False)
        if ok:
            import jax

            from euler_tpu.graph import device as _dg

            if len(jax.devices()) > 1 and _dg.kernel_mesh() is None:
                import warnings

                warnings.warn(
                    "EULER_TPU_PALLAS_SAMPLING=1 with "
                    f"{len(jax.devices())} devices but no kernel mesh:"
                    " pallas_call does not partition under pjit, so the"
                    " force flag is ignored (XLA path) — register the"
                    " mesh with device.set_kernel_mesh, as run_loop's"
                    " --device_sampling path does, to wire the kernel"
                    " per-shard",
                    stacklevel=2,
                )
                return False
        return ok
    return _backend_ok(require_single_device=True)


def sharded_available() -> bool:
    """True when the kernel can run PER-SHARD inside shard_map on this
    backend: TPU with pallas importable, any device count. This is the
    mesh-path activation check (device.set_kernel_mesh wires it);
    available() stays the single-device auto-activation check —
    pallas_call does not partition under plain pjit."""
    if _force_flag() is False:
        return False
    return _backend_ok(require_single_device=False)


def interpret_params():
    """False (compile for real) unless EULER_TPU_PALLAS_INTERPRET opts
    this process into pallas' TPU interpret mode: "1" emulates the
    kernels on CPU, "races" also enables the emulator's DMA race
    detector. Test-only — interpretation is orders of magnitude slower
    than both the compiled kernel and the XLA chain, so nothing
    auto-activates it; available() is unaffected (the interpret knob
    changes how an explicit kernel call executes, not routing)."""
    raw = os.environ.get("EULER_TPU_PALLAS_INTERPRET")
    if raw not in ("1", "races"):
        return False
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.InterpretParams(detect_races=(raw == "races"))


def eligible(m: int, count: int) -> bool:
    """True when a draw of ``m`` source nodes x ``count`` fits the
    kernel's on-core budgets (ids in scalar prefetch / SMEM, [M, count]
    output whole in VMEM); callers fall back to the XLA chain
    otherwise."""
    return (
        count <= MAX_COUNT
        and m <= MAX_M
        and m * count <= MAX_OUT_ELEMS
    )


def pack_adjacency(adj: dict, max_bytes: int = MAX_PACKED_BYTES):
    """[2KN, 128] int32, K = ceil(W/128): node i occupies rows
    2K*i..2K*i+2K-1 — its K neighbor-id rows (pad: default id) then its
    K cumulative-weight rows bitcast to int32 (pad: 1.0). Returns None
    (caller keeps the XLA path) when the slab is wider than MAX_W, or
    when the packed copy — which is KEPT ALONGSIDE nbr/cum (the fallback
    paths still need them) at a fixed K KB/node regardless of real
    degree — would exceed ``max_bytes`` of HBM."""
    nbr = np.asarray(adj["nbr"])
    cum = np.asarray(adj["cum"])
    n_rows, w = nbr.shape
    k = (w + LANES - 1) // LANES
    if w > MAX_W or 2 * k * n_rows * LANES * 4 > max_bytes:
        return None
    nbr_p = np.full((n_rows, k * LANES), n_rows - 1, np.int32)
    nbr_p[:, :w] = nbr
    # unsampleable rows (zero total weight — their cum is a neutral
    # all-1.0, see build_adjacency) draw the DEFAULT node on the host
    # path via the `sampleable` mask; the packed slab is kernel-only, so
    # bake that in by default-filling their neighbor lanes — the kernel
    # then needs no separate mask gather at draw time
    sampleable = np.asarray(
        adj.get("sampleable", np.ones(n_rows, bool))
    ).astype(bool)
    nbr_p[~sampleable] = n_rows - 1
    cum_p = np.ones((n_rows, k * LANES), np.float32)
    cum_p[:, :w] = cum
    packed = np.empty((2 * k * n_rows, LANES), np.int32)
    # node-major: [nbr_0..nbr_{K-1}, cum_0..cum_{K-1}] per node
    packed.reshape(n_rows, 2 * k, LANES)[:, :k] = nbr_p.reshape(
        n_rows, k, LANES
    )
    packed.reshape(n_rows, 2 * k, LANES)[:, k:] = cum_p.view(
        np.int32
    ).reshape(n_rows, k, LANES)
    return packed


def _prng_uniform(rows):
    """[rows, 1] 24-bit mantissa-exact uniform in [0, 1) from the core
    PRNG (seeded once per kernel via pltpu.prng_seed)."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    bits = pltpu.bitcast(pltpu.prng_random_bits((rows, 1)), jnp.uint32)
    return (bits >> 8).astype(jnp.int32).astype(jnp.float32) * (
        1.0 / (1 << 24)
    )


def _stage_draw(slab_block, rows, k, count, next_u):
    """[rows, count] int32 picks from one stage's slab rows (VMEM value,
    [2k*rows, 128], node-major K nbr rows then K cum rows per node).
    ``next_u(c)`` yields the [rows, 1] uniform for draw column c — the
    core PRNG on hardware, an injected-uniform read under interpret
    mode. Shared by the single-hop kernel and both hops of the chained
    kernel, so the draw semantics cannot drift between them."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    both = slab_block.reshape(rows, 2 * k, LANES)
    nbrs = [both[:, j, :] for j in range(k)]               # k x [rows, 128]
    cums = [
        pltpu.bitcast(both[:, k + j, :], jnp.float32) for j in range(k)
    ]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    cols = []
    for c in range(count):
        u = next_u(c)
        # rank over the whole (sorted) K*128-lane cumulative row
        idx = jnp.sum((u >= cums[0]).astype(jnp.int32), axis=1,
                      keepdims=True)
        for j in range(1, k):
            idx = idx + jnp.sum(
                (u >= cums[j]).astype(jnp.int32), axis=1, keepdims=True
            )
        idx = jnp.minimum(idx, k * LANES - 1)
        # select lane idx from the concatenated nbr rows: exactly one
        # register's local lane matches (out-of-register locals match
        # no lane and contribute 0)
        val = jnp.sum(
            jnp.where(lanes == idx, nbrs[0], 0), axis=1, keepdims=True
        )
        for j in range(1, k):
            val = val + jnp.sum(
                jnp.where(lanes == idx - j * LANES, nbrs[j], 0),
                axis=1, keepdims=True,
            )
        cols.append(val)
    # unsampleable/default rows already hold the default id in every
    # neighbor lane (pack_adjacency), so the draw needs no mask here
    return jnp.concatenate(cols, axis=1)


def _kernel(ids_ref, seed_ref, pk_hbm, *rest,
            rows, count, num_iters, k, with_u):
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if with_u:
        u_ref, out_ref, pk_s, sem = rest
    else:
        u_ref, (out_ref, pk_s, sem) = None, rest

    # both words seed the core PRNG: 62 bits of caller entropy (a lone
    # int31 word collides across long runs — ADVICE r2)
    pltpu.prng_seed(seed_ref[0], seed_ref[1])

    def dma(slot, r, row):
        # one copy moves the node's whole 2K-row block (K nbr rows + K
        # cum rows); every copy is the same size, so a single per-slot
        # semaphore counts them all
        return pltpu.make_async_copy(
            pk_hbm.at[pl.ds(row * 2 * k, 2 * k), :],
            pk_s.at[slot, pl.ds(2 * k * r, 2 * k), :],
            sem.at[slot],
        )

    def issue(slot, it):
        base = it * rows
        for r in range(rows):
            dma(slot, r, ids_ref[base + r]).start()

    def wait(slot, it):
        base = it * rows
        for r in range(rows):
            dma(slot, r, ids_ref[base + r]).wait()

    issue(0, 0)

    def body(it, _):
        slot = jax.lax.rem(it, 2)

        @pl.when(it + 1 < num_iters)
        def _():
            issue(jax.lax.rem(it + 1, 2), it + 1)

        wait(slot, it)
        if with_u:
            def next_u(c):
                return u_ref[pl.ds(it * rows, rows), c:c + 1]
        else:
            def next_u(c):
                return _prng_uniform(rows)
        out_ref[pl.ds(it * rows, rows), :] = _stage_draw(
            pk_s[slot], rows, k, count, next_u
        )
        return 0

    jax.lax.fori_loop(0, num_iters, body, 0)


def _two_word_seed(seed):
    import jax.numpy as jnp

    seed = jnp.atleast_1d(jnp.asarray(seed)).astype(jnp.int32)
    if seed.shape[0] < 2:
        seed = jnp.concatenate([seed, jnp.zeros(1, jnp.int32)])
    return seed[:2]


def sample_neighbor(adj: dict, nodes, seed, count: int, u=None):
    """[len(nodes), count] int32 weighted draws via the fused kernel.

    ``adj`` must carry the "packed" slab (models add it through
    base.Model.add_sampling_consts when available()); ``seed`` is one or
    two traced int32 words (two preferred — both are fed to the core
    PRNG; callers with a PRNG key derive them via jax.random.randint).
    A scalar/1-word seed is zero-extended.

    ``u`` (test-only, [len(nodes), count] float32 in [0, 1)): injected
    uniforms replacing the core PRNG's — interpret-mode tests use them
    to pin the kernel's picks EXACTLY to the XLA chain's semantics,
    since the emulated PRNG returns zeros."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    packed = adj["packed"]
    n_rows = adj["nbr"].shape[0]
    k = packed.shape[0] // (2 * n_rows)  # ceil(W / 128) row-pairs/node
    nodes = jnp.asarray(nodes, jnp.int32)
    shape = nodes.shape
    flat = nodes.reshape(-1)
    m = flat.shape[0]
    if m == 0:  # the kernel's prologue DMA needs >= 1 real row
        return jnp.zeros((*shape, count), jnp.int32)
    # ids become raw DMA offsets in the kernel — clamp so unknown ids
    # (negative or past the slab) land on the DEFAULT row (n_rows-1)
    # instead of reading out of bounds; device.sample_neighbor's XLA
    # path applies the identical mapping, keeping build_adjacency's
    # "unknown ids sample the default node" contract on both paths
    flat = jnp.where(flat < 0, n_rows - 1, jnp.minimum(flat, n_rows - 1))
    # power-of-two stage size (sublane-aligned dynamic slices), floored
    # at 8, scaled down by K to keep the 2-slot scratch K-independent
    max_r = max(8, 1 << ((_MAX_R // k).bit_length() - 1))
    rows = max_r if m >= max_r else max(8, 1 << (m - 1).bit_length())
    mp = ((m + rows - 1) // rows) * rows
    ids = jnp.pad(flat, (0, mp - m))
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),           # packed slab (HBM)
    ]
    args = [ids, _two_word_seed(seed), packed]
    if u is not None:
        u = jnp.pad(
            jnp.asarray(u, jnp.float32).reshape(m, count),
            ((0, mp - m), (0, 0)),
        )
        in_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
        args.append(u)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # ids, seed
        grid=(1,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, 2 * k * rows, LANES), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, rows=rows, count=count, num_iters=mp // rows, k=k,
            with_u=u is not None,
        ),
        out_shape=jax.ShapeDtypeStruct((mp, count), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret_params(),
    )(*args)
    return out[:m].reshape(*shape, count)


def _shard_map():
    """jax's shard_map across the 0.7 rename (check_rep -> check_vma);
    callers pass check_rep and get whichever kwarg this jax expects."""
    try:
        from jax import shard_map as _sm  # jax >= 0.7 (check_vma kwarg)

        def shard_map(f, **kw):
            kw["check_vma"] = kw.pop("check_rep")
            return _sm(f, **kw)

        return shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

        return shard_map


def eligible2(m: int, f1: int, f2: int, k1: int = 1, k2: int = 1) -> bool:
    """True when a chained two-hop fanout of ``m`` roots x f1 x f2 over
    K1/K2-row-pair slabs fits the fused kernel's budgets: root ids in
    scalar prefetch (SMEM), both hop outputs whole in VMEM, and the
    hop-2 scratch within its ~3 MB budget even at the MINIMUM stage
    size of 8 rows (k2 * f1 * 8 <= 1536 — without this check a wide
    hop-2 slab x large f1 would pass and then fail VMEM allocation at
    compile time instead of falling back). Callers fall back to the
    per-hop path (which may still use the single-hop kernel)
    otherwise."""
    return (
        f1 <= MAX_COUNT
        and f2 <= MAX_COUNT
        and m <= MAX_M
        and m * f1 <= MAX_OUT_ELEMS
        and m * f1 * f2 <= MAX_OUT_ELEMS
        and k2 * f1 * 8 <= 1536
        and k1 <= MAX_W // LANES
        and k2 <= MAX_W // LANES
    )


def _fanout2_kernel(ids_ref, seed_ref, pk1_hbm, pk2_hbm, *rest,
                    rows, f1, f2, num_iters, k1, k2, with_u):
    """Both fanout hops in one program. Per stage of ``rows`` roots:
    hop-1 slab rows stream in (double-buffered, like _kernel), the f1
    picks are drawn and written to out1, then async-copied VMEM->SMEM so
    they can address HBM, and the rows*f1 data-dependent hop-2 row DMAs
    are issued. Hop-2 processing runs ONE STAGE BEHIND hop-1: stage
    it's hop-2 rows arrive while stage it+1's hop-1 draw computes, so
    the dependent DMA latency hides behind compute instead of
    serializing after it."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if with_u:
        u1_ref, u2_ref, out1_ref, out2_ref, pk1_s, pk2_s, picks_v, \
            picks_s, sem1, sem2, semp = rest
    else:
        u1_ref = u2_ref = None
        (out1_ref, out2_ref, pk1_s, pk2_s, picks_v, picks_s, sem1, sem2,
         semp) = rest

    pltpu.prng_seed(seed_ref[0], seed_ref[1])
    rows2 = rows * f1

    def dma1(slot, r, row):
        return pltpu.make_async_copy(
            pk1_hbm.at[pl.ds(row * 2 * k1, 2 * k1), :],
            pk1_s.at[slot, pl.ds(2 * k1 * r, 2 * k1), :],
            sem1.at[slot],
        )

    def dma2(slot, j, row):
        return pltpu.make_async_copy(
            pk2_hbm.at[pl.ds(row * 2 * k2, 2 * k2), :],
            pk2_s.at[slot, pl.ds(2 * k2 * j, 2 * k2), :],
            sem2.at[slot],
        )

    def issue1(slot, it):
        base = it * rows
        for r in range(rows):
            dma1(slot, r, ids_ref[base + r]).start()

    def wait1(slot, it):
        base = it * rows
        for r in range(rows):
            dma1(slot, r, ids_ref[base + r]).wait()

    def issue2(slot):
        # picks_s holds THIS stage's picks (copied just before): they
        # are in-slab ids (< pk2's row count — sample_fanout2 asserts
        # both slabs share it), so no clamp is needed for the DMA
        for j in range(rows2):
            r, c = divmod(j, f1)
            dma2(slot, j, picks_s[r, c]).start()

    def wait2(slot):
        # semaphore waits count BYTES, not descriptors: picks_s has
        # moved on to the next stage by now, so re-deriving the issued
        # src rows is impossible — wait on same-shaped descriptors
        # (src row 0) instead, which decrements the same per-slot
        # semaphore by the same per-copy size
        for j in range(rows2):
            dma2(slot, j, 0).wait()

    def next_u1(it):
        if with_u:
            return lambda c: u1_ref[pl.ds(it * rows, rows), c:c + 1]
        return lambda c: _prng_uniform(rows)

    def next_u2(stage):
        if with_u:
            return lambda c: u2_ref[pl.ds(stage * rows2, rows2), c:c + 1]
        return lambda c: _prng_uniform(rows2)

    def process_hop2(slot, stage):
        wait2(slot)
        out2_ref[pl.ds(stage * rows2, rows2), :] = _stage_draw(
            pk2_s[slot], rows2, k2, f2, next_u2(stage)
        )

    issue1(0, 0)

    def body(it, _):
        slot = jax.lax.rem(it, 2)

        @pl.when(it + 1 < num_iters)
        def _():
            issue1(jax.lax.rem(it + 1, 2), it + 1)

        wait1(slot, it)
        picks = _stage_draw(pk1_s[slot], rows, k1, f1, next_u1(it))
        out1_ref[pl.ds(it * rows, rows), :] = picks
        # VMEM->SMEM so the picks can address HBM. Mosaic requires DMA
        # slices lane-aligned to the (·, 128) tiling, so the copy source
        # is a full-width scratch (picks lane-padded with zeros), not an
        # f1-wide slice of out1 — hardware rejects the narrow slice
        # (interpret mode does not model the tiling constraint).
        picks_v[:, :] = jnp.concatenate(
            [picks, jnp.zeros((rows, LANES - f1), jnp.int32)], axis=1
        ) if f1 < LANES else picks
        cp = pltpu.make_async_copy(picks_v, picks_s, semp)
        cp.start()
        cp.wait()
        issue2(slot)

        # NOTE on uniform ORDER vs the per-hop path: with the core PRNG
        # (hardware), hop-2 uniforms for stage it-1 are drawn after
        # hop-1 uniforms for stages <= it — a different position in the
        # one PRNG stream than two sequential kernels would use. That
        # changes sequences, not distributions (same independent
        # stream), exactly like the kernel-vs-threefry difference the
        # module docstring records. Injected-uniform runs are
        # position-exact by construction.
        @pl.when(it > 0)
        def _():
            process_hop2(jax.lax.rem(it + 1, 2), it - 1)

        return 0

    jax.lax.fori_loop(0, num_iters, body, 0)
    process_hop2(
        jax.lax.rem(num_iters - 1, 2), num_iters - 1
    )


def sample_fanout2(adj1: dict, adj2: dict, roots, seed, f1: int, f2: int,
                   u1=None, u2=None):
    """(hop1 [m, f1], hop2 [m*f1, f2]) int32 draws with BOTH hops fused
    into one kernel program (see _fanout2_kernel). ``adj1``/``adj2`` may
    be the same dict (homogeneous fanout) or differ (metapath); both
    must carry "packed" slabs over the same id space. ``u1``/``u2`` are
    the test-only injected uniforms (see sample_neighbor).

    Reference semantics: two chained CompactNode::SampleNeighbor rounds
    (euler/core/compact_node.cc:42-101) — identical per-hop draw
    distribution to device.sample_fanout's per-hop path."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_rows = adj1["nbr"].shape[0]
    if adj2["nbr"].shape[0] != n_rows:
        raise ValueError(
            "sample_fanout2 needs both adjacencies over one id space: "
            f"{n_rows} vs {adj2['nbr'].shape[0]} rows"
        )
    pk1, pk2 = adj1["packed"], adj2["packed"]
    k1 = pk1.shape[0] // (2 * n_rows)
    k2 = pk2.shape[0] // (2 * n_rows)
    roots = jnp.asarray(roots, jnp.int32).reshape(-1)
    m = roots.shape[0]
    if m == 0:
        return (
            jnp.zeros((0, f1), jnp.int32),
            jnp.zeros((0, f2), jnp.int32),
        )
    # same unknown-id contract as sample_neighbor: clamp to the default
    # row rather than DMA out of bounds
    roots = jnp.where(
        roots < 0, n_rows - 1, jnp.minimum(roots, n_rows - 1)
    )
    # stage size: power-of-two (sublane-aligned out1 slices), sized so
    # the hop-2 scratch (2 slots x 2*k2*R*f1 rows) stays ~<= 3 MB and
    # the full-lane-width pick buffers (R x 128 ids in VMEM scratch and
    # SMEM — full width because the VMEM->SMEM DMA must be 128-lane
    # aligned) stay <= 8 KB, i.e. R <= 16
    r_max = min(
        _MAX_R // k1,
        max(1, 1536 // (k2 * f1)),
        16,
    )
    r_max = max(8, 1 << (r_max.bit_length() - 1))
    rows = r_max if m >= r_max else max(8, 1 << (m - 1).bit_length())
    mp = ((m + rows - 1) // rows) * rows
    ids = jnp.pad(roots, (0, mp - m), constant_values=n_rows - 1)
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),           # hop-1 slab (HBM)
        pl.BlockSpec(memory_space=pl.ANY),           # hop-2 slab (HBM)
    ]
    args = [ids, _two_word_seed(seed), pk1, pk2]
    with_u = u1 is not None
    if (u1 is None) != (u2 is None):
        raise ValueError("inject both u1 and u2 or neither")
    if with_u:
        u1 = jnp.pad(
            jnp.asarray(u1, jnp.float32).reshape(m, f1),
            ((0, mp - m), (0, 0)),
        )
        u2 = jnp.pad(
            jnp.asarray(u2, jnp.float32).reshape(m * f1, f2),
            ((0, (mp - m) * f1), (0, 0)),
        )
        in_specs += [
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ]
        args += [u1, u2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # root ids, seed
        grid=(1,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, 2 * k1 * rows, LANES), jnp.int32),
            pltpu.VMEM((2, 2 * k2 * rows * f1, LANES), jnp.int32),
            pltpu.VMEM((rows, LANES), jnp.int32),
            pltpu.SMEM((rows, LANES), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out1, out2 = pl.pallas_call(
        functools.partial(
            _fanout2_kernel, rows=rows, f1=f1, f2=f2,
            num_iters=mp // rows, k1=k1, k2=k2, with_u=with_u,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((mp, f1), jnp.int32),
            jax.ShapeDtypeStruct((mp * f1, f2), jnp.int32),
        ],
        grid_spec=grid_spec,
        interpret=interpret_params(),
    )(*args)
    return out1[:m], out2[:m * f1]


def sample_fanout2_sharded(
    adj1: dict, adj2: dict, roots, seed, f1: int, f2: int, mesh,
    axis: str = "data", draw_fn=None,
):
    """sample_fanout2 under SPMD: shard_map over ``mesh``'s ``axis``
    with roots batch-sharded, both (packed) adjacencies replicated, and
    per-shard seeds decorrelated via axis_index — the same wiring as
    sample_neighbor_sharded (see its docstring for why plain pjit
    cannot express this). ``roots`` length must divide the axis size;
    device.sample_fanout checks before routing here. ``draw_fn``
    defaults to sample_fanout2; tests inject an XLA-executable stand-in
    to exercise the wiring on CPU meshes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if draw_fn is None:
        draw_fn = sample_fanout2
    roots = jnp.asarray(roots, jnp.int32).reshape(-1)
    seed = _two_word_seed(seed)

    def body(adj1_l, adj2_l, roots_l, seed_l):
        ai = jax.lax.axis_index(axis).astype(jnp.int32)
        s = seed_l + (ai + 1) * jnp.int32(0x9E3779B1 - (1 << 32))
        return draw_fn(adj1_l, adj2_l, roots_l, s, f1, f2)

    sm = _shard_map()
    out1, out2 = sm(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(), adj1),
            jax.tree.map(lambda _: P(), adj2),
            P(axis),
            P(),
        ),
        out_specs=(P(axis), P(axis)),
        check_rep=False,
    )(adj1, adj2, roots, seed)
    return out1, out2


def sample_neighbor_sharded(
    adj: dict, nodes, seed, count: int, mesh, axis: str = "data",
    draw_fn=None,
):
    """The kernel draw under SPMD: shard_map over ``mesh``'s ``axis``
    with nodes batch-sharded and the (packed) adjacency replicated, so
    each device runs ONE fused pallas_call on its local rows — the
    composition plain pjit cannot express (pallas_call does not
    partition). Per-shard seeds are decorrelated by folding in
    axis_index, otherwise every shard would replay the same core-PRNG
    stream against different rows.

    ``nodes`` is flattened; its length must divide the axis size
    (callers check — device.sample_neighbor falls back to the XLA chain
    otherwise). ``draw_fn(adj, nodes, seed, count)`` defaults to the
    kernel; tests inject an XLA-executable stand-in to exercise this
    wiring on CPU meshes where the kernel's TPU primitives cannot run.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shard_map = _shard_map()
    if draw_fn is None:
        draw_fn = sample_neighbor
    nodes = jnp.asarray(nodes, jnp.int32)
    shape = nodes.shape
    flat = nodes.reshape(-1)
    seed = _two_word_seed(seed)

    def body(adj_l, nodes_l, seed_l):
        ai = jax.lax.axis_index(axis).astype(jnp.int32)
        # distinct per-shard words (golden-ratio odd constant; int32
        # wraparound is fine — determinism is all that matters)
        s = seed_l + (ai + 1) * jnp.int32(0x9E3779B1 - (1 << 32))
        return draw_fn(adj_l, nodes_l, s, count)

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(), adj),
            P(axis),
            P(),
        ),
        out_specs=P(axis),
        check_rep=False,
    )(adj, flat, seed)
    return out.reshape(*shape, count)
