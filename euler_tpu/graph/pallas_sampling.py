"""Fused weighted-neighbor draw as a Pallas TPU kernel.

The XLA device-sampling path (device.py sample_neighbor) lowers to a
chain of ~6 small ops per hop (row gathers, RNG, compare-sum, pick) and
is latency-bound at GNN batch dims: measured on a v5e chip, the two-hop
PPI fanout (512x10 + 5120x10 draws) costs 0.72 ms/step of the 1.27 ms
train step while the MXU math is ~free (see PERF.md step anatomy). This
kernel fuses the whole per-hop draw into ONE program: the source nodes'
slab rows stream HBM->VMEM through a double-buffered row-DMA pipeline,
the on-core PRNG draws the uniforms, and the compare-sum pick happens on
the rows while the next batch of rows is in flight. Same fanout measured
at 0.24 ms/step — 3x over the XLA chain.

Layout: ``pack_adjacency`` stores each node as 2K adjacent rows of one
``[2KN, 128]`` array — its K neighbor-id rows then its K
cumulative-weight rows (bitcast to int32), K = ceil(W / 128) — so ONE
2K-row DMA fetches the whole node and every row stays aligned to the
(1, 128) HBM tiling that scattered-row slices require (a ``[N, 2K*128]``
array would tile (8, 128) and break scattered-row DMA). Pad slots hold
cum=1.0, which ``idx = #(u >= cum)`` can never select while u < 1 (the
last real slot is pinned to 1.0 at build time), and the VPU compares
each 128-lane row in one op anyway, so the pad is free compute-wise.
Graphs whose slab width exceeds MAX_W = 512 keep the XLA path (cap with
``build_adjacency(..., max_degree=512)`` to opt in — the same
truncate-to-heaviest semantics the reference applies to heavy-tailed
graphs).

Draw semantics are identical to device.sample_neighbor — first slot
whose cumulative weight exceeds u, default node for unsampleable rows
(baked into the slab: their neighbor lanes are default-filled at pack
time, so the kernel needs no mask gather; reference
CompactNode::SampleNeighbor, euler/core/compact_node.cc:42-101) — but
from the core PRNG's stream rather than threefry, so
sequences differ for the same seed while distributions match
(statistically pinned against the host engine in
tests/test_pallas_sampling.py, TPU-only).

SPMD note: pallas_call does not partition under pjit, so the kernel
auto-activates only on a single-device TPU (``available()``); meshes
keep the XLA path. Force on/off with EULER_TPU_PALLAS_SAMPLING=1/0.
"""

from __future__ import annotations

import functools
import os

import numpy as np

LANES = 128
MAX_COUNT = 128  # larger per-node draw counts keep the XLA path: the
# count loop is unrolled in the kernel and the [M, count] output lives
# whole in VMEM, both of which scale linearly with count; every model
# draw (fanouts, walks, negatives) is far below this
MAX_OUT_ELEMS = 1 << 20  # [M, count] output cap (4 MB VMEM): bigger
# draws keep the XLA path — see eligible()
MAX_M = 1 << 15  # source-node cap: ids ride scalar prefetch (SMEM, far
# smaller than VMEM — 128 KB of ids at this cap), so M needs its own
# bound even when M*count fits the output budget (e.g. count=1 walks)
MAX_W = 4 * LANES  # widest slab the kernel handles (K = ceil(W/128)
# row-pairs per node, compare-sum unrolled over K); wider keeps XLA
MAX_PACKED_BYTES = 2 << 30  # pack_adjacency opt-out: the packed slab is
# always a K*128-lane multiple (1 KB/node per K), a (K*128)/W inflation
# over nbr+cum that it is ADDED to; beyond this budget the kernel is not
# worth the HBM
_MAX_R = 512  # rows per pipeline stage (2 DMA semaphores regardless)


def _backend_ok(require_single_device: bool) -> bool:
    try:
        import jax

        if jax.default_backend() != "tpu":
            return False
        if require_single_device and len(jax.devices()) != 1:
            return False
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except Exception:  # pragma: no cover - import/backend probing
        return False
    return True


def _force_flag():
    """Strictly parsed EULER_TPU_PALLAS_SAMPLING: True ("1"/"true"),
    False ("0"/"false"), or None (unset/empty). Anything else —
    "off", "no", "False " with a space — warns once and counts as
    unset rather than silently force-enabling the kernel."""
    raw = os.environ.get("EULER_TPU_PALLAS_SAMPLING")
    if raw is None or raw == "":
        return None
    v = raw.strip().lower()
    if v in ("1", "true"):
        return True
    if v in ("0", "false"):
        return False
    import warnings

    warnings.warn(
        f"EULER_TPU_PALLAS_SAMPLING={raw!r} is not one of 0/1/false/true"
        " (case-insensitive); ignoring it",
        stacklevel=3,
    )
    return None


def available() -> bool:
    """True when the kernel path should auto-activate: TPU backend, one
    device (see SPMD note above), imports work, not overridden by env.
    EULER_TPU_PALLAS_SAMPLING=1 skips the single-device heuristic —
    but only once a kernel mesh is registered
    (device.set_kernel_mesh, which run_loop calls on the
    --device_sampling path): on a multi-device backend with NO mesh
    registered the flag warns and still returns False, because the
    direct (non-shard_map) route would run an unsharded pallas_call
    under pjit — silently wrong per-shard draws. Experts composing
    their own shard_map call pallas_sampling.sample_neighbor directly,
    which never consults this gate. The flag still requires a TPU
    backend with pallas importable — the kernel's primitives exist
    nowhere else; =0 forces the XLA path."""
    force = _force_flag()
    if force is not None:
        if not force:
            return False
        ok = _backend_ok(require_single_device=False)
        if ok:
            import jax

            from euler_tpu.graph import device as _dg

            if len(jax.devices()) > 1 and _dg.kernel_mesh() is None:
                import warnings

                warnings.warn(
                    "EULER_TPU_PALLAS_SAMPLING=1 with "
                    f"{len(jax.devices())} devices but no kernel mesh:"
                    " pallas_call does not partition under pjit, so the"
                    " force flag is ignored (XLA path) — register the"
                    " mesh with device.set_kernel_mesh, as run_loop's"
                    " --device_sampling path does, to wire the kernel"
                    " per-shard",
                    stacklevel=2,
                )
                return False
        return ok
    return _backend_ok(require_single_device=True)


def sharded_available() -> bool:
    """True when the kernel can run PER-SHARD inside shard_map on this
    backend: TPU with pallas importable, any device count. This is the
    mesh-path activation check (device.set_kernel_mesh wires it);
    available() stays the single-device auto-activation check —
    pallas_call does not partition under plain pjit."""
    if _force_flag() is False:
        return False
    return _backend_ok(require_single_device=False)


def eligible(m: int, count: int) -> bool:
    """True when a draw of ``m`` source nodes x ``count`` fits the
    kernel's on-core budgets (ids in scalar prefetch / SMEM, [M, count]
    output whole in VMEM); callers fall back to the XLA chain
    otherwise."""
    return (
        count <= MAX_COUNT
        and m <= MAX_M
        and m * count <= MAX_OUT_ELEMS
    )


def pack_adjacency(adj: dict, max_bytes: int = MAX_PACKED_BYTES):
    """[2KN, 128] int32, K = ceil(W/128): node i occupies rows
    2K*i..2K*i+2K-1 — its K neighbor-id rows (pad: default id) then its
    K cumulative-weight rows bitcast to int32 (pad: 1.0). Returns None
    (caller keeps the XLA path) when the slab is wider than MAX_W, or
    when the packed copy — which is KEPT ALONGSIDE nbr/cum (the fallback
    paths still need them) at a fixed K KB/node regardless of real
    degree — would exceed ``max_bytes`` of HBM."""
    nbr = np.asarray(adj["nbr"])
    cum = np.asarray(adj["cum"])
    n_rows, w = nbr.shape
    k = (w + LANES - 1) // LANES
    if w > MAX_W or 2 * k * n_rows * LANES * 4 > max_bytes:
        return None
    nbr_p = np.full((n_rows, k * LANES), n_rows - 1, np.int32)
    nbr_p[:, :w] = nbr
    # unsampleable rows (zero total weight — their cum is a neutral
    # all-1.0, see build_adjacency) draw the DEFAULT node on the host
    # path via the `sampleable` mask; the packed slab is kernel-only, so
    # bake that in by default-filling their neighbor lanes — the kernel
    # then needs no separate mask gather at draw time
    sampleable = np.asarray(
        adj.get("sampleable", np.ones(n_rows, bool))
    ).astype(bool)
    nbr_p[~sampleable] = n_rows - 1
    cum_p = np.ones((n_rows, k * LANES), np.float32)
    cum_p[:, :w] = cum
    packed = np.empty((2 * k * n_rows, LANES), np.int32)
    # node-major: [nbr_0..nbr_{K-1}, cum_0..cum_{K-1}] per node
    packed.reshape(n_rows, 2 * k, LANES)[:, :k] = nbr_p.reshape(
        n_rows, k, LANES
    )
    packed.reshape(n_rows, 2 * k, LANES)[:, k:] = cum_p.view(
        np.int32
    ).reshape(n_rows, k, LANES)
    return packed


def _kernel(ids_ref, seed_ref, pk_hbm, out_ref, pk_s, sem,
            *, rows, count, num_iters, k):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # both words seed the core PRNG: 62 bits of caller entropy (a lone
    # int31 word collides across long runs — ADVICE r2)
    pltpu.prng_seed(seed_ref[0], seed_ref[1])

    def dma(slot, r, row):
        # one copy moves the node's whole 2K-row block (K nbr rows + K
        # cum rows); every copy is the same size, so a single per-slot
        # semaphore counts them all
        return pltpu.make_async_copy(
            pk_hbm.at[pl.ds(row * 2 * k, 2 * k), :],
            pk_s.at[slot, pl.ds(2 * k * r, 2 * k), :],
            sem.at[slot],
        )

    def issue(slot, it):
        base = it * rows
        for r in range(rows):
            dma(slot, r, ids_ref[base + r]).start()

    def wait(slot, it):
        base = it * rows
        for r in range(rows):
            dma(slot, r, ids_ref[base + r]).wait()

    issue(0, 0)

    def body(it, _):
        slot = jax.lax.rem(it, 2)

        @pl.when(it + 1 < num_iters)
        def _():
            issue(jax.lax.rem(it + 1, 2), it + 1)

        wait(slot, it)
        both = pk_s[slot].reshape(rows, 2 * k, LANES)
        nbrs = [both[:, j, :] for j in range(k)]           # k x [rows, 128]
        cums = [
            pltpu.bitcast(both[:, k + j, :], jnp.float32) for j in range(k)
        ]
        lanes = jax.lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
        cols = []
        for _c in range(count):
            bits = pltpu.bitcast(
                pltpu.prng_random_bits((rows, 1)), jnp.uint32
            )
            # 24-bit mantissa-exact uniform in [0, 1)
            u = (bits >> 8).astype(jnp.int32).astype(jnp.float32) * (
                1.0 / (1 << 24)
            )
            # rank over the whole (sorted) K*128-lane cumulative row
            idx = jnp.sum((u >= cums[0]).astype(jnp.int32), axis=1,
                          keepdims=True)
            for j in range(1, k):
                idx = idx + jnp.sum(
                    (u >= cums[j]).astype(jnp.int32), axis=1, keepdims=True
                )
            idx = jnp.minimum(idx, k * LANES - 1)
            # select lane idx from the concatenated nbr rows: exactly one
            # register's local lane matches (out-of-register locals match
            # no lane and contribute 0)
            val = jnp.sum(
                jnp.where(lanes == idx, nbrs[0], 0), axis=1, keepdims=True
            )
            for j in range(1, k):
                val = val + jnp.sum(
                    jnp.where(lanes == idx - j * LANES, nbrs[j], 0),
                    axis=1, keepdims=True,
                )
            cols.append(val)
        # unsampleable/default rows already hold the default id in every
        # neighbor lane (pack_adjacency), so the draw needs no mask here
        out_ref[pl.ds(it * rows, rows), :] = jnp.concatenate(cols, axis=1)
        return 0

    jax.lax.fori_loop(0, num_iters, body, 0)


def sample_neighbor(adj: dict, nodes, seed, count: int):
    """[len(nodes), count] int32 weighted draws via the fused kernel.

    ``adj`` must carry the "packed" slab (models add it through
    base.Model.add_sampling_consts when available()); ``seed`` is one or
    two traced int32 words (two preferred — both are fed to the core
    PRNG; callers with a PRNG key derive them via jax.random.randint).
    A scalar/1-word seed is zero-extended."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    packed = adj["packed"]
    n_rows = adj["nbr"].shape[0]
    k = packed.shape[0] // (2 * n_rows)  # ceil(W / 128) row-pairs/node
    nodes = jnp.asarray(nodes, jnp.int32)
    shape = nodes.shape
    flat = nodes.reshape(-1)
    m = flat.shape[0]
    if m == 0:  # the kernel's prologue DMA needs >= 1 real row
        return jnp.zeros((*shape, count), jnp.int32)
    # ids become raw DMA offsets in the kernel — clamp so unknown ids
    # (negative or past the slab) land on the DEFAULT row (n_rows-1)
    # instead of reading out of bounds; device.sample_neighbor's XLA
    # path applies the identical mapping, keeping build_adjacency's
    # "unknown ids sample the default node" contract on both paths
    flat = jnp.where(flat < 0, n_rows - 1, jnp.minimum(flat, n_rows - 1))
    # power-of-two stage size (sublane-aligned dynamic slices), floored
    # at 8, scaled down by K to keep the 2-slot scratch K-independent
    max_r = max(8, 1 << ((_MAX_R // k).bit_length() - 1))
    rows = max_r if m >= max_r else max(8, 1 << (m - 1).bit_length())
    mp = ((m + rows - 1) // rows) * rows
    ids = jnp.pad(flat, (0, mp - m))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # ids, seed
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),       # packed slab (HBM)
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, 2 * k * rows, LANES), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    seed = jnp.atleast_1d(seed).astype(jnp.int32)
    if seed.shape[0] < 2:
        seed = jnp.concatenate([seed, jnp.zeros(1, jnp.int32)])
    out = pl.pallas_call(
        functools.partial(
            _kernel, rows=rows, count=count, num_iters=mp // rows, k=k,
        ),
        out_shape=jax.ShapeDtypeStruct((mp, count), jnp.int32),
        grid_spec=grid_spec,
    )(
        ids,
        seed[:2],
        packed,
    )
    return out[:m].reshape(*shape, count)


def sample_neighbor_sharded(
    adj: dict, nodes, seed, count: int, mesh, axis: str = "data",
    draw_fn=None,
):
    """The kernel draw under SPMD: shard_map over ``mesh``'s ``axis``
    with nodes batch-sharded and the (packed) adjacency replicated, so
    each device runs ONE fused pallas_call on its local rows — the
    composition plain pjit cannot express (pallas_call does not
    partition). Per-shard seeds are decorrelated by folding in
    axis_index, otherwise every shard would replay the same core-PRNG
    stream against different rows.

    ``nodes`` is flattened; its length must divide the axis size
    (callers check — device.sample_neighbor falls back to the XLA chain
    otherwise). ``draw_fn(adj, nodes, seed, count)`` defaults to the
    kernel; tests inject an XLA-executable stand-in to exercise this
    wiring on CPU meshes where the kernel's TPU primitives cannot run.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _sm  # jax >= 0.7 (check_vma kwarg)

        def shard_map(f, **kw):
            kw["check_vma"] = kw.pop("check_rep")
            return _sm(f, **kw)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    if draw_fn is None:
        draw_fn = sample_neighbor
    nodes = jnp.asarray(nodes, jnp.int32)
    shape = nodes.shape
    flat = nodes.reshape(-1)
    seed = jnp.atleast_1d(jnp.asarray(seed, jnp.int32))
    if seed.shape[0] < 2:
        seed = jnp.concatenate([seed, jnp.zeros(1, jnp.int32)])

    def body(adj_l, nodes_l, seed_l):
        ai = jax.lax.axis_index(axis).astype(jnp.int32)
        # distinct per-shard words (golden-ratio odd constant; int32
        # wraparound is fine — determinism is all that matters)
        s = seed_l + (ai + 1) * jnp.int32(0x9E3779B1 - (1 << 32))
        return draw_fn(adj_l, nodes_l, s, count)

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(), adj),
            P(axis),
            P(),
        ),
        out_specs=P(axis),
        check_rep=False,
    )(adj, flat, seed)
    return out.reshape(*shape, count)
