#include "eg_telemetry.h"

#include <algorithm>

#include "eg_blackbox.h"
#include "eg_devprof.h"
#include "eg_heat.h"
#include "eg_phase.h"
#include "eg_stats.h"

namespace eg {

namespace {

// splitmix64 finalizer (same mix as eg::Rng) over a process-global
// counter: unique, well-distributed trace ids with one atomic RMW.
uint64_t Mix(uint64_t z) {
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

void AppendU64(std::string* out, uint64_t v) {
  char buf[24];
  int n = 0;
  do {
    buf[n++] = static_cast<char>('0' + v % 10);
    v /= 10;
  } while (v);
  while (n) out->push_back(buf[--n]);
}

void AppendI64(std::string* out, int64_t v) {
  if (v < 0) {
    out->push_back('-');
    AppendU64(out, static_cast<uint64_t>(-v));
  } else {
    AppendU64(out, static_cast<uint64_t>(v));
  }
}

void AppendKey(std::string* out, const char* k) {
  out->push_back('"');
  out->append(k);
  out->append("\":");
}

}  // namespace

uint64_t NextTraceId() {
  static std::atomic<uint64_t> counter{0x9E3779B97F4A7C15ULL};
  uint64_t id = Mix(counter.fetch_add(0x9E3779B97F4A7C15ULL,
                                      std::memory_order_relaxed));
  return id ? id : 1;  // 0 means "no trace" on the wire
}

Telemetry& Telemetry::Global() {
  static Telemetry t;
  return t;
}

void Telemetry::SetSlowCapacity(int n) {
  if (n < 1) n = 1;
  std::lock_guard<std::mutex> l(span_mu_);
  span_cap_ = n;
  if (static_cast<int>(spans_.size()) > span_cap_) {
    // keep the slowest span_cap_ entries
    std::sort(spans_.begin(), spans_.end(),
              [](const TelemetrySpan& a, const TelemetrySpan& b) {
                return a.total_us > b.total_us;
              });
    spans_.resize(span_cap_);
  }
  bool full = static_cast<int>(spans_.size()) >= span_cap_;
  span_full_.store(full, std::memory_order_relaxed);
  uint64_t floor = 0;
  if (full) {
    floor = spans_[0].total_us;
    for (const auto& s : spans_) floor = std::min(floor, s.total_us);
  }
  span_floor_.store(floor, std::memory_order_relaxed);
}

int Telemetry::slow_capacity() const {
  std::lock_guard<std::mutex> l(span_mu_);
  return span_cap_;
}

void Telemetry::RecordSpan(const TelemetrySpan& span) {
  if (!enabled()) return;
  // Hot-path reject: a full journal only admits spans over its floor.
  if (span_full_.load(std::memory_order_relaxed) &&
      span.total_us <= span_floor_.load(std::memory_order_relaxed))
    return;
  TelemetrySpan s = span;
  if (s.end_us == 0) s.end_us = TelemetryNowUs();
  std::lock_guard<std::mutex> l(span_mu_);
  if (static_cast<int>(spans_.size()) < span_cap_) {
    spans_.push_back(s);
  } else {
    // evict the FASTEST resident span (the journal keeps the slowest-N)
    size_t min_i = 0;
    for (size_t i = 1; i < spans_.size(); ++i)
      if (spans_[i].total_us < spans_[min_i].total_us) min_i = i;
    if (s.total_us <= spans_[min_i].total_us) return;  // raced under floor
    spans_[min_i] = s;
  }
  bool full = static_cast<int>(spans_.size()) >= span_cap_;
  span_full_.store(full, std::memory_order_relaxed);
  if (full) {
    uint64_t floor = spans_[0].total_us;
    for (const auto& sp : spans_) floor = std::min(floor, sp.total_us);
    span_floor_.store(floor, std::memory_order_relaxed);
  }
}

std::vector<TelemetrySpan> Telemetry::SlowSpans() const {
  std::vector<TelemetrySpan> out;
  {
    std::lock_guard<std::mutex> l(span_mu_);
    out = spans_;
  }
  std::stable_sort(out.begin(), out.end(),
                   [](const TelemetrySpan& a, const TelemetrySpan& b) {
                     return a.total_us > b.total_us;
                   });
  return out;
}

void Telemetry::Reset() {
  for (auto& per_kind : cells_)
    for (auto& c : per_kind) {
      for (auto& b : c.buckets) b.store(0, std::memory_order_relaxed);
      c.total_us.store(0, std::memory_order_relaxed);
    }
  std::lock_guard<std::mutex> l(span_mu_);
  spans_.clear();
  span_full_.store(false, std::memory_order_relaxed);
  span_floor_.store(0, std::memory_order_relaxed);
}

std::string Telemetry::Json(int shard, const TelemetryGauges* g) const {
  std::string o;
  o.reserve(16384);
  o.push_back('{');
  AppendKey(&o, "shard");
  AppendI64(&o, shard);
  o.push_back(',');
  AppendKey(&o, "enabled");
  AppendI64(&o, enabled() ? 1 : 0);

  // counters: every id, zeros included — byte-parity with the
  // eg_counters_* snapshot Python reads in-process.
  o.push_back(',');
  AppendKey(&o, "counters");
  o.push_back('{');
  uint64_t ctr[kCtrCount];
  Counters::Global().Snapshot(ctr);
  for (int i = 0; i < kCtrCount; ++i) {
    if (i) o.push_back(',');
    AppendKey(&o, kCounterNames[i]);
    AppendU64(&o, ctr[i]);
  }
  o.push_back('}');

  // span-timer stats (raw ints; non-zero ops only, like native.stats())
  o.push_back(',');
  AppendKey(&o, "stats");
  o.push_back('{');
  uint64_t sc[kStatOpCount], st[kStatOpCount], sm[kStatOpCount];
  Stats::Global().Snapshot(sc, st, sm);
  bool first = true;
  for (int i = 0; i < kStatOpCount; ++i) {
    if (sc[i] == 0) continue;
    if (!first) o.push_back(',');
    first = false;
    AppendKey(&o, kStatNames[i]);
    o.push_back('[');
    AppendU64(&o, sc[i]);
    o.push_back(',');
    AppendU64(&o, st[i]);
    o.push_back(',');
    AppendU64(&o, sm[i]);
    o.push_back(']');
  }
  o.push_back('}');

  // histograms: per-op kinds emit EVERY wire op (the exposition must
  // cover the full RPC surface even before traffic); scalar kinds emit
  // their single series.
  o.push_back(',');
  AppendKey(&o, "hist");
  o.push_back('{');
  first = true;
  for (int k = 0; k < kHistKindCount; ++k) {
    int lo = kHistKindPerOp[k] ? 1 : 0;
    int hi = kHistKindPerOp[k] ? kHistOpSlots : 1;
    for (int op = lo; op < hi; ++op) {
      const Cell& c = cells_[k][op];
      if (!first) o.push_back(',');
      first = false;
      o.push_back('"');
      o.append(kHistKindNames[k]);
      if (kHistKindPerOp[k]) {
        o.push_back(':');
        o.append(kWireOpNames[op]);
      }
      o.append("\":{");
      AppendKey(&o, "b");
      o.push_back('[');
      uint64_t count = 0;
      for (int b = 0; b < kHistBuckets; ++b) {
        uint64_t v = c.buckets[b].load(std::memory_order_relaxed);
        count += v;
        if (b) o.push_back(',');
        AppendU64(&o, v);
      }
      o.append("],");
      AppendKey(&o, "count");
      AppendU64(&o, count);
      o.push_back(',');
      AppendKey(&o, "sum_us");
      AppendU64(&o, c.total_us.load(std::memory_order_relaxed));
      o.push_back('}');
    }
  }
  // step-phase + prefetch-gauge histograms (eg_phase.h) join the same
  // map, so every surface downstream of this dump — metrics_text,
  // snapshot, the STATS scrape, metrics_dump — sees them for free
  PhaseStats::Global().HistJsonInto(&o, &first);
  // per-op shards-touched value histograms (eg_heat.h) ride the same
  // map for the same reason — keys heat_spread:<op>
  Heat::Global().SpreadJsonInto(&o, &first);
  o.push_back('}');

  // process resource gauges (eg_blackbox.h): RSS / open fds / live
  // threads / cache bytes — emitted into the same dump every metrics
  // surface reads, so metrics_text()/snapshot()/the STATS scrape pick
  // them up with zero new plumbing (and a postmortem's frozen values
  // can be compared against what the live surfaces showed)
  Blackbox::Global().ResourceJsonInto(&o);

  // live serve-SLO gauges (eg_devprof.h): the windowed p50/p99 and
  // lifetime violation count euler_tpu/serving/slo.py pushes through
  // the ABI — always emitted (zeros included) so metrics_text renders
  // the eg_serve_slo_* families unconditionally
  Devprof::Global().ServeSloJsonInto(&o);

  // data-plane heat (eg_heat.h): hot-vertex top-K, sketch totals,
  // per-op ids ledger, fan-out attribution, cache-efficacy classes —
  // one section in the same dump, so the whole surface chain
  // (metrics_text/snapshot/STATS scrape/metrics_dump) inherits it
  Heat::Global().JsonInto(&o);

  if (g) {
    o.push_back(',');
    AppendKey(&o, "gauges");
    o.push_back('{');
    AppendKey(&o, "workers");
    AppendI64(&o, g->workers);
    o.push_back(',');
    AppendKey(&o, "workers_active");
    AppendI64(&o, g->active);
    o.push_back(',');
    AppendKey(&o, "queue_depth");
    AppendI64(&o, g->queue_depth);
    o.push_back(',');
    AppendKey(&o, "conns");
    AppendI64(&o, g->conns);
    o.push_back(',');
    AppendKey(&o, "draining");
    AppendI64(&o, g->draining);
    o.push_back(',');
    AppendKey(&o, "epoch");
    AppendI64(&o, g->epoch);
    o.push_back('}');
  }

  o.push_back(',');
  AppendKey(&o, "slow_spans");
  o.push_back('[');
  std::vector<TelemetrySpan> spans = SlowSpans();
  for (size_t i = 0; i < spans.size(); ++i) {
    const TelemetrySpan& s = spans[i];
    if (i) o.push_back(',');
    o.push_back('{');
    AppendKey(&o, "side");
    o.push_back('"');
    o.append(s.side == kSpanServer ? "server" : "client");
    o.append("\",");
    AppendKey(&o, "op");
    o.push_back('"');
    o.append(kWireOpNames[s.op < kHistOpSlots ? s.op : 0]);
    o.append("\",");
    // decimal STRING: a u64 trace id can exceed JSON's 2^53 safe-int
    // range, and Python int() round-trips the string exactly
    AppendKey(&o, "trace");
    o.push_back('"');
    AppendU64(&o, s.trace);
    o.append("\",");
    AppendKey(&o, "shard");
    AppendI64(&o, s.shard);
    o.push_back(',');
    AppendKey(&o, "queue_us");
    AppendU64(&o, s.queue_us);
    o.push_back(',');
    AppendKey(&o, "handler_us");
    AppendU64(&o, s.handler_us);
    o.push_back(',');
    AppendKey(&o, "wire_us");
    AppendU64(&o, s.wire_us);
    o.push_back(',');
    AppendKey(&o, "total_us");
    AppendU64(&o, s.total_us);
    o.push_back(',');
    AppendKey(&o, "end_us");
    AppendI64(&o, s.end_us);
    o.push_back(',');
    AppendKey(&o, "outcome");
    o.push_back('"');
    o.append(kSpanOutcomeNames[s.outcome < 6 ? s.outcome : 1]);
    o.push_back('"');
    o.push_back('}');
  }
  o.append("]}");
  return o;
}

}  // namespace eg
