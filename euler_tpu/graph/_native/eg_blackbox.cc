#include "eg_blackbox.h"

#include <dirent.h>
#include <execinfo.h>
#include <fcntl.h>
#include <signal.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>

#include "eg_cache.h"
#include "eg_devprof.h"
#include "eg_stats.h"

namespace eg {

namespace {

// ---- tiny append helpers for the NON-signal JSON builders ----------------

void AppendU64(std::string* out, uint64_t v) {
  char buf[24];
  int n = 0;
  do {
    buf[n++] = static_cast<char>('0' + v % 10);
    v /= 10;
  } while (v);
  while (n) out->push_back(buf[--n]);
}

void AppendI64(std::string* out, int64_t v) {
  if (v < 0) {
    out->push_back('-');
    AppendU64(out, static_cast<uint64_t>(-v));
  } else {
    AppendU64(out, static_cast<uint64_t>(v));
  }
}

void AppendKey(std::string* out, const char* k) {
  out->push_back('"');
  out->append(k);
  out->append("\":");
}

int64_t MonotonicUs() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

// ---- async-signal-safe writer --------------------------------------------
// The ONLY primitives the dump path may touch: a fixed stack/static
// buffer, hand-rolled integer formatting, and write(2). No malloc, no
// stdio, no locks — the handler may be running on a corrupted heap.
struct SafeWriter {
  int fd;
  char buf[4096];
  size_t n = 0;

  explicit SafeWriter(int f) : fd(f) {}
  void Flush() {
    size_t off = 0;
    while (off < n) {
      ssize_t w = ::write(fd, buf + off, n - off);
      if (w <= 0) break;  // best effort: a failed write must not loop
      off += static_cast<size_t>(w);
    }
    n = 0;
  }
  void Ch(char c) {
    if (n >= sizeof(buf)) Flush();
    buf[n++] = c;
  }
  void Raw(const char* s) {
    while (*s) Ch(*s++);
  }
  void U64(uint64_t v) {
    char d[24];
    int k = 0;
    do {
      d[k++] = static_cast<char>('0' + v % 10);
      v /= 10;
    } while (v);
    while (k) Ch(d[--k]);
  }
  void I64(int64_t v) {
    if (v < 0) {
      Ch('-');
      U64(static_cast<uint64_t>(-v));
    } else {
      U64(static_cast<uint64_t>(v));
    }
  }
  void Hex(uint64_t v) {
    Raw("0x");
    char d[18];
    int k = 0;
    do {
      int nib = static_cast<int>(v & 0xF);
      d[k++] = static_cast<char>(nib < 10 ? '0' + nib : 'a' + nib - 10);
      v >>= 4;
    } while (v);
    while (k) Ch(d[--k]);
  }
  void Key(const char* k) {
    Ch('"');
    Raw(k);
    Raw("\":");
  }
  void Str(const char* s) {
    Ch('"');
    Raw(s);
    Ch('"');
  }
};

const char* SignalName(int sig) {
  switch (sig) {
    case SIGSEGV: return "SIGSEGV";
    case SIGBUS:  return "SIGBUS";
    case SIGABRT: return "SIGABRT";
    case SIGFPE:  return "SIGFPE";
    case 0:       return "none";
    default:      return "signal";
  }
}

// First fatal signal wins the dump; later ones (including the re-raise
// and any secondary fault INSIDE the dump path) go straight to the
// default disposition.
std::atomic<int> g_dumping{0};

void FatalHandler(int sig) {
  int expected = 0;
  Blackbox& bb = Blackbox::Global();
  if (g_dumping.compare_exchange_strong(expected, 1) && bb.enabled() &&
      bb.postmortem_path()[0] != '\0') {
    int fd = ::open(bb.postmortem_path(), O_WRONLY | O_CREAT | O_TRUNC,
                    0644);
    if (fd >= 0) {
      bb.DumpToFd(fd, sig);
      ::close(fd);
    }
  }
  // default disposition + re-raise: the exit status must still name the
  // signal (the driver, the shell, and the chaos harness all key on it)
  ::signal(sig, SIG_DFL);
  ::raise(sig);
}

}  // namespace

AdmissionSnap& AdmissionGaugeSnap() {
  static AdmissionSnap s;
  return s;
}

Blackbox& Blackbox::Global() {
  static Blackbox* bb = new Blackbox();  // never destroyed: the signal
  return *bb;  // handler may fire during (or after) static teardown
}

BlackboxRing* Blackbox::ThreadRing() {
  thread_local BlackboxRing* ring = nullptr;
  thread_local bool exhausted = false;
  if (ring || exhausted) return ring;
  int idx = next_ring_.fetch_add(1, std::memory_order_relaxed);
  if (idx >= kBbMaxRings) {
    // fixed pool spent: later threads drop events (counted) rather than
    // share a ring (two writers would corrupt the single-writer seam)
    exhausted = true;
    return nullptr;
  }
  ring = &rings_[idx];
  ring->tid.store(static_cast<uint64_t>(::syscall(SYS_gettid)),
                  std::memory_order_relaxed);
  return ring;
}

void Blackbox::Record(uint8_t point, uint8_t op, int32_t shard,
                      uint64_t trace, uint64_t value, uint8_t outcome) {
  if (!enabled()) return;
  BlackboxRing* r = ThreadRing();
  if (!r) {
    dropped_.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  uint64_t h = r->head.load(std::memory_order_relaxed);
  BlackboxEvent& e = r->slots[h % kBbRingSlots];
  e.t_us.store(MonotonicUs(), std::memory_order_relaxed);
  e.trace.store(trace, std::memory_order_relaxed);
  e.value.store(value, std::memory_order_relaxed);
  e.shard.store(shard, std::memory_order_relaxed);
  e.point.store(point, std::memory_order_relaxed);
  e.op.store(op, std::memory_order_relaxed);
  e.outcome.store(outcome, std::memory_order_relaxed);
  r->head.store(h + 1, std::memory_order_release);
}

ResourceSample Blackbox::SampleResources() {
  ResourceSample s;
  s.t_us = MonotonicUs();
  // RSS: /proc/self/statm field 2 (resident pages)
  if (FILE* f = std::fopen("/proc/self/statm", "r")) {
    long size = 0, resident = 0;
    if (std::fscanf(f, "%ld %ld", &size, &resident) == 2)
      s.rss_bytes = static_cast<int64_t>(resident) *
                    ::sysconf(_SC_PAGESIZE);
    std::fclose(f);
  }
  // open fds: entries in /proc/self/fd (minus . and ..)
  if (DIR* d = ::opendir("/proc/self/fd")) {
    while (dirent* ent = ::readdir(d))
      if (ent->d_name[0] != '.') ++s.open_fds;
    ::closedir(d);
  }
  // live threads: /proc/self/status "Threads:\tN"
  if (FILE* f = std::fopen("/proc/self/status", "r")) {
    char line[128];
    while (std::fgets(line, sizeof(line), f)) {
      if (std::strncmp(line, "Threads:", 8) == 0) {
        s.threads = std::strtol(line + 8, nullptr, 10);
        break;
      }
    }
    std::fclose(f);
  }
  s.cache_bytes = GlobalCacheBytes().load(std::memory_order_relaxed);
  s.nbr_cache_bytes =
      GlobalNbrCacheBytes().load(std::memory_order_relaxed);
  s.device_mem_bytes = Devprof::Global().mem_bytes();
  s.device_buffers = Devprof::Global().buffers();
  return s;
}

void Blackbox::AppendHistory(const ResourceSample& s) {
  uint64_t h = hist_head_.load(std::memory_order_relaxed);
  history_[h % kBbHistorySlots].Store(s);
  hist_head_.store(h + 1, std::memory_order_release);
}

void Blackbox::SamplerLoop() {
  while (true) {
    AppendHistory(SampleResources());
    int ms = sample_ms_.load(std::memory_order_relaxed);
    for (int slept = 0; slept < ms; slept += 50)
      std::this_thread::sleep_for(std::chrono::milliseconds(
          std::min(50, ms - slept)));
  }
}

bool Blackbox::Install(const std::string& postmortem_dir, int shard,
                       int sample_ms) {
  std::lock_guard<std::mutex> l(install_mu_);  // cold path (init only)
  shard_.store(shard, std::memory_order_relaxed);
  if (sample_ms > 0)
    sample_ms_.store(sample_ms < 50 ? 50 : sample_ms,
                     std::memory_order_relaxed);
  if (!postmortem_dir.empty()) {
    // probe writability NOW: a typo'd dir must fail at init, not stay
    // silent until the one crash that needed it
    std::string probe = postmortem_dir + "/.postmortem_probe";
    int fd = ::open(probe.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) {
      error_ = "postmortem dir not writable: " + postmortem_dir;
      return false;
    }
    ::close(fd);
    ::unlink(probe.c_str());
    dir_ = postmortem_dir;
    std::string path = dir_ + "/postmortem." + std::to_string(::getpid()) +
                       ".json";
    if (path.size() >= sizeof(dump_path_)) {
      error_ = "postmortem dir path too long";
      return false;
    }
    std::memcpy(dump_path_, path.c_str(), path.size() + 1);
  }
  if (!installed_.exchange(true)) {
    // pre-warm backtrace: glibc lazily loads libgcc on the first call,
    // which allocates — do it here so the in-handler call does not
    void* warm[4];
    ::backtrace(warm, 4);
    struct sigaction sa;
    std::memset(&sa, 0, sizeof(sa));
    sa.sa_handler = FatalHandler;
    sigemptyset(&sa.sa_mask);
    for (int sig : {SIGSEGV, SIGBUS, SIGABRT, SIGFPE})
      ::sigaction(sig, &sa, nullptr);
  }
  if (!sampler_running_.exchange(true)) {
    std::thread([this] {
      try {
        SamplerLoop();
      } catch (...) {
        // std::terminate barrier (eg-lint: thread-catch): a dead
        // sampler freezes the resource history; the postmortem still
        // dumps rings + counters
      }
    }).detach();  // process-lifetime thread; never joined
    // seed the history immediately so a crash (or scrape) right after
    // init already has one sample
    AppendHistory(SampleResources());
  }
  return true;
}

void Blackbox::DumpToFd(int fd, int sig) {
  SafeWriter w(fd);
  w.Ch('{');
  w.Key("kind");
  w.Str("postmortem");
  w.Ch(',');
  w.Key("signal");
  w.I64(sig);
  w.Ch(',');
  w.Key("signal_name");
  w.Str(sig == 0 ? "exception" : SignalName(sig));
  w.Ch(',');
  w.Key("pid");
  w.I64(::getpid());
  w.Ch(',');
  w.Key("shard");
  w.I64(shard_.load(std::memory_order_relaxed));
  w.Ch(',');
  w.Key("t_us");
  w.I64(MonotonicUs());
  w.Ch(',');
  w.Key("dropped");
  w.U64(dropped_.load(std::memory_order_relaxed));

  // full eg_counters ledger — names are static strings, cells atomics
  w.Ch(',');
  w.Key("counters");
  w.Ch('{');
  for (int i = 0; i < kCtrCount; ++i) {
    if (i) w.Ch(',');
    w.Key(kCounterNames[i]);
    w.U64(Counters::Global().Get(static_cast<CounterId>(i)));
  }
  w.Ch('}');

  // admission gauges: the PollerLoop-refreshed POD snapshot (<=250 ms
  // stale), never a call into a possibly-mid-teardown server object
  AdmissionSnap& g = AdmissionGaugeSnap();
  if (g.registered.load(std::memory_order_relaxed)) {
    w.Ch(',');
    w.Key("gauges");
    w.Ch('{');
    w.Key("workers");
    w.I64(g.workers.load(std::memory_order_relaxed));
    w.Ch(',');
    w.Key("workers_active");
    w.I64(g.active.load(std::memory_order_relaxed));
    w.Ch(',');
    w.Key("queue_depth");
    w.I64(g.queue_depth.load(std::memory_order_relaxed));
    w.Ch(',');
    w.Key("conns");
    w.I64(g.conns.load(std::memory_order_relaxed));
    w.Ch(',');
    w.Key("draining");
    w.I64(g.draining.load(std::memory_order_relaxed));
    w.Ch('}');
  }

  // resource history (sampler-thread writes, read via the atomic head;
  // the handler reads memory only — no /proc parsing in signal context)
  uint64_t hh = hist_head_.load(std::memory_order_acquire);
  uint64_t hstart = hh > kBbHistorySlots ? hh - kBbHistorySlots : 0;
  w.Ch(',');
  w.Key("resource_history");
  w.Ch('[');
  for (uint64_t i = hstart; i < hh; ++i) {
    ResourceSample s = history_[i % kBbHistorySlots].Load();
    if (i != hstart) w.Ch(',');
    w.Ch('{');
    w.Key("t_us");
    w.I64(s.t_us);
    w.Ch(',');
    w.Key("rss_bytes");
    w.I64(s.rss_bytes);
    w.Ch(',');
    w.Key("open_fds");
    w.I64(s.open_fds);
    w.Ch(',');
    w.Key("threads");
    w.I64(s.threads);
    w.Ch(',');
    w.Key("cache_bytes");
    w.I64(s.cache_bytes);
    w.Ch(',');
    w.Key("device_mem_bytes");
    w.I64(s.device_mem_bytes);
    w.Ch('}');
  }
  w.Ch(']');

  // raw flight-recorder rings, oldest-first per ring
  w.Ch(',');
  w.Key("rings");
  w.Ch('[');
  bool first_ring = true;
  for (int r = 0; r < kBbMaxRings; ++r) {
    const BlackboxRing& ring = rings_[r];
    uint64_t tid = ring.tid.load(std::memory_order_relaxed);
    if (tid == 0) continue;
    uint64_t head = ring.head.load(std::memory_order_acquire);
    if (!first_ring) w.Ch(',');
    first_ring = false;
    w.Ch('{');
    w.Key("tid");
    w.U64(tid);
    w.Ch(',');
    w.Key("head");
    w.U64(head);
    w.Ch(',');
    w.Key("events");
    w.Ch('[');
    uint64_t start = head > kBbRingSlots ? head - kBbRingSlots : 0;
    for (uint64_t i = start; i < head; ++i) {
      const BlackboxEvent& e = ring.slots[i % kBbRingSlots];
      if (i != start) w.Ch(',');
      w.Ch('{');
      w.Key("t_us");
      w.I64(e.t_us.load(std::memory_order_relaxed));
      w.Ch(',');
      w.Key("point");
      uint8_t pt = e.point.load(std::memory_order_relaxed);
      w.Str(pt < kBbPointCount ? kBbPointNames[pt] : "?");
      w.Ch(',');
      w.Key("op");
      w.U64(e.op.load(std::memory_order_relaxed));
      w.Ch(',');
      w.Key("shard");
      w.I64(e.shard.load(std::memory_order_relaxed));
      w.Ch(',');
      w.Key("trace");
      w.Ch('"');
      w.U64(e.trace.load(std::memory_order_relaxed));
      w.Ch('"');
      w.Ch(',');
      w.Key("value");
      w.U64(e.value.load(std::memory_order_relaxed));
      w.Ch(',');
      w.Key("outcome");
      w.U64(e.outcome.load(std::memory_order_relaxed));
      w.Ch('}');
    }
    w.Raw("]}");
  }
  w.Ch(']');

  // backtrace addresses inside the JSON; readable frames follow the
  // JSON line via backtrace_symbols_fd (symbolizing in-handler would
  // allocate — the split keeps line 1 strictly parseable)
  static void* frames[64];
  int depth = sig == 0 ? 0 : ::backtrace(frames, 64);
  w.Ch(',');
  w.Key("backtrace");
  w.Ch('[');
  for (int i = 0; i < depth; ++i) {
    if (i) w.Ch(',');
    w.Ch('"');
    w.Hex(reinterpret_cast<uint64_t>(frames[i]));
    w.Ch('"');
  }
  w.Raw("]}");
  w.Ch('\n');
  w.Flush();
  if (depth > 0) ::backtrace_symbols_fd(frames, depth, fd);
}

bool Blackbox::WriteDump(const char* path, int sig) {
  if (!enabled()) return false;
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return false;
  DumpToFd(fd, sig);
  ::close(fd);
  return true;
}

std::string Blackbox::LiveJson() {
  std::string o;
  o.reserve(8192);
  o.push_back('{');
  AppendKey(&o, "enabled");
  AppendI64(&o, enabled() ? 1 : 0);
  o.push_back(',');
  AppendKey(&o, "shard");
  AppendI64(&o, shard_.load(std::memory_order_relaxed));
  o.push_back(',');
  AppendKey(&o, "postmortem_dir");
  o.push_back('"');
  {
    // a concurrent (re-)Install may be swapping dir_ — copy under the
    // same lock that guards its writes
    std::lock_guard<std::mutex> l(install_mu_);
    o.append(dir_);
  }
  o.push_back('"');
  o.push_back(',');
  AppendKey(&o, "dropped");
  AppendU64(&o, dropped_.load(std::memory_order_relaxed));
  o.push_back(',');
  AppendKey(&o, "rings");
  o.push_back('[');
  bool first_ring = true;
  for (int r = 0; r < kBbMaxRings; ++r) {
    const BlackboxRing& ring = rings_[r];
    uint64_t tid = ring.tid.load(std::memory_order_relaxed);
    if (tid == 0) continue;
    uint64_t head = ring.head.load(std::memory_order_acquire);
    if (!first_ring) o.push_back(',');
    first_ring = false;
    o.push_back('{');
    AppendKey(&o, "tid");
    AppendU64(&o, tid);
    o.push_back(',');
    AppendKey(&o, "head");
    AppendU64(&o, head);
    o.push_back(',');
    AppendKey(&o, "events");
    o.push_back('[');
    uint64_t start = head > kBbRingSlots ? head - kBbRingSlots : 0;
    for (uint64_t i = start; i < head; ++i) {
      const BlackboxEvent& e = ring.slots[i % kBbRingSlots];
      if (i != start) o.push_back(',');
      o.push_back('{');
      AppendKey(&o, "t_us");
      AppendI64(&o, e.t_us.load(std::memory_order_relaxed));
      o.push_back(',');
      AppendKey(&o, "point");
      o.push_back('"');
      uint8_t pt = e.point.load(std::memory_order_relaxed);
      o.append(pt < kBbPointCount ? kBbPointNames[pt] : "?");
      o.push_back('"');
      o.push_back(',');
      AppendKey(&o, "op");
      AppendU64(&o, e.op.load(std::memory_order_relaxed));
      o.push_back(',');
      AppendKey(&o, "shard");
      AppendI64(&o, e.shard.load(std::memory_order_relaxed));
      o.push_back(',');
      AppendKey(&o, "trace");
      o.push_back('"');
      AppendU64(&o, e.trace.load(std::memory_order_relaxed));
      o.push_back('"');
      o.push_back(',');
      AppendKey(&o, "value");
      AppendU64(&o, e.value.load(std::memory_order_relaxed));
      o.push_back(',');
      AppendKey(&o, "outcome");
      AppendU64(&o, e.outcome.load(std::memory_order_relaxed));
      o.push_back('}');
    }
    o.append("]}");
  }
  o.push_back(']');
  o.push_back(',');
  AppendKey(&o, "resource");
  ResourceJsonBody(&o);
  o.push_back('}');
  return o;
}

void Blackbox::ResourceJsonBody(std::string* out) {
  ResourceSample s = SampleResources();
  out->push_back('{');
  AppendKey(out, "rss_bytes");
  AppendI64(out, s.rss_bytes);
  out->push_back(',');
  AppendKey(out, "open_fds");
  AppendI64(out, s.open_fds);
  out->push_back(',');
  AppendKey(out, "threads");
  AppendI64(out, s.threads);
  out->push_back(',');
  AppendKey(out, "cache_bytes");
  AppendI64(out, s.cache_bytes);
  out->push_back(',');
  AppendKey(out, "nbr_cache_bytes");
  AppendI64(out, s.nbr_cache_bytes);
  out->push_back(',');
  AppendKey(out, "device_mem_bytes");
  AppendI64(out, s.device_mem_bytes);
  out->push_back(',');
  AppendKey(out, "device_mem_peak_bytes");
  AppendI64(out, Devprof::Global().mem_peak_bytes());
  out->push_back(',');
  AppendKey(out, "device_buffers");
  AppendI64(out, s.device_buffers);
  out->push_back(',');
  AppendKey(out, "history_depth");
  uint64_t hh = hist_head_.load(std::memory_order_acquire);
  AppendU64(out, hh > kBbHistorySlots ? kBbHistorySlots : hh);
  out->push_back('}');
}

void Blackbox::ResourceJsonInto(std::string* out) {
  out->push_back(',');
  AppendKey(out, "resource");
  ResourceJsonBody(out);
}

std::string Blackbox::HistoryJson(int shard) {
  std::string o;
  o.reserve(4096);
  o.push_back('{');
  AppendKey(&o, "shard");
  AppendI64(&o, shard);
  o.push_back(',');
  AppendKey(&o, "resource");
  ResourceJsonBody(&o);
  o.push_back(',');
  AppendKey(&o, "history");
  o.push_back('[');
  uint64_t hh = hist_head_.load(std::memory_order_acquire);
  uint64_t hstart = hh > kBbHistorySlots ? hh - kBbHistorySlots : 0;
  for (uint64_t i = hstart; i < hh; ++i) {
    ResourceSample s = history_[i % kBbHistorySlots].Load();
    if (i != hstart) o.push_back(',');
    o.push_back('{');
    AppendKey(&o, "t_us");
    AppendI64(&o, s.t_us);
    o.push_back(',');
    AppendKey(&o, "rss_bytes");
    AppendI64(&o, s.rss_bytes);
    o.push_back(',');
    AppendKey(&o, "open_fds");
    AppendI64(&o, s.open_fds);
    o.push_back(',');
    AppendKey(&o, "threads");
    AppendI64(&o, s.threads);
    o.push_back(',');
    AppendKey(&o, "cache_bytes");
    AppendI64(&o, s.cache_bytes);
    o.push_back(',');
    AppendKey(&o, "device_mem_bytes");
    AppendI64(&o, s.device_mem_bytes);
    o.push_back('}');
  }
  o.append("]}");
  return o;
}

void Blackbox::Reset() {
  for (auto& ring : rings_) ring.head.store(0, std::memory_order_relaxed);
  dropped_.store(0, std::memory_order_relaxed);
}

}  // namespace eg
