#include "eg_dispatch.h"

namespace eg {

Dispatcher::Dispatcher(int workers) {
  if (workers < 1) workers = 1;
  batches_.reset(new Batch[kMaxBatches]);
  for (int i = 0; i < kMaxBatches; ++i) free_.push_back(i);
  threads_.reserve(static_cast<size_t>(workers));
  for (int i = 0; i < workers; ++i)
    threads_.emplace_back([this] {
      try {
        WorkerLoop();
      } catch (...) {
        // std::terminate barrier (eg-lint: thread-catch): a dead worker
        // only shrinks the pool; remaining workers keep draining
      }
    });
}

Dispatcher::~Dispatcher() {
  {
    std::lock_guard<std::mutex> l(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void Dispatcher::WorkerLoop() {
  for (;;) {
    Task task{nullptr, nullptr};
    {
      std::unique_lock<std::mutex> l(mu_);
      cv_.wait(l, [this] { return stop_ || !queue_.empty(); });
      if (queue_.empty()) return;  // stop_ set and everything drained
      task = queue_.front();
      queue_.pop_front();
    }
    try {
      (*task.fn)();
    } catch (...) {
      // a throwing job degrades like a failed shard call: its rows keep
      // their prefilled defaults (callers record the failure themselves)
    }
    std::function<void()> cont;
    bool detached_last = false;
    {
      // notify while holding the batch lock: Wait() may release the
      // slot the instant its wait observes remaining == 0, and a fresh
      // Submit may re-arm it — the notify must not race a spurious
      // wakeup into signalling the WRONG generation of the slot
      std::lock_guard<std::mutex> l(task.batch->mu);
      if (--task.batch->remaining == 0) {
        detached_last = task.batch->detached;
        if (detached_last) cont = std::move(task.batch->on_done);
        task.batch->done.notify_all();
      }
    }
    if (detached_last) {
      // continuation runs on THIS worker, outside every dispatcher
      // lock, so it may submit the next batch of a hop chain without
      // deadlock — but it must never block on one
      if (cont) {
        try {
          cont();
        } catch (...) {
          // a throwing continuation must not kill the worker; the
          // async op records its own failures (ShardFailed et al.)
        }
      }
      ReleaseSlot(static_cast<int>(task.batch - batches_.get()));
    }
  }
}

int Dispatcher::AcquireSlot(std::vector<std::function<void()>> jobs,
                            bool detached,
                            std::function<void()> on_done) const {
  int slot;
  {
    std::unique_lock<std::mutex> l(pool_mu_);
    pool_cv_.wait(l, [this] { return !free_.empty(); });
    slot = free_.front();
    free_.pop_front();
  }
  // the slot is exclusively ours between acquire and release; jobs and
  // on_done are only touched by this thread until Enqueue publishes
  // them, so only the worker-visible fields need the batch lock
  Batch& b = batches_[slot];
  b.jobs = std::move(jobs);
  b.on_done = std::move(on_done);
  {
    std::lock_guard<std::mutex> l(b.mu);
    b.remaining = b.jobs.size();
    b.detached = detached;
  }
  return slot;
}

void Dispatcher::ReleaseSlot(int slot) const {
  Batch& b = batches_[slot];
  b.jobs.clear();
  b.on_done = nullptr;
  {
    std::lock_guard<std::mutex> l(pool_mu_);
    free_.push_back(slot);
  }
  pool_cv_.notify_one();
}

void Dispatcher::Enqueue(int slot) const {
  Batch& b = batches_[slot];
  {
    std::lock_guard<std::mutex> l(mu_);
    for (const auto& j : b.jobs) queue_.push_back(Task{&j, &b});
  }
  cv_.notify_all();
}

Dispatcher::BatchHandle Dispatcher::Submit(
    std::vector<std::function<void()>> jobs) const {
  int slot = AcquireSlot(std::move(jobs), false, nullptr);
  Enqueue(slot);  // an empty batch enqueues nothing; Poll/Wait see 0
  return slot;
}

bool Dispatcher::Poll(BatchHandle h) const {
  Batch& b = batches_[h];
  std::lock_guard<std::mutex> l(b.mu);
  return b.remaining == 0;
}

void Dispatcher::Wait(BatchHandle h) const {
  Batch& b = batches_[h];
  {
    std::unique_lock<std::mutex> l(b.mu);
    b.done.wait(l, [&b] { return b.remaining == 0; });
  }
  ReleaseSlot(h);
}

void Dispatcher::SubmitDetached(std::vector<std::function<void()>> jobs,
                                std::function<void()> on_done) const {
  if (jobs.empty()) {
    // nothing will ever complete to fire it: run inline on the caller
    // (initial submit thread or the previous hop's continuation worker)
    if (on_done) on_done();
    return;
  }
  int slot = AcquireSlot(std::move(jobs), true, std::move(on_done));
  Enqueue(slot);
}

void Dispatcher::Run(const std::vector<std::function<void()>>& jobs) const {
  if (jobs.empty()) return;
  Wait(Submit(jobs));
}

}  // namespace eg
