#include "eg_dispatch.h"

namespace eg {

Dispatcher::Dispatcher(int workers) {
  if (workers < 1) workers = 1;
  threads_.reserve(static_cast<size_t>(workers));
  for (int i = 0; i < workers; ++i)
    threads_.emplace_back([this] {
      try {
        WorkerLoop();
      } catch (...) {
        // std::terminate barrier (eg-lint: thread-catch): a dead worker
        // only shrinks the pool; remaining workers keep draining
      }
    });
}

Dispatcher::~Dispatcher() {
  {
    std::lock_guard<std::mutex> l(mu_);
    stop_ = true;
  }
  cv_.notify_all();
  for (auto& t : threads_) t.join();
}

void Dispatcher::WorkerLoop() {
  for (;;) {
    Task task{nullptr, nullptr};
    {
      std::unique_lock<std::mutex> l(mu_);
      cv_.wait(l, [this] { return stop_ || !queue_.empty(); });
      if (queue_.empty()) return;  // stop_ set and everything drained
      task = queue_.front();
      queue_.pop_front();
    }
    try {
      (*task.fn)();
    } catch (...) {
      // a throwing job degrades like a failed shard call: its rows keep
      // their prefilled defaults (callers record the failure themselves)
    }
    {
      // notify while holding the batch lock: Run() may destroy the Batch
      // the instant its wait observes remaining == 0, so the notify must
      // not race a spurious wakeup into a use-after-free
      std::lock_guard<std::mutex> l(task.batch->mu);
      if (--task.batch->remaining == 0) task.batch->done.notify_all();
    }
  }
}

void Dispatcher::Run(const std::vector<std::function<void()>>& jobs) const {
  if (jobs.empty()) return;
  Batch batch;
  batch.remaining = jobs.size();
  {
    std::lock_guard<std::mutex> l(mu_);
    for (const auto& j : jobs) queue_.push_back(Task{&j, &batch});
  }
  cv_.notify_all();
  std::unique_lock<std::mutex> l(batch.mu);
  batch.done.wait(l, [&batch] { return batch.remaining == 0; });
}

}  // namespace eg
