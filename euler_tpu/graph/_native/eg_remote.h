// Remote sharded graph client.
//
// Role equivalent of the reference RemoteGraph + RpcManager stack
// (reference euler/client/remote_graph.{h,cc}, remote_graph_shard.cc,
// rpc_manager.{h,cc}, rpc_client.cc): partition routing
// shard(id) = (id % num_partitions) % num_shards (remote_graph.h:118-129),
// per-request scatter by shard + ordered gather merge
// (remote_graph.cc:33-66,241-261), weighted cross-shard global sampling
// proportional to per-shard weight sums (REMOTE_SAMPLE,
// remote_graph.cc:195-221), node2vec-biased walking via client-side
// sorted-neighbor merge (graph.cc:120-151), and per-shard replica pools with
// retry + timed bad-host quarantine (rpc_manager.h:68-122,
// rpc_client.cc:29-49). Differences: the transport is the zero-dependency
// wire protocol of eg_wire.h instead of gRPC, calls are batch-synchronous,
// and discovery is the flat-file registry of eg_service.h instead of
// ZooKeeper.
//
// Hot-path shape (this file's perf contract, PERF.md "Remote path"):
//   * scatter/gather runs on a PERSISTENT worker pool (eg_dispatch.h) —
//     no thread create/join per query; large per-shard requests are
//     split into `chunk_ids=`-bounded chunks issued concurrently over
//     multiple pooled sockets (`rpc_chunks` counter);
//   * duplicate ids are COALESCED before wire encode (`coalesce=1`
//     default; `ids_deduped` counter) — one wire id and one shard lookup
//     per unique id, replies scattered back through the row maps; for
//     SampleNeighbor the kSampleNeighborUniq op carries repeat counts so
//     duplicate rows still receive independent draws;
//   * dense feature rows are served from a capacity-bounded client cache
//     (eg_cache.h, `feature_cache_mb=`, `cache_hits`/`cache_misses`) —
//     each SNAPSHOT is immutable, so cached rows only invalidate when a
//     shard announces a new epoch (eg_epoch.h): every v4 Ok reply stamps
//     the shard's serving epoch, ObserveEpoch bumps the client cache
//     generation on change, and stale-generation entries evict lazily on
//     their next probe (`epoch_stale_hits_evicted`).
#ifndef EG_REMOTE_H_
#define EG_REMOTE_H_

#include <atomic>
#include <condition_variable>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "eg_api.h"
#include "eg_async.h"
#include "eg_cache.h"
#include "eg_dispatch.h"
#include "eg_engine.h"
#include "eg_placement.h"
#include "eg_sampling.h"
#include "eg_wire.h"

namespace eg {

// Connection pool over the replicas of one shard: round-robin with
// quarantine of failing hosts, idle-socket reuse, retry across replicas.
// The replica set is mutable at runtime (mid-run re-discovery, the role
// of the reference's ZK watch callbacks adding/removing channels while
// training runs, rpc_manager.h:77-80): Call snapshots the shared_ptr
// vector under a brief lock, so Update never invalidates an in-flight
// exchange — a dropped replica's sockets close when its last reference
// (pool or call) goes away.
class ConnPool {
 public:
  struct Replica {
    ~Replica();  // closes pooled sockets
    std::string host;
    int port = 0;
    std::atomic<int64_t> bad_until_ms{0};
    // Negotiated wire version: 0 = unknown (probe with the newest
    // envelope and learn from the first reply), 1 = downgraded to raw
    // v1 (pre-envelope server), 2..kWireVersion = pinned at the highest
    // version the replica accepted (the BadVersion ladder steps down
    // one version per refusal). See eg_wire.h for the contract.
    std::atomic<int> wire_version{0};
    std::mutex mu;
    std::vector<int> idle;  // pooled connected sockets
  };

  void AddReplica(const std::string& host, int port);
  // Which shard this pool serves — stamped into client-side telemetry
  // spans (eg_telemetry.h) so a slow call names its shard.
  void SetShard(int s) { shard_ = s; }
  // Replace the replica set: existing (host, port) entries keep their
  // Replica object (pooled sockets + quarantine state survive), new
  // addresses are added, missing ones dropped. An empty `addrs` is a
  // no-op — a transiently empty/unreachable listing must never strand
  // the pool with zero replicas.
  void Update(const std::vector<std::pair<std::string, int>>& addrs);

  size_t num_replicas() const;

  // Pin every replica's wire version instead of negotiating: 1 emulates
  // a pre-envelope client (raw v1 requests, no deadline stamped), 2
  // forces the deadline envelope without a trace id, 3 the trace
  // envelope without an epoch, 4 the full epoch envelope. 0 (default)
  // negotiates per replica (v4 probe; an old server's reply walks the
  // replica down the 4 -> 3 -> 2 -> 1 ladder).
  void SetForcedWireVersion(int v) { forced_version_ = v; }

  // Install the flip-announcement hook (eg_epoch.h): called with the
  // serving epoch stamped on every v4 Ok reply, after the stamp is
  // stripped from the reply body. Runs on whatever thread completed the
  // exchange (dispatcher workers) — the observer must be thread-safe.
  // Set once at init, before any Call.
  void SetEpochObserver(std::function<void(uint64_t)> fn) {
    epoch_observer_ = std::move(fn);
  }

  // One request/reply exchange; retries across replicas with exponential
  // backoff (full jitter, base backoff_ms, capped at 2 s) between
  // attempts and an overall deadline spanning all of them (deadline_ms;
  // 0 = timeout_ms * (retries + 1), the previous worst case). The clock
  // is re-sampled per attempt so quarantine verdicts and the deadline see
  // time spent in earlier attempts. Returns false when every attempt
  // failed or the deadline expired (reply undefined). Failure counters
  // (eg_stats.h Counters) record dial failures, retries, quarantines,
  // failovers, deadline aborts, and exhausted calls.
  //
  // Server survivability reactions (wire v2, eg_admission.h):
  //   * the call's REMAINING deadline is stamped into each attempt's
  //     envelope, so a drowning server can refuse dead work;
  //   * a kStatusBusy reply fails over to the next replica IMMEDIATELY —
  //     no backoff burned, no quarantine (the server is alive, just
  //     shedding), counted in busy_failovers; only the overall deadline
  //     bounds a fully-busy cluster;
  //   * a kStatusDeadline reply ends the call at once (the budget is
  //     gone either way), counted like a client-side deadline abort;
  //   * an old server's "unknown op" answer to the envelope downgrades
  //     the replica to v1 and resends raw on the same connection
  //     (wire_downgrades).
  // Thread-safe: chunked requests Call the same pool concurrently from
  // several dispatcher workers, each exchange on its own pooled socket.
  //
  // `req_epoch` (eg_epoch.h) rides the v4 envelope: 0 asks for the
  // shard's current snapshot, nonzero pins the request to that epoch
  // when the shard still holds it (an in-flight multi-hop step keeps
  // reading the snapshot it started on across a flip). Replicas
  // negotiated below v4 simply drop the field — they serve their only
  // epoch anyway.
  bool Call(const std::string& req, std::string* reply, int retries,
            int timeout_ms, int quarantine_ms, int backoff_ms = 20,
            int deadline_ms = 0, uint64_t req_epoch = 0) const;

 private:
  mutable std::mutex mu_;  // guards replicas_ (the vector, not the pools)
  std::vector<std::shared_ptr<Replica>> replicas_;
  mutable std::atomic<size_t> rr_{0};
  int forced_version_ = 0;  // 0 = negotiate per replica
  int shard_ = -1;          // telemetry label only
  // v4 reply-stamp hook (SetEpochObserver); empty = stamps discarded.
  std::function<void(uint64_t)> epoch_observer_;
};

class RemoteGraph : public GraphAPI {
 public:
  // Config: semicolon-separated k=v (string form shared with the reference's
  // GraphConfig, graph_config.cc:33-56). Keys:
  //   registry=<dir>        flat-file registry written by Service::Start, OR
  //   shards=<h:p|h:p,...>  explicit per-shard replica lists
  //                         (',' separates shards, '|' separates replicas)
  //   retries (default 3), timeout_ms (5000), quarantine_ms (3000),
  //   backoff_ms (20): base of the exponential retry backoff (full
  //   jitter, doubling per attempt, capped at 2 s; 0 = no backoff),
  //   deadline_ms (0 = timeout_ms * (retries + 1)): overall wall-clock
  //   budget of ONE Call spanning all of its retry attempts,
  //   fault= / fault_seed=: deterministic transport failpoints
  //   (process-global FaultInjector, see eg_fault.h and FAULTS.md),
  //   rediscover_ms (default 3000 with registry=, 0 = off): period of the
  //   background registry re-LIST that diffs shard addresses into the
  //   ConnPools — the reference's ZK watch-children semantics
  //   (zk_server_monitor.cc:252-260 OnAddChild/OnRemoveChild) by polling,
  //   so a shard restarted on a NEW address is re-learned mid-run.
  // Hot-path keys (all optional):
  //   coalesce (default 1): dedup duplicate ids before wire encode
  //     (`ids_deduped`); 0 restores the pre-dedup wire shape (the bench
  //     A/B baseline),
  //   feature_cache_mb (default 64; 0 = off): byte budget of the
  //     client-side dense-feature-row cache (eg_cache.h),
  //   neighbor_cache_mb (default 16; 0 = off): byte budget of the
  //     client-side neighbor-list cache (eg_cache.h NeighborCache):
  //     nodes the heat sketch marks hot get their full adjacency slice
  //     fetched once (kFullNeighbor) and every later SampleNeighbor
  //     draw for them is served locally — distribution-identical to
  //     the shard engine (`nbr_cache_hits`/`nbr_cache_misses`),
  //   cache_policy (default "freq"; "fifo" restores PR-3 behavior):
  //     admission policy of BOTH client caches — "freq" is TinyLFU-
  //     shaped (a candidate displaces the FIFO victim only when the
  //     heat sketch estimates it hotter; `cache_admit_rejects`),
  //   placement (default 1; 0 = never ask): fetch the shard's
  //     placement map at init (kPlacement) and route ids through it
  //     (shard = map[id] % num_shards), hash fallback for unmapped ids
  //     and for servers without a map — old servers answer the stock
  //     unknown-op error, counted in `placement_fallbacks`,
  //   chunk_ids (default 16384): max unique ids per wire request; larger
  //     per-shard requests split into concurrent chunks (`rpc_chunks`),
  //   dispatch_workers (default 0 = auto: min(64, max(8, 2*shards))):
  //     size of the persistent dispatcher pool,
  //   strict (default 0): a shard call that fails after all transport
  //     retries raises through the C ABI (eg_remote_strict_error) instead
  //     of silently degrading its rows to defaults. Either way the
  //     failure is counted in `rpc_errors`.
  // Observability keys (eg_telemetry.h / eg_heat.h; process-global):
  //   telemetry (default 1): 0 disables histograms + slow-span journals
  //     (counters and stats keep recording — the kill-switch covers the
  //     new subsystem only),
  //   slow_spans (default 32): slowest-N span journal capacity,
  //   heat (default 1): 0 disables the data-plane access profiler
  //     (hot-vertex top-K + sketch feeds, fan-out attribution,
  //     cache-efficacy classes; telemetry=0 silences it too),
  //   heat_topk (default 128, max 1024): hot-key tracker capacity
  //     (resizing resets the tables).
  bool Init(const std::string& config);
  ~RemoteGraph() override;  // stops the re-discovery thread + dispatcher
  const std::string& error() const { return error_; }

  int num_shards() const { return num_shards_; }
  int num_partitions() const { return num_partitions_; }
  size_t num_replicas(int shard) const {
    return shard >= 0 && shard < num_shards_ ? pools_[shard].num_replicas()
                                             : 0;
  }
  // Liveness probe of one live shard (kPing opcode): one empty
  // request/ok-reply round trip through the full transport stack —
  // retries, deadline and wire-version negotiation included — so a
  // health checker exercises exactly the path real calls take. False
  // on transport failure / bad shard index.
  bool PingShard(int shard) const;
  // Telemetry scrape of one live shard (kStats opcode, eg_telemetry.h):
  // the shard's counters + span-timer stats + latency histograms +
  // admission gauges + slow-span journal as one JSON string — the same
  // document Telemetry::Json builds locally, so scrape-vs-local parity
  // is a field compare. False on transport failure / bad shard index.
  bool ScrapeShard(int shard, std::string* json) const;
  // Resource-gauge history of one live shard (kHistory opcode,
  // eg_blackbox.h): the shard's background-sampled RSS/fds/threads/
  // cache ring as JSON — the live twin of a postmortem's frozen
  // resource_history. False on transport failure / bad shard index.
  bool HistoryShard(int shard, std::string* json) const;
  // Data-plane heat of one live shard (kHeat opcode, eg_heat.h): the
  // shard's full hot-vertex top-K table, sketch totals, per-op ids
  // ledger and cache classes as JSON — the targeted scrape
  // scripts/heat_dump.py builds its skew report from. False on
  // transport failure / bad shard index.
  bool HeatShard(int shard, std::string* json) const;
  // ---- snapshot epochs (eg_epoch.h) ----
  // Highest serving epoch observed across shards (v4 reply stamps +
  // registry heartbeat tokens); 0 until any shard announces a flip.
  uint64_t Epoch() const override;
  // Per-shard last-observed epoch (metrics surface; 0 = none observed).
  uint64_t ShardEpoch(int shard) const {
    return shard_epoch_ && shard >= 0 && shard < num_shards_
               ? shard_epoch_[shard].load(std::memory_order_relaxed)
               : 0;
  }
  // Cache generation: bumped whenever any shard's observed epoch moves.
  // Python-side sample caches key on this the same way the native
  // feature/neighbor caches do.
  uint64_t cache_gen() const {
    return cache_gen_.load(std::memory_order_acquire);
  }
  // Ask one shard to merge a delta file and flip (kLoadDelta). The Ok
  // reply's epoch stamp doubles as the flip announcement, so this
  // client's caches invalidate before the call returns. False + *error
  // on transport failure or a shard-side load/validate/merge error.
  bool LoadDelta(int shard, const std::string& path, uint64_t* new_epoch,
                 std::string* error) const;

  // True when init fetched + parsed a placement map and ids route
  // through it (false = hash routing, the compat fallback).
  bool has_placement() const { return placement_.loaded(); }
  // Resolve the serving shard of each id through the SAME routing the
  // query paths use (placement map when loaded, hash otherwise) — the
  // observability hook scripts/heat_dump.py measures edge-cut with.
  void RouteShards(const uint64_t* ids, int n, int32_t* out) const {
    for (int i = 0; i < n; ++i) out[i] = ShardOf(ids[i]);
  }
  // ---- Async whole-step sampling (the eg_remote_sample_async ABI) ----
  // Submit one whole SampleFanout as an in-flight async op: returns a
  // slot handle >= 0, or -1 when all kMaxAsyncOps slots are busy (the
  // caller falls back to the sync path). The request arrays are COPIED;
  // the per-hop output buffers are borrowed and must stay pinned until
  // TakeAsync returns. The hop chain runs entirely on the dispatcher
  // pool: hop h+1's jobs are enqueued by hop h's completion continuation
  // (Dispatcher::SubmitDetached), never by a blocked caller thread —
  // `async_submits` / `async_inflight_peak` / `async_continuations`
  // count the pipeline's shape.
  int SampleFanoutAsync(const uint64_t* ids, int n,
                        const int32_t* etypes_flat,
                        const int32_t* etype_counts, const int32_t* counts,
                        int nhops, uint64_t default_id, uint64_t** out_ids,
                        float** out_w, int32_t** out_t) const;
  // 1 = complete, 0 = still running, -1 = bad/free slot. Non-blocking.
  int PollAsync(int slot) const;
  // Block until the op completes, then recycle its slot (0; -1 on a
  // bad/free slot). Shard failures inside the op degrade exactly like
  // the sync path: default rows + rpc_errors, and under strict= the
  // pending error the Python client polls after the take.
  int TakeAsync(int slot) const;

  // Pending strict-mode failure: copies + clears the first recorded
  // message. Empty string = no pending failure. (The fixed-shape query
  // ABI returns void, so strict failures surface through this side
  // channel — eg_remote_strict_error — which the Python client polls
  // after every remote call.)
  std::string TakeStrictError() const;

  // ---- GraphAPI ----
  int64_t NumNodes() const override { return num_nodes_; }
  int64_t NumEdges() const override { return num_edges_; }
  int32_t NodeTypeNum() const override { return node_type_num_; }
  int32_t EdgeTypeNum() const override { return edge_type_num_; }
  int32_t FeatureNum(int kind) const override {
    return kind >= 0 && kind < 6 ? fnum_[kind] : -1;
  }
  void TypeWeightSums(int kind, float* out) const override;

  void SampleNode(int count, int32_t type, uint64_t* out) const override;
  void SampleEdge(int count, int32_t type, uint64_t* out_src,
                  uint64_t* out_dst, int32_t* out_type) const override;
  void SampleNodeWithSrc(const uint64_t* src, int n, int count,
                         uint64_t* out) const override;
  void GetNodeType(const uint64_t* ids, int n, int32_t* out) const override;
  bool GetNodeWeight(const uint64_t* ids, int n, float* out) const override;

  void SampleNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                      int net, int count, uint64_t default_id,
                      uint64_t* out_ids, float* out_w,
                      int32_t* out_t) const override;
  void SampleFanout(const uint64_t* ids, int n, const int32_t* etypes_flat,
                    const int32_t* etype_counts, const int32_t* counts,
                    int nhops, uint64_t default_id, uint64_t** out_ids,
                    float** out_w, int32_t** out_t) const override;
  EGResult* GetFullNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                            int net, bool sorted) const override;
  void GetTopKNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                       int net, int k, uint64_t default_id, uint64_t* out_ids,
                       float* out_w, int32_t* out_t) const override;

  void RandomWalk(const uint64_t* ids, int n, const int32_t* etypes_flat,
                  const int32_t* etype_counts, int walk_len, float p, float q,
                  uint64_t default_id, uint64_t* out) const override;

  void GetDenseFeature(const uint64_t* ids, int n, const int32_t* fids,
                       const int32_t* dims, int nf,
                       float* out) const override;
  void GetEdgeDenseFeature(const uint64_t* src, const uint64_t* dst,
                           const int32_t* types, int n, const int32_t* fids,
                           const int32_t* dims, int nf,
                           float* out) const override;
  EGResult* GetSparseFeature(const uint64_t* ids, int n, const int32_t* fids,
                             int nf) const override;
  EGResult* GetEdgeSparseFeature(const uint64_t* src, const uint64_t* dst,
                                 const int32_t* types, int n,
                                 const int32_t* fids, int nf) const override;
  EGResult* GetBinaryFeature(const uint64_t* ids, int n, const int32_t* fids,
                             int nf) const override;
  EGResult* GetEdgeBinaryFeature(const uint64_t* src, const uint64_t* dst,
                                 const int32_t* types, int n,
                                 const int32_t* fids, int nf) const override;

 private:
  // ShardPlan lives in eg_async.h now (the async op state embeds one);
  // Build the plan (dedup when coalesce=1; identity grouping otherwise).
  // Adds `coalesced` to the ids_deduped counter.
  void BuildPlan(const uint64_t* ids, int n, ShardPlan* plan) const;
  // Identity plan routed by src id, no dedup — the edge ops key on the
  // (src, dst, type) triple, which node-id coalescing cannot collapse.
  void BuildEdgePlan(const uint64_t* src, int n, ShardPlan* plan) const;

  // One pass of discovery from the recorded source (tcp registry LIST or
  // flat-dir scan) into shard -> replica address lists. False when the
  // source is unreachable (callers keep the current pools). timeout_ms
  // bounds the registry dial: Init passes the full client timeout, the
  // background loop a short one so ~RemoteGraph never waits long for an
  // in-flight re-LIST against a blackholed registry.
  bool Discover(
      std::map<int, std::vector<std::pair<std::string, int>>>* shards,
      int timeout_ms) const;
  // Background poll: Discover + per-shard ConnPool::Update.
  void RediscoverLoop();

  // Partition routing: the placement map (when init fetched one) names
  // each id's partition explicitly — shard = map[id] % S, the inverse
  // of the service's partition-ownership rule p ≡ shard (mod S) — with
  // the hash rule as the fallback for unmapped ids and map-less
  // clusters (old servers / hash-sharded data keep working unchanged).
  inline int ShardOf(uint64_t id) const {
    if (placement_.loaded()) {
      int32_t p = placement_.Lookup(id);
      if (p >= 0)
        return static_cast<int>(static_cast<uint64_t>(p) %
                                static_cast<uint64_t>(num_shards_));
    }
    return static_cast<int>((id % static_cast<uint64_t>(num_partitions_)) %
                            static_cast<uint64_t>(num_shards_));
  }
  // rows[s] = ascending list of row indices owned by shard s (no dedup;
  // the edge ops and the fixed global-sampling ops use this form).
  void GroupByShard(const uint64_t* ids, int n,
                    std::vector<std::vector<int32_t>>* rows) const;
  // Issue req to shard; decode reply past the status byte into *reply.
  // False on transport failure or error status. `epoch` pins the
  // request to a snapshot the shard may still hold (0 = current).
  bool Call(int shard, const std::string& req, std::string* reply,
            uint64_t epoch = 0) const;
  // Flip-announcement sink (ConnPool epoch observer + registry epoch
  // tokens): raises shard_epoch_[shard] monotonically and bumps
  // cache_gen_ on every raise.
  void ObserveEpoch(int shard, uint64_t epoch) const;
  // Record a per-shard op failure: rpc_errors counter, plus the pending
  // strict-mode error under strict=1.
  void ShardFailed(int shard, const char* what) const;
  // Run fn(s) on the persistent dispatcher for every shard with rows;
  // fn returns false on failure (affected rows keep their prefilled
  // defaults; the failure is counted and, under strict=, recorded).
  void ForShards(const std::vector<std::vector<int32_t>>& rows,
                 const char* what,
                 const std::function<bool(int)>& fn) const;
  // Run chunk_fn(s, b, e) over [b, e) slices of lists[s] on the
  // dispatcher, splitting slices longer than chunk_ids_ into concurrent
  // chunks (counted in rpc_chunks when a shard's list splits).
  void RunChunked(const std::vector<std::vector<int32_t>>& lists,
                  const char* what,
                  const std::function<bool(int, int32_t, int32_t)>& chunk_fn)
      const;
  // Weighted multinomial draw of a shard per sample; type==-1 uses totals.
  void DrawShards(bool edges, int32_t type, int count, int* out) const;

  // ---- SampleNeighbor phases (shared by the sync + async paths) ----
  // The former monolithic SampleNeighbor body, split at its natural
  // barriers so the async hop chain can run the middle phase as a
  // detached dispatcher batch. Sync SampleNeighbor is now literally
  // Prep + BuildJobs + dispatcher Run + Finish over a stack NbrCall.
  // Prefill outputs, build the shard plan, split unique entries into
  // CACHED (served locally now) / PROMOTE / FETCH, size the staging.
  void NbrPrep(NbrCall* c) const;
  // One wire chunk of the FETCH (kSampleNeighbor[Uniq]) / PROMOTE
  // (kFullNeighbor + cache + local draw) lists; false on failure
  // (affected entries keep defaults). Run on dispatcher workers.
  bool NbrFetchChunk(NbrCall* c, int s, int32_t b, int32_t e) const;
  bool NbrPromoteChunk(NbrCall* c, int s, int32_t b, int32_t e) const;
  // Emit the chunked fetch + promote jobs (one combined batch — their
  // writes are disjoint) with the standard failure wrapping; counts
  // rpc_chunks exactly like RunChunked.
  void NbrBuildJobs(NbrCall* c,
                    std::vector<std::function<void()>>* jobs) const;
  // Heat fan-out attribution + scatter staged draws to the output rows.
  void NbrFinish(NbrCall* c) const;

  // ---- async hop chain ----
  // Drive op forward from its cursor: prep slices until one has wire
  // work (submit it detached with an OnSliceDone continuation and
  // return) or the fan-out completes (mark kDone, wake waiters).
  void StartSlice(AsyncSampleOp* op) const;
  // Continuation body: finish the completed slice, advance the cursor,
  // keep driving.
  void OnSliceDone(AsyncSampleOp* op) const;
  // Gather merges for variable-length sub-results (ordered re-assembly, the
  // role of the reference's MergeCallback, remote_graph.cc:241-261),
  // scattering each shard's per-unique-row segments back to every
  // original row through the plan's row maps.
  // FullNeighbor layout: u64[0]/f32[0]/i32[0] values + i32[1] row counts.
  EGResult* MergeFullNeighbor(const ShardPlan& plan,
                              std::vector<EGResult>& sub,
                              const std::vector<char>& ok, int n) const;
  // Sparse/binary features: nf slots, values in u64[k] or bytes[k], row
  // counts in i32[k].
  EGResult* MergeSlotted(const ShardPlan& plan, std::vector<EGResult>& sub,
                         const std::vector<char>& ok, int n, int nf,
                         bool u64_vals, bool byte_vals) const;

  std::string error_;
  int num_shards_ = 0, num_partitions_ = 1;
  int retries_ = 3, timeout_ms_ = 5000, quarantine_ms_ = 3000;
  int backoff_ms_ = 20, deadline_ms_ = 0;
  bool coalesce_ = true;
  bool strict_ = false;
  int chunk_ids_ = 16384;
  int dispatch_workers_ = 0;  // 0 = auto

  // discovery source recorded by Init for the periodic re-LIST
  // (empty reg_host_ AND empty reg_dir_ = static shards=, no re-discovery)
  std::string reg_host_;
  int reg_port_ = 0;
  std::string reg_dir_;
  int rediscover_ms_ = 0;
  std::thread rediscover_thread_;
  std::atomic<bool> rediscover_stop_{false};

  int64_t num_nodes_ = 0, num_edges_ = 0;
  int32_t node_type_num_ = 0, edge_type_num_ = 0;
  int32_t fnum_[6] = {0, 0, 0, 0, 0, 0};
  std::vector<float> node_wsum_agg_, edge_wsum_agg_;
  // Per-shard per-type weight sums [shard][type] and totals.
  std::vector<std::vector<float>> shard_node_wsum_, shard_edge_wsum_;

  std::vector<ConnPool> pools_;
  // Persistent scatter/gather pool (created by Init once the shard count
  // is known; jobs are leaf encode/Call/decode closures).
  std::unique_ptr<Dispatcher> dispatcher_;
  // Client-side dense-feature-row cache (safe to mutate from const query
  // methods: internally striped-locked).
  mutable FeatureCache fcache_;
  // Client-side neighbor-list cache (hot nodes' adjacency slices; same
  // striping/mutability story as fcache_).
  mutable NeighborCache ncache_;
  // id -> partition routing map fetched at init (empty = hash routing).
  PlacementMap placement_;
  bool placement_enabled_ = true;  // placement= config key
  // ---- snapshot-epoch client state (eg_epoch.h) ----
  // Last epoch each shard announced (reply stamps / registry tokens);
  // allocated by Init once num_shards_ is known. Mutable: announcements
  // arrive inside const query paths.
  mutable std::unique_ptr<std::atomic<uint64_t>[]> shard_epoch_;
  // Monotonic cache generation; every epoch raise bumps it, and all
  // cache probes/fills (native + the Python sample cache via
  // eg_remote_cache_gen) carry the bump's value.
  mutable std::atomic<uint64_t> cache_gen_{0};
  mutable std::mutex strict_mu_;        // guards strict_error_
  mutable std::string strict_error_;    // first pending strict failure
  // Async op slot pool (SampleFanoutAsync). Sized for the pipeline's
  // worst case — sampler_depth in-flight steps plus poll-side slack —
  // not for generality; a full pool answers -1 and the caller degrades
  // to sync. async_mu_ guards every op's `state` and the in-flight
  // count; the cv wakes TakeAsync waiters and the draining destructor.
  static constexpr int kMaxAsyncOps = 8;
  mutable std::mutex async_mu_;
  mutable std::condition_variable async_cv_;
  mutable AsyncSampleOp async_ops_[kMaxAsyncOps];
  mutable int async_inflight_ EG_GUARDED_BY(async_mu_) = 0;
  // Cross-shard samplers: per type a table over shards, plus totals tables.
  std::vector<PrefixTable> node_shard_by_type_, edge_shard_by_type_;
  PrefixTable node_shard_total_, edge_shard_total_;
};

}  // namespace eg

#endif  // EG_REMOTE_H_
