// Snapshot epochs: the mutable-graph refresh path over an immutable core.
//
// GraphStore is deliberately immutable after Build() — that is what makes
// every read lock-free (eg_graph.h). This layer adds mutation WITHOUT
// giving that up: a graph refresh builds a completely fresh immutable
// snapshot (base partitions merged with every delta applied so far) and
// then FLIPS the serving pointer, RCU-style. Readers pin the epoch they
// started on; a flip retires the previous epoch only after its in-flight
// readers drain (refcount, not a reader lock — the read path stays
// wait-free). The table keeps a window of kEpochKeep epochs (current +
// previous) so multi-hop operations that began just before a flip finish
// against the exact snapshot they started on; epoch N-2 is dropped at the
// flip to N, and its engine memory is freed when the last pin releases.
//
// Ledger contract (eg_stats.h): every flip counts epoch_flips; every
// retired snapshot counts epoch_drains exactly once — at the flip when
// nothing was pinned, or when its last pinned reader releases. The two
// counters together account for every dropped epoch: flips == drains
// once the system is quiescent.
//
// Delta files (`<prefix>.delta.<n>`, magic EGD1) carry the refresh
// payload: removed node ids, removed edge keys, and a standard .dat
// block stream of added/replaced records (updated feature rows are full
// replacement records — GraphStore::Build's first-occurrence-wins dedup
// makes the newest delta authoritative when stagings are merged
// newest-first). A flip rebuilds from base + ALL deltas, so the flipped
// store is bit-identical to a fresh load of the same merged inputs.
#ifndef EG_EPOCH_H_
#define EG_EPOCH_H_

#include <atomic>
#include <cstdint>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_set>
#include <vector>

#include "eg_common.h"
#include "eg_engine.h"
#include "eg_graph.h"

namespace eg {

// One immutable published snapshot. `pins` counts in-flight readers;
// `superseded` flips true when a newer epoch is published; the drain is
// counted exactly once via `drain_counted` (flip and release race to the
// exchange, whichever observes pins==0 with superseded set wins).
struct EpochSnapshot {
  uint64_t epoch = 0;
  std::shared_ptr<Engine> engine;
  std::atomic<int64_t> pins{0};
  std::atomic<bool> superseded{false};
  std::atomic<bool> drain_counted{false};
};

// RAII reader pin. Holds the snapshot alive (shared_ptr) AND holds its
// drain back (refcount) for the pin's lifetime; move-only.
class EpochPin {
 public:
  EpochPin() = default;
  explicit EpochPin(std::shared_ptr<EpochSnapshot> snap)
      : snap_(std::move(snap)) {}
  EpochPin(EpochPin&& o) noexcept : snap_(std::move(o.snap_)) {}
  EpochPin& operator=(EpochPin&& o) noexcept {
    if (this != &o) {
      Release();
      snap_ = std::move(o.snap_);
    }
    return *this;
  }
  EpochPin(const EpochPin&) = delete;
  EpochPin& operator=(const EpochPin&) = delete;
  ~EpochPin() { Release(); }

  explicit operator bool() const { return snap_ != nullptr; }
  Engine* engine() const { return snap_ ? snap_->engine.get() : nullptr; }
  uint64_t epoch() const { return snap_ ? snap_->epoch : 0; }

 private:
  void Release();

  std::shared_ptr<EpochSnapshot> snap_;
};

// The per-shard epoch table. Pin() is the only read entry point; Flip()
// the only publish. current() is a lock-free peek for gauges and reply
// stamping.
class EpochTable {
 public:
  // Epochs kept pinnable: current + previous. In-flight ops started on
  // the previous epoch finish there; anything older is already drained
  // by construction (it was superseded one whole flip ago).
  static constexpr int kEpochKeep = 2;

  // Install the initial snapshot (epoch `epoch`, usually 0 for a plain
  // base load). Not a flip: nothing is counted, nothing superseded.
  void Reset(std::shared_ptr<Engine> engine, uint64_t epoch = 0);

  uint64_t current() const {
    return current_.load(std::memory_order_acquire);
  }

  // Pin a snapshot: `requested` = 0 pins current; nonzero pins that
  // epoch IF the table still holds it, else falls back to current (the
  // wire contract — a too-old pin gets the freshest answer rather than
  // an error). Returns an empty pin only before Reset().
  EpochPin Pin(uint64_t requested = 0) const;

  // Publish `next` as epoch current+1, supersede the previous epoch
  // (counting its drain immediately when nothing is pinned), and drop
  // epoch N-2 from the keep window. Returns the new epoch.
  uint64_t Flip(std::shared_ptr<Engine> next);

 private:
  mutable std::mutex mu_;
  // Ascending by epoch; back() is current. Never more than kEpochKeep.
  std::vector<std::shared_ptr<EpochSnapshot>> held_ EG_GUARDED_BY(mu_);
  std::atomic<uint64_t> current_{0};
};

// ---- delta files ----

// Parsed `<prefix>.delta.<n>` file. Layout (all WireReader-framed,
// little-endian, counts bounded by remaining() before allocation):
//   "EGD1" [u32 version=1] [u64 seq]
//   [Arr u64 removed_nodes]
//   [Arr u64 rme_src] [Arr u64 rme_dst] [Arr i32 rme_type]
//   [Str dat_blob]            -- standard .dat block stream of
//                                added/replaced node + edge records
struct DeltaFile {
  uint64_t seq = 0;
  std::vector<uint64_t> removed_nodes;
  std::vector<uint64_t> rme_src, rme_dst;  // removed edge keys
  std::vector<int32_t> rme_type;
  std::string dat_blob;
  Staging staged;  // dat_blob parsed; reused (copied) every flip

  // Parse + stage. False + *error on bad magic/version, truncation,
  // trailing bytes, mismatched removed-edge columns, or a dat_blob
  // parse failure.
  bool Parse(const char* data, size_t size, std::string* error);
  // Reject contradictory edits: duplicate node records, duplicate edge
  // records, duplicate removal entries, a node both removed and
  // present, an edge both removed and re-emitted. Run after Parse and
  // BEFORE shard filtering (contradictions are authoring bugs — every
  // shard must refuse the file identically).
  bool Validate(std::string* error) const;
};

// Which delta records a shard keeps: nodes it owns (and edge records
// whose src it owns), mirroring the partition-file routing of
// Engine::Load — partition p = id mod num_partitions, shard owns
// p ≡ shard_idx (mod shard_num).
struct ShardOwnership {
  int shard_idx = 0;
  int shard_num = 1;
  int num_partitions = 1;

  bool OwnsNode(uint64_t id) const {
    if (shard_num <= 1) return true;
    uint64_t p = num_partitions > 0
                     ? id % static_cast<uint64_t>(num_partitions)
                     : id;
    return p % static_cast<uint64_t>(shard_num) ==
           static_cast<uint64_t>(shard_idx);
  }
};

// Drop added records the shard does not own (node records by id, edge
// records by src). Removal sets are deliberately NOT filtered: removals
// are cheap id sets, and an edge record referencing a node removed on
// ANOTHER shard must still be dropped here.
bool FilterDeltaToShard(DeltaFile* d, const ShardOwnership& own,
                        std::string* error);

// Drop removed nodes (record + feature slices), removed adjacency
// entries (the (src, nbr, type) keys in rm_edges, with group counts and
// weights adjusted), and removed/endpoint-removed edge records from a
// staging. Adjacency entries pointing AT a removed node in other nodes'
// groups are left in place — they resolve like any missing-node
// neighbor. False + *error when the staging's internal shapes are
// inconsistent (slice counts overrun the value arrays).
bool FilterStaging(
    Staging* s, const std::unordered_set<uint64_t>& rm_nodes,
    const std::unordered_set<EdgeKey, EdgeKeyHash>& rm_edges,
    std::string* error);

// Build one fresh Engine from base partition files merged with every
// delta (ascending seq, already Validated and shard-filtered). Stagings
// are ordered newest-delta-first then base, each filtered by the
// removal sets of strictly NEWER deltas — with Build's first-wins
// dedup, the result is bit-identical to a fresh load of the same
// merged inputs. Base files parse in a strided worker pool.
bool BuildMergedEngine(std::vector<std::string> base_files,
                       const std::vector<DeltaFile>& deltas,
                       std::shared_ptr<Engine>* out, std::string* error);

// Local (embedded) graph path: parse + validate `delta_paths`, merge
// them over `base_files`, and adopt the result into `eng` in place (the
// C-ABI handle identity stays stable). Epoch ends at the delta count.
bool LoadEngineWithDeltas(Engine* eng,
                          std::vector<std::string> base_files,
                          const std::vector<std::string>& delta_paths,
                          std::string* error);

}  // namespace eg

#endif  // EG_EPOCH_H_
