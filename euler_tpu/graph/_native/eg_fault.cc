#include "eg_fault.h"

#include <signal.h>

#include <chrono>
#include <cstdlib>
#include <thread>

#include "eg_stats.h"

namespace eg {

namespace {

// Exception-free number parsing: a malformed spec must land in error_,
// never throw through Configure's C-ABI callers.
bool ParseDouble(const std::string& s, double* out) {
  if (s.empty()) return false;
  char* end = nullptr;
  *out = std::strtod(s.c_str(), &end);
  return end == s.c_str() + s.size();
}

bool ParseI64(const std::string& s, int64_t* out) {
  if (s.empty()) return false;
  char* end = nullptr;
  *out = std::strtoll(s.c_str(), &end, 10);
  return end == s.c_str() + s.size();
}

int FaultIdOf(const std::string& name) {
  for (int i = 0; i < kFaultIdCount; ++i)
    if (name == kFaultNames[i]) return i;
  return -1;
}

}  // namespace

bool FaultInjector::Configure(const std::string& spec, uint64_t seed) {
  Point fresh[kFaultIdCount];
  bool any = false;
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t comma = spec.find(',', pos);
    std::string item = spec.substr(
        pos, comma == std::string::npos ? std::string::npos : comma - pos);
    pos = comma == std::string::npos ? spec.size() : comma + 1;
    if (item.empty()) continue;

    size_t colon = item.find(':');
    if (colon == std::string::npos) {
      error_ = "fault spec '" + item + "' wants <point>:<action>@<param>";
      return false;
    }
    int id = FaultIdOf(item.substr(0, colon));
    if (id < 0) {
      error_ = "unknown failpoint '" + item.substr(0, colon) + "'";
      return false;
    }
    if (fresh[id].configured) {
      error_ = "duplicate failpoint '" + item.substr(0, colon) + "'";
      return false;
    }
    std::string action = item.substr(colon + 1);
    int64_t limit = -1;
    size_t hash = action.find('#');
    if (hash != std::string::npos) {
      if (!ParseI64(action.substr(hash + 1), &limit) || limit < 0) {
        error_ = "bad fire limit in '" + item + "'";
        return false;
      }
      action = action.substr(0, hash);
    }
    Point p;
    p.limit = limit;
    if (action.compare(0, 4, "err@") == 0) {
      p.err = true;
      if (!ParseDouble(action.substr(4), &p.prob) || p.prob <= 0.0 ||
          p.prob > 1.0) {
        error_ = "bad err probability in '" + item + "' (want (0,1])";
        return false;
      }
    } else if (action.compare(0, 6, "delay@") == 0) {
      std::string params = action.substr(6);
      size_t at = params.find('@');
      std::string ms_s = at == std::string::npos ? params
                                                 : params.substr(0, at);
      double ms = 0;
      if (!ParseDouble(ms_s, &ms) || ms < 0) {
        error_ = "bad delay ms in '" + item + "'";
        return false;
      }
      p.delay_ms = static_cast<int>(ms);
      if (at != std::string::npos) {
        if (!ParseDouble(params.substr(at + 1), &p.prob) || p.prob <= 0.0 ||
            p.prob > 1.0) {
          error_ = "bad delay probability in '" + item + "' (want (0,1])";
          return false;
        }
      }
    } else {
      error_ = "unknown fault action in '" + item + "' (want err@<p> or "
               "delay@<ms>[@<p>])";
      return false;
    }
    // Per-point stream: the decision sequence at a point depends only on
    // (seed, point, hit index), never on other points' traffic.
    p.rng = Rng(seed ^ (0x9E3779B97F4A7C15ULL * (id + 1)));
    p.configured = true;
    fresh[id] = p;
    any = true;
  }
  {
    std::lock_guard<std::mutex> l(mu_);
    for (int i = 0; i < kFaultIdCount; ++i) points_[i] = fresh[i];
  }
  enabled_.store(any, std::memory_order_relaxed);
  return true;
}

void FaultInjector::Clear() {
  enabled_.store(false, std::memory_order_relaxed);
  std::lock_guard<std::mutex> l(mu_);
  for (auto& p : points_) p = Point();
}

bool FaultInjector::Fire(FaultId id) {
  int delay_ms = 0;
  bool fail = false;
  {
    std::lock_guard<std::mutex> l(mu_);
    Point& p = points_[id];
    if (!p.configured) return false;
    if (p.limit >= 0 && p.fired >= p.limit) return false;
    if (p.prob < 1.0 && p.rng.NextDouble() >= p.prob) return false;
    ++p.fired;
    delay_ms = p.delay_ms;
    fail = p.err;
  }
  if (id == kFaultCrash) {
    // Postmortem drill (FAULTS.md): the action params pick the signal —
    // err@p raises SIGSEGV, delay@SIG reuses the ms slot as the signal
    // number (6 = SIGABRT). The ledger entry lands BEFORE the raise so
    // the blackbox signal handler's counter snapshot includes this fire
    // (the client audits the dead shard's postmortem against it).
    Counters::Global().Add(kCtrCrash);
    int sig = fail ? SIGSEGV : (delay_ms > 0 ? delay_ms : SIGABRT);
    ::raise(sig);
    return true;  // unreachable for fatal dispositions; honest otherwise
  }
  if (delay_ms > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
  return fail;
}

uint64_t FaultInjector::injected(FaultId id) const {
  std::lock_guard<std::mutex> l(mu_);
  return static_cast<uint64_t>(points_[id].fired);
}

void FaultInjector::SnapshotInjected(uint64_t* out) const {
  std::lock_guard<std::mutex> l(mu_);
  for (int i = 0; i < kFaultIdCount; ++i)
    out[i] = static_cast<uint64_t>(points_[i].fired);
}

}  // namespace eg
