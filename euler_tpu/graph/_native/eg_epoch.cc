#include "eg_epoch.h"

#include <algorithm>
#include <cstring>
#include <fstream>
#include <functional>
#include <thread>

#include "eg_stats.h"
#include "eg_wire.h"

namespace eg {

namespace {

bool ReadWholeFile(const std::string& path, std::string* out) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return false;
  std::streamsize size = f.tellg();
  f.seekg(0);
  // eg-lint: allow(wire-count-alloc) sized by tellg of an already-open
  // local file — the bytes exist on disk; bad_alloc surfaces to the
  // caller as a load error
  out->resize(static_cast<size_t>(size));
  return static_cast<bool>(f.read(out->data(), size));
}

// Count the drain for a superseded snapshot exactly once. Flip (when it
// observes pins == 0) and the last pin release race to the exchange;
// whichever wins does the counting.
void MaybeCountDrain(EpochSnapshot* snap) {
  if (snap->superseded.load(std::memory_order_acquire) &&
      snap->pins.load(std::memory_order_acquire) == 0 &&
      !snap->drain_counted.exchange(true, std::memory_order_acq_rel))
    Counters::Global().Add(kCtrEpochDrain);
}

// The shared cursor walk: rebuild a staging keeping only records the
// predicates accept. Staging arrays are slice-concatenated with counts
// living in *_cnt / grp_counts — a drop must skip the record AND its
// slices in every parallel array, so the walk mirrors
// GraphStore::Build's cursor arithmetic exactly. Every slice is
// bounds-checked against its value array before it is read (ptr-arith
// discipline: counts come from parsed input, never trust them to add
// up).
bool FilterStagingImpl(
    Staging* s, const std::function<bool(uint64_t)>& drop_node,
    const std::function<bool(uint64_t, uint64_t, int32_t)>& drop_adj,
    const std::function<bool(uint64_t, uint64_t, int32_t)>& drop_edge,
    std::string* error) {
  const int32_t T = std::max(s->edge_type_num, 0);
  const int32_t NU = std::max(s->nf_u64_num, 0);
  const int32_t NF = std::max(s->nf_f32_num, 0);
  const int32_t NB = std::max(s->nf_bin_num, 0);
  const int32_t EU = std::max(s->ef_u64_num, 0);
  const int32_t EF = std::max(s->ef_f32_num, 0);
  const int32_t EB = std::max(s->ef_bin_num, 0);
  const size_t nn = s->node_ids.size();
  const size_t ne = s->e_src.size();

  if (s->node_types.size() != nn || s->node_weights.size() != nn ||
      s->grp_counts.size() != nn * static_cast<size_t>(T) ||
      s->nf_u64_cnt.size() != nn * static_cast<size_t>(NU) ||
      s->nf_f32_cnt.size() != nn * static_cast<size_t>(NF) ||
      s->nf_bin_cnt.size() != nn * static_cast<size_t>(NB) ||
      s->e_dst.size() != ne || s->e_type.size() != ne ||
      s->e_w.size() != ne ||
      s->ef_u64_cnt.size() != ne * static_cast<size_t>(EU) ||
      s->ef_f32_cnt.size() != ne * static_cast<size_t>(EF) ||
      s->ef_bin_cnt.size() != ne * static_cast<size_t>(EB)) {
    *error = "inconsistent staging shapes";
    return false;
  }

  Staging out;
  out.edge_type_num = s->edge_type_num;
  out.nf_u64_num = s->nf_u64_num;
  out.nf_f32_num = s->nf_f32_num;
  out.nf_bin_num = s->nf_bin_num;
  out.ef_u64_num = s->ef_u64_num;
  out.ef_f32_num = s->ef_f32_num;
  out.ef_bin_num = s->ef_bin_num;

  size_t nbr_cur = 0, u64_cur = 0, f32_cur = 0, bin_cur = 0;
  for (size_t i = 0; i < nn; ++i) {
    size_t nbr_n = 0, u64_n = 0, f32_n = 0, bin_n = 0;
    for (int32_t t = 0; t < T; ++t) {
      int32_t c = s->grp_counts[i * T + t];
      if (c < 0) {
        *error = "negative group count in staging";
        return false;
      }
      nbr_n += static_cast<size_t>(c);
    }
    for (int32_t k = 0; k < NU; ++k)
      u64_n += static_cast<size_t>(s->nf_u64_cnt[i * NU + k]);
    for (int32_t k = 0; k < NF; ++k)
      f32_n += static_cast<size_t>(s->nf_f32_cnt[i * NF + k]);
    for (int32_t k = 0; k < NB; ++k)
      bin_n += static_cast<size_t>(s->nf_bin_cnt[i * NB + k]);
    if (nbr_cur + nbr_n > s->nbr_ids.size() ||
        nbr_cur + nbr_n > s->nbr_w.size() ||
        u64_cur + u64_n > s->nf_u64_val.size() ||
        f32_cur + f32_n > s->nf_f32_val.size() ||
        bin_cur + bin_n > s->nf_bin_val.size()) {
      *error = "node slice counts overrun staging arrays";
      return false;
    }

    uint64_t id = s->node_ids[i];
    if (!drop_node(id)) {
      out.node_ids.push_back(id);
      out.node_types.push_back(s->node_types[i]);
      out.node_weights.push_back(s->node_weights[i]);
      size_t cur = nbr_cur;
      for (int32_t t = 0; t < T; ++t) {
        int32_t c = s->grp_counts[i * T + t];
        int32_t kept = 0;
        float wsum = 0.f;
        for (int32_t j = 0; j < c; ++j) {
          uint64_t nbr = s->nbr_ids[cur + static_cast<size_t>(j)];
          float w = s->nbr_w[cur + static_cast<size_t>(j)];
          if (drop_adj(id, nbr, t)) continue;
          out.nbr_ids.push_back(nbr);
          out.nbr_w.push_back(w);
          ++kept;
          wsum += w;
        }
        cur += static_cast<size_t>(c);
        out.grp_counts.push_back(kept);
        out.grp_weights.push_back(wsum);
      }
      size_t c = u64_cur;
      for (int32_t k = 0; k < NU; ++k) {
        size_t n = static_cast<size_t>(s->nf_u64_cnt[i * NU + k]);
        out.nf_u64_cnt.push_back(s->nf_u64_cnt[i * NU + k]);
        out.nf_u64_val.insert(out.nf_u64_val.end(),
                              s->nf_u64_val.begin() + c,
                              s->nf_u64_val.begin() + c + n);
        c += n;
      }
      c = f32_cur;
      for (int32_t k = 0; k < NF; ++k) {
        size_t n = static_cast<size_t>(s->nf_f32_cnt[i * NF + k]);
        out.nf_f32_cnt.push_back(s->nf_f32_cnt[i * NF + k]);
        out.nf_f32_val.insert(out.nf_f32_val.end(),
                              s->nf_f32_val.begin() + c,
                              s->nf_f32_val.begin() + c + n);
        c += n;
      }
      c = bin_cur;
      for (int32_t k = 0; k < NB; ++k) {
        size_t n = static_cast<size_t>(s->nf_bin_cnt[i * NB + k]);
        out.nf_bin_cnt.push_back(s->nf_bin_cnt[i * NB + k]);
        out.nf_bin_val.append(s->nf_bin_val, c, n);
        c += n;
      }
    }
    nbr_cur += nbr_n;
    u64_cur += u64_n;
    f32_cur += f32_n;
    bin_cur += bin_n;
  }

  size_t eu_cur = 0, ef_cur = 0, eb_cur = 0;
  for (size_t i = 0; i < ne; ++i) {
    size_t u64_n = 0, f32_n = 0, bin_n = 0;
    for (int32_t k = 0; k < EU; ++k)
      u64_n += static_cast<size_t>(s->ef_u64_cnt[i * EU + k]);
    for (int32_t k = 0; k < EF; ++k)
      f32_n += static_cast<size_t>(s->ef_f32_cnt[i * EF + k]);
    for (int32_t k = 0; k < EB; ++k)
      bin_n += static_cast<size_t>(s->ef_bin_cnt[i * EB + k]);
    if (eu_cur + u64_n > s->ef_u64_val.size() ||
        ef_cur + f32_n > s->ef_f32_val.size() ||
        eb_cur + bin_n > s->ef_bin_val.size()) {
      *error = "edge slice counts overrun staging arrays";
      return false;
    }

    if (!drop_edge(s->e_src[i], s->e_dst[i], s->e_type[i])) {
      out.e_src.push_back(s->e_src[i]);
      out.e_dst.push_back(s->e_dst[i]);
      out.e_type.push_back(s->e_type[i]);
      out.e_w.push_back(s->e_w[i]);
      size_t c = eu_cur;
      for (int32_t k = 0; k < EU; ++k) {
        size_t n = static_cast<size_t>(s->ef_u64_cnt[i * EU + k]);
        out.ef_u64_cnt.push_back(s->ef_u64_cnt[i * EU + k]);
        out.ef_u64_val.insert(out.ef_u64_val.end(),
                              s->ef_u64_val.begin() + c,
                              s->ef_u64_val.begin() + c + n);
        c += n;
      }
      c = ef_cur;
      for (int32_t k = 0; k < EF; ++k) {
        size_t n = static_cast<size_t>(s->ef_f32_cnt[i * EF + k]);
        out.ef_f32_cnt.push_back(s->ef_f32_cnt[i * EF + k]);
        out.ef_f32_val.insert(out.ef_f32_val.end(),
                              s->ef_f32_val.begin() + c,
                              s->ef_f32_val.begin() + c + n);
        c += n;
      }
      c = eb_cur;
      for (int32_t k = 0; k < EB; ++k) {
        size_t n = static_cast<size_t>(s->ef_bin_cnt[i * EB + k]);
        out.ef_bin_cnt.push_back(s->ef_bin_cnt[i * EB + k]);
        out.ef_bin_val.append(s->ef_bin_val, c, n);
        c += n;
      }
    }
    eu_cur += u64_n;
    ef_cur += f32_n;
    eb_cur += bin_n;
  }

  *s = std::move(out);
  return true;
}

}  // namespace

// ---- EpochPin / EpochTable ----

void EpochPin::Release() {
  if (!snap_) return;
  if (snap_->pins.fetch_sub(1, std::memory_order_acq_rel) == 1)
    MaybeCountDrain(snap_.get());
  snap_.reset();
}

void EpochTable::Reset(std::shared_ptr<Engine> engine, uint64_t epoch) {
  auto snap = std::make_shared<EpochSnapshot>();
  snap->epoch = epoch;
  snap->engine = std::move(engine);
  std::lock_guard<std::mutex> l(mu_);
  held_.clear();
  held_.push_back(std::move(snap));
  current_.store(epoch, std::memory_order_release);
}

EpochPin EpochTable::Pin(uint64_t requested) const {
  std::lock_guard<std::mutex> l(mu_);
  if (held_.empty()) return EpochPin();
  std::shared_ptr<EpochSnapshot> snap;
  if (requested != 0) {
    for (const auto& h : held_)
      if (h->epoch == requested) {
        snap = h;
        break;
      }
  }
  if (!snap) snap = held_.back();
  // Under mu_ the snapshot cannot be superseded-and-drain-checked
  // concurrently with this increment (Flip also takes mu_), so a pin
  // never resurrects a snapshot whose drain was already counted — it
  // simply rides the still-held window.
  snap->pins.fetch_add(1, std::memory_order_acq_rel);
  return EpochPin(std::move(snap));
}

uint64_t EpochTable::Flip(std::shared_ptr<Engine> next) {
  std::lock_guard<std::mutex> l(mu_);
  uint64_t e = current_.load(std::memory_order_relaxed) + 1;
  auto snap = std::make_shared<EpochSnapshot>();
  snap->epoch = e;
  snap->engine = std::move(next);
  if (!held_.empty()) {
    EpochSnapshot* prev = held_.back().get();
    prev->superseded.store(true, std::memory_order_release);
    MaybeCountDrain(prev);
  }
  held_.push_back(std::move(snap));
  // Drop epoch N-2: pinned readers (if any) still hold it alive via
  // their shared_ptr; its drain is counted by the last release.
  while (held_.size() > static_cast<size_t>(kEpochKeep))
    held_.erase(held_.begin());
  current_.store(e, std::memory_order_release);
  Counters::Global().Add(kCtrEpochFlip);
  return e;
}

// ---- delta files ----

bool DeltaFile::Parse(const char* data, size_t size, std::string* error) {
  if (size < 8 || std::memcmp(data, "EGD1", 4) != 0) {
    *error = "bad delta magic (want EGD1)";
    return false;
  }
  WireReader r(data + 4, size - 4);
  uint32_t version = r.Pod<uint32_t>();
  if (version != 1) {
    *error = "unsupported delta version " + std::to_string(version);
    return false;
  }
  seq = r.U64();
  r.Vec(&removed_nodes);
  r.Vec(&rme_src);
  r.Vec(&rme_dst);
  r.Vec(&rme_type);
  dat_blob = r.Str();
  if (!r.ok()) {
    *error = "truncated delta file";
    return false;
  }
  if (r.remaining() != 0) {
    *error = "trailing bytes after delta payload";
    return false;
  }
  if (rme_src.size() != rme_dst.size() ||
      rme_src.size() != rme_type.size()) {
    *error = "removed-edge columns disagree in length";
    return false;
  }
  staged = Staging();
  if (!dat_blob.empty() &&
      !staged.ParseFile(dat_blob.data(), dat_blob.size())) {
    *error = staged.error.empty() ? "delta dat blob parse failure"
                                  : staged.error;
    return false;
  }
  return true;
}

bool DeltaFile::Validate(std::string* error) const {
  std::unordered_set<uint64_t> rm_nodes;
  for (uint64_t id : removed_nodes)
    if (!rm_nodes.insert(id).second) {
      *error = "duplicate removed node " + std::to_string(id);
      return false;
    }
  std::unordered_set<EdgeKey, EdgeKeyHash> rm_edges;
  for (size_t i = 0; i < rme_src.size(); ++i)
    if (!rm_edges.insert(EdgeKey{rme_src[i], rme_dst[i], rme_type[i]})
             .second) {
      *error = "duplicate removed edge (" + std::to_string(rme_src[i]) +
               ", " + std::to_string(rme_dst[i]) + ", " +
               std::to_string(rme_type[i]) + ")";
      return false;
    }
  std::unordered_set<uint64_t> seen_nodes;
  for (uint64_t id : staged.node_ids) {
    if (!seen_nodes.insert(id).second) {
      *error = "duplicate node record " + std::to_string(id) +
               " within one delta";
      return false;
    }
    if (rm_nodes.count(id)) {
      *error = "node " + std::to_string(id) +
               " both removed and present in one delta";
      return false;
    }
  }
  std::unordered_set<EdgeKey, EdgeKeyHash> seen_edges;
  for (size_t i = 0; i < staged.e_src.size(); ++i) {
    EdgeKey k{staged.e_src[i], staged.e_dst[i], staged.e_type[i]};
    if (!seen_edges.insert(k).second) {
      *error = "duplicate edge record (" + std::to_string(k.src) + ", " +
               std::to_string(k.dst) + ", " + std::to_string(k.type) +
               ") within one delta";
      return false;
    }
    if (rm_edges.count(k)) {
      *error = "edge (" + std::to_string(k.src) + ", " +
               std::to_string(k.dst) + ", " + std::to_string(k.type) +
               ") both removed and re-emitted in one delta";
      return false;
    }
  }
  return true;
}

bool FilterDeltaToShard(DeltaFile* d, const ShardOwnership& own,
                        std::string* error) {
  if (own.shard_num <= 1) return true;
  return FilterStagingImpl(
      &d->staged, [&own](uint64_t id) { return !own.OwnsNode(id); },
      [](uint64_t, uint64_t, int32_t) { return false; },
      [&own](uint64_t src, uint64_t, int32_t) {
        return !own.OwnsNode(src);
      },
      error);
}

bool FilterStaging(
    Staging* s, const std::unordered_set<uint64_t>& rm_nodes,
    const std::unordered_set<EdgeKey, EdgeKeyHash>& rm_edges,
    std::string* error) {
  if (rm_nodes.empty() && rm_edges.empty()) return true;
  return FilterStagingImpl(
      s, [&](uint64_t id) { return rm_nodes.count(id) != 0; },
      [&](uint64_t src, uint64_t dst, int32_t t) {
        return !rm_edges.empty() &&
               rm_edges.count(EdgeKey{src, dst, t}) != 0;
      },
      [&](uint64_t src, uint64_t dst, int32_t t) {
        return rm_nodes.count(src) != 0 || rm_nodes.count(dst) != 0 ||
               (!rm_edges.empty() &&
                rm_edges.count(EdgeKey{src, dst, t}) != 0);
      },
      error);
}

bool BuildMergedEngine(std::vector<std::string> base_files,
                       const std::vector<DeltaFile>& deltas,
                       std::shared_ptr<Engine>* out, std::string* error) {
  std::sort(base_files.begin(), base_files.end());
  const size_t nd = deltas.size();
  const size_t nb = base_files.size();
  for (size_t i = 1; i < nd; ++i)
    if (deltas[i].seq <= deltas[i - 1].seq) {
      *error = "delta seqs not strictly ascending";
      return false;
    }

  // parts order: newest delta first, then older deltas, then base —
  // Build's first-occurrence-wins dedup makes the newest record
  // authoritative. Each level is filtered by the removal sets of
  // strictly NEWER deltas (absorbed as we walk downward), so a record
  // removed in delta k never resurfaces from delta k-1 or base.
  std::vector<Staging> parts(nd + nb);
  std::unordered_set<uint64_t> rm_nodes;
  std::unordered_set<EdgeKey, EdgeKeyHash> rm_edges;
  for (size_t k = nd; k-- > 0;) {
    Staging s = deltas[k].staged;  // copy: the DeltaFile outlives flips
    if (!FilterStaging(&s, rm_nodes, rm_edges, error)) return false;
    parts[nd - 1 - k] = std::move(s);
    rm_nodes.insert(deltas[k].removed_nodes.begin(),
                    deltas[k].removed_nodes.end());
    for (size_t j = 0; j < deltas[k].rme_src.size(); ++j)
      rm_edges.insert(EdgeKey{deltas[k].rme_src[j], deltas[k].rme_dst[j],
                              deltas[k].rme_type[j]});
  }

  // Base partitions parse in a strided worker pool (the flip path must
  // not be slower than a cold load of the same data); rm sets are
  // read-only from here, so the post-parse filter runs in-thread too.
  std::vector<std::string> errs(nb);
  unsigned nthreads = std::min<unsigned>(
      std::thread::hardware_concurrency(), static_cast<unsigned>(nb));
  nthreads = std::max(1u, nthreads);
  std::vector<std::thread> threads;
  for (unsigned w = 0; w < nthreads && nb; ++w) {
    threads.emplace_back([&, w]() {
      for (size_t i = w; i < nb; i += nthreads) {
        try {
          std::string data;
          if (!ReadWholeFile(base_files[i], &data)) {
            errs[i] = "cannot read " + base_files[i];
            continue;
          }
          Staging* part = &parts[nd + i];
          if (!part->ParseFile(data.data(), data.size())) {
            errs[i] = part->error.empty()
                          ? "parse failure in " + base_files[i]
                          : part->error;
            continue;
          }
          if (!FilterStaging(part, rm_nodes, rm_edges, &errs[i]))
            continue;
        } catch (const std::exception& ex) {
          // an exception escaping a worker thread is std::terminate —
          // surface it like any other per-file error instead
          errs[i] = base_files[i] + " threw: " + ex.what();
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  for (const auto& e : errs)
    if (!e.empty()) {
      *error = e;
      return false;
    }

  auto eng = std::make_shared<Engine>();
  if (!eng->BuildFromStagings(&parts)) {
    *error = eng->error();
    return false;
  }
  eng->set_source_files(std::move(base_files));
  *out = std::move(eng);
  return true;
}

bool LoadEngineWithDeltas(Engine* eng,
                          std::vector<std::string> base_files,
                          const std::vector<std::string>& delta_paths,
                          std::string* error) {
  std::vector<DeltaFile> deltas(delta_paths.size());
  for (size_t i = 0; i < delta_paths.size(); ++i) {
    std::string data;
    if (!ReadWholeFile(delta_paths[i], &data)) {
      *error = "cannot read delta " + delta_paths[i];
      return false;
    }
    if (!deltas[i].Parse(data.data(), data.size(), error) ||
        !deltas[i].Validate(error)) {
      *error = delta_paths[i] + ": " + *error;
      return false;
    }
  }
  // Deltas apply in seq order regardless of the path order given.
  std::sort(deltas.begin(), deltas.end(),
            [](const DeltaFile& a, const DeltaFile& b) {
              return a.seq < b.seq;
            });
  std::shared_ptr<Engine> merged;
  if (!BuildMergedEngine(std::move(base_files), deltas, &merged, error))
    return false;
  merged->set_epoch(static_cast<uint64_t>(deltas.size()));
  eng->Adopt(std::move(*merged));
  return true;
}

}  // namespace eg
