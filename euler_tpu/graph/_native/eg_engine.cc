#include "eg_engine.h"

#include <dirent.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <thread>

namespace eg {

namespace {

// Parse a trailing "_<p>.dat" partition index; -1 when absent.
int PartitionIndex(const std::string& name) {
  if (name.size() < 5 || name.compare(name.size() - 4, 4, ".dat") != 0)
    return -1;
  size_t us = name.rfind('_');
  if (us == std::string::npos) return -1;
  size_t start = us + 1, end = name.size() - 4;
  if (start >= end) return -1;
  int p = 0;
  for (size_t i = start; i < end; ++i) {
    if (name[i] < '0' || name[i] > '9') return -1;
    p = p * 10 + (name[i] - '0');
  }
  return p;
}

bool ReadWholeFile(const std::string& path, std::string* out) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return false;
  std::streamsize size = f.tellg();
  f.seekg(0);
  // eg-lint: allow(wire-count-alloc) sized by tellg of an already-open
  // local file — the bytes exist on disk; bad_alloc surfaces via eg_load
  out->resize(static_cast<size_t>(size));
  return static_cast<bool>(f.read(out->data(), size));
}

}  // namespace

bool Engine::Load(const std::string& dir, int shard_idx, int shard_num) {
  DIR* d = opendir(dir.c_str());
  if (!d) {
    error_ = "cannot open directory: " + dir;
    return false;
  }
  std::vector<std::string> files;
  while (dirent* ent = readdir(d)) {
    std::string name = ent->d_name;
    if (name.size() < 4 || name.compare(name.size() - 4, 4, ".dat") != 0)
      continue;
    int p = PartitionIndex(name);
    if (p < 0) p = 0;
    if (shard_num > 1 && p % shard_num != shard_idx) continue;
    files.push_back(dir + "/" + name);
  }
  closedir(d);
  if (files.empty()) {
    error_ = "no .dat partitions for shard in " + dir;
    return false;
  }
  return LoadFiles(std::move(files));
}

bool Engine::ParseStagings(
    const std::vector<std::string>& labels,
    const std::function<void(int, Staging*, std::string*)>& parse_one) {
  // One staging per item so the merged order is deterministic regardless
  // of thread scheduling (reference loads files across threads too,
  // euler/core/graph_builder.cc:91-120).
  int n = static_cast<int>(labels.size());
  std::vector<Staging> parts(n);
  std::vector<std::string> errors(n);
  unsigned nthreads = std::min<unsigned>(
      std::thread::hardware_concurrency(), static_cast<unsigned>(n));
  nthreads = std::max(1u, nthreads);
  std::vector<std::thread> threads;
  for (unsigned w = 0; w < nthreads; ++w) {
    threads.emplace_back([&, w]() {
      for (int i = w; i < n; i += static_cast<int>(nthreads)) {
        try {
          parse_one(i, &parts[i], &errors[i]);
        } catch (const std::exception& ex) {
          // an exception escaping a worker thread is std::terminate —
          // surface it like any other per-item error instead
          errors[i] = labels[i] + " threw: " + ex.what();
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  for (auto& e : errors)
    if (!e.empty()) {
      error_ = e;
      return false;
    }
  return store_.Build(&parts, &error_);
}

bool Engine::LoadFiles(std::vector<std::string> files) {
  std::sort(files.begin(), files.end());
  source_files_ = files;  // what a later delta merge rebuilds from
  // Bytes live only inside one worker iteration — only ~nthreads raw
  // files are in memory at once (the property the streamed path trades
  // away; see remote_fs.read_directory's RAM note).
  return ParseStagings(
      files, [&](int i, Staging* part, std::string* err) {
        std::string data;
        if (!ReadWholeFile(files[i], &data)) {
          *err = "cannot read " + files[i];
          return;
        }
        if (!part->ParseFile(data.data(), data.size()) &&
            part->error.empty())
          part->error = "parse failure in " + files[i];
      });
}

bool Engine::LoadBuffers(const char* const* bufs, const size_t* lens,
                         const char* const* names, int n) {
  if (n <= 0) {
    error_ = "no partition buffers";
    return false;
  }
  // name-sorted merge order, like LoadFiles' sort of paths — the built
  // store must not depend on the order the fetches completed in
  std::vector<int> order(n);
  for (int i = 0; i < n; ++i) order[i] = i;
  std::sort(order.begin(), order.end(), [&](int a, int b) {
    return std::strcmp(names[a], names[b]) < 0;
  });
  std::vector<std::string> labels(n);
  for (int i = 0; i < n; ++i) labels[i] = names[order[i]];
  return ParseStagings(
      labels, [&](int i, Staging* part, std::string* err) {
        int src = order[i];
        if (!part->ParseFile(bufs[src], lens[src]))
          // streamed buffers have no path in the Staging error —
          // attribute the partition name here
          *err = labels[i] + ": " +
                 (part->error.empty() ? "parse failure" : part->error);
      });
}

void Engine::SampleNode(int count, int32_t type, uint64_t* out) const {
  Rng& rng = ThreadRng();
  for (int i = 0; i < count; ++i) out[i] = store_.SampleNode(type, rng);
}

void Engine::SampleEdge(int count, int32_t type, uint64_t* out_src,
                        uint64_t* out_dst, int32_t* out_type) const {
  Rng& rng = ThreadRng();
  for (int i = 0; i < count; ++i) {
    int64_t e = store_.SampleEdgeIdx(type, rng);
    if (e < 0) {
      out_src[i] = 0;
      out_dst[i] = 0;
      out_type[i] = -1;
    } else {
      out_src[i] = store_.EdgeSrcAt(e);
      out_dst[i] = store_.EdgeDstAt(e);
      out_type[i] = store_.EdgeTypeAt(e);
    }
  }
}

void Engine::SampleNodeWithSrc(const uint64_t* src, int n, int count,
                               uint64_t* out) const {
#pragma omp parallel for schedule(static) if (n > 64)
  for (int i = 0; i < n; ++i) {
    Rng& rng = ThreadRng();
    int64_t idx = store_.NodeIndex(src[i]);
    int32_t type = idx >= 0 ? store_.NodeTypeAt(idx) : -1;
    for (int j = 0; j < count; ++j)
      out[static_cast<int64_t>(i) * count + j] = store_.SampleNode(type, rng);
  }
}

void Engine::GetNodeType(const uint64_t* ids, int n, int32_t* out) const {
#pragma omp parallel for schedule(static) if (n > 1024)
  for (int i = 0; i < n; ++i) {
    int64_t idx = store_.NodeIndex(ids[i]);
    out[i] = idx >= 0 ? store_.NodeTypeAt(idx) : -1;
  }
}

bool Engine::GetNodeWeight(const uint64_t* ids, int n, float* out) const {
#pragma omp parallel for schedule(static) if (n > 1024)
  for (int i = 0; i < n; ++i) {
    int64_t idx = store_.NodeIndex(ids[i]);
    out[i] = idx >= 0 ? store_.NodeWeightAt(idx) : 0.0f;
  }
  return true;
}

void Engine::SampleNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                            int net, int count, uint64_t default_id,
                            uint64_t* out_ids, float* out_w,
                            int32_t* out_t) const {
#pragma omp parallel for schedule(dynamic, 64) if (n * count > 2048)
  for (int i = 0; i < n; ++i) {
    Rng& rng = ThreadRng();
    int64_t off = static_cast<int64_t>(i) * count;
    store_.SampleNeighbors(store_.NodeIndex(ids[i]), etypes, net, count,
                           default_id, rng, out_ids + off, out_w + off,
                           out_t + off);
  }
}

void Engine::SampleFanout(const uint64_t* ids, int n,
                          const int32_t* etypes_flat,
                          const int32_t* etype_counts, const int32_t* counts,
                          int nhops, uint64_t default_id, uint64_t** out_ids,
                          float** out_w, int32_t** out_t) const {
  const uint64_t* cur = ids;
  int64_t cur_n = n;
  const int32_t* et = etypes_flat;
  // n * prod(counts) past 2^31 would truncate in the per-hop int cast
  // (same overflow class fixed in RemoteGraph::SampleFanout): issue each
  // hop in bounded slices instead — per-row sampling makes the slicing
  // invisible to the result.
  const int64_t kSlice = int64_t{1} << 30;
  for (int h = 0; h < nhops; ++h) {
    for (int64_t off = 0; off < cur_n; off += kSlice) {
      int m = static_cast<int>(std::min<int64_t>(kSlice, cur_n - off));
      SampleNeighbor(cur + off, m, et, etype_counts[h], counts[h],
                     default_id, out_ids[h] + off * counts[h],
                     out_w[h] + off * counts[h], out_t[h] + off * counts[h]);
    }
    cur = out_ids[h];
    cur_n *= counts[h];
    et += etype_counts[h];
  }
}

EGResult* Engine::GetFullNeighbor(const uint64_t* ids, int n,
                                  const int32_t* etypes, int net,
                                  bool sorted) const {
  auto* res = new EGResult();
  res->u64.resize(1);
  res->f32.resize(1);
  res->i32.resize(2);
  res->i32[1].resize(static_cast<size_t>(n));
  std::vector<std::vector<uint64_t>> row_ids(static_cast<size_t>(n));
  std::vector<std::vector<float>> row_w(static_cast<size_t>(n));
  std::vector<std::vector<int32_t>> row_t(static_cast<size_t>(n));
#pragma omp parallel for schedule(dynamic, 64) if (n > 256)
  for (int i = 0; i < n; ++i) {
    store_.FullNeighbors(store_.NodeIndex(ids[i]), etypes, net, sorted,
                         &row_ids[i], &row_w[i], &row_t[i]);
    res->i32[1][static_cast<size_t>(i)] =
        static_cast<int32_t>(row_ids[static_cast<size_t>(i)].size());
  }
  for (int i = 0; i < n; ++i) {
    auto& ri = row_ids[static_cast<size_t>(i)];
    res->u64[0].insert(res->u64[0].end(), ri.begin(), ri.end());
    auto& rw = row_w[static_cast<size_t>(i)];
    res->f32[0].insert(res->f32[0].end(), rw.begin(), rw.end());
    auto& rt = row_t[static_cast<size_t>(i)];
    res->i32[0].insert(res->i32[0].end(), rt.begin(), rt.end());
  }
  return res;
}

void Engine::GetTopKNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                             int net, int k, uint64_t default_id,
                             uint64_t* out_ids, float* out_w,
                             int32_t* out_t) const {
#pragma omp parallel for schedule(dynamic, 64) if (n * k > 2048)
  for (int i = 0; i < n; ++i) {
    int64_t off = static_cast<int64_t>(i) * k;
    store_.TopKNeighbors(store_.NodeIndex(ids[i]), etypes, net, k, default_id,
                         out_ids + off, out_w + off, out_t + off);
  }
}

void Engine::RandomWalk(const uint64_t* ids, int n,
                        const int32_t* etypes_flat,
                        const int32_t* etype_counts, int walk_len, float p,
                        float q, uint64_t default_id, uint64_t* out) const {
  // Per-step edge-type segment offsets.
  std::vector<int64_t> seg(static_cast<size_t>(walk_len) + 1, 0);
  for (int s = 0; s < walk_len; ++s) seg[s + 1] = seg[s] + etype_counts[s];
  int64_t stride = walk_len + 1;
#pragma omp parallel for schedule(dynamic, 16) if (n * walk_len > 512)
  for (int i = 0; i < n; ++i) {
    Rng& rng = ThreadRng();
    uint64_t* row = out + static_cast<int64_t>(i) * stride;
    row[0] = ids[i];
    uint64_t cur = ids[i];
    uint64_t parent = 0;
    bool has_parent = false;
    for (int s = 1; s <= walk_len; ++s) {
      int64_t idx = store_.NodeIndex(cur);
      uint64_t next = store_.BiasedNeighbor(
          idx, has_parent, parent, etypes_flat + seg[s - 1],
          static_cast<int>(seg[s] - seg[s - 1]), p, q, default_id, rng);
      row[s] = next;
      parent = cur;
      has_parent = true;
      cur = next;
    }
  }
}

void Engine::GetDenseFeature(const uint64_t* ids, int n, const int32_t* fids,
                             const int32_t* dims, int nf, float* out) const {
  int64_t row_dim = 0;
  for (int k = 0; k < nf; ++k) row_dim += dims[k];
#pragma omp parallel for schedule(static) if (n * row_dim > 8192)
  for (int i = 0; i < n; ++i) {
    int64_t idx = store_.NodeIndex(ids[i]);
    float* row = out + static_cast<int64_t>(i) * row_dim;
    for (int k = 0; k < nf; ++k) {
      store_.DenseFeature(idx, fids[k], dims[k], row);
      row += dims[k];
    }
  }
}

void Engine::GetEdgeDenseFeature(const uint64_t* src, const uint64_t* dst,
                                 const int32_t* types, int n,
                                 const int32_t* fids, const int32_t* dims,
                                 int nf, float* out) const {
  int64_t row_dim = 0;
  for (int k = 0; k < nf; ++k) row_dim += dims[k];
#pragma omp parallel for schedule(static) if (n * row_dim > 8192)
  for (int i = 0; i < n; ++i) {
    int64_t idx = store_.EdgeIndex(src[i], dst[i], types[i]);
    float* row = out + static_cast<int64_t>(i) * row_dim;
    for (int k = 0; k < nf; ++k) {
      store_.EdgeDenseFeature(idx, fids[k], dims[k], row);
      row += dims[k];
    }
  }
}

EGResult* Engine::GetSparseFeature(const uint64_t* ids, int n,
                                   const int32_t* fids, int nf) const {
  auto* res = new EGResult();
  res->u64.resize(static_cast<size_t>(nf));
  res->i32.resize(static_cast<size_t>(nf));
  for (int k = 0; k < nf; ++k) {
    res->i32[k].resize(static_cast<size_t>(n));
    for (int i = 0; i < n; ++i) {
      const uint64_t* vals;
      int64_t cnt;
      store_.U64Feature(store_.NodeIndex(ids[i]), fids[k], &vals, &cnt);
      res->i32[k][static_cast<size_t>(i)] = static_cast<int32_t>(cnt);
      if (cnt) res->u64[k].insert(res->u64[k].end(), vals, vals + cnt);
    }
  }
  return res;
}

EGResult* Engine::GetEdgeSparseFeature(const uint64_t* src,
                                       const uint64_t* dst,
                                       const int32_t* types, int n,
                                       const int32_t* fids, int nf) const {
  auto* res = new EGResult();
  res->u64.resize(static_cast<size_t>(nf));
  res->i32.resize(static_cast<size_t>(nf));
  for (int k = 0; k < nf; ++k) {
    res->i32[k].resize(static_cast<size_t>(n));
    for (int i = 0; i < n; ++i) {
      const uint64_t* vals;
      int64_t cnt;
      store_.EdgeU64Feature(store_.EdgeIndex(src[i], dst[i], types[i]),
                            fids[k], &vals, &cnt);
      res->i32[k][static_cast<size_t>(i)] = static_cast<int32_t>(cnt);
      if (cnt) res->u64[k].insert(res->u64[k].end(), vals, vals + cnt);
    }
  }
  return res;
}

EGResult* Engine::GetBinaryFeature(const uint64_t* ids, int n,
                                   const int32_t* fids, int nf) const {
  auto* res = new EGResult();
  res->bytes.resize(static_cast<size_t>(nf));
  res->i32.resize(static_cast<size_t>(nf));
  for (int k = 0; k < nf; ++k) {
    res->i32[k].resize(static_cast<size_t>(n));
    for (int i = 0; i < n; ++i) {
      const char* data;
      int64_t size;
      store_.BinFeature(store_.NodeIndex(ids[i]), fids[k], &data, &size);
      res->i32[k][static_cast<size_t>(i)] = static_cast<int32_t>(size);
      if (size) res->bytes[k].append(data, static_cast<size_t>(size));
    }
  }
  return res;
}

EGResult* Engine::GetEdgeBinaryFeature(const uint64_t* src,
                                       const uint64_t* dst,
                                       const int32_t* types, int n,
                                       const int32_t* fids, int nf) const {
  auto* res = new EGResult();
  res->bytes.resize(static_cast<size_t>(nf));
  res->i32.resize(static_cast<size_t>(nf));
  for (int k = 0; k < nf; ++k) {
    res->i32[k].resize(static_cast<size_t>(n));
    for (int i = 0; i < n; ++i) {
      const char* data;
      int64_t size;
      store_.EdgeBinFeature(store_.EdgeIndex(src[i], dst[i], types[i]),
                            fids[k], &data, &size);
      res->i32[k][static_cast<size_t>(i)] = static_cast<int32_t>(size);
      if (size) res->bytes[k].append(data, static_cast<size_t>(size));
    }
  }
  return res;
}

}  // namespace eg
