#include "eg_placement.h"

#include <dirent.h>

#include <cstring>
#include <fstream>
#include <sstream>

namespace eg {

namespace {

// Next power of two >= n (n >= 1).
uint64_t Pow2AtLeast(uint64_t n) {
  uint64_t p = 1;
  while (p < n) p <<= 1;
  return p;
}

}  // namespace

void PlacementMap::Clear() {
  slots_.clear();
  size_ = 0;
  num_partitions_ = 0;
}

bool PlacementMap::Parse(const std::string& bytes, std::string* err) {
  Clear();
  constexpr size_t kHeader = 4 + 4 + 8;
  if (bytes.size() < kHeader) {
    *err = "placement artifact truncated (no header)";
    return false;
  }
  uint32_t magic;
  int32_t nparts;
  int64_t count;
  std::memcpy(&magic, bytes.data(), 4);
  std::memcpy(&nparts, bytes.data() + 4, 4);
  std::memcpy(&count, bytes.data() + 8, 8);
  if (magic != kPlacementMagic) {
    *err = "placement artifact has bad magic (not an EGP1 file)";
    return false;
  }
  if (nparts <= 0) {
    *err = "placement artifact declares num_partitions <= 0";
    return false;
  }
  // Bound the declared count by what the blob can actually carry (12
  // bytes per entry) BEFORE sizing the table — a hostile count must not
  // turn a short blob into a multi-GB allocation (eg-lint rule
  // wire-count-alloc applies to file-derived counts too).
  if (count < 0 ||
      static_cast<uint64_t>(count) > (bytes.size() - kHeader) / 12) {
    *err = "placement artifact count exceeds its payload";
    return false;
  }
  if (bytes.size() != kHeader + static_cast<size_t>(count) * 12) {
    *err = "placement artifact payload size mismatch";
    return false;
  }
  if (count == 0) {
    *err = "placement artifact is empty (zero mapped ids)";
    return false;
  }
  const char* ids_p = bytes.data() + kHeader;
  const char* parts_p = ids_p + static_cast<size_t>(count) * 8;
  // <= 50% load keeps the probe chains short on the routing hot path
  slots_.assign(Pow2AtLeast(static_cast<uint64_t>(count) * 2), Slot{});
  uint64_t mask = static_cast<uint64_t>(slots_.size()) - 1;
  for (int64_t k = 0; k < count; ++k) {
    uint64_t id;
    int32_t part;
    std::memcpy(&id, ids_p + k * 8, 8);
    std::memcpy(&part, parts_p + k * 4, 4);
    if (part < 0 || part >= nparts) {
      std::ostringstream os;
      os << "placement artifact maps id " << id
         << " to out-of-range partition " << part << " (num_partitions "
         << nparts << ")";
      *err = os.str();
      Clear();
      return false;
    }
    uint64_t i = Hash(id) & mask;
    while (slots_[i].part >= 0) {
      if (slots_[i].id == id) {
        std::ostringstream os;
        os << "placement artifact maps id " << id
           << " twice — ambiguous routing";
        *err = os.str();
        Clear();
        return false;
      }
      i = (i + 1) & mask;
    }
    slots_[i].id = id;
    slots_[i].part = part;
  }
  size_ = count;
  num_partitions_ = nparts;
  return true;
}

bool ReadPlacementDir(const std::string& dir, std::string* blob,
                      std::string* err) {
  blob->clear();
  DIR* d = opendir(dir.c_str());
  if (!d) {
    *err = "cannot open data dir " + dir;
    return false;
  }
  std::string found;
  bool dup = false;
  constexpr const char* kSuffix = ".placement";
  constexpr size_t kSuffixLen = 10;
  while (dirent* ent = readdir(d)) {
    std::string name = ent->d_name;
    if (name.size() <= kSuffixLen ||
        name.compare(name.size() - kSuffixLen, kSuffixLen, kSuffix) != 0)
      continue;
    if (!found.empty()) dup = true;
    found = name;
  }
  closedir(d);
  if (dup) {
    *err = "multiple *.placement artifacts in " + dir +
           " — ambiguous routing, remove all but one";
    return false;
  }
  if (found.empty()) return true;  // hash-sharded data: no artifact
  std::ifstream f(dir + "/" + found, std::ios::binary);
  std::ostringstream os;
  os << f.rdbuf();
  if (!f) {
    *err = "cannot read placement artifact " + dir + "/" + found;
    return false;
  }
  *blob = os.str();
  return true;
}

}  // namespace eg
