// euler_tpu native graph engine — common types and utilities.
//
// TPU-native rebuild of the reference Euler graph engine
// (cf. /root/reference/euler/common/data_types.h, random.h, bytes_reader.h).
// Design departs from the reference: the store is a flat SoA arena (see
// eg_graph.h) rather than per-node heap objects, so batch sampling is
// cache-friendly and trivially parallel across a host CPU feeding TPU chips.
#ifndef EG_COMMON_H_
#define EG_COMMON_H_

#include <pthread.h>
#include <time.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

// Lock-discipline annotation, machine-checked by scripts/check_contracts.py
// (pass `lock`, rule `guarded-by`): a member declared
//
//     std::deque<Conn> ready_ EG_GUARDED_BY(mu_);
//
// may only be touched inside a scope holding an RAII guard on `mu_`
// (std::lock_guard / std::unique_lock / std::scoped_lock), including
// wait-predicate lambdas whose enclosing unique_lock holds it.
// Deliberately-unlocked accesses (constructors/destructors are exempt
// automatically; documented lock-free reads are not) need a reasoned
// `allow(guarded-by)` escape — check_native.py's eg-lint grammar — on
// or above the line. Expands to nothing — gcc 10 has no
// -Wthread-safety — so the checker, not the compiler, enforces it.
#define EG_GUARDED_BY(mu)

// Companion annotation for helper functions that are only ever called
// with `mu` already held (the caller locks, the helper touches guarded
// state freely). The checker exempts the helper's body and instead
// verifies every CALL SITE holds the guard — same enforcement story as
// EG_GUARDED_BY: checker, not compiler.
#define EG_REQUIRES(mu)

namespace eg {

using NodeID = uint64_t;

// Edge identity: (src, dst, type). Mirrors the reference wire semantics
// (reference euler/common/data_types.h:29-41) with our own hash mix.
struct EdgeKey {
  uint64_t src;
  uint64_t dst;
  int32_t type;
  bool operator==(const EdgeKey& o) const {
    return src == o.src && dst == o.dst && type == o.type;
  }
};

struct EdgeKeyHash {
  size_t operator()(const EdgeKey& k) const {
    // splitmix64-style mixing of the three fields.
    uint64_t h = k.src * 0x9E3779B97F4A7C15ULL;
    h ^= (k.dst + 0x9E3779B97F4A7C15ULL + (h << 6) + (h >> 2));
    h *= 0xBF58476D1CE4E5B9ULL;
    h ^= static_cast<uint64_t>(k.type) + (h >> 31);
    return static_cast<size_t>(h * 0x94D049BB133111EBULL);
  }
};

// Fast per-thread RNG (xorshift-based splitmix64). The reference uses
// thread_local std::default_random_engine (reference euler/common/random.cc:22);
// we need something cheaper because sampling draws dominate the host profile.
struct Rng {
  uint64_t state;
  explicit Rng(uint64_t seed = 0x853C49E6748FEA9BULL) : state(seed) {}
  inline uint64_t Next() {
    uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  // Uniform in [0, 1).
  inline double NextDouble() {
    return static_cast<double>(Next() >> 11) * (1.0 / 9007199254740992.0);
  }
  inline float NextFloat() { return static_cast<float>(NextDouble()); }
  // Uniform integer in [0, n).
  inline uint64_t NextLess(uint64_t n) {
    return n ? static_cast<uint64_t>(NextDouble() * static_cast<double>(n)) % n
             : 0;
  }
};

Rng& ThreadRng();
void SeedThreadRng(uint64_t seed);

// Mutex with a TSAN-visible lifecycle. std::mutex on Linux is
// trivially constructed AND trivially destroyed (PTHREAD_MUTEX_
// INITIALIZER, no init/destroy calls), so when an object holding one
// is deleted and the allocator hands its block to a NEW object of the
// same class, TSAN's shadow state for the old mutex survives at that
// address and the new object's first lock reports a false "double
// lock of a destroyed mutex" (reproduced on sequential Service
// create/stop churn under `make tsan`; an address-size pad on the
// PRE-telemetry tree reproduces it identically, pinning it as an
// allocator-layout artifact, SANITIZERS.md round 9). Explicit
// pthread_mutex_init/destroy are intercepted by TSAN and reset the
// shadow state, so heap-recycled servers start clean. Satisfies
// BasicLockable: use through std::lock_guard/std::unique_lock like
// any std::mutex (the raw-lock lint rule applies to callers as usual).
class PosixMutex {
 public:
  PosixMutex() { pthread_mutex_init(&m_, nullptr); }
  ~PosixMutex() { pthread_mutex_destroy(&m_); }
  PosixMutex(const PosixMutex&) = delete;
  PosixMutex& operator=(const PosixMutex&) = delete;
  void lock() { pthread_mutex_lock(&m_); }
  void unlock() { pthread_mutex_unlock(&m_); }
  bool try_lock() { return pthread_mutex_trylock(&m_) == 0; }
  pthread_mutex_t* native() { return &m_; }

 private:
  pthread_mutex_t m_;
};

// Companion condition variable with the same TSAN-visible lifecycle.
// NOT std::condition_variable (whose mutex type is fixed to
// std::mutex) and NOT std::condition_variable_any (which allocates an
// INTERNAL std::shared_ptr<std::mutex> — trivially initialized, so the
// heap-recycling false positive above just moves inside it). Runs on a
// CLOCK_MONOTONIC pthread_cond_t, so timed waits ignore wall-clock
// jumps.
class PosixCondVar {
 public:
  PosixCondVar() {
    pthread_condattr_t attr;
    pthread_condattr_init(&attr);
    pthread_condattr_setclock(&attr, CLOCK_MONOTONIC);
    pthread_cond_init(&c_, &attr);
    pthread_condattr_destroy(&attr);
  }
  ~PosixCondVar() { pthread_cond_destroy(&c_); }
  PosixCondVar(const PosixCondVar&) = delete;
  PosixCondVar& operator=(const PosixCondVar&) = delete;

  void notify_one() { pthread_cond_signal(&c_); }
  void notify_all() { pthread_cond_broadcast(&c_); }

  template <typename Pred>
  void wait(std::unique_lock<PosixMutex>& l, Pred pred) {
    while (!pred()) pthread_cond_wait(&c_, l.mutex()->native());
  }

  // Wait up to timeout_ms for pred; returns pred()'s final verdict.
  template <typename Pred>
  bool wait_for_ms(std::unique_lock<PosixMutex>& l, int64_t timeout_ms,
                   Pred pred) {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    ts.tv_sec += timeout_ms / 1000;
    ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (ts.tv_nsec >= 1000000000L) {
      ts.tv_sec += 1;
      ts.tv_nsec -= 1000000000L;
    }
    while (!pred()) {
      if (pthread_cond_timedwait(&c_, l.mutex()->native(), &ts) != 0)
        return pred();  // timeout (or error): report the final state
    }
    return true;
  }

 private:
  pthread_cond_t c_;
};

// Little-endian cursor over a byte buffer; unaligned-safe via memcpy.
// (Equivalent role to reference euler/common/bytes_reader.h:27.)
class ByteCursor {
 public:
  ByteCursor(const char* data, size_t size) : p_(data), end_(data + size) {}

  template <typename T>
  bool Read(T* out) {
    if (p_ + sizeof(T) > end_) return false;
    std::memcpy(out, p_, sizeof(T));
    p_ += sizeof(T);
    return true;
  }

  template <typename T>
  bool ReadVec(size_t n, std::vector<T>* out) {
    // compare against remaining(): `p_ + n * sizeof(T)` overflows for
    // corrupt huge n, slipping past the bound into resize()/memcpy
    if (n > remaining() / sizeof(T)) return false;
    out->resize(n);
    if (n) std::memcpy(out->data(), p_, n * sizeof(T));
    p_ += n * sizeof(T);
    return true;
  }

  bool ReadStr(size_t n, std::string* out) {
    if (n > remaining()) return false;
    out->assign(p_, n);
    p_ += n;
    return true;
  }

  bool Skip(size_t n) {
    if (n > remaining()) return false;
    p_ += n;
    return true;
  }

  size_t remaining() const { return static_cast<size_t>(end_ - p_); }
  const char* ptr() const { return p_; }

 private:
  const char* p_;
  const char* end_;
};

}  // namespace eg

#endif  // EG_COMMON_H_
