// Abstract graph query interface shared by the embedded engine (Local mode)
// and the remote sharded client (Remote mode).
//
// Role equivalent of the reference's abstract async client
// (reference euler/client/graph.h:47 with Local/Remote impls picked by
// Graph::NewGraph, graph.cc:157-185) — but batch-synchronous: the TPU input
// pipeline drives these from prefetch threads, so results return in place of
// flowing through completion callbacks.
#ifndef EG_API_H_
#define EG_API_H_

#include <cstdint>

namespace eg {

struct EGResult;

class GraphAPI {
 public:
  virtual ~GraphAPI() = default;

  // ---- introspection ----
  virtual int64_t NumNodes() const = 0;
  virtual int64_t NumEdges() const = 0;
  virtual int32_t NodeTypeNum() const = 0;
  virtual int32_t EdgeTypeNum() const = 0;
  // kind: 0=node u64, 1=node f32, 2=node binary, 3..5 same for edges.
  virtual int32_t FeatureNum(int kind) const = 0;
  // kind 0 = node, 1 = edge; out sized {node,edge}_type_num.
  virtual void TypeWeightSums(int kind, float* out) const = 0;
  // Snapshot epoch this view serves (eg_epoch.h); 0 = base load, never
  // refreshed. Remote graphs answer the max across shards.
  virtual uint64_t Epoch() const { return 0; }

  // ---- global sampling ----
  virtual void SampleNode(int count, int32_t type, uint64_t* out) const = 0;
  virtual void SampleEdge(int count, int32_t type, uint64_t* out_src,
                          uint64_t* out_dst, int32_t* out_type) const = 0;
  virtual void SampleNodeWithSrc(const uint64_t* src, int n, int count,
                                 uint64_t* out) const = 0;
  virtual void GetNodeType(const uint64_t* ids, int n,
                           int32_t* out) const = 0;
  // Per-node sampling weights (0 for unknown ids) — the device-graph
  // exporter's feed (euler_tpu/graph/device.py build_node_sampler).
  // Returns false when any row could not be resolved (remote shard
  // unreachable): unlike the query ops, which degrade to defaults, a
  // silently-zero weight would bias the exported sampler — callers must
  // surface the failure.
  virtual bool GetNodeWeight(const uint64_t* ids, int n,
                             float* out) const = 0;

  // ---- neighbor ops ----
  virtual void SampleNeighbor(const uint64_t* ids, int n,
                              const int32_t* etypes, int net, int count,
                              uint64_t default_id, uint64_t* out_ids,
                              float* out_w, int32_t* out_t) const = 0;
  virtual void SampleFanout(const uint64_t* ids, int n,
                            const int32_t* etypes_flat,
                            const int32_t* etype_counts, const int32_t* counts,
                            int nhops, uint64_t default_id, uint64_t** out_ids,
                            float** out_w, int32_t** out_t) const = 0;
  virtual EGResult* GetFullNeighbor(const uint64_t* ids, int n,
                                    const int32_t* etypes, int net,
                                    bool sorted) const = 0;
  virtual void GetTopKNeighbor(const uint64_t* ids, int n,
                               const int32_t* etypes, int net, int k,
                               uint64_t default_id, uint64_t* out_ids,
                               float* out_w, int32_t* out_t) const = 0;

  // ---- walks ----
  virtual void RandomWalk(const uint64_t* ids, int n,
                          const int32_t* etypes_flat,
                          const int32_t* etype_counts, int walk_len, float p,
                          float q, uint64_t default_id,
                          uint64_t* out) const = 0;

  // ---- features ----
  virtual void GetDenseFeature(const uint64_t* ids, int n, const int32_t* fids,
                               const int32_t* dims, int nf,
                               float* out) const = 0;
  virtual void GetEdgeDenseFeature(const uint64_t* src, const uint64_t* dst,
                                   const int32_t* types, int n,
                                   const int32_t* fids, const int32_t* dims,
                                   int nf, float* out) const = 0;
  virtual EGResult* GetSparseFeature(const uint64_t* ids, int n,
                                     const int32_t* fids, int nf) const = 0;
  virtual EGResult* GetEdgeSparseFeature(const uint64_t* src,
                                         const uint64_t* dst,
                                         const int32_t* types, int n,
                                         const int32_t* fids,
                                         int nf) const = 0;
  virtual EGResult* GetBinaryFeature(const uint64_t* ids, int n,
                                     const int32_t* fids, int nf) const = 0;
  virtual EGResult* GetEdgeBinaryFeature(const uint64_t* src,
                                         const uint64_t* dst,
                                         const int32_t* types, int n,
                                         const int32_t* fids,
                                         int nf) const = 0;
};

}  // namespace eg

#endif  // EG_API_H_
