// Locality-aware placement map: explicit id -> partition routing.
//
// Hash sharding (shard(id) = (id % P) % S) spreads every vertex
// uniformly, so on power-law graphs every sampled hop fans out to every
// shard — PR 8's eg_heat profiler measured a 49.8% edge-cut on the
// reddit_heavytail fixture (PERF.md "Data-plane heat"). The degree-aware
// partitioner in euler_tpu/graph/convert.py closes that gap by
// co-locating hub vertices with their sampled neighborhoods and emitting
// a compact placement artifact (`<prefix>.placement`) next to the .dat
// partitions. GNNSampler (arXiv:2108.11571) and FastSample
// (arXiv:2311.17847) both report skew-aware partitioning as the dominant
// remaining locality lever at scale.
//
// Artifact format (little-endian, written by convert.py, parsed here):
//   [u32 magic 'EGP1'][i32 num_partitions][i64 count]
//   [u64 ids[count]][i32 parts[count]]
//
// Both sides consume it:
//   * shards load the artifact at Service::Start and serve the raw blob
//     through the kPlacement wire op (eg_wire.h). A shard whose data dir
//     has no artifact answers the STOCK "unknown op" error — byte-
//     identical to a genuine pre-placement server, so the client needs
//     exactly one fallback path for both;
//   * clients parse the blob into this read-only open-addressed table
//     and route ShardOf(id) = map[id] % num_shards, hash fallback for
//     unmapped ids (negotiated passively, like wire v2/v3: no extra
//     round trip, old servers and old data keep working unchanged).
//
// Lookup cost: one splitmix64 hash + a short linear probe over a table
// held at <= 50% load — the routing hot path runs it once per unique id
// per query, so it must stay allocation-free and lock-free (the table is
// immutable after Parse).
#ifndef EG_PLACEMENT_H_
#define EG_PLACEMENT_H_

#include <cstdint>
#include <string>
#include <vector>

namespace eg {

constexpr uint32_t kPlacementMagic = 0x31504745;  // "EGP1" little-endian

class PlacementMap {
 public:
  // Parse a serialized artifact into the probe table. False + *err on a
  // malformed blob (bad magic, truncated arrays, out-of-range partition,
  // duplicate id) — a corrupt artifact must fail routing LOUDLY, never
  // misroute quietly. Leaves the map empty on failure.
  bool Parse(const std::string& bytes, std::string* err);

  bool loaded() const { return size_ != 0; }
  int32_t num_partitions() const { return num_partitions_; }
  int64_t size() const { return size_; }

  // Partition of one id; -1 when the id is not mapped (callers fall
  // back to hash routing). Immutable after Parse — safe from any
  // thread without synchronization.
  int32_t Lookup(uint64_t id) const {
    if (size_ == 0) return -1;
    uint64_t mask = static_cast<uint64_t>(slots_.size()) - 1;
    uint64_t i = Hash(id) & mask;
    while (true) {
      const Slot& s = slots_[i];
      if (s.part < 0) return -1;  // empty slot: id absent
      if (s.id == id) return s.part;
      i = (i + 1) & mask;
    }
  }

  void Clear();

 private:
  struct Slot {
    uint64_t id = 0;
    int32_t part = -1;  // -1 = empty
  };

  static uint64_t Hash(uint64_t x) {
    // splitmix64 finalizer — the id-hash family the sketch/cache use
    x += 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    return x ^ (x >> 31);
  }

  std::vector<Slot> slots_;
  int64_t size_ = 0;
  int32_t num_partitions_ = 0;
};

// Scan `dir` for the converter's "*.placement" artifact and read it into
// *blob. Returns false + *err on an IO error or MULTIPLE artifacts (an
// ambiguous dir must fail the service start, not route by whichever file
// sorts first); a dir with no artifact succeeds with an empty blob — the
// hash-sharded common case.
bool ReadPlacementDir(const std::string& dir, std::string* blob,
                      std::string* err);

}  // namespace eg

#endif  // EG_PLACEMENT_H_
