#include "eg_remote.h"

#include <dirent.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <thread>
#include <unordered_map>

#include "eg_blackbox.h"
#include "eg_fault.h"
#include "eg_heat.h"
#include "eg_registry.h"
#include "eg_stats.h"
#include "eg_telemetry.h"

namespace eg {

namespace {

int64_t NowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Semicolon k=v parser — the string config form shared with the reference
// (reference euler/client/graph_config.cc:33-56, create_graph.cc:50-60).
std::map<std::string, std::string> ParseConfig(const std::string& s) {
  std::map<std::string, std::string> out;
  std::stringstream ss(s);
  std::string item;
  while (std::getline(ss, item, ';')) {
    size_t eq = item.find('=');
    if (eq == std::string::npos) continue;
    out[item.substr(0, eq)] = item.substr(eq + 1);
  }
  return out;
}

bool ParseHostPort(const std::string& s, std::string* host, int* port) {
  size_t c = s.rfind(':');
  if (c == std::string::npos) return false;
  *host = s.substr(0, c);
  *port = std::atoi(s.c_str() + c + 1);
  return *port > 0;
}

// Decode an EGResult encoded by the service (see WriteResult in
// eg_service.cc). Every encoded slot costs at least 8 bytes (its i64
// length prefix), so a slot count beyond remaining()/8 cannot be honest:
// reject it before the resize below turns a hostile count from a
// malformed reply into a multi-GB zero-fill (the round-2 service crash
// class, service-side fix in OversizedResult; this is the client side).
bool ReadResultBody(WireReader* r, EGResult* out) {
  int32_t n = r->I32();
  if (n < 0 || static_cast<uint64_t>(n) > r->remaining() / 8) return false;
  out->u64.resize(n);
  for (auto& v : out->u64) r->Vec(&v);
  n = r->I32();
  if (n < 0 || static_cast<uint64_t>(n) > r->remaining() / 8) return false;
  out->f32.resize(n);
  for (auto& v : out->f32) r->Vec(&v);
  n = r->I32();
  if (n < 0 || static_cast<uint64_t>(n) > r->remaining() / 8) return false;
  out->i32.resize(n);
  for (auto& v : out->i32) r->Vec(&v);
  n = r->I32();
  if (n < 0 || static_cast<uint64_t>(n) > r->remaining() / 8) return false;
  out->bytes.resize(n);
  for (auto& s : out->bytes) s = r->Str();
  return r->ok();
}

bool ReadResult(WireReader* r, EGResult* out) {
  if (ReadResultBody(r, out)) return true;
  Counters::Global().Add(kCtrFrameReject);
  return false;
}

// Weight-proportional draws from one adjacency slice just fetched by a
// neighbor-cache promote (kFullNeighbor) — the client-side twin of
// GraphStore::SampleNeighbors for the case where the slice is in hand
// rather than cached (NeighborCache::Sample covers the cached case).
void DrawFromSlice(const uint64_t* nid, const float* nw, const int32_t* nt,
                   int64_t len, int64_t draws, uint64_t default_id,
                   Rng& rng, uint64_t* out_ids, float* out_w,
                   int32_t* out_t) {
  std::vector<double> cum(static_cast<size_t>(len));
  double total = 0.0;
  for (int64_t k = 0; k < len; ++k) {
    total += nw[k] > 0.f ? static_cast<double>(nw[k]) : 0.0;
    cum[static_cast<size_t>(k)] = total;
  }
  if (total <= 0.0) {
    for (int64_t j = 0; j < draws; ++j) {
      out_ids[j] = default_id;
      out_w[j] = 0.f;
      out_t[j] = -1;
    }
    return;
  }
  for (int64_t j = 0; j < draws; ++j) {
    double r = rng.NextDouble() * total;
    size_t k = static_cast<size_t>(
        std::lower_bound(cum.begin(), cum.end(), r) - cum.begin());
    if (k >= cum.size()) k = cum.size() - 1;  // float rounding spill
    out_ids[j] = nid[k];
    out_w[j] = nw[k];
    out_t[j] = nt[k];
  }
}

// The stock error a pre-envelope (wire v1) server answers when it reads
// the v2 envelope marker as an op code — the downgrade-negotiation
// signal (see eg_wire.h). Matched exactly: a v2 server's genuine
// unknown-op errors name ops in the real op range, never the marker.
bool IsLegacyUnknownOpReply(const std::string& reply) {
  WireReader r(reply);
  if (r.U8() != kStatusError) return false;
  std::string msg = r.Str();
  return r.ok() && r.remaining() == 0 &&
         msg == "unknown op " + std::to_string(kWireEnvelope);
}

}  // namespace

// ---------------- ConnPool ----------------

ConnPool::Replica::~Replica() {
  for (int fd : idle) ::close(fd);
}

void ConnPool::AddReplica(const std::string& host, int port) {
  auto r = std::make_shared<Replica>();
  r->host = host;
  r->port = port;
  std::lock_guard<std::mutex> l(mu_);
  replicas_.push_back(std::move(r));
}

void ConnPool::Update(const std::vector<std::pair<std::string, int>>& addrs) {
  if (addrs.empty()) return;
  std::lock_guard<std::mutex> l(mu_);
  std::vector<std::shared_ptr<Replica>> next;
  next.reserve(addrs.size());
  for (const auto& [host, port] : addrs) {
    bool dup = false;
    for (const auto& r : next)
      if (r->host == host && r->port == port) dup = true;
    if (dup) continue;
    std::shared_ptr<Replica> keep;
    for (const auto& r : replicas_)
      if (r->host == host && r->port == port) keep = r;
    if (!keep) {
      keep = std::make_shared<Replica>();
      keep->host = host;
      keep->port = port;
    }
    next.push_back(std::move(keep));
  }
  replicas_.swap(next);
  // dropped replicas die (and close their pooled sockets) when the last
  // in-flight Call snapshot releases them
}

size_t ConnPool::num_replicas() const {
  std::lock_guard<std::mutex> l(mu_);
  return replicas_.size();
}

bool ConnPool::Call(const std::string& req, std::string* reply, int retries,
                    int timeout_ms, int quarantine_ms, int backoff_ms,
                    int deadline_ms, uint64_t req_epoch) const {
  // Telemetry (eg_telemetry.h): the whole call — every retry, backoff
  // and failover included — is one client_call histogram sample and one
  // candidate slow span; the span's trace id rides the v3 envelope so
  // the serving shard's journal shows the same request.
  Telemetry& tel = Telemetry::Global();
  const bool rec = tel.enabled();
  const uint8_t op = req.empty() ? 0 : static_cast<uint8_t>(req[0]);
  const uint64_t trace = rec ? NextTraceId() : 0;
  const int64_t t_call = rec ? TelemetryNowUs() : 0;
  uint64_t wire_us = 0;  // io time of the decisive (last) exchange
  auto finish = [&](bool ok, uint8_t outcome) {
    if (rec) {
      uint64_t total = static_cast<uint64_t>(TelemetryNowUs() - t_call);
      tel.Record(kHistClientCall, op, total);
      TelemetrySpan sp;
      sp.side = kSpanClient;
      sp.op = op < kHistOpSlots ? op : 0;
      sp.outcome = outcome;
      sp.shard = shard_;
      sp.trace = trace;
      sp.wire_us = wire_us;
      sp.total_us = total;
      tel.RecordSpan(sp);
    }
    // flight recorder (eg_blackbox.h): every finished call — trace id,
    // shard, and the wire bytes moved — lands in this thread's ring,
    // so a postmortem shows what the process was asking for when it
    // died (its own kill-switch; a failed call still records, reply
    // bytes count only when one arrived)
    Blackbox::Global().Record(
        kBbClientCall, op, shard_, trace,
        req.size() + (ok ? reply->size() : 0), outcome);
    return ok;
  };
  // snapshot: Update() may swap the set mid-call; shared_ptrs keep every
  // replica this exchange touches alive. Refreshed at every attempt
  // (below) so a call already mid-retry against a restarted shard picks
  // up the re-discovered address instead of burning its whole budget on
  // the dead one — the rolling-restart drill's zero-failed-calls bar.
  std::vector<std::shared_ptr<Replica>> reps;
  {
    std::lock_guard<std::mutex> l(mu_);
    reps = replicas_;
  }
  if (reps.empty()) return finish(false, kOutcomeFailed);
  Counters& ctr = Counters::Global();
  // Overall wall-clock budget spanning every attempt; the 0 default keeps
  // the previous worst case (each attempt bounded by timeout_ms).
  const int64_t deadline =
      NowMs() + (deadline_ms > 0
                     ? deadline_ms
                     : static_cast<int64_t>(timeout_ms) * (retries + 1));
  bool failed_before = false;
  int busy_streak = 0;
  for (int attempt = 0; attempt <= retries; ++attempt) {
    // Re-sample the clock each attempt: a slow earlier attempt must age
    // quarantine verdicts and count against the deadline (the old single
    // pre-loop NowMs() went stale across attempts).
    int64_t now = NowMs();
    if (attempt > 0) {
      ctr.Add(kCtrRetry);
      // Exponential backoff with full jitter: sleep uniform in
      // [0, base << (attempt-1)], capped at 2 s and at the remaining
      // deadline — a hot retry loop against a struggling shard is a
      // self-inflicted DDoS.
      int64_t cap = std::min<int64_t>(
          static_cast<int64_t>(backoff_ms) << std::min(attempt - 1, 16),
          2000);
      int64_t sleep_ms = cap > 0
                             ? static_cast<int64_t>(ThreadRng().NextLess(
                                   static_cast<uint64_t>(cap) + 1))
                             : 0;
      sleep_ms = std::min(sleep_ms, deadline - now);
      if (sleep_ms > 0) {
        std::this_thread::sleep_for(std::chrono::milliseconds(sleep_ms));
        if (rec)
          tel.Record(kHistBackoff, 0,
                     static_cast<uint64_t>(sleep_ms) * 1000);
        now = NowMs();
      }
      if (now >= deadline) {
        ctr.Add(kCtrDeadlineExceeded);
        break;
      }
      // re-snapshot: the background re-discovery may have learned a
      // restarted replica's new address while this call backed off
      {
        std::lock_guard<std::mutex> l(mu_);
        if (!replicas_.empty()) reps = replicas_;
      }
    }
    // One attempt may loop through several BUSY failovers: a shedding
    // server ANSWERED (it is alive, just refusing new work), so BUSY
    // burns neither a retry nor backoff nor a quarantine — only the
    // overall deadline bounds a fully busy replica set.
    for (;;) {
      // Round-robin replica choice skipping quarantined hosts; if every
      // host is quarantined, use the nominal one anyway (matches the
      // reference's bad-host re-admission behavior, rpc_manager.cc:64).
      size_t start = rr_.fetch_add(1) % reps.size();
      Replica* rep = reps[start].get();
      for (size_t k = 0; k < reps.size(); ++k) {
        Replica* cand = reps[(start + k) % reps.size()].get();
        if (cand->bad_until_ms.load(std::memory_order_relaxed) <= now) {
          rep = cand;
          break;
        }
      }
      // kFaultCrash at the dial point (FAULTS.md): the client half of
      // the postmortem drill — Fire raises the configured fatal signal
      // in THIS process (the blackbox handler dumps, then the default
      // disposition kills).
      (void)FaultHit(kFaultCrash);
      int fd = -1;
      {
        std::lock_guard<std::mutex> l(rep->mu);
        if (!rep->idle.empty()) {
          fd = rep->idle.back();
          rep->idle.pop_back();
        }
      }
      if (fd < 0) {
        const int64_t t_dial = rec ? TelemetryNowUs() : 0;
        fd = DialTcp(rep->host, rep->port, timeout_ms);
        if (rec)
          tel.Record(kHistDial, 0,
                     static_cast<uint64_t>(TelemetryNowUs() - t_dial));
      }
      if (fd < 0) {
        ctr.Add(kCtrDialFail);
        ctr.Add(kCtrQuarantine);
        rep->bad_until_ms.store(now + quarantine_ms,
                                std::memory_order_relaxed);
        failed_before = true;
        break;  // next attempt (through the backoff above)
      }
      // Wire envelope: stamp the call's REMAINING budget so the server
      // can refuse work nobody will read; v3 adds the trace id, v4 the
      // requested snapshot epoch (eg_epoch.h). Replicas that negotiated
      // down (old servers) get the raw v1 request.
      int ver = forced_version_
                    ? forced_version_
                    : rep->wire_version.load(std::memory_order_relaxed);
      bool sent_envelope = ver != 1;
      // version of the decisive exchange — the reply-stamp parse below
      // keys on it (only v4 Ok replies carry the epoch)
      int eff_ver = sent_envelope ? (ver ? ver : kWireVersion) : 1;
      auto exchange = [&](const std::string& payload) {
        const int64_t t_io = rec ? TelemetryNowUs() : 0;
        bool ok = SendFrame(fd, payload) && RecvFrame(fd, reply);
        if (rec) wire_us = static_cast<uint64_t>(TelemetryNowUs() - t_io);
        return ok;
      };
      auto wrap = [&](int v) {
        int64_t remaining = deadline - NowMs();
        if (remaining < 0) remaining = 0;
        eff_ver = v;
        return WrapEnvelope(req, remaining, v, v >= 3 ? trace : 0,
                            v >= 4 ? req_epoch : 0);
      };
      bool io_ok;
      if (sent_envelope) {
        io_ok = exchange(wrap(ver ? ver : kWireVersion));
      } else {
        io_ok = exchange(req);
      }
      if (io_ok && sent_envelope && ver == 0) {
        // First exchange against this replica: learn its wire version.
        if (IsLegacyUnknownOpReply(*reply)) {
          eff_ver = 1;
          rep->wire_version.store(1, std::memory_order_relaxed);
          ctr.Add(kCtrWireDowngrade);
          // the old server answered its stock error and kept the
          // connection healthy: resend the raw request on it
          io_ok = exchange(req);
        } else {
          // Progressive BadVersion ladder: each refusal only says "too
          // new", so step down ONE version per answer (4 -> 3 -> 2) and
          // resend on the same connection until the replica accepts.
          // The replica pins at the highest version it spoke; one
          // wire_downgrades count per replica pinned below this build.
          int probe = kWireVersion;
          while (io_ok && probe > 2 && !reply->empty() &&
                 static_cast<uint8_t>((*reply)[0]) == kStatusBadVersion) {
            --probe;
            io_ok = exchange(wrap(probe));
          }
          if (io_ok) {
            rep->wire_version.store(probe, std::memory_order_relaxed);
            if (probe < kWireVersion) ctr.Add(kCtrWireDowngrade);
          }
        }
      }
      if (io_ok) {
        uint8_t status = reply->empty()
                             ? static_cast<uint8_t>(kStatusError)
                             : static_cast<uint8_t>((*reply)[0]);
        if (status == kStatusBusy) {
          // admission shed this connection (and closed it server-side):
          // fail over to the next replica NOW, no backoff burned
          ::close(fd);
          ctr.Add(kCtrBusyFailover);
          failed_before = true;
          now = NowMs();
          if (now >= deadline) {
            ctr.Add(kCtrDeadlineExceeded);
            ctr.Add(kCtrCallFail);
            return finish(false, kOutcomeDeadline);
          }
          if (++busy_streak >= static_cast<int>(reps.size())) {
            // every replica shedding: pace the loop a little instead of
            // hammering the cluster at wire speed
            std::this_thread::sleep_for(std::chrono::milliseconds(2));
            busy_streak = 0;
          }
          continue;  // same attempt, next replica
        }
        busy_streak = 0;
        if (status == kStatusDeadline) {
          // the server refused dead work — the budget is gone client-
          // side too, so end the call; the connection stays healthy
          {
            std::lock_guard<std::mutex> l(rep->mu);
            rep->idle.push_back(fd);
          }
          ctr.Add(kCtrDeadlineExceeded);
          ctr.Add(kCtrCallFail);
          return finish(false, kOutcomeDeadline);
        }
        if (failed_before) ctr.Add(kCtrFailover);
        {
          std::lock_guard<std::mutex> l(rep->mu);
          rep->idle.push_back(fd);
        }
        // v4 Ok replies carry the shard's serving epoch right after the
        // status byte (the passive flip announcement, eg_epoch.h):
        // strip it so every downstream decoder sees the versionless
        // body, then hand it to the observer (which bumps the client
        // cache generation when the epoch moved). Error/BUSY/deadline
        // replies are never stamped.
        if (status == kStatusOk && eff_ver >= 4 && reply->size() >= 9) {
          uint64_t ep;
          std::memcpy(&ep, reply->data() + 1, sizeof(ep));
          reply->erase(1, 8);
          if (epoch_observer_) epoch_observer_(ep);
        }
        return finish(true, kOutcomeOk);
      }
      ::close(fd);
      ctr.Add(kCtrQuarantine);
      rep->bad_until_ms.store(now + quarantine_ms,
                              std::memory_order_relaxed);
      failed_before = true;
      break;  // next attempt
    }
  }
  ctr.Add(kCtrCallFail);
  return finish(false, kOutcomeFailed);
}

// ---------------- RemoteGraph ----------------

RemoteGraph::~RemoteGraph() {
  if (rediscover_thread_.joinable()) {
    rediscover_stop_.store(true, std::memory_order_release);
    rediscover_thread_.join();
  }
  // Drain in-flight async ops (SampleFanoutAsync chains): their hop
  // continuations run on the dispatcher pool and touch this object, so
  // every chain must reach kDone before the members destruct. A handle
  // abandoned without TakeAsync only parks its slot until here.
  {
    std::unique_lock<std::mutex> l(async_mu_);
    async_cv_.wait(l, [this] { return async_inflight_ == 0; });
  }
  // dispatcher_ (a member) destructs after this body: by then no query
  // is in flight, so its queue is empty and the workers join promptly
}

bool RemoteGraph::Discover(
    std::map<int, std::vector<std::pair<std::string, int>>>* shards,
    int timeout_ms) const {
  shards->clear();
  if (!reg_host_.empty()) {
    // TCP registry discovery (eg_registry.h): LIST returns only live
    // (unexpired) entries — the watch-children analog of the reference's
    // ZK monitor (zk_server_monitor.cc:50-64).
    std::map<int, std::vector<std::string>> listed;
    std::map<std::pair<int, std::string>, uint64_t> epochs;
    if (!RegistryList(reg_host_, reg_port_, timeout_ms, &listed, &epochs))
      return false;
    for (auto& [shard, addrs] : listed) {
      for (auto& a : addrs) {
        std::string host;
        int port;
        if (ParseHostPort(a, &host, &port)) {
          (*shards)[shard].emplace_back(host, port);
          // heartbeat epoch tokens are the discovery half of the flip
          // announcement — a client that goes quiet between steps still
          // learns a flip within one registry poll (no-op before Init
          // allocates the epoch table)
          auto it = epochs.find({shard, a});
          if (it != epochs.end() && it->second)
            ObserveEpoch(shard, it->second);
        }
      }
    }
    return true;
  }
  if (!reg_dir_.empty()) {
    DIR* d = opendir(reg_dir_.c_str());
    if (!d) return false;
    while (dirent* ent = readdir(d)) {
      std::string name = ent->d_name;
      size_t hash = name.find('#');
      if (hash == std::string::npos || hash == 0) continue;
      int shard = std::atoi(name.substr(0, hash).c_str());
      std::ifstream f(reg_dir_ + "/" + name);
      std::string line;
      if (!std::getline(f, line)) continue;
      std::string host;
      int port;
      if (ParseHostPort(line, &host, &port))
        (*shards)[shard].emplace_back(host, port);
    }
    closedir(d);
    return true;
  }
  return false;
}

void RemoteGraph::RediscoverLoop() {
  // The polled form of the reference's ZK watch subscription
  // (rpc_manager.h:77-80 + zk_server_monitor.cc:252-260): each pass
  // re-LISTs the registry and diffs addresses into the pools, so a shard
  // that died and came back on a new host:port serves again without the
  // client being rebuilt. Shards absent from one listing keep their old
  // replicas (quarantine handles them if truly gone) — TTL expiry is
  // transient during a slow restart.
  while (!rediscover_stop_.load(std::memory_order_acquire)) {
    for (int slept = 0;
         slept < rediscover_ms_ &&
         !rediscover_stop_.load(std::memory_order_acquire);
         slept += 50)
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    if (rediscover_stop_.load(std::memory_order_acquire)) break;
    std::map<int, std::vector<std::pair<std::string, int>>> shards;
    // short dial budget: a blackholed registry must not pin this thread
    // (and thus ~RemoteGraph's join) for the full client timeout
    if (!Discover(&shards, std::min(timeout_ms_, 1000))) continue;
    for (int s = 0; s < num_shards_; ++s) {
      auto it = shards.find(s);
      if (it != shards.end()) pools_[s].Update(it->second);
    }
    Counters::Global().Add(kCtrRediscover);
  }
}

bool RemoteGraph::Init(const std::string& config) {
  auto cfg = ParseConfig(config);
  if (cfg.count("retries")) retries_ = std::stoi(cfg["retries"]);
  if (cfg.count("timeout_ms")) timeout_ms_ = std::stoi(cfg["timeout_ms"]);
  if (cfg.count("quarantine_ms"))
    quarantine_ms_ = std::stoi(cfg["quarantine_ms"]);
  if (cfg.count("backoff_ms")) backoff_ms_ = std::stoi(cfg["backoff_ms"]);
  if (cfg.count("deadline_ms")) deadline_ms_ = std::stoi(cfg["deadline_ms"]);
  if (cfg.count("coalesce")) coalesce_ = std::stoi(cfg["coalesce"]) != 0;
  if (cfg.count("strict")) strict_ = std::stoi(cfg["strict"]) != 0;
  if (cfg.count("chunk_ids")) chunk_ids_ = std::stoi(cfg["chunk_ids"]);
  if (chunk_ids_ < 1) chunk_ids_ = 1;
  if (cfg.count("dispatch_workers"))
    dispatch_workers_ = std::stoi(cfg["dispatch_workers"]);
  // wire_version=1 emulates a pre-envelope client (compat testing and an
  // operational escape hatch); 2 forces the deadline envelope without a
  // trace id; 3 forces the full trace envelope; absent = negotiate per
  // replica (the default — old servers are detected and downgraded).
  int wire_version = 0;
  if (cfg.count("wire_version")) {
    wire_version = std::stoi(cfg["wire_version"]);
    if (wire_version < 1 || wire_version > kWireVersion) {
      error_ = "wire_version must be 1.." + std::to_string(kWireVersion) +
               " (this build speaks " + std::to_string(kWireVersion) + ")";
      return false;
    }
  }
  // Observability kill-switch + slow-span journal capacity
  // (eg_telemetry.h) — process-global, like the failpoint registry.
  if (cfg.count("telemetry"))
    Telemetry::Global().SetEnabled(std::stoi(cfg["telemetry"]) != 0);
  // Data-plane heat profiler (eg_heat.h) — process-global: heat=0
  // stops id feeds/fan-out/cache-class recording, heat_topk= resizes
  // (and resets) the hot-key tracker.
  if (cfg.count("heat"))
    Heat::Global().SetEnabled(std::stoi(cfg["heat"]) != 0);
  if (cfg.count("heat_topk")) {
    int k = std::stoi(cfg["heat_topk"]);
    if (k < 1 || k > kHeatMaxTopK) {
      error_ = "heat_topk must be 1.." + std::to_string(kHeatMaxTopK) +
               " (fixed top-K tracker pool)";
      return false;
    }
    Heat::Global().SetTopK(k);
  }
  if (cfg.count("slow_spans")) {
    int cap = std::stoi(cfg["slow_spans"]);
    if (cap < 1) {
      error_ = "slow_spans must be >= 1 (journal capacity)";
      return false;
    }
    Telemetry::Global().SetSlowCapacity(cap);
  }
  // Dense-feature-row cache: default ON for remote graphs (the embedded
  // engine has no cache — its rows are already local memory); 0 disables.
  int cache_mb = 64;
  if (cfg.count("feature_cache_mb"))
    cache_mb = std::stoi(cfg["feature_cache_mb"]);
  if (cache_mb < 0) cache_mb = 0;
  fcache_.SetCapacity(static_cast<size_t>(cache_mb) << 20);
  // Neighbor-list cache: hot nodes' adjacency slices sampled locally
  // instead of per-hop wire round trips (eg_cache.h NeighborCache).
  int nbr_mb = 16;
  if (cfg.count("neighbor_cache_mb"))
    nbr_mb = std::stoi(cfg["neighbor_cache_mb"]);
  if (nbr_mb < 0) nbr_mb = 0;
  ncache_.SetCapacity(static_cast<size_t>(nbr_mb) << 20);
  // Shared admission policy of both caches: frequency-aware (TinyLFU
  // shape over the heat sketch) by default, fifo restores PR-3.
  if (cfg.count("cache_policy")) {
    const std::string& pol = cfg["cache_policy"];
    int policy;
    if (pol == "freq" || pol == "lfu") {
      policy = kCachePolicyFreq;
    } else if (pol == "fifo") {
      policy = kCachePolicyFifo;
    } else {
      error_ = "cache_policy must be 'freq' (TinyLFU-shaped admission, "
               "the default) or 'fifo' (unconditional admission)";
      return false;
    }
    fcache_.SetPolicy(policy);
    ncache_.SetPolicy(policy);
  }
  // placement=0 disables the init-time map fetch (always hash-route);
  // default 1 asks and falls back passively when no map exists.
  if (cfg.count("placement"))
    placement_enabled_ = std::stoi(cfg["placement"]) != 0;

  // Deterministic transport failpoints (eg_fault.h). Installed BEFORE the
  // per-shard kInfo fetches below, so even Init's own calls replay under
  // the configured faults — the seed owns the whole session.
  if (cfg.count("fault")) {
    uint64_t fseed = 0;
    if (cfg.count("fault_seed")) fseed = std::stoull(cfg["fault_seed"]);
    if (!FaultInjector::Global().Configure(cfg["fault"], fseed)) {
      error_ = FaultInjector::Global().error();
      return false;
    }
  }

  // shard -> replica address list
  std::map<int, std::vector<std::pair<std::string, int>>> shards;
  if (cfg.count("registry") &&
      cfg["registry"].compare(0, 6, "tcp://") == 0) {
    if (!ParseTcpRegistry(cfg["registry"], &reg_host_, &reg_port_)) {
      error_ = "bad tcp registry url " + cfg["registry"] +
               " (want tcp://host:port)";
      return false;
    }
    if (!Discover(&shards, timeout_ms_)) {
      error_ = "cannot reach tcp registry " + cfg["registry"];
      return false;
    }
  } else if (cfg.count("registry")) {
    reg_dir_ = cfg["registry"];
    if (!Discover(&shards, timeout_ms_)) {
      error_ = "cannot open registry dir " + cfg["registry"];
      return false;
    }
  } else if (cfg.count("shards")) {
    std::stringstream ss(cfg["shards"]);
    std::string shard_s;
    int idx = 0;
    while (std::getline(ss, shard_s, ',')) {
      std::stringstream rs(shard_s);
      std::string rep;
      while (std::getline(rs, rep, '|')) {
        std::string host;
        int port;
        if (ParseHostPort(rep, &host, &port))
          shards[idx].emplace_back(host, port);
      }
      ++idx;
    }
  } else {
    error_ = "remote config needs registry= or shards=";
    return false;
  }

  num_shards_ = shards.empty() ? 0 : shards.rbegin()->first + 1;
  if (num_shards_ <= 0) {
    error_ = "no shards discovered";
    return false;
  }
  pools_ = std::vector<ConnPool>(num_shards_);
  for (int s = 0; s < num_shards_; ++s) {
    if (!shards.count(s) || shards[s].empty()) {
      error_ = "no replicas for shard " + std::to_string(s);
      return false;
    }
    // set before the kInfo fetches below so even Init's own calls speak
    // the pinned version
    if (wire_version) pools_[s].SetForcedWireVersion(wire_version);
    pools_[s].SetShard(s);
    for (auto& [host, port] : shards[s]) pools_[s].AddReplica(host, port);
  }
  // Snapshot-epoch client state (eg_epoch.h): per-shard last-observed
  // epoch + the cache generation. Observers installed before the kInfo
  // fetches below, so even Init's own calls learn an already-flipped
  // cluster's epochs.
  shard_epoch_.reset(new std::atomic<uint64_t>[num_shards_]);
  for (int s = 0; s < num_shards_; ++s) {
    shard_epoch_[s].store(0, std::memory_order_relaxed);
    pools_[s].SetEpochObserver(
        [this, s](uint64_t e) { ObserveEpoch(s, e); });
  }

  // Persistent scatter/gather pool: sized so every shard can be in
  // flight at once with headroom for chunk fan-out and multiple client
  // threads (prefetch workers) sharing the graph.
  int workers = dispatch_workers_ > 0
                    ? dispatch_workers_
                    : std::min(64, std::max(8, 2 * num_shards_));
  dispatcher_ = std::make_unique<Dispatcher>(workers);

  // Per-shard meta: weight sums for cross-shard weighted sampling (the role
  // of the reference's ZK shard_meta node_sum_weight/edge_sum_weight,
  // graph_service.cc:141-142 <-> remote_graph.cc:122-155).
  shard_node_wsum_.resize(num_shards_);
  shard_edge_wsum_.resize(num_shards_);
  for (int s = 0; s < num_shards_; ++s) {
    WireWriter req;
    req.U8(kInfo);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) {
      error_ = "cannot fetch info from shard " + std::to_string(s);
      return false;
    }
    WireReader r(reply);
    r.U8();  // status already checked in Call
    int64_t nn = r.I64(), ne = r.I64();
    int32_t ntn = r.I32(), etn = r.I32();
    int32_t f[6];
    for (int k = 0; k < 6; ++k) f[k] = r.I32();
    r.I32();  // shard_idx
    int32_t shard_num = r.I32(), nparts = r.I32();
    r.Vec(&shard_node_wsum_[s]);
    r.Vec(&shard_edge_wsum_[s]);
    if (!r.ok()) {
      error_ = "malformed info reply from shard " + std::to_string(s);
      return false;
    }
    // Type/slot counts are derived from each shard's local records, so a
    // shard holding no nodes of the highest types reports fewer types —
    // the global view is the max (weight vectors are zero-padded below).
    node_type_num_ = std::max(node_type_num_, ntn);
    edge_type_num_ = std::max(edge_type_num_, etn);
    for (int k = 0; k < 6; ++k) fnum_[k] = std::max(fnum_[k], f[k]);
    if (s == 0) {
      num_partitions_ = nparts;
    } else if (nparts != num_partitions_) {
      error_ = "inconsistent num_partitions across shards";
      return false;
    }
    if (shard_num != num_shards_) {
      error_ = "shard " + std::to_string(s) + " was started with shard_num " +
               std::to_string(shard_num) + " but " +
               std::to_string(num_shards_) + " shards are registered";
      return false;
    }
    num_nodes_ += nn;
    num_edges_ += ne;
  }
  // eg-lint: allow(config-parity) `cfg` here is the shard's registry/kInfo
  // reply map, not operator config: num_partitions is written by the
  // partitioner and read back, never a user-facing key.
  if (cfg.count("num_partitions"))
    num_partitions_ = std::stoi(cfg["num_partitions"]);
  if (num_partitions_ <= 0) num_partitions_ = num_shards_;

  // Placement-map fetch (eg_placement.h): one kPlacement exchange with
  // shard 0 (every shard serves the same artifact). Negotiated
  // passively like wire v2/v3: an old server — or a shard on
  // hash-sharded data, which answers the identical stock error —
  // degrades this client to hash routing (placement_fallbacks). A map
  // that ARRIVES but is corrupt or inconsistent fails init loudly:
  // routing by a half-trusted map would misroute silently.
  if (placement_enabled_) {
    WireWriter preq;
    preq.U8(kPlacement);
    std::string reply;
    bool got = pools_[0].Call(preq.buf(), &reply, retries_, timeout_ms_,
                              quarantine_ms_, backoff_ms_, deadline_ms_) &&
               !reply.empty() &&
               static_cast<uint8_t>(reply[0]) == kStatusOk;
    if (got) {
      WireReader r(reply);
      r.U8();
      std::string blob = r.Str();
      if (!r.ok()) {
        error_ = "malformed placement reply from shard 0";
        return false;
      }
      if (!placement_.Parse(blob, &error_)) return false;
      if (placement_.num_partitions() != num_partitions_) {
        error_ = "placement map declares " +
                 std::to_string(placement_.num_partitions()) +
                 " partitions but the cluster reports " +
                 std::to_string(num_partitions_);
        return false;
      }
    } else {
      Counters::Global().Add(kCtrPlacementFallback);
    }
  }

  // Aggregate weight sums + cross-shard samplers.
  node_wsum_agg_.assign(node_type_num_, 0.f);
  edge_wsum_agg_.assign(edge_type_num_, 0.f);
  std::vector<float> node_tot(num_shards_, 0.f), edge_tot(num_shards_, 0.f);
  for (int s = 0; s < num_shards_; ++s) {
    shard_node_wsum_[s].resize(node_type_num_, 0.f);
    shard_edge_wsum_[s].resize(edge_type_num_, 0.f);
    for (int t = 0; t < node_type_num_; ++t) {
      node_wsum_agg_[t] += shard_node_wsum_[s][t];
      node_tot[s] += shard_node_wsum_[s][t];
    }
    for (int t = 0; t < edge_type_num_; ++t) {
      edge_wsum_agg_[t] += shard_edge_wsum_[s][t];
      edge_tot[s] += shard_edge_wsum_[s][t];
    }
  }
  node_shard_total_.Build(node_tot);
  edge_shard_total_.Build(edge_tot);
  node_shard_by_type_.resize(node_type_num_);
  edge_shard_by_type_.resize(edge_type_num_);
  std::vector<float> w(num_shards_);
  for (int t = 0; t < node_type_num_; ++t) {
    for (int s = 0; s < num_shards_; ++s) w[s] = shard_node_wsum_[s][t];
    node_shard_by_type_[t].Build(w);
  }
  for (int t = 0; t < edge_type_num_; ++t) {
    for (int s = 0; s < num_shards_; ++s) w[s] = shard_edge_wsum_[s][t];
    edge_shard_by_type_[t].Build(w);
  }

  // Mid-run re-discovery (registry modes only; static shards= lists have
  // no source to poll). Default 3000 ms; rediscover_ms=0 disables.
  rediscover_ms_ = cfg.count("rediscover_ms")
                       ? std::stoi(cfg["rediscover_ms"])
                       : 3000;
  if (rediscover_ms_ > 0 && (!reg_host_.empty() || !reg_dir_.empty())) {
    rediscover_stop_ = false;
    rediscover_thread_ = std::thread([this] {
      try {
        RediscoverLoop();
      } catch (...) {
        // std::terminate barrier (eg-lint: thread-catch): losing
        // re-discovery degrades to the static replica set; quarantine
        // still routes around dead hosts
      }
    });
  }
  return true;
}

void RemoteGraph::TypeWeightSums(int kind, float* out) const {
  const auto& v = kind == 0 ? node_wsum_agg_ : edge_wsum_agg_;
  std::copy(v.begin(), v.end(), out);
}

bool RemoteGraph::Call(int shard, const std::string& req,
                       std::string* reply, uint64_t epoch) const {
  if (!pools_[shard].Call(req, reply, retries_, timeout_ms_, quarantine_ms_,
                          backoff_ms_, deadline_ms_, epoch))
    return false;
  if (reply->empty() || (*reply)[0] != 0) {
    // transport delivered a frame, but the shard refused the request —
    // visible in the ledger as a rejected frame, not a silent default
    Counters::Global().Add(kCtrFrameReject);
    return false;
  }
  return true;
}

void RemoteGraph::ObserveEpoch(int shard, uint64_t epoch) const {
  if (!shard_epoch_ || shard < 0 || shard >= num_shards_) return;
  uint64_t cur = shard_epoch_[shard].load(std::memory_order_relaxed);
  // Monotonic raise: stale announcements (a reply that raced a flip, a
  // lagging registry token) never move the epoch backwards, so the
  // cache generation bumps exactly once per observed flip per shard.
  while (epoch > cur) {
    if (shard_epoch_[shard].compare_exchange_weak(
            cur, epoch, std::memory_order_acq_rel)) {
      cache_gen_.fetch_add(1, std::memory_order_acq_rel);
      return;
    }
  }
}

uint64_t RemoteGraph::Epoch() const {
  uint64_t mx = 0;
  if (shard_epoch_)
    for (int s = 0; s < num_shards_; ++s)
      mx = std::max(mx, shard_epoch_[s].load(std::memory_order_relaxed));
  return mx;
}

bool RemoteGraph::LoadDelta(int shard, const std::string& path,
                            uint64_t* new_epoch, std::string* error) const {
  if (shard < 0 || shard >= num_shards_) {
    *error = "bad shard index " + std::to_string(shard);
    return false;
  }
  WireWriter req;
  req.U8(kLoadDelta);
  req.Str(path);
  std::string reply;
  // raw pool call (not Call): an error status must surface the shard's
  // message, not collapse into a counted frame reject
  if (!pools_[shard].Call(req.buf(), &reply, retries_, timeout_ms_,
                          quarantine_ms_, backoff_ms_, deadline_ms_)) {
    *error = "shard " + std::to_string(shard) +
             " unreachable for load_delta";
    return false;
  }
  WireReader r(reply);
  uint8_t status = r.U8();
  if (status != kStatusOk) {
    std::string msg = r.Str();
    *error = r.ok() && !msg.empty()
                 ? msg
                 : "load_delta failed on shard " + std::to_string(shard);
    return false;
  }
  // the v4 epoch stamp was already stripped (and observed — this
  // client's caches invalidated) by ConnPool; the body is the new epoch
  *new_epoch = r.U64();
  if (!r.ok()) {
    *error = "malformed load_delta reply from shard " +
             std::to_string(shard);
    return false;
  }
  // belt over suspenders for pre-stamp replicas: the reply body itself
  // announces the flip even when the envelope negotiated below v4
  ObserveEpoch(shard, *new_epoch);
  return true;
}

bool RemoteGraph::PingShard(int shard) const {
  if (shard < 0 || shard >= num_shards_) return false;
  WireWriter req;
  req.U8(kPing);
  std::string reply;
  // reply is the bare ok-status byte; Call already validated it
  return Call(shard, req.buf(), &reply);
}

bool RemoteGraph::ScrapeShard(int shard, std::string* json) const {
  if (shard < 0 || shard >= num_shards_) return false;
  WireWriter req;
  req.U8(kStats);
  std::string reply;
  if (!Call(shard, req.buf(), &reply)) return false;
  WireReader r(reply);
  r.U8();  // status already checked in Call
  *json = r.Str();
  return r.ok();
}

bool RemoteGraph::HistoryShard(int shard, std::string* json) const {
  if (shard < 0 || shard >= num_shards_) return false;
  WireWriter req;
  req.U8(kHistory);
  std::string reply;
  if (!Call(shard, req.buf(), &reply)) return false;
  WireReader r(reply);
  r.U8();  // status already checked in Call
  *json = r.Str();
  return r.ok();
}

bool RemoteGraph::HeatShard(int shard, std::string* json) const {
  if (shard < 0 || shard >= num_shards_) return false;
  WireWriter req;
  req.U8(kHeat);
  std::string reply;
  if (!Call(shard, req.buf(), &reply)) return false;
  WireReader r(reply);
  r.U8();  // status already checked in Call
  *json = r.Str();
  return r.ok();
}

std::string RemoteGraph::TakeStrictError() const {
  std::lock_guard<std::mutex> l(strict_mu_);
  std::string out;
  out.swap(strict_error_);
  return out;
}

void RemoteGraph::ShardFailed(int shard, const char* what) const {
  // Pre-dispatcher ForShards threw this bool away: a fully-failed shard
  // silently yielded default rows. Now every op-level shard failure is
  // at least counted, and under strict= it surfaces as an error.
  Counters::Global().Add(kCtrRpcError);
  if (!strict_) return;
  std::lock_guard<std::mutex> l(strict_mu_);
  if (strict_error_.empty())
    strict_error_ = std::string(what) + ": shard " + std::to_string(shard) +
                    " failed after all transport retries (strict=1; see "
                    "rpc_errors/calls_failed counters)";
}

void RemoteGraph::GroupByShard(const uint64_t* ids, int n,
                               std::vector<std::vector<int32_t>>* rows) const {
  rows->assign(num_shards_, {});
  for (int i = 0; i < n; ++i) (*rows)[ShardOf(ids[i])].push_back(i);
}

void RemoteGraph::BuildPlan(const uint64_t* ids, int n,
                            ShardPlan* p) const {
  p->rows.assign(num_shards_, {});
  p->reps.assign(num_shards_, {});
  p->shard_of.assign(n, -1);
  p->pos_of.assign(n, 0);
  p->occ_of.assign(n, 0);
  p->coalesced = 0;
  if (!coalesce_) {
    for (int i = 0; i < n; ++i) {
      int s = ShardOf(ids[i]);
      p->shard_of[i] = s;
      p->pos_of[i] = static_cast<int32_t>(p->rows[s].size());
      p->rows[s].push_back(i);
      p->reps[s].push_back(1);
    }
    return;
  }
  // id -> position within its shard's unique list (the shard itself is a
  // pure function of the id, so it needs no storing)
  std::unordered_map<uint64_t, int32_t> seen;
  seen.reserve(static_cast<size_t>(n) * 2);
  for (int i = 0; i < n; ++i) {
    int s = ShardOf(ids[i]);
    auto [it, fresh] = seen.emplace(ids[i], 0);
    if (fresh) {
      it->second = static_cast<int32_t>(p->rows[s].size());
      p->rows[s].push_back(i);
      p->reps[s].push_back(1);
    } else {
      ++p->reps[s][it->second];
      ++p->coalesced;
    }
    p->shard_of[i] = s;
    p->pos_of[i] = it->second;
    p->occ_of[i] = p->reps[s][it->second] - 1;
  }
  if (p->coalesced)
    Counters::Global().Add(kCtrIdsDeduped,
                           static_cast<uint64_t>(p->coalesced));
}

void RemoteGraph::BuildEdgePlan(const uint64_t* src, int n,
                                ShardPlan* p) const {
  p->rows.assign(num_shards_, {});
  p->reps.assign(num_shards_, {});
  p->shard_of.assign(n, -1);
  p->pos_of.assign(n, 0);
  p->occ_of.assign(n, 0);
  p->coalesced = 0;
  for (int i = 0; i < n; ++i) {
    int s = ShardOf(src[i]);
    p->shard_of[i] = s;
    p->pos_of[i] = static_cast<int32_t>(p->rows[s].size());
    p->rows[s].push_back(i);
    p->reps[s].push_back(1);
  }
}

void RemoteGraph::ForShards(const std::vector<std::vector<int32_t>>& rows,
                            const char* what,
                            const std::function<bool(int)>& fn) const {
  std::vector<std::function<void()>> jobs;
  jobs.reserve(rows.size());
  for (int s = 0; s < static_cast<int>(rows.size()); ++s)
    if (!rows[s].empty())
      jobs.emplace_back([this, &fn, s, what] {
        // flight recorder: timestamp this worker picking up a shard
        // job, so a postmortem shows which shards the dispatcher pool
        // was fanning out to in its final seconds
        Blackbox::Global().Record(kBbDispatch, 0, s, 0, 0, 0);
        bool ok = false;
        try {
          ok = fn(s);
        } catch (...) {
          // a throwing shard call degrades like a failed one — its rows
          // keep their prefilled defaults (and the failure is recorded)
          ok = false;
        }
        if (!ok) ShardFailed(s, what);
      });
  dispatcher_->Run(jobs);
}

void RemoteGraph::RunChunked(
    const std::vector<std::vector<int32_t>>& lists, const char* what,
    const std::function<bool(int, int32_t, int32_t)>& chunk_fn) const {
  std::vector<std::function<void()>> jobs;
  for (int s = 0; s < static_cast<int>(lists.size()); ++s) {
    int32_t m = static_cast<int32_t>(lists[s].size());
    if (m == 0) continue;
    int32_t step = std::min<int32_t>(chunk_ids_, m);
    if (m > step)
      Counters::Global().Add(kCtrRpcChunk,
                             static_cast<uint64_t>((m + step - 1) / step));
    for (int32_t b = 0; b < m; b += step) {
      int32_t e = std::min(m, b + step);
      jobs.emplace_back([this, &chunk_fn, s, b, e, what] {
        Blackbox::Global().Record(kBbDispatch, 0, s, 0,
                                  static_cast<uint64_t>(e - b), 0);
        bool ok = false;
        try {
          ok = chunk_fn(s, b, e);
        } catch (...) {
          ok = false;
        }
        if (!ok) ShardFailed(s, what);
      });
    }
  }
  dispatcher_->Run(jobs);
}

void RemoteGraph::DrawShards(bool edges, int32_t type, int count,
                             int* out) const {
  Rng& rng = ThreadRng();
  const PrefixTable* table;
  if (type < 0)
    table = edges ? &edge_shard_total_ : &node_shard_total_;
  else
    table = edges ? &edge_shard_by_type_[type] : &node_shard_by_type_[type];
  for (int i = 0; i < count; ++i)
    out[i] = static_cast<int>(table->Draw(rng));
}

void RemoteGraph::SampleNode(int count, int32_t type, uint64_t* out) const {
  if (count <= 0) return;
  if (type >= node_type_num_) {
    std::fill(out, out + count, 0);
    return;
  }
  // Per-draw shard assignment proportional to shard weight sums, then one
  // batched SampleNode per shard, results distributed back to draw slots —
  // iid-equivalent to the reference's multinomial split + concat
  // (REMOTE_SAMPLE, remote_graph.cc:195-221).
  std::vector<int> draw_shard(count);
  DrawShards(false, type, count, draw_shard.data());
  std::vector<std::vector<int32_t>> rows(num_shards_);
  for (int i = 0; i < count; ++i) rows[draw_shard[i]].push_back(i);
  std::fill(out, out + count, 0);
  ForShards(rows, "sample_node", [&](int s) {
    WireWriter req;
    req.U8(kSampleNode);
    req.I32(static_cast<int32_t>(rows[s].size()));
    req.I32(type);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    int64_t m;
    const uint64_t* ids = r.Arr<uint64_t>(&m);
    if (!r.ok() || m != static_cast<int64_t>(rows[s].size())) return false;
    for (int64_t j = 0; j < m; ++j) out[rows[s][j]] = ids[j];
    return true;
  });
}

void RemoteGraph::SampleEdge(int count, int32_t type, uint64_t* out_src,
                             uint64_t* out_dst, int32_t* out_type) const {
  if (count <= 0) return;
  std::fill(out_src, out_src + count, 0);
  std::fill(out_dst, out_dst + count, 0);
  std::fill(out_type, out_type + count, -1);
  if (type >= edge_type_num_) return;
  std::vector<int> draw_shard(count);
  DrawShards(true, type, count, draw_shard.data());
  std::vector<std::vector<int32_t>> rows(num_shards_);
  for (int i = 0; i < count; ++i) rows[draw_shard[i]].push_back(i);
  ForShards(rows, "sample_edge", [&](int s) {
    WireWriter req;
    req.U8(kSampleEdge);
    req.I32(static_cast<int32_t>(rows[s].size()));
    req.I32(type);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    int64_t m, m2, m3;
    const uint64_t* src = r.Arr<uint64_t>(&m);
    const uint64_t* dst = r.Arr<uint64_t>(&m2);
    const int32_t* t = r.Arr<int32_t>(&m3);
    if (!r.ok() || m != static_cast<int64_t>(rows[s].size()) || m2 != m ||
        m3 != m)
      return false;
    for (int64_t j = 0; j < m; ++j) {
      out_src[rows[s][j]] = src[j];
      out_dst[rows[s][j]] = dst[j];
      out_type[rows[s][j]] = t[j];
    }
    return true;
  });
}

void RemoteGraph::GetNodeType(const uint64_t* ids, int n,
                              int32_t* out) const {
  std::fill(out, out + n, -1);
  if (n <= 0) return;
  ShardPlan plan;
  BuildPlan(ids, n, &plan);
  // per-shard staging over UNIQUE entries; chunks write disjoint ranges
  std::vector<std::vector<int32_t>> got(num_shards_);
  std::vector<std::vector<char>> ok(num_shards_);
  for (int s = 0; s < num_shards_; ++s) {
    got[s].assign(plan.rows[s].size(), -1);
    ok[s].assign(plan.rows[s].size(), 0);
  }
  RunChunked(plan.rows, "node_type", [&](int s, int32_t b, int32_t e) {
    std::vector<uint64_t> sub(static_cast<size_t>(e - b));
    for (int32_t j = b; j < e; ++j) sub[j - b] = ids[plan.rows[s][j]];
    // heat feed (eg_heat.h): every id that goes on the wire,
    // post-coalesce, tagged by op — this runs ON the dispatcher worker
    Heat::Global().Record(kHeatClient, kNodeType, sub.data(),
                          static_cast<int64_t>(sub.size()));
    WireWriter req;
    req.U8(kNodeType);
    req.Arr(sub);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    int64_t m;
    const int32_t* t = r.Arr<int32_t>(&m);
    if (!r.ok() || m != static_cast<int64_t>(sub.size())) return false;
    for (int64_t j = 0; j < m; ++j) {
      got[s][b + j] = t[j];
      ok[s][b + j] = 1;
    }
    return true;
  });
  for (int i = 0; i < n; ++i) {
    int s = plan.shard_of[i];
    if (s >= 0 && ok[s][plan.pos_of[i]]) out[i] = got[s][plan.pos_of[i]];
  }
}

bool RemoteGraph::GetNodeWeight(const uint64_t* ids, int n,
                                float* out) const {
  std::fill(out, out + n, 0.f);
  if (n <= 0) return true;
  ShardPlan plan;
  BuildPlan(ids, n, &plan);
  std::vector<std::vector<float>> got(num_shards_);
  std::vector<std::vector<char>> ok(num_shards_);
  for (int s = 0; s < num_shards_; ++s) {
    got[s].assign(plan.rows[s].size(), 0.f);
    ok[s].assign(plan.rows[s].size(), 0);
  }
  RunChunked(plan.rows, "node_weight", [&](int s, int32_t b, int32_t e) {
    std::vector<uint64_t> sub(static_cast<size_t>(e - b));
    for (int32_t j = b; j < e; ++j) sub[j - b] = ids[plan.rows[s][j]];
    Heat::Global().Record(kHeatClient, kNodeWeight, sub.data(),
                          static_cast<int64_t>(sub.size()));
    WireWriter req;
    req.U8(kNodeWeight);
    req.Arr(sub);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    int64_t m;
    const float* w = r.Arr<float>(&m);
    if (!r.ok() || m != static_cast<int64_t>(sub.size())) return false;
    for (int64_t j = 0; j < m; ++j) {
      got[s][b + j] = w[j];
      ok[s][b + j] = 1;
    }
    return true;
  });
  // Unlike the query ops (which degrade failed rows to defaults), a
  // weight silently read as 0 would bias the exported device sampler —
  // so any missing unique row fails the whole batch.
  for (int s = 0; s < num_shards_; ++s)
    for (char f : ok[s])
      if (!f) return false;
  for (int i = 0; i < n; ++i) {
    int s = plan.shard_of[i];
    if (s >= 0) out[i] = got[s][plan.pos_of[i]];
  }
  return true;
}

void RemoteGraph::SampleNodeWithSrc(const uint64_t* src, int n, int count,
                                    uint64_t* out) const {
  // Engine semantics (eg_engine.cc SampleNodeWithSrc): each row samples
  // `count` nodes from the global sampler of the src node's type (type -1 —
  // missing src — falls back to the all-types sampler). Remotely: resolve
  // src types, draw a shard per (row, draw) from that type's cross-shard
  // table, batch one SampleNode per (shard, type) on the dispatcher.
  std::vector<int32_t> types(n);
  GetNodeType(src, n, types.data());
  Rng& rng = ThreadRng();
  int64_t total = static_cast<int64_t>(n) * count;
  std::fill(out, out + total, 0);
  // (shard, type) -> slot list into out
  std::map<std::pair<int, int32_t>, std::vector<int64_t>> groups;
  for (int i = 0; i < n; ++i) {
    int32_t t = types[i] >= 0 && types[i] < node_type_num_ ? types[i] : -1;
    const PrefixTable& table =
        t < 0 ? node_shard_total_ : node_shard_by_type_[t];
    for (int j = 0; j < count; ++j) {
      int s = static_cast<int>(table.Draw(rng));
      groups[{s, t}].push_back(static_cast<int64_t>(i) * count + j);
    }
  }
  std::vector<std::function<void()>> jobs;
  jobs.reserve(groups.size());
  for (auto& [key, slots] : groups) {
    jobs.emplace_back([this, &key = key, &slots = slots, out] {
      bool ok = false;
      try {
        WireWriter req;
        req.U8(kSampleNode);
        req.I32(static_cast<int32_t>(slots.size()));
        req.I32(key.second);
        std::string reply;
        if (Call(key.first, req.buf(), &reply)) {
          WireReader r(reply);
          r.U8();
          int64_t m;
          const uint64_t* ids = r.Arr<uint64_t>(&m);
          if (r.ok() && m == static_cast<int64_t>(slots.size())) {
            for (int64_t j = 0; j < m; ++j) out[slots[j]] = ids[j];
            ok = true;
          }
        }
      } catch (...) {
        // this group's slots keep their prefilled zeros, like a failed
        // Call (the failure is recorded below)
        ok = false;
      }
      if (!ok) ShardFailed(key.first, "sample_node_with_src");
    });
  }
  dispatcher_->Run(jobs);
}

void RemoteGraph::NbrPrep(NbrCall* c) const {
  int64_t total = static_cast<int64_t>(c->n) * c->count;
  if (total > 0) {
    std::fill(c->out_ids, c->out_ids + total, c->default_id);
    std::fill(c->out_w, c->out_w + total, 0.f);
    std::fill(c->out_t, c->out_t + total, -1);
  }
  if (c->n <= 0 || c->count <= 0) return;
  BuildPlan(c->ids, c->n, &c->plan);
  // Per-shard staging over the unique entries' draw blocks: unique entry
  // j owns reps[j] * count contiguous draws at rep_off[j] * count; each
  // original row takes the block at (rep_off[pos] + occ) * count, so
  // duplicate rows receive DISTINCT (still iid) draws.
  //
  // Locality split (eg_cache.h NeighborCache): each unique entry takes
  // one of three paths —
  //   * CACHED: its adjacency slice is in the neighbor cache; sample
  //     locally, zero wire bytes (nbr_cache_hits);
  //   * PROMOTE: the heat sketch marks it hot (est >=
  //     kNbrPromoteMinFreq) — fetch its FULL slice once (kFullNeighbor),
  //     cache it, sample locally from the fetched slice;
  //   * FETCH: cold — the plain per-draw wire path, as before.
  Heat& heat = Heat::Global();
  c->heat_on = heat.enabled();
  c->use_ncache = ncache_.enabled();
  // Snapshot-epoch capture (eg_epoch.h): unless the async chain already
  // stamped a whole-op capture into this slice, pin the call to the
  // generation/epochs observed NOW — every cache probe and wire chunk of
  // this call then reads one consistent snapshot even if a flip lands
  // mid-call.
  if (!c->epoch_captured) {
    c->gen = cache_gen_.load(std::memory_order_acquire);
    if (shard_epoch_) {
      c->pin.assign(static_cast<size_t>(num_shards_), 0);
      for (int s = 0; s < num_shards_; ++s)
        c->pin[s] = shard_epoch_[s].load(std::memory_order_relaxed);
    }
    c->epoch_captured = true;
  }
  c->nspec = c->use_ncache ? NeighborCache::SpecHash(c->etypes, c->net) : 0;
  c->rep_off.assign(num_shards_, {});
  c->sid.assign(num_shards_, {});
  c->sw.assign(num_shards_, {});
  c->st.assign(num_shards_, {});
  c->ok.assign(num_shards_, {});
  // unique positions per shard still needing the wire, by path
  c->fetch.assign(num_shards_, {});
  c->promote.assign(num_shards_, {});
  for (int s = 0; s < num_shards_; ++s) {
    size_t m = c->plan.rows[s].size();
    c->rep_off[s].assign(m + 1, 0);
    for (size_t j = 0; j < m; ++j)
      c->rep_off[s][j + 1] = c->rep_off[s][j] + c->plan.reps[s][j];
    size_t draws = static_cast<size_t>(c->rep_off[s][m]) * c->count;
    c->sid[s].assign(draws, c->default_id);
    c->sw[s].assign(draws, 0.f);
    c->st[s].assign(draws, -1);
    c->ok[s].assign(m, 0);
    if (m == 0) continue;
    std::vector<uint64_t> sub(m);
    for (size_t j = 0; j < m; ++j) sub[j] = c->ids[c->plan.rows[s][j]];
    // heat feed: every unique id, post-coalesce but PRE-cache — cache
    // hits are accesses too, and both the promotion gate and the
    // TinyLFU admission read these estimates (this access included)
    if (c->heat_on)
      heat.Record(kHeatClient,
                  coalesce_ ? kSampleNeighborUniq : kSampleNeighbor,
                  sub.data(), static_cast<int64_t>(m));
    Rng& rng = ThreadRng();
    for (size_t j = 0; j < m; ++j) {
      if (c->use_ncache) {
        int64_t draws_j =
            static_cast<int64_t>(c->plan.reps[s][j]) * c->count;
        int64_t dst = c->rep_off[s][j] * c->count;
        if (ncache_.Sample(c->nspec, sub[j], static_cast<int>(draws_j),
                           c->default_id, rng, c->sid[s].data() + dst,
                           c->sw[s].data() + dst, c->st[s].data() + dst,
                           c->gen)) {
          c->ok[s][j] = 1;
          ++c->nbr_hits;
          continue;
        }
        ++c->nbr_misses;
        if (c->heat_on &&
            heat.Estimate(kHeatClient, sub[j]) >= kNbrPromoteMinFreq) {
          c->promote[s].push_back(static_cast<int32_t>(j));
          continue;
        }
      }
      c->fetch[s].push_back(static_cast<int32_t>(j));
    }
  }
  Counters& ctr = Counters::Global();
  if (c->nbr_hits) ctr.Add(kCtrNbrCacheHit, c->nbr_hits);
  if (c->nbr_misses) ctr.Add(kCtrNbrCacheMiss, c->nbr_misses);
}

bool RemoteGraph::NbrFetchChunk(NbrCall* c, int s, int32_t b,
                                int32_t e) const {
  int32_t m = e - b;
  std::vector<uint64_t> sub(static_cast<size_t>(m));
  std::vector<int32_t> subreps(static_cast<size_t>(m));
  for (int32_t x = 0; x < m; ++x) {
    int32_t pos = c->fetch[s][b + x];
    sub[x] = c->ids[c->plan.rows[s][pos]];
    subreps[x] = c->plan.reps[s][pos];
  }
  WireWriter req;
  if (coalesce_) {
    // dedup'd form: each unique id once, with its repeat count
    req.U8(kSampleNeighborUniq);
    req.Arr(sub);
    req.Arr(subreps);
  } else {
    // pre-dedup wire shape (the bench A/B baseline); reps are all 1
    // here, so the reply layout is identical
    req.U8(kSampleNeighbor);
    req.Arr(sub);
  }
  req.Arr(c->etypes, c->net);
  req.I32(c->count);
  req.U64(c->default_id);
  std::string reply;
  if (!Call(s, req.buf(), &reply,
            c->pin.empty() ? 0 : c->pin[static_cast<size_t>(s)]))
    return false;
  Heat::Global().AddShardBytes(s, req.buf().size(), reply.size());
  WireReader r(reply);
  r.U8();
  int64_t mi, mw, mt;
  const uint64_t* rid = r.Arr<uint64_t>(&mi);
  const float* rw = r.Arr<float>(&mw);
  const int32_t* rt = r.Arr<int32_t>(&mt);
  int64_t want = 0;
  for (int32_t x = 0; x < m; ++x)
    want += static_cast<int64_t>(subreps[x]) * c->count;
  if (!r.ok() || mi != want || mw != want || mt != want) return false;
  // the fetched entries are a subset of the unique list, so their
  // reply blocks scatter per entry (no contiguous rep_off range)
  int64_t src = 0;
  for (int32_t x = 0; x < m; ++x) {
    int32_t pos = c->fetch[s][b + x];
    int64_t draws_x = static_cast<int64_t>(subreps[x]) * c->count;
    int64_t dst = c->rep_off[s][pos] * c->count;
    std::copy(rid + src, rid + src + draws_x, c->sid[s].begin() + dst);
    std::copy(rw + src, rw + src + draws_x, c->sw[s].begin() + dst);
    std::copy(rt + src, rt + src + draws_x, c->st[s].begin() + dst);
    c->ok[s][pos] = 1;
    src += draws_x;
  }
  return true;
}

bool RemoteGraph::NbrPromoteChunk(NbrCall* c, int s, int32_t b,
                                  int32_t e) const {
  int32_t m = e - b;
  std::vector<uint64_t> sub(static_cast<size_t>(m));
  for (int32_t x = 0; x < m; ++x)
    sub[x] = c->ids[c->plan.rows[s][c->promote[s][b + x]]];
  WireWriter req;
  req.U8(kFullNeighbor);
  req.Arr(sub);
  req.Arr(c->etypes, c->net);
  req.U8(0);
  std::string reply;
  if (!Call(s, req.buf(), &reply,
            c->pin.empty() ? 0 : c->pin[static_cast<size_t>(s)]))
    return false;
  Heat::Global().AddShardBytes(s, req.buf().size(), reply.size());
  WireReader r(reply);
  r.U8();
  EGResult res;
  if (!ReadResult(&r, &res)) return false;
  if (res.i32.size() != 2 || res.u64.size() != 1 || res.f32.size() != 1 ||
      res.i32[1].size() != static_cast<size_t>(m))
    return false;
  int64_t want = 0;
  for (int32_t x = 0; x < m; ++x) {
    if (res.i32[1][x] < 0) return false;
    want += res.i32[1][x];
  }
  if (res.u64[0].size() != static_cast<size_t>(want) ||
      res.f32[0].size() != static_cast<size_t>(want) ||
      res.i32[0].size() != static_cast<size_t>(want))
    return false;
  Rng& rng = ThreadRng();
  int64_t off = 0;
  for (int32_t x = 0; x < m; ++x) {
    int32_t pos = c->promote[s][b + x];
    int64_t len = res.i32[1][x];
    const uint64_t* nid = res.u64[0].data() + off;
    const float* nw = res.f32[0].data() + off;
    const int32_t* nt = res.i32[0].data() + off;
    // cache the slice for every later call (TinyLFU admission
    // may still refuse it — the draws below don't depend on
    // that verdict, the slice is in hand either way)
    ncache_.Put(c->nspec, sub[x], nid, nw, nt, static_cast<size_t>(len),
                c->gen);
    int64_t draws_x = static_cast<int64_t>(c->plan.reps[s][pos]) * c->count;
    int64_t dst = c->rep_off[s][pos] * c->count;
    DrawFromSlice(nid, nw, nt, len, draws_x, c->default_id, rng,
                  c->sid[s].data() + dst, c->sw[s].data() + dst,
                  c->st[s].data() + dst);
    c->ok[s][pos] = 1;
    off += len;
  }
  return true;
}

void RemoteGraph::NbrBuildJobs(
    NbrCall* c, std::vector<std::function<void()>>* jobs) const {
  // Same chunk splitting + counting + failure wrapping as RunChunked,
  // but emitting into a caller-owned job list: fetch and promote chunks
  // ride ONE dispatcher batch (their staged writes are disjoint —
  // rep_off blocks per unique entry, ok[] entries per path, the caches
  // internally locked), which is what lets the async path treat a whole
  // slice as a single detached batch with one completion continuation.
  Counters& ctr = Counters::Global();
  auto chunked = [&](const std::vector<std::vector<int32_t>>& lists,
                     bool promote_path) {
    for (int s = 0; s < static_cast<int>(lists.size()); ++s) {
      int32_t m = static_cast<int32_t>(lists[s].size());
      if (m == 0) continue;
      int32_t step = std::min<int32_t>(chunk_ids_, m);
      if (m > step)
        ctr.Add(kCtrRpcChunk, static_cast<uint64_t>((m + step - 1) / step));
      for (int32_t b = 0; b < m; b += step) {
        int32_t e = std::min(m, b + step);
        jobs->emplace_back([this, c, s, b, e, promote_path] {
          Blackbox::Global().Record(kBbDispatch, 0, s, 0,
                                    static_cast<uint64_t>(e - b), 0);
          bool ok = false;
          try {
            ok = promote_path ? NbrPromoteChunk(c, s, b, e)
                              : NbrFetchChunk(c, s, b, e);
          } catch (...) {
            // a throwing shard call degrades like a failed one — its
            // entries keep their prefilled defaults
            ok = false;
          }
          if (!ok) ShardFailed(s, "sample_neighbor");
        });
      }
    }
  };
  chunked(c->fetch, false);
  if (c->use_ncache) chunked(c->promote, true);
}

void RemoteGraph::NbrFinish(NbrCall* c) const {
  // fan-out attribution (eg_heat.h): ids_on_wire MEASURED as the sum of
  // the per-shard fetch + promote lists (what was actually encoded), so
  // the heat surface's ledger identity (ids_on_wire == ids_requested -
  // ids_deduped - cache_hits) is a real cross-check of the coalescing
  // plan AND the neighbor cache, not a restatement. cache_hits here are
  // NEIGHBOR-cache hits (locally sampled entries).
  if (c->heat_on) {
    uint64_t on_wire = 0;
    int touched = 0;
    for (int s = 0; s < num_shards_; ++s) {
      uint64_t wire_s = c->fetch[s].size() + c->promote[s].size();
      if (wire_s) {
        ++touched;
        on_wire += wire_s;
      }
    }
    Heat::Global().RecordFanout(kSampleNeighbor,
                                static_cast<uint64_t>(c->n),
                                static_cast<uint64_t>(c->plan.coalesced),
                                c->nbr_hits, on_wire, touched);
  }
  for (int i = 0; i < c->n; ++i) {
    int s = c->plan.shard_of[i];
    int32_t pos = c->plan.pos_of[i];
    if (s < 0 || !c->ok[s][pos]) continue;
    int64_t src_off = (c->rep_off[s][pos] + c->plan.occ_of[i]) * c->count;
    int64_t dst_off = static_cast<int64_t>(i) * c->count;
    std::copy(c->sid[s].begin() + src_off,
              c->sid[s].begin() + src_off + c->count,
              c->out_ids + dst_off);
    std::copy(c->sw[s].begin() + src_off,
              c->sw[s].begin() + src_off + c->count, c->out_w + dst_off);
    std::copy(c->st[s].begin() + src_off,
              c->st[s].begin() + src_off + c->count, c->out_t + dst_off);
  }
}

void RemoteGraph::SampleNeighbor(const uint64_t* ids, int n,
                                 const int32_t* etypes, int net, int count,
                                 uint64_t default_id, uint64_t* out_ids,
                                 float* out_w, int32_t* out_t) const {
  // The sync path over the shared phases: the caller's stack holds the
  // staging and Dispatcher::Run is the completion barrier. Same code
  // the async hop chain runs, so the two are distribution-identical.
  NbrCall c;
  c.ids = ids;
  c.n = n;
  c.etypes = etypes;
  c.net = net;
  c.count = count;
  c.default_id = default_id;
  c.out_ids = out_ids;
  c.out_w = out_w;
  c.out_t = out_t;
  NbrPrep(&c);
  if (c.n <= 0 || c.count <= 0) return;
  std::vector<std::function<void()>> jobs;
  NbrBuildJobs(&c, &jobs);
  dispatcher_->Run(jobs);
  NbrFinish(&c);
}

void RemoteGraph::SampleFanout(const uint64_t* ids, int n,
                               const int32_t* etypes_flat,
                               const int32_t* etype_counts,
                               const int32_t* counts, int nhops,
                               uint64_t default_id, uint64_t** out_ids,
                               float** out_w, int32_t** out_t) const {
  const uint64_t* cur = ids;
  int64_t cur_n = n;
  const int32_t* et = etypes_flat;
  // n * prod(counts) passes 2^31 at deep fanouts; the old
  // static_cast<int>(cur_n) silently truncated there. Issue each hop in
  // INT_MAX-bounded slices instead — the per-row scatter makes slicing
  // invisible to the result.
  const int64_t kSlice = int64_t{1} << 30;
  for (int h = 0; h < nhops; ++h) {
    for (int64_t off = 0; off < cur_n; off += kSlice) {
      int m = static_cast<int>(std::min<int64_t>(kSlice, cur_n - off));
      SampleNeighbor(cur + off, m, et, etype_counts[h], counts[h],
                     default_id, out_ids[h] + off * counts[h],
                     out_w[h] + off * counts[h], out_t[h] + off * counts[h]);
    }
    cur = out_ids[h];
    cur_n *= counts[h];
    et += etype_counts[h];
  }
}

namespace {

// SampleFanout's INT_MAX-bounded hop slicing, shared with the async
// cursor so both paths walk identical (hop, slice) sequences.
constexpr int64_t kFanoutSlice = int64_t{1} << 30;

// Step op's cursor past the slice just completed: next slice of the
// same hop, or the first slice of the next hop (the finished hop's
// output becomes the frontier). Single-writer — see eg_async.h.
void AdvanceFanoutCursor(AsyncSampleOp* op) {
  op->slice_off += kFanoutSlice;
  if (op->slice_off >= op->cur_n) {
    op->cur = op->out_ids[op->hop];
    op->cur_n *= op->counts[op->hop];
    op->et += op->etype_counts[op->hop];
    ++op->hop;
    op->slice_off = 0;
  }
}

}  // namespace

void RemoteGraph::StartSlice(AsyncSampleOp* op) const {
  for (;;) {
    if (op->hop >= op->nhops) {
      // whole fan-out complete: publish kDone under async_mu_ — the
      // lock is the happens-before edge to Poll/Take readers of the
      // output buffers the chain just wrote
      std::lock_guard<std::mutex> l(async_mu_);
      op->state = AsyncSampleOp::kDone;
      --async_inflight_;
      async_cv_.notify_all();
      return;
    }
    int h = op->hop;
    int64_t off = op->slice_off;
    int m = static_cast<int>(
        std::min<int64_t>(kFanoutSlice, op->cur_n - off));
    op->call = std::make_unique<NbrCall>();
    NbrCall* c = op->call.get();
    c->ids = op->cur + off;
    c->n = m;
    c->etypes = op->et;
    c->net = op->etype_counts[h];
    c->count = op->counts[h];
    c->default_id = op->default_id;
    c->out_ids = op->out_ids[h] + off * op->counts[h];
    c->out_w = op->out_w[h] + off * op->counts[h];
    c->out_t = op->out_t[h] + off * op->counts[h];
    // whole-op epoch capture: every slice of this step reads the
    // snapshot stamped at submit, even if a shard flips between hops
    c->gen = op->gen;
    c->pin = op->pin;
    c->epoch_captured = true;
    NbrPrep(c);
    std::vector<std::function<void()>> jobs;
    if (c->n > 0 && c->count > 0) NbrBuildJobs(c, &jobs);
    if (!jobs.empty()) {
      // hop h+1's jobs will be enqueued by THIS batch's completing
      // worker — never by a blocked caller thread
      Counters::Global().Add(kCtrAsyncContinuation);
      dispatcher_->SubmitDetached(std::move(jobs), [this, op] {
        try {
          OnSliceDone(op);
        } catch (...) {
          // eg-lint thread-catch: never kill the worker — mark the op
          // done (completed slices are intact, this one keeps its
          // prefilled defaults) so TakeAsync cannot hang
          std::lock_guard<std::mutex> l(async_mu_);
          if (op->state == AsyncSampleOp::kRunning) {
            op->state = AsyncSampleOp::kDone;
            --async_inflight_;
            async_cv_.notify_all();
          }
        }
      });
      return;
    }
    // zero wire work (empty slice, or every unique entry served from
    // the neighbor cache): finish inline and keep walking the cursor
    // on this thread — a loop, not recursion, so a deep fully-cached
    // fan-out cannot grow the stack
    if (c->n > 0 && c->count > 0) NbrFinish(c);
    op->call.reset();
    AdvanceFanoutCursor(op);
  }
}

void RemoteGraph::OnSliceDone(AsyncSampleOp* op) const {
  NbrFinish(op->call.get());
  op->call.reset();
  AdvanceFanoutCursor(op);
  StartSlice(op);
}

int RemoteGraph::SampleFanoutAsync(const uint64_t* ids, int n,
                                   const int32_t* etypes_flat,
                                   const int32_t* etype_counts,
                                   const int32_t* counts, int nhops,
                                   uint64_t default_id, uint64_t** out_ids,
                                   float** out_w, int32_t** out_t) const {
  if (n < 0 || nhops <= 0 || !dispatcher_) return -1;
  int slot = -1;
  {
    std::lock_guard<std::mutex> l(async_mu_);
    for (int i = 0; i < kMaxAsyncOps; ++i) {
      if (async_ops_[i].state == AsyncSampleOp::kFree) {
        slot = i;
        break;
      }
    }
    if (slot < 0) return -1;  // pool full: the caller degrades to sync
    async_ops_[slot].state = AsyncSampleOp::kRunning;
    ++async_inflight_;
    Counters::Global().Add(kCtrAsyncSubmit);
    Counters::Global().Max(kCtrAsyncInflightPeak,
                           static_cast<uint64_t>(async_inflight_));
  }
  AsyncSampleOp& op = async_ops_[slot];
  // deep-copy the request: the submitting frame (a ctypes call from the
  // Python pipeline driver) unwinds immediately; outputs stay borrowed
  // (the caller pins them until TakeAsync)
  int net_total = 0;
  for (int h = 0; h < nhops; ++h) net_total += etype_counts[h];
  op.ids.assign(ids, ids + n);
  op.etypes_flat.assign(etypes_flat, etypes_flat + net_total);
  op.etype_counts.assign(etype_counts, etype_counts + nhops);
  op.counts.assign(counts, counts + nhops);
  op.n = n;
  op.nhops = nhops;
  op.default_id = default_id;
  op.out_ids.assign(out_ids, out_ids + nhops);
  op.out_w.assign(out_w, out_w + nhops);
  op.out_t.assign(out_t, out_t + nhops);
  op.hop = 0;
  op.slice_off = 0;
  op.cur_n = n;
  op.cur = op.ids.data();
  op.et = op.etypes_flat.data();
  // stamp the whole-op epoch capture once, at submit: a flip that lands
  // while this step's continuation chain is in flight must not tear the
  // step across snapshots (tests/test_epoch.py pins bit-parity here)
  op.gen = cache_gen_.load(std::memory_order_acquire);
  op.pin.clear();
  if (shard_epoch_) {
    op.pin.resize(static_cast<size_t>(num_shards_), 0);
    for (int s = 0; s < num_shards_; ++s)
      op.pin[static_cast<size_t>(s)] =
          shard_epoch_[s].load(std::memory_order_relaxed);
  }
  StartSlice(&op);
  return slot;
}

int RemoteGraph::PollAsync(int slot) const {
  if (slot < 0 || slot >= kMaxAsyncOps) return -1;
  std::lock_guard<std::mutex> l(async_mu_);
  int st = async_ops_[slot].state;
  if (st == AsyncSampleOp::kFree) return -1;
  return st == AsyncSampleOp::kDone ? 1 : 0;
}

int RemoteGraph::TakeAsync(int slot) const {
  if (slot < 0 || slot >= kMaxAsyncOps) return -1;
  std::unique_lock<std::mutex> l(async_mu_);
  AsyncSampleOp& op = async_ops_[slot];
  if (op.state == AsyncSampleOp::kFree) return -1;
  async_cv_.wait(l, [&op] { return op.state == AsyncSampleOp::kDone; });
  op.state = AsyncSampleOp::kFree;
  // drop the owned request copies now, not at the next submit — a
  // paused pipeline should not pin a step's id arrays indefinitely
  op.ids = {};
  op.etypes_flat = {};
  op.out_ids = {};
  op.out_w = {};
  op.out_t = {};
  return 0;
}

namespace {

// Prefix offsets of a counts array.
std::vector<int64_t> Offsets(const std::vector<int32_t>& counts) {
  std::vector<int64_t> off(counts.size() + 1, 0);
  for (size_t j = 0; j < counts.size(); ++j) off[j + 1] = off[j] + counts[j];
  return off;
}

}  // namespace

EGResult* RemoteGraph::MergeFullNeighbor(const ShardPlan& plan,
                                         std::vector<EGResult>& sub,
                                         const std::vector<char>& ok,
                                         int n) const {
  auto* res = new EGResult();
  res->u64.resize(1);
  res->f32.resize(1);
  res->i32.resize(2);
  res->i32[1].assign(n, 0);
  std::vector<std::vector<int64_t>> off(num_shards_);
  for (int s = 0; s < num_shards_; ++s) {
    // Validate reply shape before trusting its counts — a malformed shard
    // reply degrades to defaults, like the fixed-size paths' m != want
    // checks.
    if (!ok[s] || sub[s].i32.size() != 2 || sub[s].u64.size() != 1 ||
        sub[s].f32.size() != 1 ||
        sub[s].i32[1].size() != plan.rows[s].size())
      continue;
    auto o = Offsets(sub[s].i32[1]);
    size_t total = static_cast<size_t>(o.back());
    if (sub[s].u64[0].size() != total || sub[s].f32[0].size() != total ||
        sub[s].i32[0].size() != total)
      continue;
    off[s] = std::move(o);
  }
  for (int i = 0; i < n; ++i) {
    int s = plan.shard_of[i];
    if (s < 0 || !ok[s] || off[s].empty()) continue;  // defaults: count 0
    int32_t j = plan.pos_of[i];  // duplicates share their unique segment
    int64_t b = off[s][j], e = off[s][j + 1];
    res->i32[1][i] = static_cast<int32_t>(e - b);
    res->u64[0].insert(res->u64[0].end(), sub[s].u64[0].begin() + b,
                       sub[s].u64[0].begin() + e);
    res->f32[0].insert(res->f32[0].end(), sub[s].f32[0].begin() + b,
                       sub[s].f32[0].begin() + e);
    res->i32[0].insert(res->i32[0].end(), sub[s].i32[0].begin() + b,
                       sub[s].i32[0].begin() + e);
  }
  return res;
}

EGResult* RemoteGraph::MergeSlotted(const ShardPlan& plan,
                                    std::vector<EGResult>& sub,
                                    const std::vector<char>& ok, int n,
                                    int nf, bool u64_vals,
                                    bool byte_vals) const {
  auto* res = new EGResult();
  res->i32.resize(nf);
  if (u64_vals) res->u64.resize(nf);
  if (byte_vals) res->bytes.resize(nf);
  for (int k = 0; k < nf; ++k) {
    res->i32[k].assign(n, 0);
    std::vector<std::vector<int64_t>> off(num_shards_);
    for (int s = 0; s < num_shards_; ++s) {
      if (!ok[s] || static_cast<int>(sub[s].i32.size()) != nf ||
          sub[s].i32[k].size() != plan.rows[s].size())
        continue;
      if (u64_vals && static_cast<int>(sub[s].u64.size()) != nf) continue;
      if (byte_vals && static_cast<int>(sub[s].bytes.size()) != nf) continue;
      auto o = Offsets(sub[s].i32[k]);
      size_t total = static_cast<size_t>(o.back());
      if (u64_vals && sub[s].u64[k].size() != total) continue;
      if (byte_vals && sub[s].bytes[k].size() != total) continue;
      off[s] = std::move(o);
    }
    for (int i = 0; i < n; ++i) {
      int s = plan.shard_of[i];
      if (s < 0 || !ok[s] || off[s].empty()) continue;
      int32_t j = plan.pos_of[i];
      int64_t b = off[s][j], e = off[s][j + 1];
      res->i32[k][i] = static_cast<int32_t>(e - b);
      if (u64_vals)
        res->u64[k].insert(res->u64[k].end(), sub[s].u64[k].begin() + b,
                           sub[s].u64[k].begin() + e);
      if (byte_vals)
        res->bytes[k].append(sub[s].bytes[k], static_cast<size_t>(b),
                             static_cast<size_t>(e - b));
    }
  }
  return res;
}

EGResult* RemoteGraph::GetFullNeighbor(const uint64_t* ids, int n,
                                       const int32_t* etypes, int net,
                                       bool sorted) const {
  ShardPlan plan;
  BuildPlan(ids, n, &plan);
  std::vector<EGResult> sub(num_shards_);
  std::vector<char> ok(num_shards_, 0);
  // Variable-length replies stay one call per shard (chunking them would
  // need segment stitching for little gain: the dedup above already
  // removed the duplicate rows that dominate power-law batches).
  ForShards(plan.rows, "full_neighbor", [&](int s) {
    std::vector<uint64_t> subids(plan.rows[s].size());
    for (size_t j = 0; j < plan.rows[s].size(); ++j)
      subids[j] = ids[plan.rows[s][j]];
    Heat::Global().Record(kHeatClient, kFullNeighbor, subids.data(),
                          static_cast<int64_t>(subids.size()));
    WireWriter req;
    req.U8(kFullNeighbor);
    req.Arr(subids);
    req.Arr(etypes, net);
    req.U8(sorted ? 1 : 0);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    if (!ReadResult(&r, &sub[s])) return false;
    ok[s] = 1;
    return true;
  });
  // Engine layout: u64[0]=ids, f32[0]=weights, i32[0]=types, i32[1]=counts.
  return MergeFullNeighbor(plan, sub, ok, n);
}

void RemoteGraph::GetTopKNeighbor(const uint64_t* ids, int n,
                                  const int32_t* etypes, int net, int k,
                                  uint64_t default_id, uint64_t* out_ids,
                                  float* out_w, int32_t* out_t) const {
  int64_t total = static_cast<int64_t>(n) * k;
  std::fill(out_ids, out_ids + total, default_id);
  std::fill(out_w, out_w + total, 0.f);
  std::fill(out_t, out_t + total, -1);
  if (n <= 0 || k <= 0) return;
  ShardPlan plan;
  BuildPlan(ids, n, &plan);
  // Deterministic per id, so duplicates simply copy the unique reply row.
  std::vector<std::vector<uint64_t>> sid(num_shards_);
  std::vector<std::vector<float>> sw(num_shards_);
  std::vector<std::vector<int32_t>> st(num_shards_);
  std::vector<std::vector<char>> ok(num_shards_);
  for (int s = 0; s < num_shards_; ++s) {
    size_t m = plan.rows[s].size();
    sid[s].assign(m * k, default_id);
    sw[s].assign(m * k, 0.f);
    st[s].assign(m * k, -1);
    ok[s].assign(m, 0);
  }
  RunChunked(plan.rows, "topk_neighbor", [&](int s, int32_t b, int32_t e) {
    std::vector<uint64_t> sub(static_cast<size_t>(e - b));
    for (int32_t j = b; j < e; ++j) sub[j - b] = ids[plan.rows[s][j]];
    Heat::Global().Record(kHeatClient, kTopKNeighbor, sub.data(),
                          static_cast<int64_t>(sub.size()));
    WireWriter req;
    req.U8(kTopKNeighbor);
    req.Arr(sub);
    req.Arr(etypes, net);
    req.I32(k);
    req.U64(default_id);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    int64_t mi, mw, mt;
    const uint64_t* rid = r.Arr<uint64_t>(&mi);
    const float* rw = r.Arr<float>(&mw);
    const int32_t* rt = r.Arr<int32_t>(&mt);
    int64_t want = static_cast<int64_t>(sub.size()) * k;
    if (!r.ok() || mi != want || mw != want || mt != want) return false;
    std::copy(rid, rid + want, sid[s].begin() + static_cast<int64_t>(b) * k);
    std::copy(rw, rw + want, sw[s].begin() + static_cast<int64_t>(b) * k);
    std::copy(rt, rt + want, st[s].begin() + static_cast<int64_t>(b) * k);
    for (int32_t j = b; j < e; ++j) ok[s][j] = 1;
    return true;
  });
  for (int i = 0; i < n; ++i) {
    int s = plan.shard_of[i];
    int32_t pos = plan.pos_of[i];
    if (s < 0 || !ok[s][pos]) continue;
    int64_t src_off = static_cast<int64_t>(pos) * k;
    int64_t dst_off = static_cast<int64_t>(i) * k;
    std::copy(sid[s].begin() + src_off, sid[s].begin() + src_off + k,
              out_ids + dst_off);
    std::copy(sw[s].begin() + src_off, sw[s].begin() + src_off + k,
              out_w + dst_off);
    std::copy(st[s].begin() + src_off, st[s].begin() + src_off + k,
              out_t + dst_off);
  }
}

void RemoteGraph::RandomWalk(const uint64_t* ids, int n,
                             const int32_t* etypes_flat,
                             const int32_t* etype_counts, int walk_len,
                             float p, float q, uint64_t default_id,
                             uint64_t* out) const {
  int64_t stride = walk_len + 1;
  std::vector<uint64_t> cur(ids, ids + n), parent(n, 0);
  for (int i = 0; i < n; ++i) out[static_cast<int64_t>(i) * stride] = ids[i];
  bool plain = p == 1.f && q == 1.f;
  std::vector<uint64_t> next(n);
  std::vector<float> w1(n);
  std::vector<int32_t> t1(n);
  Rng& rng = ThreadRng();
  const int32_t* et = etypes_flat;
  for (int s = 1; s <= walk_len; ++s) {
    int net = etype_counts[s - 1];
    if (plain || s == 1) {
      SampleNeighbor(cur.data(), n, et, net, 1, default_id, next.data(),
                     w1.data(), t1.data());
    } else {
      // node2vec-biased step: client-side sorted-merge of current and parent
      // neighbor lists, d_tx weights w/p (return), w (distance 1), w/q
      // (distance 2) — semantics of reference euler/client/graph.cc:120-151,
      // which likewise issues two GetSortedFullNeighbor scatters per hop.
      // Walks revisit hubs constantly, so both fetches ride the dedup path.
      EGResult* cn = GetFullNeighbor(cur.data(), n, et, net, true);
      EGResult* pn = GetFullNeighbor(parent.data(), n, et, net, true);
      const auto& c_ids = cn->u64[0];
      const auto& c_w = cn->f32[0];
      const auto& c_cnt = cn->i32[1];
      const auto& p_ids = pn->u64[0];
      const auto& p_cnt = pn->i32[1];
      size_t c_off = 0, p_off = 0;
      std::vector<double> cum;
      for (int i = 0; i < n; ++i) {
        size_t cc = static_cast<size_t>(c_cnt[i]);
        size_t pc = static_cast<size_t>(p_cnt[i]);
        if (cc == 0) {
          next[i] = default_id;
        } else {
          cum.resize(cc);
          double total = 0.0;
          const uint64_t* pb = p_ids.data() + p_off;
          for (size_t j = 0; j < cc; ++j) {
            uint64_t x = c_ids[c_off + j];
            float wx = c_w[c_off + j];
            // parent-adjacency wins even for x == parent (parent with a
            // self-loop is d_tx=1): the reference merge's equality
            // branch runs before its candidate<parent check
            // (euler/client/graph.cc:126-140)
            double scale;
            if (std::binary_search(pb, pb + pc, x))
              scale = 1.0;
            else if (x == parent[i])
              scale = 1.0 / p;
            else
              scale = 1.0 / q;
            total += wx * scale;
            cum[j] = total;
          }
          double r = rng.NextDouble() * total;
          size_t j = std::lower_bound(cum.begin(), cum.end(), r) - cum.begin();
          next[i] = c_ids[c_off + std::min(j, cc - 1)];
        }
        c_off += cc;
        p_off += pc;
      }
      delete cn;
      delete pn;
    }
    for (int i = 0; i < n; ++i) {
      out[static_cast<int64_t>(i) * stride + s] = next[i];
      parent[i] = cur[i];
      cur[i] = next[i];
    }
    et += net;
  }
}

void RemoteGraph::GetDenseFeature(const uint64_t* ids, int n,
                                  const int32_t* fids, const int32_t* dims,
                                  int nf, float* out) const {
  int64_t row_dim = 0;
  for (int k = 0; k < nf; ++k) row_dim += dims[k];
  std::fill(out, out + static_cast<int64_t>(n) * row_dim, 0.f);
  if (n <= 0 || row_dim <= 0) return;
  ShardPlan plan;
  BuildPlan(ids, n, &plan);
  Counters& ctr = Counters::Global();
  const bool use_cache = fcache_.enabled();
  const uint64_t spec =
      use_cache ? FeatureCache::SpecHash(fids, dims, nf) : 0;
  // one generation + epoch-pin capture for the whole gather: every probe
  // and fill below reads a single snapshot (eg_epoch.h)
  const uint64_t gen = cache_gen_.load(std::memory_order_acquire);
  std::vector<uint64_t> pin;
  if (shard_epoch_) {
    pin.resize(static_cast<size_t>(num_shards_), 0);
    for (int s = 0; s < num_shards_; ++s)
      pin[static_cast<size_t>(s)] =
          shard_epoch_[s].load(std::memory_order_relaxed);
  }
  // Staging over unique entries; cache hits fill their rows up front and
  // drop out of the fetch lists entirely (zero wire bytes).
  std::vector<std::vector<float>> sval(num_shards_);
  std::vector<std::vector<char>> ok(num_shards_);
  std::vector<std::vector<int32_t>> fetch(num_shards_);
  // heat feed (eg_heat.h): every unique id, post-coalesce but PRE-cache
  // — cache hits are accesses too, and the frequency the cache-efficacy
  // classes bucket by must count them. The gather form walks the plan's
  // row indices in place (no staging copy), and hands back each id's
  // frequency class from the same sketch walk, so the hit/miss class
  // accounting below costs two array reads per id instead of a second
  // sketch probe.
  Heat& heat = Heat::Global();
  const bool heat_on = heat.enabled();
  std::vector<uint8_t> cls;
  uint32_t cls_hit[kHeatClasses] = {0}, cls_miss[kHeatClasses] = {0};
  uint64_t hits = 0, misses = 0;
  for (int s = 0; s < num_shards_; ++s) {
    size_t m = plan.rows[s].size();
    sval[s].assign(m * static_cast<size_t>(row_dim), 0.f);
    ok[s].assign(m, 0);
    if (heat_on && m) {
      cls.resize(m);
      heat.RecordRows(kHeatClient, kDenseFeature, ids,
                      plan.rows[s].data(), static_cast<int64_t>(m), -1,
                      cls.data());
    }
    for (size_t j = 0; j < m; ++j) {
      uint64_t id = ids[plan.rows[s][j]];
      if (use_cache &&
          fcache_.Get(spec, id, sval[s].data() + j * row_dim,
                      static_cast<size_t>(row_dim), gen)) {
        ok[s][j] = 1;
        ++hits;
        if (heat_on) ++cls_hit[cls[j]];
      } else {
        fetch[s].push_back(static_cast<int32_t>(j));
        if (use_cache) {
          ++misses;
          if (heat_on) ++cls_miss[cls[j]];
        }
      }
    }
  }
  if (heat_on && use_cache) heat.AddCacheClasses(cls_hit, cls_miss);
  if (hits) ctr.Add(kCtrCacheHit, hits);
  if (misses) ctr.Add(kCtrCacheMiss, misses);
  RunChunked(fetch, "dense_feature", [&](int s, int32_t b, int32_t e) {
    int32_t m = e - b;
    std::vector<uint64_t> sub(static_cast<size_t>(m));
    for (int32_t x = 0; x < m; ++x)
      sub[x] = ids[plan.rows[s][fetch[s][b + x]]];
    WireWriter req;
    req.U8(kDenseFeature);
    req.Arr(sub);
    req.Arr(fids, nf);
    req.Arr(dims, nf);
    std::string reply;
    if (!Call(s, req.buf(), &reply,
              pin.empty() ? 0 : pin[static_cast<size_t>(s)]))
      return false;
    Heat::Global().AddShardBytes(s, req.buf().size(), reply.size());
    WireReader r(reply);
    r.U8();
    int64_t mm;
    const float* vals = r.Arr<float>(&mm);
    if (!r.ok() || mm != static_cast<int64_t>(m) * row_dim) return false;
    for (int32_t x = 0; x < m; ++x) {
      int32_t j = fetch[s][b + x];
      std::copy(vals + static_cast<int64_t>(x) * row_dim,
                vals + static_cast<int64_t>(x + 1) * row_dim,
                sval[s].begin() + static_cast<int64_t>(j) * row_dim);
      ok[s][j] = 1;
      if (use_cache)
        fcache_.Put(spec, sub[x], vals + static_cast<int64_t>(x) * row_dim,
                    static_cast<size_t>(row_dim), gen);
    }
    return true;
  });
  // fan-out attribution: ids_on_wire measured as the post-cache fetch
  // list sizes, shards_touched as the shards a fetch actually went to
  if (heat_on) {
    uint64_t on_wire = 0;
    int touched = 0;
    for (int s = 0; s < num_shards_; ++s)
      if (!fetch[s].empty()) {
        ++touched;
        on_wire += fetch[s].size();
      }
    heat.RecordFanout(kDenseFeature, static_cast<uint64_t>(n),
                      static_cast<uint64_t>(plan.coalesced), hits, on_wire,
                      touched);
  }
  for (int i = 0; i < n; ++i) {
    int s = plan.shard_of[i];
    if (s < 0) continue;
    int32_t pos = plan.pos_of[i];
    if (!ok[s][pos]) continue;
    std::copy(sval[s].begin() + static_cast<int64_t>(pos) * row_dim,
              sval[s].begin() + static_cast<int64_t>(pos + 1) * row_dim,
              out + static_cast<int64_t>(i) * row_dim);
  }
}

void RemoteGraph::GetEdgeDenseFeature(const uint64_t* src,
                                      const uint64_t* dst,
                                      const int32_t* types, int n,
                                      const int32_t* fids,
                                      const int32_t* dims, int nf,
                                      float* out) const {
  int64_t row_dim = 0;
  for (int k = 0; k < nf; ++k) row_dim += dims[k];
  std::fill(out, out + static_cast<int64_t>(n) * row_dim, 0.f);
  // Edges live on the shard of their src node (the converter emits edge
  // records inside the src node's block — see convert.py / reference
  // euler/tools/json2dat.py:139). Edge identity is the (src, dst, type)
  // triple, so the node-id dedup/cache does not apply here.
  std::vector<std::vector<int32_t>> rows;
  GroupByShard(src, n, &rows);
  ForShards(rows, "edge_dense_feature", [&](int s) {
    size_t m = rows[s].size();
    std::vector<uint64_t> ssrc(m), sdst(m);
    std::vector<int32_t> st(m);
    for (size_t j = 0; j < m; ++j) {
      ssrc[j] = src[rows[s][j]];
      sdst[j] = dst[rows[s][j]];
      st[j] = types[rows[s][j]];
    }
    // edge ops feed their SRC ids — the routing key hash sharding cuts on
    Heat::Global().Record(kHeatClient, kEdgeDenseFeature, ssrc.data(),
                          static_cast<int64_t>(ssrc.size()));
    WireWriter req;
    req.U8(kEdgeDenseFeature);
    req.Arr(ssrc);
    req.Arr(sdst);
    req.Arr(st);
    req.Arr(fids, nf);
    req.Arr(dims, nf);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    int64_t mm;
    const float* vals = r.Arr<float>(&mm);
    if (!r.ok() || mm != static_cast<int64_t>(m) * row_dim) return false;
    for (size_t j = 0; j < m; ++j)
      std::copy(vals + j * row_dim, vals + (j + 1) * row_dim,
                out + static_cast<int64_t>(rows[s][j]) * row_dim);
    return true;
  });
}

EGResult* RemoteGraph::GetSparseFeature(const uint64_t* ids, int n,
                                        const int32_t* fids, int nf) const {
  ShardPlan plan;
  BuildPlan(ids, n, &plan);
  std::vector<EGResult> sub(num_shards_);
  std::vector<char> ok(num_shards_, 0);
  ForShards(plan.rows, "sparse_feature", [&](int s) {
    std::vector<uint64_t> subids(plan.rows[s].size());
    for (size_t j = 0; j < plan.rows[s].size(); ++j)
      subids[j] = ids[plan.rows[s][j]];
    Heat::Global().Record(kHeatClient, kSparseFeature, subids.data(),
                          static_cast<int64_t>(subids.size()));
    WireWriter req;
    req.U8(kSparseFeature);
    req.Arr(subids);
    req.Arr(fids, nf);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    if (!ReadResult(&r, &sub[s])) return false;
    ok[s] = 1;
    return true;
  });
  // Layout: u64[k]=values of slot k, i32[k]=per-row counts (nf slots each).
  return MergeSlotted(plan, sub, ok, n, nf, /*u64=*/true, /*bytes=*/false);
}

EGResult* RemoteGraph::GetEdgeSparseFeature(const uint64_t* src,
                                            const uint64_t* dst,
                                            const int32_t* types, int n,
                                            const int32_t* fids,
                                            int nf) const {
  ShardPlan plan;
  BuildEdgePlan(src, n, &plan);
  std::vector<EGResult> sub(num_shards_);
  std::vector<char> ok(num_shards_, 0);
  ForShards(plan.rows, "edge_sparse_feature", [&](int s) {
    size_t m = plan.rows[s].size();
    std::vector<uint64_t> ssrc(m), sdst(m);
    std::vector<int32_t> st(m);
    for (size_t j = 0; j < m; ++j) {
      ssrc[j] = src[plan.rows[s][j]];
      sdst[j] = dst[plan.rows[s][j]];
      st[j] = types[plan.rows[s][j]];
    }
    Heat::Global().Record(kHeatClient, kEdgeSparseFeature, ssrc.data(),
                          static_cast<int64_t>(ssrc.size()));
    WireWriter req;
    req.U8(kEdgeSparseFeature);
    req.Arr(ssrc);
    req.Arr(sdst);
    req.Arr(st);
    req.Arr(fids, nf);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    if (!ReadResult(&r, &sub[s])) return false;
    ok[s] = 1;
    return true;
  });
  return MergeSlotted(plan, sub, ok, n, nf, /*u64=*/true, /*bytes=*/false);
}

EGResult* RemoteGraph::GetBinaryFeature(const uint64_t* ids, int n,
                                        const int32_t* fids, int nf) const {
  ShardPlan plan;
  BuildPlan(ids, n, &plan);
  std::vector<EGResult> sub(num_shards_);
  std::vector<char> ok(num_shards_, 0);
  ForShards(plan.rows, "binary_feature", [&](int s) {
    std::vector<uint64_t> subids(plan.rows[s].size());
    for (size_t j = 0; j < plan.rows[s].size(); ++j)
      subids[j] = ids[plan.rows[s][j]];
    Heat::Global().Record(kHeatClient, kBinaryFeature, subids.data(),
                          static_cast<int64_t>(subids.size()));
    WireWriter req;
    req.U8(kBinaryFeature);
    req.Arr(subids);
    req.Arr(fids, nf);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    if (!ReadResult(&r, &sub[s])) return false;
    ok[s] = 1;
    return true;
  });
  return MergeSlotted(plan, sub, ok, n, nf, /*u64=*/false, /*bytes=*/true);
}

EGResult* RemoteGraph::GetEdgeBinaryFeature(const uint64_t* src,
                                            const uint64_t* dst,
                                            const int32_t* types, int n,
                                            const int32_t* fids,
                                            int nf) const {
  ShardPlan plan;
  BuildEdgePlan(src, n, &plan);
  std::vector<EGResult> sub(num_shards_);
  std::vector<char> ok(num_shards_, 0);
  ForShards(plan.rows, "edge_binary_feature", [&](int s) {
    size_t m = plan.rows[s].size();
    std::vector<uint64_t> ssrc(m), sdst(m);
    std::vector<int32_t> st(m);
    for (size_t j = 0; j < m; ++j) {
      ssrc[j] = src[plan.rows[s][j]];
      sdst[j] = dst[plan.rows[s][j]];
      st[j] = types[plan.rows[s][j]];
    }
    Heat::Global().Record(kHeatClient, kEdgeBinaryFeature, ssrc.data(),
                          static_cast<int64_t>(ssrc.size()));
    WireWriter req;
    req.U8(kEdgeBinaryFeature);
    req.Arr(ssrc);
    req.Arr(sdst);
    req.Arr(st);
    req.Arr(fids, nf);
    std::string reply;
    if (!Call(s, req.buf(), &reply)) return false;
    WireReader r(reply);
    r.U8();
    if (!ReadResult(&r, &sub[s])) return false;
    ok[s] = 1;
    return true;
  });
  return MergeSlotted(plan, sub, ok, n, nf, /*u64=*/false, /*bytes=*/true);
}

}  // namespace eg
