#include "eg_heat.h"

#include <algorithm>

namespace eg {

namespace {

thread_local int g_heat_conn = -1;

// splitmix64 finalizer — the same mix eg_telemetry/eg_cache use; one
// finalized hash per id drives both the sketch cells and the top-K
// index probe (see CmsCell below).
uint64_t Mix(uint64_t z) {
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Blocked sketch addressing from ONE splitmix64 hash per id (the same
// hash the top-K index probes): bits 0..9 pick the 64-byte block,
// disjoint higher windows pick two cells inside it. One cache line
// touched per id; the two in-block cells may coincide (1-in-8), which
// just degrades that id to a depth-1 estimate.
inline uint64_t CmsCell(uint64_t h, int d) {
  uint64_t block = h & (kHeatCmsBlocks - 1);
  uint64_t sub = (h >> (20 + d * 16)) & (kHeatCmsBlockCells - 1);
  return block * kHeatCmsBlockCells + sub;
}

void AppendU64(std::string* out, uint64_t v) {
  char buf[24];
  int n = 0;
  do {
    buf[n++] = static_cast<char>('0' + v % 10);
    v /= 10;
  } while (v);
  while (n) out->push_back(buf[--n]);
}

void AppendI64(std::string* out, int64_t v) {
  if (v < 0) {
    out->push_back('-');
    AppendU64(out, static_cast<uint64_t>(-v));
  } else {
    AppendU64(out, static_cast<uint64_t>(v));
  }
}

void AppendKey(std::string* out, const char* k) {
  out->push_back('"');
  out->append(k);
  out->append("\":");
}

}  // namespace

void HeatSetConn(int conn) { g_heat_conn = conn; }
int HeatConn() { return g_heat_conn; }

Heat& Heat::Global() {
  static Heat h;
  return h;
}

Heat::Heat() {
  for (auto& t : top_)
    for (auto& c : t.index) c = -1;
  for (auto& c : conn_fd_) c.store(-1, std::memory_order_relaxed);
}

void Heat::SetTopK(int k) {
  if (k < 1) k = 1;
  if (k > kHeatMaxTopK) k = kHeatMaxTopK;
  cap_.store(k, std::memory_order_relaxed);
  for (auto& t : top_) {
    std::lock_guard<std::mutex> l(t.mu);
    t.size = 0;
    t.tombstones = 0;
    t.min_count = 0;
    t.scan_pos = 0;
    for (auto& c : t.index) c = -1;
  }
}

int Heat::topk_capacity() const {
  return cap_.load(std::memory_order_relaxed);
}

int Heat::FindSlot(const TopTable& t, uint64_t id, uint64_t h)
    EG_REQUIRES(mu) {
  for (int probe = 0; probe < kHeatIndexSlots; ++probe) {
    int i = static_cast<int>((h + probe) & (kHeatIndexSlots - 1));
    int32_t v = t.index[i];
    if (v == -1) return -1;
    if (v >= 0 && t.ids[v] == id) return v;
  }
  return -1;  // unreachable: the table is never full (load <= 25%)
}

void Heat::InsertSlot(TopTable* t, uint64_t h, int slot) EG_REQUIRES(mu) {
  for (int probe = 0; probe < kHeatIndexSlots; ++probe) {
    int i = static_cast<int>((h + probe) & (kHeatIndexSlots - 1));
    int32_t v = t->index[i];
    if (v == -1 || v == -2) {
      if (v == -2) --t->tombstones;
      t->index[i] = slot;
      return;
    }
  }
}

void Heat::EraseSlot(TopTable* t, uint64_t id) EG_REQUIRES(mu) {
  uint64_t h = Mix(id);
  for (int probe = 0; probe < kHeatIndexSlots; ++probe) {
    int i = static_cast<int>((h + probe) & (kHeatIndexSlots - 1));
    int32_t v = t->index[i];
    if (v == -1) return;
    if (v >= 0 && t->ids[v] == id) {
      t->index[i] = -2;
      if (++t->tombstones > kHeatIndexSlots / 4) RebuildIndex(t);
      return;
    }
  }
}

void Heat::RebuildIndex(TopTable* t) EG_REQUIRES(mu) {
  for (auto& c : t->index) c = -1;
  t->tombstones = 0;
  for (int s = 0; s < t->size; ++s) InsertSlot(t, Mix(t->ids[s]), s);
}

void Heat::UpdateTop(TopTable* t, uint64_t id, uint64_t h, int cap)
    EG_REQUIRES(mu) {
  int slot = FindSlot(*t, id, h);
  if (slot >= 0) {
    ++t->counts[slot];
    return;
  }
  if (t->size < cap) {
    slot = t->size++;
    t->ids[slot] = id;
    t->counts[slot] = 1;
    t->errs[slot] = 0;
    InsertSlot(t, h, slot);
    if (t->size == cap) {
      // table just filled: every slot was inserted at count >= 1 and
      // only grew, so the smallest count is the true min level
      int m = 0;
      for (int s = 1; s < cap; ++s)
        if (t->counts[s] < t->counts[m]) m = s;
      t->min_count = t->counts[m];
      t->scan_pos = m;
    }
    return;
  }
  // space-saving replacement: evict A minimum slot (any slot at the
  // cached min level is a true minimum, see TopTable::min_count); the
  // newcomer inherits its count as the overestimate err
  int m = -1;
  for (int k = 0; k < cap; ++k) {
    int i = t->scan_pos + k;
    if (i >= cap) i -= cap;
    if (t->counts[i] == t->min_count) {
      m = i;
      t->scan_pos = i;
      break;
    }
  }
  if (m < 0) {
    // level exhausted (every min slot replaced or incremented away):
    // recompute — counts only grow, so this raises min_count
    m = 0;
    for (int s = 1; s < cap; ++s)
      if (t->counts[s] < t->counts[m]) m = s;
    t->min_count = t->counts[m];
    t->scan_pos = m;
  }
  EraseSlot(t, t->ids[m]);
  t->ids[m] = id;
  t->errs[m] = t->counts[m];
  t->counts[m] += 1;
  InsertSlot(t, h, m);
}

void Heat::Record(int side, int op, const uint64_t* keys, int64_t n,
                  int conn) {
  RecordRows(side, op, keys, nullptr, n, conn);
}

void Heat::RecordRows(int side, int op, const uint64_t* base,
                      const int32_t* rows, int64_t n, int conn,
                      uint8_t* out_classes) {
  if (!enabled() || n <= 0) return;
  if (side < 0 || side >= kHeatSideCount) return;
  if (op < 0 || op >= kHistOpSlots) op = 0;
  total_[side].fetch_add(static_cast<uint64_t>(n),
                         std::memory_order_relaxed);
  ids_by_op_[side][op].fetch_add(static_cast<uint64_t>(n),
                                 std::memory_order_relaxed);
  if (side == kHeatServer && conn >= 0) {
    // fd-labeled fixed pool: claim a slot by CAS on first sight; a
    // full pool counts into the overflow bucket instead of allocating
    bool placed = false;
    for (int c = 0; c < kHeatMaxConns; ++c) {
      int cur = conn_fd_[c].load(std::memory_order_relaxed);
      if (cur == conn) {
        conn_ids_[c].fetch_add(static_cast<uint64_t>(n),
                               std::memory_order_relaxed);
        placed = true;
        break;
      }
      if (cur == -1) {
        int expect = -1;
        if (conn_fd_[c].compare_exchange_strong(
                expect, conn, std::memory_order_relaxed)) {
          conn_ids_[c].fetch_add(static_cast<uint64_t>(n),
                                 std::memory_order_relaxed);
          placed = true;
          break;
        }
        if (expect == conn) {
          conn_ids_[c].fetch_add(static_cast<uint64_t>(n),
                                 std::memory_order_relaxed);
          placed = true;
          break;
        }
      }
    }
    if (!placed)
      conn_overflow_.fetch_add(static_cast<uint64_t>(n),
                               std::memory_order_relaxed);
  }
  // one fused pass: sketch updates + top-K update share a single Mix
  // per id; the top-K mutex is held once per BATCH. Because THIS mutex
  // serializes every writer of this side's sketch (feeds are the only
  // writers and all come through here), the cells increment with plain
  // relaxed load+store pairs instead of locked fetch_adds — an
  // uncontended `lock xadd` still costs tens of cycles per id, and two
  // per id was the measured majority of the feed's ns/id. Concurrent
  // READERS (Estimate, the scrape JSON) see relaxed atomic loads: never
  // torn, at worst one increment stale. The pre-increment row counts
  // give the frequency class (estimate = min + 1) for free.
  TopTable& t = top_[side];
  int cap = cap_.load(std::memory_order_relaxed);
  std::lock_guard<std::mutex> l(t.mu);
  // chunked two-phase walk: hash a small run of ids first, prefetching
  // each id's sketch line (one 64-byte block) while the hashes of its
  // neighbors compute — the blocked layout makes the whole sketch
  // access one prefetchable line per id
  constexpr int kChunk = 32;
  uint64_t hid[kChunk], hh[kChunk];
  for (int64_t i0 = 0; i0 < n; i0 += kChunk) {
    int m = static_cast<int>(std::min<int64_t>(kChunk, n - i0));
    for (int j = 0; j < m; ++j) {
      uint64_t id = rows ? base[rows[i0 + j]] : base[i0 + j];
      uint64_t h = Mix(id);
      hid[j] = id;
      hh[j] = h;
      __builtin_prefetch(&cms_[side][CmsCell(h, 0)], 1, 1);
    }
    for (int j = 0; j < m; ++j) {
      uint64_t h = hh[j];
      uint64_t c0 = CmsCell(h, 0), c1 = CmsCell(h, 1);
      auto bump = [&](uint64_t c) {
        auto& cell = cms_[side][c];
        uint64_t prev = cell.load(std::memory_order_relaxed);
        cell.store(prev + 1, std::memory_order_relaxed);
        return prev;
      };
      uint64_t prev_min = bump(c0);
      // coinciding in-block cells (1-in-8): count once, depth-1 est
      if (c1 != c0) prev_min = std::min(prev_min, bump(c1));
      if (out_classes) out_classes[i0 + j] = HeatClassOf(prev_min + 1);
      UpdateTop(&t, hid[j], h, cap);
    }
  }
}

uint64_t Heat::Estimate(int side, uint64_t id) const {
  if (side < 0 || side >= kHeatSideCount) return 0;
  uint64_t h = Mix(id);
  uint64_t est = UINT64_MAX;
  for (int d = 0; d < kHeatCmsDepth; ++d)
    est = std::min(est, cms_[side][CmsCell(h, d)].load(
                            std::memory_order_relaxed));
  return est == UINT64_MAX ? 0 : est;
}

void Heat::RecordFanout(int op, uint64_t ids_requested,
                        uint64_t ids_deduped, uint64_t cache_hits,
                        uint64_t ids_on_wire, int shards_touched) {
  if (!enabled()) return;
  if (op < 0 || op >= kHistOpSlots) op = 0;
  fan_calls_[op].fetch_add(1, std::memory_order_relaxed);
  fan_requested_[op].fetch_add(ids_requested, std::memory_order_relaxed);
  fan_deduped_[op].fetch_add(ids_deduped, std::memory_order_relaxed);
  fan_cache_hits_[op].fetch_add(cache_hits, std::memory_order_relaxed);
  fan_on_wire_[op].fetch_add(ids_on_wire, std::memory_order_relaxed);
  uint64_t st = shards_touched < 0 ? 0
                                   : static_cast<uint64_t>(shards_touched);
  SpreadCell& c = spread_[op];
  c.buckets[HistBucketOf(st)].fetch_add(1, std::memory_order_relaxed);
  c.total.fetch_add(st, std::memory_order_relaxed);
}

void Heat::AddShardBytes(int shard, uint64_t req_bytes,
                         uint64_t reply_bytes) {
  if (!enabled()) return;
  if (shard < 0) return;
  if (shard >= kHeatMaxShards) shard = kHeatMaxShards - 1;
  shard_req_bytes_[shard].fetch_add(req_bytes, std::memory_order_relaxed);
  shard_reply_bytes_[shard].fetch_add(reply_bytes,
                                      std::memory_order_relaxed);
}

void Heat::RecordCacheEvent(int event, uint64_t id) {
  if (!enabled()) return;
  if (event < 0 || event >= kHeatCacheEventCount) return;
  int cls = HeatClassOf(Estimate(kHeatClient, id));
  cache_class_[event][cls].fetch_add(1, std::memory_order_relaxed);
}

void Heat::AddCacheClasses(const uint32_t* hits, const uint32_t* misses) {
  if (!enabled()) return;
  for (int cls = 0; cls < kHeatClasses; ++cls) {
    if (hits[cls])
      cache_class_[kHeatCacheHit][cls].fetch_add(
          hits[cls], std::memory_order_relaxed);
    if (misses[cls])
      cache_class_[kHeatCacheMiss][cls].fetch_add(
          misses[cls], std::memory_order_relaxed);
  }
}

std::vector<Heat::TopEntry> Heat::TopK(int side) const {
  std::vector<TopEntry> out;
  if (side < 0 || side >= kHeatSideCount) return out;
  const TopTable& t = top_[side];
  {
    std::lock_guard<std::mutex> l(t.mu);
    out.reserve(t.size);
    for (int s = 0; s < t.size; ++s)
      out.push_back({t.ids[s], t.counts[s], t.errs[s]});
  }
  std::sort(out.begin(), out.end(), [](const TopEntry& a,
                                       const TopEntry& b) {
    return a.count != b.count ? a.count > b.count : a.id < b.id;
  });
  return out;
}

void Heat::Reset() {
  // hold both top-K mutexes while zeroing the sketches: the tables'
  // mutexes are what make the feed's load+store cell increments safe,
  // so the reset must exclude in-flight feeds the same way
  for (int side = 0; side < kHeatSideCount; ++side) {
    std::lock_guard<std::mutex> l(top_[side].mu);
    for (auto& c : cms_[side]) c.store(0, std::memory_order_relaxed);
  }
  for (auto& t : total_) t.store(0, std::memory_order_relaxed);
  for (auto& side : ids_by_op_)
    for (auto& c : side) c.store(0, std::memory_order_relaxed);
  for (int op = 0; op < kHistOpSlots; ++op) {
    fan_calls_[op].store(0, std::memory_order_relaxed);
    fan_requested_[op].store(0, std::memory_order_relaxed);
    fan_deduped_[op].store(0, std::memory_order_relaxed);
    fan_cache_hits_[op].store(0, std::memory_order_relaxed);
    fan_on_wire_[op].store(0, std::memory_order_relaxed);
    for (auto& b : spread_[op].buckets) b.store(0, std::memory_order_relaxed);
    spread_[op].total.store(0, std::memory_order_relaxed);
  }
  for (int s = 0; s < kHeatMaxShards; ++s) {
    shard_req_bytes_[s].store(0, std::memory_order_relaxed);
    shard_reply_bytes_[s].store(0, std::memory_order_relaxed);
  }
  for (int c = 0; c < kHeatMaxConns; ++c) {
    conn_fd_[c].store(-1, std::memory_order_relaxed);
    conn_ids_[c].store(0, std::memory_order_relaxed);
  }
  conn_overflow_.store(0, std::memory_order_relaxed);
  for (auto& ev : cache_class_)
    for (auto& c : ev) c.store(0, std::memory_order_relaxed);
  for (auto& t : top_) {
    std::lock_guard<std::mutex> l(t.mu);
    t.size = 0;
    t.tombstones = 0;
    t.min_count = 0;
    t.scan_pos = 0;
    for (auto& c : t.index) c = -1;
  }
}

void Heat::SpreadJsonInto(std::string* out, bool* first) const {
  for (int op = 1; op < kHistOpSlots; ++op) {
    const SpreadCell& c = spread_[op];
    uint64_t count = 0;
    uint64_t bvals[kHistBuckets];
    for (int b = 0; b < kHistBuckets; ++b) {
      bvals[b] = c.buckets[b].load(std::memory_order_relaxed);
      count += bvals[b];
    }
    if (count == 0) continue;  // only ops with fan-out records emit
    if (!*first) out->push_back(',');
    *first = false;
    out->append("\"heat_spread:");
    out->append(kWireOpNames[op]);
    out->append("\":{\"b\":[");
    for (int b = 0; b < kHistBuckets; ++b) {
      if (b) out->push_back(',');
      AppendU64(out, bvals[b]);
    }
    out->append("],\"count\":");
    AppendU64(out, count);
    out->append(",\"sum_us\":");
    AppendU64(out, c.total.load(std::memory_order_relaxed));
    out->push_back('}');
  }
}

void Heat::JsonInto(std::string* out) const {
  out->append(",\"heat\":");
  out->append(Json(-1));
}

std::string Heat::Json(int shard) const {
  std::string o;
  o.reserve(4096);
  o.push_back('{');
  AppendKey(&o, "shard");
  AppendI64(&o, shard);
  o.push_back(',');
  AppendKey(&o, "enabled");
  AppendI64(&o, flag() ? 1 : 0);
  o.push_back(',');
  AppendKey(&o, "topk_capacity");
  AppendI64(&o, topk_capacity());

  // sketch geometry + stream lengths (N in the eps bound per side)
  o.push_back(',');
  AppendKey(&o, "sketch");
  o.append("{\"depth\":");
  AppendI64(&o, kHeatCmsDepth);
  o.append(",\"width\":");
  AppendI64(&o, kHeatCmsWidth);
  o.append(",\"total\":{");
  for (int side = 0; side < kHeatSideCount; ++side) {
    if (side) o.push_back(',');
    AppendKey(&o, kHeatSideNames[side]);
    AppendU64(&o, Total(side));
  }
  o.append("}}");

  // top-K tables, hottest first; ids as decimal STRINGS (u64-safe,
  // same convention as trace ids)
  o.push_back(',');
  AppendKey(&o, "topk");
  o.push_back('{');
  for (int side = 0; side < kHeatSideCount; ++side) {
    if (side) o.push_back(',');
    AppendKey(&o, kHeatSideNames[side]);
    o.push_back('[');
    std::vector<TopEntry> top = TopK(side);
    for (size_t i = 0; i < top.size(); ++i) {
      if (i) o.push_back(',');
      o.append("{\"id\":\"");
      AppendU64(&o, top[i].id);
      o.append("\",\"count\":");
      AppendU64(&o, top[i].count);
      o.append(",\"err\":");
      AppendU64(&o, top[i].err);
      o.push_back('}');
    }
    o.push_back(']');
  }
  o.push_back('}');

  // ids fed per (side, op) — nonzero only
  o.push_back(',');
  AppendKey(&o, "ids");
  o.push_back('{');
  bool first = true;
  for (int side = 0; side < kHeatSideCount; ++side)
    for (int op = 0; op < kHistOpSlots; ++op) {
      uint64_t v = ids_by_op_[side][op].load(std::memory_order_relaxed);
      if (v == 0) continue;
      if (!first) o.push_back(',');
      first = false;
      o.push_back('"');
      o.append(kHeatSideNames[side]);
      o.push_back(':');
      o.append(kWireOpNames[op]);
      o.append("\":");
      AppendU64(&o, v);
    }
  o.push_back('}');

  // client fan-out attribution per op — nonzero only
  o.push_back(',');
  AppendKey(&o, "fanout");
  o.push_back('{');
  first = true;
  for (int op = 0; op < kHistOpSlots; ++op) {
    uint64_t calls = fan_calls_[op].load(std::memory_order_relaxed);
    if (calls == 0) continue;
    if (!first) o.push_back(',');
    first = false;
    o.push_back('"');
    o.append(kWireOpNames[op]);
    o.append("\":{\"calls\":");
    AppendU64(&o, calls);
    o.append(",\"ids_requested\":");
    AppendU64(&o, fan_requested_[op].load(std::memory_order_relaxed));
    o.append(",\"ids_deduped\":");
    AppendU64(&o, fan_deduped_[op].load(std::memory_order_relaxed));
    o.append(",\"cache_hits\":");
    AppendU64(&o, fan_cache_hits_[op].load(std::memory_order_relaxed));
    o.append(",\"ids_on_wire\":");
    AppendU64(&o, fan_on_wire_[op].load(std::memory_order_relaxed));
    o.append(",\"shards_touched\":");
    AppendU64(&o, spread_[op].total.load(std::memory_order_relaxed));
    o.push_back('}');
  }
  o.push_back('}');

  // per-shard wire bytes — nonzero only
  o.push_back(',');
  AppendKey(&o, "shard_bytes");
  o.push_back('[');
  first = true;
  for (int s = 0; s < kHeatMaxShards; ++s) {
    uint64_t req = shard_req_bytes_[s].load(std::memory_order_relaxed);
    uint64_t rep = shard_reply_bytes_[s].load(std::memory_order_relaxed);
    if (req == 0 && rep == 0) continue;
    if (!first) o.push_back(',');
    first = false;
    o.append("{\"shard\":");
    AppendI64(&o, s);
    o.append(",\"req_bytes\":");
    AppendU64(&o, req);
    o.append(",\"reply_bytes\":");
    AppendU64(&o, rep);
    o.push_back('}');
  }
  o.push_back(']');

  // server-side requesting-conn ledger — nonzero only
  o.push_back(',');
  AppendKey(&o, "conns");
  o.push_back('[');
  first = true;
  for (int c = 0; c < kHeatMaxConns; ++c) {
    int fd = conn_fd_[c].load(std::memory_order_relaxed);
    uint64_t n = conn_ids_[c].load(std::memory_order_relaxed);
    if (fd < 0 || n == 0) continue;
    if (!first) o.push_back(',');
    first = false;
    o.append("{\"conn\":");
    AppendI64(&o, fd);
    o.append(",\"ids\":");
    AppendU64(&o, n);
    o.push_back('}');
  }
  o.push_back(']');
  o.push_back(',');
  AppendKey(&o, "conn_overflow");
  AppendU64(&o, conn_overflow_.load(std::memory_order_relaxed));

  // cache-efficacy classes: event -> per-class counts (class c covers
  // sketch estimates in [2^(c-1), 2^c); class 0 = never estimated)
  o.push_back(',');
  AppendKey(&o, "cache_class");
  o.push_back('{');
  for (int ev = 0; ev < kHeatCacheEventCount; ++ev) {
    if (ev) o.push_back(',');
    AppendKey(&o, kHeatCacheEventNames[ev]);
    o.push_back('[');
    for (int cls = 0; cls < kHeatClasses; ++cls) {
      if (cls) o.push_back(',');
      AppendU64(&o, cache_class_[ev][cls].load(std::memory_order_relaxed));
    }
    o.push_back(']');
  }
  o.push_back('}');

  o.push_back('}');
  return o;
}

}  // namespace eg
