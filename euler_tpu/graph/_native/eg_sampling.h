// Weighted sampling tables.
//
// Two structures, matching the reference's two strategies
// (reference euler/common/compact_weighted_collection.h — prefix-sum + binary
// search, and euler/common/fast_weighted_collection.h + alias_method.cc —
// Walker alias, O(1) per draw). We use the alias table for the big global
// per-type node/edge samplers and inline prefix-sum binary search over the
// adjacency CSR for neighbor draws (no per-node table objects).
#ifndef EG_SAMPLING_H_
#define EG_SAMPLING_H_

#include <cstdint>
#include <vector>

#include "eg_common.h"

namespace eg {

// Walker alias table: O(n) build, O(1) draw.
class AliasTable {
 public:
  void Build(const float* weights, size_t n);
  void Build(const std::vector<float>& w) { Build(w.data(), w.size()); }

  inline size_t Draw(Rng& rng) const {
    if (prob_.empty()) return 0;
    size_t i = static_cast<size_t>(rng.NextLess(prob_.size()));
    return rng.NextDouble() < prob_[i] ? i : alias_[i];
  }

  size_t size() const { return prob_.size(); }
  double total_weight() const { return total_; }

 private:
  std::vector<double> prob_;
  std::vector<uint32_t> alias_;
  double total_ = 0.0;
};

// Prefix-sum table: O(n) build, O(log n) draw. Used where we also need the
// cumulative array itself (e.g. biased random-walk merge weights).
class PrefixTable {
 public:
  void Build(const float* weights, size_t n);
  void Build(const std::vector<float>& w) { Build(w.data(), w.size()); }

  size_t Draw(Rng& rng) const;

  size_t size() const { return cum_.size(); }
  double total_weight() const { return cum_.empty() ? 0.0 : cum_.back(); }

 private:
  std::vector<double> cum_;
};

// Binary search a cumulative float array segment [begin, end) for value r
// in [0, end[-1]); returns the index offset within the segment.
size_t SearchCumulative(const float* cum, size_t n, float r);

// Flat-CSR Walker/Vose build for the device-side EXACT sampler: row r's
// entries live at [offsets[r], offsets[r+1]); fill prob[slot] (chance of
// keeping the slot's own entry) and alias[slot] (ROW-LOCAL index of the
// alternative) for every slot. Zero/negative-total rows fall back to
// uniform (prob 1, alias self) — callers mask them with the engine's
// unsampleable contract, exactly like the padded-slab path. Parallel
// over rows (the device exporter calls it on multi-million-row CSRs).
void BuildAliasRows(const int64_t* offsets, int64_t num_rows,
                    const float* weights, float* prob, int32_t* alias);

}  // namespace eg

#endif  // EG_SAMPLING_H_
