// TCP shard registry: multi-host service discovery without a shared
// filesystem.
//
// Role equivalent of the reference's ZooKeeper discovery pair
// (reference euler/common/zk_server_register.cc:32-48 creates ephemeral
// znodes "<shard>#<ip:port>"; zk_server_monitor.cc:50-64 watches and parses
// them). The TPU-native reshape: one tiny TCP server — naturally hosted by
// the training coordinator process — holding soft state with TTL expiry.
// Shards REG themselves and heartbeat (re-REG) to stay alive, exactly the
// ephemeral-znode semantics: a dead shard's entry vanishes after ttl_ms with
// no session machinery. Clients LIST to discover live shards. Registry soft
// state survives registry restarts because registrants keep heartbeating.
//
// Wire format: the same [u32 len][payload] frames as the graph service
// (eg_wire.h), with text payloads:
//   "REG <shard> <host>:<port> [<epoch>]"   -> "OK"
//   "UNREG <shard> <host>:<port>"           -> "OK"
//   "LIST"                 -> "<shard> <host>:<port> <epoch>\n" per entry
// A connection may issue any number of requests; registrants typically hold
// one open for heartbeats, clients dial once per LIST.
//
// The trailing epoch token (eg_epoch.h) is the discovery half of the
// flip announcement: shards re-REG their current serving epoch every
// heartbeat, clients see it in LIST. Backward compatible both ways —
// pre-epoch registries and clients parse "<shard> <addr>" and ignore
// the extra token; a missing token reads as epoch 0.
#ifndef EG_REGISTRY_H_
#define EG_REGISTRY_H_

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace eg {

// Registry connections beyond this answer "ERR busy" and close (counted
// in busy_rejects) — the registry is tiny control-plane traffic, so a
// storm of connections here is a bug or an attack, not load to queue.
constexpr int kMaxRegistryConns = 256;

class RegistryServer {
 public:
  ~RegistryServer() { Stop(); }

  // Bind host:port (port 0 = ephemeral) and serve. Entries expire ttl_ms
  // after their last REG. False + error() on failure.
  bool Start(const std::string& host, int port, int ttl_ms);
  void Stop();

  int port() const { return port_; }
  const std::string& error() const { return error_; }

 private:
  void AcceptLoop();
  void HandleConn(int fd);
  std::string Dispatch(const std::string& req);

  std::string error_;
  int port_ = 0;
  int ttl_ms_ = 10000;
  int listen_fd_ = -1;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex mu_;  // guards entries_ and conn_fds_
  struct Entry {
    std::chrono::steady_clock::time_point expiry;
    uint64_t epoch = 0;  // last announced serving epoch (eg_epoch.h)
  };
  // (shard, "host:port") -> soft state
  std::map<std::pair<int, std::string>, Entry> entries_;
  std::set<int> conn_fds_;
  std::atomic<int> active_conns_{0};
  // signaled (under mu_) as each handler exits, so Stop() can wait on a
  // condvar instead of the old 1 ms busy-wait poll
  std::condition_variable conns_cv_;
};

// ---- client side ----

// "tcp://host:port" -> (host, port); false when s is not a tcp:// URL.
bool ParseTcpRegistry(const std::string& s, std::string* host, int* port);

// One REG/UNREG round trip on an existing connection fd (reconnects are the
// caller's job). False on IO error or non-OK reply. When ttl_ms is non-null
// and the reply carries the registry's TTL ("OK <ttl_ms>"), it is written
// there so registrants can pace heartbeats to the actual TTL.
bool RegistrySend(int fd, const std::string& line, int* ttl_ms = nullptr);

// Dial, LIST, parse into shard -> replica addresses. False on IO error
// (empty registry is ok=true with empty *out). When epochs is non-null
// it receives each entry's announced epoch keyed by (shard, addr) —
// entries from pre-epoch registrants read as 0.
bool RegistryList(
    const std::string& host, int port, int timeout_ms,
    std::map<int, std::vector<std::string>>* out,
    std::map<std::pair<int, std::string>, uint64_t>* epochs = nullptr);

}  // namespace eg

#endif  // EG_REGISTRY_H_
