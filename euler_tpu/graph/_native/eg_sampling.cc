#include "eg_sampling.h"

#include <algorithm>

namespace eg {

namespace {
thread_local Rng tls_rng(0xC0FFEE123456789ULL ^
                         reinterpret_cast<uint64_t>(&tls_rng));
}  // namespace

Rng& ThreadRng() { return tls_rng; }
void SeedThreadRng(uint64_t seed) { tls_rng = Rng(seed); }

void AliasTable::Build(const float* weights, size_t n) {
  prob_.assign(n, 1.0);
  alias_.assign(n, 0);
  total_ = 0.0;
  if (n == 0) return;
  for (size_t i = 0; i < n; ++i) total_ += weights[i];
  if (total_ <= 0.0) {
    // Degenerate: uniform.
    for (size_t i = 0; i < n; ++i) {
      prob_[i] = 1.0;
      alias_[i] = static_cast<uint32_t>(i);
    }
    total_ = 0.0;
    return;
  }
  const double scale = static_cast<double>(n) / total_;
  std::vector<double> scaled(n);
  std::vector<uint32_t> small, large;
  small.reserve(n);
  large.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    scaled[i] = weights[i] * scale;
    (scaled[i] < 1.0 ? small : large).push_back(static_cast<uint32_t>(i));
  }
  while (!small.empty() && !large.empty()) {
    uint32_t s = small.back();
    small.pop_back();
    uint32_t l = large.back();
    large.pop_back();
    prob_[s] = scaled[s];
    alias_[s] = l;
    scaled[l] = scaled[l] - (1.0 - scaled[s]);
    (scaled[l] < 1.0 ? small : large).push_back(l);
  }
  for (uint32_t i : large) prob_[i] = 1.0;
  for (uint32_t i : small) prob_[i] = 1.0;
}

void PrefixTable::Build(const float* weights, size_t n) {
  cum_.resize(n);
  double acc = 0.0;
  for (size_t i = 0; i < n; ++i) {
    acc += weights[i];
    cum_[i] = acc;
  }
}

size_t PrefixTable::Draw(Rng& rng) const {
  if (cum_.empty()) return 0;
  double r = rng.NextDouble() * cum_.back();
  auto it = std::upper_bound(cum_.begin(), cum_.end(), r);
  if (it == cum_.end()) --it;
  return static_cast<size_t>(it - cum_.begin());
}

size_t SearchCumulative(const float* cum, size_t n, float r) {
  const float* it = std::upper_bound(cum, cum + n, r);
  if (it == cum + n) --it;
  return static_cast<size_t>(it - cum);
}

void BuildAliasRows(const int64_t* offsets, int64_t num_rows,
                    const float* weights, float* prob, int32_t* alias) {
#pragma omp parallel
  {
    // per-thread scratch reused across rows (heavy-tail rows reach
    // tens of thousands of entries; reallocating per row would thrash)
    std::vector<double> scaled;
    std::vector<int32_t> small, large;
#pragma omp for schedule(dynamic, 256)
    for (int64_t r = 0; r < num_rows; ++r) {
      const int64_t base = offsets[r];
      const int64_t n = offsets[r + 1] - base;
      if (n <= 0) continue;
      double total = 0.0;
      for (int64_t i = 0; i < n; ++i) total += weights[base + i];
      if (total <= 0.0) {  // degenerate: uniform, like AliasTable
        for (int64_t i = 0; i < n; ++i) {
          prob[base + i] = 1.0f;
          alias[base + i] = static_cast<int32_t>(i);
        }
        continue;
      }
      const double scale = static_cast<double>(n) / total;
      scaled.resize(n);
      small.clear();
      large.clear();
      for (int64_t i = 0; i < n; ++i) {
        scaled[i] = weights[base + i] * scale;
        (scaled[i] < 1.0 ? small : large)
            .push_back(static_cast<int32_t>(i));
      }
      // defaults cover entries the loop leaves untouched
      for (int64_t i = 0; i < n; ++i) {
        prob[base + i] = 1.0f;
        alias[base + i] = static_cast<int32_t>(i);
      }
      while (!small.empty() && !large.empty()) {
        int32_t s = small.back();
        small.pop_back();
        int32_t l = large.back();
        large.pop_back();
        prob[base + s] = static_cast<float>(scaled[s]);
        alias[base + s] = l;
        scaled[l] -= 1.0 - scaled[s];
        (scaled[l] < 1.0 ? small : large).push_back(l);
      }
    }
  }
}

}  // namespace eg
