#include "eg_registry.h"

#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sstream>

#include "eg_fault.h"
#include "eg_stats.h"
#include "eg_wire.h"

namespace eg {

bool RegistryServer::Start(const std::string& host, int port, int ttl_ms) {
  ttl_ms_ = ttl_ms > 0 ? ttl_ms : 10000;
  listen_fd_ = ListenTcp(host.empty() ? "0.0.0.0" : host, port, &port_);
  if (listen_fd_ < 0) {
    error_ = "registry: cannot bind " + host + ":" + std::to_string(port);
    return false;
  }
  stopping_ = false;
  accept_thread_ = std::thread([this] {
    try {
      AcceptLoop();
    } catch (...) {
      // std::terminate barrier (eg-lint: thread-catch): a dead accept
      // loop stops admitting connections; registrants' heartbeats fail
      // loudly instead of the whole process aborting
    }
  });
  return true;
}

void RegistryServer::Stop() {
  if (listen_fd_ < 0) return;
  stopping_ = true;
  ::shutdown(listen_fd_, SHUT_RDWR);
  ::close(listen_fd_);
  if (accept_thread_.joinable()) accept_thread_.join();
  listen_fd_ = -1;
  // Same drain contract as the shard service: shut the live connections
  // down, then wait on the condvar (not a busy poll) until every
  // detached handler has deregistered itself.
  std::unique_lock<std::mutex> l(mu_);
  for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  conns_cv_.wait(l, [this] {
    return active_conns_.load(std::memory_order_acquire) == 0;
  });
}

void RegistryServer::AcceptLoop() {
  while (!stopping_) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) {
      if (stopping_) break;
      continue;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // Bounded accept (the service's admission treatment, sized for a
    // control plane): a connection storm gets one "ERR busy" frame and
    // a close instead of an unbounded handler-thread spawn.
    if (active_conns_.load(std::memory_order_acquire) >=
        kMaxRegistryConns) {
      Counters::Global().Add(kCtrBusyReject);
      SendFrame(fd, "ERR busy");
      ::close(fd);
      continue;
    }
    {
      std::lock_guard<std::mutex> l(mu_);
      conn_fds_.insert(fd);
    }
    active_conns_.fetch_add(1, std::memory_order_acq_rel);
    std::thread([this, fd] {
      try {
        HandleConn(fd);
      } catch (...) {
        // one hostile client must not std::terminate the registry
        // (eg-lint: thread-catch); cleanup below still runs
      }
      {
        std::lock_guard<std::mutex> l(mu_);
        conn_fds_.erase(fd);
      }
      ::close(fd);
      active_conns_.fetch_sub(1, std::memory_order_acq_rel);
      {
        // under mu_, so Stop()'s wait cannot miss the last decrement
        std::lock_guard<std::mutex> l(mu_);
        conns_cv_.notify_all();
      }
    }).detach();
  }
}

void RegistryServer::HandleConn(int fd) {
  std::string req;
  while (!stopping_ && RecvFrame(fd, &req)) {
    std::string reply = Dispatch(req);
    // kFaultRegistryReply: the REG/LIST was processed but its reply is
    // lost — registrants must treat it as a missed heartbeat and redial,
    // clients as a failed discovery pass.
    if (FaultHit(kFaultRegistryReply)) break;
    if (!SendFrame(fd, reply)) break;
  }
}

namespace {

// A registration address must look like host:port — hostile bytes that
// happen to parse as "<digits> <garbage>" must not become entries served
// to every LIST client (state poisoning; the reference's ZK quotas play
// this role for znode names).
bool ValidAddr(const std::string& a) {
  if (a.size() > 256) return false;
  auto colon = a.rfind(':');
  if (colon == std::string::npos || colon == 0 || colon + 1 >= a.size())
    return false;
  for (size_t i = colon + 1; i < a.size(); ++i)
    if (!std::isdigit(static_cast<unsigned char>(a[i]))) return false;
  for (size_t i = 0; i < colon; ++i) {
    unsigned char c = static_cast<unsigned char>(a[i]);
    if (!(std::isalnum(c) || c == '.' || c == '-' || c == '_'))
      return false;
  }
  return true;
}

}  // namespace

std::string RegistryServer::Dispatch(const std::string& req) {
  std::istringstream ss(req);
  std::string op;
  ss >> op;
  auto now = std::chrono::steady_clock::now();
  if (op == "REG" || op == "UNREG") {
    int shard = -1;
    std::string addr;
    ss >> shard >> addr;
    if (shard < 0 || shard > (1 << 20) || !ValidAddr(addr))
      return "ERR bad request";
    // optional trailing epoch token (eg_epoch.h); absent (a pre-epoch
    // registrant) or malformed reads as 0
    uint64_t epoch = 0;
    std::string tok;
    if (op == "REG" && ss >> tok) {
      char* end = nullptr;
      unsigned long long v = std::strtoull(tok.c_str(), &end, 10);
      if (end == tok.c_str() + tok.size()) epoch = v;
    }
    std::lock_guard<std::mutex> l(mu_);
    if (op == "REG")
      entries_[{shard, addr}] = {now + std::chrono::milliseconds(ttl_ms_),
                                 epoch};
    else
      entries_.erase({shard, addr});
    // reply carries the TTL so registrants can pace heartbeats to it
    return "OK " + std::to_string(ttl_ms_);
  }
  if (op == "LIST") {
    std::ostringstream out;
    std::lock_guard<std::mutex> l(mu_);
    for (auto it = entries_.begin(); it != entries_.end();) {
      if (it->second.expiry < now) {
        it = entries_.erase(it);  // expired: the ephemeral-znode analog
      } else {
        out << it->first.first << " " << it->first.second << " "
            << it->second.epoch << "\n";
        ++it;
      }
    }
    return out.str();
  }
  return "ERR unknown op";
}

// ---- client side ----

bool ParseTcpRegistry(const std::string& s, std::string* host, int* port) {
  const std::string prefix = "tcp://";
  if (s.compare(0, prefix.size(), prefix) != 0) return false;
  std::string rest = s.substr(prefix.size());
  size_t colon = rest.rfind(':');
  if (colon == std::string::npos || colon == 0) return false;
  *host = rest.substr(0, colon);
  *port = std::atoi(rest.c_str() + colon + 1);
  return *port > 0;
}

bool RegistrySend(int fd, const std::string& line, int* ttl_ms) {
  if (fd < 0 || !SendFrame(fd, line)) return false;
  std::string reply;
  if (!RecvFrame(fd, &reply) || reply.compare(0, 2, "OK") != 0) return false;
  if (ttl_ms && reply.size() > 3) {
    int t = std::atoi(reply.c_str() + 3);
    if (t > 0) *ttl_ms = t;
  }
  return true;
}

bool RegistryList(
    const std::string& host, int port, int timeout_ms,
    std::map<int, std::vector<std::string>>* out,
    std::map<std::pair<int, std::string>, uint64_t>* epochs) {
  int fd = DialTcp(host, port, timeout_ms);
  if (fd < 0) return false;
  std::string reply;
  bool ok = SendFrame(fd, "LIST") && RecvFrame(fd, &reply);
  ::close(fd);
  if (!ok) return false;
  std::istringstream ss(reply);
  std::string line;
  while (std::getline(ss, line)) {
    std::istringstream ls(line);
    int shard = -1;
    std::string addr;
    ls >> shard >> addr;
    if (shard >= 0 && !addr.empty()) {
      (*out)[shard].push_back(addr);
      if (epochs) {
        // trailing epoch token; a pre-epoch registry emits none -> 0
        uint64_t epoch = 0;
        std::string tok;
        if (ls >> tok) {
          char* end = nullptr;
          unsigned long long v = std::strtoull(tok.c_str(), &end, 10);
          if (end == tok.c_str() + tok.size()) epoch = v;
        }
        (*epochs)[{shard, addr}] = epoch;
      }
    }
  }
  return true;
}

}  // namespace eg
