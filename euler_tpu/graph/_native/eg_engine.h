// Batch engine over the graph store: the host-side query surface that feeds
// the TPU input pipeline.
//
// Functional equivalent of the reference GraphEngine
// (reference euler/core/graph_engine.h:33) plus parts of the local client
// (reference euler/client/local_graph.cc) — but batch-synchronous instead of
// callback-async: the Python side drives it from a prefetch thread pool that
// overlaps sampling with TPU compute, so the async completion machinery of
// the reference (AsyncOpKernel + callbacks) is unnecessary. Batch ops are
// parallelized with OpenMP over rows.
#ifndef EG_ENGINE_H_
#define EG_ENGINE_H_

#include <algorithm>
#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "eg_api.h"
#include "eg_graph.h"

namespace eg {

// Variable-shaped result container crossing the C ABI (fixed-shape calls
// write straight into caller-allocated numpy buffers instead).
struct EGResult {
  std::vector<std::vector<uint64_t>> u64;
  std::vector<std::vector<float>> f32;
  std::vector<std::vector<int32_t>> i32;
  std::vector<std::string> bytes;
};

class Engine : public GraphAPI {
 public:
  // Load shard `shard_idx` of `shard_num` from a directory of partition
  // files named *_<p>.dat: the shard owns partitions p ≡ shard_idx (mod
  // shard_num) (reference euler/core/graph_engine.cc:90-107). Files without
  // a partition suffix belong to partition 0.
  bool Load(const std::string& dir, int shard_idx, int shard_num);
  bool LoadFiles(std::vector<std::string> files);
  // Parse partition bytes already in memory — the streaming ingest path
  // (remote bytes go fetch -> parse -> store with no local staging; the
  // reference reads partitions straight off HDFS instead,
  // euler/common/hdfs_file_io.cc:79-80). names[i] attributes parse
  // errors; buffers are merged in name-sorted order so the store is
  // byte-identical to LoadFiles on the same partitions.
  bool LoadBuffers(const char* const* bufs, const size_t* lens,
                   const char* const* names, int n);
  // Build directly from pre-parsed stagings — the snapshot-epoch merge
  // path (eg_epoch.cc) orders and filters stagings itself before the
  // store build.
  bool BuildFromStagings(std::vector<Staging>* parts) {
    return store_.Build(parts, &error_);
  }
  const std::string& error() const { return error_; }

  const GraphStore& store() const { return store_; }

  // ---- snapshot epochs (eg_epoch.h) ----
  // Which refresh generation this store represents: 0 for a plain base
  // load, the applied-delta count for a merged load.
  uint64_t Epoch() const override { return epoch_; }
  void set_epoch(uint64_t e) { epoch_ = e; }
  // The base partition files this store was built from — what a delta
  // flip re-merges. Empty for buffer-streamed loads (those cannot
  // delta-flip; the remote tier serves that case).
  const std::vector<std::string>& source_files() const {
    return source_files_;
  }
  void set_source_files(std::vector<std::string> files) {
    source_files_ = std::move(files);
  }
  // Move another engine's built store into this one (the in-place merge
  // path, eg_epoch.cc LoadEngineWithDeltas) — the handle identity the
  // C ABI handed out stays stable.
  void Adopt(Engine&& other) {
    store_ = std::move(other.store_);
    epoch_ = other.epoch_;
    source_files_ = std::move(other.source_files_);
  }

  // ---- introspection (GraphAPI) ----
  int64_t NumNodes() const override {
    return static_cast<int64_t>(store_.num_nodes());
  }
  int64_t NumEdges() const override {
    return static_cast<int64_t>(store_.num_edges());
  }
  int32_t NodeTypeNum() const override { return store_.node_type_num(); }
  int32_t EdgeTypeNum() const override { return store_.edge_type_num(); }
  int32_t FeatureNum(int kind) const override {
    switch (kind) {
      case 0: return store_.nf_u64_num();
      case 1: return store_.nf_f32_num();
      case 2: return store_.nf_bin_num();
      case 3: return store_.ef_u64_num();
      case 4: return store_.ef_f32_num();
      case 5: return store_.ef_bin_num();
      default: return -1;
    }
  }
  void TypeWeightSums(int kind, float* out) const override {
    const auto& v = kind == 0 ? store_.node_type_weight_sums()
                              : store_.edge_type_weight_sums();
    std::copy(v.begin(), v.end(), out);
  }

  // ---- global sampling ----
  void SampleNode(int count, int32_t type, uint64_t* out) const;
  void SampleEdge(int count, int32_t type, uint64_t* out_src,
                  uint64_t* out_dst, int32_t* out_type) const;
  // Typed negative sampling: for each src row, `count` nodes drawn from the
  // global sampler of that src's node type. Replaces the reference's
  // unique/while_loop/inflate_idx pipeline
  // (reference tf_euler/python/euler_ops/sample_ops.py:39-67) with one
  // host-side batch call producing a fixed [n, count] block.
  void SampleNodeWithSrc(const uint64_t* src, int n, int count,
                         uint64_t* out) const;

  void GetNodeType(const uint64_t* ids, int n, int32_t* out) const;
  // Per-node sampling weights, 0 for unknown ids. Used by the
  // device-graph exporter to build the HBM-resident weighted root
  // sampler; also served remotely via kNodeWeight so the exporter
  // composes with sharded graphs. Always true locally (unknown ids are
  // a resolved answer: weight 0).
  bool GetNodeWeight(const uint64_t* ids, int n, float* out) const override;

  // ---- neighbor ops ----
  void SampleNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                      int net, int count, uint64_t default_id,
                      uint64_t* out_ids, float* out_w, int32_t* out_t) const;
  // Fused multi-hop fanout: one call produces every hop, avoiding the
  // per-hop op round trips of the reference
  // (reference tf_euler/python/euler_ops/neighbor_ops.py:86-92).
  // hop h input size n_h = n * prod(counts[:h]); outputs are caller
  // buffers of size n_{h+1} per hop.
  void SampleFanout(const uint64_t* ids, int n, const int32_t* etypes_flat,
                    const int32_t* etype_counts, const int32_t* counts,
                    int nhops, uint64_t default_id, uint64_t** out_ids,
                    float** out_w, int32_t** out_t) const;

  EGResult* GetFullNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                            int net, bool sorted) const;
  void GetTopKNeighbor(const uint64_t* ids, int n, const int32_t* etypes,
                       int net, int k, uint64_t default_id, uint64_t* out_ids,
                       float* out_w, int32_t* out_t) const;

  // ---- walks ----
  // out: [n, walk_len+1], column 0 = start ids. Walks through missing nodes
  // emit default_id for the rest of the walk. Each step s uses its own
  // edge-type set (etypes_flat segmented by etype_counts, one segment per
  // step) — heterogeneous metapath walks, matching the reference RandomWalk
  // op's per-step edge_types inputs (tf_euler/ops/walk_ops.cc:71-100).
  void RandomWalk(const uint64_t* ids, int n, const int32_t* etypes_flat,
                  const int32_t* etype_counts, int walk_len, float p, float q,
                  uint64_t default_id, uint64_t* out) const;

  // ---- features ----
  void GetDenseFeature(const uint64_t* ids, int n, const int32_t* fids,
                       const int32_t* dims, int nf, float* out) const;
  void GetEdgeDenseFeature(const uint64_t* src, const uint64_t* dst,
                           const int32_t* types, int n, const int32_t* fids,
                           const int32_t* dims, int nf, float* out) const;
  EGResult* GetSparseFeature(const uint64_t* ids, int n, const int32_t* fids,
                             int nf) const;
  EGResult* GetEdgeSparseFeature(const uint64_t* src, const uint64_t* dst,
                                 const int32_t* types, int n,
                                 const int32_t* fids, int nf) const;
  EGResult* GetBinaryFeature(const uint64_t* ids, int n, const int32_t* fids,
                             int nf) const;
  EGResult* GetEdgeBinaryFeature(const uint64_t* src, const uint64_t* dst,
                                 const int32_t* types, int n,
                                 const int32_t* fids, int nf) const;

 private:
  // One staging-parse fan-out shared by the file and buffer loaders
  // (strided worker pool, per-slot error attribution, merged Build) —
  // the two ingest modes must never diverge in threading or error
  // semantics. labels[i] attributes exceptions; parse_one fills
  // parts[i]/errors[i].
  bool ParseStagings(
      const std::vector<std::string>& labels,
      const std::function<void(int, Staging*, std::string*)>& parse_one);

  GraphStore store_;
  std::string error_;
  uint64_t epoch_ = 0;
  std::vector<std::string> source_files_;
};

}  // namespace eg

#endif  // EG_ENGINE_H_
