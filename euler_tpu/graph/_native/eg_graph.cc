#include "eg_graph.h"

#include <algorithm>
#include <cstddef>
#include <numeric>

namespace eg {

namespace {

// Checks a slot-count field is uniform across records.
bool FixCount(int32_t* slot, int32_t seen, const char* what,
              std::string* error) {
  if (*slot == -1) {
    *slot = seen;
    return true;
  }
  if (*slot != seen) {
    *error = std::string("non-uniform ") + what + " across records";
    return false;
  }
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// Parsing (.dat block format; spec from reference euler/tools/json2dat.py)
// ---------------------------------------------------------------------------

bool Staging::ParseFile(const char* data, size_t size) {
  ByteCursor cur(data, size);
  while (cur.remaining() > 0) {
    if (!ParseBlock(&cur)) {
      if (error.empty()) error = "truncated or malformed block";
      return false;
    }
  }
  return true;
}

bool Staging::ParseBlock(ByteCursor* cur) {
  int32_t block_bytes = 0, node_bytes = 0;
  if (!cur->Read(&block_bytes)) return false;
  if (!cur->Read(&node_bytes)) return false;
  if (node_bytes < 0 ||
      static_cast<size_t>(node_bytes) > cur->remaining()) {
    error = "bad node_info_bytes";
    return false;
  }

  // --- node record ---
  ByteCursor nc(cur->ptr(), static_cast<size_t>(node_bytes));
  if (!cur->Skip(static_cast<size_t>(node_bytes))) return false;

  uint64_t id;
  int32_t type, T;
  float weight;
  if (!nc.Read(&id) || !nc.Read(&type) || !nc.Read(&weight) || !nc.Read(&T))
    return false;
  // Corrupted types index the per-type sampler tables downstream
  // (negative -> size_t wrap, huge -> unbounded resize) — reject here.
  if (type < 0 || type > 1 << 20) {
    error = "bad node type";
    return false;
  }
  if (T < 0 || T > 1 << 20) {
    error = "bad edge_type_num";
    return false;
  }
  if (!FixCount(&edge_type_num, T, "edge_type_num", &error)) return false;

  std::vector<int32_t> gsize;
  std::vector<float> gweight;
  if (!nc.ReadVec(static_cast<size_t>(T), &gsize)) return false;
  if (!nc.ReadVec(static_cast<size_t>(T), &gweight)) return false;
  size_t total_nbr = 0;
  for (int32_t s : gsize) {
    if (s < 0) return false;
    total_nbr += static_cast<size_t>(s);
  }
  std::vector<uint64_t> nids;
  std::vector<float> nw;
  if (!nc.ReadVec(total_nbr, &nids)) return false;
  if (!nc.ReadVec(total_nbr, &nw)) return false;

  node_ids.push_back(id);
  node_types.push_back(type);
  node_weights.push_back(weight);
  grp_counts.insert(grp_counts.end(), gsize.begin(), gsize.end());
  grp_weights.insert(grp_weights.end(), gweight.begin(), gweight.end());
  // Sort each group's neighbors ascending by id (needed for the sorted-merge
  // paths: sorted full neighbor and biased-walk intersection).
  {
    size_t off = 0;
    std::vector<size_t> order;
    for (int32_t s : gsize) {
      size_t n = static_cast<size_t>(s);
      order.resize(n);
      std::iota(order.begin(), order.end(), size_t{0});
      std::sort(order.begin(), order.end(), [&](size_t a, size_t b) {
        return nids[off + a] < nids[off + b];
      });
      for (size_t j : order) {
        nbr_ids.push_back(nids[off + j]);
        nbr_w.push_back(nw[off + j]);
      }
      off += n;
    }
  }

  // --- node features: u64, f32, binary ---
  int32_t nu;
  if (!nc.Read(&nu)) return false;
  if (!FixCount(&nf_u64_num, nu, "node u64 feature num", &error)) return false;
  std::vector<int32_t> sizes;
  if (!nc.ReadVec(static_cast<size_t>(nu), &sizes)) return false;
  size_t tot = 0;
  for (int32_t s : sizes) {
    if (s < 0) return false;  // negative count -> wild iterator in Build
    tot += static_cast<size_t>(s);
  }
  nf_u64_cnt.insert(nf_u64_cnt.end(), sizes.begin(), sizes.end());
  {
    std::vector<uint64_t> vals;
    if (!nc.ReadVec(tot, &vals)) return false;
    nf_u64_val.insert(nf_u64_val.end(), vals.begin(), vals.end());
  }

  int32_t nf;
  if (!nc.Read(&nf)) return false;
  if (!FixCount(&nf_f32_num, nf, "node f32 feature num", &error)) return false;
  if (!nc.ReadVec(static_cast<size_t>(nf), &sizes)) return false;
  tot = 0;
  for (int32_t s : sizes) {
    if (s < 0) return false;
    tot += static_cast<size_t>(s);
  }
  nf_f32_cnt.insert(nf_f32_cnt.end(), sizes.begin(), sizes.end());
  {
    std::vector<float> vals;
    if (!nc.ReadVec(tot, &vals)) return false;
    nf_f32_val.insert(nf_f32_val.end(), vals.begin(), vals.end());
  }

  int32_t nb;
  if (!nc.Read(&nb)) return false;
  if (!FixCount(&nf_bin_num, nb, "node binary feature num", &error))
    return false;
  if (!nc.ReadVec(static_cast<size_t>(nb), &sizes)) return false;
  nf_bin_cnt.insert(nf_bin_cnt.end(), sizes.begin(), sizes.end());
  for (int32_t s : sizes) {
    std::string b;
    if (!nc.ReadStr(static_cast<size_t>(s), &b)) return false;
    nf_bin_val += b;
  }

  // --- edge records ---
  int32_t edge_num = 0;
  if (!cur->Read(&edge_num)) return false;
  if (edge_num < 0) return false;
  std::vector<int32_t> ebytes;
  if (!cur->ReadVec(static_cast<size_t>(edge_num), &ebytes)) return false;
  for (int32_t eb : ebytes) {
    if (eb < 0 || static_cast<size_t>(eb) > cur->remaining()) return false;
    if (!ParseEdgeRecord(cur->ptr(), static_cast<size_t>(eb))) return false;
    cur->Skip(static_cast<size_t>(eb));
  }

  // Framing check, mirroring the reference loader's "checksum"
  // (reference euler/core/graph_builder.cc:211-222).
  int64_t expect = 8 + 4LL * edge_num + node_bytes;
  for (int32_t eb : ebytes) expect += eb;
  if (expect != block_bytes) {
    error = "block framing mismatch";
    return false;
  }
  return true;
}

bool Staging::ParseEdgeRecord(const char* data, size_t size) {
  ByteCursor ec(data, size);
  uint64_t src, dst;
  int32_t type;
  float weight;
  if (!ec.Read(&src) || !ec.Read(&dst) || !ec.Read(&type) || !ec.Read(&weight))
    return false;
  if (type < 0 || type > 1 << 20) {  // see node-type check above
    error = "bad edge type";
    return false;
  }
  e_src.push_back(src);
  e_dst.push_back(dst);
  e_type.push_back(type);
  e_w.push_back(weight);

  int32_t nu;
  std::vector<int32_t> sizes;
  if (!ec.Read(&nu)) return false;
  if (!FixCount(&ef_u64_num, nu, "edge u64 feature num", &error)) return false;
  if (!ec.ReadVec(static_cast<size_t>(nu), &sizes)) return false;
  size_t tot = 0;
  for (int32_t s : sizes) {
    if (s < 0) return false;  // negative count -> wild iterator in Build
    tot += static_cast<size_t>(s);
  }
  ef_u64_cnt.insert(ef_u64_cnt.end(), sizes.begin(), sizes.end());
  {
    std::vector<uint64_t> vals;
    if (!ec.ReadVec(tot, &vals)) return false;
    ef_u64_val.insert(ef_u64_val.end(), vals.begin(), vals.end());
  }

  int32_t nf;
  if (!ec.Read(&nf)) return false;
  if (!FixCount(&ef_f32_num, nf, "edge f32 feature num", &error)) return false;
  if (!ec.ReadVec(static_cast<size_t>(nf), &sizes)) return false;
  tot = 0;
  for (int32_t s : sizes) {
    if (s < 0) return false;
    tot += static_cast<size_t>(s);
  }
  ef_f32_cnt.insert(ef_f32_cnt.end(), sizes.begin(), sizes.end());
  {
    std::vector<float> vals;
    if (!ec.ReadVec(tot, &vals)) return false;
    ef_f32_val.insert(ef_f32_val.end(), vals.begin(), vals.end());
  }

  int32_t nb;
  if (!ec.Read(&nb)) return false;
  if (!FixCount(&ef_bin_num, nb, "edge binary feature num", &error))
    return false;
  if (!ec.ReadVec(static_cast<size_t>(nb), &sizes)) return false;
  ef_bin_cnt.insert(ef_bin_cnt.end(), sizes.begin(), sizes.end());
  for (int32_t s : sizes) {
    std::string b;
    if (!ec.ReadStr(static_cast<size_t>(s), &b)) return false;
    ef_bin_val += b;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Build
// ---------------------------------------------------------------------------

bool GraphStore::Build(std::vector<Staging>* parts, std::string* error) {
  // Resolve uniform slot counts across partitions.
  int32_t T = -1, nu = -1, nf = -1, nb = -1, eu = -1, ef = -1, eb = -1;
  auto unify = [&](int32_t* acc, int32_t v, const char* what) {
    if (v == -1) return true;  // partition had no records of this kind
    if (*acc == -1) *acc = v;
    if (*acc != v) {
      *error = std::string("partitions disagree on ") + what;
      return false;
    }
    return true;
  };
  for (auto& s : *parts) {
    if (!s.error.empty()) {
      *error = s.error;
      return false;
    }
    if (!unify(&T, s.edge_type_num, "edge_type_num") ||
        !unify(&nu, s.nf_u64_num, "node u64 slots") ||
        !unify(&nf, s.nf_f32_num, "node f32 slots") ||
        !unify(&nb, s.nf_bin_num, "node binary slots") ||
        !unify(&eu, s.ef_u64_num, "edge u64 slots") ||
        !unify(&ef, s.ef_f32_num, "edge f32 slots") ||
        !unify(&eb, s.ef_bin_num, "edge binary slots"))
      return false;
  }
  edge_type_num_ = std::max(T, 0);
  nf_u64_num_ = std::max(nu, 0);
  nf_f32_num_ = std::max(nf, 0);
  nf_bin_num_ = std::max(nb, 0);
  ef_u64_num_ = std::max(eu, 0);
  ef_f32_num_ = std::max(ef, 0);
  ef_bin_num_ = std::max(eb, 0);

  size_t node_cap = 0, edge_cap = 0;
  for (auto& s : *parts) {
    node_cap += s.node_ids.size();
    edge_cap += s.e_src.size();
  }
  node_ids_.reserve(node_cap);
  node_idx_.reserve(node_cap * 2);
  edge_idx_.reserve(edge_cap * 2);
  adj_off_.push_back(0);
  nf_u64_off_.push_back(0);
  nf_f32_off_.push_back(0);
  nf_bin_off_.push_back(0);
  ef_u64_off_.push_back(0);
  ef_f32_off_.push_back(0);
  ef_bin_off_.push_back(0);

  for (auto& s : *parts) {
    // Per-partition running cursors into the concatenated staging arrays.
    size_t nbr_cur = 0, u64_cur = 0, f32_cur = 0, bin_cur = 0;
    for (size_t i = 0; i < s.node_ids.size(); ++i) {
      // Stage sizes for this node.
      size_t nbr_n = 0;
      for (int32_t t = 0; t < edge_type_num_; ++t)
        nbr_n += static_cast<size_t>(s.grp_counts[i * edge_type_num_ + t]);
      size_t u64_n = 0, f32_n = 0, bin_n = 0;
      for (int32_t k = 0; k < nf_u64_num_; ++k)
        u64_n += static_cast<size_t>(s.nf_u64_cnt[i * nf_u64_num_ + k]);
      for (int32_t k = 0; k < nf_f32_num_; ++k)
        f32_n += static_cast<size_t>(s.nf_f32_cnt[i * nf_f32_num_ + k]);
      for (int32_t k = 0; k < nf_bin_num_; ++k)
        bin_n += static_cast<size_t>(s.nf_bin_cnt[i * nf_bin_num_ + k]);

      uint64_t id = s.node_ids[i];
      bool dup = !node_idx_
                      .emplace(id, static_cast<int64_t>(node_ids_.size()))
                      .second;
      if (!dup) {
        node_ids_.push_back(id);
        node_types_.push_back(s.node_types[i]);
        node_weights_.push_back(s.node_weights[i]);
        // adjacency groups
        size_t cur = nbr_cur;
        for (int32_t t = 0; t < edge_type_num_; ++t) {
          size_t n = static_cast<size_t>(s.grp_counts[i * edge_type_num_ + t]);
          float acc = 0.f;
          for (size_t j = 0; j < n; ++j) {
            adj_nbr_.push_back(s.nbr_ids[cur + j]);
            float w = s.nbr_w[cur + j];
            adj_w_.push_back(w);
            acc += w;
            adj_cumw_.push_back(acc);
          }
          cur += n;
          adj_off_.push_back(static_cast<int64_t>(adj_nbr_.size()));
          grp_w_.push_back(acc);
        }
        // features
        size_t c = u64_cur;
        for (int32_t k = 0; k < nf_u64_num_; ++k) {
          size_t n = static_cast<size_t>(s.nf_u64_cnt[i * nf_u64_num_ + k]);
          nf_u64_val_.insert(nf_u64_val_.end(), s.nf_u64_val.begin() + c,
                             s.nf_u64_val.begin() + c + n);
          c += n;
          nf_u64_off_.push_back(static_cast<int64_t>(nf_u64_val_.size()));
        }
        c = f32_cur;
        for (int32_t k = 0; k < nf_f32_num_; ++k) {
          size_t n = static_cast<size_t>(s.nf_f32_cnt[i * nf_f32_num_ + k]);
          nf_f32_val_.insert(nf_f32_val_.end(), s.nf_f32_val.begin() + c,
                             s.nf_f32_val.begin() + c + n);
          c += n;
          nf_f32_off_.push_back(static_cast<int64_t>(nf_f32_val_.size()));
        }
        c = bin_cur;
        for (int32_t k = 0; k < nf_bin_num_; ++k) {
          size_t n = static_cast<size_t>(s.nf_bin_cnt[i * nf_bin_num_ + k]);
          nf_bin_val_.append(s.nf_bin_val, c, n);
          c += n;
          nf_bin_off_.push_back(static_cast<int64_t>(nf_bin_val_.size()));
        }
      }
      nbr_cur += nbr_n;
      u64_cur += u64_n;
      f32_cur += f32_n;
      bin_cur += bin_n;
    }

    size_t eu_cur = 0, ef_cur = 0, eb_cur = 0;
    for (size_t i = 0; i < s.e_src.size(); ++i) {
      size_t u64_n = 0, f32_n = 0, bin_n = 0;
      for (int32_t k = 0; k < ef_u64_num_; ++k)
        u64_n += static_cast<size_t>(s.ef_u64_cnt[i * ef_u64_num_ + k]);
      for (int32_t k = 0; k < ef_f32_num_; ++k)
        f32_n += static_cast<size_t>(s.ef_f32_cnt[i * ef_f32_num_ + k]);
      for (int32_t k = 0; k < ef_bin_num_; ++k)
        bin_n += static_cast<size_t>(s.ef_bin_cnt[i * ef_bin_num_ + k]);

      EdgeKey key{s.e_src[i], s.e_dst[i], s.e_type[i]};
      bool dup =
          !edge_idx_.emplace(key, static_cast<int64_t>(e_src_.size())).second;
      if (!dup) {
        e_src_.push_back(s.e_src[i]);
        e_dst_.push_back(s.e_dst[i]);
        e_type_.push_back(s.e_type[i]);
        e_w_.push_back(s.e_w[i]);
        size_t c = eu_cur;
        for (int32_t k = 0; k < ef_u64_num_; ++k) {
          size_t n = static_cast<size_t>(s.ef_u64_cnt[i * ef_u64_num_ + k]);
          ef_u64_val_.insert(ef_u64_val_.end(), s.ef_u64_val.begin() + c,
                             s.ef_u64_val.begin() + c + n);
          c += n;
          ef_u64_off_.push_back(static_cast<int64_t>(ef_u64_val_.size()));
        }
        c = ef_cur;
        for (int32_t k = 0; k < ef_f32_num_; ++k) {
          size_t n = static_cast<size_t>(s.ef_f32_cnt[i * ef_f32_num_ + k]);
          ef_f32_val_.insert(ef_f32_val_.end(), s.ef_f32_val.begin() + c,
                             s.ef_f32_val.begin() + c + n);
          c += n;
          ef_f32_off_.push_back(static_cast<int64_t>(ef_f32_val_.size()));
        }
        c = eb_cur;
        for (int32_t k = 0; k < ef_bin_num_; ++k) {
          size_t n = static_cast<size_t>(s.ef_bin_cnt[i * ef_bin_num_ + k]);
          ef_bin_val_.append(s.ef_bin_val, c, n);
          c += n;
          ef_bin_off_.push_back(static_cast<int64_t>(ef_bin_val_.size()));
        }
      }
      eu_cur += u64_n;
      ef_cur += f32_n;
      eb_cur += bin_n;
    }
    s = Staging();  // free staging memory as we go
  }

  // Node/edge type counts from the data.
  node_type_num_ = 0;
  for (int32_t t : node_types_) node_type_num_ = std::max(node_type_num_, t + 1);
  for (int32_t t : e_type_) edge_type_num_ = std::max(edge_type_num_, t + 1);

  // Global per-type samplers (weight-proportional, alias method).
  nodes_by_type_.assign(static_cast<size_t>(node_type_num_), {});
  for (size_t i = 0; i < node_ids_.size(); ++i)
    nodes_by_type_[static_cast<size_t>(node_types_[i])].push_back(
        static_cast<int64_t>(i));
  node_samplers_.resize(nodes_by_type_.size());
  node_type_wsum_.resize(nodes_by_type_.size());
  std::vector<float> w;
  for (size_t t = 0; t < nodes_by_type_.size(); ++t) {
    w.clear();
    double sum = 0.0;
    for (int64_t i : nodes_by_type_[t]) {
      w.push_back(node_weights_[i]);
      sum += node_weights_[i];
    }
    node_samplers_[t].Build(w);
    node_type_wsum_[t] = static_cast<float>(sum);
  }
  node_type_sampler_.Build(node_type_wsum_);

  edges_by_type_.assign(static_cast<size_t>(edge_type_num_), {});
  for (size_t i = 0; i < e_src_.size(); ++i)
    edges_by_type_[static_cast<size_t>(e_type_[i])].push_back(
        static_cast<int64_t>(i));
  edge_samplers_.resize(edges_by_type_.size());
  edge_type_wsum_.resize(edges_by_type_.size());
  for (size_t t = 0; t < edges_by_type_.size(); ++t) {
    w.clear();
    double sum = 0.0;
    for (int64_t i : edges_by_type_[t]) {
      w.push_back(e_w_[i]);
      sum += e_w_[i];
    }
    edge_samplers_[t].Build(w);
    edge_type_wsum_[t] = static_cast<float>(sum);
  }
  edge_type_sampler_.Build(edge_type_wsum_);
  return true;
}

// ---------------------------------------------------------------------------
// Sampling & queries
// ---------------------------------------------------------------------------

uint64_t GraphStore::SampleNode(int32_t type, Rng& rng) const {
  if (node_ids_.empty()) return 0;
  size_t t;
  if (type < 0) {
    t = node_type_sampler_.Draw(rng);
  } else if (static_cast<size_t>(type) < nodes_by_type_.size()) {
    t = static_cast<size_t>(type);
  } else {
    return 0;
  }
  const auto& idxs = nodes_by_type_[t];
  if (idxs.empty()) return 0;
  return node_ids_[idxs[node_samplers_[t].Draw(rng)]];
}

int64_t GraphStore::SampleEdgeIdx(int32_t type, Rng& rng) const {
  if (e_src_.empty()) return -1;
  size_t t;
  if (type < 0) {
    t = edge_type_sampler_.Draw(rng);
  } else if (static_cast<size_t>(type) < edges_by_type_.size()) {
    t = static_cast<size_t>(type);
  } else {
    return -1;
  }
  const auto& idxs = edges_by_type_[t];
  if (idxs.empty()) return -1;
  return idxs[edge_samplers_[t].Draw(rng)];
}

void GraphStore::SampleNeighbors(int64_t nidx, const int32_t* etypes, int net,
                                 int count, uint64_t default_id, Rng& rng,
                                 uint64_t* out_ids, float* out_w,
                                 int32_t* out_t) const {
  double total = 0.0;
  if (nidx >= 0) {
    for (int e = 0; e < net; ++e) {
      int32_t t = etypes[e];
      if (t < 0 || t >= edge_type_num_) continue;
      int64_t n;
      const float* cum = GroupCum(nidx, t, &n);
      if (n > 0) total += cum[n - 1];
    }
  }
  if (total <= 0.0) {
    for (int j = 0; j < count; ++j) {
      out_ids[j] = default_id;
      out_w[j] = 0.f;
      out_t[j] = -1;
    }
    return;
  }
  for (int j = 0; j < count; ++j) {
    double r = rng.NextDouble() * total;
    // Pick the group by weight prefix, then binary-search its cumulative
    // array. Falls back to the last non-empty group on float rounding spill.
    int32_t pick_group = -1;
    double r_in_group = 0.0;
    for (int e = 0; e < net; ++e) {
      int32_t t = etypes[e];
      if (t < 0 || t >= edge_type_num_) continue;
      int64_t n;
      const float* cum = GroupCum(nidx, t, &n);
      if (n == 0) continue;
      double gt = cum[n - 1];
      pick_group = t;
      r_in_group = r;
      if (r < gt) break;
      r -= gt;
    }
    int64_t n;
    const float* cum = GroupCum(nidx, pick_group, &n);
    size_t k = SearchCumulative(cum, static_cast<size_t>(n),
                                static_cast<float>(r_in_group));
    int64_t off = adj_off_[nidx * edge_type_num_ + pick_group];
    out_ids[j] = adj_nbr_[off + static_cast<int64_t>(k)];
    out_w[j] = adj_w_[off + static_cast<int64_t>(k)];
    out_t[j] = pick_group;
  }
}

void GraphStore::FullNeighbors(int64_t nidx, const int32_t* etypes, int net,
                               bool sorted, std::vector<uint64_t>* ids,
                               std::vector<float>* w,
                               std::vector<int32_t>* t) const {
  if (nidx < 0) return;
  if (!sorted) {
    for (int e = 0; e < net; ++e) {
      int32_t et = etypes[e];
      if (et < 0 || et >= edge_type_num_) continue;
      int64_t g = nidx * edge_type_num_ + et;
      for (int64_t j = adj_off_[g]; j < adj_off_[g + 1]; ++j) {
        ids->push_back(adj_nbr_[j]);
        w->push_back(adj_w_[j]);
        t->push_back(et);
      }
    }
    return;
  }
  // k-way merge of id-sorted groups.
  struct Head {
    int64_t pos, end;
    int32_t et;
  };
  std::vector<Head> heads;
  for (int e = 0; e < net; ++e) {
    int32_t et = etypes[e];
    if (et < 0 || et >= edge_type_num_) continue;
    int64_t g = nidx * edge_type_num_ + et;
    if (adj_off_[g] < adj_off_[g + 1])
      heads.push_back(Head{adj_off_[g], adj_off_[g + 1], et});
  }
  while (!heads.empty()) {
    size_t best = 0;
    for (size_t h = 1; h < heads.size(); ++h)
      if (adj_nbr_[heads[h].pos] < adj_nbr_[heads[best].pos]) best = h;
    ids->push_back(adj_nbr_[heads[best].pos]);
    w->push_back(adj_w_[heads[best].pos]);
    t->push_back(heads[best].et);
    if (++heads[best].pos == heads[best].end)
      heads.erase(heads.begin() + static_cast<ptrdiff_t>(best));
  }
}

void GraphStore::TopKNeighbors(int64_t nidx, const int32_t* etypes, int net,
                               int k, uint64_t default_id, uint64_t* out_ids,
                               float* out_w, int32_t* out_t) const {
  std::vector<uint64_t> ids;
  std::vector<float> w;
  std::vector<int32_t> t;
  FullNeighbors(nidx, etypes, net, false, &ids, &w, &t);
  std::vector<size_t> order(ids.size());
  std::iota(order.begin(), order.end(), size_t{0});
  size_t take = std::min(static_cast<size_t>(k), ids.size());
  std::partial_sort(order.begin(), order.begin() + static_cast<ptrdiff_t>(take),
                    order.end(),
                    [&](size_t a, size_t b) { return w[a] > w[b]; });
  for (int j = 0; j < k; ++j) {
    if (static_cast<size_t>(j) < take) {
      out_ids[j] = ids[order[static_cast<size_t>(j)]];
      out_w[j] = w[order[static_cast<size_t>(j)]];
      out_t[j] = t[order[static_cast<size_t>(j)]];
    } else {
      out_ids[j] = default_id;
      out_w[j] = 0.f;
      out_t[j] = -1;
    }
  }
}

uint64_t GraphStore::BiasedNeighbor(int64_t nidx, bool has_parent,
                                    uint64_t parent_id, const int32_t* etypes,
                                    int net, float p, float q,
                                    uint64_t default_id, Rng& rng) const {
  if (nidx < 0) return default_id;
  if (!has_parent || (p == 1.f && q == 1.f)) {
    // No parent yet (first hop) or unbiased: plain weighted draw.
    uint64_t id;
    float w;
    int32_t t;
    SampleNeighbors(nidx, etypes, net, 1, default_id, rng, &id, &w, &t);
    return id;
  }
  std::vector<uint64_t> ids;
  std::vector<float> w;
  std::vector<int32_t> t;
  FullNeighbors(nidx, etypes, net, true, &ids, &w, &t);
  if (ids.empty()) return default_id;

  std::vector<uint64_t> pids;
  std::vector<float> pw;
  std::vector<int32_t> pt;
  int64_t parent_idx = NodeIndex(parent_id);
  if (parent_idx >= 0)
    FullNeighbors(parent_idx, etypes, net, true, &pids, &pw, &pt);
  // d_tx weighting (reference euler/client/graph.cc:120-151): x adjacent
  // to parent → w (this wins even for x == parent when the parent has a
  // self-loop — the reference merge's equality branch runs first);
  // x == parent → w/p; else w/q. Sorted two-pointer intersect.
  std::vector<float> cum(ids.size());
  double acc = 0.0;
  size_t pi = 0;
  for (size_t j = 0; j < ids.size(); ++j) {
    while (pi < pids.size() && pids[pi] < ids[j]) ++pi;
    float wj = w[j];
    if (pi < pids.size() && pids[pi] == ids[j]) {
      // distance 1: keep wj
    } else if (ids[j] == parent_id) {
      wj /= p;
    } else {
      wj /= q;
    }
    acc += wj;
    cum[j] = static_cast<float>(acc);
  }
  if (acc <= 0.0) return default_id;
  float r = static_cast<float>(rng.NextDouble() * acc);
  size_t k = SearchCumulative(cum.data(), cum.size(), r);
  return ids[k];
}

void GraphStore::DenseFeature(int64_t nidx, int32_t fid, int32_t dim,
                              float* out) const {
  std::fill(out, out + dim, 0.f);
  if (nidx < 0 || fid < 0 || fid >= nf_f32_num_) return;
  int64_t g = nidx * nf_f32_num_ + fid;
  int64_t n = std::min<int64_t>(nf_f32_off_[g + 1] - nf_f32_off_[g], dim);
  const float* src = nf_f32_val_.data() + nf_f32_off_[g];
  std::copy(src, src + n, out);
}

void GraphStore::EdgeDenseFeature(int64_t eidx, int32_t fid, int32_t dim,
                                  float* out) const {
  std::fill(out, out + dim, 0.f);
  if (eidx < 0 || fid < 0 || fid >= ef_f32_num_) return;
  int64_t g = eidx * ef_f32_num_ + fid;
  int64_t n = std::min<int64_t>(ef_f32_off_[g + 1] - ef_f32_off_[g], dim);
  const float* src = ef_f32_val_.data() + ef_f32_off_[g];
  std::copy(src, src + n, out);
}

void GraphStore::U64Feature(int64_t nidx, int32_t fid, const uint64_t** vals,
                            int64_t* count) const {
  *vals = nullptr;
  *count = 0;
  if (nidx < 0 || fid < 0 || fid >= nf_u64_num_) return;
  int64_t g = nidx * nf_u64_num_ + fid;
  *vals = nf_u64_val_.data() + nf_u64_off_[g];
  *count = nf_u64_off_[g + 1] - nf_u64_off_[g];
}

void GraphStore::EdgeU64Feature(int64_t eidx, int32_t fid,
                                const uint64_t** vals, int64_t* count) const {
  *vals = nullptr;
  *count = 0;
  if (eidx < 0 || fid < 0 || fid >= ef_u64_num_) return;
  int64_t g = eidx * ef_u64_num_ + fid;
  *vals = ef_u64_val_.data() + ef_u64_off_[g];
  *count = ef_u64_off_[g + 1] - ef_u64_off_[g];
}

void GraphStore::F32Feature(int64_t nidx, int32_t fid, const float** vals,
                            int64_t* count) const {
  *vals = nullptr;
  *count = 0;
  if (nidx < 0 || fid < 0 || fid >= nf_f32_num_) return;
  int64_t g = nidx * nf_f32_num_ + fid;
  *vals = nf_f32_val_.data() + nf_f32_off_[g];
  *count = nf_f32_off_[g + 1] - nf_f32_off_[g];
}

void GraphStore::EdgeF32Feature(int64_t eidx, int32_t fid, const float** vals,
                                int64_t* count) const {
  *vals = nullptr;
  *count = 0;
  if (eidx < 0 || fid < 0 || fid >= ef_f32_num_) return;
  int64_t g = eidx * ef_f32_num_ + fid;
  *vals = ef_f32_val_.data() + ef_f32_off_[g];
  *count = ef_f32_off_[g + 1] - ef_f32_off_[g];
}

void GraphStore::BinFeature(int64_t nidx, int32_t fid, const char** data,
                            int64_t* size) const {
  *data = nullptr;
  *size = 0;
  if (nidx < 0 || fid < 0 || fid >= nf_bin_num_) return;
  int64_t g = nidx * nf_bin_num_ + fid;
  *data = nf_bin_val_.data() + nf_bin_off_[g];
  *size = nf_bin_off_[g + 1] - nf_bin_off_[g];
}

void GraphStore::EdgeBinFeature(int64_t eidx, int32_t fid, const char** data,
                                int64_t* size) const {
  *data = nullptr;
  *size = 0;
  if (eidx < 0 || fid < 0 || fid >= ef_bin_num_) return;
  int64_t g = eidx * ef_bin_num_ + fid;
  *data = ef_bin_val_.data() + ef_bin_off_[g];
  *size = ef_bin_off_[g + 1] - ef_bin_off_[g];
}

}  // namespace eg
