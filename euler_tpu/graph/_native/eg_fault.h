// Deterministic fault injection ("failpoints") for the transport stack.
//
// Production-scale remote serving treats shard death, slow links, and
// mid-frame resets as routine (ROADMAP north star; FastSample and the
// pipelined-sampling line assume the sampling tier keeps feeding the
// accelerator through exactly these hiccups). Until now every failure
// path in eg_remote/eg_service was exercised only by real process kills;
// this layer makes the same failures injectable, seeded, and countable:
//
//   fault=recv_frame:err@0.5,dial:delay@200     (see FAULTS.md)
//
// Named failpoints sit at the transport choke points (dial, send_frame,
// recv_frame, service_reply, registry_reply, heartbeat). Each point owns
// its own splitmix64 stream derived from the configured seed, so the
// decision SEQUENCE at a point is a pure function of (seed, hit index) —
// a given seed replays the exact failure pattern regardless of which
// thread hits the point (thread interleaving only changes which caller
// draws which decision, not the pattern itself).
//
// Compiled in always; the unconfigured cost is one relaxed atomic load
// per hook (FaultHit below) — nothing is registered, no lock is taken.
#ifndef EG_FAULT_H_
#define EG_FAULT_H_

#include <atomic>
#include <cstdint>
#include <mutex>
#include <string>

#include "eg_common.h"

namespace eg {

enum FaultId : int {
  kFaultDial = 0,      // DialTcp: connect fails (-1) or is delayed
  kFaultSendFrame,     // SendFrame: write fails (connection is discarded)
  kFaultRecvFrame,     // RecvFrame: read fails (mid-frame reset analog)
  kFaultServiceReply,  // service worker: reply dropped, conn closed
  kFaultRegistryReply, // RegistryServer::HandleConn: ditto for LIST/REG
  kFaultHeartbeat,     // Service heartbeat: one beat forced to miss
  // Server-side survivability failpoints (eg_admission.cc):
  kFaultAccept,        // admission: the accepted connection is dropped
                       // on the floor (err) or accept is slowed (delay)
  kFaultHandlerStall,  // worker, post-recv pre-dispatch: the handler
                       // stalls (delay — drives deadline replies) or
                       // wedges and abandons the connection (err)
  kFaultBusyForce,     // admission: the capacity check is forced to
                       // report overload — a deterministic BUSY reply
  // Postmortem-path failpoint (eg_blackbox.h): a seeded FATAL SIGNAL at
  // the dial (client) and handler (server) hook points, so the
  // flight-recorder + crash-dump path is deterministically testable.
  // Grammar reuses the action params as the signal choice:
  //   crash:err@p[#limit]          raise(SIGSEGV)
  //   crash:delay@SIG[@p][#limit]  raise(SIG) (e.g. 6 = SIGABRT)
  // The `crashes` counter is bumped BEFORE the raise, so the signal
  // handler's postmortem ledger accounts for the fire that killed it.
  kFaultCrash,
  // Snapshot-epoch failpoints (eg_epoch.h / eg_service.cc LoadDelta):
  kFaultDeltaLoad,     // delta file read/parse forced to fail (err) or
                       // slowed (delay — widens the pre-flip window the
                       // chaos soak races SIGKILL into)
  kFaultEpochFlip,     // the flip publish itself: err refuses the flip
                       // after the merged engine was built (the shard
                       // keeps serving its current epoch; counted in
                       // delta_loads_failed), delay stalls between
                       // build and publish
  kFaultIdCount,
};

// Fixed-order names; both the config grammar and Python read them.
const char* const kFaultNames[kFaultIdCount] = {
    "dial",           "send_frame", "recv_frame",
    "service_reply",  "registry_reply", "heartbeat",
    "accept",         "handler_stall",  "busy_force",
    "crash",          "delta_load",     "epoch_flip",
};

class FaultInjector {
 public:
  static FaultInjector& Global() {
    static FaultInjector f;
    return f;
  }

  // Parse and install a spec: comma-separated failpoints
  //   <point>:err@<prob>[#<limit>]
  //   <point>:delay@<ms>[@<prob>][#<limit>]
  // Replaces the whole previous configuration (per-point streams restart
  // from `seed`). Empty spec == Clear(). False + error() on a malformed
  // spec (unknown point, bad number, duplicate point) — nothing is
  // installed in that case.
  bool Configure(const std::string& spec, uint64_t seed);
  void Clear();
  const std::string& error() const { return error_; }

  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }

  // Decide whether the fault at `id` fires on this hit. Applies the
  // configured delay (sleeping in the caller's thread), counts the fire,
  // and returns true when the caller must fail the operation (err
  // faults; delay-only faults return false after sleeping).
  bool Fire(FaultId id);

  // Injected-fault ledger: how many times each point has fired since it
  // was (re)configured.
  uint64_t injected(FaultId id) const;
  void SnapshotInjected(uint64_t* out) const;

 private:
  struct Point {
    bool configured = false;
    bool err = false;   // true: fail the op; false: delay only
    double prob = 1.0;  // fire probability per hit
    int delay_ms = 0;   // sleep before (possibly) failing
    int64_t limit = -1; // max fires, -1 = unlimited
    int64_t fired = 0;
    Rng rng{0};
  };

  std::atomic<bool> enabled_{false};
  mutable std::mutex mu_;  // guards points_ (config, streams, ledger)
  Point points_[kFaultIdCount];
  std::string error_;
};

// The hook every transport choke point calls: one relaxed load when no
// fault is configured, the full decision path otherwise.
inline bool FaultHit(FaultId id) {
  FaultInjector& f = FaultInjector::Global();
  if (!f.enabled()) return false;
  return f.Fire(id);
}

}  // namespace eg

#endif  // EG_FAULT_H_
