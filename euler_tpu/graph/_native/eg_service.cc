#include "eg_service.h"

#include <dirent.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <fstream>

#include "eg_blackbox.h"
#include "eg_fault.h"
#include "eg_heat.h"
#include "eg_placement.h"
#include "eg_registry.h"
#include "eg_stats.h"
#include "eg_telemetry.h"
#include "eg_wire.h"

namespace eg {

namespace {

// Encode an EGResult (all slots of every kind) and free it.
void WriteResult(WireWriter* w, EGResult* res) {
  w->I32(static_cast<int32_t>(res->u64.size()));
  for (auto& v : res->u64) w->Arr(v);
  w->I32(static_cast<int32_t>(res->f32.size()));
  for (auto& v : res->f32) w->Arr(v);
  w->I32(static_cast<int32_t>(res->i32.size()));
  for (auto& v : res->i32) w->Arr(v);
  w->I32(static_cast<int32_t>(res->bytes.size()));
  for (auto& s : res->bytes) w->Str(s);
  delete res;
}

}  // namespace

int CountPartitions(const std::string& dir) {
  DIR* d = opendir(dir.c_str());
  if (!d) return -1;
  int max_p = -1;
  while (dirent* ent = readdir(d)) {
    std::string name = ent->d_name;
    if (name.size() < 4 || name.compare(name.size() - 4, 4, ".dat") != 0)
      continue;
    int p = 0;
    size_t us = name.rfind('_');
    if (us != std::string::npos) {
      size_t start = us + 1, end = name.size() - 4;
      bool digits = start < end;
      for (size_t i = start; i < end && digits; ++i)
        digits = name[i] >= '0' && name[i] <= '9';
      if (digits) p = std::stoi(name.substr(start, end - start));
    }
    max_p = std::max(max_p, p);
  }
  closedir(d);
  return max_p + 1;
}

bool Service::Start(const std::string& data_dir, int shard_idx, int shard_num,
                    const std::string& host, int port,
                    const std::string& registry_dir,
                    const std::string& options) {
  AdmissionOptions opt;
  if (!ParseAdmissionOptions(options, &opt, &error_)) return false;
  opt.shard_idx = shard_idx;  // server-side telemetry spans carry it
  shard_idx_ = shard_idx;
  shard_num_ = shard_num;
  num_partitions_ = CountPartitions(data_dir);
  if (num_partitions_ <= 0) {
    error_ = "no .dat partitions in " + data_dir;
    return false;
  }
  auto base = std::make_shared<Engine>();
  if (!base->Load(data_dir, shard_idx, shard_num)) {
    error_ = base->error();
    return false;
  }
  base_files_ = base->source_files();
  epochs_.Reset(std::move(base), 0);
  announced_epoch_.store(0, std::memory_order_release);
  // Placement artifact (eg_placement.h): read the blob AND parse it —
  // a corrupt artifact must fail the shard start loudly, not surface
  // later as client-side misrouting against whichever shards parsed it.
  if (!ReadPlacementDir(data_dir, &placement_blob_, &error_)) return false;
  if (!placement_blob_.empty()) {
    PlacementMap check;
    if (!check.Parse(placement_blob_, &error_)) return false;
    if (check.num_partitions() != num_partitions_) {
      error_ = "placement artifact declares " +
               std::to_string(check.num_partitions()) +
               " partitions but " + data_dir + " holds " +
               std::to_string(num_partitions_) + " .dat partitions";
      return false;
    }
  }
  host_ = host.empty() ? "127.0.0.1" : host;
  int listen_fd = ListenTcp(host_, port, &port_);
  if (listen_fd < 0) {
    error_ = "cannot bind port " + std::to_string(port);
    return false;
  }
  if (!admission_.Start(
          listen_fd, opt,
          [this](const char* req, size_t len, const Envelope& env,
                 std::string* reply) { Dispatch(req, len, env, reply); },
          &error_)) {
    ::close(listen_fd);
    return false;
  }
  started_ = true;

  if (registry_dir.compare(0, 6, "tcp://") == 0) {
    // TCP registry (eg_registry.h): REG now, then heartbeat re-REG at a
    // third of the registry's TTL (returned in the REG reply) so the
    // entry stays live — the ephemeral-znode session analog
    // (zk_server_register.cc:32-48). The initial registration must
    // succeed (fail fast on a wrong address); later heartbeats tolerate
    // registry restarts by redialing.
    if (!ParseTcpRegistry(registry_dir, &reg_host_, &reg_port_)) {
      error_ = "bad tcp registry url " + registry_dir +
               " (want tcp://host:port)";
      Stop();
      return false;
    }
    // REG lines carry a trailing epoch token ("REG <shard> <addr>
    // <epoch>") — pre-epoch registries parse shard + addr and ignore
    // the extra token, so the announcement is backward compatible. The
    // line is re-composed EVERY beat from announced_epoch_, which is
    // how a flip propagates to discovery within one TTL third.
    const std::string line_base = "REG " + std::to_string(shard_idx_) +
                                  " " + host_ + ":" +
                                  std::to_string(port_);
    int ttl_ms = 10000;
    int fd = DialTcp(reg_host_, reg_port_, 2000);
    if (fd < 0 || !RegistrySend(fd, line_base + " 0", &ttl_ms)) {
      if (fd >= 0) ::close(fd);
      error_ = "cannot register with tcp registry " + registry_dir;
      Stop();
      return false;
    }
    heartbeat_stop_ = false;
    heartbeat_thread_ = std::thread([this, line_base, fd,
                                     ttl_ms]() mutable {
      try {
        while (!heartbeat_stop_.load(std::memory_order_acquire)) {
          // wake every 50 ms so Stop() stays prompt even with short TTLs
          int beat_ms = ttl_ms / 3 > 150 ? ttl_ms / 3 : 150;
          for (int slept = 0; slept < beat_ms && !heartbeat_stop_;
               slept += 50)
            std::this_thread::sleep_for(std::chrono::milliseconds(50));
          if (heartbeat_stop_) break;
          const std::string line =
              line_base + " " +
              std::to_string(
                  announced_epoch_.load(std::memory_order_acquire));
          // kFaultHeartbeat forces this beat to miss: the held connection
          // is dropped and the redial path below must keep the registry
          // entry alive — exactly what a blipped registry link exercises.
          if (FaultHit(kFaultHeartbeat) || fd < 0 ||
              !RegistrySend(fd, line, &ttl_ms)) {
            Counters::Global().Add(kCtrHeartbeatMiss);
            if (fd >= 0) ::close(fd);
            fd = DialTcp(reg_host_, reg_port_, 2000);
            if (fd >= 0) RegistrySend(fd, line, &ttl_ms);
          }
        }
        if (fd >= 0) {
          RegistrySend(fd, "UNREG " + std::to_string(shard_idx_) + " " +
                               host_ + ":" + std::to_string(port_));
          ::close(fd);
        }
      } catch (...) {
        // std::terminate barrier (eg-lint: thread-catch): a dead heartbeat
        // only lets the registry entry expire; rediscovery handles the rest
        if (fd >= 0) ::close(fd);
      }
    });
  } else if (!registry_dir.empty()) {
    // "<shard>#<host>_<port>" file, written via rename for atomicity — the
    // flat-file stand-in for the reference's ephemeral znode
    // (zk_server_register.cc:32-48).
    registry_file_ = registry_dir + "/" + std::to_string(shard_idx) + "#" +
                     host_ + "_" + std::to_string(port_);
    std::string tmp = registry_file_ + ".tmp";
    std::ofstream f(tmp);
    f << host_ << ":" << port_ << "\n";
    f.close();
    if (!f || std::rename(tmp.c_str(), registry_file_.c_str()) != 0) {
      error_ = "cannot write registry file " + registry_file_;
      Stop();
      return false;
    }
  }
  return true;
}

void Service::Deregister() {
  if (!registry_file_.empty()) {
    ::unlink(registry_file_.c_str());
    registry_file_.clear();
  }
  if (heartbeat_thread_.joinable()) {
    heartbeat_stop_.store(true, std::memory_order_release);
    heartbeat_thread_.join();  // sends the UNREG on its way out
  }
}

void Service::Drain(int grace_ms) {
  if (!started_) return;
  // Leave discovery FIRST so clients route new work elsewhere while the
  // in-flight tail finishes — the SIGTERM half of a rolling restart
  // (DEPLOY.md runbook; registry TTL / re-discovery handles the rest).
  Deregister();
  admission_.Drain(grace_ms);
}

void Service::Stop() {
  if (!started_) return;
  Deregister();
  admission_.Stop();
  started_ = false;
}

namespace {

// Result allocations derived from request integers must be bounded by
// what a reply frame can carry anyway (SendFrame caps at kMaxFrame) —
// otherwise a well-framed request with count=INT32_MAX forces a
// multi-GB zero-initialized allocation (OOM kill or bad_alloc) before
// any data is touched.
bool OversizedResult(int64_t elems, std::string* reply) {
  // -64: headroom for the status byte and array-length prefixes, so a
  // boundary-sized result still fits its reply frame
  if (elems >= 0 && elems <= static_cast<int64_t>((kMaxFrame - 64) / 8))
    return false;
  WireWriter e;
  e.U8(1);
  e.Str("oversized request");
  *reply = std::move(e.buf());
  return true;
}

}  // namespace

void Service::Dispatch(const char* req, size_t len, const Envelope& env,
                       std::string* reply) {
  eg::SpanTimer span(eg::kStatServiceRequest);
  WireReader r(req, len);
  uint8_t op = r.U8();
  // Pin the epoch this request runs against: v4 requests may ask for
  // the epoch their op started on (0 = current); anything the table no
  // longer holds falls back to current. The pin holds the snapshot's
  // drain back until this reply is built.
  EpochPin pin =
      epochs_.Pin(env.versioned && env.version >= 4 ? env.epoch : 0);
  if (!pin) {
    *reply = StatusReply(kStatusError, "shard has no snapshot");
    return;
  }
  const Engine& eng = *pin.engine();
  WireWriter w;
  w.U8(0);  // ok status; overwritten on decode error below
  // v4 ok replies carry the shard's CURRENT epoch right after the
  // status byte — the passive flip announcement. Placeholder now,
  // patched after dispatch so a kLoadDelta reply announces the epoch
  // it just flipped to.
  const bool stamp = env.versioned && env.version >= 4;
  if (stamp) w.U64(0);

  // Server-side heat feed (eg_heat.h): the decoded id array,
  // PRE-execute, tagged by op + the requesting conn ServeConn stamped
  // into the thread-local — so a shard's top-K table reflects what it
  // was ASKED for even when the engine call later fails. Edge ops feed
  // their src ids (the routing key hash sharding cuts on).
  Heat& heat = Heat::Global();
  auto feed = [&](const uint64_t* ids, int64_t n) {
    heat.Record(kHeatServer, op, ids, n, HeatConn());
  };

  switch (op) {
    case kPing:
      break;
    case kStats: {
      // Remote telemetry scrape (eg_telemetry.h): the same JSON the
      // local euler_tpu.metrics_text() surface reads, plus this
      // server's live admission gauges — so an operator can ask any
      // shard how it is doing without shelling into its host.
      TelemetryGauges g;
      g.workers = admission_.workers();
      g.active = admission_.active();
      g.queue_depth = admission_.queue_depth();
      g.conns = admission_.conns();
      g.draining = admission_.draining() ? 1 : 0;
      g.epoch = static_cast<int64_t>(epochs_.current());
      w.Str(Telemetry::Global().Json(shard_idx_, &g));
      break;
    }
    case kHistory: {
      // Resource-gauge history scrape (eg_blackbox.h): the live view of
      // exactly what a postmortem freezes — RSS/fds/threads/cache over
      // the last ~minute — so an operator can watch a shard leak before
      // it dies, not only read about it after.
      w.Str(Blackbox::Global().HistoryJson(shard_idx_));
      break;
    }
    case kHeat: {
      // Data-plane heat scrape (eg_heat.h): this shard's full
      // hot-vertex top-K table + sketch totals + per-op/per-conn ids
      // ledger — the targeted reply scripts/heat_dump.py fits its
      // Zipf tail and cache-ceiling projections from.
      w.Str(Heat::Global().Json(shard_idx_));
      break;
    }
    case kPlacement: {
      // Placement-map fetch (eg_placement.h): the raw artifact blob,
      // verbatim. A shard serving hash-sharded data answers the STOCK
      // unknown-op error a pre-placement server would — deliberately
      // byte-identical, so the client's hash-routing fallback covers
      // old servers and map-less data through one path.
      if (placement_blob_.empty()) {
        WireWriter e;
        e.U8(1);
        e.Str("unknown op " + std::to_string(op));
        *reply = std::move(e.buf());
        return;
      }
      w.Str(placement_blob_);
      break;
    }
    case kLoadDelta: {
      // Snapshot-epoch delta load (eg_epoch.h): merge + flip, reply
      // [u64 new_epoch]. Failure answers an error string and leaves the
      // current epoch serving (already counted in delta_loads_failed).
      std::string path = r.Str();
      if (r.ok()) {
        uint64_t new_epoch = 0;
        std::string err;
        if (!LoadDelta(path, &new_epoch, &err)) {
          WireWriter e;
          e.U8(1);
          e.Str(err);
          *reply = std::move(e.buf());
          return;
        }
        w.U64(new_epoch);
      }
      break;
    }
    case kInfo: {
      const GraphStore& s = eng.store();
      w.I64(static_cast<int64_t>(s.num_nodes()));
      w.I64(static_cast<int64_t>(s.num_edges()));
      w.I32(s.node_type_num());
      w.I32(s.edge_type_num());
      w.I32(s.nf_u64_num());
      w.I32(s.nf_f32_num());
      w.I32(s.nf_bin_num());
      w.I32(s.ef_u64_num());
      w.I32(s.ef_f32_num());
      w.I32(s.ef_bin_num());
      w.I32(shard_idx_);
      w.I32(shard_num_);
      w.I32(num_partitions_);
      w.Arr(s.node_type_weight_sums());
      w.Arr(s.edge_type_weight_sums());
      break;
    }
    case kSampleNode: {
      int32_t count = r.I32(), type = r.I32();
      if (OversizedResult(count, reply)) return;
      std::vector<uint64_t> out(std::max<int32_t>(count, 0));
      if (r.ok() && count >= 0) eng.SampleNode(count, type, out.data());
      w.Arr(out);
      break;
    }
    case kSampleEdge: {
      int32_t count = r.I32(), type = r.I32();
      if (OversizedResult(3LL * count, reply)) return;
      size_t n = static_cast<size_t>(std::max<int32_t>(count, 0));
      std::vector<uint64_t> src(n), dst(n);
      std::vector<int32_t> t(n);
      if (r.ok() && count >= 0)
        eng.SampleEdge(count, type, src.data(), dst.data(), t.data());
      w.Arr(src);
      w.Arr(dst);
      w.Arr(t);
      break;
    }
    case kNodeType: {
      int64_t n;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      if (r.ok()) feed(ids, n);
      std::vector<int32_t> out(static_cast<size_t>(n));
      if (r.ok()) eng.GetNodeType(ids, static_cast<int>(n), out.data());
      w.Arr(out);
      break;
    }
    case kNodeWeight: {
      int64_t n;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      if (r.ok()) feed(ids, n);
      std::vector<float> out(static_cast<size_t>(n));
      if (r.ok()) eng.GetNodeWeight(ids, static_cast<int>(n), out.data());
      w.Arr(out);
      break;
    }
    case kSampleNeighbor: {
      int64_t n, net;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      const int32_t* etypes = r.Arr<int32_t>(&net);
      int32_t count = r.I32();
      uint64_t def = r.U64();
      if (r.ok()) feed(ids, n);
      if (OversizedResult(3LL * n * std::max<int32_t>(count, 0), reply))
        return;
      size_t total = static_cast<size_t>(n) * std::max<int32_t>(count, 0);
      std::vector<uint64_t> oid(total);
      std::vector<float> ow(total);
      std::vector<int32_t> ot(total);
      if (r.ok() && count >= 0)
        eng.SampleNeighbor(ids, static_cast<int>(n), etypes,
                               static_cast<int>(net), count, def, oid.data(),
                               ow.data(), ot.data());
      w.Arr(oid);
      w.Arr(ow);
      w.Arr(ot);
      break;
    }
    case kSampleNeighborUniq: {
      // Dedup'd neighbor sampling (see eg_wire.h): ids are unique,
      // reps[i] repeats each; the engine is called once per unique id
      // with reps[i] * count draws, so the node/group lookup happens
      // once per unique id while every draw stays iid.
      int64_t n, nr, net;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      const int32_t* reps = r.Arr<int32_t>(&nr);
      const int32_t* etypes = r.Arr<int32_t>(&net);
      int32_t count = r.I32();
      uint64_t def = r.U64();
      if (r.ok()) feed(ids, n);
      int64_t total = 0;
      bool shape_ok = r.ok() && nr == n && count >= 0;
      for (int64_t i = 0; shape_ok && i < n; ++i) {
        if (reps[i] < 1) {
          shape_ok = false;
          break;
        }
        total += static_cast<int64_t>(reps[i]) * count;
        if (total > static_cast<int64_t>(kMaxFrame)) break;  // rejected below
      }
      if (!shape_ok) {
        WireWriter e;
        e.U8(1);
        e.Str("malformed request for op " + std::to_string(op));
        *reply = std::move(e.buf());
        return;
      }
      if (OversizedResult(3 * total, reply)) return;
      std::vector<uint64_t> oid(static_cast<size_t>(total));
      std::vector<float> ow(static_cast<size_t>(total));
      std::vector<int32_t> ot(static_cast<size_t>(total));
      int64_t off = 0;
      for (int64_t i = 0; i < n; ++i) {
        int64_t m = static_cast<int64_t>(reps[i]) * count;
        if (m > 0)
          eng.SampleNeighbor(ids + i, 1, etypes, static_cast<int>(net),
                                 static_cast<int>(m), def, oid.data() + off,
                                 ow.data() + off, ot.data() + off);
        off += m;
      }
      w.Arr(oid);
      w.Arr(ow);
      w.Arr(ot);
      break;
    }
    case kFullNeighbor: {
      int64_t n, net;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      const int32_t* etypes = r.Arr<int32_t>(&net);
      uint8_t sorted = r.U8();
      if (r.ok()) feed(ids, n);
      if (r.ok()) {
        WriteResult(&w, eng.GetFullNeighbor(ids, static_cast<int>(n),
                                                etypes, static_cast<int>(net),
                                                sorted != 0));
      }
      break;
    }
    case kTopKNeighbor: {
      int64_t n, net;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      const int32_t* etypes = r.Arr<int32_t>(&net);
      int32_t k = r.I32();
      uint64_t def = r.U64();
      if (r.ok()) feed(ids, n);
      if (OversizedResult(3LL * n * std::max<int32_t>(k, 0), reply))
        return;
      size_t total = static_cast<size_t>(n) * std::max<int32_t>(k, 0);
      std::vector<uint64_t> oid(total);
      std::vector<float> ow(total);
      std::vector<int32_t> ot(total);
      if (r.ok() && k >= 0)
        eng.GetTopKNeighbor(ids, static_cast<int>(n), etypes,
                                static_cast<int>(net), k, def, oid.data(),
                                ow.data(), ot.data());
      w.Arr(oid);
      w.Arr(ow);
      w.Arr(ot);
      break;
    }
    case kDenseFeature: {
      int64_t n, nf, nd;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      const int32_t* fids = r.Arr<int32_t>(&nf);
      const int32_t* dims = r.Arr<int32_t>(&nd);
      if (r.ok()) feed(ids, n);
      int64_t row = 0;
      for (int64_t k = 0; k < nd; ++k) row += dims[k];
      // bound row before multiplying: corrupt dims could overflow n*row
      // (OversizedResult also rejects a negative row)
      if (OversizedResult(row, reply)) return;
      if (OversizedResult(n * row, reply)) return;
      std::vector<float> out(static_cast<size_t>(n * row));
      if (r.ok() && nf == nd)
        eng.GetDenseFeature(ids, static_cast<int>(n), fids, dims,
                                static_cast<int>(nf), out.data());
      w.Arr(out);
      break;
    }
    case kEdgeDenseFeature: {
      int64_t n, n2, n3, nf, nd;
      const uint64_t* src = r.Arr<uint64_t>(&n);
      const uint64_t* dst = r.Arr<uint64_t>(&n2);
      const int32_t* types = r.Arr<int32_t>(&n3);
      const int32_t* fids = r.Arr<int32_t>(&nf);
      const int32_t* dims = r.Arr<int32_t>(&nd);
      if (r.ok()) feed(src, n);
      int64_t row = 0;
      for (int64_t k = 0; k < nd; ++k) row += dims[k];
      if (OversizedResult(row, reply)) return;
      if (OversizedResult(n * row, reply)) return;
      std::vector<float> out(static_cast<size_t>(n * row));
      if (r.ok() && n == n2 && n == n3 && nf == nd)
        eng.GetEdgeDenseFeature(src, dst, types, static_cast<int>(n),
                                    fids, dims, static_cast<int>(nf),
                                    out.data());
      w.Arr(out);
      break;
    }
    case kSparseFeature: {
      int64_t n, nf;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      const int32_t* fids = r.Arr<int32_t>(&nf);
      if (r.ok()) feed(ids, n);
      if (r.ok())
        WriteResult(&w, eng.GetSparseFeature(ids, static_cast<int>(n),
                                                 fids, static_cast<int>(nf)));
      break;
    }
    case kEdgeSparseFeature: {
      int64_t n, n2, n3, nf;
      const uint64_t* src = r.Arr<uint64_t>(&n);
      const uint64_t* dst = r.Arr<uint64_t>(&n2);
      const int32_t* types = r.Arr<int32_t>(&n3);
      const int32_t* fids = r.Arr<int32_t>(&nf);
      if (r.ok()) feed(src, n);
      if (r.ok() && n == n2 && n == n3)
        WriteResult(&w, eng.GetEdgeSparseFeature(
                            src, dst, types, static_cast<int>(n), fids,
                            static_cast<int>(nf)));
      break;
    }
    case kBinaryFeature: {
      int64_t n, nf;
      const uint64_t* ids = r.Arr<uint64_t>(&n);
      const int32_t* fids = r.Arr<int32_t>(&nf);
      if (r.ok()) feed(ids, n);
      if (r.ok())
        WriteResult(&w, eng.GetBinaryFeature(ids, static_cast<int>(n),
                                                 fids, static_cast<int>(nf)));
      break;
    }
    case kEdgeBinaryFeature: {
      int64_t n, n2, n3, nf;
      const uint64_t* src = r.Arr<uint64_t>(&n);
      const uint64_t* dst = r.Arr<uint64_t>(&n2);
      const int32_t* types = r.Arr<int32_t>(&n3);
      const int32_t* fids = r.Arr<int32_t>(&nf);
      if (r.ok()) feed(src, n);
      if (r.ok() && n == n2 && n == n3)
        WriteResult(&w, eng.GetEdgeBinaryFeature(
                            src, dst, types, static_cast<int>(n), fids,
                            static_cast<int>(nf)));
      break;
    }
    default: {
      WireWriter e;
      e.U8(1);
      e.Str("unknown op " + std::to_string(op));
      *reply = std::move(e.buf());
      return;
    }
  }

  if (!r.ok()) {
    WireWriter e;
    e.U8(1);
    e.Str("malformed request for op " + std::to_string(op));
    *reply = std::move(e.buf());
    return;
  }
  if (stamp) {
    uint64_t cur = epochs_.current();
    std::memcpy(&w.buf()[1], &cur, 8);
  }
  *reply = std::move(w.buf());
}

bool Service::LoadDelta(const std::string& path, uint64_t* new_epoch,
                        std::string* error) {
  // One flip at a time per shard: concurrent kLoadDelta requests queue
  // here. Readers never block — they keep pinning whatever epoch is
  // current while the merge builds off to the side.
  std::lock_guard<std::mutex> l(delta_mu_);
  Counters& ctr = Counters::Global();
  auto fail = [&](const std::string& msg) {
    *error = msg;
    ctr.Add(kCtrDeltaLoadFail);
    return false;
  };
  // kFaultDeltaLoad: the read/parse leg forced to fail or slowed — the
  // window the chaos soak races SIGKILL into.
  if (FaultHit(kFaultDeltaLoad))
    return fail("delta_load failpoint fired for " + path);
  std::string data;
  {
    std::ifstream f(path, std::ios::binary | std::ios::ate);
    if (!f) return fail("cannot read delta " + path);
    std::streamsize size = f.tellg();
    f.seekg(0);
    // eg-lint: allow(wire-count-alloc) sized by tellg of an already-open
    // local file; bad_alloc surfaces as a handler error reply
    data.resize(static_cast<size_t>(size));
    if (!f.read(data.data(), size))
      return fail("cannot read delta " + path);
  }
  DeltaFile d;
  std::string err;
  if (!d.Parse(data.data(), data.size(), &err) || !d.Validate(&err))
    return fail(path + ": " + err);
  ShardOwnership own{shard_idx_, shard_num_, num_partitions_};
  if (!FilterDeltaToShard(&d, own, &err))
    return fail(path + ": " + err);
  if (!deltas_.empty() && d.seq <= deltas_.back().seq)
    return fail(path + ": delta seq " + std::to_string(d.seq) +
                " not above applied seq " +
                std::to_string(deltas_.back().seq));
  deltas_.push_back(std::move(d));
  std::shared_ptr<Engine> merged;
  if (!BuildMergedEngine(base_files_, deltas_, &merged, &err)) {
    deltas_.pop_back();
    return fail(path + ": " + err);
  }
  // kFaultEpochFlip: refuse (err) or stall (delay) the publish itself,
  // AFTER the merged engine was built — the shard keeps serving its
  // current epoch on refusal.
  if (FaultHit(kFaultEpochFlip)) {
    deltas_.pop_back();
    return fail(path + ": epoch_flip failpoint refused the flip");
  }
  merged->set_epoch(epochs_.current() + 1);
  uint64_t e = epochs_.Flip(std::move(merged));
  announced_epoch_.store(e, std::memory_order_release);
  *new_epoch = e;
  return true;
}

}  // namespace eg
