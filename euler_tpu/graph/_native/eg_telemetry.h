// Native observability: latency histograms, slow-span journals, and the
// cluster scrape substrate.
//
// PRs 2-4 gave the remote path counters (eg_stats.h Counters) and
// count/total/max span timers (Stats) — enough to know THAT the
// transport fought, never WHERE a request's time went. Distributed-GNN
// throughput tuning lives or dies on exactly that decomposition
// (FastSample, arXiv:2311.17847; pipelined sampling, arXiv:2110.08450:
// client queue vs wire vs handler), so this layer records:
//
//   * lock-cheap log2-bucketed latency HISTOGRAMS (fixed 1µs..60s+
//     buckets, one relaxed fetch_add per bucket hit) per RPC op on the
//     client (whole ConnPool::Call) and the server (admission handler
//     time, queue-wait time), plus dial and retry-backoff histograms;
//   * a fixed-size SLOW-SPAN journal of the slowest-N requests each
//     side has seen (op, trace id, shard, queue/handler/wire µs,
//     outcome), correlated across processes by a splitmix64 trace id
//     stamped into the wire-v3 request envelope (eg_wire.h);
//   * one JSON dump (Json below) serving both the local
//     euler_tpu.metrics_text() surface and the remote kStats scrape —
//     the same builder on both paths is what makes the scrape-vs-local
//     parity test meaningful.
//
// Cost contract: disabled (telemetry=0) every hook is one relaxed load;
// enabled, a histogram record is two relaxed RMWs and a span record is
// one relaxed load unless the span beats the journal's current floor
// (then a short mutex). Nothing here blocks the hot path on the
// journal lock for ordinary-latency requests.
#ifndef EG_TELEMETRY_H_
#define EG_TELEMETRY_H_

#include "eg_common.h"

#include <atomic>
#include <chrono>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

namespace eg {

// log2 microsecond buckets: bucket 0 = [0, 1µs); bucket b (1..26) =
// [2^(b-1), 2^b) µs; bucket 27 = [2^26 µs, inf) — 1µs to ~67s in 28
// fixed buckets (60 s lands in bucket 26). Shared with the Python
// renderer (euler_tpu/telemetry.py bucket_of), pinned by tests.
constexpr int kHistBuckets = 28;

inline int HistBucketOf(uint64_t us) {
  if (us == 0) return 0;
  int b = 64 - __builtin_clzll(us);  // floor(log2(us)) + 1
  return b < kHistBuckets - 1 ? b : kHistBuckets - 1;
}

// Histogram families. Per-op kinds index their cells by wire op code
// (eg_wire.h WireOp, 1..17); scalar kinds use slot 0.
enum HistKind : int {
  kHistClientCall = 0,  // whole ConnPool::Call per op (retries included)
  kHistServerHandler,   // admission worker: decode+execute+encode per op
  kHistServerQueue,     // poller-ready -> handler pickup wait
  kHistDial,            // DialTcp (success or failure)
  kHistBackoff,         // retry backoff sleeps
  kHistKindCount,
};

const char* const kHistKindNames[kHistKindCount] = {
    "client_call", "server_handler", "server_queue", "dial", "backoff",
};

const bool kHistKindPerOp[kHistKindCount] = {true, true, false, false,
                                             false};

// Per-op cell slots: wire ops 1..21 plus slot 0 for out-of-range ops.
constexpr int kHistOpSlots = 22;

// Fixed-order wire-op names (index == WireOp value; slot 0 = unknown).
const char* const kWireOpNames[kHistOpSlots] = {
    "other",          "ping",
    "info",           "sample_node",
    "sample_edge",    "node_type",
    "sample_neighbor", "full_neighbor",
    "topk_neighbor",  "dense_feature",
    "edge_dense_feature", "sparse_feature",
    "edge_sparse_feature", "binary_feature",
    "edge_binary_feature", "node_weight",
    "sample_neighbor_uniq", "stats",
    "history",        "heat",
    "placement",      "load_delta",
};

enum SpanSide : uint8_t { kSpanClient = 0, kSpanServer = 1 };

enum SpanOutcome : uint8_t {
  kOutcomeOk = 0,
  kOutcomeError = 1,
  kOutcomeBusy = 2,
  kOutcomeDeadline = 3,
  kOutcomeFailed = 4,   // call exhausted retries / pool empty
  kOutcomeDropped = 5,  // reply dropped (failpoint / peer gone)
};

const char* const kSpanOutcomeNames[6] = {
    "ok", "error", "busy", "deadline", "failed", "dropped",
};

struct TelemetrySpan {
  uint8_t side = kSpanClient;
  uint8_t op = 0;
  uint8_t outcome = kOutcomeOk;
  int32_t shard = -1;     // client: target shard; server: own shard idx
  uint64_t trace = 0;     // 0 = none propagated (v1/v2 peer)
  uint64_t queue_us = 0;
  uint64_t handler_us = 0;
  uint64_t wire_us = 0;
  uint64_t total_us = 0;
  // CLOCK_MONOTONIC µs when the span ENDED (stamped by RecordSpan when
  // left 0). The machine-wide monotonic epoch is what lets the trace
  // exporter (euler_tpu/trace.py) place client and shard spans from
  // different processes on one host onto a single Perfetto timeline.
  int64_t end_us = 0;
};

// Admission-layer gauges carried in the kStats scrape reply (the
// PR-4 survivability state a remote operator could not see before).
struct TelemetryGauges {
  int workers = 0;      // fixed handler pool size
  int active = 0;       // workers currently serving
  int queue_depth = 0;  // ready conns waiting for a worker
  int conns = 0;        // admitted open connections
  int draining = 0;     // 1 while Drain() is in progress / done
  int64_t epoch = 0;    // current serving snapshot epoch (eg_epoch.h)
};

inline int64_t TelemetryNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Process-global trace-id source: splitmix64 over an atomic counter, so
// ids are unique per process and well-mixed without any locking. (Not
// eg::ThreadRng — trace ids must not perturb the seeded sampler
// streams the determinism tests replay.)
uint64_t NextTraceId();

class Telemetry {
 public:
  static Telemetry& Global();

  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  void SetEnabled(bool on) {
    enabled_.store(on, std::memory_order_relaxed);
  }

  // Slow-span journal capacity (slow_spans= config key; default 32).
  void SetSlowCapacity(int n);
  int slow_capacity() const;

  // One histogram sample. Cost: two relaxed fetch_adds (bucket + sum);
  // a single relaxed load when disabled.
  void Record(HistKind kind, int op, uint64_t us) {
    if (!enabled()) return;
    if (op < 0 || op >= kHistOpSlots || !kHistKindPerOp[kind]) op = 0;
    Cell& c = cells_[kind][op];
    c.buckets[HistBucketOf(us)].fetch_add(1, std::memory_order_relaxed);
    c.total_us.fetch_add(us, std::memory_order_relaxed);
  }

  // Offer a span to the slowest-N journal. Fast reject (one relaxed
  // load) when the journal is full and the span is under its floor.
  void RecordSpan(const TelemetrySpan& s);

  // Journal snapshot, slowest first.
  std::vector<TelemetrySpan> SlowSpans() const;

  // Full JSON dump: counters (eg_stats.h), span-timer stats, every
  // histogram, the slow-span journal, and (when `gauges` is non-null,
  // i.e. in a serving process) the admission gauges. `shard` is the
  // reporting process's shard index (-1 = not a shard server). One
  // builder for the local surface AND the kStats reply.
  std::string Json(int shard, const TelemetryGauges* gauges) const;

  // Zero histograms and the journal (not the enabled flag/capacity).
  void Reset();

 private:
  struct Cell {
    std::atomic<uint64_t> buckets[kHistBuckets];
    std::atomic<uint64_t> total_us;
  };

  std::atomic<bool> enabled_{true};
  Cell cells_[kHistKindCount][kHistOpSlots] = {};
  mutable std::mutex span_mu_;  // guards spans_ + span_cap_
  std::vector<TelemetrySpan> spans_ EG_GUARDED_BY(span_mu_);
  int span_cap_ EG_GUARDED_BY(span_mu_) = 32;
  std::atomic<bool> span_full_{false};
  std::atomic<uint64_t> span_floor_{0};  // min total_us once full
};

}  // namespace eg

#endif  // EG_TELEMETRY_H_
