// Device-plane gauges: the XLA side of the observability stack.
//
// Everything the other four planes measure lives on the host or the
// wire; the device half of the paper's TPU-native claim — how much HBM
// the program holds, how many live buffers, and how the serve SLO is
// actually tracking — was invisible. The sampling itself has to happen
// in Python (only jax can read device.memory_stats() or walk
// live_arrays()), so this module is deliberately thin: a handful of
// process-global relaxed atomics the Python side refreshes through the
// C ABI, and which the native emitters then fold into every existing
// surface for free — eg_blackbox's resource sample/ring (postmortems
// see the device-memory trajectory of a dying process), Telemetry::Json
// (metrics_text / STATS scrape), and the fatal-signal dump (reads
// memory only, so atomics are exactly what the handler may touch).
//
// The serve-SLO gauges are the live twin of SLOTracker.report():
// euler_tpu/serving/slo.py pushes its windowed p50/p99 and lifetime
// violation count here every few records, so a scrape sees serving
// latency without draining the server. Compile/recompile COUNTS live in
// eg_stats.h (kCtrDeviceCompile...) and compile LATENCY in the
// "phase:compile" histogram (eg_phase.h) — this header only holds the
// gauges that have no counter/histogram shape.
#ifndef EG_DEVPROF_H_
#define EG_DEVPROF_H_

#include <atomic>
#include <cstdint>
#include <string>

namespace eg {

class Devprof {
 public:
  static Devprof& Global();

  // Refresh the device-memory gauges (Python sampler thread / one-shot
  // probes). Tracks the high-water mark as a monotone CAS so a scrape
  // between samples still sees the peak.
  void SetMem(int64_t bytes, int64_t buffers);

  // Refresh the live serve-SLO gauges (SLOTracker pushes µs values).
  void SetServeSlo(uint64_t p50_us, uint64_t p99_us, uint64_t violations,
                   uint64_t count);

  int64_t mem_bytes() const {
    return mem_bytes_.load(std::memory_order_relaxed);
  }
  int64_t mem_peak_bytes() const {
    return mem_peak_bytes_.load(std::memory_order_relaxed);
  }
  int64_t buffers() const {
    return buffers_.load(std::memory_order_relaxed);
  }

  // Append `,"serve_slo":{"p50_us":..,"p99_us":..,"violations":..,
  // "count":..}` to an in-progress JSON object (Telemetry::Json calls
  // this right after the resource section). Always emitted — zeros
  // included — so the metric families render unconditionally and the
  // doc-drift gate sees them in every scrape.
  void ServeSloJsonInto(std::string* out) const;

  void Reset();

 private:
  std::atomic<int64_t> mem_bytes_{0};
  std::atomic<int64_t> mem_peak_bytes_{0};
  std::atomic<int64_t> buffers_{0};
  std::atomic<uint64_t> slo_p50_us_{0};
  std::atomic<uint64_t> slo_p99_us_{0};
  std::atomic<uint64_t> slo_violations_{0};
  std::atomic<uint64_t> slo_count_{0};
};

}  // namespace eg

#endif  // EG_DEVPROF_H_
