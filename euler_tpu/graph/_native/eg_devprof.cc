#include "eg_devprof.h"

namespace eg {

namespace {

void AppendU64(std::string* out, uint64_t v) {
  char buf[24];
  int n = 0;
  do {
    buf[n++] = static_cast<char>('0' + v % 10);
    v /= 10;
  } while (v);
  while (n) out->push_back(buf[--n]);
}

void AppendKey(std::string* out, const char* k) {
  out->push_back('"');
  out->append(k);
  out->append("\":");
}

}  // namespace

Devprof& Devprof::Global() {
  static Devprof d;
  return d;
}

void Devprof::SetMem(int64_t bytes, int64_t buffers) {
  mem_bytes_.store(bytes, std::memory_order_relaxed);
  buffers_.store(buffers, std::memory_order_relaxed);
  int64_t prev = mem_peak_bytes_.load(std::memory_order_relaxed);
  while (prev < bytes &&
         !mem_peak_bytes_.compare_exchange_weak(prev, bytes,
                                                std::memory_order_relaxed)) {
  }
}

void Devprof::SetServeSlo(uint64_t p50_us, uint64_t p99_us,
                          uint64_t violations, uint64_t count) {
  slo_p50_us_.store(p50_us, std::memory_order_relaxed);
  slo_p99_us_.store(p99_us, std::memory_order_relaxed);
  slo_violations_.store(violations, std::memory_order_relaxed);
  slo_count_.store(count, std::memory_order_relaxed);
}

void Devprof::ServeSloJsonInto(std::string* out) const {
  out->push_back(',');
  AppendKey(out, "serve_slo");
  out->push_back('{');
  AppendKey(out, "p50_us");
  AppendU64(out, slo_p50_us_.load(std::memory_order_relaxed));
  out->push_back(',');
  AppendKey(out, "p99_us");
  AppendU64(out, slo_p99_us_.load(std::memory_order_relaxed));
  out->push_back(',');
  AppendKey(out, "violations");
  AppendU64(out, slo_violations_.load(std::memory_order_relaxed));
  out->push_back(',');
  AppendKey(out, "count");
  AppendU64(out, slo_count_.load(std::memory_order_relaxed));
  out->push_back('}');
}

void Devprof::Reset() {
  mem_bytes_.store(0, std::memory_order_relaxed);
  mem_peak_bytes_.store(0, std::memory_order_relaxed);
  buffers_.store(0, std::memory_order_relaxed);
  slo_p50_us_.store(0, std::memory_order_relaxed);
  slo_p99_us_.store(0, std::memory_order_relaxed);
  slo_violations_.store(0, std::memory_order_relaxed);
  slo_count_.store(0, std::memory_order_relaxed);
}

}  // namespace eg
