// Data-plane access profiler: WHICH vertices the cluster touches.
//
// PRs 5-7 made the control plane observable — how long calls take
// (eg_telemetry), where a step's time goes (eg_phase), what a dying
// process was doing (eg_blackbox). None of it can say which vertex ids
// are hot, how a hop's frontier fans out across shards, or why the
// feature cache hits — the exact measurements ROADMAP item 5
// (locality-aware sharding + hot-vertex caching) needs before it can be
// built or judged. GNNSampler (arXiv:2108.11571) and FastSample
// (arXiv:2311.17847) both show power-law access skew as the dominant
// distributed-GNN lever; this layer quantifies that skew on live
// workloads with fixed memory:
//
//   * a SPACE-SAVING top-K hot-key tracker per side (client/server):
//     K fixed slots + a fixed open-addressed index, no allocation ever.
//     Space-saving guarantees count >= true >= count - err per tracked
//     id, and exact counts (err == 0) whenever K covers the stream's
//     distinct ids — the property tests/test_heat.py pins;
//   * a COUNT-MIN sketch per side (depth x width atomic counters,
//     relaxed fetch_adds only): point estimates over the whole id
//     space, est >= true and est <= true + (e/width) * N with
//     probability 1 - e^-depth — the frequency oracle the
//     cache-efficacy classes and the top-K admission answer read;
//   * per-hop FAN-OUT ATTRIBUTION on the client: for each
//     SampleNeighbor/GetDenseFeature call, ids_requested /
//     ids_after_dedup / cache_hits / ids_on_wire and a shards-touched
//     value histogram per op (emitted into the shared "hist" map as
//     heat_spread:<op>), plus request/reply bytes per shard;
//   * CACHE-EFFICACY classes: eg_cache hits/misses/evictions bucketed
//     by the key's current sketch-estimated frequency class — the
//     direct "would a frequency-aware cache help" answer.
//
// Feed points: client-side in the eg_remote per-shard encode lambdas
// (post-coalesce — one feed per unique id per call, exactly what goes
// on the wire plus cache hits), server-side in Service::Dispatch
// (pre-execute, tagged by op + the requesting conn ServeConn stamps
// into a thread-local).
//
// Cost contract: behind the existing telemetry kill-switch plus its own
// `heat=` flag — disabled, every hook is two relaxed loads. Enabled,
// one splitmix64 hash per id drives the sketch rows AND the top-K index
// probe; the tracker mutex is taken ONCE per batch (not per id) and —
// because that mutex serializes every sketch writer — the cells
// increment with plain relaxed load+store pairs, not locked RMWs. No
// allocation on the hot path (fixed arrays, tombstoned open
// addressing). Priced by the remote_bench heat on/off A/B under the
// <2% contract (PERF.md "Data-plane heat").
#ifndef EG_HEAT_H_
#define EG_HEAT_H_

#include "eg_common.h"

#include <atomic>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

#include "eg_telemetry.h"

namespace eg {

enum HeatSide : int { kHeatClient = 0, kHeatServer = 1, kHeatSideCount };

const char* const kHeatSideNames[kHeatSideCount] = {"client", "server"};

// Count-min sketch geometry: a cache-line-BLOCKED sketch — 8192 cells
// per side arranged as 1024 blocks of 8 (one 64-byte line each). An id
// hashes to ONE block and two cells inside it, so a feed touches a
// single cache line per id: the sketch walk's cold-line misses, not
// its arithmetic, were the measured majority of the heat cost on the
// remote hot path (the <2% remote_bench contract is what forces the
// blocked layout). Estimates keep the count-min shape — est >= true
// always, overestimates ~eps * N with eps = e/width per query w.h.p.;
// in-block cell correlation trades a small constant in that bound for
// half the memory traffic, and the exactness tests pin the realized
// bound empirically.
constexpr int kHeatCmsDepth = 2;        // cells read per estimate
constexpr int kHeatCmsWidth = 8192;     // total cells (power of two)
constexpr int kHeatCmsBlockCells = 8;   // cells per 64-byte block
constexpr int kHeatCmsBlocks = kHeatCmsWidth / kHeatCmsBlockCells;

// Top-K tracker pool bounds. `heat_topk=` (default kHeatDefaultTopK)
// selects the live capacity within the fixed pool.
constexpr int kHeatMaxTopK = 1024;
constexpr int kHeatDefaultTopK = 128;
// Open-addressed id -> slot index; power of two, load factor <= 25%.
constexpr int kHeatIndexSlots = 4096;

// Frequency classes for cache-efficacy accounting: class c covers
// sketch estimates in [2^(c-1), 2^c) (class 0 = estimate 0, never seen;
// the last class is open-ended).
constexpr int kHeatClasses = 8;

inline int HeatClassOf(uint64_t est) {
  if (est == 0) return 0;
  int b = 64 - __builtin_clzll(est);  // bit_length
  return b < kHeatClasses ? b : kHeatClasses - 1;
}

enum HeatCacheEvent : int {
  kHeatCacheHit = 0,
  kHeatCacheMiss,
  kHeatCacheEvict,
  kHeatCacheEventCount,
};

const char* const kHeatCacheEventNames[kHeatCacheEventCount] = {
    "hit", "miss", "evict",
};

// Per-shard wire-byte ledger and per-conn server attribution bounds
// (fixed pools; overflow lands in the last slot, counted as such).
constexpr int kHeatMaxShards = 64;
constexpr int kHeatMaxConns = 64;

// Requesting-conn tag for server-side feeds: AdmissionServer::ServeConn
// stamps the conn fd into a thread-local before dispatching, so
// Service::Dispatch can tag its feeds without widening the handler
// signature. -1 = no conn (client side / local engine).
void HeatSetConn(int conn);
int HeatConn();

class Heat {
 public:
  static Heat& Global();

  // Effective switch: own flag AND the process-global telemetry
  // kill-switch (telemetry=0 silences this subsystem too).
  bool enabled() const {
    return flag_.load(std::memory_order_relaxed) &&
           Telemetry::Global().enabled();
  }
  bool flag() const { return flag_.load(std::memory_order_relaxed); }
  void SetEnabled(bool on) {
    flag_.store(on, std::memory_order_relaxed);
  }

  // Live top-K capacity (`heat_topk=`); clamped to [1, kHeatMaxTopK].
  // Resets both sides' tables: space-saving guarantees are only
  // meaningful for a capacity held over the whole stream.
  void SetTopK(int k);
  int topk_capacity() const;

  // Feed one batch of ids (one side, one op, optional server conn).
  // Sketch updates are relaxed atomics per id; the top-K mutex is taken
  // once for the whole batch.
  void Record(int side, int op, const uint64_t* keys, int64_t n,
              int conn = -1);
  // Gather form: feed base[rows[i]] for i in [0, n) — the dense-feature
  // path's unique ids live scattered behind a row-index plan, and
  // staging them into a contiguous scratch vector would cost an
  // allocation per call on the hot path. When `out_classes` is
  // non-null it receives each id's frequency class INCLUDING this
  // access, computed from the fetch_add return values the feed already
  // paid for — the dense path's cache-efficacy accounting reads these
  // instead of a second sketch walk per probed id.
  void RecordRows(int side, int op, const uint64_t* base,
                  const int32_t* rows, int64_t n, int conn = -1,
                  uint8_t* out_classes = nullptr);

  // Point estimate from the side's sketch (>= true feed count).
  uint64_t Estimate(int side, uint64_t id) const;

  // Client fan-out attribution for one whole SampleNeighbor /
  // GetDenseFeature call. ids_on_wire is MEASURED (ids actually
  // encoded), so `ids_on_wire == ids_requested - ids_deduped -
  // cache_hits` is a cross-check the tests assert, not an identity
  // baked in here.
  void RecordFanout(int op, uint64_t ids_requested, uint64_t ids_deduped,
                    uint64_t cache_hits, uint64_t ids_on_wire,
                    int shards_touched);

  // Request/reply bytes one shard exchange moved (client side).
  void AddShardBytes(int shard, uint64_t req_bytes, uint64_t reply_bytes);

  // One cache event for vertex `id`, bucketed by the CLIENT sketch's
  // current estimate class — the eviction hook in eg_cache.cc (rare:
  // one per evicted row; hits/misses use the batched form below).
  void RecordCacheEvent(int event, uint64_t id);
  // Batched hit/miss class accounting: per-class counts a dense call
  // accumulated locally from RecordRows' out_classes (one call per
  // GetDenseFeature instead of two sketch reads per probed id).
  void AddCacheClasses(const uint32_t* hits, const uint32_t* misses);

  struct TopEntry {
    uint64_t id = 0;
    uint64_t count = 0;  // upper bound on the true feed count
    uint64_t err = 0;    // overestimate bound: true >= count - err
  };
  // Snapshot of one side's tracker, sorted by count descending.
  std::vector<TopEntry> TopK(int side) const;

  // Total ids fed per side (sketch stream length N in the eps bound).
  uint64_t Total(int side) const {
    return total_[side].load(std::memory_order_relaxed);
  }

  // Full dump: {"shard","enabled","topk_capacity","sketch","topk",
  // "ids","fanout","shard_bytes","conns","cache_class"} — the kHeat
  // wire reply and the eg_heat_json local surface.
  std::string Json(int shard) const;
  // Append `,"heat":{...}` (same body) to an in-progress JSON object —
  // Telemetry::Json calls this, so metrics_text(), snapshot(), the
  // STATS scrape and metrics_dump inherit the heat state for free.
  void JsonInto(std::string* out) const;
  // Append the per-op shards-touched value histograms to the shared
  // "hist" map (keys heat_spread:<op>, same cell shape as the phase
  // histograms so one Python renderer serves all of them).
  void SpreadJsonInto(std::string* out, bool* first) const;

  // Zero everything except the enabled flag and top-K capacity.
  void Reset();

 private:
  Heat();

  struct TopTable {
    mutable std::mutex mu;
    int size EG_GUARDED_BY(mu) = 0;
    int tombstones EG_GUARDED_BY(mu) = 0;
    // cached minimum level: counts only grow, so any slot whose count
    // equals min_count IS a true minimum — replacements resume a
    // rotating scan at that level instead of an O(cap) argmin per
    // untracked arrival (amortized O(1); a full rescan only when the
    // level is exhausted, which itself raised cap slots one level)
    uint64_t min_count EG_GUARDED_BY(mu) = 0;
    int scan_pos EG_GUARDED_BY(mu) = 0;
    uint64_t ids[kHeatMaxTopK] EG_GUARDED_BY(mu);
    uint64_t counts[kHeatMaxTopK] EG_GUARDED_BY(mu);
    uint64_t errs[kHeatMaxTopK] EG_GUARDED_BY(mu);
    // -1 empty, -2 tombstone, >= 0 slot index
    int32_t index[kHeatIndexSlots] EG_GUARDED_BY(mu);
  };

  struct SpreadCell {
    std::atomic<uint64_t> buckets[kHistBuckets];
    std::atomic<uint64_t> total;
  };

  // Slot helpers mutate TopTable freely; callers take t.mu first.
  static int FindSlot(const TopTable& t, uint64_t id, uint64_t h)
      EG_REQUIRES(mu);
  static void InsertSlot(TopTable* t, uint64_t h, int slot) EG_REQUIRES(mu);
  static void EraseSlot(TopTable* t, uint64_t id) EG_REQUIRES(mu);
  static void RebuildIndex(TopTable* t) EG_REQUIRES(mu);
  void UpdateTop(TopTable* t, uint64_t id, uint64_t h, int cap)
      EG_REQUIRES(mu);

  std::atomic<bool> flag_{true};
  std::atomic<int> cap_{kHeatDefaultTopK};

  // flat blocked layout; 64-byte aligned so block == cache line
  alignas(64) std::atomic<uint64_t> cms_[kHeatSideCount][kHeatCmsWidth] =
      {};
  std::atomic<uint64_t> total_[kHeatSideCount] = {};
  TopTable top_[kHeatSideCount];

  // per (side, op) ids fed
  std::atomic<uint64_t> ids_by_op_[kHeatSideCount][kHistOpSlots] = {};

  // client fan-out attribution per op
  std::atomic<uint64_t> fan_calls_[kHistOpSlots] = {};
  std::atomic<uint64_t> fan_requested_[kHistOpSlots] = {};
  std::atomic<uint64_t> fan_deduped_[kHistOpSlots] = {};
  std::atomic<uint64_t> fan_cache_hits_[kHistOpSlots] = {};
  std::atomic<uint64_t> fan_on_wire_[kHistOpSlots] = {};
  SpreadCell spread_[kHistOpSlots] = {};

  // per-shard wire bytes (client side; slot kHeatMaxShards-1 absorbs
  // out-of-range shard indices)
  std::atomic<uint64_t> shard_req_bytes_[kHeatMaxShards] = {};
  std::atomic<uint64_t> shard_reply_bytes_[kHeatMaxShards] = {};

  // server-side requesting-conn ledger: fd-labeled fixed pool
  // (conn_fd_ slots start at -1 = unclaimed, set in the constructor)
  std::atomic<int> conn_fd_[kHeatMaxConns];
  std::atomic<uint64_t> conn_ids_[kHeatMaxConns] = {};
  std::atomic<uint64_t> conn_overflow_{0};

  std::atomic<uint64_t> cache_class_[kHeatCacheEventCount][kHeatClasses] =
      {};
};

}  // namespace eg

#endif  // EG_HEAT_H_
