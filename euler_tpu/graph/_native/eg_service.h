// Sharded graph service: serves one shard's Engine over TCP.
//
// Role equivalent of the reference's async gRPC server
// (reference euler/service/graph_service.cc:112-168 — N completion queues ×
// N threads of CallData state machines) re-shaped for the simpler wire
// protocol. Since the survivability rework the transport runs on the
// bounded-admission layer (eg_admission.h): a poller multiplexes idle
// connections, a FIXED handler pool (workers= option, default 2×cores)
// runs read-decode-execute-reply turns, overload answers BUSY instead of
// queueing unboundedly, and v2 requests carry a deadline the handlers
// honor before computing (eg_wire.h envelope). Drain() supports rolling
// restarts: deregister, stop accepting, finish in-flight, close.
//
// Discovery: instead of ZooKeeper ephemeral znodes
// (reference euler/common/zk_server_register.cc:32-48 "<shard>#<ip:port>"
// children), the service drops a registry file "<shard>#<host>_<port>" into
// a shared directory (atomic rename; removed on Drain/Stop), or REGisters
// with a TCP registry (eg_registry.h) and heartbeats to keep its TTL entry
// alive. On a TPU pod the natural registry_dir is on the shared filesystem
// all hosts mount.
#ifndef EG_SERVICE_H_
#define EG_SERVICE_H_

#include <atomic>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "eg_admission.h"
#include "eg_engine.h"
#include "eg_epoch.h"

namespace eg {

// Count partitions in a data dir: max "*_<p>.dat" index + 1 (files without a
// partition suffix count as partition 0). Matches the shard->partition map
// of reference euler/core/graph_engine.cc:90-107.
int CountPartitions(const std::string& dir);

class Service {
 public:
  ~Service() { Stop(); }

  // Loads shard `shard_idx` of `shard_num` from data_dir, binds host:port
  // (port 0 = ephemeral) and starts serving. If registry_dir is non-empty,
  // registers there: either a shared directory (flat file) or
  // "tcp://host:port" of a RegistryServer (heartbeat re-registration keeps
  // the TTL entry alive — the ephemeral-znode analog, eg_registry.h).
  // `options` is a "k=v;k=v" admission spec (workers/pending/max_conns/
  // io_timeout_ms/idle_timeout_ms/linger_ms/drain_ms/wire_version/
  // telemetry/slow_spans/blackbox/postmortem_dir — see
  // eg_admission.h); unknown keys fail loudly. False + error() on
  // failure.
  bool Start(const std::string& data_dir, int shard_idx, int shard_num,
             const std::string& host, int port,
             const std::string& registry_dir,
             const std::string& options = "");

  // Rolling-restart half: deregister from discovery (flat file unlinked /
  // UNREG sent), stop accepting, let in-flight requests finish (condvar,
  // bounded by grace_ms; <0 = the drain_ms option), close every
  // connection. Idempotent; Stop() runs it first.
  void Drain(int grace_ms = -1);
  void Stop();

  int port() const { return port_; }
  int shard_idx() const { return shard_idx_; }
  const std::string& error() const { return error_; }

  // ---- snapshot epochs (eg_epoch.h) ----
  // Merge one `<prefix>.delta.<n>` file over base + every delta applied
  // so far, flip the serving epoch to the fresh snapshot, and announce
  // it (reply stamps + registry heartbeat). Serialized per shard —
  // concurrent loads queue on delta_mu_. False + *error on read/parse/
  // validate/merge failure or a delta_load / epoch_flip failpoint; the
  // current epoch keeps serving and delta_loads_failed counts it.
  bool LoadDelta(const std::string& path, uint64_t* new_epoch,
                 std::string* error);
  uint64_t epoch() const { return epochs_.current(); }

 private:
  // Leave discovery: unlink the flat-file entry and/or stop the
  // heartbeat thread (which UNREGs on its way out). Idempotent.
  void Deregister();
  // Decode one request body (envelope already stripped by the admission
  // worker), run it on the pinned epoch's engine, encode the reply
  // (stamped with the current epoch for v4 requests).
  void Dispatch(const char* req, size_t len, const Envelope& env,
                std::string* reply);

  // Current + previous snapshot; every Dispatch pins one (v4 requests
  // may pin the previous epoch so in-flight multi-hop steps finish on
  // the snapshot they started on).
  EpochTable epochs_;
  std::mutex delta_mu_;  // serializes LoadDelta (one flip at a time)
  // Every delta applied so far, ascending seq — each flip re-merges
  // base_files_ + all of these so the snapshot is bit-identical to a
  // fresh merged load.
  std::vector<DeltaFile> deltas_ EG_GUARDED_BY(delta_mu_);
  std::vector<std::string> base_files_;
  // What the registry heartbeat announces; stored (not read from
  // epochs_) so the beat thread never touches the flip path.
  std::atomic<uint64_t> announced_epoch_{0};
  std::string error_;
  // Raw placement artifact from the data dir (eg_placement.h), served
  // verbatim through kPlacement so clients route by the same map the
  // converter partitioned with. Empty = hash-sharded data — kPlacement
  // then answers the stock unknown-op error, indistinguishable from a
  // pre-placement server (one client fallback path for both).
  std::string placement_blob_;
  std::string host_;
  int port_ = 0;
  int shard_idx_ = 0, shard_num_ = 1, num_partitions_ = 1;
  bool started_ = false;
  std::string registry_file_;
  // tcp:// registry registration (empty host = not in tcp mode)
  std::string reg_host_;
  int reg_port_ = 0;
  std::thread heartbeat_thread_;
  std::atomic<bool> heartbeat_stop_{false};

  AdmissionServer admission_;
};

}  // namespace eg

#endif  // EG_SERVICE_H_
