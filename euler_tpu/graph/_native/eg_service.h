// Sharded graph service: serves one shard's Engine over TCP.
//
// Role equivalent of the reference's async gRPC server
// (reference euler/service/graph_service.cc:112-168 — N completion queues ×
// N threads of CallData state machines) re-shaped for the simpler wire
// protocol: an accept loop + one handler thread per connection, each running
// a read-decode-execute-reply loop. Clients multiplex by holding several
// connections, so server-side concurrency = number of client connections —
// the same effective model as CQ-per-core without the gRPC machinery.
//
// Discovery: instead of ZooKeeper ephemeral znodes
// (reference euler/common/zk_server_register.cc:32-48 "<shard>#<ip:port>"
// children), the service drops a registry file "<shard>#<host>_<port>" into
// a shared directory (atomic rename; removed on Stop). On a TPU pod the
// natural registry_dir is on the shared filesystem all hosts mount.
#ifndef EG_SERVICE_H_
#define EG_SERVICE_H_

#include <atomic>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "eg_engine.h"

namespace eg {

// Count partitions in a data dir: max "*_<p>.dat" index + 1 (files without a
// partition suffix count as partition 0). Matches the shard->partition map
// of reference euler/core/graph_engine.cc:90-107.
int CountPartitions(const std::string& dir);

class Service {
 public:
  ~Service() { Stop(); }

  // Loads shard `shard_idx` of `shard_num` from data_dir, binds host:port
  // (port 0 = ephemeral) and starts serving. If registry_dir is non-empty,
  // registers there: either a shared directory (flat file) or
  // "tcp://host:port" of a RegistryServer (heartbeat re-registration keeps
  // the TTL entry alive — the ephemeral-znode analog, eg_registry.h).
  // False + error() on failure.
  bool Start(const std::string& data_dir, int shard_idx, int shard_num,
             const std::string& host, int port,
             const std::string& registry_dir);
  void Stop();

  int port() const { return port_; }
  int shard_idx() const { return shard_idx_; }
  const std::string& error() const { return error_; }
  const Engine& engine() const { return engine_; }

 private:
  void AcceptLoop();
  void HandleConn(int fd);
  // Decode one request, run it on the engine, encode the reply.
  void Dispatch(const std::string& req, std::string* reply) const;

  Engine engine_;
  std::string error_;
  std::string host_;
  int port_ = 0;
  int shard_idx_ = 0, shard_num_ = 1, num_partitions_ = 1;
  std::string registry_file_;
  // tcp:// registry registration (empty host = not in tcp mode)
  std::string reg_host_;
  int reg_port_ = 0;
  std::thread heartbeat_thread_;
  std::atomic<bool> heartbeat_stop_{false};

  int listen_fd_ = -1;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex mu_;  // guards conn_fds_
  std::set<int> conn_fds_;
  // Handler threads are detached; Stop() waits for this to drain so no
  // handler can outlive the Service it references.
  std::atomic<int> active_conns_{0};
};

}  // namespace eg

#endif  // EG_SERVICE_H_
