#include "eg_admission.h"

#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdlib>
#include <cstring>

#include "eg_blackbox.h"
#include "eg_fault.h"
#include "eg_heat.h"
#include "eg_stats.h"
#include "eg_telemetry.h"
#include "eg_wire.h"

namespace eg {

namespace {

int64_t NowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void SetConnTimeouts(int fd, int timeout_ms) {
  if (timeout_ms <= 0) return;
  timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

// Exception-free int parse (this runs under the C ABI: a malformed
// option must land in *err, never throw through eg_capi).
bool ParseIntOpt(const std::string& s, int* out) {
  if (s.empty()) return false;
  char* end = nullptr;
  long v = std::strtol(s.c_str(), &end, 10);
  if (end != s.c_str() + s.size()) return false;
  *out = static_cast<int>(v);
  return true;
}

}  // namespace

bool ParseAdmissionOptions(const std::string& spec, AdmissionOptions* opt,
                           std::string* err) {
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t semi = spec.find(';', pos);
    std::string item = spec.substr(
        pos, semi == std::string::npos ? std::string::npos : semi - pos);
    pos = semi == std::string::npos ? spec.size() : semi + 1;
    if (item.empty()) continue;
    size_t eq = item.find('=');
    if (eq == std::string::npos) {
      *err = "service option '" + item + "' wants key=value";
      return false;
    }
    std::string key = item.substr(0, eq);
    if (key == "postmortem_dir") {
      // the one string-valued option: where the fatal-signal handler
      // writes this serving process's dump (eg_blackbox.h)
      opt->postmortem_dir = item.substr(eq + 1);
      continue;
    }
    int v = 0;
    if (!ParseIntOpt(item.substr(eq + 1), &v)) {
      *err = "bad integer in service option '" + item + "'";
      return false;
    }
    if (key == "workers") {
      opt->workers = v;
    } else if (key == "pending") {
      opt->pending = v;
    } else if (key == "max_conns") {
      opt->max_conns = v;
    } else if (key == "io_timeout_ms") {
      opt->io_timeout_ms = v;
    } else if (key == "idle_timeout_ms") {
      opt->idle_timeout_ms = v;
    } else if (key == "linger_ms") {
      opt->linger_ms = v;
    } else if (key == "drain_ms") {
      opt->drain_ms = v;
    } else if (key == "wire_version") {
      if (v < 1 || v > kWireVersion) {
        *err = "wire_version must be 1.." + std::to_string(kWireVersion) +
               " (this build speaks " + std::to_string(kWireVersion) + ")";
        return false;
      }
      opt->legacy_wire = v == 1;
      opt->v2_only = v == 2;
      opt->v3_only = v == 3;
    } else if (key == "telemetry") {
      opt->telemetry = v != 0 ? 1 : 0;
    } else if (key == "slow_spans") {
      if (v < 1) {
        *err = "slow_spans must be >= 1 (journal capacity)";
        return false;
      }
      opt->slow_spans = v;
    } else if (key == "blackbox") {
      opt->blackbox = v != 0 ? 1 : 0;
    } else if (key == "heat") {
      opt->heat = v != 0 ? 1 : 0;
    } else if (key == "heat_topk") {
      if (v < 1 || v > kHeatMaxTopK) {
        *err = "heat_topk must be 1.." + std::to_string(kHeatMaxTopK) +
               " (fixed top-K tracker pool)";
        return false;
      }
      opt->heat_topk = v;
    } else {
      // loudness rule: a typo'd key must not be dropped silently
      *err = "unknown service option '" + key +
             "' (known: workers, pending, max_conns, io_timeout_ms, "
             "idle_timeout_ms, linger_ms, drain_ms, wire_version, "
             "telemetry, slow_spans, blackbox, heat, heat_topk, "
             "postmortem_dir)";
      return false;
    }
  }
  return true;
}

bool AdmissionServer::Start(int listen_fd, const AdmissionOptions& opt,
                            Handler handler, std::string* err) {
  opt_ = opt;
  // telemetry=/slow_spans= options act on the process-global telemetry
  // switch (eg_telemetry.h) — the server half of the kill-switch the
  // client reaches through its graph config
  if (opt_.telemetry >= 0)
    Telemetry::Global().SetEnabled(opt_.telemetry != 0);
  if (opt_.slow_spans > 0)
    Telemetry::Global().SetSlowCapacity(opt_.slow_spans);
  // blackbox=/postmortem_dir= options: the server half of the flight-
  // recorder kill-switch and the fatal-signal dump path (eg_blackbox.h)
  if (opt_.blackbox >= 0) Blackbox::Global().SetEnabled(opt_.blackbox != 0);
  // heat=/heat_topk= options: the server half of the data-plane heat
  // profiler's switches (eg_heat.h)
  if (opt_.heat >= 0) Heat::Global().SetEnabled(opt_.heat != 0);
  if (opt_.heat_topk > 0) Heat::Global().SetTopK(opt_.heat_topk);
  if (!opt_.postmortem_dir.empty() &&
      !Blackbox::Global().Install(opt_.postmortem_dir, opt_.shard_idx)) {
    *err = Blackbox::Global().error();
    return false;
  }
  if (opt_.workers <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    opt_.workers = 2 * static_cast<int>(hc ? hc : 2);
  }
  if (opt_.pending < 1) opt_.pending = 1;
  if (opt_.max_conns < opt_.workers + opt_.pending)
    opt_.max_conns = opt_.workers + opt_.pending;
  if (opt_.linger_ms < 0) opt_.linger_ms = 0;
  handler_ = std::move(handler);
  listen_fd_ = listen_fd;
  // non-blocking listen: the poller accept-bursts until EAGAIN, so one
  // poll wakeup drains a whole storm of pending connects
  int fl = ::fcntl(listen_fd_, F_GETFL, 0);
  ::fcntl(listen_fd_, F_SETFL, fl | O_NONBLOCK);
  int pfds[2];
  if (::pipe(pfds) != 0) {
    *err = "admission: cannot create wake pipe";
    return false;
  }
  wake_r_ = pfds[0];
  wake_w_ = pfds[1];
  ::fcntl(wake_r_, F_SETFL, O_NONBLOCK);
  ::fcntl(wake_w_, F_SETFL, O_NONBLOCK);
  {
    // poller thread not spawned yet, but take the lock anyway: the
    // guarded-by contract is simpler than a start-ordering argument
    std::lock_guard<PosixMutex> l(mu_);
    stop_ = false;
  }
  draining_.store(false, std::memory_order_release);
  poller_ = std::thread([this] {
    try {
      PollerLoop();
    } catch (...) {
      // std::terminate barrier (eg-lint: thread-catch): a dead poller
      // stops admitting and re-arming connections until restart; the
      // workers drain what is already queued
    }
  });
  workers_.reserve(static_cast<size_t>(opt_.workers));
  for (int i = 0; i < opt_.workers; ++i)
    workers_.emplace_back([this] {
      try {
        WorkerLoop();
      } catch (...) {
        // std::terminate barrier (eg-lint: thread-catch): a dead worker
        // shrinks the pool; the siblings keep serving
      }
    });
  started_ = true;
  return true;
}

void AdmissionServer::Wake() {
  if (wake_w_ >= 0) {
    char b = 1;
    // best effort: a full pipe already guarantees a pending wakeup
    (void)!::write(wake_w_, &b, 1);
  }
}

void AdmissionServer::CloseConn(int fd) {
  {
    std::lock_guard<PosixMutex> l(mu_);
    all_fds_.erase(fd);
  }
  ::close(fd);
  if (conns_.fetch_sub(1, std::memory_order_acq_rel) == 1 &&
      draining_.load(std::memory_order_acquire)) {
    std::lock_guard<PosixMutex> l(mu_);
    drained_cv_.notify_all();
  }
}

void AdmissionServer::ReturnConn(int fd) {
  bool close_now;
  {
    std::lock_guard<PosixMutex> l(mu_);
    close_now = stop_ || draining_.load(std::memory_order_relaxed);
    if (!close_now) returned_.push_back(fd);
  }
  if (close_now) {
    CloseConn(fd);
    return;
  }
  Wake();
}

void AdmissionServer::AcceptBurst(std::map<int, int64_t>* idle,
                                  std::map<int, int64_t>* dying,
                                  int64_t now) {
  for (;;) {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;  // EAGAIN (burst drained) or listener gone
    // kFaultAccept: err drops the connection at the door (accept-path
    // flakiness); delay slows admission without dropping.
    if (FaultHit(kFaultAccept)) {
      ::close(fd);
      continue;
    }
    SetConnTimeouts(fd, opt_.io_timeout_ms);
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // Bounded admission: when in-flight work already saturates the pool
    // plus its pending headroom (or the fd budget is gone), answer one
    // BUSY frame and close — the client fails over immediately instead
    // of this server queueing work it cannot start.
    bool busy = FaultHit(kFaultBusyForce);
    if (!busy) {
      int in_flight = active_.load(std::memory_order_relaxed) +
                      ready_count_.load(std::memory_order_relaxed);
      busy = in_flight >= opt_.workers + opt_.pending ||
             conns_.load(std::memory_order_relaxed) >= opt_.max_conns;
    }
    if (busy) {
      Counters::Global().Add(kCtrBusyReject);
      SendFrame(fd, StatusReply(kStatusBusy,
                                "server busy: admission queue full"));
      // Half-close and drain to EOF instead of closing outright: a
      // close with the client's request bytes still arriving turns into
      // an RST that can clobber the unread BUSY reply — the client
      // would see a reset (quarantine + backoff) instead of the
      // fail-fast failover the reply exists to trigger.
      ::shutdown(fd, SHUT_WR);
      if (static_cast<int>(dying->size()) < 256)
        (*dying)[fd] = now + 500;
      else
        ::close(fd);  // reject storm beyond the drain budget: RST it is
      continue;
    }
    conns_.fetch_add(1, std::memory_order_acq_rel);
    {
      std::lock_guard<PosixMutex> l(mu_);
      all_fds_.insert(fd);
    }
    (*idle)[fd] = now;
  }
}

void AdmissionServer::PollerLoop() {
  // fd -> since-when-idle (ms); an idle connection costs a poll slot,
  // never a handler — the fix for pooled client sockets pinning the
  // old thread-per-connection servers
  std::map<int, int64_t> idle;
  // BUSY-rejected fds being drained to EOF (fd -> give-up deadline)
  std::map<int, int64_t> dying;
  std::vector<pollfd> pfds;
  bool listen_open = listen_fd_ >= 0;
  for (;;) {
    {
      std::lock_guard<PosixMutex> l(mu_);
      if (stop_) break;
    }
    bool draining = draining_.load(std::memory_order_acquire);
    if (draining && listen_open) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      listen_open = false;
    }
    if (draining && !idle.empty()) {
      for (const auto& [fd, since] : idle) CloseConn(fd);
      idle.clear();
    }
    pfds.clear();
    pfds.push_back({wake_r_, POLLIN, 0});
    if (listen_open) pfds.push_back({listen_fd_, POLLIN, 0});
    for (const auto& [fd, since] : idle) pfds.push_back({fd, POLLIN, 0});
    size_t ndying = dying.size();
    for (const auto& [fd, until] : dying) pfds.push_back({fd, POLLIN, 0});
    int rc = ::poll(pfds.data(), static_cast<nfds_t>(pfds.size()), 250);
    if (rc < 0 && errno != EINTR) {
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
      continue;
    }
    int64_t now = NowMs();
    // Refresh the blackbox's POD gauge snapshot every cycle (<=250 ms
    // stale): the fatal-signal dump reads THIS, never the live server
    // object a crashing process may already be tearing down.
    {
      AdmissionSnap& snap = AdmissionGaugeSnap();
      snap.workers.store(opt_.workers, std::memory_order_relaxed);
      snap.active.store(active_.load(std::memory_order_relaxed),
                        std::memory_order_relaxed);
      snap.queue_depth.store(ready_count_.load(std::memory_order_relaxed),
                             std::memory_order_relaxed);
      snap.conns.store(conns_.load(std::memory_order_relaxed),
                       std::memory_order_relaxed);
      snap.draining.store(draining ? 1 : 0, std::memory_order_relaxed);
      snap.registered.store(1, std::memory_order_relaxed);
    }
    size_t k = 0;
    if (pfds[k].revents & POLLIN) {
      char buf[64];
      while (::read(wake_r_, buf, sizeof(buf)) > 0) {
      }
    }
    ++k;
    // conns workers handed back: re-arm (or close when draining raced)
    std::vector<int> back;
    {
      std::lock_guard<PosixMutex> l(mu_);
      back.swap(returned_);
    }
    for (int fd : back) {
      if (draining_.load(std::memory_order_acquire))
        CloseConn(fd);
      else
        idle[fd] = now;
    }
    if (listen_open) {
      if (pfds[k].revents & POLLIN) AcceptBurst(&idle, &dying, now);
      ++k;
    }
    bool any_ready = false;
    size_t idle_end = pfds.size() - ndying;
    for (; k < idle_end; ++k) {
      if (pfds[k].revents == 0) continue;
      int fd = pfds[k].fd;
      if (idle.erase(fd) == 0) continue;  // already re-armed this cycle
      {
        std::lock_guard<PosixMutex> l(mu_);
        ready_.push_back({fd, now});
      }
      ready_count_.fetch_add(1, std::memory_order_acq_rel);
      any_ready = true;
    }
    if (any_ready) ready_cv_.notify_all();
    // BUSY'd fds draining to EOF: discard arriving bytes, close on
    // EOF/error or when the give-up deadline passes
    for (size_t d = idle_end; d < pfds.size(); ++d) {
      if (pfds[d].revents == 0) continue;
      char scratch[4096];
      ssize_t r = ::recv(pfds[d].fd, scratch, sizeof(scratch), MSG_DONTWAIT);
      if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
        ::close(pfds[d].fd);
        dying.erase(pfds[d].fd);
      }
    }
    for (auto it = dying.begin(); it != dying.end();) {
      if (now >= it->second) {
        ::close(it->first);
        it = dying.erase(it);
      } else {
        ++it;
      }
    }
    if (opt_.idle_timeout_ms > 0) {
      for (auto it = idle.begin(); it != idle.end();) {
        if (now - it->second > opt_.idle_timeout_ms) {
          CloseConn(it->first);
          it = idle.erase(it);
        } else {
          ++it;
        }
      }
    }
  }
  // stop: the accounting owner for idle and dying conns is this thread
  for (const auto& [fd, since] : idle) CloseConn(fd);
  for (const auto& [fd, until] : dying) ::close(fd);
  if (listen_open) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
}

void AdmissionServer::WorkerLoop() {
  for (;;) {
    ReadyConn c;
    bool drop = false;
    {
      std::unique_lock<PosixMutex> l(mu_);
      ready_cv_.wait(l, [this] { return stop_ || !ready_.empty(); });
      if (ready_.empty()) return;  // stop_ and nothing left to drop
      c = ready_.front();
      ready_.pop_front();
      ready_count_.fetch_sub(1, std::memory_order_acq_rel);
      drop = stop_;
      if (!drop) active_.fetch_add(1, std::memory_order_acq_rel);
    }
    if (drop) {
      CloseConn(c.fd);
      continue;
    }
    ServeConn(c);
    active_.fetch_sub(1, std::memory_order_acq_rel);
    if (draining_.load(std::memory_order_acquire)) {
      std::lock_guard<PosixMutex> l(mu_);
      drained_cv_.notify_all();
    }
  }
}

void AdmissionServer::ServeConn(ReadyConn c) {
  Counters& ctr = Counters::Global();
  // Requesting-conn tag for the data-plane heat feeds (eg_heat.h):
  // Service::Dispatch runs on this thread and reads it back, so the
  // shard's per-conn id ledger can name WHO generates the hot traffic
  // without widening the handler signature.
  HeatSetConn(c.fd);
  std::string req, reply;
  int64_t ready_ms = c.ready_ms;
  for (;;) {
    IoStatus rs = RecvFrameEx(c.fd, &req);
    if (rs != IoStatus::kOk) {
      // kTimeout: the peer began a frame and wedged mid-send — the
      // socket timeout freed this handler slot
      if (rs == IoStatus::kTimeout) ctr.Add(kCtrHandlerTimeout);
      CloseConn(c.fd);
      return;
    }
    // Telemetry (eg_telemetry.h): queue wait = poller-ready to here;
    // handler time = everything between recv and the reply being ready
    // (the stall failpoint included, so delay faults land requests in
    // deterministic buckets); wire time = the reply send.
    Telemetry& tel = Telemetry::Global();
    const bool rec = tel.enabled();
    uint64_t queue_us = 0;
    if (rec) {
      int64_t waited_ms = NowMs() - ready_ms;
      queue_us = waited_ms > 0 ? static_cast<uint64_t>(waited_ms) * 1000
                               : 0;
      tel.Record(kHistServerQueue, 0, queue_us);
    }
    const int64_t t_handle = rec ? TelemetryNowUs() : 0;
    Envelope env;
    uint8_t op = 0;
    reply.clear();
    if (!PeekEnvelope(req, &env)) {
      ctr.Add(kCtrFrameReject);
      reply = StatusReply(kStatusError, "truncated request envelope");
    } else {
      if (req.size() > env.body_off)
        op = static_cast<uint8_t>(req[env.body_off]);
      // flight recorder (eg_blackbox.h): the decoded request — op,
      // trace id, wire bytes — BEFORE anything can go wrong serving
      // it, so a handler that dies mid-dispatch leaves the fatal
      // call's trace id in its ring tail (the postmortem merge keys
      // the incident timeline on exactly this event)
      Blackbox::Global().Record(kBbServerRecv, op, opt_.shard_idx,
                                env.trace_id, req.size(), 0);
      if (opt_.legacy_wire && env.versioned) {
        // v1-server emulation (wire_version=1 option): answer exactly
        // what a pre-envelope build answers, so the client's downgrade
        // negotiation can be pinned against a real service
        reply = StatusReply(kStatusError,
                            "unknown op " + std::to_string(kWireEnvelope));
      } else if (opt_.v2_only && env.versioned && env.version > 2) {
        // v2-server emulation (wire_version=2 option): refuse the v3
        // trace envelope the way a pre-telemetry build does, driving
        // the client's pin-at-v2 downgrade path
        ctr.Add(kCtrFrameReject);
        reply = StatusReply(
            kStatusBadVersion,
            "unsupported wire version " + std::to_string(env.version) +
                " (server speaks up to 2)");
      } else if (opt_.v3_only && env.versioned && env.version > 3) {
        // v3-server emulation (wire_version=3 option): refuse the v4
        // epoch envelope the way a pre-epoch build does, driving the
        // client's progressive 4 -> 3 downgrade path
        ctr.Add(kCtrFrameReject);
        reply = StatusReply(
            kStatusBadVersion,
            "unsupported wire version " + std::to_string(env.version) +
                " (server speaks up to 3)");
      } else if (env.versioned && env.version > kWireVersion) {
        ctr.Add(kCtrFrameReject);
        reply = StatusReply(
            kStatusBadVersion,
            "unsupported wire version " + std::to_string(env.version) +
                " (server speaks up to " + std::to_string(kWireVersion) +
                ")");
      } else {
        // kFaultHandlerStall sits between recv and the deadline check:
        // a delay fault ages the request so the deadline path below
        // fires deterministically; an err fault wedges the handler,
        // which abandons the connection (the client sees a reset and
        // retries)
        if (FaultHit(kFaultHandlerStall)) {
          CloseConn(c.fd);
          return;
        }
        // kFaultCrash at the handler point (FAULTS.md): the server
        // half of the postmortem drill — Fire raises the configured
        // fatal signal AFTER the kBbServerRecv record above, so the
        // dump's ring tail carries the fatal call's trace id.
        (void)FaultHit(kFaultCrash);
        if (env.deadline_ms >= 0 && NowMs() - ready_ms > env.deadline_ms) {
          // the client's budget is gone: an answer would be dead compute
          ctr.Add(kCtrDeadlineReject);
          reply = StatusReply(kStatusDeadline,
                              "deadline expired before dispatch");
        } else {
          try {
            handler_(req.data() + env.body_off, req.size() - env.body_off,
                     env, &reply);
          } catch (const std::exception& ex) {
            // a malformed request must come back as an error reply, not
            // tear down the connection (let alone the worker)
            reply = StatusReply(kStatusError,
                                std::string("server error: ") + ex.what());
          } catch (...) {
            reply = StatusReply(kStatusError, "server error");
          }
        }
      }
    }
    const uint64_t handler_us =
        rec ? static_cast<uint64_t>(TelemetryNowUs() - t_handle) : 0;
    if (rec) tel.Record(kHistServerHandler, op, handler_us);
    const uint8_t status =
        reply.empty() ? static_cast<uint8_t>(kStatusError)
                      : static_cast<uint8_t>(reply[0]);
    auto record_span = [&](uint64_t wire_us, uint8_t outcome) {
      if (!rec) return;
      TelemetrySpan sp;
      sp.side = kSpanServer;
      sp.op = op < kHistOpSlots ? op : 0;
      sp.shard = opt_.shard_idx;
      sp.trace = env.trace_id;
      sp.queue_us = queue_us;
      sp.handler_us = handler_us;
      sp.wire_us = wire_us;
      sp.total_us = queue_us + handler_us + wire_us;
      sp.outcome = outcome;
      tel.RecordSpan(sp);
    };
    // kFaultServiceReply drops the computed reply on the floor and
    // closes the connection — the client sees a mid-exchange reset and
    // must retry (possibly on another replica).
    if (FaultHit(kFaultServiceReply)) {
      record_span(0, kOutcomeDropped);
      CloseConn(c.fd);
      return;
    }
    const int64_t t_send = rec ? TelemetryNowUs() : 0;
    IoStatus ss = SendFrameEx(c.fd, reply);
    const uint8_t reply_outcome =
        ss != IoStatus::kOk         ? kOutcomeDropped
        : status == kStatusOk       ? kOutcomeOk
        : status == kStatusBusy     ? kOutcomeBusy
        : status == kStatusDeadline ? kOutcomeDeadline
                                    : kOutcomeError;
    record_span(rec ? static_cast<uint64_t>(TelemetryNowUs() - t_send) : 0,
                reply_outcome);
    Blackbox::Global().Record(kBbServerReply, op, opt_.shard_idx,
                              env.trace_id, reply.size(), reply_outcome);
    if (ss != IoStatus::kOk) {
      // kTimeout: the peer stopped reading and the send buffer filled —
      // again the socket timeout frees the slot
      if (ss == IoStatus::kTimeout) ctr.Add(kCtrHandlerTimeout);
      CloseConn(c.fd);
      return;
    }
    bool stopping;
    {
      std::lock_guard<PosixMutex> l(mu_);
      stopping = stop_;
    }
    if (stopping || draining_.load(std::memory_order_acquire)) {
      CloseConn(c.fd);
      return;
    }
    // fairness: with work waiting, hand the connection back; otherwise
    // linger briefly — a synchronous client's next request lands within
    // microseconds on loopback, and skipping the poller round-trip
    // keeps the hot path at thread-per-conn latency
    if (ready_count_.load(std::memory_order_relaxed) > 0) {
      ReturnConn(c.fd);
      return;
    }
    pollfd p{c.fd, POLLIN, 0};
    int pr = ::poll(&p, 1, opt_.linger_ms);
    if (pr <= 0 || !(p.revents & POLLIN)) {
      ReturnConn(c.fd);
      return;
    }
    ready_ms = NowMs();
  }
}

void AdmissionServer::Drain(int grace_ms) {
  if (!started_) return;
  bool first = false;
  {
    std::lock_guard<PosixMutex> l(mu_);
    if (!draining_.load(std::memory_order_relaxed)) {
      draining_.store(true, std::memory_order_release);
      first = true;
    }
  }
  if (first) Counters::Global().Add(kCtrDraining);
  Wake();
  if (grace_ms < 0) grace_ms = opt_.drain_ms;
  std::unique_lock<PosixMutex> l(mu_);
  drained_cv_.wait_for_ms(l, grace_ms, [this] {
    return conns_.load(std::memory_order_acquire) == 0;
  });
}

void AdmissionServer::Stop() {
  if (!started_) return;
  Drain(-1);
  {
    std::lock_guard<PosixMutex> l(mu_);
    stop_ = true;
    // grace expired with work still in flight: force every blocked IO
    // to return so the joins below stay prompt
    for (int fd : all_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  ready_cv_.notify_all();
  Wake();
  if (poller_.joinable()) poller_.join();
  for (auto& t : workers_)
    if (t.joinable()) t.join();
  workers_.clear();
  std::set<int> leftover;
  {
    std::lock_guard<PosixMutex> l(mu_);
    leftover.swap(all_fds_);
    ready_.clear();
    returned_.clear();
  }
  for (int fd : leftover) ::close(fd);
  conns_.store(0, std::memory_order_release);
  ready_count_.store(0, std::memory_order_release);
  if (wake_r_ >= 0) ::close(wake_r_);
  if (wake_w_ >= 0) ::close(wake_w_);
  wake_r_ = wake_w_ = -1;
  started_ = false;
}

}  // namespace eg
