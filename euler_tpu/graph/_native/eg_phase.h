// Step-phase profiler substrate: where a TRAINING step's time goes.
//
// eg_telemetry answers "where did this RPC's time go"; nothing answers
// "where did this training STEP's time go" — sampling vs host→device
// transfer vs device compute vs consumer stall on the prefetch queue.
// Pipelined-sampling work (arXiv:2110.08450) and FastSample
// (arXiv:2311.17847) both show input stalls dominating GNN step time
// exactly while they are invisible; ROADMAP item 1's acceptance
// criterion (`input_stall_ms -> ~0`) needs this measurement layer to
// exist before the pipelining PR can be judged against it.
//
// Two recorders, both the same lock-free cell shape as eg_telemetry:
//
//   * per-phase µs HISTOGRAMS (input_stall / sample / h2d / device /
//     host / step) — recorded by the Python training loop and prefetch
//     pipeline through the eg_phase_record ABI;
//   * prefetch pipeline VALUE histograms (queue depth at dequeue,
//     workers busy at dequeue) — dimensionless log2 buckets, so
//     count/sum give dequeues and mean depth and the bucket shape
//     distinguishes "queue always empty" (starved consumer) from
//     "queue deep but workers idle" (slow shard, not slow workers).
//
// The kill-switch is shared with eg_telemetry (`telemetry=0` disables
// both), and PhaseStats::HistJsonInto emits into the SAME "hist" map
// Telemetry::Json builds — keys "phase:<name>" / "prefetch_depth" /
// "prefetch_busy" — so metrics_text(), snapshot(), the STATS scrape,
// and every percentile helper pick the phases up with zero new plumbing.
#ifndef EG_PHASE_H_
#define EG_PHASE_H_

#include <atomic>
#include <cstdint>
#include <string>

#include "eg_telemetry.h"

namespace eg {

// Fixed phase order — the Python twin (euler_tpu/telemetry.py PHASES)
// indexes by this enum through the eg_phase_record ABI, pinned by tests.
enum StepPhase : int {
  kPhaseInputStall = 0,  // consumer blocked on the prefetch queue
  kPhaseSample,          // worker make_batch produce time (graph engine)
  kPhaseH2d,             // host->device transfer (shard_batch/device_put)
  kPhaseDevice,          // device compute, fenced via block_until_ready
  kPhaseHost,            // optimizer/bookkeeping tail on the host
  kPhaseStep,            // whole-step wall (the sum check for the rest)
  kPhaseCompile,         // XLA backend compile (jax.monitoring via
                         // euler_tpu/devprof.py — NOT part of the
                         // step-sum identity; compiles overlap steps)
  kPhaseCount,
};

const char* const kPhaseNames[kPhaseCount] = {
    "input_stall", "sample", "h2d", "device", "host", "step", "compile",
};

// Prefetch pipeline gauges recorded as value histograms.
enum PrefetchGauge : int {
  kGaugeQueueDepth = 0,  // ready batches at consumer dequeue
  kGaugeWorkersBusy,     // workers inside make_batch at dequeue
  kGaugeCount,
};

// Scalar hist-map keys (no per-op label, like "dial"/"backoff").
const char* const kPrefetchGaugeKeys[kGaugeCount] = {
    "prefetch_depth", "prefetch_busy",
};

// Serve-request phase order (euler_tpu/serving, OBSERVABILITY.md
// "Serve phases") — where one inference request's time goes, the
// request-level twin of the training StepPhase above. The Python twin
// (euler_tpu/telemetry.py SERVE_PHASES) indexes by this enum through
// the eg_serve_record ABI, pinned by tests.
enum ServePhase : int {
  kServeQueueWait = 0,  // submit -> micro-batch collect (coalescing wait)
  kServeSample,         // neighborhood sampling via the graph client
  kServeDispatch,       // h2d + jitted forward, fenced block_until_ready
  kServeTotal,          // submit -> reply wall (the sum check)
  kServePhaseCount,
};

const char* const kServePhaseNames[kServePhaseCount] = {
    "queue_wait", "sample", "dispatch", "total",
};

// Scalar hist-map key for the micro-batch size value histogram
// (dimensionless log2 buckets: count = device dispatches, sum = unique
// ids dispatched — their ratio is the coalescing factor the micro-
// batcher exists to produce).
const char kServeBatchKey[] = "serve_batch";

class PhaseStats {
 public:
  static PhaseStats& Global();

  // One µs sample for a step phase. Same cost contract as
  // Telemetry::Record: two relaxed RMWs, one relaxed load when the
  // shared telemetry kill-switch is off.
  void Record(int phase, uint64_t us) {
    if (!Telemetry::Global().enabled()) return;
    if (phase < 0 || phase >= kPhaseCount) return;
    Cell& c = phases_[phase];
    c.buckets[HistBucketOf(us)].fetch_add(1, std::memory_order_relaxed);
    c.total.fetch_add(us, std::memory_order_relaxed);
  }

  // One dimensionless sample for a prefetch gauge (depth, busy count).
  void RecordGauge(int which, uint64_t value) {
    if (!Telemetry::Global().enabled()) return;
    if (which < 0 || which >= kGaugeCount) return;
    Cell& c = gauges_[which];
    c.buckets[HistBucketOf(value)].fetch_add(1, std::memory_order_relaxed);
    c.total.fetch_add(value, std::memory_order_relaxed);
  }

  // One µs sample for a serve-request phase (eg::ServePhase order).
  // Same kill-switch and cost contract as Record, so `telemetry=0`
  // leaves the serve hot path histogram-free.
  void RecordServe(int phase, uint64_t us) {
    if (!Telemetry::Global().enabled()) return;
    if (phase < 0 || phase >= kServePhaseCount) return;
    Cell& c = serve_[phase];
    c.buckets[HistBucketOf(us)].fetch_add(1, std::memory_order_relaxed);
    c.total.fetch_add(us, std::memory_order_relaxed);
  }

  // One micro-batch dispatch: `ids` = unique ids in the device batch.
  void RecordServeBatch(uint64_t ids) {
    if (!Telemetry::Global().enabled()) return;
    Cell& c = serve_batch_;
    c.buckets[HistBucketOf(ids)].fetch_add(1, std::memory_order_relaxed);
    c.total.fetch_add(ids, std::memory_order_relaxed);
  }

  void Reset();

  // Append this recorder's series to an in-progress JSON "hist" map
  // (caller owns the braces; `first` tracks comma state across both
  // emitters). Keys: "phase:<name>" and the scalar gauge keys above,
  // each {"b": [...], "count": n, "sum_us": s} — identical shape to the
  // telemetry histograms so one Python renderer serves both.
  void HistJsonInto(std::string* out, bool* first) const;

 private:
  struct Cell {
    std::atomic<uint64_t> buckets[kHistBuckets];
    std::atomic<uint64_t> total;
  };

  Cell phases_[kPhaseCount] = {};
  Cell gauges_[kGaugeCount] = {};
  Cell serve_[kServePhaseCount] = {};
  Cell serve_batch_ = {};
};

}  // namespace eg

#endif  // EG_PHASE_H_
