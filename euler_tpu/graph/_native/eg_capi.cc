// C ABI for the euler_tpu graph engine, consumed from Python via ctypes.
//
// Role equivalent to the reference's ctypes surface
// (reference tf_euler/utils/create_graph.cc:47 CreateGraph and
// euler/service/python_api.cc StartService), generalized to a handle-based
// batch API: fixed-shape calls write into caller-allocated numpy buffers;
// variable-shape calls return an EGResult handle the caller drains and frees.
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "eg_engine.h"

using eg::EGResult;
using eg::Engine;

namespace {
thread_local std::string g_last_error;
}

extern "C" {

const char* eg_last_error() { return g_last_error.c_str(); }

void* eg_create() { return new Engine(); }

void eg_destroy(void* h) { delete static_cast<Engine*>(h); }

int eg_load(void* h, const char* dir, int shard_idx, int shard_num) {
  auto* e = static_cast<Engine*>(h);
  if (!e->Load(dir, shard_idx, shard_num)) {
    g_last_error = e->error();
    return -1;
  }
  return 0;
}

int eg_load_files(void* h, const char** files, int nfiles) {
  auto* e = static_cast<Engine*>(h);
  std::vector<std::string> fs(files, files + nfiles);
  if (!e->LoadFiles(std::move(fs))) {
    g_last_error = e->error();
    return -1;
  }
  return 0;
}

void eg_seed(uint64_t seed) { eg::SeedThreadRng(seed); }

// ---- introspection ----
int64_t eg_num_nodes(void* h) {
  return static_cast<int64_t>(static_cast<Engine*>(h)->store().num_nodes());
}
int64_t eg_num_edges(void* h) {
  return static_cast<int64_t>(static_cast<Engine*>(h)->store().num_edges());
}
int32_t eg_node_type_num(void* h) {
  return static_cast<Engine*>(h)->store().node_type_num();
}
int32_t eg_edge_type_num(void* h) {
  return static_cast<Engine*>(h)->store().edge_type_num();
}
// kind: 0=node u64, 1=node f32, 2=node binary, 3=edge u64, 4=edge f32,
// 5=edge binary.
int32_t eg_feature_num(void* h, int kind) {
  const auto& s = static_cast<Engine*>(h)->store();
  switch (kind) {
    case 0: return s.nf_u64_num();
    case 1: return s.nf_f32_num();
    case 2: return s.nf_bin_num();
    case 3: return s.ef_u64_num();
    case 4: return s.ef_f32_num();
    case 5: return s.ef_bin_num();
    default: return -1;
  }
}
// Per-type weight sums for cross-shard weighted sampling; out has
// node_type_num (kind 0) or edge_type_num (kind 1) floats.
void eg_type_weight_sums(void* h, int kind, float* out) {
  const auto& s = static_cast<Engine*>(h)->store();
  const auto& v =
      kind == 0 ? s.node_type_weight_sums() : s.edge_type_weight_sums();
  std::memcpy(out, v.data(), v.size() * sizeof(float));
}

// ---- sampling ----
void eg_sample_node(void* h, int count, int32_t type, uint64_t* out) {
  static_cast<Engine*>(h)->SampleNode(count, type, out);
}

void eg_sample_edge(void* h, int count, int32_t type, uint64_t* out_src,
                    uint64_t* out_dst, int32_t* out_type) {
  static_cast<Engine*>(h)->SampleEdge(count, type, out_src, out_dst, out_type);
}

void eg_sample_node_with_src(void* h, const uint64_t* src, int n, int count,
                             uint64_t* out) {
  static_cast<Engine*>(h)->SampleNodeWithSrc(src, n, count, out);
}

void eg_get_node_type(void* h, const uint64_t* ids, int n, int32_t* out) {
  static_cast<Engine*>(h)->GetNodeType(ids, n, out);
}

void eg_sample_neighbor(void* h, const uint64_t* ids, int n,
                        const int32_t* etypes, int net, int count,
                        uint64_t default_id, uint64_t* out_ids, float* out_w,
                        int32_t* out_t) {
  static_cast<Engine*>(h)->SampleNeighbor(ids, n, etypes, net, count,
                                          default_id, out_ids, out_w, out_t);
}

// etypes_flat: concatenated per-hop edge-type lists; etype_counts[h] =
// number of edge types for hop h; counts[h] = fanout of hop h.
// out_*: per-hop caller buffers, hop h sized n * prod(counts[:h+1]).
void eg_sample_fanout(void* h, const uint64_t* ids, int n,
                      const int32_t* etypes_flat, const int32_t* etype_counts,
                      const int32_t* counts, int nhops, uint64_t default_id,
                      uint64_t** out_ids, float** out_w, int32_t** out_t) {
  static_cast<Engine*>(h)->SampleFanout(ids, n, etypes_flat, etype_counts,
                                        counts, nhops, default_id, out_ids,
                                        out_w, out_t);
}

void* eg_get_full_neighbor(void* h, const uint64_t* ids, int n,
                           const int32_t* etypes, int net, int sorted) {
  return static_cast<Engine*>(h)->GetFullNeighbor(ids, n, etypes, net,
                                                  sorted != 0);
}

void eg_get_top_k_neighbor(void* h, const uint64_t* ids, int n,
                           const int32_t* etypes, int net, int k,
                           uint64_t default_id, uint64_t* out_ids,
                           float* out_w, int32_t* out_t) {
  static_cast<Engine*>(h)->GetTopKNeighbor(ids, n, etypes, net, k, default_id,
                                           out_ids, out_w, out_t);
}

// etypes_flat/etype_counts: per-step edge-type segments (walk_len segments).
void eg_random_walk(void* h, const uint64_t* ids, int n,
                    const int32_t* etypes_flat, const int32_t* etype_counts,
                    int walk_len, float p, float q, uint64_t default_id,
                    uint64_t* out) {
  static_cast<Engine*>(h)->RandomWalk(ids, n, etypes_flat, etype_counts,
                                      walk_len, p, q, default_id, out);
}

// ---- features ----
void eg_get_dense_feature(void* h, const uint64_t* ids, int n,
                          const int32_t* fids, const int32_t* dims, int nf,
                          float* out) {
  static_cast<Engine*>(h)->GetDenseFeature(ids, n, fids, dims, nf, out);
}

void eg_get_edge_dense_feature(void* h, const uint64_t* src,
                               const uint64_t* dst, const int32_t* types,
                               int n, const int32_t* fids,
                               const int32_t* dims, int nf, float* out) {
  static_cast<Engine*>(h)->GetEdgeDenseFeature(src, dst, types, n, fids, dims,
                                               nf, out);
}

void* eg_get_sparse_feature(void* h, const uint64_t* ids, int n,
                            const int32_t* fids, int nf) {
  return static_cast<Engine*>(h)->GetSparseFeature(ids, n, fids, nf);
}

void* eg_get_edge_sparse_feature(void* h, const uint64_t* src,
                                 const uint64_t* dst, const int32_t* types,
                                 int n, const int32_t* fids, int nf) {
  return static_cast<Engine*>(h)->GetEdgeSparseFeature(src, dst, types, n,
                                                       fids, nf);
}

void* eg_get_binary_feature(void* h, const uint64_t* ids, int n,
                            const int32_t* fids, int nf) {
  return static_cast<Engine*>(h)->GetBinaryFeature(ids, n, fids, nf);
}

void* eg_get_edge_binary_feature(void* h, const uint64_t* src,
                                 const uint64_t* dst, const int32_t* types,
                                 int n, const int32_t* fids, int nf) {
  return static_cast<Engine*>(h)->GetEdgeBinaryFeature(src, dst, types, n,
                                                       fids, nf);
}

// ---- result access ----
// kind: 0=u64, 1=f32, 2=i32, 3=bytes; slot indexes within that kind.
int64_t eg_result_size(void* r, int kind, int slot) {
  auto* res = static_cast<EGResult*>(r);
  switch (kind) {
    case 0:
      return slot < static_cast<int>(res->u64.size())
                 ? static_cast<int64_t>(res->u64[slot].size())
                 : -1;
    case 1:
      return slot < static_cast<int>(res->f32.size())
                 ? static_cast<int64_t>(res->f32[slot].size())
                 : -1;
    case 2:
      return slot < static_cast<int>(res->i32.size())
                 ? static_cast<int64_t>(res->i32[slot].size())
                 : -1;
    case 3:
      return slot < static_cast<int>(res->bytes.size())
                 ? static_cast<int64_t>(res->bytes[slot].size())
                 : -1;
    default:
      return -1;
  }
}

void eg_result_copy(void* r, int kind, int slot, void* out) {
  auto* res = static_cast<EGResult*>(r);
  switch (kind) {
    case 0:
      std::memcpy(out, res->u64[slot].data(),
                  res->u64[slot].size() * sizeof(uint64_t));
      break;
    case 1:
      std::memcpy(out, res->f32[slot].data(),
                  res->f32[slot].size() * sizeof(float));
      break;
    case 2:
      std::memcpy(out, res->i32[slot].data(),
                  res->i32[slot].size() * sizeof(int32_t));
      break;
    case 3:
      std::memcpy(out, res->bytes[slot].data(), res->bytes[slot].size());
      break;
  }
}

void eg_result_free(void* r) { delete static_cast<EGResult*>(r); }

}  // extern "C"
