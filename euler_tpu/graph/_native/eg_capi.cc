// C ABI for the euler_tpu graph engine, consumed from Python via ctypes.
//
// Role equivalent to the reference's ctypes surface
// (reference tf_euler/utils/create_graph.cc:47 CreateGraph and
// euler/service/python_api.cc StartService), generalized to a handle-based
// batch API: fixed-shape calls write into caller-allocated numpy buffers;
// variable-shape calls return an EGResult handle the caller drains and frees.
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "eg_blackbox.h"
#include "eg_devprof.h"
#include "eg_engine.h"
#include "eg_epoch.h"
#include "eg_fault.h"
#include "eg_heat.h"
#include "eg_phase.h"
#include "eg_registry.h"
#include "eg_sampling.h"
#include "eg_stats.h"
#include "eg_telemetry.h"
#include "eg_remote.h"
#include "eg_service.h"

using eg::EGResult;
using eg::Engine;
using eg::GraphAPI;
using eg::RegistryList;
using eg::RegistryServer;
using eg::RemoteGraph;
using eg::Service;

namespace {
thread_local std::string g_last_error;

inline GraphAPI* API(void* h) { return static_cast<GraphAPI*>(h); }
inline Engine* Local(void* h) { return static_cast<Engine*>(API(h)); }
}  // namespace

// Exception barrier for the C ABI (eg-lint rule abi-barrier): an exception
// unwinding past extern "C" into ctypes frames is std::terminate (SIGABRT)
// for the host Python process, so every entry point runs its body inside
//   try { ... } EG_API_GUARD(<sentinel>)
// and failures land in g_last_error + the sentinel return instead.
#define EG_API_GUARD(...)                      \
  catch (const std::exception& ex) {           \
    g_last_error = ex.what();                  \
    return __VA_ARGS__;                        \
  } catch (...) {                              \
    g_last_error = "unknown native exception"; \
    return __VA_ARGS__;                        \
  }

extern "C" {

// eg-lint: allow(abi-barrier) the error reporter itself: returns a
// thread_local buffer, cannot throw, and must never clobber the error state
const char* eg_last_error() { return g_last_error.c_str(); }

void* eg_create() {
  try {
    return static_cast<GraphAPI*>(new Engine());
  }
  EG_API_GUARD(nullptr)
}

void eg_destroy(void* h) {
  try {
    delete API(h);
  }
  EG_API_GUARD()
}

int eg_load(void* h, const char* dir, int shard_idx, int shard_num) {
  auto* e = Local(h);
  try {
    if (!e->Load(dir, shard_idx, shard_num)) {
      g_last_error = e->error();
      return -1;
    }
  } catch (const std::exception& ex) {
    // corrupt input must surface as a Python error, never cross the C
    // ABI as an exception (std::terminate -> SIGABRT)
    g_last_error = std::string("graph load failed: ") + ex.what();
    return -1;
  }
  return 0;
}

int eg_load_files(void* h, const char** files, int nfiles) {
  auto* e = Local(h);
  try {
    std::vector<std::string> fs(files, files + nfiles);
    if (!e->LoadFiles(std::move(fs))) {
      g_last_error = e->error();
      return -1;
    }
  } catch (const std::exception& ex) {
    g_last_error = std::string("graph load failed: ") + ex.what();
    return -1;
  }
  return 0;
}

// Streaming ingest: partition bytes fetched by the caller (e.g. off an
// object store) parse straight into the store — no local staging file.
// The buffers only need to live for the duration of this call.
int eg_load_buffers(void* h, const void* const* bufs, const uint64_t* lens,
                    const char* const* names, int n) {
  auto* e = Local(h);
  try {
    std::vector<size_t> sz(n);
    for (int i = 0; i < n; ++i) sz[i] = static_cast<size_t>(lens[i]);
    if (!e->LoadBuffers(reinterpret_cast<const char* const*>(bufs),
                        sz.data(), names, n)) {
      g_last_error = e->error();
      return -1;
    }
  } catch (const std::exception& ex) {
    g_last_error = std::string("graph load failed: ") + ex.what();
    return -1;
  }
  return 0;
}

// ---- snapshot epochs (eg_epoch.h; FAULTS.md "Graph refresh") ----
// Apply `<prefix>.delta.<n>` files to an embedded (local) graph:
// `paths` is ';'-joined; the engine rebuilds base + all deltas into a
// fresh immutable snapshot and adopts it in place (handle identity
// stable, epoch advances to the delta count). Remote handles must use
// eg_remote_load_delta — the Python layer enforces the split. -1 +
// eg_last_error on parse/validation/merge failure (the serving snapshot
// is untouched).
int eg_load_deltas(void* h, const char* paths) {
  auto* e = Local(h);
  try {
    std::vector<std::string> ps;
    std::string joined = paths ? paths : "";
    size_t pos = 0;
    while (pos <= joined.size()) {
      size_t semi = joined.find(';', pos);
      if (semi == std::string::npos) semi = joined.size();
      if (semi > pos) ps.emplace_back(joined.substr(pos, semi - pos));
      pos = semi + 1;
    }
    if (ps.empty()) {
      g_last_error = "load_deltas: no delta paths given";
      return -1;
    }
    std::string err;
    if (!eg::LoadEngineWithDeltas(e, e->source_files(), ps, &err)) {
      // same ledger entry as Service::LoadDelta refusals: the operator
      // watches ONE counter for refused deltas on any leg (FAULTS.md)
      eg::Counters::Global().Add(eg::kCtrDeltaLoadFail);
      g_last_error = err;
      return -1;
    }
    return 0;
  }
  EG_API_GUARD(-1)
}

// Serving epoch of the handle: a local engine reports the epoch its
// current snapshot was built at (0 = base load, N = after N deltas); a
// remote graph reports the max epoch announced by any shard so far
// (passively learned from v4 reply stamps and registry heartbeats).
uint64_t eg_graph_epoch(void* h) {
  try {
    return API(h)->Epoch();
  }
  EG_API_GUARD(0)
}

void eg_seed(uint64_t seed) {
  try {
    eg::SeedThreadRng(seed);
  }
  EG_API_GUARD()
}

// ---- remote mode (Graph::NewGraph(mode=Remote) equivalent,
// reference euler/client/graph.cc:157-185) ----
// Config: "registry=<dir>" or "shards=h:p|h:p,..." (+ retries/timeout_ms/
// quarantine_ms). Returns a handle usable with every query function below,
// or nullptr (see eg_last_error). A config that fails to parse (e.g.
// "retries=x", std::stoi throws) lands in the guard, not std::terminate.
void* eg_remote_create(const char* config) {
  try {
    auto g = std::make_unique<RemoteGraph>();
    if (!g->Init(config ? config : "")) {
      g_last_error = g->error();
      return nullptr;
    }
    return static_cast<GraphAPI*>(g.release());
  }
  EG_API_GUARD(nullptr)
}

int eg_remote_shards(void* h) {
  try {
    return static_cast<RemoteGraph*>(API(h))->num_shards();
  }
  EG_API_GUARD(-1)
}
int eg_remote_partitions(void* h) {
  try {
    return static_cast<RemoteGraph*>(API(h))->num_partitions();
  }
  EG_API_GUARD(-1)
}
// Current replica count of one shard's pool — observability for the
// mid-run re-discovery path (and its tests).
int eg_remote_replica_count(void* h, int shard) {
  try {
    return static_cast<int>(
        static_cast<RemoteGraph*>(API(h))->num_replicas(shard));
  }
  EG_API_GUARD(-1)
}
// 1 when the remote graph routes ids through a placement map fetched at
// init (kPlacement), 0 when it hash-routes (old server / hash-sharded
// data / placement=0) — observability for the locality A/B and the
// compat tests.
int eg_remote_has_placement(void* h) {
  try {
    return static_cast<RemoteGraph*>(API(h))->has_placement() ? 1 : 0;
  }
  EG_API_GUARD(-1)
}
// Resolve the serving shard of each id through the client's ACTUAL
// routing (placement map when loaded, hash fallback otherwise) — the
// edge-cut instrument scripts/heat_dump.py measures locality with must
// see the same routing the data plane uses, not re-derive the hash rule.
void eg_remote_route(void* h, const uint64_t* ids, int n, int32_t* out) {
  try {
    static_cast<RemoteGraph*>(API(h))->RouteShards(ids, n, out);
  }
  EG_API_GUARD()
}
// Pending strict-mode failure of a remote graph (strict=1 config key):
// copies the first recorded message into buf (NUL-terminated, truncated
// to cap) and clears it, returning 1; 0 when nothing is pending. The
// fixed-shape query entry points return void, so a shard that failed
// after every transport retry surfaces here — the Python client polls
// this after each remote call and raises instead of training on the
// default-filled rows.
int eg_remote_strict_error(void* h, char* buf, int cap) {
  try {
    std::string err = static_cast<RemoteGraph*>(API(h))->TakeStrictError();
    if (err.empty()) return 0;
    if (cap > 0) {
      size_t m = std::min(err.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, err.data(), m);
      buf[m] = '\0';
    }
    return 1;
  }
  EG_API_GUARD(-1)
}

// Last epoch announced by one shard (0 = never flipped or unknown) —
// the per-shard view behind eg_graph_epoch's max, for the drill script
// and metrics_dump's per-shard epoch column.
uint64_t eg_remote_epoch(void* h, int shard) {
  try {
    return static_cast<RemoteGraph*>(API(h))->ShardEpoch(shard);
  }
  EG_API_GUARD(0)
}
// The client's cache generation: bumped once per observed epoch raise
// on any shard. Python-side caches (serving/microbatch.py) key their
// entries by this exactly like the native feature/neighbor caches.
uint64_t eg_remote_cache_gen(void* h) {
  try {
    return static_cast<RemoteGraph*>(API(h))->cache_gen();
  }
  EG_API_GUARD(0)
}
// Ask shard `shard` to merge delta file `path` (a path on the SHARD's
// filesystem) and flip its serving epoch (kLoadDelta). Returns the new
// epoch (>= 1), or -1 with the shard's own error message in
// eg_last_error (the shard keeps serving its old snapshot on failure).
int64_t eg_remote_load_delta(void* h, int shard, const char* path) {
  try {
    uint64_t ep = 0;
    std::string err;
    if (!static_cast<RemoteGraph*>(API(h))->LoadDelta(
            shard, path ? path : "", &ep, &err)) {
      g_last_error = err.empty() ? "load_delta failed" : err;
      return -1;
    }
    return static_cast<int64_t>(ep);
  }
  EG_API_GUARD(-1)
}

// ---- async whole-step sampling (remote graphs only) ----
// Submit one whole SampleFanout as an in-flight async op on the remote
// client's dispatcher pool: the hop chain runs as completion
// continuations (hop h+1's shard jobs are enqueued by hop h's last
// completing worker), so the calling thread returns immediately and the
// depth-N step pipeline (euler_tpu/parallel/prefetch.py pipeline(),
// `sampler_depth=`) can overlap steps k+1..k+N's sampling with step k's
// H2D + device compute. Same argument shape as eg_sample_fanout; the
// out_* buffers must stay pinned until eg_remote_async_take returns
// (graph.py's handle object owns the numpy arrays). Returns a slot
// handle >= 0, or -1 when the op pool is full / the handle is not a
// remote graph — callers fall back to the sync eg_sample_fanout.
int eg_remote_sample_async(void* h, const uint64_t* ids, int n,
                           const int32_t* etypes_flat,
                           const int32_t* etype_counts,
                           const int32_t* counts, int nhops,
                           uint64_t default_id, uint64_t** out_ids,
                           float** out_w, int32_t** out_t) {
  try {
    return static_cast<RemoteGraph*>(API(h))->SampleFanoutAsync(
        ids, n, etypes_flat, etype_counts, counts, nhops, default_id,
        out_ids, out_w, out_t);
  }
  EG_API_GUARD(-1)
}
// 1 = op complete (take will not block), 0 = still running, -1 = bad or
// free slot. Non-blocking — the pipeline driver polls this to finish
// steps in submission order without stalling the submit side.
int eg_remote_async_poll(void* h, int slot) {
  try {
    return static_cast<RemoteGraph*>(API(h))->PollAsync(slot);
  }
  EG_API_GUARD(-1)
}
// Block until the op completes, then recycle its slot (0; -1 on a bad
// or free slot). After this returns the out_* buffers hold the step's
// sample; shard failures inside the op degraded exactly like the sync
// path (default rows + rpc_errors, and under strict= the pending
// eg_remote_strict_error the Python client polls after the take).
int eg_remote_async_take(void* h, int slot) {
  try {
    return static_cast<RemoteGraph*>(API(h))->TakeAsync(slot);
  }
  EG_API_GUARD(-1)
}

// ---- graph service (StartService equivalent,
// reference euler/service/python_api.cc:26-52) ----
// `options` is the "k=v;k=v" admission spec (workers/pending/max_conns/
// io_timeout_ms/idle_timeout_ms/linger_ms/drain_ms/wire_version/
// telemetry/slow_spans/blackbox/postmortem_dir — see eg_admission.h);
// NULL/empty = defaults. Unknown keys fail loudly.
void* eg_service_start(const char* data_dir, int shard_idx, int shard_num,
                       const char* host, int port, const char* registry_dir,
                       const char* options) {
  try {
    auto s = std::make_unique<Service>();
    if (!s->Start(data_dir, shard_idx, shard_num, host ? host : "",
                  port, registry_dir ? registry_dir : "",
                  options ? options : "")) {
      g_last_error = s->error();
      return nullptr;
    }
    return s.release();
  }
  EG_API_GUARD(nullptr)
}

int eg_service_port(void* s) {
  try {
    return static_cast<Service*>(s)->port();
  }
  EG_API_GUARD(-1)
}

// Drain-before-stop (the SIGTERM half of a rolling restart, DEPLOY.md):
// deregister from discovery, stop accepting, let in-flight requests
// finish (up to grace_ms; <=0 = the service's drain_ms option), close
// every connection. The handle stays valid; call eg_service_stop to
// free it.
void eg_service_drain(void* s, int grace_ms) {
  try {
    static_cast<Service*>(s)->Drain(grace_ms > 0 ? grace_ms : -1);
  }
  EG_API_GUARD()
}

// In-process delta load + epoch flip (the embedded-service twin of the
// kLoadDelta wire op; service.py --load_delta and the drill script use
// the wire path). Returns the new epoch, -1 + eg_last_error on failure.
int64_t eg_service_load_delta(void* s, const char* path) {
  try {
    uint64_t ep = 0;
    std::string err;
    if (!static_cast<Service*>(s)->LoadDelta(path ? path : "", &ep,
                                             &err)) {
      g_last_error = err.empty() ? "load_delta failed" : err;
      return -1;
    }
    return static_cast<int64_t>(ep);
  }
  EG_API_GUARD(-1)
}

// Current serving epoch of an in-process service (0 until first flip).
uint64_t eg_service_epoch(void* s) {
  try {
    return static_cast<Service*>(s)->epoch();
  }
  EG_API_GUARD(0)
}

void eg_service_stop(void* s) {
  try {
    delete static_cast<Service*>(s);
  }
  EG_API_GUARD()
}

// ---- TCP shard registry (ZooKeeper discovery equivalent,
// reference euler/common/zk_server_register.cc + zk_server_monitor.cc) ----
void* eg_registry_start(const char* host, int port, int ttl_ms) {
  try {
    auto r = std::make_unique<RegistryServer>();
    if (!r->Start(host ? host : "", port, ttl_ms)) {
      g_last_error = r->error();
      return nullptr;
    }
    return r.release();
  }
  EG_API_GUARD(nullptr)
}

int eg_registry_port(void* r) {
  try {
    return static_cast<RegistryServer*>(r)->port();
  }
  EG_API_GUARD(-1)
}

void eg_registry_stop(void* r) {
  try {
    delete static_cast<RegistryServer*>(r);
  }
  EG_API_GUARD()
}

// LIST a registry at host:port into caller-supplied buf as
// "<shard> <host>:<port>\n" lines. Returns bytes written, or -1 when the
// registry is unreachable. A listing larger than cap is truncated at the
// last complete line (never mid-entry, so the result always parses).
int eg_registry_query(const char* host, int port, int timeout_ms, char* buf,
                      int cap) {
  try {
    std::map<int, std::vector<std::string>> listed;
    if (!RegistryList(host ? host : "127.0.0.1", port, timeout_ms, &listed))
      return -1;
    std::string out;
    for (auto& [shard, addrs] : listed)
      for (auto& a : addrs)
        out += std::to_string(shard) + " " + a + "\n";
    size_t n = out.size();
    if (n > static_cast<size_t>(cap)) {
      size_t nl = out.rfind('\n', static_cast<size_t>(cap) - 1);
      n = nl == std::string::npos ? 0 : nl + 1;
    }
    if (n > 0) memcpy(buf, out.data(), n);
    return static_cast<int>(n);
  }
  EG_API_GUARD(-1)
}

// ---- introspection ----
int64_t eg_num_nodes(void* h) {
  try {
    return API(h)->NumNodes();
  }
  EG_API_GUARD(-1)
}
int64_t eg_num_edges(void* h) {
  try {
    return API(h)->NumEdges();
  }
  EG_API_GUARD(-1)
}
int32_t eg_node_type_num(void* h) {
  try {
    return API(h)->NodeTypeNum();
  }
  EG_API_GUARD(-1)
}
int32_t eg_edge_type_num(void* h) {
  try {
    return API(h)->EdgeTypeNum();
  }
  EG_API_GUARD(-1)
}
// kind: 0=node u64, 1=node f32, 2=node binary, 3=edge u64, 4=edge f32,
// 5=edge binary.
int32_t eg_feature_num(void* h, int kind) {
  try {
    return API(h)->FeatureNum(kind);
  }
  EG_API_GUARD(-1)
}
// Per-type weight sums for cross-shard weighted sampling; out has
// node_type_num (kind 0) or edge_type_num (kind 1) floats.
void eg_type_weight_sums(void* h, int kind, float* out) {
  try {
    API(h)->TypeWeightSums(kind, out);
  }
  EG_API_GUARD()
}

// ---- sampling ----
void eg_sample_node(void* h, int count, int32_t type, uint64_t* out) {
  try {
    eg::SpanTimer span(eg::kStatSampleNode);
    API(h)->SampleNode(count, type, out);
  }
  EG_API_GUARD()
}

void eg_sample_edge(void* h, int count, int32_t type, uint64_t* out_src,
                    uint64_t* out_dst, int32_t* out_type) {
  try {
    eg::SpanTimer span(eg::kStatSampleEdge);
    API(h)->SampleEdge(count, type, out_src, out_dst, out_type);
  }
  EG_API_GUARD()
}

void eg_sample_node_with_src(void* h, const uint64_t* src, int n, int count,
                             uint64_t* out) {
  try {
    eg::SpanTimer span(eg::kStatSampleNode);
    API(h)->SampleNodeWithSrc(src, n, count, out);
  }
  EG_API_GUARD()
}

// Per-node sampling weights for the device-graph exporter; works in both
// modes (remote scatters a kNodeWeight RPC per shard). Returns 0 on
// success, -1 when any shard could not answer (the exporter must not
// build a sampler from silently-zero weights).
int eg_get_node_weight(void* h, const uint64_t* ids, int n, float* out) {
  try {
    if (API(h)->GetNodeWeight(ids, n, out)) return 0;
    g_last_error = "node_weights: one or more shards unreachable";
    return -1;
  }
  EG_API_GUARD(-1)
}

void eg_get_node_type(void* h, const uint64_t* ids, int n, int32_t* out) {
  try {
    eg::SpanTimer span(eg::kStatNodeType);
    API(h)->GetNodeType(ids, n, out);
  }
  EG_API_GUARD()
}

void eg_sample_neighbor(void* h, const uint64_t* ids, int n,
                        const int32_t* etypes, int net, int count,
                        uint64_t default_id, uint64_t* out_ids, float* out_w,
                        int32_t* out_t) {
  try {
    eg::SpanTimer span(eg::kStatSampleNeighbor);
    API(h)->SampleNeighbor(ids, n, etypes, net, count, default_id, out_ids,
                           out_w, out_t);
  }
  EG_API_GUARD()
}

// etypes_flat: concatenated per-hop edge-type lists; etype_counts[h] =
// number of edge types for hop h; counts[h] = fanout of hop h.
// out_*: per-hop caller buffers, hop h sized n * prod(counts[:h+1]).
void eg_sample_fanout(void* h, const uint64_t* ids, int n,
                      const int32_t* etypes_flat, const int32_t* etype_counts,
                      const int32_t* counts, int nhops, uint64_t default_id,
                      uint64_t** out_ids, float** out_w, int32_t** out_t) {
  try {
    eg::SpanTimer span(eg::kStatSampleFanout);
    API(h)->SampleFanout(ids, n, etypes_flat, etype_counts, counts, nhops,
                         default_id, out_ids, out_w, out_t);
  }
  EG_API_GUARD()
}

// Flat-CSR alias-table build for the device-side exact sampler (pure
// function, no engine handle): offsets [num_rows+1], weights/prob
// [offsets[num_rows]], alias row-LOCAL int32 indices. See
// eg::BuildAliasRows.
void eg_build_alias_csr(const int64_t* offsets, int64_t num_rows,
                        const float* weights, float* prob, int32_t* alias) {
  try {
    eg::BuildAliasRows(offsets, num_rows, weights, prob, alias);
  }
  EG_API_GUARD()
}

void* eg_get_full_neighbor(void* h, const uint64_t* ids, int n,
                           const int32_t* etypes, int net, int sorted) {
  try {
    eg::SpanTimer span(eg::kStatFullNeighbor);
    return API(h)->GetFullNeighbor(ids, n, etypes, net, sorted != 0);
  }
  EG_API_GUARD(nullptr)
}

void eg_get_top_k_neighbor(void* h, const uint64_t* ids, int n,
                           const int32_t* etypes, int net, int k,
                           uint64_t default_id, uint64_t* out_ids,
                           float* out_w, int32_t* out_t) {
  try {
    eg::SpanTimer span(eg::kStatTopKNeighbor);
    API(h)->GetTopKNeighbor(ids, n, etypes, net, k, default_id, out_ids,
                            out_w, out_t);
  }
  EG_API_GUARD()
}

// etypes_flat/etype_counts: per-step edge-type segments (walk_len segments).
void eg_random_walk(void* h, const uint64_t* ids, int n,
                    const int32_t* etypes_flat, const int32_t* etype_counts,
                    int walk_len, float p, float q, uint64_t default_id,
                    uint64_t* out) {
  try {
    eg::SpanTimer span(eg::kStatRandomWalk);
    API(h)->RandomWalk(ids, n, etypes_flat, etype_counts, walk_len, p, q,
                       default_id, out);
  }
  EG_API_GUARD()
}

// ---- features ----
void eg_get_dense_feature(void* h, const uint64_t* ids, int n,
                          const int32_t* fids, const int32_t* dims, int nf,
                          float* out) {
  try {
    eg::SpanTimer span(eg::kStatDenseFeature);
    API(h)->GetDenseFeature(ids, n, fids, dims, nf, out);
  }
  EG_API_GUARD()
}

void eg_get_edge_dense_feature(void* h, const uint64_t* src,
                               const uint64_t* dst, const int32_t* types,
                               int n, const int32_t* fids,
                               const int32_t* dims, int nf, float* out) {
  try {
    eg::SpanTimer span(eg::kStatDenseFeature);
    API(h)->GetEdgeDenseFeature(src, dst, types, n, fids, dims, nf, out);
  }
  EG_API_GUARD()
}

void* eg_get_sparse_feature(void* h, const uint64_t* ids, int n,
                            const int32_t* fids, int nf) {
  try {
    eg::SpanTimer span(eg::kStatSparseFeature);
    return API(h)->GetSparseFeature(ids, n, fids, nf);
  }
  EG_API_GUARD(nullptr)
}

void* eg_get_edge_sparse_feature(void* h, const uint64_t* src,
                                 const uint64_t* dst, const int32_t* types,
                                 int n, const int32_t* fids, int nf) {
  try {
    eg::SpanTimer span(eg::kStatSparseFeature);
    return API(h)->GetEdgeSparseFeature(src, dst, types, n, fids, nf);
  }
  EG_API_GUARD(nullptr)
}

void* eg_get_binary_feature(void* h, const uint64_t* ids, int n,
                            const int32_t* fids, int nf) {
  try {
    eg::SpanTimer span(eg::kStatBinaryFeature);
    return API(h)->GetBinaryFeature(ids, n, fids, nf);
  }
  EG_API_GUARD(nullptr)
}

void* eg_get_edge_binary_feature(void* h, const uint64_t* src,
                                 const uint64_t* dst, const int32_t* types,
                                 int n, const int32_t* fids, int nf) {
  try {
    eg::SpanTimer span(eg::kStatBinaryFeature);
    return API(h)->GetEdgeBinaryFeature(src, dst, types, n, fids, nf);
  }
  EG_API_GUARD(nullptr)
}

// ---- result access ----
// kind: 0=u64, 1=f32, 2=i32, 3=bytes; slot indexes within that kind.
int64_t eg_result_size(void* r, int kind, int slot) {
  try {
    auto* res = static_cast<EGResult*>(r);
    switch (kind) {
      case 0:
        return slot < static_cast<int>(res->u64.size())
                   ? static_cast<int64_t>(res->u64[slot].size())
                   : -1;
      case 1:
        return slot < static_cast<int>(res->f32.size())
                   ? static_cast<int64_t>(res->f32[slot].size())
                   : -1;
      case 2:
        return slot < static_cast<int>(res->i32.size())
                   ? static_cast<int64_t>(res->i32[slot].size())
                   : -1;
      case 3:
        return slot < static_cast<int>(res->bytes.size())
                   ? static_cast<int64_t>(res->bytes[slot].size())
                   : -1;
      default:
        return -1;
    }
  }
  EG_API_GUARD(-1)
}

void eg_result_copy(void* r, int kind, int slot, void* out) {
  try {
    auto* res = static_cast<EGResult*>(r);
    switch (kind) {
      case 0:
        std::memcpy(out, res->u64[slot].data(),
                    res->u64[slot].size() * sizeof(uint64_t));
        break;
      case 1:
        std::memcpy(out, res->f32[slot].data(),
                    res->f32[slot].size() * sizeof(float));
        break;
      case 2:
        std::memcpy(out, res->i32[slot].data(),
                    res->i32[slot].size() * sizeof(int32_t));
        break;
      case 3:
        std::memcpy(out, res->bytes[slot].data(), res->bytes[slot].size());
        break;
    }
  }
  EG_API_GUARD()
}

void eg_result_free(void* r) {
  try {
    delete static_cast<EGResult*>(r);
  }
  EG_API_GUARD()
}


// ---- stats (span-timer subsystem, eg_stats.h) ----
int eg_stat_count() {
  try {
    return eg::kStatOpCount;
  }
  EG_API_GUARD(0)
}

const char* eg_stat_name(int i) {
  try {
    return (i >= 0 && i < eg::kStatOpCount) ? eg::kStatNames[i] : "";
  }
  EG_API_GUARD("")
}

// out arrays sized eg_stat_count().
void eg_stats_snapshot(uint64_t* counts, uint64_t* total_ns,
                       uint64_t* max_ns) {
  try {
    eg::Stats::Global().Snapshot(counts, total_ns, max_ns);
  }
  EG_API_GUARD()
}

void eg_stats_reset() {
  try {
    eg::Stats::Global().Reset();
  }
  EG_API_GUARD()
}

// ---- failure counters (eg_stats.h Counters: transport retries,
// quarantines, failovers, deadline aborts, rejected frames, ...) ----
int eg_counter_count() {
  try {
    return eg::kCtrCount;
  }
  EG_API_GUARD(0)
}

const char* eg_counter_name(int i) {
  try {
    return (i >= 0 && i < eg::kCtrCount) ? eg::kCounterNames[i] : "";
  }
  EG_API_GUARD("")
}

// out sized eg_counter_count().
void eg_counters_snapshot(uint64_t* out) {
  try {
    eg::Counters::Global().Snapshot(out);
  }
  EG_API_GUARD()
}

void eg_counters_reset() {
  try {
    eg::Counters::Global().Reset();
  }
  EG_API_GUARD()
}

// Bump one counter from Python (the prefetch pipeline runs in Python
// threads but its ledger must live next to the native transport's so
// one snapshot/scrape covers both). Out-of-range ids are ignored.
void eg_counter_add(int i, uint64_t n) {
  try {
    if (i >= 0 && i < eg::kCtrCount)
      eg::Counters::Global().Add(static_cast<eg::CounterId>(i), n);
  }
  EG_API_GUARD()
}

// ---- telemetry (eg_telemetry.h: latency histograms, slow-span
// journals, the STATS scrape — see OBSERVABILITY.md) ----
int eg_telemetry_enabled() {
  try {
    return eg::Telemetry::Global().enabled() ? 1 : 0;
  }
  EG_API_GUARD(-1)
}

void eg_telemetry_set_enabled(int on) {
  try {
    eg::Telemetry::Global().SetEnabled(on != 0);
  }
  EG_API_GUARD()
}

// Zero histograms (latency AND step-phase) + the slow-span journal +
// the data-plane heat state (enabled flags and capacities survive —
// this is the clean-slate primitive tests use).
void eg_telemetry_reset() {
  try {
    eg::Telemetry::Global().Reset();
    eg::PhaseStats::Global().Reset();
    eg::Heat::Global().Reset();
    eg::Devprof::Global().Reset();
  }
  EG_API_GUARD()
}

// ---- step-phase profiler (eg_phase.h; OBSERVABILITY.md "Step
// phases") ----
// One µs sample for phase `phase` (eg::StepPhase order, mirrored by
// euler_tpu/telemetry.py PHASES). Honors the telemetry kill-switch.
// Also lands in the flight recorder (eg_blackbox.h, its own
// kill-switch): a postmortem of a dead TRAINER shows which step phase
// it died in, not just which RPCs were in flight.
void eg_phase_record(int phase, uint64_t us) {
  try {
    eg::PhaseStats::Global().Record(phase, us);
    eg::Blackbox::Global().Record(eg::kBbPhase,
                                  static_cast<uint8_t>(phase & 0xFF), -1,
                                  0, us, 0);
  }
  EG_API_GUARD()
}

// One dimensionless prefetch-pipeline sample: which 0 = queue depth at
// dequeue, 1 = workers busy at dequeue (eg::PrefetchGauge order).
void eg_phase_gauge(int which, uint64_t value) {
  try {
    eg::PhaseStats::Global().RecordGauge(which, value);
  }
  EG_API_GUARD()
}

// One µs sample for serve-request phase `phase` (eg::ServePhase order,
// mirrored by euler_tpu/telemetry.py SERVE_PHASES). Honors the
// telemetry kill-switch; lands in the same "hist" map as everything
// else (keys "serve:<name>"), so every scrape surface picks it up.
void eg_serve_record(int phase, uint64_t us) {
  try {
    eg::PhaseStats::Global().RecordServe(phase, us);
  }
  EG_API_GUARD()
}

// One micro-batch device dispatch: `ids` = unique ids in the batch
// (the "serve_batch" value histogram — count is dispatches, sum is
// ids, their ratio the coalescing factor).
void eg_serve_batch(uint64_t ids) {
  try {
    eg::PhaseStats::Global().RecordServeBatch(ids);
  }
  EG_API_GUARD()
}

// ---- device-plane gauges (eg_devprof.h; OBSERVABILITY.md "Device
// plane") ----
// Refresh the device-memory gauges: euler_tpu/devprof.py samples
// device.memory_stats() (or a live-array census on CPU) and pushes the
// result here so blackbox resource rings, postmortems and every metrics
// surface see device bytes with zero new plumbing.
void eg_devprof_set_mem(int64_t bytes, int64_t buffers) {
  try {
    eg::Devprof::Global().SetMem(bytes, buffers);
  }
  EG_API_GUARD()
}

// Refresh the live serve-SLO gauges (µs): euler_tpu/serving/slo.py
// pushes its windowed p50/p99 and lifetime violations every few
// records, so a scrape reads serving latency without draining.
void eg_serve_slo_set(uint64_t p50_us, uint64_t p99_us,
                      uint64_t violations, uint64_t count) {
  try {
    eg::Devprof::Global().SetServeSlo(p50_us, p99_us, violations, count);
  }
  EG_API_GUARD()
}

void eg_telemetry_set_slow_capacity(int n) {
  try {
    eg::Telemetry::Global().SetSlowCapacity(n);
  }
  EG_API_GUARD()
}

// Local telemetry dump as JSON (counters + stats + histograms + slow
// spans; no admission gauges — those belong to a serving process and
// ride the STATS scrape). Writes up to cap-1 bytes + NUL into buf and
// returns the FULL length needed, so a caller seeing ret >= cap simply
// retries with a bigger buffer. -1 on failure.
int eg_telemetry_json(char* buf, int cap) {
  try {
    std::string js = eg::Telemetry::Global().Json(-1, nullptr);
    if (cap > 0) {
      size_t m = std::min(js.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, js.data(), m);
      buf[m] = '\0';
    }
    return static_cast<int>(js.size());
  }
  EG_API_GUARD(-1)
}

// The span-record primitive the native sites use, exposed so Python can
// journal app-level spans (run_loop step phases) and tests can pin the
// journal's eviction order with exact microsecond values.
void eg_telemetry_record_span(int side, int op, int outcome, int shard,
                              uint64_t trace, uint64_t queue_us,
                              uint64_t handler_us, uint64_t wire_us,
                              uint64_t total_us) {
  try {
    eg::TelemetrySpan s;
    s.side = side ? eg::kSpanServer : eg::kSpanClient;
    s.op = op >= 0 && op < eg::kHistOpSlots ? static_cast<uint8_t>(op) : 0;
    s.outcome = outcome >= 0 && outcome < 6 ? static_cast<uint8_t>(outcome)
                                            : 1;
    s.shard = shard;
    s.trace = trace;
    s.queue_us = queue_us;
    s.handler_us = handler_us;
    s.wire_us = wire_us;
    s.total_us = total_us;
    eg::Telemetry::Global().RecordSpan(s);
  }
  EG_API_GUARD()
}

// Remote liveness probe: one kPing round trip to shard `shard` through
// the full transport stack (retries/deadline/wire negotiation per the
// graph's config). 1 = shard answered, 0 = unreachable or bad index.
int eg_remote_ping(void* h, int shard) {
  try {
    return static_cast<RemoteGraph*>(API(h))->PingShard(shard) ? 1 : 0;
  }
  EG_API_GUARD(0)
}

// Remote scrape: fetch shard `shard`'s telemetry JSON over the STATS
// wire opcode (retries/deadline per the graph's transport config). Same
// buf/cap/return contract as eg_telemetry_json; -1 on transport failure
// or bad shard index (see eg_last_error).
int eg_remote_scrape(void* h, int shard, char* buf, int cap) {
  try {
    std::string js;
    if (!static_cast<RemoteGraph*>(API(h))->ScrapeShard(shard, &js)) {
      g_last_error = "telemetry scrape failed: shard " +
                     std::to_string(shard) + " unreachable or invalid";
      return -1;
    }
    if (cap > 0) {
      size_t m = std::min(js.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, js.data(), m);
      buf[m] = '\0';
    }
    return static_cast<int>(js.size());
  }
  EG_API_GUARD(-1)
}

// ---- data-plane heat profiler (eg_heat.h; OBSERVABILITY.md
// "Data-plane heat") ----
int eg_heat_enabled() {
  try {
    return eg::Heat::Global().flag() ? 1 : 0;
  }
  EG_API_GUARD(-1)
}

void eg_heat_set_enabled(int on) {
  try {
    eg::Heat::Global().SetEnabled(on != 0);
  }
  EG_API_GUARD()
}

// Resize (and reset) the hot-key tracker (`heat_topk=` config key).
void eg_heat_set_topk(int k) {
  try {
    eg::Heat::Global().SetTopK(k);
  }
  EG_API_GUARD()
}

// Feed a batch of ids from Python (app-level access streams, and the
// exactness tests that pin the sketch against ground truth). side:
// 0 = client, 1 = server; op indexes kWireOpNames (0 = other).
void eg_heat_record(int side, int op, const uint64_t* ids, int64_t n) {
  try {
    eg::Heat::Global().Record(side, op, ids, n);
  }
  EG_API_GUARD()
}

// Count-min point estimate for one id (>= its true feed count).
uint64_t eg_heat_estimate(int side, uint64_t id) {
  try {
    return eg::Heat::Global().Estimate(side, id);
  }
  EG_API_GUARD(0)
}

// Local heat dump as JSON (top-K tables, sketch totals, per-op ids
// ledger, fan-out attribution, cache classes). Same buf/cap/return
// contract as eg_telemetry_json.
int eg_heat_json(char* buf, int cap) {
  try {
    std::string js = eg::Heat::Global().Json(-1);
    if (cap > 0) {
      size_t m = std::min(js.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, js.data(), m);
      buf[m] = '\0';
    }
    return static_cast<int>(js.size());
  }
  EG_API_GUARD(-1)
}

// Zero the heat state (enabled flag + top-K capacity survive).
void eg_heat_reset() {
  try {
    eg::Heat::Global().Reset();
  }
  EG_API_GUARD()
}

// Remote heat scrape (kHeat opcode): fetch shard `shard`'s full heat
// dump. Same buf/cap/return contract as eg_remote_scrape; -1 on
// transport failure or bad shard index.
int eg_remote_heat(void* h, int shard, char* buf, int cap) {
  try {
    std::string js;
    if (!static_cast<RemoteGraph*>(API(h))->HeatShard(shard, &js)) {
      g_last_error = "heat scrape failed: shard " + std::to_string(shard) +
                     " unreachable or invalid";
      return -1;
    }
    if (cap > 0) {
      size_t m = std::min(js.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, js.data(), m);
      buf[m] = '\0';
    }
    return static_cast<int>(js.size());
  }
  EG_API_GUARD(-1)
}

// ---- blackbox flight recorder + postmortem path (eg_blackbox.h;
// OBSERVABILITY.md "Postmortems") ----
int eg_blackbox_enabled() {
  try {
    return eg::Blackbox::Global().enabled() ? 1 : 0;
  }
  EG_API_GUARD(-1)
}

void eg_blackbox_set_enabled(int on) {
  try {
    eg::Blackbox::Global().SetEnabled(on != 0);
  }
  EG_API_GUARD()
}

// Arm the postmortem path: remember postmortem_dir (empty/NULL = leave
// the dump destination alone), label dumps with `shard`, install the
// fatal-signal handlers, start the resource sampler (period sample_ms,
// 0 = keep current). -1 + eg_last_error when the dir is unwritable.
int eg_blackbox_init(const char* postmortem_dir, int shard, int sample_ms) {
  try {
    if (!eg::Blackbox::Global().Install(
            postmortem_dir ? postmortem_dir : "", shard, sample_ms)) {
      g_last_error = eg::Blackbox::Global().error();
      return -1;
    }
    return 0;
  }
  EG_API_GUARD(-1)
}

// One app-level flight-recorder event from Python (the run_loop /
// prefetch layer accounts into the same rings the native hooks use).
void eg_blackbox_record(int point, int op, int shard, uint64_t trace,
                        uint64_t value, int outcome) {
  try {
    eg::Blackbox::Global().Record(
        point >= 0 && point < eg::kBbPointCount
            ? static_cast<uint8_t>(point)
            : static_cast<uint8_t>(eg::kBbApp),
        static_cast<uint8_t>(op & 0xFF), shard, trace, value,
        static_cast<uint8_t>(outcome & 0xFF));
  }
  EG_API_GUARD()
}

// Live flight-recorder + resource-history dump as JSON. Same buf/cap/
// return contract as eg_telemetry_json.
int eg_blackbox_json(char* buf, int cap) {
  try {
    std::string js = eg::Blackbox::Global().LiveJson();
    if (cap > 0) {
      size_t m = std::min(js.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, js.data(), m);
      buf[m] = '\0';
    }
    return static_cast<int>(js.size());
  }
  EG_API_GUARD(-1)
}

// Local resource-gauge history (the in-process twin of the kHistory
// scrape). Same buf/cap/return contract as eg_telemetry_json.
int eg_blackbox_history(char* buf, int cap) {
  try {
    eg::Blackbox& bb = eg::Blackbox::Global();
    std::string js = bb.HistoryJson(bb.shard());
    if (cap > 0) {
      size_t m = std::min(js.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, js.data(), m);
      buf[m] = '\0';
    }
    return static_cast<int>(js.size());
  }
  EG_API_GUARD(-1)
}

// Write a postmortem dump NOW (the manual path: run_loop's unhandled-
// exception hook, tests). Same format as the fatal-signal dump with
// signal 0 ("exception"). -1 when the blackbox is disabled or the path
// cannot be opened.
int eg_blackbox_dump(const char* path) {
  try {
    if (!path || !eg::Blackbox::Global().WriteDump(path, 0)) {
      g_last_error = "blackbox dump failed (disabled, or path not "
                     "writable)";
      return -1;
    }
    return 0;
  }
  EG_API_GUARD(-1)
}

// Zero the flight-recorder rings + drop ledger (enabled flag, handlers
// and resource history survive) — the clean-slate primitive tests use.
void eg_blackbox_reset() {
  try {
    eg::Blackbox::Global().Reset();
  }
  EG_API_GUARD()
}

// Remote resource-history scrape (kHistory opcode): fetch shard
// `shard`'s gauge ring. Same buf/cap/return contract as
// eg_remote_scrape; -1 on transport failure or bad shard index.
int eg_remote_history(void* h, int shard, char* buf, int cap) {
  try {
    std::string js;
    if (!static_cast<RemoteGraph*>(API(h))->HistoryShard(shard, &js)) {
      g_last_error = "history scrape failed: shard " +
                     std::to_string(shard) + " unreachable or invalid";
      return -1;
    }
    if (cap > 0) {
      size_t m = std::min(js.size(), static_cast<size_t>(cap - 1));
      memcpy(buf, js.data(), m);
      buf[m] = '\0';
    }
    return static_cast<int>(js.size());
  }
  EG_API_GUARD(-1)
}

// ---- deterministic failpoints (eg_fault.h; FAULTS.md) ----
// Install a process-global fault spec, e.g.
// "recv_frame:err@0.5,dial:delay@200"; seed makes the per-point failure
// sequences replayable. Empty/NULL spec clears. -1 + eg_last_error on a
// malformed spec (nothing installed).
int eg_fault_config(const char* spec, uint64_t seed) {
  try {
    if (!eg::FaultInjector::Global().Configure(spec ? spec : "", seed)) {
      g_last_error = eg::FaultInjector::Global().error();
      return -1;
    }
    return 0;
  }
  EG_API_GUARD(-1)
}

void eg_fault_clear() {
  try {
    eg::FaultInjector::Global().Clear();
  }
  EG_API_GUARD()
}

int eg_fault_count() {
  try {
    return eg::kFaultIdCount;
  }
  EG_API_GUARD(0)
}

const char* eg_fault_name(int i) {
  try {
    return (i >= 0 && i < eg::kFaultIdCount) ? eg::kFaultNames[i] : "";
  }
  EG_API_GUARD("")
}

// Injected-fault ledger: fires per failpoint since its last (re)config.
// out sized eg_fault_count().
void eg_fault_injected(uint64_t* out) {
  try {
    eg::FaultInjector::Global().SnapshotInjected(out);
  }
  EG_API_GUARD()
}

}  // extern "C"
