// Bounded-admission connection server: the survivability layer under
// the shard service (eg_service.h).
//
// The 2019-Euler shape this replaces — an accept loop spawning one
// unbounded detached handler thread per connection — dies at the first
// connection storm (thread exhaustion), wedges a handler forever on a
// stalled client, and has no way to say "not now" besides letting the
// backlog grow. Production sampling tiers live or die on exactly this
// (FastSample, arXiv:2311.17847; pipelined sampling, arXiv:2110.08450):
// the service must shed load it cannot serve, refuse work whose answers
// nobody will read, and hand back its registry slot before it stops.
//
// Shape: one poller thread multiplexes every idle connection (idle
// connections cost a poll slot, never a handler), a FIXED pool of
// `workers` handler threads serves connections that have a request
// ready, and admission is bounded — when in-flight work reaches
// `workers + pending` (or open connections reach `max_conns`), a new
// connection is answered with one kStatusBusy frame and closed instead
// of queueing unboundedly. The client's ConnPool::Call treats BUSY as
// an immediate fail-fast failover (eg_remote.cc), so shed load moves to
// a replica instead of piling onto the struggling server.
//
// Deadlines: v2 requests stamp their remaining budget (eg_wire.h
// envelope); workers check it against the time the request became
// readable and answer kStatusDeadline instead of computing dead
// answers. SO_RCVTIMEO/SO_SNDTIMEO on every accepted socket bound how
// long a wedged peer can pin a handler slot (`handler_timeouts`).
//
// Drain: Drain() stops accepting, closes idle connections, lets
// in-flight requests finish (condvar, bounded by `drain_ms`), then
// closes — the server half of a rolling restart (DEPLOY.md runbook).
//
// Failpoints (eg_fault.h): `accept` drops/delays accepted connections,
// `handler_stall` stalls or wedges a worker pre-dispatch, `busy_force`
// forces the admission check to report overload — all seeded and
// countable, so every path above is deterministically testable.
#ifndef EG_ADMISSION_H_
#define EG_ADMISSION_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "eg_common.h"
#include "eg_wire.h"

namespace eg {

struct AdmissionOptions {
  int workers = 0;           // handler pool size; 0 = 2 * hw threads
  int pending = 64;          // admitted-work headroom beyond the pool:
                             // BUSY when active + ready >= workers+pending
  int max_conns = 1024;      // absolute open-connection cap (idle incl.)
  int io_timeout_ms = 5000;  // SO_RCVTIMEO/SO_SNDTIMEO per connection
  int idle_timeout_ms = 0;   // close connections idle this long; 0 = never
  int linger_ms = 2;         // post-reply wait for a follow-up request
                             // before handing the conn back to the poller
  int drain_ms = 5000;       // Drain()/Stop() grace for in-flight work
  bool legacy_wire = false;  // emulate a wire-v1 server (answer envelopes
                             // with the stock unknown-op error) — the
                             // cross-version compatibility test hook
  bool v2_only = false;      // emulate a wire-v2 server (kStatusBadVersion
                             // to v3 envelopes; v2 served normally) — the
                             // trace-id downgrade drill's other direction
  bool v3_only = false;      // emulate a wire-v3 server (kStatusBadVersion
                             // to v4 epoch envelopes; v3 served normally) —
                             // the epoch-stamp downgrade drill's hook
  int telemetry = -1;        // -1 = leave the process-global telemetry
                             // switch alone; 0/1 set it (eg_telemetry.h)
  int slow_spans = 0;        // >0 = slow-span journal capacity
  int blackbox = -1;         // -1 = leave the process-global blackbox
                             // switch alone; 0/1 set it (eg_blackbox.h)
  int heat = -1;             // -1 = leave the process-global heat
                             // profiler alone; 0/1 set it (eg_heat.h)
  int heat_topk = 0;         // >0 = hot-key tracker capacity (resets it)
  std::string postmortem_dir;  // non-empty: arm the fatal-signal dump
                               // path for this serving process
  int shard_idx = -1;        // set programmatically by Service::Start so
                             // server-side spans carry their shard
};

// Parse "k=v;k=v" admission options (workers/pending/max_conns/
// io_timeout_ms/idle_timeout_ms/linger_ms/drain_ms/wire_version/
// telemetry/slow_spans/blackbox/heat/heat_topk/postmortem_dir).
// Unknown keys and malformed numbers fail loudly: false + *err.
bool ParseAdmissionOptions(const std::string& spec, AdmissionOptions* opt,
                           std::string* err);

class AdmissionServer {
 public:
  // Request handler: decode body (envelope already stripped), write the
  // reply payload. `env` is the parsed request envelope — the service
  // reads the v4 pinned epoch from it and stamps ok replies with the
  // current epoch. Must not throw for ordinary malformed input (the
  // worker adds a catch-all barrier regardless).
  using Handler = std::function<void(const char* req, size_t len,
                                     const Envelope& env,
                                     std::string* reply)>;

  ~AdmissionServer() { Stop(); }

  // Takes ownership of a bound listening fd and starts the poller +
  // worker pool. False + *err when thread/pipe setup fails.
  bool Start(int listen_fd, const AdmissionOptions& opt, Handler handler,
             std::string* err);

  // Stop accepting, close idle connections, let queued/in-flight
  // requests finish (up to grace_ms; <0 = opt.drain_ms), then close.
  // Idempotent; counted once in the `draining` counter.
  void Drain(int grace_ms = -1);

  // Drain (default grace), then join every thread and close every fd.
  // Idempotent; the destructor calls it.
  void Stop();

  bool draining() const {
    return draining_.load(std::memory_order_acquire);
  }
  int workers() const { return opt_.workers; }
  // Live admission gauges for the kStats scrape (eg_telemetry.h
  // TelemetryGauges): how loaded this server is RIGHT NOW — the
  // operator-visible half of bounded admission.
  int active() const { return active_.load(std::memory_order_relaxed); }
  int queue_depth() const {
    return ready_count_.load(std::memory_order_relaxed);
  }
  int conns() const { return conns_.load(std::memory_order_relaxed); }

 private:
  struct ReadyConn {
    int fd = -1;
    int64_t ready_ms = 0;  // when the poller saw the request readable —
                           // the base the stamped deadline counts from
  };

  void PollerLoop();
  void WorkerLoop();
  // Serve one connection until it goes idle (returned to the poller),
  // errors, times out, or the server drains.
  void ServeConn(ReadyConn c);
  void AcceptBurst(std::map<int, int64_t>* idle,
                   std::map<int, int64_t>* dying, int64_t now);
  void CloseConn(int fd);   // close + accounting + drain notification
  void ReturnConn(int fd);  // hand an idle conn back to the poller
  void Wake();              // nudge the poller out of poll()

  AdmissionOptions opt_;
  Handler handler_;
  int listen_fd_ = -1;
  int wake_r_ = -1, wake_w_ = -1;
  bool started_ = false;
  std::thread poller_;
  std::vector<std::thread> workers_;

  // PosixMutex + condition_variable_any (not std::mutex): servers are
  // created and destroyed repeatedly in one process (rolling restarts,
  // tests), and a recycled heap block would otherwise carry the
  // previous server's stale TSAN mutex shadow state — see PosixMutex
  // in eg_common.h.
  mutable PosixMutex mu_;  // guards ready_, returned_, all_fds_, stop_
  PosixCondVar ready_cv_;    // workers wait for ready conns
  PosixCondVar drained_cv_;  // Drain waits for conns_ == 0
  std::deque<ReadyConn> ready_ EG_GUARDED_BY(mu_);
  std::vector<int> returned_ EG_GUARDED_BY(mu_);
  // every open conn fd, for forced shutdown
  std::set<int> all_fds_ EG_GUARDED_BY(mu_);
  bool stop_ EG_GUARDED_BY(mu_) = false;
  std::atomic<bool> draining_{false};
  std::atomic<int> active_{0};       // workers currently serving
  std::atomic<int> ready_count_{0};  // mirrors ready_.size() lock-free
  std::atomic<int> conns_{0};        // total admitted open connections
};

}  // namespace eg

#endif  // EG_ADMISSION_H_
