#include "eg_cache.h"

#include <cstring>

#include "eg_heat.h"

namespace eg {

std::atomic<int64_t>& GlobalCacheBytes() {
  static std::atomic<int64_t> bytes{0};
  return bytes;
}

FeatureCache::~FeatureCache() {
  for (auto& st : stripes_)
    GlobalCacheBytes().fetch_sub(static_cast<int64_t>(st.bytes),
                                 std::memory_order_relaxed);
}

void FeatureCache::SetCapacity(size_t bytes) {
  cap_ = bytes;
  if (cap_ != 0) return;
  for (auto& st : stripes_) {
    std::lock_guard<std::mutex> l(st.mu);
    st.map.clear();
    st.fifo.clear();
    GlobalCacheBytes().fetch_sub(static_cast<int64_t>(st.bytes),
                                 std::memory_order_relaxed);
    st.bytes = 0;
  }
}

uint64_t FeatureCache::SpecHash(const int32_t* fids, const int32_t* dims,
                                int nf) {
  uint64_t h = 0xCBF29CE484222325ULL;  // FNV-1a
  auto mix = [&h](int32_t v) {
    for (int b = 0; b < 4; ++b) {
      h ^= static_cast<uint64_t>((v >> (8 * b)) & 0xFF);
      h *= 0x100000001B3ULL;
    }
  };
  for (int k = 0; k < nf; ++k) mix(fids[k]);
  for (int k = 0; k < nf; ++k) mix(dims[k]);
  return h;
}

uint64_t FeatureCache::Mix(uint64_t spec, uint64_t id) {
  // splitmix64 finalizer over the combined key
  uint64_t z = spec ^ (id + 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

bool FeatureCache::Get(uint64_t spec, uint64_t id, float* out,
                       size_t row_dim) {
  if (cap_ == 0) return false;
  uint64_t key = Mix(spec, id);
  Stripe& st = stripes_[key % kStripes];
  std::lock_guard<std::mutex> l(st.mu);
  auto it = st.map.find(key);
  // the full (spec, id, dim) identity is verified: a key collision is a
  // miss, never somebody else's row. (Cache-efficacy hit/miss classes
  // are accounted by the dense-feature caller, which already holds each
  // probed id's frequency class from its heat feed — see eg_heat.h
  // AddCacheClasses; the eviction hook below stays here because only
  // the cache knows its victims.)
  if (it == st.map.end() || it->second.spec != spec || it->second.id != id ||
      it->second.row.size() != row_dim)
    return false;
  std::memcpy(out, it->second.row.data(), row_dim * sizeof(float));
  return true;
}

void FeatureCache::Put(uint64_t spec, uint64_t id, const float* row,
                       size_t row_dim) {
  if (cap_ == 0) return;
  size_t cost = row_dim * sizeof(float) + kEntryOverhead;
  size_t stripe_cap = cap_ / kStripes;
  if (cost > stripe_cap) return;  // a single over-budget row never caches
  uint64_t key = Mix(spec, id);
  Stripe& st = stripes_[key % kStripes];
  std::lock_guard<std::mutex> l(st.mu);
  if (st.map.count(key)) return;  // racing fetchers: first insert wins
  while (st.bytes + cost > stripe_cap && !st.fifo.empty()) {
    auto victim = st.map.find(st.fifo.front());
    st.fifo.pop_front();
    if (victim == st.map.end()) continue;
    size_t freed =
        victim->second.row.size() * sizeof(float) + kEntryOverhead;
    st.bytes -= freed;
    GlobalCacheBytes().fetch_sub(static_cast<int64_t>(freed),
                                 std::memory_order_relaxed);
    // eviction bucketed by the VICTIM's frequency class: a hot row
    // evicted by FIFO is exactly the event a frequency-aware admission
    // policy would prevent (ROADMAP item 5's cache question)
    Heat::Global().RecordCacheEvent(kHeatCacheEvict, victim->second.id);
    st.map.erase(victim);
  }
  Entry e;
  e.spec = spec;
  e.id = id;
  e.row.assign(row, row + row_dim);
  st.map.emplace(key, std::move(e));
  st.fifo.push_back(key);
  st.bytes += cost;
  GlobalCacheBytes().fetch_add(static_cast<int64_t>(cost),
                               std::memory_order_relaxed);
}

size_t FeatureCache::bytes() const {
  size_t total = 0;
  for (const auto& st : stripes_) {
    std::lock_guard<std::mutex> l(st.mu);
    total += st.bytes;
  }
  return total;
}

}  // namespace eg
