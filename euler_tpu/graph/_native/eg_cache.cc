#include "eg_cache.h"

#include <algorithm>
#include <cstring>

#include "eg_heat.h"
#include "eg_stats.h"

namespace eg {

std::atomic<int64_t>& GlobalCacheBytes() {
  static std::atomic<int64_t> total{0};
  return total;
}

std::atomic<int64_t>& GlobalNbrCacheBytes() {
  static std::atomic<int64_t> total{0};
  return total;
}

bool CacheAdmit(int policy, uint64_t candidate, uint64_t victim) {
  if (policy != kCachePolicyFreq) return true;
  Heat& heat = Heat::Global();
  // No estimator -> no grounds to reject: degrade to FIFO admission
  // rather than rejecting everything on zero-vs-zero estimates.
  if (!heat.enabled()) return true;
  // TinyLFU shape: the candidate must beat the victim STRICTLY — on a
  // tie the resident row wins (it has already paid its fetch).
  return heat.Estimate(kHeatClient, candidate) >
         heat.Estimate(kHeatClient, victim);
}

// ---------------- FeatureCache ----------------

FeatureCache::~FeatureCache() {
  for (auto& st : stripes_)
    GlobalCacheBytes().fetch_sub(static_cast<int64_t>(st.bytes),
                                 std::memory_order_relaxed);
}

void FeatureCache::SetCapacity(size_t budget) {
  cap_ = budget;
  if (cap_ != 0) return;
  for (auto& st : stripes_) {
    std::lock_guard<std::mutex> l(st.mu);
    st.map.clear();
    st.fifo.clear();
    GlobalCacheBytes().fetch_sub(static_cast<int64_t>(st.bytes),
                                 std::memory_order_relaxed);
    st.bytes = 0;
  }
}

uint64_t FeatureCache::SpecHash(const int32_t* fids, const int32_t* dims,
                                int nf) {
  uint64_t h = 0xCBF29CE484222325ULL;  // FNV-1a
  auto mix = [&h](int32_t v) {
    for (int b = 0; b < 4; ++b) {
      h ^= static_cast<uint64_t>((v >> (8 * b)) & 0xFF);
      h *= 0x100000001B3ULL;
    }
  };
  for (int k = 0; k < nf; ++k) mix(fids[k]);
  for (int k = 0; k < nf; ++k) mix(dims[k]);
  return h;
}

uint64_t FeatureCache::Mix(uint64_t spec, uint64_t id) {
  // splitmix64 finalizer over the combined key
  uint64_t z = spec ^ (id + 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

bool FeatureCache::Get(uint64_t spec, uint64_t id, float* out,
                       size_t row_dim, uint64_t gen) {
  if (cap_ == 0) return false;
  uint64_t key = Mix(spec, id);
  Stripe& st = stripes_[key % kStripes];
  std::lock_guard<std::mutex> l(st.mu);
  auto it = st.map.find(key);
  // the full (spec, id, dim) identity is verified: a key collision is a
  // miss, never somebody else's row. (Cache-efficacy hit/miss classes
  // are accounted by the dense-feature caller, which already holds each
  // probed id's frequency class from its heat feed — see eg_heat.h
  // AddCacheClasses; the eviction hook below stays here because only
  // the cache knows its victims.)
  if (it == st.map.end() || it->second.spec != spec || it->second.id != id ||
      it->second.row.size() != row_dim)
    return false;
  if (it->second.gen != gen) {
    // Pre-flip row: evict lazily right here (its fifo slot becomes a
    // harmless dangling key the eviction walk skips) and miss — the
    // caller refetches against the new epoch's snapshot.
    size_t freed = it->second.row.size() * sizeof(float) + kEntryOverhead;
    st.bytes -= freed;
    GlobalCacheBytes().fetch_sub(static_cast<int64_t>(freed),
                                 std::memory_order_relaxed);
    st.map.erase(it);
    Counters::Global().Add(kCtrEpochStaleEvict);
    return false;
  }
  std::memcpy(out, it->second.row.data(), row_dim * sizeof(float));
  return true;
}

void FeatureCache::Put(uint64_t spec, uint64_t id, const float* row,
                       size_t row_dim, uint64_t gen) {
  if (cap_ == 0) return;
  size_t cost = row_dim * sizeof(float) + kEntryOverhead;
  size_t stripe_cap = cap_ / kStripes;
  if (cost > stripe_cap) return;  // a single over-budget row never caches
  uint64_t key = Mix(spec, id);
  Stripe& st = stripes_[key % kStripes];
  std::lock_guard<std::mutex> l(st.mu);
  auto resident = st.map.find(key);
  if (resident != st.map.end()) {
    // racing fetchers at the same generation: first insert wins
    if (resident->second.gen == gen) return;
    // pre-flip row being refreshed: evict it so the new-epoch row lands
    size_t freed =
        resident->second.row.size() * sizeof(float) + kEntryOverhead;
    st.bytes -= freed;
    GlobalCacheBytes().fetch_sub(static_cast<int64_t>(freed),
                                 std::memory_order_relaxed);
    st.map.erase(resident);
    Counters::Global().Add(kCtrEpochStaleEvict);
  }
  while (st.bytes + cost > stripe_cap && !st.fifo.empty()) {
    auto victim = st.map.find(st.fifo.front());
    if (victim != st.map.end()) {
      // Frequency-aware admission (TinyLFU shape): the candidate must
      // beat the FIFO victim's sketch-estimated frequency to displace
      // it — a cold scan row cannot flush a pinned hub row. The dense
      // path feeds the sketch PRE-cache, so the candidate's current
      // access is already in its estimate.
      if (!CacheAdmit(policy_, id, victim->second.id)) {
        Counters::Global().Add(kCtrCacheAdmitReject);
        return;
      }
      size_t freed =
          victim->second.row.size() * sizeof(float) + kEntryOverhead;
      st.bytes -= freed;
      GlobalCacheBytes().fetch_sub(static_cast<int64_t>(freed),
                                   std::memory_order_relaxed);
      // eviction bucketed by the VICTIM's frequency class: a hot row
      // evicted despite admission filtering is exactly the event the
      // cache-efficacy classes exist to expose (ROADMAP item 5)
      Heat::Global().RecordCacheEvent(kHeatCacheEvict, victim->second.id);
      st.map.erase(victim);
    }
    st.fifo.pop_front();
  }
  Entry e;
  e.spec = spec;
  e.id = id;
  e.gen = gen;
  e.row.assign(row, row + row_dim);
  st.map.emplace(key, std::move(e));
  st.fifo.push_back(key);
  st.bytes += cost;
  GlobalCacheBytes().fetch_add(static_cast<int64_t>(cost),
                               std::memory_order_relaxed);
}

size_t FeatureCache::bytes() const {
  size_t total = 0;
  for (const auto& st : stripes_) {
    std::lock_guard<std::mutex> l(st.mu);
    total += st.bytes;
  }
  return total;
}

// ---------------- NeighborCache ----------------

NeighborCache::~NeighborCache() {
  for (auto& st : stripes_)
    GlobalNbrCacheBytes().fetch_sub(static_cast<int64_t>(st.bytes),
                                    std::memory_order_relaxed);
}

void NeighborCache::SetCapacity(size_t budget) {
  cap_ = budget;
  if (cap_ != 0) return;
  for (auto& st : stripes_) {
    std::lock_guard<std::mutex> l(st.mu);
    st.map.clear();
    st.fifo.clear();
    GlobalNbrCacheBytes().fetch_sub(static_cast<int64_t>(st.bytes),
                                    std::memory_order_relaxed);
    st.bytes = 0;
  }
}

uint64_t NeighborCache::SpecHash(const int32_t* etypes, int net) {
  uint64_t h = 0xCBF29CE484222325ULL;  // FNV-1a
  for (int k = 0; k < net; ++k) {
    int32_t v = etypes[k];
    for (int b = 0; b < 4; ++b) {
      h ^= static_cast<uint64_t>((v >> (8 * b)) & 0xFF);
      h *= 0x100000001B3ULL;
    }
  }
  return h;
}

uint64_t NeighborCache::Mix(uint64_t spec, uint64_t id) {
  uint64_t z = spec ^ (id + 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

bool NeighborCache::Sample(uint64_t spec, uint64_t id, int count,
                           uint64_t default_id, Rng& rng, uint64_t* out_ids,
                           float* out_w, int32_t* out_t, uint64_t gen) {
  if (cap_ == 0) return false;
  uint64_t key = Mix(spec, id);
  Stripe& st = stripes_[key % kStripes];
  std::lock_guard<std::mutex> l(st.mu);
  auto it = st.map.find(key);
  if (it == st.map.end() || it->second.spec != spec || it->second.id != id)
    return false;
  if (it->second.gen != gen) {
    // pre-flip adjacency slice: evict lazily and miss — sampling from
    // it could draw a removed edge or miss an added one
    size_t freed = EntryCost(it->second.ids.size());
    st.bytes -= freed;
    GlobalNbrCacheBytes().fetch_sub(static_cast<int64_t>(freed),
                                    std::memory_order_relaxed);
    st.map.erase(it);
    Counters::Global().Add(kCtrEpochStaleEvict);
    return false;
  }
  const Entry& e = it->second;
  double total = e.cum.empty() ? 0.0 : e.cum.back();
  if (total <= 0.0) {
    // empty (or zero-weight) slice: the engine answers defaults — so
    // does the cache, and the answer is a HIT (no wire trip needed)
    for (int j = 0; j < count; ++j) {
      out_ids[j] = default_id;
      out_w[j] = 0.f;
      out_t[j] = -1;
    }
    return true;
  }
  // Weight-proportional draw against the prefix sums — the same
  // distribution GraphStore::SampleNeighbors realizes shard-side
  // (group-prefix walk + in-group cumulative search flatten to one
  // cumulative search over the concatenated groups).
  for (int j = 0; j < count; ++j) {
    double r = rng.NextDouble() * total;
    size_t k = static_cast<size_t>(
        std::lower_bound(e.cum.begin(), e.cum.end(), r) - e.cum.begin());
    if (k >= e.ids.size()) k = e.ids.size() - 1;  // float rounding spill
    out_ids[j] = e.ids[k];
    out_w[j] = e.w[k];
    out_t[j] = e.t[k];
  }
  return true;
}

void NeighborCache::Put(uint64_t spec, uint64_t id, const uint64_t* nbr_ids,
                        const float* nbr_w, const int32_t* nbr_t, size_t n,
                        uint64_t gen) {
  if (cap_ == 0) return;
  size_t cost = EntryCost(n);
  size_t stripe_cap = cap_ / kStripes;
  if (cost > stripe_cap) return;  // an over-budget slice never caches
  uint64_t key = Mix(spec, id);
  Stripe& st = stripes_[key % kStripes];
  std::lock_guard<std::mutex> l(st.mu);
  auto resident = st.map.find(key);
  if (resident != st.map.end()) {
    // racing fetchers at the same generation: first insert wins
    if (resident->second.gen == gen) return;
    // pre-flip slice being refreshed: evict so the new epoch's lands
    size_t freed = EntryCost(resident->second.ids.size());
    st.bytes -= freed;
    GlobalNbrCacheBytes().fetch_sub(static_cast<int64_t>(freed),
                                    std::memory_order_relaxed);
    st.map.erase(resident);
    Counters::Global().Add(kCtrEpochStaleEvict);
  }
  while (st.bytes + cost > stripe_cap && !st.fifo.empty()) {
    auto victim = st.map.find(st.fifo.front());
    if (victim != st.map.end()) {
      if (!CacheAdmit(policy_, id, victim->second.id)) {
        Counters::Global().Add(kCtrCacheAdmitReject);
        return;
      }
      size_t freed = EntryCost(victim->second.ids.size());
      st.bytes -= freed;
      GlobalNbrCacheBytes().fetch_sub(static_cast<int64_t>(freed),
                                      std::memory_order_relaxed);
      st.map.erase(victim);
    }
    st.fifo.pop_front();
  }
  Entry e;
  e.spec = spec;
  e.id = id;
  e.gen = gen;
  e.ids.assign(nbr_ids, nbr_ids + n);
  e.w.assign(nbr_w, nbr_w + n);
  e.t.assign(nbr_t, nbr_t + n);
  e.cum.resize(n);
  double run = 0.0;
  for (size_t k = 0; k < n; ++k) {
    // negative weights cannot enter the sampling mass (the engine's
    // cumulative arrays are built from non-negative edge weights)
    run += nbr_w[k] > 0.f ? static_cast<double>(nbr_w[k]) : 0.0;
    e.cum[k] = run;
  }
  st.map.emplace(key, std::move(e));
  st.fifo.push_back(key);
  st.bytes += cost;
  GlobalNbrCacheBytes().fetch_add(static_cast<int64_t>(cost),
                                  std::memory_order_relaxed);
}

size_t NeighborCache::bytes() const {
  size_t total = 0;
  for (const auto& st : stripes_) {
    std::lock_guard<std::mutex> l(st.mu);
    total += st.bytes;
  }
  return total;
}

}  // namespace eg
