// Span-timer statistics for the sampling engine and service.
//
// The reference ships only a thread-local stopwatch used in one perf test
// (reference euler/common/timmer.cc:24-33) and glog lines; SURVEY §5.1
// calls for a real span timer in the TPU build's sampling service. This is
// it: lock-free per-op accumulators (count / total ns / max ns) recorded
// at the C-ABI choke point, so every query — embedded engine, remote
// client round-trip, or service-side request — is measured with one
// mechanism. Snapshots are racy-but-consistent-enough reads of relaxed
// atomics; overhead per call is two clock reads + three relaxed RMWs.
#ifndef EG_STATS_H_
#define EG_STATS_H_

#include <atomic>
#include <chrono>
#include <cstdint>

namespace eg {

enum StatOp : int {
  kStatSampleNode = 0,
  kStatSampleEdge,
  kStatSampleNeighbor,
  kStatSampleFanout,
  kStatFullNeighbor,
  kStatTopKNeighbor,
  kStatRandomWalk,
  kStatDenseFeature,
  kStatSparseFeature,
  kStatBinaryFeature,
  kStatNodeType,
  kStatServiceRequest,  // one per served RPC (service side)
  kStatOpCount,
};

// Fixed-order names; Python reads them at runtime via eg_stat_name(i).
const char* const kStatNames[kStatOpCount] = {
    "sample_node",    "sample_edge",   "sample_neighbor", "sample_fanout",
    "full_neighbor",  "topk_neighbor", "random_walk",     "dense_feature",
    "sparse_feature", "binary_feature", "node_type",      "service_request",
};

class Stats {
 public:
  static Stats& Global() {
    static Stats s;
    return s;
  }

  void Record(StatOp op, uint64_t ns) {
    auto& c = cells_[op];
    c.count.fetch_add(1, std::memory_order_relaxed);
    c.total_ns.fetch_add(ns, std::memory_order_relaxed);
    uint64_t prev = c.max_ns.load(std::memory_order_relaxed);
    while (prev < ns &&
           !c.max_ns.compare_exchange_weak(prev, ns,
                                           std::memory_order_relaxed)) {
    }
  }

  void Snapshot(uint64_t* counts, uint64_t* total_ns, uint64_t* max_ns) const {
    for (int i = 0; i < kStatOpCount; ++i) {
      counts[i] = cells_[i].count.load(std::memory_order_relaxed);
      total_ns[i] = cells_[i].total_ns.load(std::memory_order_relaxed);
      max_ns[i] = cells_[i].max_ns.load(std::memory_order_relaxed);
    }
  }

  void Reset() {
    for (auto& c : cells_) {
      c.count.store(0, std::memory_order_relaxed);
      c.total_ns.store(0, std::memory_order_relaxed);
      c.max_ns.store(0, std::memory_order_relaxed);
    }
  }

 private:
  struct Cell {
    std::atomic<uint64_t> count{0};
    std::atomic<uint64_t> total_ns{0};
    std::atomic<uint64_t> max_ns{0};
  };
  Cell cells_[kStatOpCount];
};

// Failure counters for the remote transport: where the span timers above
// measure how long the sampling tier takes, these count how often it has
// to fight for an answer (retries, quarantines, failovers, deadline
// aborts, rejected frames, registry churn). Same mechanism — relaxed
// atomics recorded at the choke points, snapshot into Python through the
// stats surface — so a production run and the chaos soak (FAULTS.md)
// read identical ledgers.
enum CounterId : int {
  kCtrDialFail = 0,      // DialTcp failed inside ConnPool::Call
  kCtrRetry,             // attempts beyond the first within one Call
  kCtrQuarantine,        // a replica marked bad (timed quarantine)
  kCtrFailover,          // a Call that succeeded after >=1 failed attempt
  kCtrCallFail,          // a Call that exhausted retries/deadline
  kCtrDeadlineExceeded,  // a Call aborted by its overall deadline
  kCtrFrameReject,       // oversize/malformed/error-status frame rejected
  kCtrRediscover,        // background registry re-LIST applied to pools
  kCtrHeartbeatMiss,     // a service registry heartbeat that had to redial
  // Remote hot-path efficiency ledger (perf counters, not failures —
  // same mechanism so one snapshot covers both): how many ids the
  // client did NOT have to put on the wire, and how its requests were
  // shaped. On power-law graphs duplicate hub ids dominate a batch, so
  // these are the terms of the communication-win accounting
  // (ids_on_wire_after = ids_requested - ids_deduped - cache_hits).
  kCtrIdsDeduped,        // duplicate ids coalesced before wire encode
  kCtrCacheHit,          // feature-row cache hits (per unique id probed)
  kCtrCacheMiss,         // feature-row cache misses (row fetched remotely)
  kCtrRpcChunk,          // chunked sub-requests (counted per chunk when a
                         // per-shard request was split; unsplit adds 0)
  kCtrRpcError,          // a per-shard op failed after all transport
                         // retries (its rows degraded to defaults, or the
                         // call raised under strict=)
  // Server-side survivability ledger (eg_admission.h): how often the
  // shard service shed, refused, or reclaimed work instead of wedging —
  // plus the client-side reactions that keep those events invisible to
  // training (fail-fast failover, wire downgrade).
  kCtrBusyReject,        // admission answered BUSY instead of queueing
  kCtrBusyFailover,      // client treated a BUSY reply as an immediate
                         // failover (no backoff burned, no quarantine)
  kCtrHandlerTimeout,    // a handler abandoned a wedged connection on an
                         // SO_RCVTIMEO/SO_SNDTIMEO expiry (slot freed)
  kCtrDeadlineReject,    // a handler refused a request whose stamped
                         // deadline had already expired (no dead compute)
  kCtrDraining,          // a server entered drain (dereg + finish + close)
  kCtrWireDowngrade,     // a replica negotiated down to wire v1 (old
                         // server detected on its first exchange)
  // Prefetch pipeline ledger (euler_tpu/parallel/prefetch.py bumps
  // these through the eg_counter_add ABI): how the training input
  // pipeline behaved — produced vs dropped batches, and workers that
  // DIED after init (a dead worker otherwise only surfaces as the
  // consumer's exception at that step; the counter makes it visible in
  // any scrape, see OBSERVABILITY.md "Step phases").
  kCtrPrefetchProduced,     // batches produced by prefetch workers
  kCtrPrefetchDropped,      // produced batches never consumed (consumer
                            // abandoned the iterator / error teardown)
  kCtrPrefetchWorkerError,  // a prefetch worker killed by an exception
  // Postmortem ledger (eg_blackbox.h / FAULTS.md): fires of the seeded
  // `crash` failpoint, bumped BEFORE the signal is raised so the
  // fatal-signal dump's counter snapshot includes the fire that killed
  // the process — the exact-arithmetic anchor the blackbox tests audit
  // a dead shard's postmortem against.
  kCtrCrash,
  // Locality ledger (eg_placement.h / eg_cache.h): how the routing and
  // caching layers exploit access skew. nbr_cache hits/misses mirror
  // the feature-cache pair for the client-side neighbor-list cache (a
  // hit samples a hub hop locally — zero wire bytes, zero shard work);
  // cache_admit_rejects counts candidates the frequency-aware (TinyLFU-
  // shaped) admission turned away because the FIFO victim was hotter;
  // placement_fallbacks counts clients that asked for a placement map
  // and degraded to hash routing (old server or hash-sharded data).
  kCtrNbrCacheHit,
  kCtrNbrCacheMiss,
  kCtrCacheAdmitReject,
  kCtrPlacementFallback,
  // Serving ledger (euler_tpu/serving bumps these through the
  // eg_counter_add ABI): how the embedding inference path admitted and
  // shed load. serve_requests counts every submitted embed request;
  // serve_busy_rejects counts requests the micro-batcher's bounded
  // queue (or the frontend's connection cap) answered BUSY — the
  // serve-side twin of busy_rejects; serve_deadline_rejects counts
  // requests whose deadline expired before their batch dispatched
  // (answered DEADLINE, never sent to the device); serve_batches
  // counts device dispatches — serve_requests/serve_batches is the
  // request-coalescing factor the micro-batcher exists to produce.
  kCtrServeRequest,
  kCtrServeBusyReject,
  kCtrServeDeadlineReject,
  kCtrServeBatch,
  // Device-plane ledger (euler_tpu/devprof.py bumps these through the
  // eg_counter_add ABI): the XLA side of the step. device_compiles
  // counts every backend compile observed (jax.monitoring listener, or
  // the wrapped-jit fallback where events are unavailable);
  // device_recompiles counts compiles AFTER a watched function's
  // warmup — each one is journaled with the arg-shape/dtype diff that
  // triggered it, because a silent recompile is the classic way a
  // fixed-bucket device program quietly becomes 100x slower.
  // serve_recompiles is the eg_serve compile-storm guard's twin (the
  // padded fixed-bucket forward must compile exactly once); h2d/d2h
  // count transfer bytes bracketing the train/serve device boundaries.
  kCtrDeviceCompile,
  kCtrDeviceRecompile,
  kCtrServeRecompile,
  kCtrH2dBytes,
  kCtrD2hBytes,
  // Async-sampler ledger (eg_remote.cc SampleFanoutAsync): the
  // completion-queue pipeline's shape. async_submits counts whole-step
  // async ops submitted; async_inflight_peak is a high-water mark (via
  // Counters::Max) of ops concurrently in flight — at sampler_depth=N
  // it should read N, proving the pipeline really overlapped;
  // async_continuations counts hop/slice continuations fired on the
  // dispatcher pool (jobs enqueued by a completing worker, never by a
  // blocked caller — the mechanism of arXiv 2110.08450's overlap).
  kCtrAsyncSubmit,
  kCtrAsyncInflightPeak,
  kCtrAsyncContinuation,
  // Snapshot-epoch ledger (eg_epoch.h): the mutable-graph refresh path.
  // epoch_flips counts published flips (a delta load that swapped the
  // serving snapshot); epoch_drains counts superseded snapshots whose
  // last pinned reader released (counted once per retired epoch — flips
  // with no in-flight readers drain immediately, so every flip
  // eventually produces exactly one drain while the snapshot is still
  // in the keep window); epoch_stale_hits_evicted counts client cache
  // entries (feature/neighbor/sample) evicted on a generation-stale
  // hit; delta_loads_failed counts kLoadDelta requests refused (parse/
  // validate/merge failure, or the delta_load/epoch_flip failpoints) —
  // the graph keeps serving its current epoch in every failure case.
  kCtrEpochFlip,
  kCtrEpochDrain,
  kCtrEpochStaleEvict,
  kCtrDeltaLoadFail,
  kCtrCount,
};

const char* const kCounterNames[kCtrCount] = {
    "dials_failed",       "retries",          "quarantines",
    "failovers",          "calls_failed",     "deadlines_exceeded",
    "frames_rejected",    "rediscoveries",    "heartbeat_misses",
    "ids_deduped",        "cache_hits",       "cache_misses",
    "rpc_chunks",         "rpc_errors",       "busy_rejects",
    "busy_failovers",     "handler_timeouts", "deadline_rejects",
    "draining",           "wire_downgrades",  "prefetch_produced",
    "prefetch_dropped",   "prefetch_worker_errors", "crashes",
    "nbr_cache_hits",     "nbr_cache_misses",
    "cache_admit_rejects", "placement_fallbacks",
    "serve_requests",     "serve_busy_rejects",
    "serve_deadline_rejects", "serve_batches",
    "device_compiles",    "device_recompiles",
    "serve_recompiles",   "h2d_bytes",        "d2h_bytes",
    "async_submits",      "async_inflight_peak", "async_continuations",
    "epoch_flips",        "epoch_drains",
    "epoch_stale_hits_evicted", "delta_loads_failed",
};

class Counters {
 public:
  static Counters& Global() {
    static Counters c;
    return c;
  }

  void Add(CounterId id, uint64_t n = 1) {
    cells_[id].fetch_add(n, std::memory_order_relaxed);
  }

  // CAS-max for high-water-mark counters (async_inflight_peak): the
  // cell monotonically tracks the largest value ever reported.
  void Max(CounterId id, uint64_t v) {
    uint64_t prev = cells_[id].load(std::memory_order_relaxed);
    while (prev < v &&
           !cells_[id].compare_exchange_weak(prev, v,
                                             std::memory_order_relaxed)) {
    }
  }

  uint64_t Get(CounterId id) const {
    return cells_[id].load(std::memory_order_relaxed);
  }

  void Snapshot(uint64_t* out) const {
    for (int i = 0; i < kCtrCount; ++i)
      out[i] = cells_[i].load(std::memory_order_relaxed);
  }

  void Reset() {
    for (auto& c : cells_) c.store(0, std::memory_order_relaxed);
  }

 private:
  std::atomic<uint64_t> cells_[kCtrCount]{};
};

// RAII span: records wall time from construction to destruction.
class SpanTimer {
 public:
  explicit SpanTimer(StatOp op)
      : op_(op), start_(std::chrono::steady_clock::now()) {}
  ~SpanTimer() {
    auto ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                  std::chrono::steady_clock::now() - start_)
                  .count();
    Stats::Global().Record(op_, static_cast<uint64_t>(ns));
  }
  SpanTimer(const SpanTimer&) = delete;
  SpanTimer& operator=(const SpanTimer&) = delete;

 private:
  StatOp op_;
  std::chrono::steady_clock::time_point start_;
};

}  // namespace eg

#endif  // EG_STATS_H_
