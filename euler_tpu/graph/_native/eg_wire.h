// Binary wire protocol for the sharded graph service.
//
// Role equivalent of the reference's protobuf wire layer
// (reference euler/proto/graph_service.proto: 13 RPCs with flat id/weight
// array replies) — redesigned as a zero-dependency length-prefixed binary
// protocol: requests and replies are flat little-endian arrays, so
// marshaling is memcpy-shaped (the reference's §3.5 hot loop #3 is gRPC
// serialize/deserialize of exactly such arrays).
//
// Frame:   [u32 payload_len][payload]
// Request: payload = [u8 op][args...]                              (wire v1)
//          [u8 0xE7][u8 2][i64 deadline_ms][u8 op][args...]        (wire v2)
//          [u8 0xE7][u8 3][i64 deadline_ms][u64 trace_id][u8 op][args...]
//                                                                  (wire v3)
//          [u8 0xE7][u8 4][i64 deadline_ms][u64 trace_id][u64 epoch]
//          [u8 op][args...]                                        (wire v4)
// Reply:   payload = [u8 status][body...]   status 0 = ok, else see
//          WireStatus (1 = error string; 2 BUSY; 3 DEADLINE; 4 BADVERSION).
//          To a v4 request, an OK reply body is prefixed with the shard's
//          CURRENT epoch: [u8 0][u64 epoch][body...] — the passive flip
//          announcement clients learn graph refreshes from (eg_epoch.h).
//          Error/BUSY/DEADLINE/BADVERSION replies are never stamped, so
//          their layout stays identical across all versions.
//
// Version negotiation (backward compatible in every direction, all
// passive — no extra handshake round trip, ever):
//   * current clients wrap every request in the 0xE7 envelope, stamping
//     the call's REMAINING deadline budget (ms) so the server can
//     refuse requests whose answers nobody will read, (v3) the call's
//     trace id so both sides' slow-span journals correlate
//     (eg_telemetry.h), and (v4) the EPOCH the op pinned at start —
//     0 = current; a nonzero epoch asks the shard to serve that
//     snapshot if it still holds it (in-flight multi-hop steps finish
//     against the snapshot they started on, eg_epoch.h).
//   * current servers accept ALL forms: a first byte in the op range is
//     a v1 request (no deadline, no trace); 0xE7 opens an envelope,
//     whose version byte selects the header layout (v2 = 10 bytes,
//     v3 = 18, v4 = 26). An envelope whose version is above the
//     server's speaks back kStatusBadVersion with a plain-text
//     explanation — never a hang or a crash.
//   * a v1 server sees 0xE7 as an unknown op and answers its stock
//     "unknown op 231" error with the connection still healthy; clients
//     recognize exactly that reply on a replica's first exchange, mark
//     the replica v1 (`wire_downgrades` counter), and resend the raw
//     request on the same connection. A v2- or v3-only server instead
//     answers kStatusBadVersion to the v4 envelope; the client steps
//     the replica down one version (4 -> 3 -> 2) and resends — one
//     `wire_downgrades` count per replica pinned below kWireVersion,
//     at most two extra exchanges on its first call ever.
#ifndef EG_WIRE_H_
#define EG_WIRE_H_

#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

namespace eg {

// Op codes — one per GraphService RPC (reference graph_service.proto:13-25;
// sorted-ness is a flag on kFullNeighbor, dense == Float32 feature).
enum WireOp : uint8_t {
  kPing = 1,
  kInfo = 2,
  kSampleNode = 3,
  kSampleEdge = 4,
  kNodeType = 5,
  kSampleNeighbor = 6,
  kFullNeighbor = 7,
  kTopKNeighbor = 8,
  kDenseFeature = 9,
  kEdgeDenseFeature = 10,
  kSparseFeature = 11,
  kEdgeSparseFeature = 12,
  kBinaryFeature = 13,
  kEdgeBinaryFeature = 14,
  // Beyond the reference's 13 RPCs: flat per-node sampling weights, so
  // the device-graph exporter (build_node_sampler) composes with remote
  // mode instead of requiring the whole graph embedded in one process.
  kNodeWeight = 15,
  // Dedup-aware neighbor sampling: the client coalesces duplicate ids
  // before encode and sends each UNIQUE id once with a repeat count; the
  // shard replies reps[i] * count iid draws per unique id, flattened in
  // request order. Independence across duplicate rows is preserved
  // (every draw is a fresh engine sample), while hub ids — repeated
  // thousands of times in a power-law batch — cost one id on the wire
  // and one node/group lookup on the shard.
  // Request: [Arr u64 ids][Arr i32 reps][Arr i32 etypes][i32 count][u64 def]
  // Reply:   [Arr u64 nbr][Arr f32 w][Arr i32 t], each sum(reps)*count long.
  kSampleNeighborUniq = 16,
  // Remote observability scrape (eg_telemetry.h): ask a live shard for
  // its full telemetry dump — counters, span-timer stats, latency
  // histograms, admission gauges, slow-span journal. Request: no args.
  // Reply: [Str json] — the same JSON Telemetry::Json builds for the
  // local surface, so scrape-vs-local parity is one string compare.
  kStats = 17,
  // Resource-gauge history scrape (eg_blackbox.h): the shard's 60-entry
  // background-sampled ring of {RSS, open fds, live threads, cache
  // bytes} plus a fresh sample — the live view of exactly what a
  // postmortem dump freezes. Request: no args. Reply: [Str json].
  kHistory = 18,
  // Data-plane heat scrape (eg_heat.h): the shard's full hot-vertex
  // top-K table, count-min sketch totals, per-op ids ledger, and
  // cache-efficacy classes — the targeted form of the heat section
  // that also rides every kStats reply. Request: no args.
  // Reply: [Str json].
  kHeat = 19,
  // Placement-map fetch (eg_placement.h): the raw id -> partition
  // artifact the degree-aware converter emitted next to this shard's
  // .dat partitions, so clients can route hub neighborhoods to the
  // shard that actually holds them. Request: no args. Reply:
  // [Str blob]. A shard serving hash-sharded data (no artifact)
  // answers the STOCK "unknown op 20" error — byte-identical to a
  // genuine pre-placement server, so one client fallback path (degrade
  // to hash routing) covers old servers and old data alike.
  kPlacement = 20,
  // Snapshot-epoch delta load (eg_epoch.h): merge one delta file into a
  // fresh immutable snapshot and flip the shard's serving epoch to it.
  // Request: [Str path] — a shard-local `<prefix>.delta.<n>` file.
  // Reply: [u64 new_epoch]. Serialized per shard (concurrent loads
  // queue); failure (parse/validate/merge, or the delta_load/epoch_flip
  // failpoints) answers an error string, counts delta_loads_failed,
  // and leaves the current epoch serving.
  kLoadDelta = 21,
};

constexpr uint32_t kMaxFrame = 1u << 30;  // 1 GiB sanity cap

// Highest request-envelope version this build speaks; stamped by clients
// and checked by servers (see the negotiation contract above).
constexpr uint8_t kWireVersion = 4;
// Request-envelope marker. Deliberately far outside the op range so a v1
// server classifies an enveloped request as an unknown op (clean error)
// instead of misparsing it.
constexpr uint8_t kWireEnvelope = 0xE7;

// Reply status byte. v1 peers only know 0/1; every later code reads as a
// generic refused frame there (counted, retried) — degraded, never wrong.
enum WireStatus : uint8_t {
  kStatusOk = 0,
  kStatusError = 1,       // body = error string
  kStatusBusy = 2,        // admission shed the connection; fail over NOW
  kStatusDeadline = 3,    // request's stamped deadline expired server-side
  kStatusBadVersion = 4,  // envelope version above the server's
};

// Parsed view of a request payload's (optional) envelope.
struct Envelope {
  bool versioned = false;   // payload opened with kWireEnvelope
  uint8_t version = 1;      // stamped version (1 when not versioned)
  int64_t deadline_ms = -1; // client's remaining budget; <0 = none stamped
  uint64_t trace_id = 0;    // v3+ trace id; 0 = none propagated
  uint64_t epoch = 0;       // v4 pinned epoch; 0 = serve current
  size_t body_off = 0;      // offset of the v1 [u8 op][args...] body
};

// [kWireEnvelope][u8 version][i64 deadline_ms]([u64 trace_id] for v3+)
// ([u64 epoch] for v4) + payload. `version` must be 2, 3 or 4 (v2 has
// no trace-id field, only v4 carries the epoch pin).
std::string WrapEnvelope(const std::string& payload, int64_t deadline_ms,
                         uint8_t version = kWireVersion,
                         uint64_t trace_id = 0, uint64_t epoch = 0);
// Classify a request payload; false only for a TRUNCATED envelope (marker
// present but header short for its stamped version) — a payload without
// the marker is v1, ok. Versions above kWireVersion parse the common
// 10-byte prefix only (the caller rejects them with kStatusBadVersion
// before the body would matter).
bool PeekEnvelope(const std::string& payload, Envelope* env);
// [u8 status][Str msg] reply payload.
std::string StatusReply(uint8_t status, const std::string& msg);

class WireWriter {
 public:
  std::string& buf() { return buf_; }

  void U8(uint8_t v) { buf_.push_back(static_cast<char>(v)); }
  template <typename T>
  void Pod(T v) {
    buf_.append(reinterpret_cast<const char*>(&v), sizeof(T));
  }
  void I32(int32_t v) { Pod(v); }
  void I64(int64_t v) { Pod(v); }
  void U64(uint64_t v) { Pod(v); }
  void F32(float v) { Pod(v); }

  template <typename T>
  void Arr(const T* p, int64_t n) {
    I64(n);
    if (n) buf_.append(reinterpret_cast<const char*>(p), n * sizeof(T));
  }
  template <typename T>
  void Arr(const std::vector<T>& v) {
    Arr(v.data(), static_cast<int64_t>(v.size()));
  }
  void Str(const std::string& s) {
    I64(static_cast<int64_t>(s.size()));
    buf_.append(s);
  }

 private:
  std::string buf_;
};

class WireReader {
 public:
  WireReader(const char* p, size_t n) : p_(p), n_(n) {}
  explicit WireReader(const std::string& s) : p_(s.data()), n_(s.size()) {}

  bool ok() const { return ok_; }
  // Bytes left unread — the bound for counts decoded from the payload:
  // any honest element/slot count costs at least its encoding's bytes,
  // so callers reject counts beyond remaining()/<min bytes per entry>
  // before allocating (eg-lint rule wire-count-alloc).
  size_t remaining() const { return n_ - off_; }

  uint8_t U8() {
    uint8_t v = 0;
    Copy(&v, 1);
    return v;
  }
  template <typename T>
  T Pod() {
    T v{};
    Copy(&v, sizeof(T));
    return v;
  }
  int32_t I32() { return Pod<int32_t>(); }
  int64_t I64() { return Pod<int64_t>(); }
  uint64_t U64() { return Pod<uint64_t>(); }
  float F32() { return Pod<float>(); }

  // View of a length-prefixed array; nullptr on underrun. Zero-copy when
  // the payload offset happens to be aligned for T; otherwise the data is
  // memcpy'd into an owned 8-byte-aligned scratch block (offsets after the
  // leading status/op byte are usually odd, so replies typically take the
  // copy path — still one copy, same as protobuf parsing).
  template <typename T>
  const T* Arr(int64_t* n) {
    *n = I64();
    // Divide instead of multiplying: n * sizeof(T) can wrap for a hostile
    // length, which would pass the underrun check and then explode in the
    // caller's vector allocation.
    if (!ok_ || *n < 0 ||
        static_cast<uint64_t>(*n) > (n_ - off_) / sizeof(T)) {
      ok_ = false;
      *n = 0;
      return nullptr;
    }
    size_t bytes = static_cast<size_t>(*n) * sizeof(T);
    const char* raw = p_ + off_;
    off_ += bytes;
    if (reinterpret_cast<uintptr_t>(raw) % alignof(T) == 0)
      return reinterpret_cast<const T*>(raw);
    auto buf = std::make_unique<std::vector<uint64_t>>((bytes + 7) / 8);
    std::memcpy(buf->data(), raw, bytes);
    const T* p = reinterpret_cast<const T*>(buf->data());
    scratch_.push_back(std::move(buf));
    return p;
  }
  template <typename T>
  void Vec(std::vector<T>* out) {
    int64_t n;
    const T* p = Arr<T>(&n);
    out->assign(p, p + n);
  }
  std::string Str() {
    int64_t n;
    const char* p = Arr<char>(&n);
    return std::string(p ? p : "", static_cast<size_t>(n));
  }

 private:
  void Copy(void* out, size_t sz) {
    if (sz > n_ - off_) {
      ok_ = false;
      std::memset(out, 0, sz);
      return;
    }
    std::memcpy(out, p_ + off_, sz);
    off_ += sz;
  }

  const char* p_;
  size_t n_;
  size_t off_ = 0;
  bool ok_ = true;
  std::vector<std::unique_ptr<std::vector<uint64_t>>> scratch_;
};

// ---- framed socket IO (implemented in eg_wire.cc) ----

// Outcome of one framed IO op, for callers that must distinguish a
// wedged peer (socket timeout — the handler-slot-freeing case) from a
// clean close or a protocol rejection.
enum class IoStatus {
  kOk,
  kClosed,   // peer closed / reset / write error
  kTimeout,  // SO_RCVTIMEO / SO_SNDTIMEO expired mid-op
  kReject,   // oversize declared length (counted in frames_rejected)
};

// Write [u32 len][payload]; false on error.
bool SendFrame(int fd, const std::string& payload);
// SendFrame distinguishing a send-buffer timeout (client stopped
// reading) from a plain broken pipe.
IoStatus SendFrameEx(int fd, const std::string& payload);
// Read one frame into *payload; false on error/close/oversize.
bool RecvFrame(int fd, std::string* payload);
// RecvFrame distinguishing timeout/close/oversize (see IoStatus).
IoStatus RecvFrameEx(int fd, std::string* payload);
// Blocking TCP connect with send/recv timeouts + TCP_NODELAY; -1 on failure.
int DialTcp(const std::string& host, int port, int timeout_ms);
// Listen socket on host:port (port 0 = ephemeral); *bound_port receives the
// actual port. -1 on failure.
int ListenTcp(const std::string& host, int port, int* bound_port);

}  // namespace eg

#endif  // EG_WIRE_H_
