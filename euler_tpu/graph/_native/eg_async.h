// Staging state of the asynchronous remote sampler.
//
// The sync remote path (eg_remote.cc SampleNeighbor) keeps its whole
// scatter/gather state on the caller's stack: the caller blocks in
// Dispatcher::Run, so stack lifetime covers every worker job. The async
// path (SampleFanoutAsync) has no blocked caller — hop h+1's jobs are
// enqueued by hop h's completion continuation on the dispatcher pool
// (arXiv 2110.08450's overlap, FastSample's communication-tax cut) — so
// the same state must live in heap objects that survive the submitting
// frame. This header holds those objects; both paths run the SAME
// NbrPrep/NbrFetchChunk/NbrPromoteChunk/NbrFinish member functions over
// them, which is what pins async sampling distribution-identical to
// sync (tests/test_async_parity.py).
#ifndef EG_ASYNC_H_
#define EG_ASYNC_H_

#include <cstdint>
#include <memory>
#include <vector>

#include "eg_common.h"

namespace eg {

// How one request's ids scatter to shards after (optional) coalescing:
// per shard the unique ids' first-occurrence row list plus per-entry
// duplicate counts, and for every ORIGINAL row the (shard, unique
// position, occurrence index) it resolves to — the row maps replies
// scatter back through. (Hoisted out of RemoteGraph so the async op
// state below can embed one; built by RemoteGraph::BuildPlan.)
struct ShardPlan {
  std::vector<std::vector<int32_t>> rows;  // [shard] -> unique rows
  std::vector<std::vector<int32_t>> reps;  // [shard] -> dup count/unique
  std::vector<int32_t> shard_of;           // [orig row]
  std::vector<int32_t> pos_of;             // [orig row] -> unique pos
  std::vector<int32_t> occ_of;             // [orig row] -> occurrence
  int64_t coalesced = 0;                   // rows removed from the wire
};

// One SampleNeighbor call's inputs + per-shard staging, factored out of
// the former monolithic method body. Input pointers are BORROWED — they
// must outlive the call (the sync path borrows the caller's arguments;
// the async path points into its op's owned copies and the previous
// hop's output buffers). Staging buffers are written by dispatcher
// workers in disjoint blocks (each unique entry owns
// reps[j] * count draws at rep_off[j] * count), so the batch needs no
// lock of its own: the dispatcher's batch completion is the barrier.
struct NbrCall {
  // inputs
  const uint64_t* ids = nullptr;
  int n = 0;
  const int32_t* etypes = nullptr;
  int net = 0;
  int count = 0;
  uint64_t default_id = 0;
  uint64_t* out_ids = nullptr;
  float* out_w = nullptr;
  int32_t* out_t = nullptr;
  // staging (filled by RemoteGraph::NbrPrep)
  ShardPlan plan;
  std::vector<std::vector<int64_t>> rep_off;  // [shard] rep prefix sums
  std::vector<std::vector<uint64_t>> sid;     // [shard] staged draw ids
  std::vector<std::vector<float>> sw;         // [shard] staged weights
  std::vector<std::vector<int32_t>> st;       // [shard] staged types
  std::vector<std::vector<char>> ok;          // [shard] per-unique entry
  std::vector<std::vector<int32_t>> fetch;    // unique pos on the wire
  std::vector<std::vector<int32_t>> promote;  // unique pos to promote
  uint64_t nspec = 0;          // NeighborCache::SpecHash(etypes, net)
  uint64_t nbr_hits = 0, nbr_misses = 0;
  bool heat_on = false;
  bool use_ncache = false;
  // Snapshot-epoch capture (eg_epoch.h): `gen` keys every cache probe/
  // fill of this call, `pin[s]` is the epoch requested from shard s in
  // v4 envelopes — so all of a call's chunks read ONE snapshot even
  // when a delta flip lands mid-call. NbrPrep captures both from the
  // graph's last-observed state unless the async chain already stamped
  // the whole op's capture (epoch_captured).
  uint64_t gen = 0;
  std::vector<uint64_t> pin;  // [shard] requested epoch; empty = current
  bool epoch_captured = false;
};

// One in-flight whole-step async fan-out (RemoteGraph::SampleFanoutAsync
// slot). The op OWNS copies of the request arrays — the submitting
// caller's frame (a ctypes call from the Python pipeline driver) unwinds
// immediately — but only BORROWS the per-hop output buffers, which the
// caller pins until TakeAsync returns (graph.py's handle object holds
// the numpy arrays).
//
// Cursor discipline: hop/slice_off/cur/cur_n/et/call are written by
// exactly one thread at a time — the submitter until the first
// SubmitDetached, then whichever worker runs each completion
// continuation — with the dispatcher's queue and batch mutexes
// supplying the happens-before edge between writers. `state` is the
// only field read concurrently (Poll/Take/destructor vs the chain) and
// is guarded by RemoteGraph::async_mu_.
struct AsyncSampleOp {
  enum State { kFree = 0, kRunning, kDone };

  // owned request copies
  std::vector<uint64_t> ids;
  std::vector<int32_t> etypes_flat, etype_counts, counts;
  int n = 0, nhops = 0;
  uint64_t default_id = 0;
  // borrowed per-hop output buffers (pinned by the caller)
  std::vector<uint64_t*> out_ids;
  std::vector<float*> out_w;
  std::vector<int32_t*> out_t;

  // hop/slice cursor (single-writer handoff, see above)
  int hop = 0;
  int64_t slice_off = 0;
  int64_t cur_n = 0;
  const uint64_t* cur = nullptr;
  const int32_t* et = nullptr;
  std::unique_ptr<NbrCall> call;  // current slice's staging
  // Whole-op epoch capture (eg_epoch.h), stamped at submit and copied
  // into every slice's NbrCall: an in-flight step keeps reading the
  // snapshot it started on even when a shard flips between its hops
  // (the server holds the previous epoch for exactly this reader).
  uint64_t gen = 0;
  std::vector<uint64_t> pin;

  int state EG_GUARDED_BY(async_mu_) = kFree;
};

}  // namespace eg

#endif  // EG_ASYNC_H_
