#include "eg_phase.h"

namespace eg {

namespace {

void AppendU64(std::string* out, uint64_t v) {
  char buf[24];
  int n = 0;
  do {
    buf[n++] = static_cast<char>('0' + v % 10);
    v /= 10;
  } while (v);
  while (n) out->push_back(buf[--n]);
}

void AppendCell(std::string* out, bool* first, const char* key,
                const std::atomic<uint64_t>* buckets,
                const std::atomic<uint64_t>& total) {
  if (!*first) out->push_back(',');
  *first = false;
  out->push_back('"');
  out->append(key);
  out->append("\":{\"b\":[");
  uint64_t count = 0;
  for (int b = 0; b < kHistBuckets; ++b) {
    uint64_t v = buckets[b].load(std::memory_order_relaxed);
    count += v;
    if (b) out->push_back(',');
    AppendU64(out, v);
  }
  out->append("],\"count\":");
  AppendU64(out, count);
  out->append(",\"sum_us\":");
  AppendU64(out, total.load(std::memory_order_relaxed));
  out->push_back('}');
}

}  // namespace

PhaseStats& PhaseStats::Global() {
  static PhaseStats p;
  return p;
}

void PhaseStats::Reset() {
  for (auto& c : phases_) {
    for (auto& b : c.buckets) b.store(0, std::memory_order_relaxed);
    c.total.store(0, std::memory_order_relaxed);
  }
  for (auto& c : gauges_) {
    for (auto& b : c.buckets) b.store(0, std::memory_order_relaxed);
    c.total.store(0, std::memory_order_relaxed);
  }
  for (auto& c : serve_) {
    for (auto& b : c.buckets) b.store(0, std::memory_order_relaxed);
    c.total.store(0, std::memory_order_relaxed);
  }
  for (auto& b : serve_batch_.buckets)
    b.store(0, std::memory_order_relaxed);
  serve_batch_.total.store(0, std::memory_order_relaxed);
}

void PhaseStats::HistJsonInto(std::string* out, bool* first) const {
  for (int p = 0; p < kPhaseCount; ++p) {
    std::string key = std::string("phase:") + kPhaseNames[p];
    AppendCell(out, first, key.c_str(), phases_[p].buckets,
               phases_[p].total);
  }
  for (int g = 0; g < kGaugeCount; ++g) {
    AppendCell(out, first, kPrefetchGaugeKeys[g], gauges_[g].buckets,
               gauges_[g].total);
  }
  for (int s = 0; s < kServePhaseCount; ++s) {
    std::string key = std::string("serve:") + kServePhaseNames[s];
    AppendCell(out, first, key.c_str(), serve_[s].buckets,
               serve_[s].total);
  }
  AppendCell(out, first, kServeBatchKey, serve_batch_.buckets,
             serve_batch_.total);
}

}  // namespace eg
