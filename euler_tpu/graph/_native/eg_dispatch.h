// Persistent shard-request dispatcher for the remote client — now with
// a completion-queue API.
//
// The pre-dispatcher ForShards fan-out spawned and joined one ephemeral
// std::thread per shard on EVERY query (9 call sites in eg_remote.cc) —
// at Reddit-scale batch rates that is thousands of thread create/join
// pairs per second of pure overhead on the hot path, exactly the
// communication tax FastSample (PAPERS.md, arxiv 2311.17847) and the
// pipelined-sampling line (arxiv 2110.08450) say to cut. This replaces
// it with a single long-lived worker pool owned by the RemoteGraph.
//
// Three submission shapes over the same pool:
//
//   * Run(jobs) — the original blocking batch: submit, sleep until
//     every job drained. All pre-async call sites (ForShards /
//     RunChunked / SampleNodeWithSrc) use this unchanged; it is now
//     literally Submit + Wait.
//   * Submit(jobs) -> BatchHandle, then Poll(h) / Wait(h) — the
//     completion-queue form: the caller keeps running and collects
//     completion later. Handles are recycled from a fixed slot pool
//     (the slot owns the job storage, so the caller's frame may unwind
//     immediately); Wait releases the slot.
//   * SubmitDetached(jobs, on_done) — fire-and-continue: the worker
//     that completes the LAST job of the batch runs `on_done` (outside
//     every dispatcher lock), then the slot self-releases. This is the
//     hop-chain primitive of the async sampler (eg_remote.cc
//     SampleFanoutAsync): hop h+1's jobs are enqueued by hop h's
//     completion continuation, never by a blocked caller thread.
//
// One pool shared across all shards rather than one thread per
// ConnPool: chunked requests to a single shard must be issuable
// concurrently over multiple pooled sockets, which a strict
// one-worker-per-pool design cannot do. Per-shard fairness comes from
// FIFO submission order; the ConnPools themselves stay per-shard.
//
// Concurrency contract: jobs must never call Run()/Wait() themselves (a
// job waiting on workers while holding a worker slot can starve the
// pool). Every eg_remote job is a leaf — encode / Call / decode — so
// this holds by construction. Continuations may SUBMIT new batches
// (that is their purpose) but must not block on them. Multiple client
// threads (prefetch workers) may submit concurrently; batches
// interleave on the shared queue and complete independently.
#ifndef EG_DISPATCH_H_
#define EG_DISPATCH_H_

#include "eg_common.h"

#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace eg {

class Dispatcher {
 public:
  // Slot index into the fixed batch pool; valid from Submit until the
  // Wait that releases it.
  using BatchHandle = int;

  // Starts `workers` long-lived threads (clamped to >= 1).
  explicit Dispatcher(int workers);
  // Drains the queue, then stops and joins every worker. No batch may
  // be in flight (the owning RemoteGraph is being destroyed; it drains
  // its async ops first).
  ~Dispatcher();

  Dispatcher(const Dispatcher&) = delete;
  Dispatcher& operator=(const Dispatcher&) = delete;

  // Run every job on the worker pool and block until all complete. The
  // job closures are copied into the batch slot. A throwing job counts
  // as completed: its effects degrade exactly like a failed shard call
  // (callers wrap jobs so failure is recorded before the exception
  // would escape).
  void Run(const std::vector<std::function<void()>>& jobs) const;

  // Non-blocking batch: enqueue `jobs` (storage moves into the slot)
  // and return its handle. Blocks only in the pathological case of all
  // kMaxBatches slots being in flight at once.
  BatchHandle Submit(std::vector<std::function<void()>> jobs) const;

  // True when every job of the batch has completed. Non-blocking; the
  // handle stays valid (poll-loop friendly) until Wait releases it.
  bool Poll(BatchHandle h) const;

  // Block until the batch completes, then recycle its slot. The handle
  // is dead after this returns.
  void Wait(BatchHandle h) const;

  // Detached batch: no handle. The worker completing the last job runs
  // `on_done` (outside the dispatcher and slot locks; exceptions are
  // swallowed — continuations record their own failures), then the
  // slot self-releases. Empty `jobs` runs `on_done` inline on the
  // calling thread.
  void SubmitDetached(std::vector<std::function<void()>> jobs,
                      std::function<void()> on_done) const;

  int workers() const { return static_cast<int>(threads_.size()); }

 private:
  // One slot of the recyclable batch pool. The slot owns its jobs'
  // storage (queue_ tasks point into it) from acquire until release.
  struct Batch {
    std::mutex mu;
    std::condition_variable done;
    size_t remaining EG_GUARDED_BY(mu) = 0;
    bool detached EG_GUARDED_BY(mu) = false;
    std::vector<std::function<void()>> jobs;
    std::function<void()> on_done;
  };
  struct Task {
    const std::function<void()>* fn;  // points into batch->jobs
    Batch* batch;
  };

  // Bounded only to keep handles small and recycling trivial: the sync
  // paths hold at most one slot per calling thread, the async sampler
  // at most one per in-flight op.
  static constexpr int kMaxBatches = 64;

  // Take a free slot (blocking when all are in flight) and arm it.
  int AcquireSlot(std::vector<std::function<void()>> jobs, bool detached,
                  std::function<void()> on_done) const;
  void ReleaseSlot(int slot) const;
  // Push the armed slot's jobs onto the shared queue and wake workers.
  void Enqueue(int slot) const;
  void WorkerLoop();

  mutable std::mutex mu_;  // guards queue_ and stop_
  mutable std::condition_variable cv_;
  mutable std::deque<Task> queue_ EG_GUARDED_BY(mu_);
  bool stop_ EG_GUARDED_BY(mu_) = false;

  // Slot pool. The Batch objects themselves live for the dispatcher's
  // lifetime; free_ holds the indices currently available.
  mutable std::mutex pool_mu_;
  mutable std::condition_variable pool_cv_;
  mutable std::deque<int> free_ EG_GUARDED_BY(pool_mu_);
  mutable std::unique_ptr<Batch[]> batches_;

  std::vector<std::thread> threads_;
};

}  // namespace eg

#endif  // EG_DISPATCH_H_
