// Persistent shard-request dispatcher for the remote client.
//
// The pre-dispatcher ForShards fan-out spawned and joined one ephemeral
// std::thread per shard on EVERY query (9 call sites in eg_remote.cc) —
// at Reddit-scale batch rates that is thousands of thread create/join
// pairs per second of pure overhead on the hot path, exactly the
// communication tax FastSample (PAPERS.md, arxiv 2311.17847) and the
// pipelined-sampling line (arxiv 2110.08450) say to cut. This replaces
// it with a single long-lived worker pool owned by the RemoteGraph:
// callers submit a batch of independent jobs (one per shard, or several
// per shard when a large request is split into chunks) and block until
// the batch completes.
//
// One pool shared across all shards rather than one thread per
// ConnPool: chunked requests to a single shard must be issuable
// concurrently over multiple pooled sockets, which a strict
// one-worker-per-pool design cannot do. Per-shard fairness comes from
// FIFO submission order; the ConnPools themselves stay per-shard.
//
// Concurrency contract: jobs must never call Run() themselves (a job
// waiting on workers while holding a worker slot can starve the pool).
// Every eg_remote job is a leaf — encode / Call / decode — so this
// holds by construction. Multiple client threads (prefetch workers) may
// call Run() concurrently; batches interleave on the shared queue and
// complete independently.
#ifndef EG_DISPATCH_H_
#define EG_DISPATCH_H_

#include "eg_common.h"

#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace eg {

class Dispatcher {
 public:
  // Starts `workers` long-lived threads (clamped to >= 1).
  explicit Dispatcher(int workers);
  // Drains the queue, then stops and joins every worker. No Run() may be
  // in flight (the owning RemoteGraph is being destroyed).
  ~Dispatcher();

  Dispatcher(const Dispatcher&) = delete;
  Dispatcher& operator=(const Dispatcher&) = delete;

  // Run every job on the worker pool and block until all complete. The
  // job closures are borrowed (the caller's vector must outlive the
  // call — it does, Run blocks). A throwing job counts as completed:
  // its effects degrade exactly like a failed shard call (callers wrap
  // jobs so failure is recorded before the exception would escape).
  void Run(const std::vector<std::function<void()>>& jobs) const;

  int workers() const { return static_cast<int>(threads_.size()); }

 private:
  struct Batch {
    std::mutex mu;
    std::condition_variable done;
    size_t remaining = 0;
  };
  struct Task {
    const std::function<void()>* fn;
    Batch* batch;
  };

  void WorkerLoop();

  mutable std::mutex mu_;  // guards queue_ and stop_
  mutable std::condition_variable cv_;
  mutable std::deque<Task> queue_ EG_GUARDED_BY(mu_);
  bool stop_ EG_GUARDED_BY(mu_) = false;
  std::vector<std::thread> threads_;
};

}  // namespace eg

#endif  // EG_DISPATCH_H_
