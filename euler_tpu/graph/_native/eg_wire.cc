#include "eg_wire.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

#include "eg_fault.h"
#include "eg_stats.h"

namespace eg {

namespace {

IoStatus WriteAll(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) return IoStatus::kTimeout;
      return IoStatus::kClosed;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return IoStatus::kOk;
}

IoStatus ReadAll(int fd, char* p, size_t n) {
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) return IoStatus::kTimeout;
      return IoStatus::kClosed;
    }
    if (r == 0) return IoStatus::kClosed;  // peer closed
    p += r;
    n -= static_cast<size_t>(r);
  }
  return IoStatus::kOk;
}

void SetTimeouts(int fd, int timeout_ms) {
  if (timeout_ms <= 0) return;
  timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

}  // namespace

IoStatus SendFrameEx(int fd, const std::string& payload) {
  if (FaultHit(kFaultSendFrame)) return IoStatus::kClosed;
  uint32_t len = static_cast<uint32_t>(payload.size());
  if (payload.size() > kMaxFrame) return IoStatus::kReject;
  char hdr[4];
  std::memcpy(hdr, &len, 4);
  IoStatus s = WriteAll(fd, hdr, 4);
  if (s != IoStatus::kOk) return s;
  return WriteAll(fd, payload.data(), payload.size());
}

bool SendFrame(int fd, const std::string& payload) {
  return SendFrameEx(fd, payload) == IoStatus::kOk;
}

IoStatus RecvFrameEx(int fd, std::string* payload) {
  char hdr[4];
  IoStatus s = ReadAll(fd, hdr, 4);
  if (s != IoStatus::kOk) return s;
  // Fires after the header — a frame demonstrably began arriving — so an
  // injected fault is a true mid-frame reset (bytes lost, connection
  // must be discarded). Deliberately NOT at entry: a server handler
  // parked between requests would otherwise draw from the stream while
  // idle, making fault accounting depend on scheduler timing.
  if (FaultHit(kFaultRecvFrame)) return IoStatus::kClosed;
  uint32_t len;
  std::memcpy(&len, hdr, 4);
  if (len > kMaxFrame) {
    Counters::Global().Add(kCtrFrameReject);
    return IoStatus::kReject;
  }
  payload->resize(len);
  if (len == 0) return IoStatus::kOk;
  return ReadAll(fd, payload->data(), len);
}

bool RecvFrame(int fd, std::string* payload) {
  return RecvFrameEx(fd, payload) == IoStatus::kOk;
}

// ---- versioned request envelope ----

std::string WrapEnvelope(const std::string& payload, int64_t deadline_ms,
                         uint8_t version, uint64_t trace_id,
                         uint64_t epoch) {
  std::string out;
  out.reserve(payload.size() + 26);
  out.push_back(static_cast<char>(kWireEnvelope));
  out.push_back(static_cast<char>(version));
  char buf[8];
  std::memcpy(buf, &deadline_ms, 8);
  out.append(buf, 8);
  if (version >= 3) {
    std::memcpy(buf, &trace_id, 8);
    out.append(buf, 8);
  }
  if (version >= 4) {
    std::memcpy(buf, &epoch, 8);
    out.append(buf, 8);
  }
  out.append(payload);
  return out;
}

bool PeekEnvelope(const std::string& payload, Envelope* env) {
  *env = Envelope();
  if (payload.empty() ||
      static_cast<uint8_t>(payload[0]) != kWireEnvelope)
    return true;  // plain v1 request
  if (payload.size() < 10) return false;  // truncated envelope header
  env->versioned = true;
  env->version = static_cast<uint8_t>(payload[1]);
  std::memcpy(&env->deadline_ms, payload.data() + 2, 8);
  env->body_off = 10;
  if (env->version == 3 || env->version == 4) {
    // exactly the versions this build KNOWS read past the common header;
    // FUTURE versions keep the 10-byte parse (the server answers
    // kStatusBadVersion before the body offset could matter, so an
    // unknown layout never misparses)
    if (payload.size() < 18) return false;
    std::memcpy(&env->trace_id, payload.data() + 10, 8);
    env->body_off = 18;
    if (env->version == 4) {
      if (payload.size() < 26) return false;
      std::memcpy(&env->epoch, payload.data() + 18, 8);
      env->body_off = 26;
    }
  }
  return true;
}

std::string StatusReply(uint8_t status, const std::string& msg) {
  WireWriter w;
  w.U8(status);
  w.Str(msg);
  return std::move(w.buf());
}

int DialTcp(const std::string& host, int port, int timeout_ms) {
  if (FaultHit(kFaultDial)) return -1;
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (::getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0)
    return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    SetTimeouts(fd, timeout_ms);
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

int ListenTcp(const std::string& host, int port, int* bound_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host.empty() || host == "0.0.0.0") {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  if (::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen) == 0)
    *bound_port = ntohs(addr.sin_port);
  else
    *bound_port = port;
  return fd;
}

}  // namespace eg
