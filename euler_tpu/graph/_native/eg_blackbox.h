// Always-on flight recorder + fatal-signal postmortem dumps.
//
// Everything the observability stack built so far — counters, span
// timers, histograms, journals, the STATS scrape — lives in process
// memory and answers questions about a LIVE process. When a shard
// SIGSEGVs, a handler deadlocks into an abort, or an OOM kill takes a
// replica, all of it evaporates with the address space: the operator
// learns that a process died, never what it was doing in its final
// seconds. Production GNN serving treats that gap as unacceptable (the
// operational failure analyses behind FastSample, arXiv:2311.17847,
// and pipelined sampling, arXiv:2110.08450, attribute most lost
// cluster time to UNATTRIBUTED stalls and crashes). This layer closes
// it with three pieces:
//
//   * a lock-free per-thread ring FLIGHT RECORDER: fixed-slot event
//     records (point, op, shard, trace id, wire bytes / µs value,
//     outcome, CLOCK_MONOTONIC µs) written with a handful of relaxed
//     stores per event and zero allocation on the hot path, fed from
//     the same hook points eg_telemetry already instruments
//     (ConnPool::Call, AdmissionServer::ServeConn, the dispatcher
//     workers, eg_phase);
//   * a FATAL-SIGNAL path: async-signal-safe handlers for
//     SIGSEGV/SIGBUS/SIGABRT/SIGFPE that write a postmortem file —
//     the raw rings, the full eg_counters ledger, the admission
//     gauges, a backtrace, and the resource-gauge history — using
//     only open/write/atomic loads and a fixed-format integer writer
//     (no malloc, no stdio, no locks), then re-raise with the default
//     disposition so the exit status still names the signal;
//   * RESOURCE GAUGES (RSS, open fds, live threads, client cache
//     bytes) sampled by a low-rate background thread into a 60-entry
//     history ring, answerable live through Telemetry::Json (the
//     "resource" section every metrics surface inherits) and the
//     kHistory wire opcode, and frozen into every postmortem.
//
// Postmortem file format (OBSERVABILITY.md "Postmortems"): line 1 is
// one JSON document; any following lines are backtrace_symbols_fd
// output (human-readable frames — produced OUTSIDE the JSON because
// symbolization must not allocate inside a signal handler).
// euler_tpu.postmortem_read() parses both halves.
//
// Kill-switch: `blackbox=` (graph config key / service option /
// eg_blackbox_set_enabled), default ON — disabled, every hook is one
// relaxed load and a fatal signal writes NOTHING (the handler still
// re-raises). Handlers install only when a postmortem dir is set.
#ifndef EG_BLACKBOX_H_
#define EG_BLACKBOX_H_

#include "eg_common.h"

#include <atomic>
#include <cstdint>
#include <string>

namespace eg {

// Where in the stack an event was recorded. Fixed order — the JSON
// emitters and euler_tpu/blackbox.py name points by this table.
enum BlackboxPoint : uint8_t {
  kBbClientCall = 0,  // ConnPool::Call finished (ok or failed)
  kBbServerRecv,      // admission worker decoded a request envelope
  kBbServerReply,     // admission worker sent (or dropped) its reply
  kBbDispatch,        // dispatcher worker began a per-shard job
  kBbPhase,           // step-phase sample (op = StepPhase index)
  kBbApp,             // app-level event via the eg_blackbox_record ABI
  kBbPointCount,
};

const char* const kBbPointNames[kBbPointCount] = {
    "client_call", "server_recv", "server_reply",
    "dispatch",    "phase",       "app",
};

// One fixed ring slot. Fields are individually-atomic so concurrent
// live readers (eg_blackbox_json, the signal handler on another
// thread's stack) race benignly under TSAN: a torn EVENT (half old,
// half new) is possible at the ring seam, a torn FIELD is not.
struct BlackboxEvent {
  std::atomic<int64_t> t_us{0};    // CLOCK_MONOTONIC µs at record
  std::atomic<uint64_t> trace{0};  // wire-v3 trace id; 0 = none
  std::atomic<uint64_t> value{0};  // wire bytes (rpc), µs (phase), free
  std::atomic<int32_t> shard{-1};
  std::atomic<uint8_t> point{0};
  std::atomic<uint8_t> op{0};
  std::atomic<uint8_t> outcome{0};
};

constexpr int kBbRingSlots = 256;  // per-thread tail, ~the final seconds
constexpr int kBbMaxRings = 64;    // fixed pool: no allocation, ever

// Single-writer ring. head counts events EVER written by the owning
// thread; slot (head % kBbRingSlots) is the next write target, so the
// resident window is [head - min(head, slots), head) oldest-first —
// the eviction order the wraparound test pins. Rings outlive their
// threads: a worker that died an hour ago still shows its tail in the
// postmortem.
struct BlackboxRing {
  std::atomic<uint64_t> head{0};
  std::atomic<uint64_t> tid{0};  // OS tid label; 0 = slot unclaimed
  BlackboxEvent slots[kBbRingSlots];
};

// One resource-gauge sample as read from /proc (plain fields — a
// local value, never shared).
struct ResourceSample {
  int64_t t_us = 0;
  int64_t rss_bytes = 0;    // /proc/self/statm resident pages * pagesize
  int64_t open_fds = 0;     // entries in /proc/self/fd
  int64_t threads = 0;      // /proc/self/status Threads:
  int64_t cache_bytes = 0;  // client feature-cache bytes (eg_cache.h)
  int64_t nbr_cache_bytes = 0;  // client neighbor-list cache bytes
  int64_t device_mem_bytes = 0;  // device bytes in use (eg_devprof.h —
                                 // memory_stats() or live-array census)
  int64_t device_buffers = 0;    // live device buffer count
};

// A history-ring slot: individually-atomic fields, same reasoning as
// BlackboxEvent — the sampler overwrites wrapped slots while dumps and
// scrapes read them, and a torn SAMPLE at the seam is acceptable where
// a torn FIELD is not.
struct ResourceCell {
  std::atomic<int64_t> t_us{0};
  std::atomic<int64_t> rss_bytes{0};
  std::atomic<int64_t> open_fds{0};
  std::atomic<int64_t> threads{0};
  std::atomic<int64_t> cache_bytes{0};
  std::atomic<int64_t> device_mem_bytes{0};

  void Store(const ResourceSample& s) {
    t_us.store(s.t_us, std::memory_order_relaxed);
    rss_bytes.store(s.rss_bytes, std::memory_order_relaxed);
    open_fds.store(s.open_fds, std::memory_order_relaxed);
    threads.store(s.threads, std::memory_order_relaxed);
    cache_bytes.store(s.cache_bytes, std::memory_order_relaxed);
    device_mem_bytes.store(s.device_mem_bytes, std::memory_order_relaxed);
  }
  ResourceSample Load() const {
    ResourceSample s;
    s.t_us = t_us.load(std::memory_order_relaxed);
    s.rss_bytes = rss_bytes.load(std::memory_order_relaxed);
    s.open_fds = open_fds.load(std::memory_order_relaxed);
    s.threads = threads.load(std::memory_order_relaxed);
    s.cache_bytes = cache_bytes.load(std::memory_order_relaxed);
    s.device_mem_bytes = device_mem_bytes.load(std::memory_order_relaxed);
    return s;
  }
};

constexpr int kBbHistorySlots = 60;

// Last-refreshed admission gauges (eg_admission.cc PollerLoop stores
// them every cycle, <=250 ms stale): the signal handler must not call
// into a server object that may be mid-teardown, so it reads this POD
// snapshot instead.
struct AdmissionSnap {
  std::atomic<int> registered{0};
  std::atomic<int> workers{0};
  std::atomic<int> active{0};
  std::atomic<int> queue_depth{0};
  std::atomic<int> conns{0};
  std::atomic<int> draining{0};
};

AdmissionSnap& AdmissionGaugeSnap();

class Blackbox {
 public:
  static Blackbox& Global();

  bool enabled() const { return enabled_.load(std::memory_order_relaxed); }
  void SetEnabled(bool on) {
    enabled_.store(on, std::memory_order_relaxed);
  }

  // One flight-recorder event: a handful of relaxed stores into this
  // thread's ring (claimed from the fixed pool on first use); a single
  // relaxed load when disabled. Never allocates, never locks.
  void Record(uint8_t point, uint8_t op, int32_t shard, uint64_t trace,
              uint64_t value, uint8_t outcome);

  // Arm the postmortem path: remember the dump directory + this
  // process's shard index, install the fatal-signal handlers
  // (SIGSEGV/SIGBUS/SIGABRT/SIGFPE), and start the resource sampler
  // thread (period sample_ms, min 50; 0 keeps a previous/default
  // period). Re-invocable: later calls update dir/shard. False +
  // error() when the directory is not writable.
  bool Install(const std::string& postmortem_dir, int shard,
               int sample_ms = 0);
  std::string error() const {
    std::lock_guard<std::mutex> l(install_mu_);
    return error_;
  }
  int shard() const { return shard_.load(std::memory_order_relaxed); }

  // One fresh resource sample read from /proc (NOT signal-safe; the
  // sampler thread and the JSON surfaces use it — the signal handler
  // reads the history ring instead).
  static ResourceSample SampleResources();

  // Write a postmortem dump to `path` (manual path: run_loop's
  // crash-on-unhandled-exception hook, tests). sig 0 = not a signal.
  // Uses the same async-signal-safe builder as the handler. False on
  // open failure or blackbox disabled.
  bool WriteDump(const char* path, int sig);

  // Live JSON: {"enabled","shard","postmortem_dir","dropped","rings":
  // [{tid,head,events:[...]}],"resource":{...},"history":[...]} — the
  // console `stats blackbox` / eg_blackbox_json surface.
  std::string LiveJson();

  // Resource history JSON for the kHistory wire reply:
  // {"shard","resource":{latest},"history":[{t_us,rss_bytes,...}]}.
  std::string HistoryJson(int shard);

  // Append `,"resource":{...}` (latest live sample + history depth) to
  // an in-progress JSON object — Telemetry::Json calls this so every
  // existing metrics surface (metrics_text, snapshot, STATS scrape,
  // metrics_dump) inherits the gauges with zero new plumbing.
  void ResourceJsonInto(std::string* out);

  // Reset the rings + drop ledger (NOT the enabled flag or the
  // installed handlers) — the clean-slate primitive tests use.
  void Reset();

  // -- internals shared with the signal handler (must stay signal-safe)
  void DumpToFd(int fd, int sig);
  const char* postmortem_path() const { return dump_path_; }

 private:
  Blackbox() = default;
  BlackboxRing* ThreadRing();
  void SamplerLoop();
  void AppendHistory(const ResourceSample& s);
  // `{rss_bytes,...,history_depth}` object body shared by the live
  // surfaces (NOT the signal path — it samples /proc).
  void ResourceJsonBody(std::string* out);

  std::atomic<bool> enabled_{true};
  std::atomic<int> shard_{-1};
  std::atomic<int> next_ring_{0};
  std::atomic<uint64_t> dropped_{0};  // events lost to pool exhaustion
  BlackboxRing rings_[kBbMaxRings];

  // resource history: single writer (sampler thread), atomic head
  std::atomic<uint64_t> hist_head_{0};
  ResourceCell history_[kBbHistorySlots];

  // fixed-size dump path: composed at Install so the handler never
  // touches std::string
  char dump_path_[512] = {0};
  std::atomic<bool> installed_{false};
  std::atomic<int> sample_ms_{1000};
  std::atomic<bool> sampler_running_{false};
  // Install/config strings: written only under install_mu_ (Install is
  // the cold init path); surfaces that read them take the same lock.
  mutable std::mutex install_mu_;
  std::string error_ EG_GUARDED_BY(install_mu_);
  std::string dir_ EG_GUARDED_BY(install_mu_);
};

}  // namespace eg

#endif  // EG_BLACKBOX_H_
