// Immutable in-memory heterogeneous graph store, flat SoA layout.
//
// Functional equivalent of the reference's graph core
// (reference euler/core/graph.h, compact_graph.cc, compact_node.cc,
// graph_builder.cc) with a different architecture: instead of a hash map of
// per-node heap objects each owning little vectors, everything lives in a
// handful of flat arrays (global CSR over [node x edge_type] groups, feature
// CSRs, edge SoA). The store is immutable after Build(), so all reads are
// lock-free, cache-friendly, and trivially parallel — which is what matters
// when one host CPU must keep TPU chips fed.
//
// On-disk format: the reference's length-prefixed block .dat format
// (spec derived from /root/reference/euler/tools/json2dat.py:40-175 and the
// framing check in /root/reference/euler/core/graph_builder.cc:166-224), so
// existing converters and fixtures interoperate.
#ifndef EG_GRAPH_H_
#define EG_GRAPH_H_

#include <cstdint>
#include <string>
#include <unordered_map>
#include <vector>

#include "eg_common.h"
#include "eg_sampling.h"

namespace eg {

// Mutable staging area one loader thread fills while parsing blocks.
// Concatenated into the final store by GraphStore::Build.
struct Staging {
  // Slot/type counts discovered from records (must be uniform).
  int32_t edge_type_num = -1;
  int32_t nf_u64_num = -1, nf_f32_num = -1, nf_bin_num = -1;
  int32_t ef_u64_num = -1, ef_f32_num = -1, ef_bin_num = -1;

  std::vector<uint64_t> node_ids;
  std::vector<int32_t> node_types;
  std::vector<float> node_weights;
  std::vector<int32_t> grp_counts;   // [nodes * edge_type_num]
  std::vector<float> grp_weights;    // [nodes * edge_type_num]
  std::vector<uint64_t> nbr_ids;     // per group, sorted by id
  std::vector<float> nbr_w;

  std::vector<int32_t> nf_u64_cnt;   // [nodes * nf_u64_num]
  std::vector<uint64_t> nf_u64_val;
  std::vector<int32_t> nf_f32_cnt;
  std::vector<float> nf_f32_val;
  std::vector<int32_t> nf_bin_cnt;
  std::string nf_bin_val;

  std::vector<uint64_t> e_src, e_dst;
  std::vector<int32_t> e_type;
  std::vector<float> e_w;
  std::vector<int32_t> ef_u64_cnt;
  std::vector<uint64_t> ef_u64_val;
  std::vector<int32_t> ef_f32_cnt;
  std::vector<float> ef_f32_val;
  std::vector<int32_t> ef_bin_cnt;
  std::string ef_bin_val;

  std::string error;  // non-empty on parse failure

  // Parse every block in `data` (the full contents of one .dat partition).
  bool ParseFile(const char* data, size_t size);

 private:
  bool ParseBlock(ByteCursor* cur);
  bool ParseEdgeRecord(const char* data, size_t size);
};

class GraphStore {
 public:
  // Merge staging partitions (in deterministic order) and build samplers,
  // hash indexes, and cumulative weights. Returns false + error on mismatch.
  bool Build(std::vector<Staging>* parts, std::string* error);

  // ---- introspection ----
  size_t num_nodes() const { return node_ids_.size(); }
  size_t num_edges() const { return e_src_.size(); }
  int32_t node_type_num() const { return node_type_num_; }
  int32_t edge_type_num() const { return edge_type_num_; }
  int32_t nf_u64_num() const { return nf_u64_num_; }
  int32_t nf_f32_num() const { return nf_f32_num_; }
  int32_t nf_bin_num() const { return nf_bin_num_; }
  int32_t ef_u64_num() const { return ef_u64_num_; }
  int32_t ef_f32_num() const { return ef_f32_num_; }
  int32_t ef_bin_num() const { return ef_bin_num_; }
  // Per-type weight sums (used for cross-shard weighted global sampling,
  // cf. reference euler/core/graph_engine.h:136-164).
  const std::vector<float>& node_type_weight_sums() const {
    return node_type_wsum_;
  }
  const std::vector<float>& edge_type_weight_sums() const {
    return edge_type_wsum_;
  }

  // ---- lookup ----
  // Returns -1 if the id is not present.
  inline int64_t NodeIndex(uint64_t id) const {
    auto it = node_idx_.find(id);
    return it == node_idx_.end() ? -1 : it->second;
  }
  inline int64_t EdgeIndex(uint64_t src, uint64_t dst, int32_t type) const {
    auto it = edge_idx_.find(EdgeKey{src, dst, type});
    return it == edge_idx_.end() ? -1 : it->second;
  }
  inline int32_t NodeTypeAt(int64_t idx) const { return node_types_[idx]; }
  inline float NodeWeightAt(int64_t idx) const { return node_weights_[idx]; }
  uint64_t NodeIdAt(int64_t idx) const { return node_ids_[idx]; }

  // ---- global sampling (weight-proportional) ----
  // type == -1: sample the type first by weight sum, then a node within it
  // (semantics of reference euler/core/compact_graph.cc:32-56).
  uint64_t SampleNode(int32_t type, Rng& rng) const;
  // Returns edge index, -1 when no edge matches.
  int64_t SampleEdgeIdx(int32_t type, Rng& rng) const;
  uint64_t EdgeSrcAt(int64_t idx) const { return e_src_[idx]; }
  uint64_t EdgeDstAt(int64_t idx) const { return e_dst_[idx]; }
  int32_t EdgeTypeAt(int64_t idx) const { return e_type_[idx]; }

  // ---- per-node adjacency ----
  // Weighted draw of `count` neighbors (with replacement) restricted to the
  // given edge types. Fills default_id/weight 0/type -1 when the node has no
  // matching neighbors (semantics of reference
  // tf_euler/kernels/sample_neighbor_op.cc:43-82 + compact_node.cc:42-101).
  void SampleNeighbors(int64_t nidx, const int32_t* etypes, int net, int count,
                       uint64_t default_id, Rng& rng, uint64_t* out_ids,
                       float* out_w, int32_t* out_t) const;

  // Append all neighbors in the given edge types. If `sorted`, merge groups
  // ascending by neighbor id (groups are already id-sorted).
  void FullNeighbors(int64_t nidx, const int32_t* etypes, int net, bool sorted,
                     std::vector<uint64_t>* ids, std::vector<float>* w,
                     std::vector<int32_t>* t) const;

  // Top-k by weight (descending), padded with default_id/0/-1.
  void TopKNeighbors(int64_t nidx, const int32_t* etypes, int net, int k,
                     uint64_t default_id, uint64_t* out_ids, float* out_w,
                     int32_t* out_t) const;

  // node2vec-biased single draw given the previous walk node (parent).
  // Weight scaling w/p for return, w for distance-1, w/q for distance-2
  // (semantics of reference euler/client/graph.cc:120-151). has_parent=false
  // on the first hop degrades to a plain weighted draw.
  uint64_t BiasedNeighbor(int64_t nidx, bool has_parent, uint64_t parent_id,
                          const int32_t* etypes, int net, float p, float q,
                          uint64_t default_id, Rng& rng) const;

  // ---- features ----
  // Copy up to `dim` float values of feature slot `fid`; zero-pad the rest.
  void DenseFeature(int64_t nidx, int32_t fid, int32_t dim, float* out) const;
  void EdgeDenseFeature(int64_t eidx, int32_t fid, int32_t dim,
                        float* out) const;
  // Raw spans for variable-length gathers.
  void U64Feature(int64_t nidx, int32_t fid, const uint64_t** vals,
                  int64_t* count) const;
  void EdgeU64Feature(int64_t eidx, int32_t fid, const uint64_t** vals,
                      int64_t* count) const;
  void F32Feature(int64_t nidx, int32_t fid, const float** vals,
                  int64_t* count) const;
  void EdgeF32Feature(int64_t eidx, int32_t fid, const float** vals,
                      int64_t* count) const;
  void BinFeature(int64_t nidx, int32_t fid, const char** data,
                  int64_t* size) const;
  void EdgeBinFeature(int64_t eidx, int32_t fid, const char** data,
                      int64_t* size) const;

 private:
  friend class Engine;

  inline const float* GroupCum(int64_t nidx, int32_t t, int64_t* n) const {
    int64_t g = nidx * edge_type_num_ + t;
    *n = adj_off_[g + 1] - adj_off_[g];
    return adj_cumw_.data() + adj_off_[g];
  }

  int32_t node_type_num_ = 0, edge_type_num_ = 0;
  int32_t nf_u64_num_ = 0, nf_f32_num_ = 0, nf_bin_num_ = 0;
  int32_t ef_u64_num_ = 0, ef_f32_num_ = 0, ef_bin_num_ = 0;

  std::vector<uint64_t> node_ids_;
  std::vector<int32_t> node_types_;
  std::vector<float> node_weights_;

  std::vector<int64_t> adj_off_;   // [nodes * edge_type_num + 1]
  std::vector<uint64_t> adj_nbr_;  // id-sorted within each group
  std::vector<float> adj_w_;
  std::vector<float> adj_cumw_;    // cumulative within group
  std::vector<float> grp_w_;       // [nodes * edge_type_num]

  std::vector<int64_t> nf_u64_off_;  // [nodes * nf_u64_num + 1]
  std::vector<uint64_t> nf_u64_val_;
  std::vector<int64_t> nf_f32_off_;
  std::vector<float> nf_f32_val_;
  std::vector<int64_t> nf_bin_off_;
  std::string nf_bin_val_;

  std::vector<uint64_t> e_src_, e_dst_;
  std::vector<int32_t> e_type_;
  std::vector<float> e_w_;
  std::vector<int64_t> ef_u64_off_;
  std::vector<uint64_t> ef_u64_val_;
  std::vector<int64_t> ef_f32_off_;
  std::vector<float> ef_f32_val_;
  std::vector<int64_t> ef_bin_off_;
  std::string ef_bin_val_;

  std::unordered_map<uint64_t, int64_t> node_idx_;
  std::unordered_map<EdgeKey, int64_t, EdgeKeyHash> edge_idx_;

  // Global weight-proportional samplers, one alias table per type plus a
  // type-level prefix table (cf. reference compact_graph.cc:74-104).
  std::vector<std::vector<int64_t>> nodes_by_type_;
  std::vector<AliasTable> node_samplers_;
  PrefixTable node_type_sampler_;
  std::vector<float> node_type_wsum_;

  std::vector<std::vector<int64_t>> edges_by_type_;
  std::vector<AliasTable> edge_samplers_;
  PrefixTable edge_type_sampler_;
  std::vector<float> edge_type_wsum_;
};

}  // namespace eg

#endif  // EG_GRAPH_H_
