// Client-side caches for the remote graph client: dense feature rows
// and (new) neighbor adjacency slices, both frequency-aware.
//
// Each SNAPSHOT of the graph is immutable (eg_epoch.h: a delta load
// builds a fresh snapshot and flips the serving epoch; nothing mutates
// in place), so anything fetched once is valid for as long as the
// client's cache GENERATION stands. Every Get/Put/Sample carries the
// caller's current generation (RemoteGraph bumps it when any shard's
// announced epoch moves): entries remember the generation they were
// filled under, and a hit from an older generation is erased on the
// spot (counted in `epoch_stale_hits_evicted`) and reported as a miss —
// lazy invalidation, no flush sweep, no wrong-epoch row ever returned.
// Static deployments never bump the generation and keep the original
// fetched-once-valid-forever behavior. On heavy-tail graphs the same
// hub rows are refetched endlessly
// by successive batches (hubs carry most edge mass, so every fanout
// lands on them); caching them client-side removes those rows from the
// wire entirely.
//
// Admission (PR 9, ROADMAP item 5): pure FIFO held 86.4% on the
// reddit_heavytail stream with its misses concentrated in a churn tail
// (PERF.md "Data-plane heat" cache-efficacy classes). Both caches now
// default to FREQUENCY-AWARE admission in the TinyLFU shape: when a
// stripe is full, the candidate is admitted only if its estimated
// access frequency beats the FIFO victim's — the estimator is eg_heat's
// client count-min sketch, which the query paths already feed with
// every id PRE-cache (so a candidate's current access is counted).
// Hot hub rows therefore pin instead of churning; a cold scan cannot
// flush them. `cache_policy=fifo` restores unconditional admission, and
// the policy silently degrades to FIFO while the heat estimator is
// disabled (no estimates -> no grounds to reject). Rejections are
// counted (`cache_admit_rejects`).
//
// FeatureCache — keyed by (feature-spec hash, node id): the same id
// requested with different fids/dims is a different row, so the spec
// participates in the key and is verified on hit (a 64-bit map-key
// collision degrades to a miss, never to a wrong row). Striped locking,
// FIFO eviction order under the admission filter. Config key
// `feature_cache_mb=` (remote graphs; default on, 0 disables).
//
// NeighborCache — keyed by (edge-type-spec hash, node id): one entry is
// a node's FULL adjacency slice over the requested edge types (ids,
// weights, types, plus the weight prefix sums), fetched once via
// kFullNeighbor when the heat sketch marks the node hot, then every
// later SampleNeighbor draw for it is served locally: Sample() draws
// proportional to edge weight against the stored prefix sums — the
// exact distribution the shard engine samples from
// (GraphStore::SampleNeighbors), so repeated hub hops stop crossing the
// wire at all while staying distribution-identical. Config key
// `neighbor_cache_mb=` (remote graphs; default on, 0 disables);
// counters `nbr_cache_hits`/`nbr_cache_misses`.
#ifndef EG_CACHE_H_
#define EG_CACHE_H_

#include <atomic>
#include <cstdint>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "eg_common.h"

namespace eg {

// Process-global resident-byte gauges (one per cache kind, in practice
// one cache of each per RemoteGraph): stripes add/subtract their deltas
// so the blackbox resource sampler (eg_blackbox.h) and the fatal-signal
// dump can read cache pressure with one relaxed load — a postmortem
// must not walk stripe mutexes.
std::atomic<int64_t>& GlobalCacheBytes();
std::atomic<int64_t>& GlobalNbrCacheBytes();

// Admission policies (`cache_policy=` config key, shared by both
// caches; default frequency-aware).
enum CachePolicy : int {
  kCachePolicyFifo = 0,  // always admit; evict FIFO
  kCachePolicyFreq = 1,  // TinyLFU shape: admit only over the victim
};

// The shared TinyLFU admission decision: should `candidate` displace
// `victim`? True when the client heat sketch estimates the candidate's
// access frequency strictly above the victim's; always true under FIFO
// policy or while the estimator is disabled. Exposed (rather than
// private to the caches) so tests can pin the decision against a
// hand-computed sketch without driving a full eviction scenario.
bool CacheAdmit(int policy, uint64_t candidate, uint64_t victim);

class FeatureCache {
 public:
  ~FeatureCache();  // returns resident bytes to the global gauge

  // Total byte budget across stripes; 0 disables (Get misses, Put drops).
  void SetCapacity(size_t budget);
  bool enabled() const { return cap_ != 0; }
  // Admission policy (CachePolicy); default frequency-aware.
  void SetPolicy(int policy) { policy_ = policy; }
  int policy() const { return policy_; }

  // FNV-1a over the (fids, dims) request shape — the spec half of the key.
  static uint64_t SpecHash(const int32_t* fids, const int32_t* dims, int nf);

  // On hit, copy row_dim floats into out and return true. `gen` is the
  // caller's cache generation: an entry filled under an older one is
  // evicted here (epoch_stale_hits_evicted) and the probe misses.
  bool Get(uint64_t spec, uint64_t id, float* out, size_t row_dim,
           uint64_t gen);
  // Insert a fetched row tagged with the caller's generation (no-op
  // when disabled, already present at this generation, or rejected by
  // frequency-aware admission — rejections counted). A resident entry
  // from an older generation is replaced, not kept.
  void Put(uint64_t spec, uint64_t id, const float* row, size_t row_dim,
           uint64_t gen);

  // Resident payload bytes (approximate: entry overhead included) —
  // observability for tests pinning the capacity bound.
  size_t bytes() const;

 private:
  struct Entry {
    uint64_t spec;
    uint64_t id;
    uint64_t gen;  // cache generation the row was filled under
    std::vector<float> row;
  };
  struct Stripe {
    mutable std::mutex mu;
    std::unordered_map<uint64_t, Entry> map EG_GUARDED_BY(mu);
    // insertion order of map keys
    std::deque<uint64_t> fifo EG_GUARDED_BY(mu);
    size_t bytes EG_GUARDED_BY(mu) = 0;
  };
  static constexpr int kStripes = 16;
  // ~per-entry bookkeeping cost charged against the budget on top of the
  // row payload (map node + fifo slot + Entry header).
  static constexpr size_t kEntryOverhead = 96;

  static uint64_t Mix(uint64_t spec, uint64_t id);

  size_t cap_ = 0;
  int policy_ = kCachePolicyFreq;
  Stripe stripes_[kStripes];
};

// Minimum client-sketch frequency estimate at which SampleNeighbor
// promotes a missed node into the neighbor cache (fetching its full
// adjacency costs one kFullNeighbor round; a node must be provably hot
// before that spend amortizes). Deliberately a small power of two so
// the promotion point is easy to drive deterministically in tests.
constexpr uint64_t kNbrPromoteMinFreq = 8;

class NeighborCache {
 public:
  ~NeighborCache();  // returns resident bytes to the global gauge

  void SetCapacity(size_t budget);
  bool enabled() const { return cap_ != 0; }
  void SetPolicy(int policy) { policy_ = policy; }

  // FNV-1a over the requested edge-type set — the spec half of the key
  // (the same id asked with different etypes is a different slice).
  static uint64_t SpecHash(const int32_t* etypes, int net);

  // On hit, draw `count` neighbors proportional to edge weight from the
  // cached slice into out_* (the GraphStore::SampleNeighbors
  // distribution: weight-proportional across the union of the
  // requested edge-type groups; an empty or zero-weight slice fills
  // default_id/-1 like the engine does) and return true. A slice filled
  // under an older generation than `gen` is evicted and the probe
  // misses (epoch_stale_hits_evicted).
  bool Sample(uint64_t spec, uint64_t id, int count, uint64_t default_id,
              Rng& rng, uint64_t* out_ids, float* out_w, int32_t* out_t,
              uint64_t gen);

  // Insert one node's full adjacency slice over the spec's edge types
  // (parallel arrays, n entries; n == 0 caches the empty slice — a
  // leaf hub's "no neighbors" answer is as cacheable as any other),
  // tagged with the caller's cache generation.
  void Put(uint64_t spec, uint64_t id, const uint64_t* nbr_ids,
           const float* nbr_w, const int32_t* nbr_t, size_t n,
           uint64_t gen);

  size_t bytes() const;

 private:
  struct Entry {
    uint64_t spec;
    uint64_t id;
    uint64_t gen;  // cache generation the slice was filled under
    std::vector<uint64_t> ids;
    std::vector<float> w;
    std::vector<int32_t> t;
    std::vector<double> cum;  // weight prefix sums (sampling table)
  };
  struct Stripe {
    mutable std::mutex mu;
    std::unordered_map<uint64_t, Entry> map EG_GUARDED_BY(mu);
    std::deque<uint64_t> fifo EG_GUARDED_BY(mu);
    size_t bytes EG_GUARDED_BY(mu) = 0;
  };
  static constexpr int kStripes = 16;
  static constexpr size_t kEntryOverhead = 160;  // 4 vectors + map node

  static size_t EntryCost(size_t n) {
    return n * (sizeof(uint64_t) + sizeof(float) + sizeof(int32_t) +
                sizeof(double)) +
           kEntryOverhead;
  }
  static uint64_t Mix(uint64_t spec, uint64_t id);

  size_t cap_ = 0;
  int policy_ = kCachePolicyFreq;
  Stripe stripes_[kStripes];
};

}  // namespace eg

#endif  // EG_CACHE_H_
