// Client-side dense-feature-row cache for the remote graph client.
//
// The graph is immutable after load (the engine has no mutation API and
// the shard services never rewrite a loaded store), so a feature row
// fetched once is valid forever — no invalidation protocol, just a
// capacity bound. On heavy-tail graphs the same hub rows are refetched
// endlessly by successive batches (hubs carry most edge mass, so every
// fanout lands on them); caching them client-side removes those rows
// from the wire entirely. Config key `feature_cache_mb=` (remote graphs;
// default on at a small budget, 0 disables).
//
// Keyed by (feature-spec hash, node id): the same id requested with
// different fids/dims is a different row, so the spec participates in
// the key and is verified on hit (a 64-bit map-key collision degrades to
// a miss, never to a wrong row). Striped locking + per-stripe FIFO
// eviction: hot hubs re-enter within a batch or two, so recency tracking
// buys little over FIFO here and FIFO keeps the hit path to one hash
// probe under a stripe mutex.
#ifndef EG_CACHE_H_
#define EG_CACHE_H_

#include <atomic>
#include <cstdint>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace eg {

// Process-global resident-byte gauge across every FeatureCache (in
// practice one per RemoteGraph): stripes add/subtract their deltas so
// the blackbox resource sampler (eg_blackbox.h) and the fatal-signal
// dump can read cache pressure with one relaxed load — a postmortem
// must not walk stripe mutexes.
std::atomic<int64_t>& GlobalCacheBytes();

class FeatureCache {
 public:
  ~FeatureCache();  // returns resident bytes to the global gauge

  // Total byte budget across stripes; 0 disables (Get misses, Put drops).
  void SetCapacity(size_t bytes);
  bool enabled() const { return cap_ != 0; }

  // FNV-1a over the (fids, dims) request shape — the spec half of the key.
  static uint64_t SpecHash(const int32_t* fids, const int32_t* dims, int nf);

  // On hit, copy row_dim floats into out and return true.
  bool Get(uint64_t spec, uint64_t id, float* out, size_t row_dim);
  // Insert a fetched row (no-op when disabled or already present).
  void Put(uint64_t spec, uint64_t id, const float* row, size_t row_dim);

  // Resident payload bytes (approximate: entry overhead included) —
  // observability for tests pinning the capacity bound.
  size_t bytes() const;

 private:
  struct Entry {
    uint64_t spec;
    uint64_t id;
    std::vector<float> row;
  };
  struct Stripe {
    mutable std::mutex mu;
    std::unordered_map<uint64_t, Entry> map;
    std::deque<uint64_t> fifo;  // insertion order of map keys
    size_t bytes = 0;
  };
  static constexpr int kStripes = 16;
  // ~per-entry bookkeeping cost charged against the budget on top of the
  // row payload (map node + fifo slot + Entry header).
  static constexpr size_t kEntryOverhead = 96;

  static uint64_t Mix(uint64_t spec, uint64_t id);

  size_t cap_ = 0;
  Stripe stripes_[kStripes];
};

}  // namespace eg

#endif  // EG_CACHE_H_
