from euler_tpu.graph.graph import Graph
from euler_tpu.graph.convert import convert, convert_dicts
from euler_tpu.graph.service import GraphService

__all__ = ["Graph", "GraphService", "convert", "convert_dicts"]
