from euler_tpu.graph.graph import Graph
from euler_tpu.graph.convert import convert, convert_dicts

__all__ = ["Graph", "convert", "convert_dicts"]
