"""Interactive graph console.

Reference equivalent: tools/console/console.cc:35-77 — a linenoise REPL
over the graph client with commands help / con / nf / ef / nb. Rebuilt on
Python readline over the ctypes client (a justified hybrid: the reference
console is pure data plumbing over the client API, SURVEY §2.1), with the
same command surface plus sampling/walk extras:

    con  "directory=/data/ppi"            connect (key=value config)
    con  "mode=remote;registry=/reg"      connect to a sharded service
    nf   dense  "1, 2, 3" "0, 1"          node features by type + slots
    nf   sparse "1, 2" "0"
    nf   binary "1" "0"
    ef   dense  "1:2:0, 2:3:1" "0"        edge features (src:dst:type ids)
    nb   "1, 2, 3" "0, 1"                 full weighted neighbors
    sn   <count> [node_type]              sample nodes
    se   <count> [edge_type]              sample edges
    walk "1, 2" "0" <len> [p] [q]         random walks
    epoch [load <path> [shard]]           snapshot epochs / apply a delta
    help [command] / quit

Usage:  python -m euler_tpu.console [--config "directory=..."]
"""

from __future__ import annotations

import argparse
import shlex
import sys

import numpy as np

COMMANDS = {
    "help": ("Command help message", "help [command]", "help con"),
    "con": (
        "Connect to a graph (embedded or remote)",
        "con <config>",
        'con "directory=/data/ppi"  |  con "mode=remote;registry=/reg"',
    ),
    "nf": (
        "Get features for nodes (dense slots take fid:dim)",
        "nf <dense|sparse|binary> <nids> <fids>",
        'nf dense "1, 2, 3" "0:50, 1:2"  |  nf sparse "1, 2" "0"',
    ),
    "ef": (
        "Get features for edges (dense slots take fid:dim)",
        "ef <dense|sparse|binary> <src:dst:type,...> <fids>",
        'ef dense "1:2:0, 2:3:1" "0:4"',
    ),
    "nb": (
        "Get full weighted neighbors for nodes",
        "nb <nids> <etypes>",
        'nb "1, 2, 3" "0, 1"',
    ),
    "sn": ("Sample nodes by weight", "sn <count> [node_type=-1]", "sn 5 0"),
    "se": ("Sample edges by weight", "se <count> [edge_type=-1]", "se 5"),
    "walk": (
        "Random walks (node2vec p/q optional)",
        "walk <nids> <etypes> <walk_len> [p] [q]",
        'walk "1, 2" "0" 5 1.0 2.0',
    ),
    "stats": (
        "Show native stats: span timers + counters; 'hist' for latency "
        "histograms (p50/p90/p99 per op), 'phases' for the step-phase "
        "profiler (input_stall/sample/h2d/device + prefetch gauges), "
        "'slow' for the slow-span journal, 'blackbox' for the flight "
        "recorder + resource gauges, 'heat' for the data-plane access "
        "profiler (hot-vertex top-K, fan-out, cache classes), 'reset' "
        "to zero everything",
        "stats [hist|phases|slow|blackbox|heat|reset]",
        "stats heat",
    ),
    "epoch": (
        "Show the snapshot epoch: local graphs print the merged-delta "
        "epoch; remote graphs print the client's last-observed epoch "
        "per shard plus the cache generation. 'epoch load <path> "
        "[shard]' applies a delta file (convert.py --delta-from) — "
        "local merges in-process, remote flips the given shard live",
        "epoch [load <path> [shard]]",
        "epoch  |  epoch load /data/part.delta.1 0",
    ),
    "embed": (
        "Query a running embedding server (euler_tpu.serve)",
        "embed <host:port> <nids> [deadline_ms]",
        'embed 127.0.0.1:9200 "1, 2, 3"  |  embed 127.0.0.1:9200 "5" 50',
    ),
    "quit": ("Exit the console", "quit", "quit"),
}


def _ids(text: str) -> np.ndarray:
    return np.array(
        [int(x) for x in text.replace(",", " ").split()], dtype=np.int64
    )


def _edge_ids(text: str):
    src, dst, et = [], [], []
    for tok in text.replace(",", " ").split():
        s, d, t = tok.split(":")
        src.append(int(s))
        dst.append(int(d))
        et.append(int(t))
    return (
        np.array(src, dtype=np.int64),
        np.array(dst, dtype=np.int64),
        et,
    )


def _dense_slots(text: str):
    """Parse 'fid:dim' tokens (dim defaults to 1)."""
    fids, dims = [], []
    for tok in text.replace(",", " ").split():
        if ":" in tok:
            f, d = tok.split(":")
        else:
            f, d = tok, "1"
        fids.append(int(f))
        dims.append(int(d))
    return fids, dims


def _split_ragged(values, counts):
    rows, off = [], 0
    for c in counts:
        rows.append(values[off : off + int(c)])
        off += int(c)
    return rows


def _help(args: list) -> None:
    names = [args[0]] if args and args[0] in COMMANDS else sorted(COMMANDS)
    for name in names:
        desc, usage, example = COMMANDS[name]
        print(f"{name:6s} {desc}\n       usage:   {usage}"
              f"\n       example: {example}")


class Console:
    def __init__(self):
        self.graph = None

    def _need_graph(self) -> bool:
        if self.graph is None:
            print("not connected — run: con \"directory=...\"", file=sys.stderr)
            return False
        return True

    def do_con(self, args: list) -> None:
        import euler_tpu

        if not args:
            return _help(["con"])
        # same loader as Graph(config=...): inline k=v;k=v or an .ini path
        self.graph = euler_tpu.Graph(config=args[0])
        print(
            f"connected: {self.graph.num_nodes} nodes, "
            f"{self.graph.num_edges} edges, "
            f"{self.graph.num_shards} shard(s)"
        )

    def do_nf(self, args: list) -> None:
        if len(args) != 3:
            return _help(["nf"])
        if not self._need_graph():
            return
        kind, nids = args[0], _ids(args[1])
        if kind == "dense":
            fids, dims = _dense_slots(args[2])
            vals = self.graph.get_dense_feature(nids, fids, dims)
            for i, nid in enumerate(nids):
                print(f"node {nid}: {vals[i].tolist()}")
        elif kind == "sparse":
            fids = [int(x) for x in _ids(args[2])]
            slots = self.graph.get_sparse_feature(nids, fids)
            for f, (values, counts) in zip(fids, slots):
                for nid, row in zip(nids, _split_ragged(values, counts)):
                    print(f"node {nid} slot {f}: {row.tolist()}")
        elif kind == "binary":
            fids = [int(x) for x in _ids(args[2])]
            slots = self.graph.get_binary_feature(nids, fids)
            for f, rows in zip(fids, slots):
                for nid, row in zip(nids, rows):
                    print(f"node {nid} slot {f}: {row!r}")
        else:
            _help(["nf"])

    def do_ef(self, args: list) -> None:
        if len(args) != 3:
            return _help(["ef"])
        if not self._need_graph():
            return
        kind = args[0]
        src, dst, types = _edge_ids(args[1])
        eids = list(zip(src.tolist(), dst.tolist(), types))
        if kind == "dense":
            fids, dims = _dense_slots(args[2])
            vals = self.graph.get_edge_dense_feature(
                src, dst, types, fids, dims
            )
            for i, eid in enumerate(eids):
                print(f"edge {eid}: {vals[i].tolist()}")
        elif kind == "sparse":
            fids = [int(x) for x in _ids(args[2])]
            slots = self.graph.get_edge_sparse_feature(src, dst, types, fids)
            for f, (values, counts) in zip(fids, slots):
                for eid, row in zip(eids, _split_ragged(values, counts)):
                    print(f"edge {eid} slot {f}: {row.tolist()}")
        elif kind == "binary":
            fids = [int(x) for x in _ids(args[2])]
            slots = self.graph.get_edge_binary_feature(src, dst, types, fids)
            for f, rows in zip(fids, slots):
                for eid, row in zip(eids, rows):
                    print(f"edge {eid} slot {f}: {row!r}")
        else:
            _help(["ef"])

    def do_nb(self, args: list) -> None:
        if len(args) != 2:
            return _help(["nb"])
        if not self._need_graph():
            return
        nids = _ids(args[0])
        etypes = [int(x) for x in _ids(args[1])]
        nbr, w, t, counts = self.graph.get_full_neighbor(nids, etypes)
        off = 0
        for nid, c in zip(nids, counts):
            row = ", ".join(
                f"{int(nbr[j])}({w[j]:.3g},t{int(t[j])})"
                for j in range(off, off + int(c))
            )
            off += int(c)
            print(f"node {nid}: [{row}]")

    def do_sn(self, args: list) -> None:
        if not args:
            return _help(["sn"])
        if not self._need_graph():
            return
        t = int(args[1]) if len(args) > 1 else -1
        print(self.graph.sample_node(int(args[0]), t).tolist())

    def do_se(self, args: list) -> None:
        if not args:
            return _help(["se"])
        if not self._need_graph():
            return
        t = int(args[1]) if len(args) > 1 else -1
        src, dst, types = self.graph.sample_edge(int(args[0]), t)
        print([
            (int(s), int(d), int(et))
            for s, d, et in zip(src, dst, types)
        ])

    def do_walk(self, args: list) -> None:
        if len(args) < 3:
            return _help(["walk"])
        if not self._need_graph():
            return
        nids = _ids(args[0])
        etypes = [int(x) for x in _ids(args[1])]
        p = float(args[3]) if len(args) > 3 else 1.0
        q = float(args[4]) if len(args) > 4 else 1.0
        walks = self.graph.random_walk(nids, etypes, int(args[2]), p=p, q=q)
        for row in walks:
            print(" -> ".join(str(int(x)) for x in row))

    def do_epoch(self, args: list) -> None:
        if not self._need_graph():
            return
        g = self.graph
        if args and args[0] == "load":
            if len(args) < 2:
                return _help(["epoch"])
            shard = int(args[2]) if len(args) > 2 else None
            ep = g.load_delta(args[1], shard=shard)
            where = "local" if shard is None else f"shard {shard}"
            print(f"applied {args[1]} -> {where} epoch {ep}")
            return
        if args:
            return _help(["epoch"])
        if g.mode == "local":
            print(f"epoch {g.epoch()} (local; {g.epoch()} delta(s) merged)")
            return
        # remote: the client's passive view (v4 reply stamps + registry
        # heartbeats), which may trail a shard that flipped but hasn't
        # answered this client since
        for s in range(g.num_shards):
            print(f"shard {s}: epoch {g.shard_epoch(s)}")
        print(f"cache_gen {g.cache_gen} (feature/neighbor/sample caches "
              f"keyed on this; stale generations evict on next touch)")

    def do_embed(self, args: list) -> None:
        if len(args) < 2:
            return _help(["embed"])
        from euler_tpu.serving import BusyError, DeadlineError, EmbedClient

        deadline = float(args[2]) if len(args) > 2 else None
        client = EmbedClient(args[0])
        try:
            rows = client.embed(_ids(args[1]), deadline_ms=deadline)
        except BusyError:
            print("BUSY (server shed the request — retry with backoff)")
            return
        except DeadlineError:
            print("DEADLINE (expired before dispatch)")
            return
        finally:
            client.close()
        for nid, row in zip(_ids(args[1]), rows):
            vals = " ".join(f"{v:.6f}" for v in row[:8])
            more = " ..." if rows.shape[1] > 8 else ""
            print(f"{int(nid)}: [{vals}{more}]  dim={rows.shape[1]}")

    def do_stats(self, args: list) -> None:
        from euler_tpu.graph.native import (
            counters,
            counters_reset,
            stats,
            stats_reset,
        )

        if args and args[0] == "reset":
            from euler_tpu.telemetry import telemetry_reset

            stats_reset()
            counters_reset()
            telemetry_reset()
            print("stats reset")
            return
        if args and args[0] == "hist":
            # latency histograms (eg_telemetry): p50/p90/p99 per series
            from euler_tpu.telemetry import percentiles, telemetry_json

            rows = [
                (key, h["count"], percentiles(h))
                for key, h in sorted(telemetry_json()["hist"].items())
                if h["count"] > 0
            ]
            if not rows:
                print("no latency samples recorded")
                return
            print(f"{'series':36s} {'count':>8s} {'p50_us':>10s} "
                  f"{'p90_us':>10s} {'p99_us':>10s}")
            for key, count, pct in rows:
                print(f"{key:36s} {count:8d} {pct[50]:10.1f} "
                      f"{pct[90]:10.1f} {pct[99]:10.1f}")
            return
        if args and args[0] == "phases":
            # step-phase profiler (OBSERVABILITY.md "Step phases"):
            # per-phase latency percentiles + the prefetch pipeline's
            # depth/busy means and produced/dropped/error counters
            from euler_tpu.telemetry import (
                PHASES,
                percentiles,
                phase_hists,
                telemetry_json,
            )

            data = telemetry_json()
            hists = phase_hists(data)
            rows = [
                (name, hists[name])
                for name in PHASES
                if hists.get(name, {}).get("count", 0) > 0
            ]
            if not rows:
                print("no step phases recorded (run a training step "
                      "with telemetry on)")
                return
            print(f"{'phase':12s} {'count':>8s} {'mean_ms':>9s} "
                  f"{'p50_us':>10s} {'p90_us':>10s} {'p99_us':>10s}")
            for name, h in rows:
                pct = percentiles(h)
                mean_ms = h["sum_us"] / h["count"] / 1000.0
                print(f"{name:12s} {h['count']:8d} {mean_ms:9.2f} "
                      f"{pct[50]:10.1f} {pct[90]:10.1f} {pct[99]:10.1f}")
            for key, label in (("prefetch_depth", "queue depth"),
                               ("prefetch_busy", "workers busy")):
                h = data["hist"].get(key)
                if h and h["count"]:
                    print(f"prefetch {label}: mean "
                          f"{h['sum_us'] / h['count']:.2f} over "
                          f"{h['count']} dequeues")
            pf = {k: v for k, v in counters().items()
                  if k.startswith("prefetch_") and v}
            if pf:
                print(f"prefetch counters: {pf}")
            return
        if args and args[0] == "blackbox":
            # flight recorder + resource gauges (eg_blackbox,
            # OBSERVABILITY.md "Postmortems"): the live view of exactly
            # what a fatal-signal postmortem would freeze
            from euler_tpu.blackbox import blackbox_json

            d = blackbox_json()
            r = d["resource"]
            state = "on" if d["enabled"] else "OFF"
            print(f"blackbox {state}  shard {d['shard']}  "
                  f"postmortem_dir {d['postmortem_dir'] or '(unarmed)'}  "
                  f"dropped {d['dropped']}")
            print(f"resource: rss {r['rss_bytes'] / 1e6:.1f}MB  "
                  f"fds {r['open_fds']}  threads {r['threads']}  "
                  f"cache {r['cache_bytes'] / 1e6:.1f}MB  "
                  f"history {r['history_depth']}/60 samples")
            if not d["rings"]:
                print("flight recorder empty (no instrumented calls yet)")
                return
            for ring in d["rings"]:
                evs = ring["events"]
                print(f"ring tid={ring['tid']} events={ring['head']} "
                      f"(showing last {min(len(evs), 8)}):")
                for e in evs[-8:]:
                    print(f"  {e['t_us']:>14d}us {e['point']:12s} "
                          f"op={e['op']:<2d} shard={e['shard']:<3d} "
                          f"value={e['value']:<8d} "
                          f"trace={int(e['trace']):#x}")
            return
        if args and args[0] == "heat":
            # data-plane access profiler (eg_heat, OBSERVABILITY.md
            # "Data-plane heat"): hot-vertex top-K per side, the
            # client ids ledger, and cache-efficacy classes
            from euler_tpu.heat import heat_json, topk_share

            d = heat_json()
            state = "on" if d["enabled"] else "OFF"
            tot = d["sketch"]["total"]
            print(f"heat {state}  topk_capacity {d['topk_capacity']}  "
                  f"ids fed: client {tot['client']} server "
                  f"{tot['server']}")
            any_rows = False
            for side in ("client", "server"):
                top = d["topk"][side]
                if not top:
                    continue
                any_rows = True
                share = topk_share(d, side)
                print(f"{side} top-{len(top)} (share of stream "
                      f"{share:.1%}):")
                print(f"  {'rank':>4s} {'id':>12s} {'count':>10s} "
                      f"{'err':>8s}")
                for rank, e in enumerate(top[:10], 1):
                    print(f"  {rank:4d} {e['id']:12d} {e['count']:10d} "
                          f"{e['err']:8d}")
            if not any_rows:
                print("no ids fed yet (run remote queries with heat on)")
                return
            if d["fanout"]:
                print(f"{'op':22s} {'calls':>7s} {'requested':>10s} "
                      f"{'deduped':>8s} {'cache_hit':>9s} "
                      f"{'on_wire':>8s} {'shards':>7s}")
                for op, f in sorted(d["fanout"].items()):
                    print(f"{op:22s} {f['calls']:7d} "
                          f"{f['ids_requested']:10d} "
                          f"{f['ids_deduped']:8d} {f['cache_hits']:9d} "
                          f"{f['ids_on_wire']:8d} "
                          f"{f['shards_touched']:7d}")
            cc = d["cache_class"]
            if any(sum(v) for v in cc.values()):
                print("cache events by frequency class "
                      "(class c = estimate in [2^(c-1), 2^c)):")
                for event in ("hit", "miss", "evict"):
                    print(f"  {event:6s} {cc[event]}")
            return
        if args and args[0] == "slow":
            from euler_tpu.telemetry import slow_spans

            spans = slow_spans()
            if not spans:
                print("slow-span journal empty")
                return
            print(f"{'side':6s} {'op':20s} {'shard':>5s} {'total_us':>9s} "
                  f"{'queue':>7s} {'handler':>8s} {'wire':>7s} "
                  f"{'outcome':8s} trace")
            for s in spans:
                print(f"{s['side']:6s} {s['op']:20s} {s['shard']:5d} "
                      f"{s['total_us']:9d} {s['queue_us']:7d} "
                      f"{s['handler_us']:8d} {s['wire_us']:7d} "
                      f"{s['outcome']:8s} {s['trace']:#018x}")
            return
        snap = stats()
        if not snap:
            print("no ops recorded")
        else:
            print(f"{'op':16s} {'count':>10s} {'total_ms':>10s} "
                  f"{'avg_us':>10s} {'max_us':>10s}")
            for name, s in sorted(snap.items()):
                print(f"{name:16s} {s['count']:10d} {s['total_ms']:10.2f} "
                      f"{s['avg_us']:10.2f} {s['max_us']:10.2f}")
        # one ledger, both sides: client transport fight (retries,
        # failovers, ...) and — when this process serves a shard —
        # server survivability (busy_rejects, handler_timeouts,
        # deadline_rejects, draining), all via the eg_counters_* ABI
        fails = {k: v for k, v in counters().items() if v}
        if fails:
            print("counters:")
            for name, v in sorted(fails.items()):
                print(f"  {name:20s} {v:10d}")
        # the full subcommand roster, so the bare command advertises
        # every surface (the help text stopped being updated after the
        # telemetry PR — now generated-ish: keep in step with COMMANDS)
        print("subcommands: stats hist | phases | slow | blackbox | "
              "heat | reset")

    def execute(self, line: str) -> bool:
        """Run one command line; returns False on quit."""
        try:
            parts = shlex.split(line)
        except ValueError as e:
            print(f"parse error: {e}", file=sys.stderr)
            return True
        if not parts:
            return True
        cmd, args = parts[0], parts[1:]
        if cmd in ("quit", "exit"):
            return False
        if cmd == "help":
            _help(args)
            return True
        handler = getattr(self, f"do_{cmd}", None)
        if handler is None:
            print(f"invalid command: {cmd}", file=sys.stderr)
            _help([])
            return True
        try:
            handler(args)
        except Exception as e:  # keep the REPL alive on bad input
            print(f"error: {e}", file=sys.stderr)
        return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="euler_tpu.console")
    ap.add_argument("--config", default="",
                    help='connect on startup, e.g. "directory=/data/ppi"')
    args = ap.parse_args(argv)
    try:
        import readline  # noqa: F401  (history + line editing)
    except ImportError:
        pass
    console = Console()
    if args.config:
        try:
            console.do_con([args.config])
        except Exception as e:
            print(f"error: {e}", file=sys.stderr)
    while True:
        try:
            line = input("euler> ")
        except EOFError:
            break
        except KeyboardInterrupt:
            print()
            continue
        if not console.execute(line):
            break
    return 0


if __name__ == "__main__":
    sys.exit(main())
