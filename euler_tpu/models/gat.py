"""GAT supervised model.

Reference equivalent: tf_euler/python/models/gat.py:25 + the AttEncoder
(encoders.py:563-632). Host: sample nb_num neighbors + gather features into
the [B, nb+1, F] sequence; device: all-pairs attention heads.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from euler_tpu.models import base
from euler_tpu.nn import metrics
from euler_tpu.nn.encoders import AttEncoder


class _GATModule(nn.Module):
    head_num: int
    hidden_dim: int
    num_classes: int
    sigmoid_loss: bool = True
    nb_num: int = 5
    adj_key: str = ""

    def setup(self):
        self.encoder = AttEncoder(
            head_num=self.head_num,
            hidden_dim=self.hidden_dim,
            out_dim=self.num_classes,
        )

    def _seq_ids(self, batch, consts):
        if "seq_ids" in batch:
            return batch["seq_ids"]
        # device sampling: draw the nb_num attention neighbors here
        import jax
        import jax.numpy as jnp

        from euler_tpu.graph import device as device_graph

        roots = batch["roots"]
        key = jax.random.PRNGKey(batch["seed"][0])
        nbrs = device_graph.sample_neighbor(
            consts["adj"][self.adj_key], roots, key, self.nb_num
        )
        return jnp.concatenate([roots[:, None], nbrs], axis=1)

    def _logits(self, batch, consts, seq_ids):
        if "seq" in batch:
            return self.encoder(batch["seq"])
        # device-resident features: gather [B, nb+1, fdim] from the table
        # (cast restores float32 when the table is stored reduced-precision)
        return self.encoder(
            consts["features"][seq_ids].astype(jnp.float32)
        )

    def embed(self, batch, consts=None):
        seq_ids = None if "seq" in batch else self._seq_ids(batch, consts)
        return self._logits(batch, consts, seq_ids)

    def __call__(self, batch, consts=None):
        # The reference AttEncoder's out_dim IS num_classes (logits).
        seq_ids = None if "seq" in batch else self._seq_ids(batch, consts)
        logits = self._logits(batch, consts, seq_ids)
        labels = base.lookup_labels(
            batch, consts,
            seq_ids[:, 0] if seq_ids is not None else None,
        )
        loss, predictions = base.supervised_decoder(
            logits, labels, self.sigmoid_loss
        )
        return base.ModelOutput(
            embedding=logits,
            loss=loss,
            metric_name="f1",
            metric=metrics.f1_counts(labels, predictions),
        )


class GAT(base.Model):
    metric_name = "f1"

    def __init__(
        self,
        label_idx: int,
        label_dim: int,
        feature_idx: int,
        feature_dim: int,
        max_id: int = -1,
        head_num: int = 1,
        hidden_dim: int = 128,
        nb_num: int = 5,
        edge_type: int = 0,
        num_classes: Optional[int] = None,
        sigmoid_loss: bool = True,
        device_features: bool = False,
        feature_dtype: Optional[str] = None,
        device_sampling: bool = False,
        train_node_type: int = -1,
    ):
        super().__init__()
        self.feature_dtype = feature_dtype
        self.device_features = base.resolve_device_features(
            device_features, feature_idx, max_id
        )
        self.max_id = max_id
        self.init_device_sampling(device_sampling)
        self.train_node_type = train_node_type
        self.label_idx = label_idx
        self.label_dim = label_dim
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.nb_num = nb_num
        self.edge_type = [edge_type] if np.isscalar(edge_type) else list(
            edge_type
        )
        self._adj_key = self.adj_key(self.edge_type)
        self.module = _GATModule(
            head_num=head_num,
            hidden_dim=hidden_dim,
            num_classes=num_classes or label_dim,
            sigmoid_loss=sigmoid_loss,
            nb_num=nb_num,
            adj_key=self._adj_key,
        )

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if self.device_sampling:
            self.add_sampling_consts(
                consts, graph, [self.edge_type],
                roots_type=self.train_node_type,
            )
        return consts

    def sample(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(roots)
        B = len(roots)
        default = self.max_id + 1 if self.max_id >= 0 else -1
        nbrs, _, _ = graph.sample_neighbor(
            roots, self.edge_type, self.nb_num, default
        )
        if self.device_features:
            seq_ids = np.concatenate(
                [roots.reshape(B, 1), nbrs.reshape(B, self.nb_num)], axis=1
            )
            seq_ids = np.clip(seq_ids, 0, self.max_id + 1).astype(np.int32)
            return {"seq_ids": seq_ids}
        node_feats = graph.get_dense_feature(
            roots, [self.feature_idx], [self.feature_dim]
        ).reshape(B, 1, self.feature_dim)
        nbr_feats = graph.get_dense_feature(
            nbrs.reshape(-1), [self.feature_idx], [self.feature_dim]
        ).reshape(B, self.nb_num, self.feature_dim)
        seq = np.concatenate([node_feats, nbr_feats], axis=1)
        labels = graph.get_dense_feature(
            roots, [self.label_idx], [self.label_dim]
        )
        return {"seq": seq, "labels": labels}
