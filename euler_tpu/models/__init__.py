from euler_tpu.models.base import Model, ModelOutput
from euler_tpu.models.graphsage import GraphSage, SupervisedGraphSage

__all__ = ["Model", "ModelOutput", "GraphSage", "SupervisedGraphSage"]
