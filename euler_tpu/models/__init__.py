"""Model zoo registry (reference tf_euler/python/models/__init__.py)."""

from euler_tpu.models.base import Model, ModelOutput, ScalableStoreModel
from euler_tpu.models.gat import GAT
from euler_tpu.models.gcn import ScalableGCN, SupervisedGCN
from euler_tpu.models.graphsage import (
    GraphSage,
    ScalableSage,
    SupervisedGraphSage,
)
from euler_tpu.models.lasgnn import LasGNN
from euler_tpu.models.lshne import LsHNE
from euler_tpu.models.shallow import LINE, Node2Vec

__all__ = [
    "LasGNN",
    "LsHNE",
    "Model",
    "ModelOutput",
    "ScalableStoreModel",
    "GAT",
    "ScalableGCN",
    "SupervisedGCN",
    "GraphSage",
    "ScalableSage",
    "SupervisedGraphSage",
    "LINE",
    "Node2Vec",
]
