"""LsHNE: multi-view heterogeneous-graph walk embedding.

Reference equivalent: tf_euler/python/models/lshne.py:27-213. Semantics kept:
per-view metapath walks -> skip-gram pairs -> per-node-type DNN towers ->
cosine softmax loss against typed negatives, plus a cross-view attention
embedding trained jointly.

TPU adaptations:
- The reference gathers valid pairs with tf.where (dynamic shape,
  lshne.py:95-108); here every view keeps its static pair count and a
  validity mask, and the loss/MRR are masked sums — fixed shapes end to end.
- The reference computes all src_type_num towers for every node and selects
  by one-hot matmul (lshne.py:125-138); here the tower parameters live in a
  single [T, in, out] tensor and each row gathers its type's slice — one
  batched einsum instead of T dense passes.
- Typed negatives come from the engine's native sample_node_with_src.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from euler_tpu import ops
from euler_tpu.models import base
from euler_tpu.nn.layers import SparseEmbedding

EPS = 1e-8


class TypedDense(nn.Module):
    """Per-node-type dense layer: weight[T, in, out], row i uses slice
    type[i] (the reference's per-type tower stacks, lshne.py:62-77)."""

    num_types: int
    features: int

    @nn.compact
    def __call__(self, x, type_idx):
        w = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.num_types, x.shape[-1], self.features),
        )
        b = self.param(
            "bias", nn.initializers.zeros, (self.num_types, self.features)
        )
        type_idx = jnp.clip(type_idx, 0, self.num_types - 1)
        return (
            jnp.einsum("bi,bio->bo", x, jnp.take(w, type_idx, axis=0))
            + jnp.take(b, type_idx, axis=0)
        )


def _cosine(a, b):
    # sqrt(x + eps) keeps the gradient finite for exactly-zero embeddings
    # (masked/missing nodes whose features are all padding).
    prod = jnp.sum(a * b, axis=-1, keepdims=True)
    na = jnp.sqrt(jnp.sum(a * a, axis=-1, keepdims=True) + EPS)
    nb = jnp.sqrt(jnp.sum(b * b, axis=-1, keepdims=True) + EPS)
    return prod / (na * nb)


class _LsHNEModule(nn.Module):
    view_num: int
    dim: int
    num_negs: int
    src_type_num: int
    sparse_feature_dims: Sequence[int]
    feature_embedding_dim: int = 16
    hidden_dim: int = 256
    gamma: float = 5.0
    # device-sampling mode: per view, a tuple of metapaths, each a tuple
    # of per-step consts["adj"] keys
    view_adj_keys: Sequence = ()
    left_win: int = 1
    right_win: int = 1
    default_node: int = -1

    def setup(self):
        self.feature_embeddings = [
            SparseEmbedding(d, self.feature_embedding_dim, combiner="sum")
            for d in self.sparse_feature_dims
        ]
        self.src_hidden = [
            TypedDense(self.src_type_num, self.hidden_dim)
            for _ in range(self.view_num)
        ]
        self.src_out = [
            TypedDense(self.src_type_num, self.dim)
            for _ in range(self.view_num)
        ]
        self.tar_hidden = TypedDense(self.src_type_num, self.hidden_dim)
        self.tar_out = TypedDense(self.src_type_num, self.dim)
        self.att_vec = self.param(
            "att_vec",
            nn.initializers.truncated_normal(stddev=0.1),
            (self.view_num, self.dim),
        )

    def _features(self, node):
        embs = [
            emb(ids, mask)
            for emb, (ids, mask) in zip(
                self.feature_embeddings, node["sparse"]
            )
        ]
        return jnp.concatenate(embs, axis=-1)

    def encode_src(self, node, view: int):
        x = self._features(node)
        t = node["types"]
        h = self.src_hidden[view](x, t)
        return self.src_out[view](h, t)

    def encode_tar(self, node):
        x = self._features(node)
        t = node["types"]
        h = self.tar_hidden(x, t)
        return self.tar_out(h, t)

    def att_embedding(self, node, view_emb=None, view: int = -1):
        """Attention-combine the per-view source encodings
        (reference get_att_embedding, lshne.py:163-175)."""
        views = []
        for i in range(self.view_num):
            if i == view and view_emb is not None:
                views.append(view_emb)
            else:
                views.append(self.encode_src(node, i))
        stack = jnp.stack(views, axis=1)  # [B, V, dim]
        logit = jnp.sum(stack * self.att_vec, axis=-1)  # [B, V]
        w = nn.softmax(logit, axis=-1)
        return jnp.einsum("bv,bvd->bd", w, stack)

    def _decode(self, emb, emb_pos, emb_negs, mask):
        """Masked cosine softmax-CE + MRR (reference decoder,
        lshne.py:140-161). emb/emb_pos [B, d]; emb_negs [B, negs, d]."""
        pos_cos = _cosine(emb, emb_pos)  # [B, 1]
        neg_cos = _cosine(emb[:, None, :], emb_negs)[..., 0]  # [B, negs]
        # gamma tempers the [-1,1] cosine range before the softmax so the
        # positive can dominate (reference lshne.py decoder scaling).
        logits = self.gamma * jnp.concatenate([pos_cos, neg_cos], axis=-1)
        logp = nn.log_softmax(logits, axis=-1)
        per_pair = -logp[:, 0]
        loss = jnp.sum(per_pair * mask)
        rank = 1.0 + jnp.sum(neg_cos >= pos_cos, axis=-1)
        mrr = jnp.sum(mask / rank) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss, mrr

    def _dev_node(self, ids, consts):
        """Node-input dict gathered from the device-resident tables."""
        t = consts["tsampler"]["types"][ids]
        return {
            "sparse": [
                (tab["ids"][ids], tab["mask"][ids])
                for tab in consts["sparse"]
            ],
            "types": jnp.clip(t, 0, None),
        }

    def _device_views(self, batch, consts):
        """(views, root) built inside jit: metapath walks -> skip-gram
        pairs per view, typed negatives per source — the device analog of
        LsHNE.sample."""
        import jax

        from euler_tpu import ops as _ops
        from euler_tpu.graph import device as device_graph

        roots = batch["roots"]
        key = jax.random.PRNGKey(batch["seed"][0])
        views = []
        for v, patterns in enumerate(self.view_adj_keys):
            kv = jax.random.fold_in(key, v)
            srcs, poss = [], []
            for pi, step_keys in enumerate(patterns):
                adjs = [consts["adj"][k] for k in step_keys]
                paths = device_graph.random_walk(
                    adjs, roots, jax.random.fold_in(kv, pi),
                    len(step_keys),
                )
                ti, ci = _ops.walk.pair_indices(
                    len(step_keys) + 1, self.left_win, self.right_win
                )
                srcs.append(paths[:, ti])
                poss.append(paths[:, ci])
            src = jnp.concatenate(srcs, axis=1).reshape(-1)
            pos = jnp.concatenate(poss, axis=1).reshape(-1)
            mask = (
                (src != self.default_node) & (pos != self.default_node)
            ).astype(jnp.float32)
            safe_src = jnp.where(src == self.default_node, 0, src)
            negs = device_graph.sample_node_with_src(
                consts["tsampler"], safe_src,
                jax.random.fold_in(kv, 1 << 20), self.num_negs,
            ).reshape(-1)
            views.append(
                {
                    "src": self._dev_node(src, consts),
                    "pos": self._dev_node(pos, consts),
                    "negs": self._dev_node(negs, consts),
                    "mask": mask,
                }
            )
        return views, self._dev_node(roots, consts)

    def _views_and_root(self, batch, consts):
        if "views" in batch:
            return batch["views"], batch["root"]
        return self._device_views(batch, consts)

    def embed(self, batch, consts=None):
        if "root" in batch:
            return self.att_embedding(batch["root"])
        return self.att_embedding(self._dev_node(batch["roots"], consts))

    def __call__(self, batch, consts=None):
        views, root = self._views_and_root(batch, consts)
        total = 0.0
        mrrs = []
        for v, view in enumerate(views):
            emb = self.encode_src(view["src"], v)
            emb_pos = self.encode_tar(view["pos"])
            B = emb.shape[0]
            emb_negs = self.encode_tar(
                {
                    "sparse": view["negs"]["sparse"],
                    "types": view["negs"]["types"],
                }
            ).reshape(B, self.num_negs, self.dim)
            mask = view["mask"]
            loss_v, _ = self._decode(emb, emb_pos, emb_negs, mask)
            emb_att = self.att_embedding(view["src"], emb, v)
            loss_att, mrr = self._decode(emb_att, emb_pos, emb_negs, mask)
            total = total + loss_v + loss_att
            mrrs.append(mrr)
        embedding = self.att_embedding(root)
        return base.ModelOutput(
            embedding=embedding,
            loss=total,
            metric_name="mrr",
            metric=jnp.mean(jnp.stack(mrrs)),
        )


class LsHNE(base.Model):
    """Multi-view LsHNE. path_patterns: per view, a list of metapaths; each
    metapath is a per-step list of edge-type lists (heterogeneous walks)."""

    metric_name = "mrr"

    def __init__(
        self,
        node_type: int,
        path_patterns: Sequence[Sequence[Sequence[Sequence[int]]]],
        max_id: int,
        dim: int,
        sparse_feature_dims: Sequence[int],
        feature_ids: Sequence[int],
        feature_embedding_dim: int = 16,
        sparse_max_len: int = 16,
        walk_len: int = 3,
        left_win_size: int = 1,
        right_win_size: int = 1,
        num_negs: int = 5,
        gamma: float = 5.0,
        src_type_num: int = 20,
        device_sampling: bool = False,
    ):
        super().__init__()
        if len(path_patterns) < 1:
            raise ValueError("need at least one view")
        self.node_type = node_type
        self.path_patterns = path_patterns
        self.max_id = max_id
        self.init_device_sampling(device_sampling, require_features=False)
        self.src_type_num = src_type_num
        self.walk_len = walk_len
        self.left_win_size = left_win_size
        self.right_win_size = right_win_size
        self.num_negs = num_negs
        self.feature_ids = list(feature_ids)
        self.sparse_max_len = sparse_max_len
        self.gamma = gamma
        # per view, per metapath: one adj key per STEP — the host walk's
        # metapath semantics (walk length = len(pattern), each step
        # restricted to its own edge-type set)
        self._view_adj_keys = tuple(
            tuple(
                tuple(self.adj_key(step) for step in pattern)
                for pattern in patterns
            )
            for patterns in path_patterns
        )
        self.module = _LsHNEModule(
            view_num=len(path_patterns),
            dim=dim,
            num_negs=num_negs,
            src_type_num=src_type_num,
            sparse_feature_dims=tuple(sparse_feature_dims),
            feature_embedding_dim=feature_embedding_dim,
            gamma=gamma,
            view_adj_keys=self._view_adj_keys,
            left_win=left_win_size,
            right_win=right_win_size,
            default_node=max_id + 1,
        )

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if not self.device_sampling:
            return consts
        from euler_tpu.graph import device as device_graph

        step_sets = [
            step
            for patterns in self.path_patterns
            for pattern in patterns
            for step in pattern
        ]
        self.add_sampling_consts(
            consts, graph, step_sets, roots_type=self.node_type
        )
        consts["tsampler"] = device_graph.build_typed_node_sampler(
            graph, self.src_type_num, self.max_id
        )
        consts["sparse"] = base.upload_sparse_tables(
            graph, self.max_id, self.feature_ids, self.sparse_max_len,
            [0] * len(self.feature_ids),
        )
        return consts

    def _node_inputs(self, graph, ids: np.ndarray) -> dict:
        ids = ids.reshape(-1)
        safe = np.where(ids < 0, 0, ids)
        types = graph.node_types(safe)
        return {
            "sparse": ops.get_sparse_feature(
                graph, safe, self.feature_ids, self.sparse_max_len,
                default_values=[0] * len(self.feature_ids),
            ),
            "types": np.clip(types, 0, None).astype(np.int32),
        }

    def sample(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(roots)
        views = []
        for patterns in self.path_patterns:
            pair_list = []
            for pattern in patterns:
                paths = graph.random_walk(
                    roots, list(pattern), p=1.0, q=1.0, default_node=-1
                )
                pair_list.append(
                    ops.gen_pair(
                        paths, self.left_win_size, self.right_win_size
                    )
                )
            pairs = np.concatenate(pair_list, axis=1)  # [B, P, 2]
            flat = pairs.reshape(-1, 2)
            src, pos = flat[:, 0], flat[:, 1]
            mask = ((src != -1) & (pos != -1)).astype(np.float32)
            negs = graph.sample_node_with_src(
                np.where(src < 0, 0, src), self.num_negs
            )
            views.append(
                {
                    "src": self._node_inputs(graph, src),
                    "pos": self._node_inputs(graph, pos),
                    "negs": self._node_inputs(graph, negs),
                    "mask": mask,
                }
            )
        return {"views": views, "root": self._node_inputs(graph, roots)}

    def sample_embed(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        return {"root": self._node_inputs(graph, roots)}
