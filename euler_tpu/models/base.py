"""Model zoo base classes.

Reference equivalent: tf_euler/python/models/base.py (ModelOutput :28,
UnsupervisedModel :41-105, SupervisedModel :181-234).

Architecture: every model is a pair of phases —
  sample(graph, inputs) -> batch dict        (host, numpy, inside prefetch)
  module.apply(vars, batch) -> ModelOutput   (device, pure JAX, jitted)
The reference interleaves graph ops into the TF graph; splitting them is
what makes the device step a single static XLA program and lets the host
sampler run ahead of the TPU.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from euler_tpu.nn import metrics


@dataclasses.dataclass
class ModelOutput:
    embedding: Any
    loss: Any
    metric_name: str
    metric: Any  # scalar (mrr/acc) or f1 counts [tp, fp, fn]


def supervised_decoder(logits, labels, sigmoid_loss: bool):
    """Loss + hard predictions (reference models/base.py:207-221)."""
    if sigmoid_loss:
        loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
        predictions = jnp.floor(nn.sigmoid(logits) + 0.5)
    else:
        loss = optax.softmax_cross_entropy(logits, labels).mean()
        num_classes = logits.shape[-1]
        predictions = nn.one_hot(jnp.argmax(logits, axis=-1), num_classes)
    return loss, predictions


def unsupervised_decoder(emb, emb_pos, emb_negs, xent_loss: bool):
    """Negative-sampling decoder (reference models/base.py:82-95).

    emb/emb_pos: [B, 1, d]; emb_negs: [B, num_negs, d].
    """
    logits = jnp.einsum("bid,bjd->bij", emb, emb_pos)  # [B,1,1]
    neg_logits = jnp.einsum("bid,bjd->bij", emb, emb_negs)  # [B,1,negs]
    mrr = metrics.mrr(logits, neg_logits)
    if xent_loss:
        true_xent = optax.sigmoid_binary_cross_entropy(
            logits, jnp.ones_like(logits)
        )
        neg_xent = optax.sigmoid_binary_cross_entropy(
            neg_logits, jnp.zeros_like(neg_logits)
        )
        loss = true_xent.sum() + neg_xent.sum()
    else:
        neg_cost = jax_logsumexp(neg_logits)
        loss = -jnp.sum(logits - neg_cost)
    return loss, mrr


def jax_logsumexp(x):
    import jax.scipy.special as jsp

    return jsp.logsumexp(x, axis=2, keepdims=True)


def shared_negs_decoder(emb, emb_pos, emb_negs, xent_loss: bool):
    """UnsupervisedModelV2-style shared negatives
    (reference models/base.py:152-165): emb_negs [num_negs, d] shared by the
    whole batch."""
    logits = jnp.einsum("bid,bjd->bij", emb, emb_pos)
    neg_logits = jnp.einsum("bid,nd->bin", emb, emb_negs)
    mrr = metrics.mrr(logits, neg_logits)
    if xent_loss:
        true_xent = optax.sigmoid_binary_cross_entropy(
            logits, jnp.ones_like(logits)
        )
        neg_xent = optax.sigmoid_binary_cross_entropy(
            neg_logits, jnp.zeros_like(neg_logits)
        )
        loss = true_xent.sum() + neg_xent.sum()
    else:
        neg_cost = jax_logsumexp(neg_logits)
        loss = -jnp.sum(logits - neg_cost)
    return loss, mrr


def upload_sparse_tables(
    graph, max_id: int, feature_idxs, max_len: int, default_values
) -> list:
    """Padded sparse-feature tables for every node (rows 0..max_id+1, row
    max_id+1 = default/padding), as device arrays ready for
    state['consts'] — one {'ids', 'mask'} dict per feature slot. Shared
    by every model family that gathers sparse features on device."""
    from euler_tpu import ops

    all_ids = np.arange(max_id + 2, dtype=np.int64)
    tables = ops.get_sparse_feature(
        graph, all_ids, list(feature_idxs), max_len,
        default_values=list(default_values),
    )
    return [
        {
            "ids": jnp.asarray(t_ids.astype(np.int32)),
            "mask": jnp.asarray(t_mask),
        }
        for t_ids, t_mask in tables
    ]


def gather_consts(feats: dict, consts: dict) -> dict:
    """Materialize device-resident features for one node set: replace the
    host-side 'gids' indices with gathers from the HBM-resident tables
    (dense rows, and padded sparse id+mask rows when configured). A
    reduced-precision table (feature_dtype='bfloat16') is cast back to
    float32 after the gather so the module math is unchanged — only the
    HBM-resident bytes (and the gather traffic) shrink."""
    if not consts or "gids" not in feats:
        return feats
    feats = dict(feats)
    g = feats["gids"]
    if "features" in consts:
        feats["dense"] = consts["features"][g].astype(jnp.float32)
    if "sparse" in consts and "sparse" not in feats:
        feats["sparse"] = [
            (t["ids"][g], t["mask"][g]) for t in consts["sparse"]
        ]
    return feats


def lookup_labels(batch: dict, consts: dict, root_ids):
    """Labels for a supervised batch: host-gathered if present, otherwise
    a device gather from the consts label table at root_ids."""
    if "labels" in batch:
        return batch["labels"]
    if not consts:
        raise ValueError(
            "batch has no 'labels' and no consts tables were passed: a "
            "device_features=True batch must be applied with "
            "state['consts'] (from Model.init_state)"
        )
    return consts["labels"][root_ids]


def resolve_device_features(
    device_features: bool,
    feature_idx: int,
    max_id: int,
    has_sparse: bool = False,
) -> bool:
    """Validate a model's device_features request. Silently off when the
    model has no dense (or sparse, when has_sparse) features; a hard error
    when max_id is unset, because the table would have one row and every
    id would clip to it — silently training all nodes on node 0's
    features."""
    if not device_features or (feature_idx < 0 and not has_sparse):
        return False
    if max_id < 0:
        raise ValueError(
            "device_features=True requires max_id >= 0 (the feature/label "
            "tables are sized max_id+2)"
        )
    return True


class Model:
    """Host-side model driver: owns config, builds the flax module, and
    implements the sampling phase. Subclasses define:
      module: nn.Module with __call__(batch) -> ModelOutput
      sample(graph, inputs) -> batch dict (numpy arrays, fixed shapes)
    and optionally sample_embed/embed for inference. Models with extra
    device state (embedding stores) override init_state/make_train_step.

    device_features=True switches dense feature/label delivery from
    host-gather-and-transfer to device-resident tables: init_state uploads
    the full feature (and label) table to HBM once (state['consts'],
    replicated, aliased across steps via donation), sample() ships only
    int32 node ids, and the module gathers rows on device. This is the
    TPU-native replacement for the reference's PS-side embedding gathers
    (tf_euler/python/utils/embedding.py) and cuts per-step host->device
    traffic by ~feature_dim x."""

    metric_name = "loss"
    batch_size_ratio = 1  # reference Model.batch_size_ratio
    device_features = False
    # storage dtype for the device-resident dense feature table (model
    # constructors expose this as the feature_dtype kwarg; the
    # EULER_TPU_FEATURE_DTYPE env var overrides process-wide). None =
    # float32. 'bfloat16' halves the table's HBM footprint and gather
    # bytes; rows are cast back to float32 at the gather.
    feature_dtype: Optional[str] = None

    def __init__(self):
        self.module: nn.Module = None

    def sample(self, graph, inputs) -> dict:
        raise NotImplementedError

    # Inference phase: by default reuse the training batch layout.
    def sample_embed(self, graph, inputs) -> dict:
        return self.sample(graph, inputs)

    # ---- split sampling (the sampler_depth pipeline's model API) ----
    # The depth-N step pipeline (euler_tpu/parallel/prefetch.py
    # pipeline(), train.py sampler_depth=) needs sampling split at its
    # blocking point: sample_start submits the step's graph queries
    # WITHOUT waiting (remote graphs: one eg_remote_sample_async op
    # whose hop chain runs on the native dispatcher pool) and returns an
    # opaque pending token; sample_finish blocks on that token and
    # builds the batch. The defaults keep every model correct — start
    # does the whole synchronous sample and finish just unwraps — so
    # only models with an async fast path (SupervisedGraphSage) override.
    def sample_start(self, graph, inputs):
        return self.sample(graph, inputs)

    def sample_finish(self, graph, pending) -> dict:
        return pending

    # ---- device-resident sampling (euler_tpu/graph/device.py) ----
    def init_device_sampling(
        self, device_sampling: bool, require_features: bool = True
    ) -> None:
        """Resolve the device_sampling flag (call AFTER device_features is
        resolved) and set up the per-batch seed counter. Models whose
        encoder can run id-only (shallow embeddings) pass
        require_features=False."""
        import itertools

        if device_sampling and require_features and not self.device_features:
            raise ValueError(
                "device_sampling=True requires device_features=True "
                "(the sampled ids are consumed by on-device gathers)"
            )
        self.device_sampling = bool(device_sampling) and (
            self.device_features or not require_features
        )
        # itertools.count: sample() runs in concurrent prefetch workers
        # and next() is atomic, where += would race and duplicate seeds
        self._sample_seed = itertools.count(1)

    # device-sampling adjacency form, set via set_sampling_options:
    # a max_degree slab cap for heavy-tailed graphs (truncation, the
    # reference-semantics deviation PERF.md prices), or the exact O(E)
    # alias form (no truncation; build_alias_adjacency)
    sampling_max_degree: Optional[int] = None
    sampling_alias: bool = False
    # families whose device pipeline reads the 2-D slab itself (the
    # full-neighborhood GCN path walks adj["nbr"][:, W]) set this False:
    # the flat-CSR alias dict has no slab to walk
    alias_sampling_ok: bool = True

    def set_sampling_options(
        self, max_degree: Optional[int] = None, alias: bool = False
    ) -> None:
        """Choose the device adjacency form BEFORE init_state/train:
        ``max_degree`` caps the padded slab's width (heaviest neighbors
        kept — changes hub distributions, see PERF.md's truncation
        study); ``alias`` switches to the exact flat-CSR alias sampler
        (no truncation, O(edges) memory) — the recommended form for
        power-law graphs. Biased (p/q) walk adjacencies build the alias
        form with id-sorted rows and route through the exact
        rejection-sampled walk (device.alias_biased_random_walk)."""
        if alias and max_degree is not None:
            raise ValueError(
                "alias sampling is exact: max_degree does not apply"
            )
        if alias and not self.alias_sampling_ok:
            raise ValueError(
                f"{type(self).__name__} walks the 2-D adjacency slab "
                "(full-neighborhood aggregation) — alias sampling does "
                "not apply; use max_degree to bound slab width instead"
            )
        self.sampling_max_degree = max_degree
        self.sampling_alias = alias

    @staticmethod
    def adj_key(edge_types, sorted: bool = False) -> str:
        """consts['adj'] key for one edge-type set (shared so every model
        family and its module agree on the naming). sorted=True names the
        id-sorted slab variant biased walks need."""
        return (
            "et" + "_".join(map(str, edge_types))
            + ("_sorted" if sorted else "")
        )

    def add_sampling_consts(
        self,
        consts: dict,
        graph,
        edge_type_sets,
        negs_type: Optional[int] = None,
        roots_type: Optional[int] = None,
        max_degree: Optional[int] = None,
        sorted: bool = False,
    ) -> dict:
        """Upload the device-sampling structures: one adjacency slab per
        DISTINCT edge-type set plus optional typed node samplers for
        negatives and scan-loop roots (aliased when the types match).
        ``max_degree`` caps the slab width on heavy-tailed graphs
        (heaviest neighbors kept, build_adjacency warns); ``sorted``
        builds id-sorted rows (under their own keys) for
        device_graph.biased_random_walk. ``max_degree`` defaults to the
        model's set_sampling_options value; so does the slab-vs-alias
        choice (alias = exact flat-CSR tables, never sorted)."""
        from euler_tpu.graph import device as device_graph

        from euler_tpu.graph import pallas_sampling

        explicit_cap = max_degree is not None
        if max_degree is None:
            max_degree = self.sampling_max_degree
        # an explicit per-call cap (e.g. GCN's pad-cap slabs) always
        # means "this caller walks the slab" — never swap it for alias
        use_alias = self.sampling_alias and not explicit_cap
        # pack for the fused kernel on a single-device TPU (auto) or when
        # a kernel mesh is registered (per-shard shard_map path)
        use_pallas = pallas_sampling.available() or (
            device_graph.kernel_mesh() is not None
            and pallas_sampling.sharded_available()
        )
        adj = consts.setdefault("adj", {})
        for et in edge_type_sets:
            k = self.adj_key(et, sorted=sorted)
            if k not in adj:
                if use_alias:
                    # sorted alias rows feed the exact rejection-sampled
                    # biased walk (alias_biased_random_walk)
                    adj[k] = device_graph.build_alias_adjacency(
                        graph, et, self.max_id, sorted=sorted
                    )
                    continue
                if sorted and max_degree is not None:
                    # ENFORCED guard on the measured distortion: biased
                    # (p/q) walks over a truncated sorted slab sample a
                    # distribution at mean TVD ~0.35 from the reference's
                    # on hub-parent steps (PERF.md walk study) — silently
                    # training Node2Vec on that is not acceptable. The
                    # CSR export is fetched ONCE and the truncation
                    # decision made from its counts, so the guard never
                    # allocates a throwaway (N x max_degree) slab on
                    # exactly the heavy-tail graphs it exists for.
                    pre = device_graph._fetch_flat_csr(
                        graph, et, self.max_id, 65536, sorted=True
                    )
                    trunc = int((pre[0] > max_degree).sum())
                    if trunc:
                        import warnings

                        warnings.warn(
                            "add_sampling_consts: sorted slab for edge "
                            f"types {list(et)} would truncate {trunc} "
                            f"rows at max_degree={max_degree}; biased "
                            "walks on a truncated slab are measurably "
                            "distorted (mean TVD ~0.35, PERF.md walk "
                            "study) — switching this walk adjacency to "
                            "the exact alias+rejection form"
                        )
                        adj[k] = device_graph.build_alias_adjacency(
                            graph, et, self.max_id, sorted=True,
                            _prefetched=pre,
                        )
                        continue
                    slab = device_graph.build_adjacency(
                        graph, et, self.max_id, max_degree=max_degree,
                        sorted=True, _prefetched=pre,
                    )
                else:
                    slab = device_graph.build_adjacency(
                        graph, et, self.max_id, max_degree=max_degree,
                        sorted=sorted,
                    )
                # host-side metadata, never part of the traced consts
                slab.pop("truncated_rows", 0)
                adj[k] = slab
                if use_pallas and not sorted:
                    # packed slab routes sample_neighbor through the
                    # fused Pallas kernel (sorted slabs feed biased
                    # walks, which read nbr/cum directly — no packing)
                    packed = pallas_sampling.pack_adjacency(adj[k])
                    if packed is not None:
                        adj[k]["packed"] = packed
        if negs_type is not None:
            consts["negs"] = device_graph.build_node_sampler(
                graph, negs_type, self.max_id
            )
        if roots_type is not None:
            if negs_type == roots_type and "negs" in consts:
                consts["roots"] = consts["negs"]
            else:
                consts["roots"] = device_graph.build_node_sampler(
                    graph, roots_type, self.max_id
                )
        return consts

    def device_sample_batch(self, inputs) -> dict:
        """The whole per-step host payload in device-sampling mode: root
        ids + a per-batch RNG seed ([B] so it shards like the rest; the
        module reads element 0 — all equal)."""
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        return {
            "roots": np.clip(roots, 0, self.max_id + 1).astype(np.int32),
            "seed": np.full(
                len(roots), next(self._sample_seed), np.int32
            ),
        }

    def node_inputs(self, graph, ids: np.ndarray) -> dict:
        """Shared host-side gather of one node set's ShallowEncoder inputs,
        driven by the model's configured feature attributes (use_id /
        feature_idx / feature_dim / sparse_feature_idx /
        sparse_feature_max_ids / sparse_max_len / max_id)."""
        from euler_tpu import ops

        ids = np.asarray(ids).reshape(-1)
        feats: dict = {}
        if getattr(self, "use_id", False):
            feats["ids"] = np.clip(ids, 0, self.max_id + 1).astype(np.int32)
        if getattr(self, "feature_idx", -1) >= 0:
            if self.device_features:
                feats["gids"] = (
                    feats["ids"]
                    if "ids" in feats
                    else np.clip(ids, 0, self.max_id + 1).astype(np.int32)
                )
            else:
                feats["dense"] = graph.get_dense_feature(
                    ids, [self.feature_idx], [self.feature_dim]
                )
        sparse_idx = getattr(self, "sparse_feature_idx", [])
        if sparse_idx:
            if self.device_features:
                # the padded sparse tables live in consts (build_consts);
                # the module gathers rows at gids on device
                feats.setdefault(
                    "gids",
                    np.clip(ids, 0, self.max_id + 1).astype(np.int32),
                )
            else:
                feats["sparse"] = ops.get_sparse_feature(
                    graph,
                    ids,
                    sparse_idx,
                    self.sparse_max_len,
                    default_values=[
                        m + 1 for m in self.sparse_feature_max_ids
                    ],
                )
        return feats

    # ---- device state & steps ----
    def build_consts(self, graph) -> dict:
        """Device-resident lookup tables (uploaded once at init). Row
        max_id+1 is the default/padding node; the engine returns zeros for
        it, matching the host-gather path's default fill."""
        if not self.device_features:
            return {}
        n = self.max_id + 2
        ids = np.arange(n, dtype=np.int64)
        consts = {}
        if getattr(self, "feature_idx", -1) >= 0:
            # feature_dtype='bfloat16' (constructor kwarg or
            # EULER_TPU_FEATURE_DTYPE env) halves the table's HBM
            # footprint and the per-step gather bytes; rows are cast back
            # to float32 at the gather (gather_consts), so everything
            # downstream is unchanged. Labels stay float32 — they are
            # loss targets, not gathered at fanout scale.
            dt = self.feature_dtype or os.environ.get(
                "EULER_TPU_FEATURE_DTYPE"
            )
            if dt:
                try:
                    dt = jnp.dtype(dt)
                except TypeError as e:
                    raise ValueError(
                        f"bad feature table dtype {dt!r} (from the "
                        "feature_dtype kwarg or EULER_TPU_FEATURE_DTYPE; "
                        "use a numpy dtype name like 'bfloat16')"
                    ) from e
            consts["features"] = jnp.asarray(
                graph.get_dense_feature(
                    ids, [self.feature_idx], [self.feature_dim]
                ),
                dtype=dt or None,
            )
        if getattr(self, "label_idx", -1) >= 0:
            consts["labels"] = jnp.asarray(
                graph.get_dense_feature(
                    ids, [self.label_idx], [self.label_dim]
                )
            )
        sparse_idx = getattr(self, "sparse_feature_idx", [])
        if sparse_idx:
            consts["sparse"] = upload_sparse_tables(
                graph, self.max_id, sparse_idx, self.sparse_max_len,
                [m + 1 for m in self.sparse_feature_max_ids],
            )
        return consts

    def _apply(self, params, batch, consts, **kw):
        if consts:
            return self.module.apply({"params": params}, batch, consts, **kw)
        return self.module.apply({"params": params}, batch, **kw)

    def init_state(self, rng, graph, example_inputs, optimizer) -> dict:
        batch = self.sample(graph, example_inputs)
        consts = self.build_consts(graph)
        if consts:
            variables = self.module.init(rng, batch, consts)
        else:
            variables = self.module.init(rng, batch)
        params = variables["params"]
        state = {"params": params, "opt_state": optimizer.init(params)}
        if consts:
            state["consts"] = consts
        return state

    def make_train_step(self, optimizer):
        """Pure (state, batch) -> (state, loss, metric); jitted by the
        trainer with params replicated and batch sharded over 'data'. The
        (donated) consts tables pass through unchanged, so XLA aliases
        their buffers — zero copies per step."""

        def train_step(state, batch):
            consts = state.get("consts")

            def loss_fn(p):
                out = self._apply(p, batch, consts)
                return out.loss, out

            (loss, out), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state["params"])
            updates, opt_state = optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
            new_state = {"params": params, "opt_state": opt_state}
            if consts:
                new_state["consts"] = consts
            return new_state, loss, out.metric

        return train_step

    def make_eval_step(self):
        def eval_step(state, batch):
            out = self._apply(state["params"], batch, state.get("consts"))
            return out.loss, out.metric

        return eval_step

    def make_embed_step(self):
        def embed_step(state, batch):
            return self._apply(
                state["params"],
                batch,
                state.get("consts"),
                method=self.module.embed,
            )

        return embed_step


class ScalableStoreModel(Model):
    """Shared training machinery for the Scalable{GCN,Sage} family
    (reference encoders.py:218-519 + the gcn.py/graphsage.py session hooks).

    Each step samples only the 1-hop neighborhood; deeper layers read stale
    neighbor embeddings from per-layer stores. The reference splits the
    bookkeeping across three TF session hooks and an auxiliary Adam; here it
    all fuses into one jitted step:
      1. read stale downstream grads at this batch's nodes, clear the rows
      2. main update from d(loss)/d(params)
      3. store-Adam update from d(store_loss)/d(params), where store_loss =
         sum(node_emb * stale_grad)
      4. scatter-add d(loss + store_loss)/d(store_read) at the neighbors
      5. write fresh activations back to the stores
    Requires: self.num_layers, self.dim, self.max_id,
    self.store_learning_rate, self.store_init_maxval, and a module exposing
    forward_train(batch, store_reads) -> (loss, metric, node_embeddings, emb)
    with batch keys node_ids / neigh_ids.
    """

    def init_state(self, rng, graph, example_inputs, optimizer) -> dict:
        batch = self.sample(graph, example_inputs)
        consts = self.build_consts(graph) or None
        # a device-sampling batch (roots + seed) expands here eagerly so
        # the module init sees the node_ids/neigh_ids layout
        batch = self._expand_batch(batch, consts)
        store_reads = [
            jnp.zeros((len(batch["neigh_ids"]), self.dim))
            for _ in range(self.num_layers - 1)
        ]
        # Scalable modules all take consts=None, so pass it positionally.
        variables = self.module.init(rng, batch, store_reads, consts)
        params = variables["params"]
        n_store = self.max_id + 2
        k1 = jax.random.fold_in(rng, 1)
        stores = [
            jax.random.uniform(
                jax.random.fold_in(k1, i),
                (n_store, self.dim),
                minval=0.0,
                maxval=self.store_init_maxval,
            )
            for i in range(1, self.num_layers)
        ]
        grad_stores = [
            jnp.zeros((n_store, self.dim)) for _ in range(1, self.num_layers)
        ]
        store_opt = optax.adam(self.store_learning_rate)
        state = {
            "params": params,
            "opt_state": optimizer.init(params),
            "stores": stores,
            "grad_stores": grad_stores,
            "store_opt_state": store_opt.init(params),
        }
        if consts:
            state["consts"] = consts
        return state

    def make_train_step(self, optimizer):
        store_opt = optax.adam(self.store_learning_rate)
        module = self.module
        num_stores = self.num_layers - 1

        def train_step(state, batch):
            consts = state.get("consts")  # None when not device_features
            batch = self._expand_batch(batch, consts)
            node_ids = batch["node_ids"]
            neigh_ids = batch["neigh_ids"]
            store_reads = [s[neigh_ids] for s in state["stores"]]
            stale = [gs[node_ids] for gs in state["grad_stores"]]
            grad_stores = [
                gs.at[node_ids].set(jnp.zeros_like(s))
                for gs, s in zip(state["grad_stores"], stale)
            ]

            def forward(params, reads):
                return module.apply(
                    {"params": params},
                    batch,
                    reads,
                    consts,
                    method=module.forward_train,
                )

            def loss_fn(params, reads):
                loss, metric, node_embeddings, _ = forward(params, reads)
                return loss, (metric, node_embeddings)

            (loss, (metric, node_embs)), (gp_main, gr_main) = (
                jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)(
                    state["params"], store_reads
                )
            )
            updates, opt_state = optimizer.update(
                gp_main, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)

            if num_stores > 0:

                def store_loss_fn(params, reads):
                    _, _, node_embeddings, _ = forward(params, reads)
                    return sum(
                        jnp.sum(emb * jax.lax.stop_gradient(g))
                        for emb, g in zip(node_embeddings, stale)
                    )

                gp_store, gr_store = jax.grad(
                    store_loss_fn, argnums=(0, 1)
                )(state["params"], store_reads)
                supdates, store_opt_state = store_opt.update(
                    gp_store, state["store_opt_state"], params
                )
                params = optax.apply_updates(params, supdates)
                grad_stores = [
                    gs.at[neigh_ids].add(gm + gss)
                    for gs, gm, gss in zip(grad_stores, gr_main, gr_store)
                ]
            else:
                store_opt_state = state["store_opt_state"]

            stores = [
                s.at[node_ids].set(jax.lax.stop_gradient(emb))
                for s, emb in zip(state["stores"], node_embs)
            ]
            new_state = {
                "params": params,
                "opt_state": opt_state,
                "stores": stores,
                "grad_stores": grad_stores,
                "store_opt_state": store_opt_state,
            }
            if consts:
                new_state["consts"] = consts
            return new_state, loss, metric

        return train_step

    def _expand_batch(self, batch, consts):
        """Hook: turn a device-sampling batch (roots + seed) into the
        node_ids/neigh_ids layout inside jit. Default: pass through."""
        return batch

    def _apply_with_stores(self, state, batch):
        batch = self._expand_batch(batch, state.get("consts"))
        store_reads = [s[batch["neigh_ids"]] for s in state["stores"]]
        return self.module.apply(
            {"params": state["params"]},
            batch,
            store_reads,
            state.get("consts"),
        )

    def make_eval_step(self):
        def eval_step(state, batch):
            out = self._apply_with_stores(state, batch)
            return out.loss, out.metric

        return eval_step

    def make_embed_step(self):
        def embed_step(state, batch):
            out = self._apply_with_stores(state, batch)
            return out.embedding

        return embed_step
