"""Model zoo base classes.

Reference equivalent: tf_euler/python/models/base.py (ModelOutput :28,
UnsupervisedModel :41-105, SupervisedModel :181-234).

Architecture: every model is a pair of phases —
  sample(graph, inputs) -> batch dict        (host, numpy, inside prefetch)
  module.apply(vars, batch) -> ModelOutput   (device, pure JAX, jitted)
The reference interleaves graph ops into the TF graph; splitting them is
what makes the device step a single static XLA program and lets the host
sampler run ahead of the TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from euler_tpu.nn import metrics


@dataclasses.dataclass
class ModelOutput:
    embedding: Any
    loss: Any
    metric_name: str
    metric: Any  # scalar (mrr/acc) or f1 counts [tp, fp, fn]


def supervised_decoder(logits, labels, sigmoid_loss: bool):
    """Loss + hard predictions (reference models/base.py:207-221)."""
    if sigmoid_loss:
        loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
        predictions = jnp.floor(nn.sigmoid(logits) + 0.5)
    else:
        loss = optax.softmax_cross_entropy(logits, labels).mean()
        num_classes = logits.shape[-1]
        predictions = nn.one_hot(jnp.argmax(logits, axis=-1), num_classes)
    return loss, predictions


def unsupervised_decoder(emb, emb_pos, emb_negs, xent_loss: bool):
    """Negative-sampling decoder (reference models/base.py:82-95).

    emb/emb_pos: [B, 1, d]; emb_negs: [B, num_negs, d].
    """
    logits = jnp.einsum("bid,bjd->bij", emb, emb_pos)  # [B,1,1]
    neg_logits = jnp.einsum("bid,bjd->bij", emb, emb_negs)  # [B,1,negs]
    mrr = metrics.mrr(logits, neg_logits)
    if xent_loss:
        true_xent = optax.sigmoid_binary_cross_entropy(
            logits, jnp.ones_like(logits)
        )
        neg_xent = optax.sigmoid_binary_cross_entropy(
            neg_logits, jnp.zeros_like(neg_logits)
        )
        loss = true_xent.sum() + neg_xent.sum()
    else:
        neg_cost = jax_logsumexp(neg_logits)
        loss = -jnp.sum(logits - neg_cost)
    return loss, mrr


def jax_logsumexp(x):
    import jax.scipy.special as jsp

    return jsp.logsumexp(x, axis=2, keepdims=True)


def shared_negs_decoder(emb, emb_pos, emb_negs, xent_loss: bool):
    """UnsupervisedModelV2-style shared negatives
    (reference models/base.py:152-165): emb_negs [num_negs, d] shared by the
    whole batch."""
    logits = jnp.einsum("bid,bjd->bij", emb, emb_pos)
    neg_logits = jnp.einsum("bid,nd->bin", emb, emb_negs)
    mrr = metrics.mrr(logits, neg_logits)
    if xent_loss:
        true_xent = optax.sigmoid_binary_cross_entropy(
            logits, jnp.ones_like(logits)
        )
        neg_xent = optax.sigmoid_binary_cross_entropy(
            neg_logits, jnp.zeros_like(neg_logits)
        )
        loss = true_xent.sum() + neg_xent.sum()
    else:
        neg_cost = jax_logsumexp(neg_logits)
        loss = -jnp.sum(logits - neg_cost)
    return loss, mrr


class Model:
    """Host-side model driver: owns config, builds the flax module, and
    implements the sampling phase. Subclasses define:
      module: nn.Module with __call__(batch) -> ModelOutput
      sample(graph, inputs) -> batch dict (numpy arrays, fixed shapes)
    and optionally sample_embed/embed for inference. Models with extra
    device state (embedding stores) override init_state/make_train_step."""

    metric_name = "loss"
    batch_size_ratio = 1  # reference Model.batch_size_ratio

    def __init__(self):
        self.module: nn.Module = None

    def sample(self, graph, inputs) -> dict:
        raise NotImplementedError

    # Inference phase: by default reuse the training batch layout.
    def sample_embed(self, graph, inputs) -> dict:
        return self.sample(graph, inputs)

    # ---- device state & steps ----
    def init_state(self, rng, graph, example_inputs, optimizer) -> dict:
        batch = self.sample(graph, np.asarray(example_inputs))
        variables = self.module.init(rng, batch)
        params = variables["params"]
        return {"params": params, "opt_state": optimizer.init(params)}

    def make_train_step(self, optimizer):
        """Pure (state, batch) -> (state, loss, metric); jitted by the
        trainer with params replicated and batch sharded over 'data'."""

        def train_step(state, batch):
            def loss_fn(p):
                out = self.module.apply({"params": p}, batch)
                return out.loss, out

            (loss, out), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state["params"])
            updates, opt_state = optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
            return (
                {"params": params, "opt_state": opt_state},
                loss,
                out.metric,
            )

        return train_step

    def make_eval_step(self):
        def eval_step(state, batch):
            out = self.module.apply({"params": state["params"]}, batch)
            return out.loss, out.metric

        return eval_step

    def make_embed_step(self):
        def embed_step(state, batch):
            return self.module.apply(
                {"params": state["params"]},
                batch,
                method=self.module.embed,
            )

        return embed_step
