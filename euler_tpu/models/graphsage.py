"""GraphSAGE models (supervised + unsupervised).

Reference equivalent: tf_euler/python/models/graphsage.py (:26 GraphSage,
:59 SupervisedGraphSage) and examples/sage.py. Sampling (fanout + feature
gather) runs on the host in one fused native call; the device module is the
aggregation pyramid + decoder.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import numpy as np

from euler_tpu.models import base
from euler_tpu.nn import metrics
from euler_tpu.nn.encoders import (
    SageEncoder,
    ScalableSageEncoder,
    ShallowEncoder,
)


class _SupervisedSageModule(nn.Module):
    fanouts: Sequence[int]
    dim: int
    num_classes: int
    aggregator: str = "mean"
    concat: bool = False
    sigmoid_loss: bool = True
    # node-encoder config
    feature_dim: int = 0
    max_id: int = -1
    embedding_dim: int = 16
    sparse_feature_max_ids: Sequence[int] = ()
    # device-sampling mode: per-hop keys into consts["adj"]
    hop_adj_keys: Sequence[str] = ()

    def setup(self):
        self.node_encoder = ShallowEncoder(
            feature_dim=self.feature_dim,
            max_id=self.max_id,
            embedding_dim=self.embedding_dim,
            sparse_feature_max_ids=self.sparse_feature_max_ids,
        )
        self.encoder = SageEncoder(
            self.fanouts, self.dim, self.aggregator, self.concat
        )
        self.predict = nn.Dense(self.num_classes)

    def _hops(self, batch, consts):
        """Training inputs per hop: host-sampled ("hops") or sampled HERE
        on device from the HBM-resident adjacency ("roots" + "seed")."""
        if "hops" in batch:
            return batch["hops"]
        import jax

        from euler_tpu.graph import device as device_graph

        key = jax.random.PRNGKey(batch["seed"][0])
        adjs = [consts["adj"][k] for k in self.hop_adj_keys]
        ids = device_graph.sample_fanout(
            adjs, batch["roots"], key, list(self.fanouts)
        )
        if self.max_id >= 0:  # use_id: the gids double as embedding ids
            return [{"gids": i, "ids": i} for i in ids]
        return [{"gids": i} for i in ids]

    def _embed_hops(self, hops, consts):
        hidden = [
            self.node_encoder(base.gather_consts(f, consts)) for f in hops
        ]
        return self.encoder(hidden)

    def embed(self, batch, consts=None):
        return self._embed_hops(self._hops(batch, consts), consts)

    def __call__(self, batch, consts=None):
        hops = self._hops(batch, consts)
        embedding = self._embed_hops(hops, consts)
        logits = self.predict(embedding)
        labels = base.lookup_labels(batch, consts, hops[0].get("gids"))
        loss, predictions = base.supervised_decoder(
            logits, labels, self.sigmoid_loss
        )
        return base.ModelOutput(
            embedding=embedding,
            loss=loss,
            metric_name="f1",
            metric=metrics.f1_counts(labels, predictions),
        )


class SupervisedGraphSage(base.Model):
    """Supervised node classification (reference models/graphsage.py:59-78,
    examples/sage.py:51-76)."""

    metric_name = "f1"

    def __init__(
        self,
        label_idx: int,
        label_dim: int,
        metapath: Sequence[Sequence[int]],
        fanouts: Sequence[int],
        dim: int,
        feature_idx: int = -1,
        feature_dim: int = 0,
        aggregator: str = "mean",
        concat: bool = False,
        max_id: int = -1,
        use_id: bool = False,
        embedding_dim: int = 16,
        sparse_feature_idx: Sequence[int] = (),
        sparse_feature_max_ids: Sequence[int] = (),
        sparse_max_len: int = 16,
        num_classes: Optional[int] = None,
        sigmoid_loss: bool = True,
        device_features: bool = False,
        feature_dtype: Optional[str] = None,
        device_sampling: bool = False,
        train_node_type: int = -1,
    ):
        super().__init__()
        self.feature_dtype = feature_dtype
        self.train_node_type = train_node_type
        self.device_features = base.resolve_device_features(
            device_features, feature_idx, max_id,
            has_sparse=bool(sparse_feature_idx),
        )
        self.max_id = max_id
        self.init_device_sampling(device_sampling)
        self.label_idx = label_idx
        self.label_dim = label_dim
        self.metapath = [list(m) for m in metapath]
        self.fanouts = list(fanouts)
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.use_id = use_id
        self.sparse_feature_idx = list(sparse_feature_idx)
        self.sparse_feature_max_ids = list(sparse_feature_max_ids)
        self.sparse_max_len = sparse_max_len
        self.default_node = max_id + 1 if max_id >= 0 else -1
        # device-sampling: one adjacency slab per distinct hop type-set,
        # hops referencing the same set share one upload
        self._hop_adj_keys = [self.adj_key(m) for m in self.metapath]
        self.module = _SupervisedSageModule(
            fanouts=tuple(fanouts),
            dim=dim,
            num_classes=num_classes or label_dim,
            aggregator=aggregator,
            concat=concat,
            sigmoid_loss=sigmoid_loss,
            feature_dim=feature_dim if feature_idx >= 0 else 0,
            max_id=max_id if use_id else -1,
            embedding_dim=embedding_dim,
            sparse_feature_max_ids=tuple(sparse_feature_max_ids),
            hop_adj_keys=tuple(self._hop_adj_keys),
        )

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if self.device_sampling:
            self.add_sampling_consts(
                consts, graph, self.metapath,
                roots_type=self.train_node_type,
            )
        return consts

    def _batch_from_hops(self, graph, inputs, ids_per_hop) -> dict:
        hops = [self.node_inputs(graph, ids) for ids in ids_per_hop]
        if self.device_features:
            return {"hops": hops}  # labels gathered on device from consts
        labels = graph.get_dense_feature(
            inputs, [self.label_idx], [self.label_dim]
        )
        return {"hops": hops, "labels": labels}

    def sample(self, graph, inputs) -> dict:
        inputs = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            # the fanout happens inside the jitted step; host ships only
            # root ids + a per-batch seed for the device RNG
            return self.device_sample_batch(inputs)
        ids_per_hop, _, _ = graph.sample_fanout(
            inputs, self.metapath, self.fanouts, self.default_node
        )
        return self._batch_from_hops(graph, inputs, ids_per_hop)

    def sample_start(self, graph, inputs):
        """Non-blocking half of sample() for the sampler_depth pipeline:
        submit the whole fan-out as one native async op (hop chain on
        the remote client's dispatcher pool) and return immediately.
        Falls back to the synchronous sample() whenever the graph has no
        async path (local mode, mock graphs) or the native op pool is
        momentarily full — the pipeline then still works, just without
        native overlap for that step."""
        inputs = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(inputs)
        start = getattr(graph, "sample_fanout_async", None)
        handle = (
            start(inputs, self.metapath, self.fanouts, self.default_node)
            if start is not None
            else None
        )
        if handle is None:
            return self.sample(graph, inputs)
        return (inputs, handle)

    def sample_finish(self, graph, pending) -> dict:
        if not (
            isinstance(pending, tuple)
            and len(pending) == 2
            and hasattr(pending[1], "take")
        ):
            return pending  # sample_start already produced the batch
        inputs, handle = pending
        ids_per_hop, _, _ = handle.take()
        return self._batch_from_hops(graph, inputs, ids_per_hop)


class _ScalableSageModule(nn.Module):
    """Training-mode ScalableSage forward: 1-hop fanout + per-layer store
    reads (reference encoders.py:449-483)."""

    fanout: int
    num_layers: int
    dim: int
    num_classes: int
    aggregator: str = "mean"
    concat: bool = False
    sigmoid_loss: bool = True
    feature_dim: int = 0
    max_id: int = -1
    embedding_dim: int = 16

    def setup(self):
        self.node_encoder = ShallowEncoder(
            feature_dim=self.feature_dim,
            max_id=self.max_id,
            embedding_dim=self.embedding_dim,
        )
        self.encoder = ScalableSageEncoder(
            fanout=self.fanout,
            num_layers=self.num_layers,
            dim=self.dim,
            aggregator=self.aggregator,
            concat=self.concat,
        )
        self.predict = nn.Dense(self.num_classes)

    def forward_train(self, batch, store_reads, consts=None):
        node_feat = self.node_encoder(
            base.gather_consts(batch["node_feats"], consts)
        )
        neigh_feat = self.node_encoder(
            base.gather_consts(batch["neigh_feats"], consts)
        )
        emb, node_embeddings = self.encoder(node_feat, neigh_feat, store_reads)
        logits = self.predict(emb)
        labels = base.lookup_labels(batch, consts, batch["node_ids"])
        loss, predictions = base.supervised_decoder(
            logits, labels, self.sigmoid_loss
        )
        return (
            loss,
            metrics.f1_counts(labels, predictions),
            node_embeddings,
            emb,
        )

    def __call__(self, batch, store_reads, consts=None):
        loss, f1c, _, emb = self.forward_train(batch, store_reads, consts)
        return base.ModelOutput(
            embedding=emb, loss=loss, metric_name="f1", metric=f1c
        )


class ScalableSage(base.ScalableStoreModel):
    """ScalableSage (reference models/graphsage.py:81 + encoders.py:404-519):
    GraphSAGE whose receptive field is capped at one sampled hop per step by
    per-layer historical-embedding stores. Store machinery inherited from
    base.ScalableStoreModel."""

    metric_name = "f1"

    def __init__(
        self,
        label_idx: int,
        label_dim: int,
        edge_type: Sequence[int],
        fanout: int,
        num_layers: int,
        dim: int,
        max_id: int,
        aggregator: str = "mean",
        concat: bool = False,
        feature_idx: int = -1,
        feature_dim: int = 0,
        use_id: bool = False,
        embedding_dim: int = 16,
        store_learning_rate: float = 0.001,
        store_init_maxval: float = 0.05,
        num_classes: Optional[int] = None,
        sigmoid_loss: bool = True,
        device_features: bool = False,
        feature_dtype: Optional[str] = None,
        device_sampling: bool = False,
        train_node_type: int = -1,
    ):
        super().__init__()
        self.feature_dtype = feature_dtype
        self.device_features = base.resolve_device_features(
            device_features, feature_idx, max_id
        )
        self.max_id = max_id
        self.init_device_sampling(device_sampling)
        self.train_node_type = train_node_type
        self.label_idx = label_idx
        self.label_dim = label_dim
        self.edge_type = list(edge_type)
        self.fanout = fanout
        self.num_layers = num_layers
        self.dim = dim
        self.max_id = max_id
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.use_id = use_id
        self.store_learning_rate = store_learning_rate
        self.store_init_maxval = store_init_maxval
        self._adj_key = self.adj_key(self.edge_type)
        self.module = _ScalableSageModule(
            fanout=fanout,
            num_layers=num_layers,
            dim=dim,
            num_classes=num_classes or label_dim,
            aggregator=aggregator,
            concat=concat,
            sigmoid_loss=sigmoid_loss,
            feature_dim=feature_dim if feature_idx >= 0 else 0,
            max_id=max_id if use_id else -1,
            embedding_dim=embedding_dim,
        )

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if self.device_sampling:
            self.add_sampling_consts(
                consts, graph, [self.edge_type],
                roots_type=self.train_node_type,
            )
        return consts

    def _expand_batch(self, batch, consts):
        if "roots" not in batch:
            return batch
        import jax

        from euler_tpu.graph import device as device_graph

        roots = batch["roots"]
        key = jax.random.PRNGKey(batch["seed"][0])
        neigh = device_graph.sample_neighbor(
            consts["adj"][self._adj_key], roots, key, self.fanout
        ).reshape(-1)
        node_feats = {"gids": roots}
        neigh_feats = {"gids": neigh}
        if self.use_id:
            node_feats["ids"] = roots
            neigh_feats["ids"] = neigh
        return {
            "node_feats": node_feats,
            "neigh_feats": neigh_feats,
            "node_ids": roots,
            "neigh_ids": neigh,
        }

    def sample(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(roots)
        ids_per_hop, _, _ = graph.sample_fanout(
            roots, [self.edge_type], [self.fanout], self.max_id + 1
        )
        neigh = ids_per_hop[1]
        batch = {
            "node_feats": self.node_inputs(graph, roots),
            "neigh_feats": self.node_inputs(graph, neigh),
            "node_ids": np.clip(roots, 0, self.max_id + 1),
            "neigh_ids": np.clip(neigh, 0, self.max_id + 1),
        }
        if not self.device_features:
            batch["labels"] = graph.get_dense_feature(
                roots, [self.label_idx], [self.label_dim]
            )
        return batch


class _UnsupervisedSageModule(nn.Module):
    fanouts: Sequence[int]
    dim: int
    aggregator: str = "mean"
    concat: bool = False
    xent_loss: bool = False
    feature_dim: int = 0
    max_id: int = -1
    embedding_dim: int = 16
    sparse_feature_max_ids: Sequence[int] = ()
    shared_negs: bool = False
    # device-sampling mode
    hop_adj_keys: Sequence[str] = ()
    pos_adj_key: str = ""
    num_negs: int = 5

    def setup(self):
        self.node_encoder = ShallowEncoder(
            feature_dim=self.feature_dim,
            max_id=self.max_id,
            embedding_dim=self.embedding_dim,
            sparse_feature_max_ids=self.sparse_feature_max_ids,
        )
        self.encoder = SageEncoder(
            self.fanouts, self.dim, self.aggregator, self.concat
        )
        # Context encoder: separate tower over the same input layout
        # (reference GraphSage.{target,context}_encoder are two encoders,
        # models/graphsage.py:26-56).
        self.context_node_encoder = ShallowEncoder(
            feature_dim=self.feature_dim,
            max_id=self.max_id,
            embedding_dim=self.embedding_dim,
            sparse_feature_max_ids=self.sparse_feature_max_ids,
        )
        self.context_encoder = SageEncoder(
            self.fanouts, self.dim, self.aggregator, self.concat
        )

    def _encode(self, hops, context: bool, consts=None):
        hops = [base.gather_consts(f, consts) for f in hops]
        if context:
            hidden = [self.context_node_encoder(f) for f in hops]
            return self.context_encoder(hidden)
        hidden = [self.node_encoder(f) for f in hops]
        return self.encoder(hidden)

    def _device_fanout(self, roots, consts, key):
        from euler_tpu.graph import device as device_graph

        adjs = [consts["adj"][k] for k in self.hop_adj_keys]
        ids = device_graph.sample_fanout(
            adjs, roots, key, list(self.fanouts)
        )
        if self.max_id >= 0:
            return [{"gids": i, "ids": i} for i in ids]
        return [{"gids": i} for i in ids]

    def _all_hops(self, batch, consts):
        """(src_hops, pos_hops, neg_hops): host-sampled or built here from
        roots + seed (positives = 1-hop draws, negatives = global typed
        draws from consts['negs'])."""
        if "src_hops" in batch:
            return (
                batch["src_hops"],
                batch.get("pos_hops"),
                batch.get("neg_hops"),
            )
        import jax

        from euler_tpu.graph import device as device_graph

        roots = batch["roots"]
        key = jax.random.PRNGKey(batch["seed"][0])
        k_pos, k_neg, k_src, k_p, k_n = jax.random.split(key, 5)
        pos = device_graph.sample_neighbor(
            consts["adj"][self.pos_adj_key], roots, k_pos, 1
        ).reshape(-1)
        negs = device_graph.sample_node(
            consts["negs"], k_neg, roots.shape[0] * self.num_negs
        )
        return (
            self._device_fanout(roots, consts, k_src),
            self._device_fanout(pos, consts, k_p),
            self._device_fanout(negs, consts, k_n),
        )

    def embed(self, batch, consts=None):
        src_hops, _, _ = self._all_hops(batch, consts)
        return self._encode(src_hops, False, consts)

    def __call__(self, batch, consts=None):
        src_hops, pos_hops, neg_hops = self._all_hops(batch, consts)
        emb = self._encode(src_hops, False, consts)
        emb_pos = self._encode(pos_hops, True, consts)
        emb_negs = self._encode(neg_hops, True, consts)
        B = emb.shape[0]
        emb3 = emb.reshape(B, 1, -1)
        pos3 = emb_pos.reshape(B, 1, -1)
        if self.shared_negs:
            loss, mrr = base.shared_negs_decoder(
                emb3, pos3, emb_negs, self.xent_loss
            )
        else:
            negs3 = emb_negs.reshape(B, -1, emb.shape[-1])
            loss, mrr = base.unsupervised_decoder(
                emb3, pos3, negs3, self.xent_loss
            )
        return base.ModelOutput(
            embedding=emb, loss=loss, metric_name="mrr", metric=mrr
        )


class GraphSage(base.Model):
    """Unsupervised GraphSAGE (reference models/graphsage.py:26-56):
    positives are 1-hop neighbors, negatives are global typed samples."""

    metric_name = "mrr"

    def __init__(
        self,
        node_type: int,
        edge_type: Sequence[int],
        max_id: int,
        metapath: Sequence[Sequence[int]],
        fanouts: Sequence[int],
        dim: int,
        num_negs: int = 5,
        feature_idx: int = -1,
        feature_dim: int = 0,
        aggregator: str = "mean",
        concat: bool = False,
        xent_loss: bool = False,
        use_id: bool = False,
        embedding_dim: int = 16,
        device_features: bool = False,
        feature_dtype: Optional[str] = None,
        device_sampling: bool = False,
    ):
        super().__init__()
        self.feature_dtype = feature_dtype
        self.device_features = base.resolve_device_features(
            device_features, feature_idx, max_id
        )
        self.max_id = max_id
        self.init_device_sampling(device_sampling)
        self.node_type = node_type
        self.edge_type = list(edge_type)
        self.metapath = [list(m) for m in metapath]
        self.fanouts = list(fanouts)
        self.num_negs = num_negs
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.use_id = use_id
        self.default_node = max_id + 1
        self._hop_adj_keys = [self.adj_key(m) for m in self.metapath]
        self._pos_adj_key = self.adj_key(self.edge_type)
        self.module = _UnsupervisedSageModule(
            fanouts=tuple(fanouts),
            dim=dim,
            aggregator=aggregator,
            concat=concat,
            xent_loss=xent_loss,
            feature_dim=feature_dim if feature_idx >= 0 else 0,
            max_id=max_id if use_id else -1,
            embedding_dim=embedding_dim,
            hop_adj_keys=tuple(self._hop_adj_keys),
            pos_adj_key=self._pos_adj_key,
            num_negs=num_negs,
        )

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if self.device_sampling:
            # typed negatives (reference: global sample_node(node_type));
            # scan-loop roots alias the same typed sampler
            self.add_sampling_consts(
                consts, graph, self.metapath + [self.edge_type],
                negs_type=self.node_type, roots_type=self.node_type,
            )
        return consts

    def _hops(self, graph, ids: np.ndarray) -> list:
        ids_per_hop, _, _ = graph.sample_fanout(
            ids, self.metapath, self.fanouts, self.default_node
        )
        return [self.node_inputs(graph, hop_ids) for hop_ids in ids_per_hop]

    def sample(self, graph, inputs) -> dict:
        inputs = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(inputs)
        pos, _, _ = graph.sample_neighbor(
            inputs, self.edge_type, 1, self.default_node
        )
        negs = graph.sample_node(
            len(inputs) * self.num_negs, self.node_type
        )
        return {
            "src_hops": self._hops(graph, inputs),
            "pos_hops": self._hops(graph, pos.reshape(-1)),
            "neg_hops": self._hops(graph, negs),
        }

    def sample_embed(self, graph, inputs) -> dict:
        inputs = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.sample(graph, inputs)
        return {"src_hops": self._hops(graph, inputs)}
