"""Shallow network-embedding models: LINE and Node2Vec.

Reference equivalents: tf_euler/python/models/line.py:26 (first/second
order) and node2vec.py:26 (walk -> gen_pair -> shallow encoders). Walks and
pair generation run on the host (one native call for the whole walk chain,
vs the reference's walk_len sequential async RPCs,
tf_euler/kernels/random_walk_op.cc:31-140); the device sees fixed-shape
(src, pos, negs) node-input batches.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import numpy as np

from euler_tpu import ops
from euler_tpu.models import base
from euler_tpu.nn.encoders import ShallowEncoder


class _ShallowUnsupModule(nn.Module):
    dim: int
    feature_dim: int = 0
    max_id: int = -1
    embedding_dim: int = 16
    sparse_feature_max_ids: Sequence[int] = ()
    combiner: str = "add"
    xent_loss: bool = False
    num_negs: int = 5
    share_context: bool = False  # LINE first-order shares the encoder
    # device-sampling mode: LINE when walk_len == 0, Node2Vec otherwise
    adj_key: str = ""
    walk_len: int = 0
    left_win: int = 0
    right_win: int = 0
    has_features: bool = False
    has_sparse: bool = False
    # node2vec bias; p=q=1 takes the plain-walk fast path. Biased walks
    # need adj_key to name an id-SORTED slab (built by
    # add_sampling_consts(sorted=True)).
    walk_p: float = 1.0
    walk_q: float = 1.0
    # rejection-walk proposal budget (alias adjacencies only); 0 =
    # device.DEFAULT_WALK_TRIALS
    walk_trials: int = 0

    def setup(self):
        kw = dict(
            dim=self.dim,
            feature_dim=self.feature_dim,
            max_id=self.max_id,
            embedding_dim=self.embedding_dim,
            sparse_feature_max_ids=tuple(self.sparse_feature_max_ids),
            combiner=self.combiner,
        )
        self.target = ShallowEncoder(**kw)
        if not self.share_context:
            self.context = ShallowEncoder(**kw)

    def _context(self, x):
        return self.target(x) if self.share_context else self.context(x)

    def _feats(self, ids):
        f = {}
        if self.max_id >= 0:
            f["ids"] = ids
        if self.has_features or self.has_sparse:
            f["gids"] = ids
        return f

    def _inputs(self, batch, consts):
        """(src, pos, negs) encoder inputs: host-sampled or derived here
        from roots + seed (LINE: 1-hop positives; Node2Vec: device walks
        -> skip-gram pairs)."""
        if "src" in batch:
            return batch["src"], batch.get("pos"), batch.get("negs")
        import jax

        from euler_tpu.graph import device as device_graph

        roots = batch["roots"]
        key = jax.random.PRNGKey(batch["seed"][0])
        k_walk, k_neg = jax.random.split(key)
        adj = consts["adj"][self.adj_key]
        if self.walk_len > 0:
            if self.walk_p != 1.0 or self.walk_q != 1.0:
                # trace-time guard: biased membership search is garbage
                # on unsorted rows; the naming convention (adj_key(et,
                # sorted=True)) is the sortedness contract
                if not self.adj_key.endswith("_sorted"):
                    raise ValueError(
                        "biased walks (walk_p/walk_q != 1) need an "
                        "id-sorted adjacency slab: build consts with "
                        "add_sampling_consts(sorted=True) and pass the "
                        "matching adj_key(et, sorted=True)"
                    )
                if "off" in adj:
                    # flat-CSR alias form (chosen by set_sampling_options
                    # or forced by the truncation guard): the rejection-
                    # sampled walk is exact over FULL neighbor lists
                    paths = device_graph.alias_biased_random_walk(
                        adj, roots, k_walk, self.walk_len,
                        self.walk_p, self.walk_q,
                        trials=self.walk_trials or None,
                    )
                else:
                    paths = device_graph.biased_random_walk(
                        adj, roots, k_walk, self.walk_len,
                        self.walk_p, self.walk_q,
                    )
            else:
                paths = device_graph.random_walk(
                    adj, roots, k_walk, self.walk_len
                )
            ti, ci = ops.walk.pair_indices(
                self.walk_len + 1, self.left_win, self.right_win
            )
            src = paths[:, ti].reshape(-1)
            pos = paths[:, ci].reshape(-1)
        else:
            src = roots
            pos = device_graph.sample_neighbor(adj, roots, k_walk, 1)[:, 0]
        negs = device_graph.sample_node(
            consts["negs"], k_neg, src.shape[0] * self.num_negs
        )
        return self._feats(src), self._feats(pos), self._feats(negs)

    def embed(self, batch, consts=None):
        src, _, _ = self._inputs(batch, consts)
        return self.target(base.gather_consts(src, consts))

    def __call__(self, batch, consts=None):
        src, pos, negs = self._inputs(batch, consts)
        emb = self.target(base.gather_consts(src, consts))  # [B, d]
        emb_pos = self._context(base.gather_consts(pos, consts))
        emb_negs = self._context(base.gather_consts(negs, consts))
        B = emb.shape[0]
        loss, mrr = base.unsupervised_decoder(
            emb.reshape(B, 1, -1),
            emb_pos.reshape(B, 1, -1),
            emb_negs.reshape(B, self.num_negs, -1),
            self.xent_loss,
        )
        return base.ModelOutput(
            embedding=emb, loss=loss, metric_name="mrr", metric=mrr
        )


class _ShallowUnsupervised(base.Model):
    """Shared host plumbing for models whose batch is (src, pos, negs)
    node-input dicts."""

    metric_name = "mrr"

    def __init__(
        self,
        node_type: int,
        max_id: int,
        feature_idx: int = -1,
        feature_dim: int = 0,
        use_id: bool = True,
        sparse_feature_idx: Sequence[int] = (),
        sparse_feature_max_ids: Sequence[int] = (),
        sparse_max_len: int = 16,
        num_negs: int = 5,
        device_features: bool = False,
        feature_dtype: Optional[str] = None,
        device_sampling: bool = False,
    ):
        super().__init__()
        self.feature_dtype = feature_dtype
        self.node_type = node_type
        self.max_id = max_id
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.use_id = use_id
        self.sparse_feature_idx = list(sparse_feature_idx)
        self.sparse_feature_max_ids = list(sparse_feature_max_ids)
        self.sparse_max_len = sparse_max_len
        self.num_negs = num_negs
        self.device_features = base.resolve_device_features(
            device_features, feature_idx, max_id,
            has_sparse=bool(sparse_feature_idx),
        )
        # the id-embedding path needs no feature table: device_sampling
        # composes with use_id alone (device_features only required when
        # dense features are configured)
        if device_sampling and not self.device_features and (
            feature_idx >= 0 or sparse_feature_idx
        ):
            raise ValueError(
                "device_sampling with dense/sparse features requires "
                "device_features=True (the tables must be HBM-resident)"
            )
        self.init_device_sampling(device_sampling, require_features=False)

    adj_sorted = False  # Node2Vec sets True for biased (p/q != 1) walks

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if self.device_sampling:
            self.add_sampling_consts(
                consts, graph, [self.edge_type],
                negs_type=self.node_type, roots_type=self.node_type,
                sorted=self.adj_sorted,
            )
        return consts

    def _pack(self, graph, src, pos, negs) -> dict:
        return {
            "src": self.node_inputs(graph, src),
            "pos": self.node_inputs(graph, pos),
            "negs": self.node_inputs(graph, negs),
        }

    def sample_embed(self, graph, inputs) -> dict:
        ids = np.asarray(inputs, dtype=np.int64).reshape(-1)
        return {"src": self.node_inputs(graph, ids)}


class LINE(_ShallowUnsupervised):
    """LINE (reference models/line.py:26): positives are direct neighbors;
    order 1 shares the target/context encoder, order 2 uses two towers."""

    def __init__(
        self,
        node_type: int,
        edge_type: Sequence[int],
        max_id: int,
        dim: int,
        order: int = 1,
        combiner: str = "add",
        xent_loss: bool = False,
        embedding_dim: int = 16,
        **kwargs,
    ):
        super().__init__(node_type, max_id, **kwargs)
        if order not in (1, 2, "first", "second"):
            raise ValueError(f"LINE order must be 1 or 2, got {order}")
        self.edge_type = list(edge_type)
        self.module = _ShallowUnsupModule(
            dim=dim,
            feature_dim=self.feature_dim if self.feature_idx >= 0 else 0,
            max_id=max_id if self.use_id else -1,
            embedding_dim=embedding_dim,
            sparse_feature_max_ids=tuple(self.sparse_feature_max_ids),
            combiner=combiner,
            xent_loss=xent_loss,
            num_negs=self.num_negs,
            share_context=order in (1, "first"),
            adj_key=self.adj_key(self.edge_type),
            has_features=self.device_features and self.feature_idx >= 0,
            has_sparse=self.device_features
            and bool(self.sparse_feature_idx),
        )

    def sample(self, graph, inputs) -> dict:
        src = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(src)
        pos, _, _ = graph.sample_neighbor(
            src, self.edge_type, 1, self.max_id + 1
        )
        negs = graph.sample_node(len(src) * self.num_negs, self.node_type)
        return self._pack(graph, src, pos.reshape(-1), negs)


class Node2Vec(_ShallowUnsupervised):
    """Node2Vec (reference models/node2vec.py:26): biased walks ->
    skip-gram pairs -> shallow encoders. batch_size_ratio is the pair count
    per root (the effective batch multiplier, reference node2vec.py:44-46).
    """

    def __init__(
        self,
        node_type: int,
        edge_type: Sequence[int],
        max_id: int,
        dim: int,
        walk_len: int = 3,
        walk_p: float = 1.0,
        walk_q: float = 1.0,
        left_win_size: int = 1,
        right_win_size: int = 1,
        combiner: str = "add",
        xent_loss: bool = False,
        embedding_dim: int = 16,
        walk_trials: int = 0,
        **kwargs,
    ):
        super().__init__(node_type, max_id, **kwargs)
        if walk_trials < 0:
            raise ValueError(
                f"walk_trials must be >= 0 (0 = library default), got "
                f"{walk_trials}"
            )
        self.edge_type = list(edge_type)
        self.walk_len = walk_len
        self.walk_p = walk_p
        self.walk_q = walk_q
        # biased walks reweight candidates by d_tx (reference
        # graph.cc:120-151); on device that membership test runs over
        # id-sorted slab rows. p=q=1 keeps the plain-draw fast path, the
        # same degeneration the reference takes (graph.cc:196-199).
        self.adj_sorted = self.device_sampling and (
            walk_p != 1.0 or walk_q != 1.0
        )
        self.left_win_size = left_win_size
        self.right_win_size = right_win_size
        self.batch_size_ratio = ops.walk.pair_count(
            walk_len + 1, left_win_size, right_win_size
        )
        self.module = _ShallowUnsupModule(
            dim=dim,
            feature_dim=self.feature_dim if self.feature_idx >= 0 else 0,
            max_id=max_id if self.use_id else -1,
            embedding_dim=embedding_dim,
            sparse_feature_max_ids=tuple(self.sparse_feature_max_ids),
            combiner=combiner,
            xent_loss=xent_loss,
            num_negs=self.num_negs,
            adj_key=self.adj_key(self.edge_type, sorted=self.adj_sorted),
            walk_len=walk_len,
            left_win=left_win_size,
            right_win=right_win_size,
            has_features=self.device_features and self.feature_idx >= 0,
            has_sparse=self.device_features
            and bool(self.sparse_feature_idx),
            walk_p=walk_p,
            walk_q=walk_q,
            walk_trials=walk_trials,
        )

    def sample(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(roots)
        paths = graph.random_walk(
            roots,
            self.edge_type,
            self.walk_len,
            p=self.walk_p,
            q=self.walk_q,
            default_node=self.max_id + 1,
        )
        pairs = ops.gen_pair(paths, self.left_win_size, self.right_win_size)
        flat = pairs.reshape(-1, 2)  # [B*num_pairs, 2]
        src, pos = flat[:, 0], flat[:, 1]
        negs = graph.sample_node(len(src) * self.num_negs, self.node_type)
        return self._pack(graph, src, pos, negs)
