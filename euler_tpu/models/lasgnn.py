"""LasGNN: multi-metapath SparseSage with dot attention + cosine logit.

Reference equivalent: tf_euler/python/models/lasgnn.py:74-156 (+ the
SparseSageEncoder, encoders.py:522-560). Inputs are (label, target node
group, context node groups); each group is encoded by one SparseSage per
metapath, metapath embeddings are combined by dot-product attention, and
the target/context cosine (x5) feeds a sigmoid loss with streaming AUC.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from euler_tpu import ops
from euler_tpu.models import base
from euler_tpu.nn import metrics
from euler_tpu.nn.encoders import SparseSageEncoder
from euler_tpu.nn.layers import SparseEmbedding


class DotAttention(nn.Module):
    """Dot-product attention over the second-to-last axis
    (reference lasgnn.py:27-58): inputs [..., num_values, dim] ->
    [..., dim]."""

    @nn.compact
    def __call__(self, inputs):
        kernel = self.param(
            "kernel",
            nn.initializers.variance_scaling(0.36, "fan_in", "uniform"),
            inputs.shape[-2:],
        )
        similarity = jnp.sum(inputs * kernel, axis=-1)
        coef = nn.softmax(similarity, axis=-1)
        return jnp.sum(inputs * coef[..., None], axis=-2)


class _LasGNNModule(nn.Module):
    metapath_counts: Sequence[int]  # metapaths per group
    group_sizes: Sequence[int]  # nodes per group (group 0 = target, size 1)
    fanouts: Sequence[int]
    dim: int
    feature_dims: Sequence[int]
    aggregator: str = "mean"
    concat: bool = False
    # device-sampling mode: per group, per metapath, per-hop keys into
    # consts["adj"] (the heterogeneous fanout runs inside the jitted step)
    group_adj_keys: Sequence = ()

    def setup(self):
        # Shared sparse embeddings across all towers (reference
        # lasgnn.py:93-94 shared_embeddings), dims + 2 like
        # SparseSageEncoder.create_sparse_embeddings (feature_dim + 1 slots
        # plus the padding id).
        self.sparse_embeddings = [
            SparseEmbedding(d + 2, 16) for d in self.feature_dims
        ]
        # each tower is the public SparseSageEncoder (reference
        # encoders.py:522-560) sharing ONE embedding set across every
        # metapath tower (reference lasgnn.py:93-94 shared_embeddings)
        self.towers = [
            [
                SparseSageEncoder(
                    tuple(self.fanouts), self.dim,
                    aggregator=self.aggregator, concat=self.concat,
                    shared_embeddings=self.sparse_embeddings,
                )
                for _ in range(m)
            ]
            for m in self.metapath_counts
        ]
        self.attentions = [DotAttention() for _ in self.metapath_counts]
        self.target_ff = nn.Dense(self.dim)
        self.context_ff = nn.Dense(self.dim)

    def _device_groups(self, batch, consts, only_target: bool = False):
        """The per-group/per-metapath hop structure built inside jit:
        heterogeneous fanouts over the HBM-resident adjacency slabs, hop
        features gathered from the consts sparse tables — the device
        analog of LasGNN.sample."""
        import jax

        from euler_tpu.graph import device as device_graph

        key = jax.random.PRNGKey(batch["seed"][0])
        groups = []
        n_groups = 1 if only_target else len(self.group_adj_keys)
        for g in range(n_groups):
            flat = batch[f"group{g}"].reshape(-1)
            per_metapath = []
            for m, hop_keys in enumerate(self.group_adj_keys[g]):
                adjs = [consts["adj"][k] for k in hop_keys]
                ids = device_graph.sample_fanout(
                    adjs, flat, jax.random.fold_in(key, (g << 8) | m),
                    list(self.fanouts),
                )
                per_metapath.append(
                    {
                        "hops": [
                            {
                                "sparse": [
                                    (tab["ids"][h], tab["mask"][h])
                                    for tab in consts["sparse"]
                                ]
                            }
                            for h in ids
                        ]
                    }
                )
            groups.append(per_metapath)
        return groups

    def _groups(self, batch, consts, only_target: bool = False):
        if "groups" in batch:
            return batch["groups"]
        return self._device_groups(batch, consts, only_target)

    def group_embeddings(self, groups):
        """Per group: [B, n_g * dim] after metapath attention + flatten
        (reference lasgnn.py:130-140)."""
        outs = []
        for g, (towers, att, n_g) in enumerate(
            zip(self.towers, self.attentions, self.group_sizes)
        ):
            per_metapath = []
            for m, tower in enumerate(towers):
                hops = [h["sparse"] for h in groups[g][m]["hops"]]
                emb = tower(hops)  # [B*n_g, dim]
                per_metapath.append(emb.reshape(-1, n_g, emb.shape[-1]))
            stack = jnp.stack(per_metapath, axis=-2)  # [B, n_g, M, dim]
            combined = att(stack)  # [B, n_g, dim]
            outs.append(combined.reshape(combined.shape[0], -1))
        return outs

    def embed(self, batch, consts=None):
        """Target-group embedding only — context towers are not computed
        (batch may contain just the target group)."""
        groups = self._groups(batch, consts, only_target=True)
        per_metapath = []
        n_g = self.group_sizes[0]
        for m, tower in enumerate(self.towers[0]):
            hops = [h["sparse"] for h in groups[0][m]["hops"]]
            emb = tower(hops)
            per_metapath.append(emb.reshape(-1, n_g, emb.shape[-1]))
        stack = jnp.stack(per_metapath, axis=-2)
        combined = self.attentions[0](stack)
        return self.target_ff(combined.reshape(combined.shape[0], -1))

    def __call__(self, batch, consts=None):
        groups = self.group_embeddings(self._groups(batch, consts))
        target = self.target_ff(groups[0])
        context = self.context_ff(jnp.concatenate(groups[1:], axis=-1))
        # sqrt(x + eps) keeps gradients finite for exactly-zero embeddings.
        tn = target / jnp.sqrt(
            jnp.sum(target * target, axis=-1, keepdims=True) + 1e-12
        )
        cn = context / jnp.sqrt(
            jnp.sum(context * context, axis=-1, keepdims=True) + 1e-12
        )
        cosine = jnp.sum(tn * cn, axis=-1, keepdims=True)
        logit = cosine * 5.0
        label = batch["label"]
        import optax

        loss = optax.sigmoid_binary_cross_entropy(logit, label).mean()
        return base.ModelOutput(
            embedding=target,
            loss=loss,
            metric_name="auc",
            metric=metrics.auc_counts(label, nn.sigmoid(logit)),
        )


class LasGNN(base.Model):
    """LasGNN. The training source yields structured inputs
    (label [B,1], groups: list of [B, n_g] int64 node-id arrays); the first
    group is the target (n_0 = 1)."""

    metric_name = "auc"

    def __init__(
        self,
        metapaths_of_groups: Sequence[Sequence[Sequence[Sequence[int]]]],
        fanouts: Sequence[int],
        dim: int,
        feature_ixs: Sequence[int],
        feature_dims: Sequence[int],
        group_sizes: Sequence[int],
        max_id: int = -1,
        aggregator: str = "mean",
        concat: bool = False,
        sparse_max_len: int = 16,
        device_sampling: bool = False,
    ):
        super().__init__()
        self.metapaths_of_groups = metapaths_of_groups
        self.fanouts = list(fanouts)
        self.feature_ixs = list(feature_ixs)
        self.feature_dims = list(feature_dims)
        self.group_sizes = list(group_sizes)
        self.max_id = max_id
        self.sparse_max_len = sparse_max_len
        if device_sampling and max_id < 0:
            # mirrors resolve_device_features: without it every id clips
            # to 0 and the one-row consts tables train on garbage
            raise ValueError(
                "device_sampling=True requires max_id >= 0 (the "
                "adjacency/feature tables are sized max_id+2)"
            )
        self.init_device_sampling(device_sampling, require_features=False)
        # per group, per metapath: one consts["adj"] key per HOP (each hop
        # restricted to its own edge-type set — the host sample_fanout's
        # heterogeneous metapath semantics)
        self._group_adj_keys = tuple(
            tuple(
                tuple(self.adj_key(hop) for hop in metapath)
                for metapath in metapaths
            )
            for metapaths in metapaths_of_groups
        )
        self.module = _LasGNNModule(
            metapath_counts=tuple(len(m) for m in metapaths_of_groups),
            group_sizes=tuple(group_sizes),
            fanouts=tuple(fanouts),
            dim=dim,
            feature_dims=tuple(feature_dims),
            aggregator=aggregator,
            concat=concat,
            group_adj_keys=(
                self._group_adj_keys if self.device_sampling else ()
            ),
        )

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if not self.device_sampling:
            return consts
        hop_sets = [
            hop
            for metapaths in self.metapaths_of_groups
            for metapath in metapaths
            for hop in metapath
        ]
        self.add_sampling_consts(consts, graph, hop_sets)
        consts["sparse"] = base.upload_sparse_tables(
            graph, self.max_id, self.feature_ixs, self.sparse_max_len,
            [d + 1 for d in self.feature_dims],
        )
        return consts

    def _hop_inputs(self, graph, ids: np.ndarray) -> dict:
        return {
            "sparse": ops.get_sparse_feature(
                graph,
                ids,
                self.feature_ixs,
                self.sparse_max_len,
                default_values=[d + 1 for d in self.feature_dims],
            )
        }

    def sample(self, graph, inputs) -> dict:
        label = np.asarray(inputs["label"], dtype=np.float32).reshape(-1, 1)
        if self.device_sampling:
            # host ships only labels + per-group node ids + a seed; the
            # heterogeneous fanouts and sparse-feature gathers happen
            # inside the jitted step against the HBM-resident slabs
            batch = {"label": label}
            for g, group_ids in enumerate(inputs["groups"]):
                ids = np.asarray(group_ids, dtype=np.int64)
                batch[f"group{g}"] = np.clip(
                    ids, 0, self.max_id + 1
                ).astype(np.int32)
            batch["seed"] = np.full(
                len(label), next(self._sample_seed), np.int32
            )
            return batch
        groups = []
        for g, (group_ids, metapaths) in enumerate(
            zip(inputs["groups"], self.metapaths_of_groups)
        ):
            flat = np.asarray(group_ids, dtype=np.int64).reshape(-1)
            per_metapath = []
            for metapath in metapaths:
                ids_per_hop, _, _ = graph.sample_fanout(
                    flat, metapath, self.fanouts, self.max_id + 1
                )
                per_metapath.append(
                    {
                        "hops": [
                            self._hop_inputs(graph, ids)
                            for ids in ids_per_hop
                        ]
                    }
                )
            groups.append(per_metapath)
        return {"label": label, "groups": groups}

    def sample_embed(self, graph, inputs) -> dict:
        """Target group only — no context sampling for embedding export."""
        ids = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return {
                "group0": np.clip(ids, 0, self.max_id + 1)
                .astype(np.int32)
                .reshape(-1, self.group_sizes[0]),
                "seed": np.full(
                    len(ids), next(self._sample_seed), np.int32
                ),
            }
        per_metapath = []
        for metapath in self.metapaths_of_groups[0]:
            ids_per_hop, _, _ = graph.sample_fanout(
                ids, metapath, self.fanouts, self.max_id + 1
            )
            per_metapath.append(
                {
                    "hops": [
                        self._hop_inputs(graph, h) for h in ids_per_hop
                    ]
                }
            )
        return {"groups": [per_metapath]}
