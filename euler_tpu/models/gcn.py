"""GCN models: full-neighbor supervised GCN + ScalableGCN.

Reference equivalents: tf_euler/python/models/gcn.py (SupervisedGCN :26,
ScalableGCN :47 + the session-run-hook store machinery) and encoders.py
(GCNEncoder :165, ScalableGCNEncoder :218-324).

TPU adaptations:
- Full-neighbor expansion pads to static per-hop node/edge caps
  (ragged -> fixed shapes); aggregation is segment_sum.
- ScalableGCN's embedding/gradient stores are device arrays carried in the
  train state, and the reference's three session hooks (update_store,
  update_gradient, optimize_store) plus the auxiliary store Adam all fuse
  into the single jitted train step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from euler_tpu import ops
from euler_tpu.models import base
from euler_tpu.nn import metrics
from euler_tpu.nn.encoders import GCNEncoder, ShallowEncoder


class _SupervisedGCNModule(nn.Module):
    num_layers: int
    dim: int
    num_classes: int
    aggregator: str = "gcn"
    use_residual: bool = False
    sigmoid_loss: bool = True
    feature_dim: int = 0
    max_id: int = -1
    embedding_dim: int = 16
    sparse_feature_max_ids: Sequence[int] = ()
    # device-sampling mode: per-hop keys into consts["adj"] + static
    # unique-node caps (the full-neighbor expansion is deterministic, so
    # "sampling" here is just the on-device multi-hop dedup)
    hop_adj_keys: Sequence[str] = ()
    node_caps: Sequence[int] = ()

    def setup(self):
        self.node_encoder = ShallowEncoder(
            dim=self.dim if self.use_residual else None,
            feature_dim=self.feature_dim,
            max_id=self.max_id,
            embedding_dim=self.embedding_dim,
            sparse_feature_max_ids=tuple(self.sparse_feature_max_ids),
            combiner="add" if self.use_residual else "concat",
        )
        self.encoder = GCNEncoder(
            num_layers=self.num_layers,
            dim=self.dim,
            aggregator=self.aggregator,
            use_residual=self.use_residual,
        )
        self.predict = nn.Dense(self.num_classes)

    def _hops_adjs(self, batch, consts):
        """(hop feature dicts, adjacency dicts): host-built ("hops" +
        "adjs") or expanded HERE on device from the HBM-resident slabs
        ("roots")."""
        if "hops" in batch:
            return batch["hops"], batch["adjs"]
        from euler_tpu.graph import device as device_graph

        adjs = [consts["adj"][k] for k in self.hop_adj_keys]
        hops = device_graph.multi_hop_neighbor(
            adjs, batch["roots"], list(self.node_caps)
        )
        node_sets = [batch["roots"]] + [h["nodes"] for h in hops]
        if self.max_id >= 0:  # use_id: the gids double as embedding ids
            feats = [{"gids": i, "ids": i} for i in node_sets]
        else:
            feats = [{"gids": i} for i in node_sets]
        return feats, hops

    def _forward(self, batch, consts):
        hops, adjs = self._hops_adjs(batch, consts)
        hidden = [
            self.node_encoder(base.gather_consts(f, consts)) for f in hops
        ]
        return self.encoder(hidden, adjs), hops

    def embed(self, batch, consts=None):
        return self._forward(batch, consts)[0]

    def __call__(self, batch, consts=None):
        embedding, hops = self._forward(batch, consts)
        logits = self.predict(embedding)
        labels = base.lookup_labels(batch, consts, hops[0].get("gids"))
        loss, predictions = base.supervised_decoder(
            logits, labels, self.sigmoid_loss
        )
        return base.ModelOutput(
            embedding=embedding,
            loss=loss,
            metric_name="f1",
            metric=metrics.f1_counts(labels, predictions),
        )


class SupervisedGCN(base.Model):
    """Full-neighbor GCN (reference models/gcn.py:26). max_nodes_per_hop /
    max_edges_per_hop are the static pad caps required for TPU shapes."""

    metric_name = "f1"
    # full-neighborhood aggregation walks the 2-D slab (device.py
    # multi_hop_neighbor) — the flat-CSR alias form has no slab to walk
    alias_sampling_ok = False

    def __init__(
        self,
        label_idx: int,
        label_dim: int,
        metapath: Sequence[Sequence[int]],
        dim: int,
        max_nodes_per_hop: Sequence[int],
        max_edges_per_hop: Sequence[int],
        aggregator: str = "gcn",
        feature_idx: int = -1,
        feature_dim: int = 0,
        max_id: int = -1,
        use_id: bool = False,
        embedding_dim: int = 16,
        sparse_feature_idx: Sequence[int] = (),
        sparse_feature_max_ids: Sequence[int] = (),
        sparse_max_len: int = 16,
        use_residual: bool = False,
        num_classes: Optional[int] = None,
        sigmoid_loss: bool = True,
        device_features: bool = False,
        feature_dtype: Optional[str] = None,
        device_sampling: bool = False,
        max_degree: Optional[int] = None,
    ):
        super().__init__()
        self.feature_dtype = feature_dtype
        self.device_features = base.resolve_device_features(
            device_features, feature_idx, max_id
        )
        self.init_device_sampling(device_sampling)
        self.label_idx = label_idx
        self.label_dim = label_dim
        self.metapath = [list(m) for m in metapath]
        self.max_nodes_per_hop = list(max_nodes_per_hop)
        self.max_edges_per_hop = list(max_edges_per_hop)
        self.max_degree = max_degree
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.max_id = max_id
        self.use_id = use_id
        self.sparse_feature_idx = list(sparse_feature_idx)
        self.sparse_feature_max_ids = list(sparse_feature_max_ids)
        self.sparse_max_len = sparse_max_len
        self._hop_adj_keys = [self.adj_key(m) for m in self.metapath]
        self.module = _SupervisedGCNModule(
            num_layers=len(self.metapath),
            dim=dim,
            num_classes=num_classes or label_dim,
            aggregator=aggregator,
            use_residual=use_residual,
            sigmoid_loss=sigmoid_loss,
            feature_dim=feature_dim if feature_idx >= 0 else 0,
            max_id=max_id if use_id else -1,
            embedding_dim=embedding_dim,
            sparse_feature_max_ids=tuple(sparse_feature_max_ids),
            hop_adj_keys=tuple(self._hop_adj_keys),
            node_caps=tuple(self.max_nodes_per_hop),
        )

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if self.device_sampling:
            self.add_sampling_consts(
                consts, graph, self.metapath, max_degree=self.max_degree
            )
        return consts

    def sample(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            # the full-neighbor multi-hop expansion happens inside the
            # jitted step (deterministic — the seed is unused)
            return self.device_sample_batch(roots)
        roots, hops = ops.get_multi_hop_neighbor(
            graph,
            roots,
            self.metapath,
            max_nodes_per_hop=self.max_nodes_per_hop,
            max_edges_per_hop=self.max_edges_per_hop,
            default_node=self.max_id + 1 if self.max_id >= 0 else -1,
        )
        hop_feats = [self.node_inputs(graph, roots)] + [
            self.node_inputs(graph, h.nodes) for h in hops
        ]
        batch = {"hops": hop_feats, "adjs": [h.adj for h in hops]}
        if not self.device_features:
            batch["labels"] = graph.get_dense_feature(
                roots, [self.label_idx], [self.label_dim]
            )
        return batch


class _ScalableGCNModule(nn.Module):
    """Training-mode ScalableGCN forward: 1-hop adjacency + per-layer store
    reads (reference encoders.py:254-288). Pure function of
    (params, store_reads); the store plumbing lives in the train step."""

    num_layers: int
    dim: int
    num_classes: int
    aggregator: str = "gcn"
    use_residual: bool = False
    sigmoid_loss: bool = True
    feature_dim: int = 0
    max_id: int = -1
    embedding_dim: int = 16

    def setup(self):
        self.node_encoder = ShallowEncoder(
            dim=self.dim if self.use_residual else None,
            feature_dim=self.feature_dim,
            max_id=self.max_id,
            embedding_dim=self.embedding_dim,
            combiner="add" if self.use_residual else "concat",
        )
        from euler_tpu.nn import sparse_aggregators

        agg_cls = sparse_aggregators.get(self.aggregator)
        self.aggs = [
            agg_cls(
                self.dim,
                activation=nn.relu if l < self.num_layers - 1 else None,
            )
            for l in range(self.num_layers)
        ]
        self.predict = nn.Dense(self.num_classes)

    def forward_train(self, batch, store_reads, consts=None):
        node_emb = self.node_encoder(
            base.gather_consts(batch["node_feats"], consts)
        )
        neigh_emb = self.node_encoder(
            base.gather_consts(batch["neigh_feats"], consts)
        )
        adj = batch["adj"]
        node_embeddings = []
        for layer in range(self.num_layers):
            h = self.aggs[layer]((node_emb, neigh_emb, adj))
            if self.use_residual:
                h = node_emb + h
            node_emb = h
            node_embeddings.append(node_emb)
            if layer < self.num_layers - 1:
                neigh_emb = store_reads[layer]
        logits = self.predict(node_emb)
        labels = base.lookup_labels(batch, consts, batch["node_ids"])
        loss, predictions = base.supervised_decoder(
            logits, labels, self.sigmoid_loss
        )
        return (
            loss,
            metrics.f1_counts(labels, predictions),
            node_embeddings,
            node_emb,
        )

    def __call__(self, batch, store_reads, consts=None):
        loss, f1c, _, emb = self.forward_train(batch, store_reads, consts)
        return base.ModelOutput(
            embedding=emb, loss=loss, metric_name="f1", metric=f1c
        )


class ScalableGCN(base.ScalableStoreModel):
    """ScalableGCN (reference models/gcn.py:47 + encoders.py:218-324): each
    step samples only the 1-hop neighborhood; deeper layers read stale
    neighbor embeddings from a store. Training machinery inherited
    from base.ScalableStoreModel."""

    metric_name = "f1"
    # _expand_batch gathers full slab rows (adj["nbr"][roots] over W
    # columns) — needs the 2-D slab form
    alias_sampling_ok = False

    def __init__(
        self,
        label_idx: int,
        label_dim: int,
        edge_type: Sequence[int],
        num_layers: int,
        dim: int,
        max_id: int,
        max_neighbors: int,
        max_edges: Optional[int] = None,
        aggregator: str = "gcn",
        feature_idx: int = -1,
        feature_dim: int = 0,
        use_id: bool = False,
        embedding_dim: int = 16,
        use_residual: bool = False,
        store_learning_rate: float = 0.001,
        store_init_maxval: float = 0.05,
        num_classes: Optional[int] = None,
        sigmoid_loss: bool = True,
        device_features: bool = False,
        feature_dtype: Optional[str] = None,
        device_sampling: bool = False,
        train_node_type: int = -1,
    ):
        super().__init__()
        self.feature_dtype = feature_dtype
        self.device_features = base.resolve_device_features(
            device_features, feature_idx, max_id
        )
        self.max_id = max_id
        self.init_device_sampling(device_sampling)
        self.train_node_type = train_node_type
        self.label_idx = label_idx
        self.label_dim = label_dim
        self.edge_type = list(edge_type)
        self.num_layers = num_layers
        self.dim = dim
        # Per-ROOT caps: the reference expands the full ragged 1-hop
        # neighborhood (encoders.py:262 get_multi_hop_neighbor); for static
        # TPU shapes we pad to batch * max_neighbors unique neighbors and
        # batch * max_edges adjacency entries per sampled batch.
        self.max_neighbors = max_neighbors
        self.max_edges = max_edges if max_edges is not None else max_neighbors * 4
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.use_id = use_id
        self.store_learning_rate = store_learning_rate
        self.store_init_maxval = store_init_maxval
        self.module = _ScalableGCNModule(
            num_layers=num_layers,
            dim=dim,
            num_classes=num_classes or label_dim,
            aggregator=aggregator,
            use_residual=use_residual,
            sigmoid_loss=sigmoid_loss,
            feature_dim=feature_dim if feature_idx >= 0 else 0,
            max_id=max_id if use_id else -1,
            embedding_dim=embedding_dim,
        )
        # NOTE: evaluation uses the same 1-hop + stale-store approximation
        # as training (ScalableStoreModel.make_eval_step). The reference's
        # non-training branch (encoders.py:256-258) instead runs the exact
        # full-neighbor GCN; use SupervisedGCN with the trained params for
        # exact evaluation.

    def build_consts(self, graph) -> dict:
        consts = super().build_consts(graph)
        if self.device_sampling:
            # max_neighbors (the host path's per-root dense cap) bounds
            # the slab width too: a power-law hub must not balloon every
            # batch to B x global-max-degree
            self.add_sampling_consts(
                consts, graph, [self.edge_type],
                roots_type=self.train_node_type,
                max_degree=self.max_neighbors,
            )
        return consts

    def _expand_batch(self, batch, consts):
        """Device full-neighbor expansion: the adjacency slab row IS the
        1-hop neighborhood (padded to W, masked by degree) — no host
        dedup; duplicate neighbor slots scatter-add like duplicate edges.
        """
        if "roots" not in batch:
            return batch
        slab = consts["adj"][self.adj_key(self.edge_type)]
        roots = batch["roots"]
        B = roots.shape[0]
        W = slab["nbr"].shape[1]
        nbrs = slab["nbr"][roots]                      # [B, W]
        deg = slab["deg"][roots]                       # [B]
        mask = (
            jnp.arange(W, dtype=jnp.int32)[None, :] < deg[:, None]
        ).astype(jnp.float32)
        flat = nbrs.reshape(-1)
        adj = {
            "src": jnp.repeat(jnp.arange(B, dtype=jnp.int32), W),
            "dst": jnp.arange(B * W, dtype=jnp.int32),
            "mask": mask.reshape(-1),
        }
        node_feats = {"gids": roots}
        neigh_feats = {"gids": flat}
        if self.use_id:
            node_feats["ids"] = roots
            neigh_feats["ids"] = flat
        return {
            "node_feats": node_feats,
            "neigh_feats": neigh_feats,
            "node_ids": roots,
            "neigh_ids": flat,
            "adj": adj,
        }

    def sample(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        if self.device_sampling:
            return self.device_sample_batch(roots)
        B = len(roots)
        roots_out, hops = ops.get_multi_hop_neighbor(
            graph,
            roots,
            [self.edge_type],
            max_nodes_per_hop=[B * self.max_neighbors],
            max_edges_per_hop=[B * self.max_edges],
            default_node=self.max_id + 1,
        )
        hop = hops[0]
        batch = {
            "node_feats": self.node_inputs(graph, roots_out),
            "neigh_feats": self.node_inputs(graph, hop.nodes),
            "node_ids": np.clip(roots_out, 0, self.max_id + 1),
            "neigh_ids": np.clip(hop.nodes, 0, self.max_id + 1),
            "adj": hop.adj,
        }
        if not self.device_features:
            batch["labels"] = graph.get_dense_feature(
                roots, [self.label_idx], [self.label_dim]
            )
        return batch

