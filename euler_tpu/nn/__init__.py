from euler_tpu.nn import aggregators, layers, metrics, sparse_aggregators
from euler_tpu.nn.encoders import (
    GCNEncoder,
    SageEncoder,
    ScalableSageEncoder,
    ShallowEncoder,
    SparseSageEncoder,
)

__all__ = [
    "aggregators",
    "layers",
    "metrics",
    "sparse_aggregators",
    "GCNEncoder",
    "SageEncoder",
    "ScalableSageEncoder",
    "ShallowEncoder",
    "SparseSageEncoder",
]
