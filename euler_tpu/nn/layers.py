"""Core layers (flax.linen).

Reference equivalent: tf_euler/python/base_layers.py (Dense :69,
Embedding :116, SparseEmbedding :146). SparseEmbedding here consumes the
padded (ids, mask) pairs produced by ops.get_sparse_feature instead of a
tf.SparseTensor — a masked lookup-and-combine that stays fixed-shape on TPU.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp


class Dense(nn.Module):
    dim: int
    activation: Optional[Callable] = None
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        y = nn.Dense(self.dim, use_bias=self.use_bias)(x)
        if self.activation is not None:
            y = self.activation(y)
        return y


class Embedding(nn.Module):
    """Id embedding table of size max_id+1 (ids are clipped into range;
    callers pass max_id+1 as the default/padding id like the reference)."""

    num: int
    dim: int
    stddev: float = 0.1

    @nn.compact
    def __call__(self, ids):
        table = self.param(
            "embeddings",
            nn.initializers.truncated_normal(stddev=self.stddev),
            (self.num, self.dim),
        )
        ids = jnp.clip(ids, 0, self.num - 1)
        return table[ids]


class SparseEmbedding(nn.Module):
    """Masked combine over padded sparse-id features.

    combiner 'sum' matches the reference default
    (base_layers.py:146 embedding_lookup_sparse combiner='sum').
    """

    num: int
    dim: int
    combiner: str = "sum"
    stddev: float = 0.0002

    @nn.compact
    def __call__(self, ids, mask):
        table = self.param(
            "embeddings",
            nn.initializers.truncated_normal(stddev=self.stddev),
            (self.num, self.dim),
        )
        ids = jnp.clip(ids, 0, self.num - 1)
        emb = table[ids] * mask[..., None]  # [n, L, dim]
        total = emb.sum(axis=-2)
        if self.combiner == "sum":
            return total
        if self.combiner == "mean":
            denom = jnp.maximum(mask.sum(axis=-1, keepdims=True), 1.0)
            return total / denom
        raise ValueError(f"unknown combiner {self.combiner}")
