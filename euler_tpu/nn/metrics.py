"""Streaming metrics.

Reference equivalent: tf_euler/python/metrics.py (streaming f1 from
tp/fp/fn :23-34, mrr :36-44). JAX is functional, so the streaming state is
an explicit counts pytree the training loop threads through steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def f1_counts(labels, predictions):
    """Per-batch (tp, fp, fn) for micro-F1 accumulation. Inputs binarize
    like tf.metrics.true_positives (cast to bool)."""
    labels = (labels != 0).astype(jnp.float32)
    predictions = (predictions != 0).astype(jnp.float32)
    tp = jnp.sum(predictions * labels)
    fp = jnp.sum(predictions * (1.0 - labels))
    fn = jnp.sum((1.0 - predictions) * labels)
    return jnp.stack([tp, fp, fn])


def f1_from_counts(counts) -> float:
    """Micro-F1 from accumulated [tp, fp, fn]."""
    tp, fp, fn = np.asarray(counts, dtype=np.float64)
    eps = 1e-7
    precision = tp / (eps + tp + fp)
    recall = tp / (eps + tp + fn)
    return float(2.0 * precision * recall / (precision + recall + eps))


def mrr(logits, neg_logits):
    """Mean reciprocal rank of the positive among its negatives.

    logits: [..., 1, 1]; neg_logits: [..., 1, k]. Ties resolve against the
    positive (matches the reference's double-top_k construction where the
    positive is the last column).
    """
    rank = 1.0 + jnp.sum(neg_logits >= logits, axis=-1)
    return jnp.mean(1.0 / rank)


AUC_BINS = 200


def auc_counts(labels, scores, nbins: int = AUC_BINS):
    """Per-batch [2, nbins] score histograms (row 0 = negatives, row 1 =
    positives) for streaming AUC (the JAX analog of tf.metrics.auc's
    bucketed accumulators, used by the reference LasGNN,
    models/lasgnn.py:153)."""
    labels = (labels.reshape(-1) != 0).astype(jnp.float32)
    scores = jnp.clip(scores.reshape(-1), 0.0, 1.0 - 1e-7)
    bins = (scores * nbins).astype(jnp.int32)
    onehot = jax.nn.one_hot(bins, nbins)
    pos = jnp.sum(onehot * labels[:, None], axis=0)
    neg = jnp.sum(onehot * (1.0 - labels)[:, None], axis=0)
    return jnp.stack([neg, pos])


def auc_from_counts(counts) -> float:
    """Trapezoidal AUC from accumulated [2, nbins] histograms."""
    neg, pos = np.asarray(counts, dtype=np.float64)
    p_tot, n_tot = pos.sum(), neg.sum()
    if p_tot == 0 or n_tot == 0:
        return 0.5
    # For each positive bin b: negatives strictly below + half of ties.
    neg_below = np.concatenate([[0.0], np.cumsum(neg)[:-1]])
    wins = np.sum(pos * (neg_below + 0.5 * neg))
    return float(wins / (p_tot * n_tot))


def accuracy(labels, predictions):
    return jnp.mean(
        (jnp.argmax(labels, -1) == jnp.argmax(predictions, -1)).astype(
            jnp.float32
        )
    )
