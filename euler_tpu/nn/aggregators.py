"""Dense (fanout-shaped) aggregators for sampled-neighbor encoders.

Reference equivalent: tf_euler/python/aggregators.py:25-113. Inputs are
(self_embedding [n, d], neigh_embedding [n, fanout, d]); everything is a
reduce + matmul, which XLA fuses and maps onto the MXU.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.nn.layers import Dense


class GCNAggregator(nn.Module):
    dim: int
    activation: Optional[Callable] = nn.relu

    @nn.compact
    def __call__(self, inputs):
        self_emb, neigh_emb = inputs
        all_emb = jnp.concatenate([self_emb[:, None, :], neigh_emb], axis=1)
        agg = all_emb.mean(axis=1)
        return Dense(self.dim, self.activation, use_bias=False)(agg)


class _BaseAggregator(nn.Module):
    dim: int
    activation: Optional[Callable] = nn.relu
    concat: bool = False

    def aggregate(self, neigh_emb):
        raise NotImplementedError

    @nn.compact
    def __call__(self, inputs):
        self_emb, neigh_emb = inputs
        dim = self.dim
        if self.concat:
            if dim % 2:
                raise ValueError("dim must be even when concat=True")
            dim //= 2
        agg = self.aggregate(neigh_emb)
        from_self = Dense(dim, self.activation, use_bias=False)(self_emb)
        from_neigh = Dense(dim, self.activation, use_bias=False)(agg)
        if self.concat:
            return jnp.concatenate([from_self, from_neigh], axis=1)
        return from_self + from_neigh


class MeanAggregator(_BaseAggregator):
    def aggregate(self, neigh_emb):
        return neigh_emb.mean(axis=1)


class MeanPoolAggregator(_BaseAggregator):
    def aggregate(self, neigh_emb):
        h = Dense(self.dim, nn.relu)(neigh_emb)
        return h.mean(axis=1)


class MaxPoolAggregator(_BaseAggregator):
    def aggregate(self, neigh_emb):
        h = Dense(self.dim, nn.relu)(neigh_emb)
        return h.max(axis=1)


AGGREGATORS = {
    "gcn": GCNAggregator,
    "mean": MeanAggregator,
    "meanpool": MeanPoolAggregator,
    "maxpool": MaxPoolAggregator,
}


def get(name: str):
    return AGGREGATORS.get(name)
