"""Node encoders (device side, flax.linen).

Reference equivalent: tf_euler/python/encoders.py. The key architectural
change vs the reference: encoders are *pure device modules* — all graph
queries (fanout sampling, multi-hop expansion, feature gather) happen on the
host in the model's `sample()` phase, and the encoder consumes the resulting
fixed-shape arrays. That split is what lets the whole train step jit into a
single XLA program and lets sampling overlap device compute.

Host-side input conventions:
  feats dict (per node set): optional keys
    'ids'    [n] int32/int64  — for the id-embedding path
    'dense'  [n, sum(feature_dim)] float32
    'sparse' list of (ids [n, L], mask [n, L]) per sparse slot
  SageEncoder: list of per-hop feats dicts, hop h has n*prod(fanouts[:h]) rows.
  GCNEncoder: per-hop feats + adjacency dicts {src, dst, w, mask} — use
  MultiHop.adj from ops.get_multi_hop_neighbor.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from euler_tpu.nn import aggregators as dense_aggs
from euler_tpu.nn import sparse_aggregators as sparse_aggs
from euler_tpu.nn.layers import Dense, Embedding, SparseEmbedding


class ShallowEncoder(nn.Module):
    """Id embedding + dense features + sparse-feature embeddings, combined
    by 'add' or 'concat' (reference encoders.py:30-162)."""

    dim: Optional[int] = None
    feature_dim: int = 0  # total host-gathered dense feature width
    max_id: int = -1  # >=0 enables the id-embedding path
    embedding_dim: int = 16
    sparse_feature_max_ids: Sequence[int] = ()
    combiner: str = "concat"

    @property
    def output_dim(self) -> int:
        if self.dim is not None:
            return self.dim
        out = self.feature_dim
        if self.max_id >= 0:
            out += self.embedding_dim
        out += self.embedding_dim * len(self.sparse_feature_max_ids)
        return out

    @nn.compact
    def __call__(self, feats: dict):
        embeddings = []
        emb_dim = self.dim if self.combiner == "add" else self.embedding_dim
        if self.max_id >= 0:
            embeddings.append(
                Embedding(self.max_id + 2, emb_dim)(feats["ids"])
            )
        if self.feature_dim:
            dense = feats["dense"]
            if self.combiner == "add":
                dense = Dense(self.dim, use_bias=False)(dense)
            embeddings.append(dense)
        for k, max_id in enumerate(self.sparse_feature_max_ids):
            ids, mask = feats["sparse"][k]
            embeddings.append(SparseEmbedding(max_id + 2, emb_dim)(ids, mask))
        if self.combiner == "add":
            return sum(embeddings)
        out = jnp.concatenate(embeddings, axis=-1)
        if self.dim is not None:
            out = Dense(self.dim, use_bias=False)(out)
        return out


class SageEncoder(nn.Module):
    """GraphSAGE aggregation over host-sampled fanouts
    (reference encoders.py:327-401). `hidden` is the per-hop encoded
    feature list; layer l aggregates hop h with hop h+1."""

    fanouts: Sequence[int]
    dim: int
    aggregator: str = "mean"
    concat: bool = False

    @nn.compact
    def __call__(self, hidden: list):
        num_layers = len(self.fanouts)
        assert len(hidden) == num_layers + 1
        agg_cls = dense_aggs.get(self.aggregator)
        aggs = [
            agg_cls(
                self.dim,
                activation=nn.relu if l < num_layers - 1 else None,
                concat=self.concat,
            )
            if agg_cls is not dense_aggs.GCNAggregator
            else agg_cls(
                self.dim,
                activation=nn.relu if l < num_layers - 1 else None,
            )
            for l in range(num_layers)
        ]
        for layer in range(num_layers):
            next_hidden = []
            for hop in range(num_layers - layer):
                d = hidden[hop].shape[-1]
                neigh = hidden[hop + 1].reshape(-1, self.fanouts[hop], d)
                next_hidden.append(aggs[layer]((hidden[hop], neigh)))
            hidden = next_hidden
        return hidden[0]


class SparseSageEncoder(nn.Module):
    """Sparse-feature GraphSAGE (reference encoders.py:522-560): per-slot
    SparseEmbedding lookups (embedding_dim each, concatenated — the
    reference hardcodes 16) feed SageEncoder aggregation.

    ``hops`` is the per-hop list of per-slot (ids, mask) padded sparse
    features (hop h sized batch * prod(fanouts[:h]); the 'sparse' entry
    of the feats-dict convention above). Pass already-constructed
    SparseEmbedding modules via ``shared_embeddings`` to tie the tables
    across towers (the reference's shared_embeddings argument) — LasGNN
    shares one set across all its metapath towers this way."""

    fanouts: Sequence[int]
    dim: int
    feature_dims: Sequence[int] = ()  # per-slot max sparse id
    aggregator: str = "mean"
    concat: bool = False
    embedding_dim: int = 16
    shared_embeddings: Optional[Sequence[SparseEmbedding]] = None

    def setup(self):
        if self.shared_embeddings is not None:
            self.sparse_embeddings = list(self.shared_embeddings)
        else:
            # feature_dim + 1 sparse slots plus the padding id
            self.sparse_embeddings = [
                SparseEmbedding(d + 2, self.embedding_dim)
                for d in self.feature_dims
            ]
        self.sage = SageEncoder(
            tuple(self.fanouts), self.dim, self.aggregator, self.concat
        )

    def __call__(self, hops):
        hidden = [
            jnp.concatenate(
                [
                    emb(ids, mask)
                    for emb, (ids, mask) in zip(
                        self.sparse_embeddings, hop
                    )
                ],
                axis=-1,
            )
            for hop in hops
        ]
        return self.sage(hidden)


class GCNEncoder(nn.Module):
    """Full-neighbor multi-hop GCN over padded COO adjacency
    (reference encoders.py:165-215)."""

    num_layers: int
    dim: int
    aggregator: str = "gcn"
    use_residual: bool = False

    @nn.compact
    def __call__(self, hidden: list, adjs: list):
        assert len(hidden) == self.num_layers + 1
        assert len(adjs) == self.num_layers
        agg_cls = sparse_aggs.get(self.aggregator)
        aggs = [
            agg_cls(
                self.dim,
                activation=nn.relu if l < self.num_layers - 1 else None,
            )
            for l in range(self.num_layers)
        ]
        for layer in range(self.num_layers):
            next_hidden = []
            for hop in range(self.num_layers - layer):
                h = aggs[layer]((hidden[hop], hidden[hop + 1], adjs[hop]))
                if self.use_residual:
                    h = hidden[hop] + h
                next_hidden.append(h)
            hidden = next_hidden
        return hidden[0]


class _AttHead(nn.Module):
    """One all-pairs attention head over [B, n, F]
    (reference encoders.py:587-598 att_head)."""

    out_size: int

    @nn.compact
    def __call__(self, seq, activation=nn.elu):
        seq_fts = nn.Dense(self.out_size, use_bias=False)(seq)  # [B, n, out]
        f1 = nn.Dense(1)(seq_fts)  # [B, n, 1]
        f2 = nn.Dense(1)(seq_fts)  # [B, n, 1]
        logits = f1 + jnp.swapaxes(f2, 1, 2)  # [B, n, n]
        coefs = nn.softmax(nn.leaky_relu(logits), axis=-1)
        vals = jnp.einsum("bij,bjd->bid", coefs, seq_fts)
        bias = self.param("bias", nn.initializers.zeros, (self.out_size,))
        out = vals + bias
        if activation is not None:
            out = activation(out)
        return out


class AttEncoder(nn.Module):
    """GAT-style attention over a sampled neighborhood
    (reference encoders.py:563-632): input is the [B, nb+1, F] feature
    sequence (self node at position 0 + nb sampled neighbors); two rounds of
    attention heads; output is position 0's features. All-pairs softmax
    attention on a tiny nb+1 axis — dense matmuls, MXU-friendly."""

    head_num: int = 1
    hidden_dim: int = 256
    out_dim: int = 1

    @nn.compact
    def __call__(self, seq):
        hidden = [
            _AttHead(self.hidden_dim)(seq) for _ in range(self.head_num)
        ]
        h1 = jnp.concatenate(hidden, axis=-1)
        outs = [_AttHead(self.out_dim)(h1) for _ in range(self.head_num)]
        out = sum(outs) / self.head_num  # [B, n, out_dim]
        return out[:, 0, :]


class ScalableSageEncoder(nn.Module):
    """GraphSAGE with historical-embedding stores: each layer >0 reads its
    neighbor embeddings from a store instead of recursive sampling, capping
    the receptive field at one hop per step
    (reference encoders.py:404-519). The store read/write and the
    two-optimizer store-gradient dance live in the model's train step; this
    module is the pure function: given per-layer neighbor embeddings
    (store_reads), produce the per-layer node embeddings."""

    fanout: int
    num_layers: int
    dim: int
    aggregator: str = "mean"
    concat: bool = False

    @nn.compact
    def __call__(self, node_feat, neigh_feat, store_reads: list):
        """node_feat [B, d0]; neigh_feat [B*fanout, d0]; store_reads: list of
        num_layers-1 arrays [B*fanout, dim] (stale neighbor embeddings).
        Returns (final [B, dim'], node_embeddings per layer)."""
        agg_cls = dense_aggs.get(self.aggregator)
        node_emb, neigh_emb = node_feat, neigh_feat
        node_embeddings = []
        for layer in range(self.num_layers):
            agg = agg_cls(
                self.dim,
                activation=nn.relu if layer < self.num_layers - 1 else None,
                **({} if agg_cls is dense_aggs.GCNAggregator
                   else {"concat": self.concat}),
            )
            d = node_emb.shape[-1]
            neigh = neigh_emb.reshape(-1, self.fanout, d)
            node_emb = agg((node_emb, neigh))
            node_embeddings.append(node_emb)
            if layer < self.num_layers - 1:
                neigh_emb = store_reads[layer]
        return node_emb, node_embeddings
