"""Segment-op aggregators over padded COO adjacency (full-neighbor GCN path).

Reference equivalent: tf_euler/python/sparse_aggregators.py:20-146, which
uses tf.SparseTensor matmul/softmax. Here the adjacency is the padded COO
from ops.get_multi_hop_neighbor (adj_src/adj_dst index the current/next hop
node arrays) and aggregation is jax.ops.segment_sum with static segment
counts — the XLA-native form of sparse x dense. Padding edges carry
edge_mask 0 and contribute nothing.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from euler_tpu.nn.layers import Dense


def _degree(adj_src, edge_mask, num_nodes):
    return jax.ops.segment_sum(edge_mask, adj_src, num_segments=num_nodes)


def _gather_sum(values, adj_src, num_nodes):
    return jax.ops.segment_sum(values, adj_src, num_segments=num_nodes)


class GCNAggregator(nn.Module):
    """(self + sum(neigh)/deg) @ W, or renorm (self + sum)/(1+deg) @ W
    (reference sparse_aggregators.py:37-55 uses binary adjacency)."""

    dim: int
    activation: Optional[Callable] = nn.relu
    renorm: bool = False

    @nn.compact
    def __call__(self, inputs):
        self_emb, neigh_emb, adj = inputs
        src, dst, edge_mask = adj["src"], adj["dst"], adj["mask"]
        n = self_emb.shape[0]
        deg = _degree(src, edge_mask, n)[:, None]
        msgs = neigh_emb[dst] * edge_mask[:, None]
        agg = _gather_sum(msgs, src, n)
        if self.renorm:
            agg = (self_emb + agg) / (1.0 + deg)
        else:
            agg = self_emb + agg / jnp.maximum(deg, 1e-7)
        return Dense(self.dim, self.activation, use_bias=False)(agg)


class MeanAggregator(nn.Module):
    dim: int
    activation: Optional[Callable] = nn.relu
    concat: bool = False

    @nn.compact
    def __call__(self, inputs):
        self_emb, neigh_emb, adj = inputs
        src, dst, edge_mask = adj["src"], adj["dst"], adj["mask"]
        n = self_emb.shape[0]
        dim = self.dim // 2 if self.concat else self.dim
        deg = _degree(src, edge_mask, n)[:, None]
        msgs = neigh_emb[dst] * edge_mask[:, None]
        agg = _gather_sum(msgs, src, n) / jnp.maximum(deg, 1e-7)
        from_self = Dense(dim, self.activation, use_bias=False)(self_emb)
        from_neigh = Dense(dim, self.activation, use_bias=False)(agg)
        if self.concat:
            return jnp.concatenate([from_self, from_neigh], axis=1)
        return from_self + from_neigh


def segment_softmax(logits, segments, num_segments, mask):
    """Numerically-stable softmax of edge logits within each src segment.
    Masked edges get zero probability."""
    neg = jnp.finfo(logits.dtype).min
    masked = jnp.where(mask > 0, logits, neg)
    seg_max = jax.ops.segment_max(masked, segments, num_segments=num_segments)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    e = jnp.exp(masked - seg_max[segments]) * mask
    denom = jax.ops.segment_sum(e, segments, num_segments=num_segments)
    return e / jnp.maximum(denom[segments], 1e-16)


class SingleAttentionAggregator(nn.Module):
    """GAT-style single head over COO adjacency
    (reference sparse_aggregators.py:84-116). With renorm, a virtual
    self-edge is added to each row's softmax."""

    dim: int
    activation: Optional[Callable] = nn.relu
    renorm: bool = False

    @nn.compact
    def __call__(self, inputs):
        self_emb, neigh_emb, adj = inputs
        src, dst, edge_mask = adj["src"], adj["dst"], adj["mask"]
        n = self_emb.shape[0]
        dense = Dense(self.dim, use_bias=False)
        self_gate = Dense(1, use_bias=False)
        all_gate = Dense(1, use_bias=False)
        from_self = dense(self_emb)          # [n, dim]
        from_all = dense(neigh_emb)          # [m, dim]
        self_w = self_gate(from_self)[:, 0]  # [n]
        all_w = all_gate(from_all)[:, 0]     # [m]

        logits = nn.leaky_relu(self_w[src] + all_w[dst])
        if self.renorm:
            # Append one self-edge per node to the softmax support; its
            # "context" logit is the all-gate applied to the self projection
            # (the reference concatenates self rows into the `all` set,
            # sparse_aggregators.py:96-101).
            self_logits = nn.leaky_relu(self_w + all_gate(from_self)[:, 0])
            ext_logits = jnp.concatenate([logits, self_logits])
            ext_src = jnp.concatenate([src, jnp.arange(n, dtype=src.dtype)])
            ext_mask = jnp.concatenate([edge_mask, jnp.ones(n)])
            coef = segment_softmax(ext_logits, ext_src, n, ext_mask)
            msgs = jnp.concatenate([from_all[dst], from_self]) * coef[:, None]
            out = jax.ops.segment_sum(msgs, ext_src, num_segments=n)
        else:
            coef = segment_softmax(logits, src, n, edge_mask)
            msgs = from_all[dst] * coef[:, None]
            out = jax.ops.segment_sum(msgs, src, num_segments=n)
            out = from_self + out
        if self.activation is not None:
            out = self.activation(out)
        return out


class AttentionAggregator(nn.Module):
    """Multi-head concat (reference sparse_aggregators.py:119-133)."""

    dim: int
    num_heads: int = 4
    activation: Optional[Callable] = nn.relu
    renorm: bool = False

    @nn.compact
    def __call__(self, inputs):
        head_dim = self.dim // self.num_heads
        outs = [
            SingleAttentionAggregator(
                head_dim, self.activation, self.renorm
            )(inputs)
            for _ in range(self.num_heads)
        ]
        return jnp.concatenate(outs, axis=1)


AGGREGATORS = {
    "gcn": GCNAggregator,
    "mean": MeanAggregator,
    "attention": AttentionAggregator,
}


def get(name: str):
    return AGGREGATORS.get(name)
