"""Random walk + skip-gram pair ops.

Reference equivalents: tf_euler/python/euler_ops/walk_ops.py, the RandomWalk
async kernel chain (tf_euler/kernels/random_walk_op.cc:31-140 — walk_len
sequential round trips) and GenPair (tf_euler/kernels/gen_pair_op.cc:43-95).
The walk here is one native call that runs the whole chain inside the
engine; gen_pair is vectorized numpy with the same enumeration order and the
same exact (dense, unpadded) pair count.
"""

from __future__ import annotations

import numpy as np


def random_walk(g, nodes, edge_types, walk_len, p=1.0, q=1.0, default_node=-1):
    """[n, walk_len+1] int64 node2vec walks (column 0 = start)."""
    return g.random_walk(nodes, edge_types, walk_len, p, q, default_node)


def pair_count(path_len: int, left_win: int, right_win: int) -> int:
    """Exact number of skip-gram pairs per path (matches the reference's
    static shape function, tf_euler/ops/walk_ops.cc:40-54)."""
    count = path_len * (left_win + right_win)
    for i in range(min(left_win, path_len)):
        count -= left_win - i
    for i in range(min(right_win, path_len)):
        count -= right_win - i
    return count


def pair_indices(path_len: int, left_win: int, right_win: int):
    """Static (target, context) position index arrays for skip-gram pair
    enumeration — shared by the host gen_pair and the on-device walk path
    (euler_tpu/graph/device.py), so both enumerate in the reference
    kernel's order: positions j = 0..len-1, left contexts j-1, j-2, ...,
    then right contexts j+1, j+2, ..."""
    blocks = []
    for j in range(path_len):
        for k in range(left_win):
            if j - k - 1 >= 0:
                blocks.append((j, j - k - 1))
        for k in range(right_win):
            if j + k + 1 < path_len:
                blocks.append((j, j + k + 1))
    tgt = np.array([b[0] for b in blocks], dtype=np.int32)
    ctx = np.array([b[1] for b in blocks], dtype=np.int32)
    return tgt, ctx


def gen_pair(paths, left_win_size: int, right_win_size: int) -> np.ndarray:
    """[batch, pair_count, 2] (target, context) pairs."""
    paths = np.asarray(paths, dtype=np.int64)
    if paths.ndim == 1:
        paths = paths[None, :]
    batch, path_len = paths.shape
    tgt_idx, ctx_idx = pair_indices(path_len, left_win_size, right_win_size)
    if len(tgt_idx) == 0:
        return np.zeros((batch, 0, 2), dtype=np.int64)
    pairs = np.stack([paths[:, tgt_idx], paths[:, ctx_idx]], axis=-1)
    return pairs
