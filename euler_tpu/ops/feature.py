"""Feature gather ops.

Reference equivalent: tf_euler/python/euler_ops/feature_ops.py. Dense gather
is already fixed-shape; sparse (uint64 id-list) features are returned padded
+ masked instead of as tf.SparseTensor, ready for embedding-lookup +
masked-combine on device.
"""

from __future__ import annotations

import numpy as np


def get_dense_feature(g, nodes, feature_ids, dimensions):
    """[n, sum(dimensions)] float32 (zero-padded per slot)."""
    return g.get_dense_feature(nodes, feature_ids, dimensions)


def get_edge_dense_feature(g, src, dst, types, feature_ids, dimensions):
    return g.get_edge_dense_feature(src, dst, types, feature_ids, dimensions)


def get_sparse_feature(
    g, nodes, feature_ids, max_len, default_values=None, edge=None
):
    """Padded sparse (id-list) features.

    Args:
      max_len: per-slot pad length (int or list). Longer rows are truncated.
      default_values: per-slot fill id for padding positions (defaults to 0;
        the reference uses max_id+1, pass that for parity with
        ShallowEncoder semantics).
      edge: optional (src, dst, types) triple to gather edge features
        instead of node features.

    Returns per slot: (ids [n, max_len] int64, mask [n, max_len] float32).
    """
    nslots = len(feature_ids)
    if isinstance(max_len, int):
        max_len = [max_len] * nslots
    if default_values is None:
        default_values = [0] * nslots
    if edge is not None:
        raw = g.get_edge_sparse_feature(*edge, feature_ids)
    else:
        raw = g.get_sparse_feature(nodes, feature_ids)
    out = []
    for k in range(nslots):
        vals, counts = raw[k]
        n = len(counts)
        L = max_len[k]
        ids = np.full((n, L), default_values[k], dtype=np.int64)
        mask = np.zeros((n, L), dtype=np.float32)
        off = 0
        for i, c in enumerate(counts):
            c = int(c)
            take = min(c, L)
            ids[i, :take] = vals[off : off + take]
            mask[i, :take] = 1.0
            off += c
        out.append((ids, mask))
    return out
