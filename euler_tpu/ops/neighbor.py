"""Neighbor ops: fanout sampling and padded multi-hop adjacency.

Reference equivalents: tf_euler/python/euler_ops/neighbor_ops.py
(sample_fanout :64-97, get_multi_hop_neighbor :99-130). The multi-hop result
here is padded + masked COO instead of tf.SparseTensor so the GCN/attention
aggregators can run as jax.ops.segment_sum over static shapes.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def sample_neighbor(g, nodes, edge_types, count, default_node=-1):
    return g.sample_neighbor(nodes, edge_types, count, default_node)


def sample_fanout(g, nodes, edge_types, counts, default_node=-1):
    """Multi-hop weighted fanout; one fused native call for all hops.

    Returns (ids_per_hop, weights_per_hop, types_per_hop) like the
    reference: ids_per_hop[0] is the flattened input, hop h has
    n * prod(counts[:h]) rows.
    """
    return g.sample_fanout(nodes, edge_types, counts, default_node)


@dataclasses.dataclass
class MultiHop:
    """One hop of padded multi-hop adjacency.

    nodes:      [max_nodes] int64 node ids of the *next* hop (padded with
                default_node).
    num_nodes:  true count before padding.
    adj_src:    [max_edges] int32 — index into the *current* hop's node
                array for each edge.
    adj_dst:    [max_edges] int32 — index into `nodes` for each edge.
    adj_w:      [max_edges] float32 edge weight (0 on padding).
    num_edges:  true count before padding.
    """

    nodes: np.ndarray
    num_nodes: int
    adj_src: np.ndarray
    adj_dst: np.ndarray
    adj_w: np.ndarray
    num_edges: int

    @property
    def adj(self) -> dict:
        """Adjacency dict for the sparse aggregators
        (euler_tpu.nn.sparse_aggregators): keys src/dst/w/mask, where mask
        marks real (non-padding) edges."""
        mask = (
            np.arange(len(self.adj_src), dtype=np.float32) < self.num_edges
        ).astype(np.float32)
        return {
            "src": self.adj_src,
            "dst": self.adj_dst,
            "w": self.adj_w,
            "mask": mask,
        }


def get_multi_hop_neighbor(
    g,
    nodes,
    edge_types,
    max_nodes_per_hop=None,
    max_edges_per_hop=None,
    default_node=-1,
):
    """Full-neighbor multi-hop expansion with per-hop dedup.

    Args:
      g: Graph.
      nodes: 1-D int64 root node ids.
      edge_types: per-hop list of edge-type lists.
      max_nodes_per_hop / max_edges_per_hop: per-hop static pad sizes. When
        None, arrays are exact-size (host-only use); when set, arrays are
        padded (and raise if the true size exceeds the cap) so the device
        step sees static shapes.

    Returns (roots, hops): roots is the flattened input ids; hops is a list
    of MultiHop, one per entry of edge_types.
    """
    cur = np.asarray(nodes, dtype=np.int64).reshape(-1)
    roots = cur
    hops: list[MultiHop] = []
    for h, et in enumerate(edge_types):
        nbr, w, _, counts = g.get_full_neighbor(cur, et)
        uniq, inv = np.unique(nbr, return_inverse=True)
        src = np.repeat(np.arange(len(cur), dtype=np.int32), counts)
        dst = inv.astype(np.int32)
        n_nodes, n_edges = len(uniq), len(nbr)
        if max_nodes_per_hop is not None:
            cap = max_nodes_per_hop[h]
            if n_nodes > cap:
                raise ValueError(
                    f"hop {h}: {n_nodes} unique neighbors > cap {cap}"
                )
            uniq = np.concatenate(
                [uniq, np.full(cap - n_nodes, default_node, dtype=np.int64)]
            )
        if max_edges_per_hop is not None:
            cap = max_edges_per_hop[h]
            if n_edges > cap:
                raise ValueError(f"hop {h}: {n_edges} edges > cap {cap}")
            pad = cap - n_edges
            # Padding edges point at slot 0 with weight 0: they contribute
            # nothing to weighted segment sums.
            src = np.concatenate([src, np.zeros(pad, dtype=np.int32)])
            dst = np.concatenate([dst, np.zeros(pad, dtype=np.int32)])
            w = np.concatenate([w, np.zeros(pad, dtype=np.float32)])
        hops.append(
            MultiHop(
                nodes=uniq,
                num_nodes=n_nodes,
                adj_src=src,
                adj_dst=dst,
                adj_w=w.astype(np.float32, copy=False),
                num_edges=n_edges,
            )
        )
        cur = uniq[:n_nodes] if max_nodes_per_hop is not None else uniq
    return roots, hops
