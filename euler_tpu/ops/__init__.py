"""Host-side graph query ops with TPU-friendly (fixed-shape) outputs.

Equivalent surface to the reference's Python op wrappers
(reference tf_euler/python/euler_ops/{neighbor,sample,feature,walk}_ops.py),
re-designed for the JAX split: these run on the host (inside the prefetch
pipeline), and everything they return is either exactly-shaped or padded +
masked so the device step can be jitted with static shapes.
"""

from euler_tpu.ops.neighbor import (
    MultiHop,
    get_multi_hop_neighbor,
    sample_fanout,
    sample_neighbor,
)
from euler_tpu.ops.feature import (
    get_dense_feature,
    get_edge_dense_feature,
    get_sparse_feature,
)
from euler_tpu.ops.sample import sample_edge, sample_node, sample_node_with_src
from euler_tpu.ops.walk import gen_pair, random_walk

__all__ = [
    "MultiHop",
    "get_multi_hop_neighbor",
    "sample_fanout",
    "sample_neighbor",
    "get_dense_feature",
    "get_edge_dense_feature",
    "get_sparse_feature",
    "sample_edge",
    "sample_node",
    "sample_node_with_src",
    "gen_pair",
    "random_walk",
]
