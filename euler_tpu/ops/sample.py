"""Global sampling ops.

Reference equivalent: tf_euler/python/euler_ops/sample_ops.py. The typed
negative sampler (sample_node_with_src) is a single native batch call here —
the reference needed a unique/while_loop/inflate_idx TF pipeline
(sample_ops.py:39-67) because per-row typed draws were awkward in TF; the
host engine does it directly.
"""


def sample_node(g, count, node_type=-1):
    return g.sample_node(count, node_type)


def sample_edge(g, count, edge_type=-1):
    return g.sample_edge(count, edge_type)


def sample_node_with_src(g, src_nodes, count):
    """[n, count] negatives drawn from each src node's type distribution."""
    return g.sample_node_with_src(src_nodes, count)
