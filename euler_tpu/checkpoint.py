"""Checkpoint / resume for training state.

Role equivalent of the reference's delegation to
tf.train.MonitoredTrainingSession(checkpoint_dir=...) (reference
tf_euler/python/run_loop.py:132-138): periodic save of the full training
state (params + optimizer state) with automatic resume from the latest
step on restart. Built on orbax, the JAX-native checkpointer — state is a
pytree of (possibly sharded) jax.Arrays, saved asynchronously so the train
loop does not stall. Graph data itself is never checkpointed: like the
reference, the store is an immutable input (SURVEY §5.4).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np


def _manager(ckpt_dir: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(ckpt_dir),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True
        ),
    )


class Checkpointer:
    """Periodic saver + latest-step restorer over one directory."""

    def __init__(self, ckpt_dir: str, max_to_keep: int = 3):
        self.dir = os.path.abspath(ckpt_dir)
        self._mngr = _manager(ckpt_dir, max_to_keep)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def save(self, step: int, state: Any, force: bool = False) -> None:
        import orbax.checkpoint as ocp

        # 'consts' holds device-resident graph tables (features/labels) —
        # immutable inputs reconstructible from the graph, per the module
        # invariant that graph data is never checkpointed. Excluding them
        # also keeps checkpoints interchangeable across device_features
        # on/off.
        if isinstance(state, dict) and "consts" in state:
            state = {k: v for k, v in state.items() if k != "consts"}
        self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure of state_like (an initialized state
        pytree — shapes/dtypes/shardings are taken from it). A 'consts'
        entry in state_like is carried over as-is, not read from disk."""
        import jax
        import orbax.checkpoint as ocp

        # Loud, actionable failures instead of an orbax stack trace: a
        # missing checkpoint names the directory and what IS there, and
        # a tree mismatch (below) names both ends of the contract —
        # these fire at serving startup (serve.py requires a restore),
        # where "FileNotFoundError: .../d" helps nobody.
        steps = sorted(self._mngr.all_steps())
        if step is None:
            step = self._mngr.latest_step()
            if step is None:
                raise ValueError(
                    f"no checkpoint in {self.dir} (no saved steps — "
                    f"train with --model_dir={self.dir} first)"
                )
        elif step not in steps:
            raise ValueError(
                f"no checkpoint for step {step} in {self.dir} "
                f"(available steps: {steps})"
            )
        consts = None
        if isinstance(state_like, dict) and "consts" in state_like:
            consts = state_like["consts"]
            state_like = {
                k: v for k, v in state_like.items() if k != "consts"
            }
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x),
                x.dtype if hasattr(x, "dtype") else np.asarray(x).dtype,
                sharding=getattr(x, "sharding", None),
            ),
            state_like,
        )
        try:
            restored = self._mngr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        except Exception as e:
            raise ValueError(
                f"checkpoint at step {step} in {self.dir} does not "
                f"match the provided state_like structure (saved with "
                f"a different model/optimizer config?): "
                f"{type(e).__name__}: {e}"
            ) from e
        if consts is not None:
            restored = dict(restored)
            restored["consts"] = consts
        return restored

    def wait(self) -> None:
        """Block until async saves complete (call before process exit)."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mngr.close()
