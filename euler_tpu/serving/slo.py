"""Serve-latency SLO tracking: exact p50/p99 over a sliding window.

The native ``serve:total`` histogram is log2-bucketed (cheap, scrape-
friendly, but ~2x-coarse at the tail); an SLO verdict wants exact
order statistics over recent traffic. This tracker keeps the last
``window`` successful request latencies in a ring and reports exact
percentiles against the configured target — the number an operator
pages on, next to (not instead of) the histogram families.
"""

from __future__ import annotations

import math
import threading

# Every N records the tracker pushes its window p50/p99 + lifetime
# violations into the native serve-SLO gauges (eg_devprof.h), so
# metrics_text()/the STATS scrape read live serving latency
# (eg_serve_slo_ms{quantile=...}) without draining the server. The push
# sorts the window (O(w log w)) — amortized to every 32nd request it is
# noise next to a device dispatch.
_PUSH_EVERY = 32


class SLOTracker:
    """p50/p99 of served request latency vs a target, over a ring of
    the most recent ``window`` successful completions."""

    def __init__(self, target_ms: float, window: int = 2048):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.target_ms = float(target_ms)
        self.window = int(window)
        self._ring = [0.0] * self.window
        self._count = 0  # total recorded (ring holds min(count, window))
        self._violations = 0  # recorded samples over target, lifetime
        self._lock = threading.Lock()

    def record(self, total_us: float) -> None:
        ms = float(total_us) / 1e3
        with self._lock:
            self._ring[self._count % self.window] = ms
            self._count += 1
            if ms > self.target_ms:
                self._violations += 1
            push_due = self._count == 1 or self._count % _PUSH_EVERY == 0
        if push_due:
            self.push_gauges()

    def push_gauges(self) -> None:
        """Refresh the native live gauges (eg_serve_slo_ms /
        eg_serve_slo_violations_total) from the current window."""
        from euler_tpu.graph.native import lib

        p50 = self.percentile(50)
        p99 = self.percentile(99)
        with self._lock:
            violations, count = self._violations, self._count
        lib().eg_serve_slo_set(
            int(p50 * 1000), int(p99 * 1000), violations, count
        )

    def percentile(self, q: float) -> float:
        """Exact q-th percentile (nearest-rank) of the window; 0.0 when
        empty."""
        with self._lock:
            n = min(self._count, self.window)
            if n == 0:
                return 0.0
            ordered = sorted(self._ring[:n])
        rank = max(int(math.ceil(q / 100.0 * n)), 1)
        return ordered[rank - 1]

    def report(self) -> dict:
        """One verdict dict: counts, exact p50/p99 ms over the window,
        lifetime violations, and ``ok`` (window p99 <= target)."""
        p50 = self.percentile(50)
        p99 = self.percentile(99)
        with self._lock:
            count = self._count
            violations = self._violations
        return {
            "target_ms": self.target_ms,
            "count": count,
            "p50_ms": round(p50, 3),
            "p99_ms": round(p99, 3),
            "violations": violations,
            "ok": count == 0 or p99 <= self.target_ms,
        }
