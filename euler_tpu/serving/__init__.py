"""Online embedding inference support (euler_tpu/serve.py).

The serving stack is three small layers over the existing machinery:

* :class:`MicroBatcher` — request-level coalescing: concurrent embed
  queries merge into one padded-bucket device dispatch (flush on
  ``max_batch`` unique ids or ``max_wait_us``), with bounded admission
  (queue cap -> BUSY shedding, the PR-4 pattern) and per-request
  deadline enforcement.
* :class:`SLOTracker` — p50/p99 of served request latency against a
  configured target.
* :class:`EmbedFrontend` / :class:`EmbedClient` — a line-delimited JSON
  TCP protocol carrying base64 float32 embeddings (bit-exact through
  the wire), a live ``stats`` op, and SIGTERM-style drain.

Telemetry rides the existing native hist map (keys ``serve:<phase>`` /
``serve_batch``) and counter roster (``serve_*``), so metrics_text(),
the STATS scrape, and scripts/metrics_dump.py pick the serving path up
with zero new per-surface plumbing (OBSERVABILITY.md "Serve phases").
"""

from euler_tpu.serving.microbatch import (
    BusyError,
    DeadlineError,
    MicroBatcher,
)
from euler_tpu.serving.slo import SLOTracker
from euler_tpu.serving.frontend import EmbedClient, EmbedFrontend

# Serve-only CLI flags, shared between `python -m euler_tpu.serve` and
# run_loop's --serve_after (and used by run_loop to REJECT them in a
# plain train run, where they would silently do nothing).
SERVE_FLAG_DEFAULTS = {
    "serve_host": "127.0.0.1",
    "serve_port": 9200,
    "serve_max_batch": 64,
    "serve_max_wait_us": 2000,
    "serve_queue_cap": 128,
    "serve_slo_ms": 100.0,
    "serve_max_conns": 64,
    "serve_sample_cache": 65536,
    "serve_deadline_ms": 0,
    "serve_strict_bucket": 0,
}


def add_serve_flags(p):
    """Define the serving flag surface on an argparse parser (defaults
    from SERVE_FLAG_DEFAULTS, which run_loop audits overrides against)."""
    d = SERVE_FLAG_DEFAULTS
    p.add_argument("--serve_host", default=d["serve_host"], help=(
        "address the embedding frontend binds"))
    p.add_argument("--serve_port", type=int, default=d["serve_port"],
                   help="embedding frontend port (0 = ephemeral)")
    p.add_argument("--serve_max_batch", type=int,
                   default=d["serve_max_batch"], help=(
        "unique ids per micro-batch device dispatch; concurrent "
        "requests coalesce up to this"))
    p.add_argument("--serve_max_wait_us", type=int,
                   default=d["serve_max_wait_us"], help=(
        "micro-batch flush window: a request waits at most this long "
        "for co-batchable traffic before dispatching"))
    p.add_argument("--serve_queue_cap", type=int,
                   default=d["serve_queue_cap"], help=(
        "bounded admission: requests queued beyond this are answered "
        "BUSY (serve_busy_rejects) instead of building unbounded "
        "latency"))
    p.add_argument("--serve_slo_ms", type=float, default=d["serve_slo_ms"],
                   help="latency SLO target the p50/p99 tracker reports "
                        "against")
    p.add_argument("--serve_max_conns", type=int,
                   default=d["serve_max_conns"], help=(
        "frontend connection cap; clients beyond it get one BUSY reply "
        "and a close"))
    p.add_argument("--serve_sample_cache", type=int,
                   default=d["serve_sample_cache"], help=(
        "per-id sampled-neighborhood cache entries (a served id's "
        "neighborhood is drawn once, seeded by id, and reused — "
        "deterministic embeddings and no repeat sampling for hot ids)"))
    p.add_argument("--serve_deadline_ms", type=int,
                   default=d["serve_deadline_ms"], help=(
        "default per-request deadline; a request not dispatched within "
        "it is answered DEADLINE (serve_deadline_rejects). 0 = none. "
        "Clients can override per request"))
    p.add_argument("--serve_strict_bucket", type=int,
                   default=d["serve_strict_bucket"], help=(
        "compile-storm guard severity: any post-warmup XLA recompile "
        "of the serve forward already bumps serve_recompiles and "
        "journals the shape diff; 1 additionally makes it raise (the "
        "fixed-bucket program is the bit-parity anchor — a recompile "
        "means the bucket contract broke)"))
    return p


def serve_flag_overrides(args) -> list:
    """Names of serve-only flags set away from their defaults — the
    run_loop train-mode rejection list."""
    return sorted(
        f"--{name}" for name, default in SERVE_FLAG_DEFAULTS.items()
        if getattr(args, name, default) != default
    )


__all__ = [
    "BusyError", "DeadlineError", "MicroBatcher", "SLOTracker",
    "EmbedClient", "EmbedFrontend", "SERVE_FLAG_DEFAULTS",
    "add_serve_flags", "serve_flag_overrides",
]
