"""TCP request frontend for the embedding server + its Python client.

Protocol: one JSON object per line, both directions.

    {"op": "embed", "ids": [..], "deadline_ms": 50}   ->
        {"ok": true, "shape": [n, d], "dtype": "float32",
         "data": "<base64 raw little-endian float32>"}
        {"ok": false, "error": "busy"}       (admission shed — retry)
        {"ok": false, "error": "deadline"}   (expired before dispatch)
    {"op": "stats"}  -> {"ok": true, "slo": {...}, "serve_phases": {...},
                         "counters": {serve_*...}, "batch": {...}}
    {"op": "ping"}   -> {"ok": true, "draining": false}

Embeddings travel as base64 of the raw float32 buffer so the wire is
bit-exact — the parity criterion (served == direct forward) holds
through a network hop, not just in-process. The ``stats`` op is the
live scrape the load drill asserts shedding against without touching
the server process.

Drain follows the GraphService shape: stop accepting, finish in-flight
connections, then the owner closes the batcher (which itself drains
its queue) — a rolling restart loses no accepted request.
"""

from __future__ import annotations

import base64
import json
import socket
import threading
from typing import Optional

import numpy as np

from euler_tpu.graph import native
from euler_tpu.serving.microbatch import BusyError, DeadlineError


class EmbedFrontend:
    """Line-JSON TCP frontend over one EmbedServer."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 max_conns: int = 64, default_deadline_ms: int = 0):
        self._server = server
        self.max_conns = int(max_conns)
        self.default_deadline_ms = int(default_deadline_ms)
        self._draining = False
        self._conns: set = set()
        self._threads: list = []
        self._lock = threading.Lock()
        self._listener = socket.create_server(
            (host, int(port)), backlog=128, reuse_port=False
        )
        # accept() wakes on this timeout to check the drain flag —
        # closing a listener does NOT reliably wake a thread blocked in
        # accept(), and its freed port/fd can be reused by a later
        # frontend, which the stale thread would then steal from
        self._listener.settimeout(0.25)
        self.address = "%s:%d" % self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="eg-serve-accept", daemon=True
        )
        self._accept_thread.start()

    # ---- lifecycle ----

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self, grace_s: float = 5.0) -> None:
        """Stop accepting, let in-flight connections finish (up to
        ``grace_s``). The owner then closes the EmbedServer, whose
        batcher drains its queue — no accepted request is dropped."""
        self._draining = True
        # join BEFORE closing: the accept loop exits on its own flag
        # check (<= its accept timeout), and only then is the port
        # released — never while a thread could still accept on it
        self._accept_thread.join(timeout=max(grace_s, 0.5))
        try:
            self._listener.close()
        except OSError:
            pass
        deadline = grace_s
        for t in list(self._threads):
            t.join(timeout=max(deadline, 0.1))

    def stop(self) -> None:
        """Drain with zero grace, then force-close anything left."""
        self.drain(grace_s=0.5)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in list(self._threads):
            t.join(timeout=2.0)

    # ---- accept / serve ----

    def _accept_loop(self) -> None:
        while not self._draining:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue  # periodic drain-flag check
            except OSError:
                return  # listener closed (stop)
            with self._lock:
                over = len(self._conns) >= self.max_conns
                if not over:
                    self._conns.add(conn)
            if over or self._draining:
                # one BUSY reply, then close: the connection cap is the
                # frontend's admission tier (the queue cap is the
                # batcher's) — both shed onto the same counter
                native.counter_add("serve_busy_rejects", 1)
                try:
                    conn.sendall(
                        b'{"ok": false, "error": "busy"}\n'
                    )
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            t = threading.Thread(
                target=self._handle, args=(conn,),
                name="eg-serve-conn", daemon=True,
            )
            with self._lock:
                self._threads.append(t)
            t.start()

    def _handle(self, conn) -> None:
        try:
            f = conn.makefile("rwb")
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    reply = self._reply(json.loads(line))
                except ValueError as e:
                    reply = {"ok": False, "error": f"bad request: {e}"}
                f.write(json.dumps(reply).encode() + b"\n")
                f.flush()
        except OSError:
            pass  # client went away mid-exchange
        finally:
            with self._lock:
                self._conns.discard(conn)
                self._threads = [
                    t for t in self._threads
                    if t is not threading.current_thread()
                ]
            try:
                conn.close()
            except OSError:
                pass

    def _reply(self, msg: dict) -> dict:
        op = msg.get("op")
        if op == "ping":
            return {"ok": True, "draining": self._draining}
        if op == "stats":
            return {"ok": True, **self._server.stats()}
        if op == "embed":
            ids = msg.get("ids")
            if not ids:
                return {"ok": False, "error": "embed needs ids"}
            deadline_ms = msg.get(
                "deadline_ms", self.default_deadline_ms
            ) or None
            try:
                rows = self._server.embed(ids, deadline_ms=deadline_ms)
            except BusyError:
                return {"ok": False, "error": "busy"}
            except DeadlineError:
                return {"ok": False, "error": "deadline"}
            except Exception as e:
                return {"ok": False, "error": f"internal: {e}"}
            rows = np.ascontiguousarray(rows, dtype=np.float32)
            return {
                "ok": True,
                "shape": list(rows.shape),
                "dtype": "float32",
                "data": base64.b64encode(rows.tobytes()).decode(),
            }
        return {"ok": False, "error": f"unknown op {op!r}"}


class EmbedClient:
    """Blocking line-JSON client for one EmbedFrontend address."""

    def __init__(self, address: str, timeout_s: float = 30.0):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection(
            (host, int(port)), timeout=timeout_s
        )
        self._file = self._sock.makefile("rwb")
        self._lock = threading.Lock()

    def _call(self, msg: dict) -> dict:
        with self._lock:
            self._file.write(json.dumps(msg).encode() + b"\n")
            self._file.flush()
            line = self._file.readline()
        if not line:
            raise ConnectionError("embed server closed the connection")
        reply = json.loads(line)
        if not reply.get("ok"):
            # admission sheds map to typed errors on EVERY op — a ping
            # against a full frontend must say BUSY, not hand back a
            # dict the caller has to grep
            err = reply.get("error", "")
            if err == "busy":
                raise BusyError("server busy")
            if err == "deadline":
                raise DeadlineError("server-side deadline expired")
        return reply

    def embed(self, ids, deadline_ms: Optional[float] = None) -> np.ndarray:
        """Embeddings for ids, [len(ids), dim] float32 — bit-exact to
        the server's device output. Raises BusyError on shed (retry
        with backoff) and DeadlineError on expiry."""
        msg: dict = {"op": "embed", "ids": [int(i) for i in ids]}
        if deadline_ms is not None:
            msg["deadline_ms"] = deadline_ms
        reply = self._call(msg)
        if not reply.get("ok"):
            raise RuntimeError(f"embed failed: {reply.get('error', '')}")
        raw = base64.b64decode(reply["data"])
        return np.frombuffer(raw, dtype=np.float32).reshape(
            reply["shape"]
        )

    def stats(self) -> dict:
        return self._call({"op": "stats"})

    def ping(self) -> dict:
        return self._call({"op": "ping"})

    def close(self) -> None:
        try:
            self._file.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
