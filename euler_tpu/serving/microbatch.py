"""Request-level micro-batching for embedding inference.

Concurrent embed queries coalesce into one padded-bucket device
dispatch: the dispatcher thread collects queued requests until either
``max_batch`` unique ids are pending or the oldest request has waited
``max_wait_us``, dedupes ids across requests (two clients asking for
the same hub node cost one sample + one device row — the FastSample
coalescing observation applied to serving), runs the server's
``embed_unique`` callback once, and scatters rows back per request.

Admission is bounded the PR-4 way: at most ``queue_cap`` requests may
be queued; beyond that :meth:`submit` raises :class:`BusyError`
immediately (counter ``serve_busy_rejects``) instead of building
unbounded queue latency. A request carrying a deadline that expires
before its batch dispatches is answered :class:`DeadlineError`
(``serve_deadline_rejects``) and never reaches the device.

Phase telemetry (queue_wait / total here; sample / dispatch inside the
server's callback) rides the native ``serve:<phase>`` histograms —
kill-switch honored natively, so ``telemetry=0`` leaves this hot path
histogram-free.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from euler_tpu import telemetry as T
from euler_tpu.graph import native


class BusyError(RuntimeError):
    """Admission refused: the serve queue is at capacity (shed, retry)."""


class DeadlineError(RuntimeError):
    """The request's deadline expired before its batch dispatched."""


class _Request:
    __slots__ = ("ids", "deadline", "t_submit", "done", "rows", "error")

    def __init__(self, ids: np.ndarray, deadline: Optional[float]):
        self.ids = ids
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.t_submit = time.monotonic()
        self.done = threading.Event()
        self.rows: Optional[np.ndarray] = None
        self.error: Optional[Exception] = None


class MicroBatcher:
    """Coalesce concurrent embed requests into bounded device batches.

    ``embed_unique(uids)`` is the server's batch callback: unique int64
    ids in, one float row per id out (same order). ``on_done(total_us,
    error)`` is an optional completion hook (the SLO tracker's feed).
    """

    def __init__(
        self,
        embed_unique: Callable[[np.ndarray], np.ndarray],
        max_batch: int = 64,
        max_wait_us: int = 2000,
        queue_cap: int = 128,
        on_done: Optional[Callable[[float, Optional[Exception]], None]] = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if queue_cap < 1:
            raise ValueError(f"queue_cap must be >= 1, got {queue_cap}")
        self._embed_unique = embed_unique
        self.max_batch = int(max_batch)
        self._max_wait_s = max(int(max_wait_us), 0) / 1e6
        self.queue_cap = int(queue_cap)
        self._on_done = on_done
        self._queue: deque[_Request] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----

    def start(self) -> "MicroBatcher":
        with self._cond:
            if self._thread is not None:
                return self
            self._closed = False
            self._thread = threading.Thread(
                target=self._run, name="eg-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        """Drain: stop admitting, dispatch everything queued, stop."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
            self._thread = None
        if t is not None:
            t.join()

    # ---- request path ----

    def submit(self, ids, deadline_ms: Optional[float] = None) -> np.ndarray:
        """Embed ``ids``; blocks until the coalesced batch completes.

        Raises :class:`BusyError` when the queue is full and
        :class:`DeadlineError` when ``deadline_ms`` elapses before the
        batch dispatches."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        if ids.size == 0:
            raise ValueError("submit() needs at least one id")
        native.counter_add("serve_requests", 1)
        deadline = (
            time.monotonic() + deadline_ms / 1e3
            if deadline_ms is not None and deadline_ms > 0 else None
        )
        req = _Request(ids, deadline)
        with self._cond:
            if self._closed or self._thread is None:
                raise RuntimeError("serving stopped (batcher not running)")
            if len(self._queue) >= self.queue_cap:
                native.counter_add("serve_busy_rejects", 1)
                raise BusyError(
                    f"serve queue at capacity ({self.queue_cap} requests "
                    "pending) — shed, retry with backoff"
                )
            self._queue.append(req)
            self._cond.notify_all()
        req.done.wait()
        if req.error is not None:
            raise req.error
        return req.rows

    # ---- dispatcher ----

    def _pending_unique_locked(self) -> int:
        seen: set = set()
        for r in self._queue:
            seen.update(r.ids.tolist())
        return len(seen)

    def _pop_batch_locked(self) -> list:
        """FIFO-pop requests whose combined unique ids fit max_batch.
        A single oversize request still pops alone — the server's
        callback chunks it across dispatches."""
        batch: list = []
        uniq: set = set()
        while self._queue:
            r = self._queue[0]
            new = [i for i in r.ids.tolist() if i not in uniq]
            if batch and len(uniq) + len(new) > self.max_batch:
                break
            uniq.update(new)
            batch.append(self._queue.popleft())
        return batch

    def _run(self) -> None:
        try:
            while True:
                with self._cond:
                    while not self._queue and not self._closed:
                        self._cond.wait()
                    if not self._queue:
                        return  # closed and drained
                    # coalescing window: flush on max_batch unique ids,
                    # the oldest request's max_wait expiring, or close
                    window_end = self._queue[0].t_submit + self._max_wait_s
                    while (
                        not self._closed
                        and self._queue
                        and self._pending_unique_locked() < self.max_batch
                    ):
                        remaining = window_end - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                    batch = self._pop_batch_locked()
                if batch:
                    self._dispatch(batch)
        except BaseException as e:  # never die silently mid-serve
            with self._cond:
                leftovers = list(self._queue)
                self._queue.clear()
            for r in leftovers:
                r.error = RuntimeError(f"serve dispatcher died: {e!r}")
                r.done.set()
            raise

    def _dispatch(self, batch: list) -> None:
        now = time.monotonic()
        live: list = []
        for r in batch:
            T.record_serve_phase("queue_wait", (now - r.t_submit) * 1e6)
            if r.deadline is not None and now >= r.deadline:
                native.counter_add("serve_deadline_rejects", 1)
                r.error = DeadlineError(
                    f"deadline expired {(now - r.deadline) * 1e3:.1f}ms "
                    "before dispatch"
                )
                self._finish(r)
            else:
                live.append(r)
        if not live:
            return
        index: dict = {}
        for r in live:
            for i in r.ids.tolist():
                if i not in index:
                    index[i] = len(index)
        uids = np.fromiter(index.keys(), dtype=np.int64, count=len(index))
        native.counter_add("serve_batches", 1)
        T.record_serve_batch(len(uids))
        try:
            rows = self._embed_unique(uids)
        except Exception as e:
            for r in live:
                r.error = e
                self._finish(r)
            return
        for r in live:
            r.rows = rows[[index[i] for i in r.ids.tolist()]]
            self._finish(r)

    def _finish(self, r: _Request) -> None:
        total_us = (time.monotonic() - r.t_submit) * 1e6
        T.record_serve_phase("total", total_us)
        if self._on_done is not None:
            self._on_done(total_us, r.error)
        r.done.set()
