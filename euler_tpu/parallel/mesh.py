"""Device mesh + sharding rules.

The TPU replacement for the reference's parameter-server data parallelism
(reference tf_euler/python/run_loop.py:371-397 ClusterSpec{ps,worker} +
replica_device_setter): parameters are replicated across the mesh, each
batch is sharded over the 'data' axis, and XLA inserts the gradient
all-reduce over ICI inside the jitted train step. No parameter servers,
no explicit gradient exchange code.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """1-D data-parallel mesh over the first num_devices devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), ("data",))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over 'data'."""
    return NamedSharding(mesh, P("data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host batch pytree onto the mesh, leading dim sharded."""
    sharding = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
